"""Benchmark — the BASELINE.json north star on real hardware.

Times one gang-constrained scheduling cycle at 50k pods × 5k nodes
(heterogeneous GPU gangs, 3 weighted queues, minMember=4): host→device ship
of the snapshot arrays, the compiled allocate solve (predicates + scoring +
fairness + ordering + gang commit/discard), and the assignment vector back.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is measured against the driver-provided target of a 1000 ms
cycle (BASELINE.md: the reference publishes no numbers; its design cadence
is the 1 s schedule-period) — >1 means faster than target.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time


def _backend_healthy(timeout_s: float = 120.0) -> bool:
    """Probe jax backend init in a subprocess — a wedged TPU tunnel hangs
    inside backend init with no timeout, which would hang the whole bench."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax.numpy as j; j.zeros(1); print('ok')"],
            timeout=timeout_s, capture_output=True, text=True,
        )
        return "ok" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def _backend_healthy_with_retry() -> bool:
    """Bounded retry with backoff: a wedged tunnel sometimes recovers; give it
    two more (short) chances before falling back to a labeled CPU run.  The
    retry probes are kept short so the worst case adds ~90s, not minutes —
    the driver's own timeout has to cover the CPU-fallback run too."""
    if _backend_healthy(timeout_s=120.0):
        return True
    for delay_s in (10.0, 20.0):
        time.sleep(delay_s)
        if _backend_healthy(timeout_s=30.0):
            return True
    return False


if __name__ == "__main__" and os.environ.get("KB_BENCH_CHILD") != "1":
    if not _backend_healthy_with_retry():
        # TPU tunnel wedged: rerun ourselves on CPU so the driver still gets
        # a (clearly labeled) number instead of a hang
        from kube_batch_tpu.envutil import hardened_cpu_env

        env = hardened_cpu_env()
        env.update(KB_BENCH_CHILD="1", KB_BENCH_BACKEND_NOTE="cpu_fallback")
        sys.exit(subprocess.call([sys.executable, __file__], env=env))
    os.environ["KB_BENCH_CHILD"] = "1"

import jax
import numpy as np

from kube_batch_tpu.ops.assignment import AllocateConfig, allocate_solve
from kube_batch_tpu.testing.synthetic import synthetic_device_snapshot

TARGET_MS = 1000.0  # <1s per cycle on TPU v5e (BASELINE.md north star)

N_TASKS = 50_000
N_NODES = 5_000
CYCLES = 5


def one_cycle(snap_np, config):
    snap = jax.device_put(snap_np)             # host→device: the only ship in
    result = allocate_solve(snap, config)      # compiled cycle program
    assigned = np.asarray(result.assigned)     # device→host: assignment back
    return assigned


def main() -> None:
    config = AllocateConfig()
    snap_np, meta = synthetic_device_snapshot(
        n_tasks=N_TASKS,
        n_nodes=N_NODES,
        gang_size=4,
        n_queues=3,
        gpu_task_frac=0.2,
        gpu_node_frac=0.25,
    )

    # warmup: compile + first execute
    assigned = one_cycle(snap_np, config)
    placed = int((assigned[: meta.n_tasks] >= 0).sum())

    times = []
    for _ in range(CYCLES):
        t0 = time.perf_counter()
        one_cycle(snap_np, config)
        times.append((time.perf_counter() - t0) * 1e3)

    p50 = statistics.median(times)
    note = os.environ.get("KB_BENCH_BACKEND_NOTE", "")
    metric = (
        f"gang_allocate_cycle_ms_{N_TASKS // 1000}k_pods_"
        f"{N_NODES // 1000}k_nodes_placed_{placed}"
    )
    if note:
        metric += f"_{note}"
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(p50, 2),
                "unit": "ms",
                "vs_baseline": round(TARGET_MS / p50, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
