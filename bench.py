"""Benchmark — the BASELINE.json north star on real hardware.

Times the FULL scheduling cycle at 50k pods × 5k nodes (heterogeneous
gangs, 3 weighted queues, minMember=4): open_session (cache deep-clone +
plugin open) → allocate.execute (device snapshot build + compiled solve +
host replay + bulk bind) → close_session (status writeback), through the
real cache handlers and fake binder — the end-to-end path the reference's
1 s schedule-period covers (scheduler.go:88-102, options.go:28).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "phases"}.
value is the e2e p50 over the timed cycles; phases is the p50 per-phase
breakdown in ms. vs_baseline is measured against the driver-provided target
of a 1000 ms cycle — >1 means faster than target.
"""

from __future__ import annotations

import gc
import json
import os
import statistics
import subprocess
import sys
import time


def _backend_healthy(timeout_s: float = 120.0) -> bool:
    """Probe jax backend init in a subprocess — a wedged TPU tunnel hangs
    inside backend init with no timeout, which would hang the whole bench."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax.numpy as j; j.zeros(1); print('ok')"],
            timeout=timeout_s, capture_output=True, text=True,
        )
        return "ok" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def _backend_healthy_with_retry() -> bool:
    """Bounded retry with backoff: a wedged tunnel sometimes recovers; give it
    two more (short) chances before falling back to a labeled CPU run.  The
    retry probes are kept short so the worst case adds ~90s, not minutes —
    the driver's own timeout has to cover the CPU-fallback run too."""
    if _backend_healthy(timeout_s=120.0):
        return True
    for delay_s in (10.0, 20.0):
        time.sleep(delay_s)
        if _backend_healthy(timeout_s=30.0):
            return True
    return False


if __name__ == "__main__" and os.environ.get("KB_BENCH_CHILD") != "1":
    if not _backend_healthy_with_retry():
        # TPU tunnel wedged: rerun ourselves on CPU so the driver still gets
        # a (clearly labeled) number instead of a hang
        from kube_batch_tpu.envutil import hardened_cpu_env

        env = hardened_cpu_env()
        env.update(KB_BENCH_CHILD="1", KB_BENCH_BACKEND_NOTE="cpu_fallback")
        sys.exit(subprocess.call([sys.executable, __file__], env=env))
    os.environ["KB_BENCH_CHILD"] = "1"

from kube_batch_tpu.envutil import enable_persistent_compilation_cache  # noqa: E402

enable_persistent_compilation_cache()  # compiles survive across invocations

import numpy as np  # noqa: E402

from kube_batch_tpu import actions as _actions  # noqa: E402,F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: E402,F401 — registers
from kube_batch_tpu.api.resident import (  # noqa: E402
    scatter_summary as _resident_scatter_summary,
)
from kube_batch_tpu.framework.conf import load_scheduler_conf  # noqa: E402
from kube_batch_tpu.framework.session import close_session, open_session  # noqa: E402
from kube_batch_tpu.framework.interface import get_action  # noqa: E402
from kube_batch_tpu.testing.synthetic import synthetic_cluster  # noqa: E402

TARGET_MS = 1000.0  # <1s per cycle on TPU v5e (BASELINE.md north star)

N_TASKS = 50_000
N_NODES = 5_000
CYCLES = 6  # p50 over more cycles — host-load noise at this scale is ±10%


def one_cycle(conf, cache):
    """One full scheduling cycle; returns (phase_ms, binds)."""
    phases = {}
    t0 = time.perf_counter()
    ssn = open_session(cache, conf.tiers)
    phases["open_session"] = (time.perf_counter() - t0) * 1e3
    for name in conf.actions:
        t0 = time.perf_counter()
        get_action(name).execute(ssn)
        phases[f"action_{name}"] = (time.perf_counter() - t0) * 1e3
    t0 = time.perf_counter()
    close_session(ssn)
    phases["close_session"] = (time.perf_counter() - t0) * 1e3
    # fold the allocate-internal breakdown in (snapshot build / device solve /
    # host replay) — recorded by the action itself
    for k, v in get_action("allocate").last_phase_ms.items():
        phases[f"allocate_{k}"] = v
    t0 = time.perf_counter()
    cache.flush_binds()
    phases["async_bind_drain"] = (time.perf_counter() - t0) * 1e3
    return phases


def _pct(values, p):
    """Nearest-rank percentile (the shared sim/metrics definition)."""
    from kube_batch_tpu.sim.metrics import nearest_rank

    return nearest_rank(values, p)


def measure(conf, make_cache, cycles):
    """Warm once (compile), then time `cycles` fresh-cache runs under the
    shared gc discipline. Returns (p50_ms, phase_p50, phase_p90, warmup_ms,
    placed_on_warmup) — the warmup/compile cycle is timed and labeled
    separately so compile cost never leaks into the steady percentiles."""
    warm = make_cache()
    t0 = time.perf_counter()
    one_cycle(conf, warm)
    warmup_ms = (time.perf_counter() - t0) * 1e3
    placed = len(warm.binder.binds)
    del warm
    e2e, per_phase = [], []
    for _ in range(cycles):
        cache = make_cache()
        gc.collect()
        gc.disable()
        t0 = time.perf_counter()
        phases = one_cycle(conf, cache)
        e2e.append((time.perf_counter() - t0) * 1e3)
        gc.enable()
        per_phase.append(phases)
        del cache
    phase_p50 = {
        k: round(statistics.median(p[k] for p in per_phase), 1)
        for k in per_phase[0]
    }
    phase_p90 = {
        k: round(_pct([p[k] for p in per_phase], 0.90), 1)
        for k in per_phase[0]
    }
    return statistics.median(e2e), phase_p50, phase_p90, warmup_ms, placed


def multicycle_bench(conf, n_tasks, n_nodes, cycles=8, warmup_cycles=2,
                     churn_frac=0.02, seed=0, delta=True, wobble=0.1):
    """The steady-state multi-cycle regime the 1 s schedule period actually
    runs in: ONE persistent cache, per-cycle churn (bound gangs complete,
    new gangs arrive) with a ±10% pod-count wobble, back-to-back cycles.

    This is where the cross-cycle resident snapshot earns its keep — and
    where a shape-bucket regression would show as retraces.  Per cycle it
    records the phase breakdown, the open/snapshot path taken (delta vs
    full), and the jit compile delta; the summary separates the labeled
    warmup cycles from the steady percentiles.  `delta=False` forces the
    full-rebuild path for the same workload, giving the reduction
    denominator on the same host."""
    import itertools

    import numpy as np

    from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Pod, PodGroup
    from kube_batch_tpu.api.types import PodPhase
    from kube_batch_tpu.testing.synthetic import CPU_CHOICES, MEM_CHOICES
    from kube_batch_tpu.utils import jitstats

    cache = synthetic_cluster(
        n_tasks=n_tasks, n_nodes=n_nodes, gang_size=4, n_queues=3
    )
    cache.delta_enabled = delta
    # pre-reserve the wobble ceiling so axis growth (a one-off recompile)
    # happens at warmup, never mid-steady-state
    cache.columns.reserve(
        n_tasks=int(n_tasks * 1.15), n_jobs=int(n_tasks / 4 * 1.15) + 8
    )
    rng = np.random.default_rng(seed)
    serial = itertools.count(1_000_000)
    gang = 4

    def churn_step():
        k = max(1, int(len(cache.jobs) * churn_frac))
        done = 0
        for uid, job in list(cache.jobs.items()):
            if done >= k:
                break
            pods = [cache.pods.get(key) for key in job.tasks]
            if not pods or any(p is None or p.node_name is None for p in pods):
                continue
            for p in sorted(pods, key=lambda p: p.name):
                cache.delete_pod(p)
            cache.delete_pod_group(uid)
            done += 1
        want = int(n_tasks * (1.0 + wobble * float(rng.uniform(-1, 1))))
        while len(cache.pods) + gang <= want:
            j = next(serial)
            cache.add_pod_group(PodGroup(
                name=f"mc{j}", namespace="bench", min_member=gang,
                queue=f"q{j % 3}", creation_index=j,
            ))
            for t in range(gang):
                cache.add_pod(Pod(
                    name=f"mc{j}-{t}", namespace="bench",
                    requests={
                        "cpu": float(rng.choice(CPU_CHOICES)),
                        "memory": float(rng.choice(MEM_CHOICES)),
                    },
                    annotations={GROUP_NAME_ANNOTATION: f"mc{j}"},
                    phase=PodPhase.PENDING,
                    creation_index=j * 10 + t,
                ))

    def warm_failure_histogram():
        """The fit-error histogram only dispatches on cycles with unplaced
        pending tasks, which may first occur mid-steady-state — compile it
        during warmup so the zero-retrace claim covers failure cycles too.
        Warms the variant the allocate dispatch would actually pick, so a
        sharded run doesn't warm (and hold resident copies for) the wrong
        path."""
        from kube_batch_tpu.actions.allocate import build_session_snapshot
        from kube_batch_tpu.api.columns import resident_snap
        from kube_batch_tpu.ops.assignment import failure_histogram_solve
        from kube_batch_tpu.framework.session import (
            close_session as _close, open_session as _open,
        )
        from kube_batch_tpu.parallel.mesh import (
            default_mesh, sharded_failure_histogram, should_shard,
        )

        ssn = _open(cache, conf.tiers)
        try:
            snap, _ = build_session_snapshot(ssn)
            if should_shard(snap.node_alloc.shape[0]):
                mesh = default_mesh()
                sharded_failure_histogram(
                    resident_snap(cache.columns, snap, mesh), mesh
                ).block_until_ready()
            else:
                failure_histogram_solve(
                    resident_snap(cache.columns, snap)
                ).block_until_ready()
        finally:
            _close(ssn)

    records = []
    pod_counts = []
    for c in range(warmup_cycles + cycles):
        if c:
            churn_step()
        if c == warmup_cycles:
            warm_failure_histogram()
        pod_counts.append(len(cache.pods))
        compiles0 = jitstats.total_compiles()
        gc.collect()
        gc.disable()
        t0 = time.perf_counter()
        rec = one_cycle(conf, cache)
        rec["e2e"] = (time.perf_counter() - t0) * 1e3
        gc.enable()
        rec["compiles"] = jitstats.total_compiles() - compiles0
        rec["open_path"] = cache.last_open_path
        rec["snapshot_path"] = cache.columns.last_snapshot_path
        rec["topk"] = get_action("allocate").last_topk
        rec["solve_rounds"] = get_action("allocate").last_solve_rounds
        records.append(rec)
    # span-recorder stats for the trace_overhead section: spans per cycle
    # and per-stage counts, straight off the per-cache tracer (obs/trace)
    tracer = getattr(cache, "tracer", None)
    trace_stats = (
        tracer.stage_attribution()
        if tracer is not None and tracer.enabled else None
    )
    cache.stop()

    warm, steady = records[:warmup_cycles], records[warmup_cycles:]
    phase_keys = sorted(set().union(*(set(r) for r in steady))
                        - {"compiles", "open_path", "snapshot_path",
                           "topk", "solve_rounds"})
    summary = {
        k: {
            "p50": round(_pct([r.get(k, 0.0) for r in steady], 0.50), 2),
            "p90": round(_pct([r.get(k, 0.0) for r in steady], 0.90), 2),
        }
        for k in phase_keys
    }
    open_plus_snap = [
        r.get("open_session", 0.0) + r.get("allocate_snapshot_build", 0.0)
        for r in steady
    ]
    paths = {}
    for r in steady:
        key = f"{r['open_path']}/{r['snapshot_path']}"
        paths[key] = paths.get(key, 0) + 1
    # candidate-compaction evidence (ISSUE 10): which steady cycles ran the
    # compacted program, the K/bucket they ran at, and the exhaustion /
    # full-head-re-entry counters that prove K is sized right (an
    # exhaustion rate near 0 means the table almost never falls back)
    topk_cycles = [r for r in steady if r.get("topk")]
    rounds_steady = [r.get("solve_rounds", 0) for r in steady]
    topk_summary = {
        "compacted_cycles": len(topk_cycles),
        "steady_cycles": len(steady),
        "rounds_run_p50": _pct(rounds_steady, 0.50) if rounds_steady else 0,
    }
    if topk_cycles:
        exh = sum(r["topk"]["exhausted"] for r in topk_cycles)
        reent = sum(r["topk"]["reentries"] for r in topk_cycles)
        rounds_c = sum(max(r.get("solve_rounds", 0), 1) for r in topk_cycles)
        topk_summary.update({
            "k": topk_cycles[-1]["topk"]["k"],
            "bucket": max(r["topk"]["bucket"] for r in topk_cycles),
            "exhausted_total": exh,
            "reentries_total": reent,
            "exhaustion_rate_per_round": round(exh / rounds_c, 4),
            "reentries_per_solve": round(reent / len(topk_cycles), 3),
        })
    # warm-carry evidence (ISSUE 14): which steady cycles ran the carried
    # table, how many cold-rebuilt, and the invalidated-row fraction —
    # re-ranked rows over the live bucket, the delta-work claim
    warm_cycles = [r["topk"]["warm"] for r in topk_cycles
                   if r["topk"].get("warm")]
    warm_summary = {"warm_cycles": len(warm_cycles)}
    if warm_cycles:
        merged = [w for w in warm_cycles if not w["cold"]]
        fracs = [
            w["reranked"] / max(w["bucket_live"], 1) for w in merged
        ]
        warm_summary.update({
            "cold_builds": len(warm_cycles) - len(merged),
            "invalidated_row_fraction_mean": (
                round(float(np.mean(fracs)), 4) if fracs else None
            ),
            "changed_nodes_mean": (
                round(float(np.mean([w["changed"] for w in merged])), 1)
                if merged else None
            ),
        })
    topk_summary["warm"] = warm_summary
    return {
        "delta_enabled": delta,
        "pods_target": n_tasks,
        "nodes": n_nodes,
        "churn_frac": churn_frac,
        "pod_count_range": [min(pod_counts), max(pod_counts)],
        "warmup_cycles": warmup_cycles,
        "warmup_e2e_ms": [round(r["e2e"], 1) for r in warm],
        "warmup_compiles": sum(r["compiles"] for r in warm),
        "steady_cycles": len(steady),
        "steady": summary,
        "open_plus_snapshot_build_ms": {
            "p50": round(_pct(open_plus_snap, 0.50), 2),
            "p90": round(_pct(open_plus_snap, 0.90), 2),
        },
        # the acceptance counters: which path each steady cycle took, and
        # whether ANY steady cycle retraced (must be 0 across the wobble)
        "snapshot_paths": paths,
        "retraces_steady": sum(r["compiles"] for r in steady),
        "topk": topk_summary,
        "jit_compile_counts": jitstats.compile_counts(),
        # which solve the cycles dispatched ("single" | "sharded") and the
        # per-cycle device-resident cache's delta-vs-full bytes-moved
        # evidence, per path (api/resident.py counters)
        "solve_mode": get_action("allocate").last_solve_mode,
        "shard_impl": _shard_impl(),
        "resident_scatter": _resident_scatter_summary(
            cache.columns.resident_counters()
        ),
        # per-slot warm-carry lifetime counters (plans / cold builds /
        # re-ranked and changed totals) — the ColumnStore-side view of
        # the per-cycle "warm" records above
        "warm_tables": cache.columns.warm_counters(),
        "trace": trace_stats,
    }


def _shard_impl() -> str:
    from kube_batch_tpu.parallel.mesh import shard_map_enabled, task_shards

    impl = "shard_map" if shard_map_enabled() else "pjit"
    ts = task_shards()
    return f"{impl},tasks={ts}" if ts > 1 else impl


def run_multicycle_pair(conf, n_tasks, n_nodes, cycles=8):
    """Delta vs forced-full-rebuild on the same host/workload; returns
    (delta_report, full_report, open+snapshot p50 reduction)."""
    mc_delta = multicycle_bench(conf, n_tasks, n_nodes, cycles=cycles,
                                delta=True)
    mc_full = multicycle_bench(conf, n_tasks, n_nodes, cycles=cycles,
                               delta=False)
    d = mc_delta["open_plus_snapshot_build_ms"]["p50"]
    f = mc_full["open_plus_snapshot_build_ms"]["p50"]
    reduction = round(1.0 - d / f, 3) if f > 0 else 0.0
    return mc_delta, mc_full, reduction


def _oracle_ab_pair(env_key, on_fn, off_fn):
    """The shared scaffolding of every fast-path-vs-oracle comparison:
    run ``on_fn`` with ``env_key`` unset (the fast path's default), then
    ``off_fn`` with it pinned to "0" (the oracle), restoring the caller's
    environment either way."""
    saved = os.environ.get(env_key)
    try:
        os.environ.pop(env_key, None)
        on = on_fn()
        os.environ[env_key] = "0"
        off = off_fn()
    finally:
        if saved is None:
            os.environ.pop(env_key, None)
        else:
            os.environ[env_key] = saved
    return on, off


def run_topk_pair(conf, n_tasks, n_nodes, cycles=6):
    """Compacted-vs-full solve-phase comparison on the same host/workload
    (ISSUE 10 acceptance): the multicycle regime with KB_TOPK at its
    default vs KB_TOPK=0 (the full-matrix oracle).  Returns a dict with
    both solve p50s, the speedup, and the compacted run's candidate-table
    stats — the compacted run must also show zero steady retraces."""
    on, off = _oracle_ab_pair(
        "KB_TOPK",
        lambda: multicycle_bench(conf, n_tasks, n_nodes, cycles=cycles),
        lambda: multicycle_bench(conf, n_tasks, n_nodes, cycles=cycles),
    )
    s_on = on["steady"].get("allocate_solve", {}).get("p50", 0.0)
    s_off = off["steady"].get("allocate_solve", {}).get("p50", 0.0)
    return {
        "pods": n_tasks, "nodes": n_nodes,
        "solve_p50_ms_topk": s_on,
        "solve_p50_ms_full": s_off,
        "solve_speedup": round(s_off / s_on, 2) if s_on > 0 else 0.0,
        "e2e_p50_ms_topk": on["steady"].get("e2e", {}).get("p50"),
        "e2e_p50_ms_full": off["steady"].get("e2e", {}).get("p50"),
        "retraces_steady_topk": on.get("retraces_steady"),
        "topk": on.get("topk"),
    }


def run_warm_pair(conf, n_tasks, n_nodes, cycles=6):
    """Warm-vs-cold solve-phase comparison on the same host/workload
    (ISSUE 14 acceptance): the multicycle regime with KB_WARM at its
    default (carried candidate table + in-program repair) vs KB_WARM=0
    (the cold per-solve build oracle), both with compaction on.  Returns
    both solve p50s, the speedup, the warm run's invalidated-row fraction
    (re-ranked rows over the live bucket — the delta-work evidence), and
    the warm run's steady retrace count (must be 0)."""
    # the acceptance regime is ≤2% GANG churn and nothing else: the
    # pod-count wobble is OFF for both legs (fair A/B) — the default
    # ±10% wobble is the retrace-hunting workload, whose random
    # multi-hundred-pod bursts legitimately visit new sub-bucket rungs
    # (a one-time compile each, like any shape-bucket growth).  The
    # shared warmup is long enough both for the workload to reach its
    # standing-backlog equilibrium (the regime the carry serves) and for
    # the rung ratchets to settle off the cold-start burst
    # (WARM_RUNG_DECAY plans) before the steady window.
    def leg():
        return multicycle_bench(conf, n_tasks, n_nodes, cycles=cycles,
                                warmup_cycles=14, wobble=0.0)

    on, off = _oracle_ab_pair("KB_WARM", leg, leg)
    s_on = on["steady"].get("allocate_solve", {}).get("p50", 0.0)
    s_off = off["steady"].get("allocate_solve", {}).get("p50", 0.0)
    return {
        "pods": n_tasks, "nodes": n_nodes,
        "solve_p50_ms_warm": s_on,
        "solve_p50_ms_cold": s_off,
        "solve_speedup": round(s_off / s_on, 2) if s_on > 0 else 0.0,
        "e2e_p50_ms_warm": on["steady"].get("e2e", {}).get("p50"),
        "e2e_p50_ms_cold": off["steady"].get("e2e", {}).get("p50"),
        "retraces_steady_warm": on.get("retraces_steady"),
        "warm": (on.get("topk") or {}).get("warm"),
        "topk": on.get("topk"),
    }


def guard_overhead_bench(conf, n_tasks=20_000, n_nodes=2_000, reps=13,
                         steady_cycles=6):
    """Sentinel-on vs sentinel-off cost (guard-plane acceptance): the
    fused invariant tail must cost <5% of steady-cycle p50.

    Methodology: the sentinel is a FUSED tail on each solve program, and
    a full-program A/B pair is unmeasurable on a loaded 2-core CPU box —
    a ~1-3ms tail hides under the solve's ±10% run-to-run wobble (an
    A-then-B multicycle pair even flips sign between runs).  So the tail
    programs THEMSELVES are timed — ``allocate_invariants`` /
    ``evict_invariants`` + the eligibility checksum, jitted standalone on
    the real snapshot and a real solve result: exactly the operations the
    fusion appends, with none of the solve's noise.  The per-cycle cost
    sums one allocate tail and both eviction tails (every sentinel-fused
    dispatch of the shipped 5-action steady cycle); the denominator is
    the steady-cycle e2e p50 from a multicycle run under the production
    default (guard on).  Audit cycles are excluded by design: they
    re-run the oracle as OVERLAPPED work."""
    import functools
    import time as _time

    import jax

    def _timed(fn, *args):
        jax.block_until_ready(fn(*args))
        ts = []
        for _ in range(reps):
            t0 = _time.perf_counter()
            jax.block_until_ready(fn(*args))
            ts.append(_time.perf_counter() - t0)
        return statistics.median(ts) * 1e3

    cache = synthetic_cluster(
        n_tasks=n_tasks, n_nodes=n_nodes, gang_size=4, n_queues=3
    )
    ssn = open_session(cache, conf.tiers)
    try:
        from kube_batch_tpu.actions.allocate import session_allocate_config
        from kube_batch_tpu.api.columns import resident_snap
        from kube_batch_tpu.ops.assignment import allocate_solve
        from kube_batch_tpu.ops.eviction import EvictConfig, evict_solve
        from kube_batch_tpu.ops.invariants import (
            allocate_invariants,
            eligibility_checksum,
            evict_invariants,
        )

        cols = cache.columns
        snap, _meta = cols.device_snapshot(ssn)
        config = session_allocate_config(ssn)._replace(topk=0)
        dev = resident_snap(cols, snap)
        res = allocate_solve(dev, config)
        jax.block_until_ready(res)
        atail = jax.jit(functools.partial(allocate_invariants, config=config))
        ck = jax.jit(eligibility_checksum)
        t_alloc = _timed(lambda: (atail(dev, res), ck(dev)))
        ecfg = EvictConfig(mode="preempt")
        eres = evict_solve(dev, ecfg)
        jax.block_until_ready(eres)
        etail = jax.jit(functools.partial(evict_invariants, config=ecfg))
        t_evict = _timed(lambda: (etail(dev, eres), ck(dev)))
    finally:
        close_session(ssn)
    del cache
    # per steady cycle: one allocate tail + reclaim & preempt tails
    deltas = t_alloc + 2.0 * t_evict
    # denominator: the steady-cycle e2e p50 under the production default
    # (guard on) — overhead_pct is the whole cycle's sentinel tax
    mc = multicycle_bench(conf, n_tasks, n_nodes, cycles=steady_cycles)
    e2e = mc["steady"].get("e2e", {}).get("p50", 0.0)
    return {
        "pods": n_tasks, "nodes": n_nodes, "reps": reps,
        "target": "overhead_pct < 5",
        "allocate_sentinel_tail_ms": round(t_alloc, 2),
        "evict_sentinel_tail_ms": round(t_evict, 2),
        "sentinel_delta_ms_per_cycle": round(deltas, 2),
        "steady_cycle_e2e_p50_ms": e2e,
        "overhead_pct": round(100.0 * deltas / e2e, 2) if e2e > 0 else 0.0,
        "retraces_steady": mc.get("retraces_steady"),
    }


def trace_overhead_bench(conf, n_tasks=20_000, n_nodes=2_000, cycles=6,
                         reps=20_000):
    """Span-recorder cost vs the steady e2e p50 (<2% acceptance target),
    with zero new steady retraces.

    Methodology (the guard_overhead precedent): a full A/B multicycle
    pair on the loaded 2-core box is noise-dominated — a sub-ms per-cycle
    tracing cost hides under the solve's ±10% wobble — so the span
    machinery ITSELF is micro-timed (context-manager enter/exit with ring
    retention, plus the device-span counter probes: the jit compile-count
    read and the resident-counter read, paid twice per device span) and
    multiplied by the spans-per-cycle the traced multicycle run actually
    created; the denominator is that run's steady e2e p50.  The A/B pair
    still runs and is reported as corroboration, and the traced run's
    retrace counter is the zero-new-retraces acceptance."""
    import tempfile

    from kube_batch_tpu.obs.recorder import FlightRecorder
    from kube_batch_tpu.obs.trace import Tracer
    from kube_batch_tpu.utils import jitstats

    saved = os.environ.get("KB_TRACE")
    try:
        os.environ.pop("KB_TRACE", None)        # default = tracing on
        on = multicycle_bench(conf, n_tasks, n_nodes, cycles=cycles)
        os.environ["KB_TRACE"] = "0"
        off = multicycle_bench(conf, n_tasks, n_nodes, cycles=cycles)
    finally:
        if saved is None:
            os.environ.pop("KB_TRACE", None)
        else:
            os.environ["KB_TRACE"] = saved
    e2e_on = on["steady"].get("e2e", {}).get("p50", 0.0)
    e2e_off = off["steady"].get("e2e", {}).get("p50", 0.0)
    trace = on.get("trace") or {}
    n_cycles = 2 + cycles  # multicycle_bench's warmup + steady cycles
    spans_per_cycle = trace.get("spans_total", 0) / n_cycles
    device_span_names = {"solve_dispatch", "device_wait", "gate_dispatch",
                         "fit_histogram_dispatch", "fit_errors",
                         "audit_dispatch"}
    dev_spans_per_cycle = sum(
        c for name, c in (trace.get("stages") or {}).items()
        if name in device_span_names
    ) / n_cycles

    # micro: span enter/exit with full retention (ring + stage counters)
    tr = Tracer(
        recorder=FlightRecorder(
            ring=256, directory=tempfile.mkdtemp(prefix="kb-flight-bench-")
        ),
        enabled=True,
    )
    t0 = time.perf_counter()
    for _ in range(reps):
        with tr.span("bench"):
            pass
    span_ms = (time.perf_counter() - t0) / reps * 1e3
    # micro: the device-span counter probes (sampled at enter AND exit)
    t0 = time.perf_counter()
    for _ in range(1000):
        jitstats.total_compiles()
    jit_probe_ms = (time.perf_counter() - t0) / 1000 * 1e3
    probe_cache = synthetic_cluster(n_tasks=256, n_nodes=32, gang_size=4,
                                    n_queues=1)
    cols = probe_cache.columns
    t0 = time.perf_counter()
    for _ in range(1000):
        cols.resident_counters()
    scat_probe_ms = (time.perf_counter() - t0) / 1000 * 1e3
    probe_cache.stop()

    modeled_ms = (
        spans_per_cycle * span_ms
        + dev_spans_per_cycle * 2.0 * (jit_probe_ms + scat_probe_ms)
    )
    return {
        "pods": n_tasks, "nodes": n_nodes,
        "target": "overhead_pct < 2",
        "spans_per_cycle": round(spans_per_cycle, 1),
        "device_spans_per_cycle": round(dev_spans_per_cycle, 1),
        "span_cost_us": round(span_ms * 1e3, 3),
        "device_probe_cost_us": round(
            (jit_probe_ms + scat_probe_ms) * 1e3, 3),
        "trace_delta_ms_per_cycle": round(modeled_ms, 3),
        "steady_cycle_e2e_p50_ms": e2e_on,
        "overhead_pct": round(100.0 * modeled_ms / e2e_on, 3)
        if e2e_on > 0 else 0.0,
        # corroborating A/B pair (noise-dominated on a loaded CPU box —
        # the modeled number above is the acceptance figure)
        "e2e_p50_ms_trace_on": e2e_on,
        "e2e_p50_ms_trace_off": e2e_off,
        "ab_delta_pct": round(100.0 * (e2e_on - e2e_off) / e2e_off, 2)
        if e2e_off > 0 else 0.0,
        # zero NEW steady retraces with tracing on (the inertness half)
        "retraces_steady_trace_on": on.get("retraces_steady"),
        "retraces_attributed": trace.get("retraces_attributed"),
    }


def lock_profile_bench(conf, n_tasks=2_000, n_nodes=200, cycles=8,
                       feeders=2):
    """Lock-hold / acquire-wait profile over the pipelined cycle under
    concurrent staged ingest — the measurement the ROADMAP's 'striped
    per-kind ingest locks (profile first)' item asks for.  lockdep's
    TrackedLock accumulates per-lock-class wait/hold (per-thread, merged
    at report time); feeder threads stage gang arrivals through the real
    ingest surface while the pipelined loop cycles, so the profile shows
    whether the single staging buffer (or the cache big lock) actually
    contends before anyone pays for striping."""
    import threading

    from kube_batch_tpu.analysis import lockdep
    from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Pod, PodGroup
    from kube_batch_tpu.api.types import PodPhase
    from kube_batch_tpu.scheduler import Scheduler

    was_installed = lockdep.current_state() is not None
    state = lockdep.install()
    try:
        # the cache is built AFTER install so its locks are tracked
        cache = synthetic_cluster(
            n_tasks=n_tasks, n_nodes=n_nodes, gang_size=4, n_queues=2
        )
        cache.columns.reserve(
            n_tasks=n_tasks + 4 * feeders * cycles * 4,
            n_jobs=n_tasks // 4 + feeders * cycles * 4 + 8,
        )
        sched = Scheduler(cache, conf=conf)
        sched.run_once()  # warm the compiles outside the profiled window
        cache.enable_ingest_staging()
        stop_evt = threading.Event()

        def feeder(fid: int):
            i = 0
            while not stop_evt.is_set():
                name = f"lf{fid}-{i}"
                cache.add_pod_group(PodGroup(
                    name=name, namespace="lp", min_member=1, queue="q0",
                    creation_index=9_000_000 + fid * 100_000 + i,
                ))
                cache.add_pod(Pod(
                    name=f"{name}-0", namespace="lp",
                    requests={"cpu": 100.0, "memory": float(2 ** 28)},
                    annotations={GROUP_NAME_ANNOTATION: name},
                    phase=PodPhase.PENDING,
                    creation_index=90_000_000 + fid * 100_000 + i,
                ))
                i += 1
                time.sleep(0.002)

        threads = [threading.Thread(target=feeder, args=(f,), daemon=True)
                   for f in range(feeders)]
        for t in threads:
            t.start()
        for _ in range(cycles):
            sched.run_once_pipelined()
        stop_evt.set()
        for t in threads:
            t.join(timeout=10)
        sched.drain_pipeline()
        cache.disable_ingest_staging()
        if sched._wb_pool is not None:
            sched._wb_pool.shutdown(wait=True)
            sched._wb_pool = None
        cache.stop()
        prof = state.profile_report()
    finally:
        if not was_installed:
            lockdep.uninstall()
    # rank by total acquire-wait: the contention signal striping would fix
    top = dict(list(prof.items())[:10])
    cache_sites = {
        site: rec for site, rec in prof.items()
        if "cache.cache" in site
    }
    total_wait = sum(r["wait_ms_total"] for r in prof.values())
    ingest_wait = sum(r["wait_ms_total"] for r in cache_sites.values())
    return {
        "pods": n_tasks, "nodes": n_nodes, "cycles": cycles,
        "feeder_threads": feeders,
        "total_wait_ms": round(total_wait, 3),
        "cache_lock_wait_ms": round(ingest_wait, 3),
        "top_sites_by_wait": top,
    }


def collective_evidence(n_tasks, n_nodes):
    """Per-round cross-shard byte accounting of the shard_map allocate
    solve, TRACED at the bench's real padded shapes (utils/jitstats.
    collective_inventory over the program XLA compiles — measured from the
    jaxpr, not asserted).  The scaling proof: re-trace with the node count
    doubled at fixed tasks (per-round bytes must not move — the round
    collectives are the O(tasks) winner-vector reductions) and with the
    task count doubled (bytes must ~double)."""
    from kube_batch_tpu.analysis.jaxpr_audit import abstract_snapshot
    from kube_batch_tpu.api.snapshot import bucket
    from kube_batch_tpu.parallel.mesh import (
        collective_stats,
        default_mesh,
        shard_map_enabled,
    )

    mesh = default_mesh()
    if mesh is None:
        return {"skipped": "single-device backend"}
    if not shard_map_enabled():
        return {"skipped": "KB_SHARD_MAP=0 (pjit oracle path)"}
    J, Q = bucket(max(1, n_tasks // 4)), 8

    def stats(t, n):
        return collective_stats(
            mesh, snap=abstract_snapshot(T=bucket(t), N=bucket(n), J=J, Q=Q)
        )

    base = stats(n_tasks, n_nodes)
    nodes2 = stats(n_tasks, 2 * n_nodes)
    tasks2 = stats(2 * n_tasks, n_nodes)
    rounds = get_action("allocate").last_solve_rounds
    return {
        "mesh": base["mesh"],
        "task_bucket": base["task_bucket"],
        "node_bucket": base["node_bucket"],
        "per_round_bytes": base["per_round_bytes"],
        # the one-time node-ledger all_gather (O(N·R) per SOLVE, not round)
        "per_solve_bytes": base["per_solve_bytes"],
        "ops": base["ops"],
        # measured rounds of the last cycle × traced per-round bytes = the
        # cycle's cross-shard budget
        "rounds_last_cycle": rounds,
        "bytes_last_cycle": (
            base["per_solve_bytes"]
            + base["per_round_bytes"] * max(rounds, 1)
        ),
        "per_round_bytes_nodes_x2": nodes2["per_round_bytes"],
        "per_round_bytes_tasks_x2": tasks2["per_round_bytes"],
        "per_round_scales_with_tasks": bool(
            nodes2["per_round_bytes"] == base["per_round_bytes"]
            and tasks2["per_round_bytes"] > base["per_round_bytes"]
        ),
        # the compacted program's contract: after the ONE per-solve
        # candidate merge + node-column gathers, rounds cross zero bytes
        "topk": _topk_collective_evidence(n_tasks, n_nodes, J, Q),
    }


def _topk_collective_evidence(n_tasks, n_nodes, J, Q):
    from kube_batch_tpu.actions.allocate import TOPK_PEND_BUCKETS, resolve_topk
    from kube_batch_tpu.analysis.jaxpr_audit import abstract_snapshot
    from kube_batch_tpu.api.snapshot import bucket
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.parallel.mesh import collective_stats, default_mesh

    k = resolve_topk()
    if not k:
        # KB_TOPK=0: the measured cycles dispatched the full program —
        # emitting compacted-path evidence here would attribute it to a
        # run that never executed the compacted solve
        return {"disabled": "KB_TOPK=0 (full-matrix oracle run)"}
    st = collective_stats(
        default_mesh(), config=AllocateConfig(topk=k),
        snap=abstract_snapshot(T=bucket(n_tasks), N=bucket(n_nodes), J=J, Q=Q),
        pend_bucket=TOPK_PEND_BUCKETS[0],
    )
    return {
        "k": k,
        "pend_bucket": st["pend_bucket"],
        "per_round_bytes": st["per_round_bytes"],
        "per_solve_bytes": st["per_solve_bytes"],
        "zero_round_collectives": st["per_round_bytes"] == 0,
    }


def hbm_round_head_model(T=500_000, N=50_000, R=8, node_ring=8,
                         hbm_gb=16.0):
    """Per-device residency model of the [T, N]-scale round-head
    intermediates at the 500k×50k north star: ~14 live bytes per
    (task, node) block element at the round peak (masked+score_static f32,
    tie-hash i32, fit/static bools).  The node axis shards along one
    fixed-width ICI ring (``node_ring``); extra devices can only join the
    TASK axis — which is exactly when 2-D sharding is the difference
    between fitting the 16 GB v5e HBM and not.  The task-axis bench probe
    pairs this model with an actually-completed 2-D-mesh cycle."""
    BYTES_PER_ELT = 14
    budget = hbm_gb * 2**30
    rows = []
    for ts in (1, 2, 4, 8):
        per_dev = (T / ts) * (N / node_ring) * BYTES_PER_ELT
        rows.append({
            "task_shards": ts,
            "devices": ts * node_ring,
            "round_head_gb": round(per_dev / 2**30, 1),
            "fits_hbm": bool(per_dev < budget),
        })
    return {
        "tasks": T, "nodes": N, "node_ring": node_ring,
        "hbm_gb": hbm_gb, "bytes_per_elt": BYTES_PER_ELT,
        "configs": rows,
    }


def hbm_headroom_bench():
    """The tier-C audit's bytes-vs-budget numbers as a bench section, so
    the headroom trajectory is tracked across PRs like any other perf
    number.  Tracing is abstract (no device memory, backend-independent):
    the peaks are the liveness model's per-device bytes at each ladder
    point — see analysis/hbm_audit.py for the model and its documented
    overestimate-direction slack.  Entries that fail to trace at a point
    record ``traced: false`` (the audit's KBT000 covers the alarm)."""
    from kube_batch_tpu.analysis.hbm_audit import GIB, headroom_report

    rep = headroom_report()
    entries = {}
    worst = None
    for name, per_point in rep["entries"].items():
        compact = {}
        for pt, d in per_point.items():
            if not d["traced"]:
                compact[pt] = {"traced": False}
                continue
            compact[pt] = {
                "peak_gib": round(d["peak_bytes"] / GIB, 3),
                "headroom_gib": round(d["headroom_bytes"] / GIB, 3),
                "over_budget": d["over_budget"],
            }
            if worst is None or d["peak_bytes"] > worst[2]:
                worst = (name, pt, d["peak_bytes"])
        entries[name] = compact
    out = {
        "budget_gib": round(rep["budget_bytes"] / GIB, 1),
        "budget_profile": rep["budget_profile"],
        "points": {
            p["name"]: {"tasks": p["tasks"], "nodes": p["nodes"],
                        "T": p["T"], "N": p["N"], "P": p["P"]}
            for p in rep["points"]
        },
        "entries": entries,
    }
    if worst is not None:
        out["worst"] = {
            "entry": worst[0], "point": worst[1],
            "peak_gib": round(worst[2] / GIB, 3),
        }
    return out


def task_axis_probe(conf, n_tasks, n_nodes, cycles=3):
    """The task-axis-sharded cycle: rerun the steady-state regime on a 2-D
    (tasks=2 × nodes) mesh (KB_TASK_SHARDS=2) and report that the cycle
    completes sharded with zero steady retraces, next to the HBM model
    showing the node×task sizes only the 2-D mesh can hold resident."""
    import jax

    n_dev = len(jax.devices())
    if n_dev < 4 or n_dev % 2:
        return {"skipped": f"{n_dev} devices (need an even count >= 4)",
                "hbm_model": hbm_round_head_model()}
    saved = os.environ.get("KB_TASK_SHARDS")
    os.environ["KB_TASK_SHARDS"] = "2"
    try:
        rep = multicycle_bench(conf, n_tasks, n_nodes, cycles=cycles)
    finally:
        if saved is None:
            os.environ.pop("KB_TASK_SHARDS", None)
        else:
            os.environ["KB_TASK_SHARDS"] = saved
    return {
        "task_shards": 2,
        "solve_mode": rep.get("solve_mode"),
        "steady_e2e_ms": rep.get("steady", {}).get("e2e"),
        "retraces_steady": rep.get("retraces_steady"),
        "resident_scatter": rep.get("resident_scatter"),
        "hbm_model": hbm_round_head_model(),
    }


def sharded_multicycle(conf, n_tasks, n_nodes, cycles=6):
    """The sharded steady-state section: the multicycle regime (persistent
    cache, 2% churn, ±10% wobble) dispatched over the device mesh — reports
    the per-shard delta-vs-full upload reduction, the retrace counters,
    the traced per-round collective-bytes evidence, and the task-axis
    (2-D mesh) probe.  Requires ≥2 devices and a node axis past the shard
    gate."""
    import jax

    from kube_batch_tpu.parallel.mesh import SHARD_MIN_NODES

    if len(jax.devices()) < 2:
        return {"skipped": "single-device backend"}
    if n_nodes < SHARD_MIN_NODES:
        return {"skipped": f"node axis below shard gate ({SHARD_MIN_NODES})"}
    rep = multicycle_bench(conf, n_tasks, n_nodes, cycles=cycles)
    if rep.get("solve_mode") != "sharded":
        rep["warning"] = "solve did not dispatch sharded"
    try:
        rep["collectives"] = collective_evidence(n_tasks, n_nodes)
    except Exception as e:  # noqa: BLE001 — evidence must not sink the bench
        rep["collectives_error"] = f"{type(e).__name__}: {e}"
    try:
        # probe at a bounded size: the 2-D mesh's point is the HBM model +
        # a completed sharded cycle, not a second full-scale run
        rep["task_axis"] = task_axis_probe(
            conf, min(n_tasks, 2000), min(n_nodes, 600)
        )
    except Exception as e:  # noqa: BLE001
        rep["task_axis_error"] = f"{type(e).__name__}: {e}"
    return rep


def whatif_serving_bench(conf, n_tasks=20_000, n_nodes=2_000,
                         n_clients=16, requests_per_client=25):
    """The serve/ query-plane bench (ISSUE 8): N concurrent what-if
    clients against a 20k×2k snapshot, driven straight at
    ``QueryPlane.submit`` (the HTTP hop is constant per request and
    covered by the check.sh smoke — this section measures the batcher +
    probe dispatch).  Reports p50/p99 request latency, achieved QPS, mean
    batch size, and dispatches per 100 requests; the amortization claim is
    dispatch counter < requests (many requests per device dispatch) with
    ZERO probe retraces after warmup across varying batch fill."""
    import threading

    import numpy as np

    from kube_batch_tpu.serve.plane import QueryPlane
    from kube_batch_tpu.utils import jitstats

    cache = synthetic_cluster(
        n_tasks=n_tasks, n_nodes=n_nodes, gang_size=4, n_queues=3
    )
    qp = QueryPlane(cache, max_batch=32, window_s=0.002, start_thread=True)
    try:
        one_cycle(conf, cache)  # the cycle publishes the snapshot lease
        gib = float(2 ** 30)

        def ask(count, cpu):
            return {"queue": "q0", "count": count,
                    "requests": {"cpu": cpu, "memory": gib}}

        def probe_compiles():
            # every probe path — single-device "probe_solve" AND the
            # per-mesh "sharded_probe_solve[impl]" registrations — so the
            # zero-retrace claim measures whichever path serving took
            return sum(v for k, v in jitstats.compile_counts().items()
                       if "probe_solve" in k)

        # warmup: compile the probe at the serving (B, G) buckets
        for count in (1, 3, 8):
            qp.submit(ask(count, 500.0)).result(timeout=300)
        compiles0 = probe_compiles()
        req0, disp0 = qp.requests_served, qp.dispatches

        lat: list = []
        errors: list = []
        lock = threading.Lock()

        def client(k):
            rng = np.random.default_rng(k)
            mine = []
            try:
                for _ in range(requests_per_client):
                    body = ask(int(rng.integers(1, 9)),
                               float(rng.choice([250.0, 1000.0, 4000.0])))
                    t0 = time.perf_counter()
                    resp = qp.submit(body).result(timeout=300)
                    mine.append((time.perf_counter() - t0) * 1e3)
                    assert "feasible" in resp
            except Exception as e:  # noqa: BLE001 — surface, don't hang
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return
            with lock:
                lat.extend(mine)

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        elapsed = time.perf_counter() - t0
        total = qp.requests_served - req0
        dispatches = qp.dispatches - disp0
        retraces = probe_compiles() - compiles0
        out = {
            "n_tasks": n_tasks,
            "n_nodes": n_nodes,
            "clients": n_clients,
            "requests": total,
            "whatif_p50_ms": round(_pct(lat, 0.50), 2) if lat else None,
            "whatif_p99_ms": round(_pct(lat, 0.99), 2) if lat else None,
            "qps": round(total / elapsed, 1) if elapsed > 0 else None,
            "device_dispatches": dispatches,
            "mean_batch_size": round(total / dispatches, 2) if dispatches else None,
            "dispatches_per_100_requests": (
                round(100.0 * dispatches / total, 1) if total else None
            ),
            # the acceptance pair: amortized (≫1 request per dispatch) and
            # no steady-state retraces across varying batch fill
            "amortized": bool(total > dispatches > 0),
            "retraces_after_warmup": retraces,
        }
        if errors:
            out["client_errors"] = errors[:3]
        return out
    finally:
        qp.close()


def replication_serving_bench(conf, n_tasks=1_000, n_nodes=96,
                              clients_per_follower=4,
                              requests_per_client=25):
    """The replicate/ follower read plane's horizontal-scale evidence: a
    leader (publisher + AdminServer) with 1→3 REAL follower processes
    (``--follower`` subprocesses, own devices + probe executables each)
    serving /v1/whatif over loopback HTTP.  Offered load grows with the
    follower count (``clients_per_follower`` threads per live follower),
    so aggregate QPS should scale ~linearly while the leader pays one
    record encode per cycle regardless of fan-out.  Followers run pinned
    to CPU (hardened_cpu_env) — the section measures read-plane scaling
    against itself, and a TPU leader must not share its devices with
    bench children.  Also records the one-time evidence that each
    follower bit-matches the leader verdict on the frozen snapshot and
    reports zero staleness lag."""
    import socket
    import threading
    import urllib.request

    from kube_batch_tpu.cmd.server import AdminServer
    from kube_batch_tpu.envutil import hardened_cpu_env
    from kube_batch_tpu.replicate.publisher import ReplicationPublisher
    from kube_batch_tpu.serve.plane import QueryPlane

    gib = float(2 ** 30)
    body = json.dumps({"queue": "q0", "count": 2,
                       "requests": {"cpu": 500.0, "memory": gib}}).encode()

    def post(url, data=body, timeout=60):
        req = urllib.request.Request(
            url + "/v1/whatif", data=data,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode())

    cache = synthetic_cluster(
        n_tasks=n_tasks, n_nodes=n_nodes, gang_size=4, n_queues=2
    )
    cache.replication = pub = ReplicationPublisher()
    qp = QueryPlane(cache, max_batch=16, window_s=0.002, start_thread=True)
    srv = AdminServer(cache, port=0, query_plane=qp)
    srv.start()
    leader_url = f"http://127.0.0.1:{srv.port}"
    procs, out = [], {}
    try:
        one_cycle(conf, cache)  # publish the lease + replication record
        pub.barrier()

        ports = []
        for _ in range(3):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
            s.close()
        env = hardened_cpu_env()
        for port in ports:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "kube_batch_tpu.cmd.main",
                 "--follower", leader_url,
                 "--listen-address", f"127.0.0.1:{port}"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            ))
        urls = [f"http://127.0.0.1:{p}" for p in ports]

        # readiness: the pull loop has adopted a snapshot once /v1/whatif
        # answers 200 (it 503s before the first lease); then a warm probe
        # per follower so subprocess compile never lands in the timed window
        deadline = time.perf_counter() + 300
        for url in urls:
            while True:
                try:
                    resp = post(url, timeout=10)
                    if "feasible" in resp:
                        break
                except Exception:  # noqa: BLE001 — still starting up
                    pass
                if time.perf_counter() > deadline:
                    raise RuntimeError(f"follower at {url} never became "
                                       f"ready (subprocess startup)")
                time.sleep(0.5)

        # frozen-snapshot evidence: every follower must answer the leader's
        # verdict byte-identically, at zero reported lag
        want = json.dumps(post(leader_url), sort_keys=True)
        matches = [json.dumps(post(u), sort_keys=True) == want for u in urls]
        lags = [post(u)["staleness"]["lag_cycles"] for u in urls]

        def drive(n_followers: int) -> dict:
            lat: list = []
            lock = threading.Lock()

            def client(k):
                url = urls[k % n_followers]
                mine = []
                for _ in range(requests_per_client):
                    t0 = time.perf_counter()
                    post(url)
                    mine.append((time.perf_counter() - t0) * 1e3)
                with lock:
                    lat.extend(mine)

            threads = [threading.Thread(target=client, args=(k,))
                       for k in range(n_followers * clients_per_follower)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            elapsed = time.perf_counter() - t0
            return {
                "clients": len(threads),
                "requests": len(lat),
                "qps": round(len(lat) / elapsed, 1) if elapsed > 0 else None,
                "p50_ms": round(_pct(lat, 0.50), 2) if lat else None,
                "p99_ms": round(_pct(lat, 0.99), 2) if lat else None,
            }

        scale = {k: drive(k) for k in (1, 2, 3)}
        q1, q3 = scale[1]["qps"], scale[3]["qps"]
        out = {
            "n_tasks": n_tasks, "n_nodes": n_nodes,
            "bit_match_all_followers": bool(all(matches)),
            "staleness_lag_cycles": lags,
            "qps_by_follower_count": {str(k): v for k, v in scale.items()},
            "scaling_1_to_3": round(q3 / q1, 2) if q1 else None,
            "leader_records": pub.counters(),
        }
        return out
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
        srv.stop()
        qp.close()
        pub.close()


def pipelined_bench(conf, n_tasks=400, n_nodes=48, arrivals=10,
                    period=1.0, seed=0):
    """Event-driven pipelined cycles (ISSUE 9): the arrival→decision
    latency a user actually observes, measured live — a feeder thread
    posts single-pod gangs at random offsets while the L1 loop runs in
    (a) the reference's serial wait.Until(1 s) shape and (b) the
    event-driven pipelined mode (ingest staging + trigger wake + staged
    close with the writeback worker).  Same arrival stream, same warmed
    cache shape; the serial loop's latency is dominated by the tick (mean
    ~period/2, p99 → period), the pipelined loop's by its min-period
    floor.  Also reports the overlap gain: the writeback ms each pipelined
    cycle hides behind the next cycle's compute, and zero steady retraces
    on both paths."""
    import threading

    import numpy as np

    from kube_batch_tpu import metrics as prom_metrics
    from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Pod, PodGroup
    from kube_batch_tpu.api.types import PodPhase
    from kube_batch_tpu.metrics.metrics import PIPELINE_OVERLAP
    from kube_batch_tpu.scheduler import Scheduler
    from kube_batch_tpu.utils import jitstats

    def one_mode(pipelined: bool) -> dict:
        cache = synthetic_cluster(
            n_tasks=n_tasks, n_nodes=n_nodes, gang_size=4, n_queues=3
        )
        sched = Scheduler(cache, conf=conf, schedule_period=period)
        sched.pipelined = pipelined
        sched.min_period = 0.02
        sched.max_period = period
        # pre-reserve the feed's axis growth so it lands in a pre-warmed
        # bucket — the zero-retrace claim must hold through the arrivals
        cache.columns.reserve(
            n_tasks=n_tasks + 4 * arrivals,
            n_jobs=n_tasks // 4 + 4 * arrivals,
        )
        # warmup: compile + place the synthetic backlog before the feed
        for _ in range(2):
            sched.run_once()
        sink: list = []
        prom_metrics.set_decision_latency_sink(sink)
        compiles0 = jitstats.total_compiles()
        overlap0 = (PIPELINE_OVERLAP._sum.get((), 0.0),
                    PIPELINE_OVERLAP._count.get((), 0))
        rng = np.random.default_rng(seed)
        offsets = rng.uniform(0.15, 0.45, size=arrivals)
        fed: list = []

        def feeder():
            for i, dt in enumerate(offsets):
                time.sleep(float(dt))
                name = f"arr{i}"
                cache.add_pod_group(PodGroup(
                    name=name, namespace="feed", min_member=1, queue="q0",
                    creation_index=5_000_000 + i,
                ))
                cache.add_pod(Pod(
                    name=f"{name}-0", namespace="feed",
                    requests={"cpu": 250.0, "memory": float(2 ** 30)},
                    annotations={GROUP_NAME_ANNOTATION: name},
                    phase=PodPhase.PENDING,
                    creation_index=50_000_000 + i,
                ))
                fed.append(f"feed/{name}-0")

        loop = threading.Thread(target=sched.run_forever, daemon=True)
        feed = threading.Thread(target=feeder, daemon=True)
        try:
            loop.start()
            feed.start()
            feed.join(timeout=60)
            deadline = time.perf_counter() + 6 * period + 10
            while time.perf_counter() < deadline:
                if len(sink) >= arrivals:
                    break
                time.sleep(0.05)
        finally:
            sched.stop()
            loop.join(timeout=30)
            prom_metrics.set_decision_latency_sink(None)
        retraces = jitstats.total_compiles() - compiles0
        out = {
            "mode": "pipelined" if pipelined else "serial",
            "arrivals": arrivals,
            "decided": len(sink),
            "p50_ms": round(_pct(sink, 0.50), 1) if sink else None,
            "p99_ms": round(_pct(sink, 0.99), 1) if sink else None,
            "mean_ms": round(sum(sink) / len(sink), 1) if sink else None,
            "retraces_steady": retraces,
        }
        if pipelined:
            ov_sum = PIPELINE_OVERLAP._sum.get((), 0.0) - overlap0[0]
            ov_n = PIPELINE_OVERLAP._count.get((), 0) - overlap0[1]
            out["writeback_overlapped_ms_mean"] = (
                round(ov_sum / ov_n, 2) if ov_n else None
            )
            out["writeback_stages"] = ov_n
        return out

    serial = one_mode(False)
    pipe = one_mode(True)
    ratio = None
    if serial["p99_ms"] and pipe["p99_ms"]:
        ratio = round(serial["p99_ms"] / pipe["p99_ms"], 2)
    return {
        "n_tasks": n_tasks,
        "n_nodes": n_nodes,
        "period_s": period,
        "serial": serial,
        "pipelined": pipe,
        # the acceptance pair: arrival→decision p99 ≥2× better than the
        # fixed tick, with zero steady retraces on BOTH paths
        "p99_improvement": ratio,
        "acceptance_2x": bool(ratio is not None and ratio >= 2.0
                              and serial["retraces_steady"] == 0
                              and pipe["retraces_steady"] == 0),
    }


def main() -> None:
    if os.environ.get("KB_BENCH_SHARDED_CHILD") == "1":
        # forced-host-device child (CPU fallback's sharded evidence): a
        # small sharded steady-state run, one JSON line on stdout
        conf = load_scheduler_conf(None)
        print(json.dumps(
            {"multicycle_sharded": sharded_multicycle(conf, 2000, 600,
                                                      cycles=6)}
        ))
        return

    start = time.perf_counter()
    # soft deadline for the optional sections: the headline number and the
    # TPU capture must land even if compiles run long — better a JSON line
    # missing pipeline5/het30 than a driver timeout with no line at all
    deadline_s = float(os.environ.get("KB_BENCH_DEADLINE", "420"))

    conf = load_scheduler_conf(None)  # default: allocate, backfill
    # CPU fallback (wedged tunnel): one trimmed headline pass only, citing
    # the last committed BENCH_TPU.json capture as corroborating evidence —
    # a ~20s/cycle CPU run of every section would blow the driver's timeout
    note = os.environ.get("KB_BENCH_BACKEND_NOTE", "")
    fallback = note == "cpu_fallback"  # only the self-re-exec sets this
    cycles = 2 if fallback else CYCLES

    def make_cache():
        return synthetic_cluster(
            n_tasks=N_TASKS, n_nodes=N_NODES, gang_size=4, n_queues=3
        )

    p50, phase_p50, phase_p90, warmup_ms, placed = measure(
        conf, make_cache, cycles
    )
    solve_rounds = get_action("allocate").last_solve_rounds
    metric = (
        f"full_cycle_ms_{N_TASKS // 1000}k_pods_"
        f"{N_NODES // 1000}k_nodes_placed_{placed}"
    )
    if note:
        metric += f"_{note}"
    result = {
        "metric": metric,
        "value": round(p50, 2),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / p50, 2),
        "phases": phase_p50,
        "phases_p90": phase_p90,
        # the compile cycle, labeled apart from the steady percentiles —
        # a retrace regression shows up HERE, not smeared into the p50
        "warmup_cycle_ms": round(warmup_ms, 1),
        # measured convergence of the final timed cycle's solve (the
        # while_loops early-exit well inside the 6x3 round budget)
        "solve_rounds": solve_rounds,
    }

    if fallback:
        # the multi-cycle steady-state evidence is backend-independent (the
        # acceptance criterion reads "any backend"): a trimmed pair still
        # proves the delta-vs-full reduction and the zero-retrace wobble
        try:
            mc_d, mc_f, red = run_multicycle_pair(conf, 6_000, 600, cycles=8)
            result["multicycle"] = mc_d
            result["multicycle_full_rebuild"] = mc_f
            result["multicycle_open_snapshot_reduction"] = red
        except Exception as e:  # noqa: BLE001 — the JSON line must land
            result["multicycle_error"] = f"{type(e).__name__}: {e}"
        # compacted-vs-full solve comparison at the ISSUE 10 acceptance
        # shape (20k×2k, CPU) — the ≥2× solve-phase p50 evidence
        try:
            result["topk_compare"] = run_topk_pair(
                conf, 20_000, 2_000, cycles=4
            )
        except Exception as e:  # noqa: BLE001
            result["topk_compare_error"] = f"{type(e).__name__}: {e}"
        # warm-vs-cold carried-table comparison at the same regime (ISSUE
        # 14's ≥3× solve-phase target at ≤2% churn)
        try:
            result["incremental_solve"] = run_warm_pair(
                conf, 20_000, 2_000, cycles=4
            )
        except Exception as e:  # noqa: BLE001
            result["incremental_solve_error"] = f"{type(e).__name__}: {e}"
        # span-recorder overhead (<2% of steady p50, zero new retraces) +
        # the lockdep contention profile — modeled-cost methodology, valid
        # on any backend (ISSUE 13 acceptance)
        try:
            result["trace_overhead"] = trace_overhead_bench(
                conf, cycles=4
            )
        except Exception as e:  # noqa: BLE001
            result["trace_overhead_error"] = f"{type(e).__name__}: {e}"
        try:
            result["lock_profile"] = lock_profile_bench(conf, cycles=6)
        except Exception as e:  # noqa: BLE001
            result["lock_profile_error"] = f"{type(e).__name__}: {e}"
        # tier-C HBM headroom: abstract traces, identical on any backend —
        # a wedged tunnel changes nothing about the liveness model's bytes
        try:
            result["hbm_headroom"] = hbm_headroom_bench()
        except Exception as e:  # noqa: BLE001
            result["hbm_headroom_error"] = f"{type(e).__name__}: {e}"
        # sharded steady-state evidence on a forced 4-device host mesh — a
        # child process, because the device count must be fixed before the
        # child's jax initializes (this process is already single-device)
        try:
            from kube_batch_tpu.envutil import hardened_cpu_env

            env = hardened_cpu_env(n_devices=4)
            env.update(KB_BENCH_CHILD="1", KB_BENCH_SHARDED_CHILD="1")
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True, timeout=600,
            )
            line = out.stdout.strip().splitlines()[-1]
            result["multicycle_sharded"] = json.loads(line)[
                "multicycle_sharded"]
        except Exception as e:  # noqa: BLE001
            result["multicycle_sharded_error"] = f"{type(e).__name__}: {e}"
        # serving evidence is backend-independent (amortization + retrace
        # counters, not absolute latency) — run the full 20k×2k section
        try:
            result["whatif_serving"] = whatif_serving_bench(conf)
        except Exception as e:  # noqa: BLE001
            result["whatif_serving_error"] = f"{type(e).__name__}: {e}"
        # follower read-plane scaling is loopback-HTTP + CPU followers —
        # backend-independent by construction
        try:
            result["replication_serving"] = replication_serving_bench(conf)
        except Exception as e:  # noqa: BLE001
            result["replication_serving_error"] = f"{type(e).__name__}: {e}"
        # arrival→decision latency is a POLICY number (tick vs trigger),
        # valid on any backend — the ≥2× acceptance evidence runs here too
        try:
            result["pipelined"] = pipelined_bench(conf)
        except Exception as e:  # noqa: BLE001
            result["pipelined_error"] = f"{type(e).__name__}: {e}"
        # the go-loop denominators are CPU measurements — valid evidence
        # even on a wedged tunnel; the meaningful ratio is against the last
        # committed TPU capture's cycle, not this fallback run's
        try:
            from kube_batch_tpu.testing.go_baseline import run_go_baseline

            go_stats = run_go_baseline(N_TASKS, N_NODES, gang_size=4, n_queues=3)
            result["go_loop_ms"] = round(go_stats["elapsed_ms"], 1)
            for k in ("native_single_ms", "native_pooled_ms",
                      "native_single_divergence", "native_pooled_divergence"):
                if k in go_stats:
                    result[f"go_loop_{k}"] = go_stats[k]
        except Exception as e:  # noqa: BLE001
            result["go_loop_error"] = f"{type(e).__name__}: {e}"
        _emit(result, tpu_capture_note=True)
        return

    skipped = []

    def section(name, margin_s=0.0):
        """Deadline gate: a completed section merges into the capture; a
        skipped one is recorded and keeps its previously captured value.
        `margin_s` is the section's worst-case runtime — checked up front,
        because the deadline can't interrupt a section mid-flight and a
        case started just under the wire would blow the driver timeout."""
        if time.perf_counter() - start + margin_s > deadline_s:
            skipped.append(name)
            return False
        return True

    import contextlib

    @contextlib.contextmanager
    def guarded(name):
        """A failing section (e.g. a Mosaic compile error in the Pallas
        probe) records its error and lets the later sections still run —
        the JSON line and the capture must land regardless."""
        try:
            yield
        except Exception as e:  # noqa: BLE001
            result[f"{name}_error"] = f"{type(e).__name__}: {e}"

    # ---- steady-state multi-cycle regime (cross-cycle resident snapshot):
    # delta vs forced-full-rebuild on the same host, plus the zero-retrace
    # proof across the ±10% pod-count wobble — the PR's acceptance evidence
    if section("multicycle", margin_s=150):
        with guarded("multicycle"):
            mc_d, mc_f, red = run_multicycle_pair(
                conf, N_TASKS, N_NODES, cycles=8
            )
            result["multicycle"] = mc_d
            result["multicycle_full_rebuild"] = mc_f
            result["multicycle_open_snapshot_reduction"] = red

    # ---- compacted-vs-full solve comparison (ISSUE 10): the top-K
    # candidate table's ≥2× solve-phase p50 claim at the 20k×2k regime,
    # with the compacted run's exhaustion/retrace counters
    if section("topk_compare", margin_s=150):
        with guarded("topk_compare"):
            result["topk_compare"] = run_topk_pair(
                conf, 20_000, 2_000, cycles=6
            )

    # ---- warm-vs-cold solve comparison (ISSUE 14): the carried candidate
    # table's ≥3× solve-phase p50 claim at ≤2% gang churn (20k×2k, CPU),
    # with the per-cycle invalidated-row fraction and zero steady retraces
    if section("incremental_solve", margin_s=320):
        with guarded("incremental_solve"):
            result["incremental_solve"] = run_warm_pair(
                conf, 20_000, 2_000, cycles=6
            )

    # ---- result-integrity guard overhead: the fused sentinel's cost on
    # the steady cycle must stay under 5% of p50 (the verdict rides the
    # existing per-action readback; audit cycles are overlapped work)
    if section("guard_overhead", margin_s=150):
        with guarded("guard_overhead"):
            result["guard_overhead"] = guard_overhead_bench(conf)

    # ---- cycle tracing plane (ISSUE 13): the span recorder's cost vs the
    # steady p50 must stay under 2% with zero new steady retraces, and the
    # lockdep contention profile answers the striped-ingest-lock question
    if section("trace_overhead", margin_s=200):
        with guarded("trace_overhead"):
            result["trace_overhead"] = trace_overhead_bench(conf)
    if section("lock_profile", margin_s=60):
        with guarded("lock_profile"):
            result["lock_profile"] = lock_profile_bench(conf)

    # ---- tier-C HBM headroom: the liveness audit's peak-live-bytes vs the
    # v5e budget per entry per ladder point — abstract traces only, so the
    # numbers are identical on any backend and regress visibly in the JSON
    if section("hbm_headroom", margin_s=90):
        with guarded("hbm_headroom"):
            result["hbm_headroom"] = hbm_headroom_bench()

    # ---- the SHARDED steady-state regime: same persistent-cache churn
    # cycle over the device mesh — the per-shard scatter-delta residency's
    # bytes-moved reduction and zero-retrace proof (this PR's acceptance)
    if section("multicycle_sharded", margin_s=150):
        with guarded("multicycle_sharded"):
            result["multicycle_sharded"] = sharded_multicycle(
                conf, N_TASKS, N_NODES
            )

    # ---- the serve/ query plane: concurrent what-if clients against a
    # 20k×2k snapshot — request latency, QPS, and the amortization proof
    # (dispatches ≪ requests, zero retraces across varying batch fill)
    if section("whatif_serving", margin_s=120):
        with guarded("whatif_serving"):
            result["whatif_serving"] = whatif_serving_bench(conf)

    # ---- the replicate/ follower read plane: 1→3 real --follower
    # subprocesses against a publishing leader — aggregate /v1/whatif QPS
    # must scale ~linearly with the follower count, each follower
    # bit-matching the leader's frozen-snapshot verdict at zero lag
    if section("replication_serving", margin_s=360):
        with guarded("replication_serving"):
            result["replication_serving"] = replication_serving_bench(conf)

    # ---- event-driven pipelined cycles: live arrival→decision latency,
    # serial 1 s tick vs trigger-driven loop, + the writeback overlap gain
    if section("pipelined", margin_s=60):
        with guarded("pipelined"):
            result["pipelined"] = pipelined_bench(conf)

    # ---- ≥10×-vs-Go-loop target (BASELINE.md): time the faithful
    # sequential re-creation of the reference's allocate loop over the same
    # workload.  Three denominators bracket the reference (measured, not
    # argued — go_baseline module docstring): the numpy re-creation, the
    # whole loop in compiled C single-threaded (maximally generous), and
    # the C loop with the reference's 16-worker chunked pass.
    if section("go_loop", margin_s=45):
        with guarded("go_loop"):
            from kube_batch_tpu.testing.go_baseline import run_go_baseline

            go_stats = run_go_baseline(N_TASKS, N_NODES, gang_size=4, n_queues=3)
            result["go_loop_ms"] = round(go_stats["elapsed_ms"], 1)
            result["speedup_vs_go_loop"] = round(go_stats["elapsed_ms"] / p50, 1)
            if "native_single_ms" in go_stats:
                result["go_loop_native_single_ms"] = go_stats["native_single_ms"]
                result["speedup_vs_go_loop_native_single"] = round(
                    go_stats["native_single_ms"] / p50, 2
                )
            if "native_pooled_ms" in go_stats:
                result["go_loop_native_pooled_ms"] = go_stats["native_pooled_ms"]
                result["speedup_vs_go_loop_native_pooled"] = round(
                    go_stats["native_pooled_ms"] / p50, 2
                )
            # a diverging C run reports a divergence count INSTEAD of a time —
            # surface it so the invalid-denominator state is visible in the
            # artifact rather than reading like a missing toolchain
            for k in ("native_single_divergence", "native_pooled_divergence"):
                if k in go_stats:
                    result[f"go_loop_{k}"] = go_stats[k]

    # ---- Pallas round-head vs XLA on the real backend (VERDICT r3 #2):
    # the hardware number that decides the kernel's fate
    import jax

    if jax.default_backend() != "cpu" and section("pallas_roundhead", margin_s=90):
        with guarded("pallas_roundhead"):
            from kube_batch_tpu.testing.pallas_bench import compare_roundhead

            result["pallas_roundhead"] = compare_roundhead(N_TASKS, N_NODES)

    # ---- the SHIPPED 5-action pipeline (enqueue, reclaim, allocate,
    # backfill, preempt — config/kube-batch-tpu-conf.yaml) at the same
    # 50k×5k scale; podgroups start Pending so enqueue has real work
    from kube_batch_tpu.api.types import PodGroupPhase

    if section("pipeline5", margin_s=180):
        with guarded("pipeline5"):
            from kube_batch_tpu.framework.conf import shipped_conf_path

            conf5 = load_scheduler_conf(shipped_conf_path())

            def pending_cluster():
                cache = synthetic_cluster(
                    n_tasks=N_TASKS, n_nodes=N_NODES, gang_size=4, n_queues=3
                )
                for job in cache.jobs.values():
                    if job.pod_group is not None:
                        job.pod_group.phase = PodGroupPhase.PENDING
                return cache

            p50_5, phases5_p50, _phases5_p90, _w5, placed5 = measure(
                conf5, pending_cluster, 3
            )
            result["pipeline5_ms"] = round(p50_5, 2)
            result["pipeline5_placed"] = placed5
            result["pipeline5_vs_headline"] = round(p50_5 / p50, 2)
            result["pipeline5_phases"] = phases5_p50

    # ---- heterogeneous-constraints case (BASELINE config #5 / VERDICT r2
    # weak #6): 30% of tasks carry hostPorts, routing their jobs through the
    # fallback machinery — must stay within ~2× the homogeneous cycle
    if section("het30", margin_s=120):
        with guarded("het30"):

            def het_cluster():
                return synthetic_cluster(
                    n_tasks=N_TASKS, n_nodes=N_NODES, gang_size=4, n_queues=3,
                    host_ports_frac=0.3,
                )

            p50_het, _, _, _, placed_het = measure(conf, het_cluster, 3)
            result["het30_ms"] = round(p50_het, 2)
            result["het30_placed"] = placed_het
            result["het30_vs_headline"] = round(p50_het / p50, 2)
            result["het30_fallback"] = get_action("allocate").last_fallback

    # ---- the full BASELINE.json config matrix (testing/benchmark.py — the
    # kubemark successor, VERDICT r3 #1): per-config latency percentiles,
    # each case individually deadline-gated
    from kube_batch_tpu.testing.benchmark import build_cases

    matrix = {}
    for case in build_cases():
        # worst-case runtime per case: the 50k/60k-task cases pay fresh
        # compiles + host replay; the kubemark density case sleeps through
        # its batch feed and drain
        margin = 300 if "50k" in case.name else (
            150 if "latency" in case.name else 90
        )
        if not section(f"matrix.{case.name}", margin_s=margin):
            continue
        try:
            matrix[case.name] = case.run(2)
        except Exception as e:  # a broken case must not kill the JSON line
            matrix[case.name] = {"error": f"{type(e).__name__}: {e}"}
    if matrix:
        result["matrix"] = matrix

    if skipped:
        result["sections_skipped"] = ",".join(skipped) + " (deadline)"
    _emit(result, tpu_capture_note=False)


def _emit(result: dict, tpu_capture_note: bool) -> None:
    """Persist a TPU capture (real backend) or cite the last committed one
    (CPU fallback), then print the single JSON line.

    Partial real-backend runs MERGE their completed sections into the
    committed capture instead of refusing to write (the round-3 behavior
    left the capture headline-only whenever any section hit the deadline) —
    sections the current run skipped keep their previously captured values,
    and the remaining gaps are recorded in `sections_missing`."""
    tpu_capture_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "BENCH_TPU.json")
    import jax

    if not tpu_capture_note and jax.default_backend() != "cpu":
        # durable, timestamped TPU capture — committed to the repo so a
        # wedged-tunnel round still carries driver-checkable TPU evidence
        import datetime

        capture = {}
        try:
            with open(tpu_capture_path) as f:
                capture = json.load(f)
        except (OSError, ValueError):
            pass
        now = datetime.datetime.now(
            datetime.timezone.utc
        ).isoformat(timespec="seconds")
        # section errors stay on the printed line only (same invariant as
        # the per-case matrix merge below) — the durable capture records
        # measurements and gaps, not transient failures
        fresh = {
            k: v for k, v in result.items()
            if k != "sections_skipped" and not k.endswith("_error")
        }
        # matrix merges per-case so a run that only got through two configs
        # doesn't drop the previously captured ones; a case that ERRORED
        # this run must not clobber good committed evidence either — its
        # error stays on the printed line only
        if "matrix" in fresh:
            prior = capture.get("matrix")
            prior = dict(prior) if isinstance(prior, dict) else {}
            for name, case_result in fresh["matrix"].items():
                if "error" in case_result and "error" not in prior.get(name, {"error": 1}):
                    continue  # keep the prior good numbers
                prior[name] = case_result
            fresh["matrix"] = prior
        # per-section provenance: merged-but-not-rerun sections keep their
        # original capture timestamp, so stale carried-over numbers are
        # distinguishable from freshly measured ones
        stamps = capture.get("section_captured_at")
        stamps = dict(stamps) if isinstance(stamps, dict) else {}
        for k in fresh:
            if k not in ("metric", "unit"):
                stamps[k] = now
        capture.update(fresh)
        capture["section_captured_at"] = stamps
        capture.pop("sections_missing", None)
        missing = [
            s for s in ("go_loop_ms", "pallas_roundhead", "pipeline5_ms",
                        "het30_ms", "multicycle", "multicycle_sharded",
                        "whatif_serving", "replication_serving",
                        "topk_compare", "incremental_solve")
            if s not in capture
        ]
        # the matrix is complete only when every build_cases() config has a
        # non-error entry — a single captured case must not read as "the
        # full config matrix landed"
        from kube_batch_tpu.testing.benchmark import build_cases

        have = capture.get("matrix") or {}
        missing += [
            f"matrix.{c.name}" for c in build_cases()
            if "error" in have.get(c.name, {"error": 1})
        ]
        if missing:
            capture["sections_missing"] = ",".join(missing)
        capture["captured_at"] = now
        capture["device_kind"] = jax.devices()[0].device_kind
        try:
            with open(tpu_capture_path, "w") as f:
                json.dump(capture, f, indent=1)
        except OSError:
            pass
    elif tpu_capture_note and os.path.exists(tpu_capture_path):
        # CPU fallback: cite the last committed TPU capture as corroborating
        # evidence next to the live (fallback-labeled) number
        try:
            with open(tpu_capture_path) as f:
                result["last_tpu_capture"] = json.load(f)
            # the ratio that matters: CPU-measured denominators over the
            # TPU-captured cycle (this run's CPU cycle is not the numerator)
            cap = result["last_tpu_capture"]
            cap_ms = cap.get("value") if isinstance(cap, dict) else None
            if not isinstance(cap_ms, (int, float)):
                cap_ms = None  # corrupted capture must not kill the line
            if cap_ms and "go_loop_ms" in result:
                result["speedup_vs_go_loop_at_last_tpu_capture"] = round(
                    result["go_loop_ms"] / cap_ms, 1
                )
                if "go_loop_native_pooled_ms" in result:
                    result["speedup_vs_go_loop_native_pooled_at_last_tpu_capture"] = round(
                        result["go_loop_native_pooled_ms"] / cap_ms, 2
                    )
                if "go_loop_native_single_ms" in result:
                    result["speedup_vs_go_loop_native_single_at_last_tpu_capture"] = round(
                        result["go_loop_native_single_ms"] / cap_ms, 2
                    )
        except (OSError, ValueError):
            pass
    print(json.dumps(result))


if __name__ == "__main__":
    main()
