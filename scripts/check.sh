#!/usr/bin/env bash
# kbt-check, all four tiers: the static AST/flow rules over the package
# tree, the jaxpr-level audit of the registered jitted entry points, the
# tier-C liveness/HBM-budget audit (every entry point traced at the
# abstract shape ladder up to 1M×100k — CPU-pinned, traces only, no device
# memory is ever allocated), AND the tier-D thread/lock-domain race rules
# (KBT301-304 over the inferred per-class lock domains) — then the seeded
# chaos smoke (bind-storm + leader-failover sim presets), so
# fault-hardening invariants run on every PR alongside the lint tiers.
# Exit 0 = clean, 1 = findings / violated chaos invariants, 2 = usage error.
#
# CI usage:  scripts/check.sh [--jsonl]
# The jaxpr tier imports jax; pin it to CPU so the check never touches (or
# hangs on) an accelerator tunnel — tracing is abstract, the backend only
# matters for the donation table, and CPU is the declared-() baseline.
# A forced host-platform device count gives the audit a virtual mesh so the
# SHARDED solve variants trace too — both the shard_map bodies (incl. the
# 2-D tasks×nodes mesh variant and the mesh enqueue gate) and the pjit
# oracle (KBT101-104 over every sharded path, without a multi-device CI
# mesh); an explicit count in XLA_FLAGS wins.
set -euo pipefail
cd "$(dirname "$0")/.."
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
  export XLA_FLAGS="--xla_force_host_platform_device_count=4${XLA_FLAGS:+ $XLA_FLAGS}"
fi
env JAX_PLATFORMS=cpu python -m kube_batch_tpu.analysis --jaxpr --hbm --races "$@"

# chaos smoke: each preset's CLI exits nonzero on a violated recovery
# invariant (lost/duplicate binds, accounting drift, failed fault
# recovery) — deterministic per seed, CPU-only, ~1 min combined
echo "kbt-check: chaos smoke (bind-storm, leader-failover)"
env JAX_PLATFORMS=cpu python -m kube_batch_tpu.sim \
  --preset bind-storm --seed 0 --no-fairness-series >/dev/null
env JAX_PLATFORMS=cpu python -m kube_batch_tpu.sim \
  --preset leader-failover --seed 5 --no-fairness-series >/dev/null
echo "kbt-check: chaos smoke clean"

# guard smoke: the result-integrity corruption preset — three resident
# device-column corruptions must each trip the sentinel with ZERO bad
# binds dispatched (no duplicate acks, no accounting drift), demotion
# must engage and re-promote, and every trip's diagnostics bundle must
# --replay-bundle deterministically (exit nonzero on any violation)
echo "kbt-check: guard smoke (corruption preset + bundle replay)"
GUARD_TMP="$(mktemp -d)"
trap 'rm -rf "$GUARD_TMP"' EXIT
env JAX_PLATFORMS=cpu KB_GUARD_DIR="$GUARD_TMP" python -m kube_batch_tpu.sim \
  --preset corruption --seed 0 --no-fairness-series >/dev/null
for bundle in "$GUARD_TMP"/trip-*; do
  env JAX_PLATFORMS=cpu python -m kube_batch_tpu.sim \
    --replay-bundle "$bundle" >/dev/null
done
echo "kbt-check: guard smoke clean"

# whatif smoke: the serve/ query plane end to end — loopback AdminServer,
# mixed feasible/infeasible gangs via the kb-ctl whatif CLI, verdict +
# Prometheus-counter + amortization assertions (scripts/whatif_smoke.py)
echo "kbt-check: whatif smoke (query plane)"
env JAX_PLATFORMS=cpu python scripts/whatif_smoke.py

# pipeline smoke: the event-driven pipelined loop's virtual-time evidence —
# trigger-bound p99 ≥2× better than the fixed 1 s tick, and the bind-storm
# chaos preset pipelined with zero duplicate binds and a full drain
echo "kbt-check: pipeline smoke (event-driven cycles)"
env JAX_PLATFORMS=cpu python scripts/pipeline_smoke.py

# trace smoke: the cycle tracing plane — traced sim run with a validating
# Chrome trace-event export, corruption-trip flight-recorder dumps that
# validate, and the pipelined overlap rendered as overlapping spans
# (scripts/trace_smoke.py; KBT014 keeps span bodies clock-free statically)
echo "kbt-check: trace smoke (spans + flight recorder)"
env JAX_PLATFORMS=cpu python scripts/trace_smoke.py

# warm smoke: the KB_WARM A/B leg (ISSUE 14) — the warm-churn preset run
# twice, carried candidate table vs the cold per-solve build; every acked
# bind must be bit-identical and the carry must actually engage (the CLI
# exits nonzero on either failure)
echo "kbt-check: warm smoke (KB_WARM A/B, warm-churn preset)"
env JAX_PLATFORMS=cpu python -m kube_batch_tpu.sim \
  --preset warm-churn --seed 3 --warm-ab --no-fairness-series >/dev/null
echo "kbt-check: warm smoke clean"

# replication smoke: the replicate/ follower read plane over real loopback
# HTTP — a leader + two pull-loop followers under randomized churn, with
# bit-matched /v1/whatif(+/sweep) verdicts once caught up, staleness p99
# ≤ 1 cycle on live followers, and serving continuity + warm re-adoption
# through one follower kill/restart (scripts/replication_smoke.py)
echo "kbt-check: replication smoke (leader + 2 followers)"
env JAX_PLATFORMS=cpu python scripts/replication_smoke.py
