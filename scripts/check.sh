#!/usr/bin/env bash
# kbt-check, both tiers: the static AST/flow rules over the package tree
# AND the jaxpr-level audit of the registered jitted entry points.
# Exit 0 = clean, 1 = findings, 2 = usage error (same contract as the CLI).
#
# CI usage:  scripts/check.sh [--jsonl]
# The jaxpr tier imports jax; pin it to CPU so the check never touches (or
# hangs on) an accelerator tunnel — tracing is abstract, the backend only
# matters for the donation table, and CPU is the declared-() baseline.
# A forced host-platform device count gives the audit a virtual mesh so the
# SHARDED solve variants trace too (KBT101-104 over the sharded path,
# without a multi-device CI mesh); an explicit count in XLA_FLAGS wins.
set -euo pipefail
cd "$(dirname "$0")/.."
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
  export XLA_FLAGS="--xla_force_host_platform_device_count=4${XLA_FLAGS:+ $XLA_FLAGS}"
fi
exec env JAX_PLATFORMS=cpu python -m kube_batch_tpu.analysis --jaxpr "$@"
