"""check.sh whatif smoke: the query plane end to end over real HTTP.

Starts an AdminServer + QueryPlane on a loopback port against a small
synthetic cluster, runs one scheduling cycle (which publishes the snapshot
lease), then drives a batch of mixed feasible/infeasible gangs through the
`kb-ctl whatif` CLI and asserts the verdicts and the Prometheus counters —
including the amortization invariant (device dispatches < requests served).

Exit 0 = clean, 1 = a violated invariant.  CPU-only, a few seconds.
"""

from __future__ import annotations

import os
import re
import sys
import urllib.request

# runnable as `python scripts/whatif_smoke.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fail(msg: str) -> None:
    print(f"whatif smoke FAILED: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> None:
    import kube_batch_tpu.actions  # noqa: F401 — registers actions
    import kube_batch_tpu.plugins  # noqa: F401 — registers plugins
    from kube_batch_tpu.cli import whatif as cli
    from kube_batch_tpu.cmd.server import AdminServer
    from kube_batch_tpu.framework.conf import load_scheduler_conf
    from kube_batch_tpu.framework.interface import get_action
    from kube_batch_tpu.framework.session import close_session, open_session
    from kube_batch_tpu.serve.plane import QueryPlane
    from kube_batch_tpu.testing.synthetic import synthetic_cluster

    cache = synthetic_cluster(n_tasks=40, n_nodes=8, gang_size=4, n_queues=2)
    conf = load_scheduler_conf(None)
    qp = QueryPlane(cache, max_batch=8, window_s=0.002, dispatch_timeout=60,
                    start_thread=True)
    srv = AdminServer(cache, port=0, query_plane=qp)
    srv.start()
    try:
        ssn = open_session(cache, conf.tiers)
        try:
            for name in conf.actions:
                get_action(name).execute(ssn)
        finally:
            close_session(ssn)
        cache.flush_binds()
        server = f"http://127.0.0.1:{srv.port}"

        # mixed verdicts via the CLI, concurrent probes riding few dispatches
        rc = cli.main(["--server", server, "--queue", "q0", "--count", "2",
                       "--cpu", "1000", "--repeat", "8",
                       "--expect", "feasible"])
        if rc != 0:
            _fail(f"feasible probe exited {rc}")
        rc = cli.main(["--server", server, "--queue", "q0", "--count", "2",
                       "--cpu", "900000", "--repeat", "4",
                       "--expect", "infeasible"])
        if rc != 0:
            _fail(f"infeasible probe exited {rc}")

        with urllib.request.urlopen(f"{server}/metrics", timeout=30) as r:
            text = r.read().decode()

        def counter(pat: str) -> float:
            m = re.search(pat + r"\S*\s+([0-9.e+]+)", text)
            return float(m.group(1)) if m else 0.0

        feas = counter(r'volcano_whatif_requests_total{verdict="feasible"}')
        infeas = counter(
            r'volcano_whatif_requests_total{verdict="infeasible"}')
        dispatches = counter(r"volcano_whatif_device_dispatches_total")
        if feas < 8:
            _fail(f"feasible counter {feas} < 8")
        if infeas < 4:
            _fail(f"infeasible counter {infeas} < 4")
        if not 0 < dispatches < feas + infeas:
            _fail(f"no amortization: {dispatches} dispatches for "
                  f"{feas + infeas} requests")
        if "volcano_whatif_batch_size" not in text:
            _fail("batch-size histogram missing from /metrics")
        print(f"whatif smoke clean: {int(feas)} feasible + {int(infeas)} "
              f"infeasible over {int(dispatches)} dispatches")
    finally:
        srv.stop()
        qp.close()


if __name__ == "__main__":
    main()
