"""Pipelined-cycle smoke (wired into scripts/check.sh): seed-deterministic
virtual-time evidence for the event-driven loop.

Two checks, one JSON summary line:

1. Trigger policy (smoke preset, trigger-bound): the pipelined loop's
   pod-arrival→bind-decision p99 must beat the fixed 1 s tick by ≥ 2×
   (it is bounded by the min-period floor, not the period), with the same
   jobs completed and clean invariants.
2. Chaos integrity (bind-storm preset, capacity-bound): the pipelined loop
   under the binder-flap storm must produce ZERO duplicate/lost binds,
   drain the whole workload, and report a p99 no worse than the serial
   tick's (the tail there is queueing, not the tick — the ratio is
   reported, the ≥2× bar belongs to the trigger-bound cases above and to
   the CPU bench's live-arrival section).

Exit 0 = all invariants hold; 1 = any violated.
"""

from __future__ import annotations

import json
import os
import sys

# runnable as `python scripts/pipeline_smoke.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kube_batch_tpu.envutil import apply_hardened_cpu_env  # noqa: E402

apply_hardened_cpu_env()

from kube_batch_tpu.sim.runner import run_preset  # noqa: E402


def main() -> int:
    errors = []

    serial = run_preset("smoke", seed=3)
    pipe = run_preset("smoke", seed=3, pipelined=True)
    p99_serial = serial["pod_bind_latency_vt"]["p99"]
    p99_pipe = pipe["pod_bind_latency_vt"]["p99"]
    if pipe["bind_integrity"]["duplicate_binds"]:
        errors.append("smoke/pipelined: duplicate binds")
    if pipe["invariants"]["errors"]:
        errors.append(f"smoke/pipelined: {pipe['invariants']['errors']}")
    if pipe["jobs"] != serial["jobs"]:
        errors.append(
            f"smoke: job outcomes diverged {pipe['jobs']} vs {serial['jobs']}")
    if not (p99_pipe * 2 <= p99_serial):
        errors.append(
            f"smoke: pipelined p99 {p99_pipe} not ≥2× better than the "
            f"fixed tick's {p99_serial}")

    storm = run_preset("bind-storm", seed=0, pipelined=True)
    bi = storm["bind_integrity"]
    if bi["duplicate_binds"]:
        errors.append("bind-storm/pipelined: duplicate binds")
    if storm["invariants"]["errors"]:
        errors.append(f"bind-storm/pipelined: {storm['invariants']['errors']}")
    jobs = storm["jobs"]
    if jobs["completed"] != jobs["submitted"]:
        errors.append(
            f"bind-storm/pipelined: {jobs['completed']}/{jobs['submitted']} "
            "jobs completed — storm did not drain")

    print(json.dumps({
        "smoke_p99_vt": {"serial": p99_serial, "pipelined": p99_pipe,
                         "improvement": round(p99_serial / p99_pipe, 1)
                         if p99_pipe else None},
        "bind_storm_pipelined": {
            "p99_vt": storm["pod_bind_latency_vt"]["p99"],
            "mean_vt": storm["pod_bind_latency_vt"]["mean"],
            "cycles": storm["cycles_run"],
            "duplicate_binds": bi["duplicate_binds"],
            "acked_binds": bi["acked_binds"],
        },
        "errors": errors,
    }, sort_keys=True))
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
