"""check.sh replication smoke: the follower read plane end to end over
real loopback HTTP.

Starts a leader (AdminServer + QueryPlane + ReplicationPublisher) on a
loopback port against a small synthetic cluster, attaches TWO follower
processes-in-miniature (FollowerCache + QueryPlane + ReplicationFollower
pull loop + their own AdminServer each), then drives randomized churn
cycles on the leader while probing every live serving endpoint:

- verdict bit-match: once caught up, leader and both followers must
  answer /v1/whatif and /v1/whatif/sweep byte-identically;
- bounded staleness: the lag_cycles reported by live followers during
  churn must stay ≤ 1 at p99;
- serving continuity: one follower's pull loop is killed mid-churn and
  restarted — its HTTP plane must keep answering throughout, re-adopt
  its device residency WARM, and catch back up over the delta chain.

Exit 0 = clean, 1 = a violated invariant.  CPU-only, a few seconds.
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.request

# runnable as `python scripts/replication_smoke.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fail(msg: str) -> None:
    print(f"replication smoke FAILED: {msg}", file=sys.stderr)
    sys.exit(1)


def _post(server: str, path: str, body: dict) -> dict:
    req = urllib.request.Request(
        server + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read().decode())


def main() -> None:
    import numpy as np

    import kube_batch_tpu.actions  # noqa: F401 — registers actions
    import kube_batch_tpu.plugins  # noqa: F401 — registers plugins
    from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Pod, PodGroup
    from kube_batch_tpu.api.types import PodPhase
    from kube_batch_tpu.cmd.server import AdminServer
    from kube_batch_tpu.framework.conf import load_scheduler_conf
    from kube_batch_tpu.framework.interface import get_action
    from kube_batch_tpu.framework.session import close_session, open_session
    from kube_batch_tpu.replicate.follower import (
        FollowerCache,
        ReplicationFollower,
    )
    from kube_batch_tpu.replicate.publisher import ReplicationPublisher
    from kube_batch_tpu.serve.plane import QueryPlane
    from kube_batch_tpu.testing.synthetic import synthetic_cluster

    GiB = float(2 ** 30)
    rng = np.random.default_rng(7)
    conf = load_scheduler_conf(None)

    cache = synthetic_cluster(n_tasks=24, n_nodes=6, gang_size=2, n_queues=2)
    cache.replication = pub = ReplicationPublisher()
    qp = QueryPlane(cache, max_batch=8, window_s=0.002, dispatch_timeout=60)
    srv = AdminServer(cache, port=0, query_plane=qp)
    srv.start()
    leader_url = f"http://127.0.0.1:{srv.port}"

    def cycle() -> None:
        ssn = open_session(cache, conf.tiers)
        try:
            for name in conf.actions:
                get_action(name).execute(ssn)
        finally:
            close_session(ssn)
        cache.flush_binds()

    cycle()  # publish the first lease + replication record

    followers = []
    try:
        for i in range(2):
            fcache = FollowerCache()
            fqp = QueryPlane(fcache, max_batch=8, window_s=0.002,
                             dispatch_timeout=60)
            f = ReplicationFollower(leader_url, cache=fcache,
                                    query_plane=fqp, poll_s=0.005)
            fsrv = AdminServer(fcache, port=0, query_plane=fqp)
            fsrv.start()
            f.start()
            followers.append((f, fqp, fsrv,
                              f"http://127.0.0.1:{fsrv.port}"))

        probe_body = {"queue": "q0", "count": 2,
                      "requests": {"cpu": 1000, "memory": GiB}}

        # wait for both followers to adopt the initial snapshot
        deadline = time.monotonic() + 30
        while any(f.applier.applied_seq < 1 for f, *_ in followers):
            if time.monotonic() > deadline:
                _fail("followers never adopted the initial snapshot")
            time.sleep(0.01)

        killed = followers[1][0]
        resident_before_kill = killed.applier.resident
        lags: list = []
        churn_i = 0
        for c in range(12):
            # randomized churn: 1-3 new small gangs per cycle
            for _ in range(int(rng.integers(1, 4))):
                g = f"smoke-{churn_i}"
                churn_i += 1
                cache.add_pod_group(PodGroup(
                    name=g, namespace="smoke", min_member=1, queue="q0",
                    creation_index=1000 + churn_i))
                cache.add_pod(Pod(
                    name=f"{g}-0", namespace="smoke",
                    requests={"cpu": float(rng.integers(100, 500)),
                              "memory": GiB / 4},
                    annotations={GROUP_NAME_ANNOTATION: g},
                    phase=PodPhase.PENDING,
                    creation_index=10_000 + churn_i))
            cycle()
            if c == 4:
                killed.stop()       # pull loop dies; its HTTP plane stays up
            if c == 8:
                killed.start()      # restart → warm re-adopt + catch-up
            time.sleep(0.02)
            # every server must answer mid-churn (continuity), and live
            # followers must report bounded staleness
            for idx, (f, _fqp, _fsrv, url) in enumerate(followers):
                resp = _post(url, "/v1/whatif", probe_body)
                if "staleness" not in resp:
                    _fail(f"follower {idx} response missing staleness")
                if f._thread is not None:    # pull loop live
                    lags.append(resp["staleness"]["lag_cycles"])
            _post(leader_url, "/v1/whatif", probe_body)

        pub.barrier()
        head = pub.counters()["head_seq"]
        deadline = time.monotonic() + 30
        while any(f.applier.applied_seq < head for f, *_ in followers):
            if time.monotonic() > deadline:
                _fail(f"followers never caught up to head {head}: "
                      f"{[f.applier.applied_seq for f, *_ in followers]}")
            time.sleep(0.01)

        if killed.applier.resident is not resident_before_kill:
            _fail("restarted follower dropped its resident cache "
                  "(expected warm re-adoption)")

        p99 = float(np.percentile(lags, 99)) if lags else 0.0
        if p99 > 1.0:
            _fail(f"staleness p99 {p99} cycles > 1 (lags {sorted(lags)})")

        # frozen head: every serving plane must agree bit-for-bit
        bodies = [
            ("/v1/whatif", probe_body),
            ("/v1/whatif", {"queue": "q1", "count": 3,
                            "requests": {"cpu": 900000}}),
            ("/v1/whatif", {"queue": "q0", "count": 1,
                            "requests": {"cpu": 500, "memory": GiB},
                            "min_resources": {"cpu": 4000}}),
            ("/v1/whatif/sweep", {"queue": "q0", "max_count": 32,
                                  "requests": {"cpu": 2000,
                                               "memory": GiB}}),
        ]
        matched = 0
        for path, body in bodies:
            want = json.dumps(_post(leader_url, path, body), sort_keys=True)
            for idx, (_f, _fqp, _fsrv, url) in enumerate(followers):
                got = json.dumps(_post(url, path, body), sort_keys=True)
                if got != want:
                    _fail(f"follower {idx} diverged on {path} {body}:\n"
                          f"  leader   {want}\n  follower {got}")
                matched += 1

        counters = pub.counters()
        if counters["records_delta"] < 8:
            _fail(f"churn traveled as {counters['records_delta']} deltas "
                  f"(expected the steady state on the wire)")
        gaps = sum(f.applier.gaps for f, *_ in followers)
        print(f"replication smoke clean: {head} cycles "
              f"({counters['records_delta']} deltas, "
              f"{counters['records_full']} fulls, {gaps} gaps), "
              f"{matched} bit-matched verdicts across 2 followers, "
              f"staleness p99 {p99:.0f} cycle(s) over {len(lags)} samples")
    finally:
        for f, fqp, fsrv, _url in followers:
            f.stop()
            fsrv.stop()
            fqp.close()
        srv.stop()
        qp.close()
        pub.close()


if __name__ == "__main__":
    main()
