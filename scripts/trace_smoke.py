"""Cycle-tracing smoke (wired into scripts/check.sh): the span recorder,
the Chrome trace-event export, and the flight recorder, end to end.

Three checks, one JSON summary line:

1. Traced sim run: the smoke preset with tracing on must produce a
   stage-attribution section and a Chrome trace-event export that passes
   structural validation (complete events, balanced nesting, monotonic
   per-thread timestamps).
2. Anomaly capture: the corruption chaos preset's guard trips must each
   arm a flight-recorder dump; every dump's ``trace.json`` must validate
   and its ``meta.json`` must carry the guard_trip trigger.
3. Pipelined overlap: a short REAL pipelined run (wall clock, fake
   backends, slowed binder drain) must render the overlap structure —
   cycle N's writeback span, on its own thread track, overlapping cycle
   N+1's compute spans — and the manual-trigger dump of exactly that ring
   must validate.

Exit 0 = all invariants hold; 1 = any violated.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

# runnable as `python scripts/trace_smoke.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kube_batch_tpu.envutil import apply_hardened_cpu_env  # noqa: E402

apply_hardened_cpu_env()

_TMP = tempfile.mkdtemp(prefix="kb-trace-smoke-")
os.environ["KB_TRACE_DIR"] = os.path.join(_TMP, "flight")
os.environ["KB_GUARD_DIR"] = os.path.join(_TMP, "guard")

from kube_batch_tpu.obs.trace import (  # noqa: E402
    chrome_trace,
    validate_chrome_trace,
)
from kube_batch_tpu.sim.runner import run_preset  # noqa: E402


def main() -> int:
    errors = []
    summary = {}

    # ---- 1. traced sim smoke + chrome export --------------------------
    chrome_path = os.path.join(_TMP, "smoke-trace.json")
    report = run_preset("smoke", seed=0, chrome_trace_path=chrome_path)
    sa = report.get("stage_attribution") or {}
    if not sa.get("cycles_traced"):
        errors.append("smoke: no traced cycles (is KB_TRACE=0 leaking in?)")
    with open(chrome_path) as f:
        doc = json.load(f)
    errs = validate_chrome_trace(doc)
    if errs:
        errors.append(f"smoke chrome trace invalid: {errs[:3]}")
    names = {e["name"] for e in doc.get("traceEvents", [])
             if e.get("ph") == "X"}
    for want in ("session_open", "status_derive", "action:allocate",
                 "solve_dispatch"):
        if want not in names:
            errors.append(f"smoke trace missing the {want} span")
    summary["sim_smoke"] = {
        "cycles_traced": sa.get("cycles_traced"),
        "spans_total": sa.get("spans_total"),
        "retraces_attributed": sa.get("retraces_attributed"),
    }

    # ---- 2. corruption trips → validating flight dumps ----------------
    report = run_preset("corruption", seed=0)
    guard = report.get("guard") or {}
    if guard.get("chaos_ok") is not True:
        errors.append("corruption: chaos_ok failed")
    dumps = guard.get("flight_dumps") or []
    if not dumps:
        errors.append("corruption: guard trips produced no flight dumps")
    for d in dumps:
        try:
            with open(os.path.join(d, "trace.json")) as f:
                derrs = validate_chrome_trace(json.load(f))
            if derrs:
                errors.append(f"flight dump {d} invalid: {derrs[:3]}")
            with open(os.path.join(d, "meta.json")) as f:
                meta = json.load(f)
            if meta.get("reason") != "guard_trip":
                errors.append(f"flight dump {d}: unexpected reason "
                              f"{meta.get('reason')}")
        except OSError as e:
            errors.append(f"flight dump {d} unreadable: {e}")
    summary["corruption"] = {
        "trips": guard.get("trips_total"),
        "flight_dumps": len(dumps),
        "alert_fired": (guard.get("alerts", {}).get("alerts", {})
                        .get("guard_trips", {}).get("fired_total", 0)),
    }

    # ---- 3. the pipelined overlap, rendered ----------------------------
    overlap = _overlap_check(errors)
    summary["pipelined_overlap"] = overlap

    print(json.dumps({**summary, "errors": errors}, sort_keys=True))
    return 1 if errors else 0


def _overlap_check(errors) -> dict:
    """A short real pipelined run whose writeback is slowed enough that
    cycle N's egress provably overlaps cycle N+1's compute — then assert
    the exported spans actually show it."""
    from kube_batch_tpu import actions as _a  # noqa: F401 — registers
    from kube_batch_tpu import plugins as _p  # noqa: F401 — registers
    from kube_batch_tpu.api.pod import (
        GROUP_NAME_ANNOTATION,
        Node,
        Pod,
        PodGroup,
        Queue,
    )
    from kube_batch_tpu.api.types import PodPhase
    from kube_batch_tpu.cache.cache import SchedulerCache
    from kube_batch_tpu.cache.fake import (
        FakeBinder,
        FakeEvictor,
        FakeStatusUpdater,
    )
    from kube_batch_tpu.framework.conf import load_scheduler_conf
    from kube_batch_tpu.scheduler import Scheduler

    cache = SchedulerCache(binder=FakeBinder(), evictor=FakeEvictor(),
                           status_updater=FakeStatusUpdater())
    cache.add_queue(Queue(name="q0", uid="uq0", weight=1))
    for i in range(4):
        cache.add_node(Node(
            name=f"n{i}",
            allocatable={"cpu": 16000.0, "memory": float(64 * 2 ** 30),
                         "pods": 110.0},
        ))
    sched = Scheduler(cache, conf=load_scheduler_conf(None))

    def add_gang(serial):
        g = f"ov{serial}"
        cache.add_pod_group(PodGroup(
            name=g, namespace="sm", uid=f"pg-{g}", min_member=1,
            queue="q0", creation_index=serial,
        ))
        cache.add_pod(Pod(
            name=f"{g}-0", namespace="sm", uid=f"pod-{g}",
            requests={"cpu": 500.0, "memory": float(2 ** 30)},
            annotations={GROUP_NAME_ANNOTATION: g},
            phase=PodPhase.PENDING, creation_index=serial * 100,
        ))

    add_gang(1)
    sched.run_once_pipelined()  # warm compiles
    orig_flush = cache.flush_binds

    def slow_flush():
        time.sleep(0.08)
        return orig_flush()

    cache.flush_binds = slow_flush
    add_gang(2)
    sched.run_once_pipelined()
    add_gang(3)
    sched.run_once_pipelined()
    sched.drain_pipeline()
    cache.flush_binds = orig_flush
    records = cache.flight_recorder.records()
    found = False
    for i, rec in enumerate(records[:-1]):
        for wb in (s for s in rec.spans if s.name == "writeback"):
            for nxt in (s for s in records[i + 1].spans
                        if s.name in ("session_open", "action:allocate")):
                if wb.t0 < nxt.t1 and nxt.t0 < wb.t1 and wb.tid != nxt.tid:
                    found = True
    if not found:
        errors.append("pipelined overlap not visible in the span records")
    # the manual-trigger dump of this ring must validate too
    cache.flight_recorder.trigger("smoke_manual")
    dumps = cache.flight_recorder.flush()
    doc = chrome_trace(records)
    errs = validate_chrome_trace(doc)
    if errs:
        errors.append(f"overlap trace invalid: {errs[:3]}")
    cache.stop()
    return {"overlap_rendered": found, "manual_dumps": len(dumps)}


if __name__ == "__main__":
    sys.exit(main())
