#!/usr/bin/env bash
# The example/job.yaml analog: a 6-replica gang (PodGroup minMember=6)
# submitted to a running scheduler's ingest API.
#
#   python -m kube_batch_tpu.cmd.main --listen-address 127.0.0.1:8080 &
#   ./examples/gang-job.sh
set -euo pipefail
SERVER=${SERVER:-http://127.0.0.1:8080}

curl -sf -XPOST "$SERVER/v1/queues" -d '{"name":"default","weight":1}' > /dev/null
curl -sf -XPOST "$SERVER/v1/podgroups" -d '{
  "name": "qj-1", "namespace": "default", "min_member": 6
}' > /dev/null
for i in $(seq 0 5); do
  curl -sf -XPOST "$SERVER/v1/pods" -d '{
    "name": "qj-1-'"$i"'", "namespace": "default",
    "requests": {"cpu": 1000, "memory": 1073741824},
    "annotations": {"scheduling.k8s.io/group-name": "qj-1"}
  }' > /dev/null
done
echo "submitted gang qj-1 (minMember=6); bindings:"
sleep 2
curl -sf "$SERVER/v1/bindings"
echo
