"""Per-cycle device-resident snapshot columns with scatter-delta refresh.

``ColumnStore.resident_features`` already keeps the ingest-static columns
(task requests/bitsets, node allocatable) alive on device across cycles.
This module extends residency to the *per-cycle* columns — statuses, node
ledgers, job/queue rows — which until now were re-uploaded wholesale by
every solve dispatch even when a steady-state cycle changed a few hundred
rows out of 50k.

Mechanism: for each cached field the host keeps a mirror of what the device
holds.  Each cycle the freshly built host column is diffed against the
mirror (one vectorized compare — cheaper than the upload it replaces):

- no rows changed  → the cached device array is handed to the solve as-is;
- a small delta    → the (rows, values) pair is padded to a FIXED slot
  count and applied on device as one scatter (``.at[rows].set(mode="drop")``
  with out-of-range padding indices), with the stale device buffer DONATED
  to the update so XLA writes in place instead of allocating;
- a large delta or a shape change (axis growth) → full re-upload.

The fixed slot width keeps the scatter's jit cache to one specialization
per (field shape, dtype): steady-state cycles compile nothing (the
bench's retrace counters prove it).  Values are bit-identical to a full
upload by construction — the scatter writes exactly the host rows — and
tests/test_snapshot_delta.py checks the round-trip.

Donation is skipped on the CPU backend (unsupported there; jax would warn
every cycle).

Mesh-sharded residency (:class:`ShardedPerCycleDeviceCache`): the sharded
solve keeps the same columns alive as ``NamedSharding``-placed buffers —
node-axis columns sharded over the mesh, everything else replicated — and
refreshes them with PER-SHARD fixed-width donated scatter deltas.  The
changed rows are partitioned by owning shard on the host and shipped as
``[n_shards, slots]`` LOCAL indices + values whose leading axis carries the
mesh sharding, so the jitted update (a vmapped per-shard scatter with
explicit ``in_shardings``/``out_shardings``) routes each delta slice
straight to its owning chip — no gather, no reshard, no cross-chip traffic.
Fallbacks to a full (sharded) re-upload: cold cache, axis growth, a delta
wider than the per-shard slot budget (high churn), or a mesh change (the
ColumnStore drops the old mesh's cache wholesale — see
``per_cycle_resident``; the shape buckets are divisible by any
power-of-two mesh axis, and jax itself rejects an indivisible placement
before any solve could run).

Donation audit (PR 4): every donating call site in this module rebinds the
donated name to the call's result (``dev = _scatter_fn()(dev, ...)``) —
the shape KBT006 (analysis/flowrules.py) verifies package-wide, so a
post-donation read introduced later fails the tier-1 self-enforcement
test.  The scatters (single-device AND per-mesh) are registered in the
jaxpr audit (analysis/jaxpr_audit.py), which asserts their donation wiring
per backend (KBT104) and that no f64/transfer/callback sneaks into the
traced update.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from kube_batch_tpu.utils import jitstats

# snapshot fields refreshed per cycle (everything the static feature cache
# does not own, minus the variable-K sparse affinity rows)
PER_CYCLE_FIELDS: Tuple[str, ...] = (
    "task_status", "task_node", "task_valid", "task_pending",
    "task_best_effort",
    "node_idle", "node_releasing", "node_used", "node_valid", "node_sched",
    "job_min_avail", "job_ready", "job_queue", "job_prio", "job_creation",
    "job_valid", "job_schedulable", "job_allocated",
    "queue_weight", "queue_capability", "queue_alloc", "queue_request",
    "queue_valid",
    "total",
)

#: the subset whose leading axis is the node axis — sharded over the mesh
#: on the sharded solve path (parallel/mesh.snapshot_shardings); everything
#: else replicates
NODE_AXIS_FIELDS = frozenset((
    "node_idle", "node_releasing", "node_used", "node_valid", "node_sched",
))

#: fixed scatter width buckets — a delta ships at the smallest bucket that
#: holds it, so tiny steady-state deltas don't pay the worst-case payload;
#: every bucket is pre-warmed at full-upload time, so the bounded set of
#: specializations per (field shape, dtype) never retraces mid-steady-state.
#: Deltas wider than the largest bucket take the full-upload path (at which
#: point the transfer is no longer the bottleneck anyway).
SCATTER_SLOT_BUCKETS: Tuple[int, ...] = (64, 512, 4096)
SCATTER_SLOTS = SCATTER_SLOT_BUCKETS[-1]

#: per-shard slot-width buckets of the mesh scatter: the [n_shards, slots]
#: delta is sharded on its leading axis, so each chip receives exactly its
#: own slice.  This static ladder is the DEFAULT (zero observed churn);
#: the sharded cache retargets its live ladder from the churn EWMA
#: (:func:`adaptive_ladder`), capped by SHARD_SCATTER_SLOTS.
SHARD_SCATTER_SLOT_BUCKETS: Tuple[int, ...] = (16, 128, 1024)
SHARD_SCATTER_SLOTS = SHARD_SCATTER_SLOT_BUCKETS[-1]

#: churn EWMA smoothing for the adaptive per-shard ladder
CHURN_EWMA_DECAY = 0.8


def _slot_bucket(n: int, buckets: Tuple[int, ...]) -> int:
    """Smallest slot bucket holding an n-row delta (caller guarantees
    n ≤ buckets[-1])."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def all_shard_buckets(max_slots: int) -> Tuple[int, ...]:
    """Every per-shard bucket width the adaptive ladder can ever select
    (powers of two from 16 up to the hard cap).  The cold-upload prewarm
    compiles ALL of them, so a later ladder retarget is pure payload-
    sizing bookkeeping — no compile can ever land in a steady-state
    cycle, no matter where the churn EWMA moves."""
    out = []
    v = min(16, max_slots)
    while True:
        out.append(v)
        if v >= max_slots:
            return tuple(out)
        v = min(v * 2, max_slots)


def adaptive_ladder(ewma: float, max_slots: int) -> Tuple[int, ...]:
    """Per-shard slot-bucket ladder sized from the observed churn EWMA
    (replacing the static 16/128/1024 cap): the base bucket is the
    smallest power of two ≥ max(16, 2×ewma) — 2× headroom so the typical
    steady-state delta lands in the FIRST bucket instead of climbing the
    ladder — then ×8 steps up to the hard cap.  Zero churn reproduces the
    static default exactly; a steady high-churn regime drops the
    too-small buckets (their payloads would never be used) and starts at
    a bucket the observed deltas actually fit."""
    base = 16
    target = max(16.0, 2.0 * ewma)
    while base < target and base < max_slots:
        base *= 2
    base = min(base, max_slots)
    ladder = [base]
    while ladder[-1] < max_slots:
        ladder.append(min(ladder[-1] * 8, max_slots))
    return tuple(ladder)


_SCATTER = None


def _scatter_fn():
    """The shared jitted scatter — ONE module-level function so every cache
    instance (simulator multi-scheduler runs, bench pairs, the test suite)
    reuses the same compiled specializations and jitstats tracks a single
    entry instead of retaining one wrapper per dead instance."""
    global _SCATTER
    if _SCATTER is None:
        import jax

        def scatter(dev, rows, vals):
            return dev.at[rows].set(vals, mode="drop")

        # donate the stale device buffer on real accelerators so the
        # update writes in place; CPU ignores donation (and warns), so
        # skip it there
        donate = () if jax.default_backend() == "cpu" else (0,)
        _SCATTER = jitstats.register(
            "resident_scatter", jax.jit(scatter, donate_argnums=donate)
        )
    return _SCATTER


# per-(mesh, sharded?) jitted scatters — memoized so steady-state sharded
# cycles reuse one compiled specialization per (field shape, dtype), same
# contract as the single-device _scatter_fn
_MESH_SCATTER: dict = {}


def _mesh_repl_scatter_fn(mesh):
    """The replicated-placement scatter for `mesh`: same update as the
    single-device one, with explicit replicated in/out shardings so the
    result stays a committed mesh array the sharded solve accepts as-is."""
    fn = _MESH_SCATTER.get((mesh, "repl"))
    if fn is None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(mesh, P())

        def scatter(dev, rows, vals):
            return dev.at[rows].set(vals, mode="drop")

        donate = () if jax.default_backend() == "cpu" else (0,)
        fn = jitstats.register(
            "resident_scatter_repl",
            jax.jit(scatter, donate_argnums=donate,
                    in_shardings=(repl, repl, repl), out_shardings=repl),
        )
        _MESH_SCATTER[(mesh, "repl")] = fn
    return fn


def _mesh_shard_scatter_fn(mesh):
    """The per-shard scatter for node-axis columns: `dev` is [N, ...]
    sharded over the node axis, `rows`/`vals` are [n_shards, slots(, ...)]
    sharded on their LEADING axis with shard-LOCAL row indices — the vmap
    over the shard axis makes each chip scatter only its own delta slice
    (out-of-range padding rows drop), and the explicit shardings keep GSPMD
    from inserting any gather/reshard around the update."""
    fn = _MESH_SCATTER.get((mesh, "shard"))
    if fn is None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kube_batch_tpu.parallel.mesh import NODE_AXIS

        shard = NamedSharding(mesh, P(NODE_AXIS))

        def scatter_sharded(dev, rows, vals):
            n_shards = rows.shape[0]
            dev3 = dev.reshape(
                (n_shards, dev.shape[0] // n_shards) + dev.shape[1:]
            )
            out = jax.vmap(
                lambda d, r, v: d.at[r].set(v, mode="drop")
            )(dev3, rows, vals)
            return out.reshape(dev.shape)

        donate = () if jax.default_backend() == "cpu" else (0,)
        fn = jitstats.register(
            "resident_scatter_sharded",
            jax.jit(scatter_sharded, donate_argnums=donate,
                    in_shardings=(shard, shard, shard), out_shardings=shard),
        )
        _MESH_SCATTER[(mesh, "shard")] = fn
    return fn


def changed_rows(mirror: np.ndarray, host: np.ndarray) -> np.ndarray:
    """Ascending row indices where ``host`` differs from ``mirror`` — the
    ONE vectorized diff behind both device-cache scatter refreshes AND the
    replication publisher's wire deltas (replicate/publisher.py), so a
    follower's scatter payload is row-for-row the leader's."""
    if host.ndim == 1:
        return np.flatnonzero(mirror != host)
    return np.flatnonzero(np.any(mirror != host, axis=1))


def scatter_summary(per_path_counters: Dict[str, Dict[str, int]]
                    ) -> Dict[str, Dict]:
    """Per-path counter summary with the delta-vs-full bytes-moved
    reduction — the ONE derivation behind the bench artifact and the sim's
    longitudinal report (`ColumnStore.resident_counters()` feeds it)."""
    out: Dict[str, Dict] = {}
    for path, c in per_path_counters.items():
        moved = c["bytes_full"] + c["bytes_scatter"]
        rec = dict(c)
        rec["bytes_moved"] = moved
        if c["bytes_if_full"]:
            rec["upload_reduction"] = round(
                1.0 - moved / c["bytes_if_full"], 3
            )
        out[path] = rec
    return out


class PerCycleDeviceCache:
    def __init__(self) -> None:
        self._mirror: Dict[str, np.ndarray] = {}
        self._dev: Dict[str, object] = {}
        # per-swap delta record: field → changed row indices (np.ndarray)
        # for a scatter refresh, None for a full upload; clean fields are
        # absent.  The warm-started allocate's table invalidation
        # (WarmTableState.absorb) consumes this — the scatter diff already
        # knows exactly where state moved, so the candidate-table carry
        # rides the same knowledge instead of re-deriving it.
        self.delta_record: Dict[str, object] = {}
        # last (input snap, swapped result): the failure-histogram dispatch
        # re-swaps the SAME snap the solve dispatch just synced — a
        # guaranteed all-clean diff over every field, skipped by identity
        self._last_in = None
        self._last_out = None
        # monotonic swap version — the warm-standby revalidation's token:
        # a cache that has synced at least one snapshot (version > 0) and
        # passes the store's consistency check after a failover rebuild is
        # kept (buffers + compiled specializations survive; the next swap's
        # mirror diff absorbs any residual divergence as ordinary deltas)
        self.version = 0
        # diagnostics for the bench / tests
        self.full_uploads = 0
        self.scatter_updates = 0
        self.clean_hits = 0
        # bytes actually shipped host→device vs what full per-cycle uploads
        # would have shipped — the bench's delta-vs-full reduction evidence
        self.bytes_full = 0
        self.bytes_scatter = 0
        self.bytes_if_full = 0

    def counters(self) -> Dict[str, int]:
        return {
            "version": self.version,
            "full_uploads": self.full_uploads,
            "scatter_updates": self.scatter_updates,
            "clean_hits": self.clean_hits,
            "bytes_full": self.bytes_full,
            "bytes_scatter": self.bytes_scatter,
            "bytes_if_full": self.bytes_if_full,
        }

    @staticmethod
    def _payload_bytes(slots: int, host: np.ndarray) -> int:
        """Scatter payload size for a `slots`-wide delta of `host`'s row
        shape (int32 index + one value row per slot)."""
        row = host.dtype.itemsize * int(
            np.prod(host.shape[1:], dtype=np.int64)
        )
        return slots * (4 + row)

    def _refresh(self, field: str, host: np.ndarray):
        import jax

        self.bytes_if_full += host.nbytes
        mirror = self._mirror.get(field)
        if (
            mirror is None
            or mirror.shape != host.shape
            or mirror.dtype != host.dtype
        ):
            self.full_uploads += 1
            self.bytes_full += host.nbytes
            self.delta_record[field] = None
            dev = jax.device_put(host)
            # pre-warm EVERY slot-bucket specialization for this (shape,
            # dtype) NOW — an all-out-of-range index vector writes nothing,
            # so the values are untouched, but any real delta width in a
            # later steady-state cycle becomes a cache hit, never a
            # retrace.  TWO passes: the first bucket's first call sees the
            # device_put-placed buffer, while real deltas always see a
            # scatter OUTPUT buffer — whose layout can key a fresh
            # specialization; the second pass compiles every bucket against
            # the output-typed buffer too
            for _ in range(2):
                for slots in SCATTER_SLOT_BUCKETS:
                    rows = np.full(slots, host.shape[0], np.int32)
                    vals = np.zeros((slots,) + host.shape[1:], host.dtype)
                    dev = _scatter_fn()(dev, rows, vals)
            self._mirror[field] = host.copy()
            self._dev[field] = dev
            return dev
        changed = changed_rows(mirror, host)
        if changed.size == 0:
            self.clean_hits += 1
            return self._dev[field]
        # the delta is known row-exactly from here down — either path
        # moves exactly `changed`, which is what the warm-table carry's
        # invalidation consumes
        self.delta_record[field] = changed
        slots = _slot_bucket(changed.size, SCATTER_SLOT_BUCKETS)
        if (
            changed.size > SCATTER_SLOTS
            # a tiny column: shipping the whole thing is cheaper than the
            # smallest fixed-width scatter payload
            or self._payload_bytes(slots, host) >= host.nbytes
        ):
            # specializations are already warm — no prewarm on this path
            self.full_uploads += 1
            self.bytes_full += host.nbytes
            dev = jax.device_put(host)
            self._mirror[field] = host.copy()
            self._dev[field] = dev
            return dev
        n = host.shape[0]
        # pad with an out-of-range row index — mode="drop" discards the
        # padding writes, so the scatter shape depends only on the (pre-
        # warmed) slot bucket, never on the exact delta size
        rows = np.full(slots, n, np.int32)
        rows[: changed.size] = changed
        vals = np.zeros((slots,) + host.shape[1:], host.dtype)
        vals[: changed.size] = host[changed]
        dev = _scatter_fn()(self._dev[field], rows, vals)
        mirror[changed] = host[changed]
        self._dev[field] = dev
        self.scatter_updates += 1
        self.bytes_scatter += rows.nbytes + vals.nbytes
        return dev

    def swap(self, snap):
        """`snap` with every per-cycle field replaced by its device-resident
        copy (refreshed by delta).  The caller keeps using the ORIGINAL
        host-backed snap for numpy reads — only the returned copy feeds the
        solve, mirroring the resident_features contract.  A repeat call
        with the identical snap object (the same cycle's second dispatch)
        returns the memoized result without re-diffing."""
        if snap is self._last_in:
            return self._last_out
        self.version += 1
        self.delta_record = {}
        updates = {
            field: self._refresh(field, np.asarray(getattr(snap, field)))
            for field in PER_CYCLE_FIELDS
        }
        out = snap._replace(**updates)
        self._last_in, self._last_out = snap, out
        return out


class ShardedPerCycleDeviceCache(PerCycleDeviceCache):
    """Per-cycle residency for the mesh-sharded solve path (module
    docstring): node-axis columns live sharded over `mesh`, everything else
    replicated across it, refreshed by per-shard donated scatter deltas.

    Multi-host meshes: each process materializes and ships only its own
    ADDRESSABLE shards — uploads and per-shard payloads go through
    ``jax.make_array_from_callback`` (the callback is invoked per local
    shard only), so a host's cross-DCN upstream per cycle is its own
    shard's delta rows, never the full column.  The byte counters record
    the per-HOST share on sharded fields.

    The per-shard slot ladder is ADAPTIVE (:func:`adaptive_ladder`): a
    churn EWMA over the per-cycle max per-shard delta width retargets the
    bucket set, replacing the static 16/128/1024 sizing.  The cold-upload
    prewarm compiles the FULL reachable bucket set up front
    (:func:`all_shard_buckets`, no-op scatters with all-out-of-range
    padding indices), so a retarget is pure payload-sizing bookkeeping
    and a real delta of any admissible width is a jit cache hit — steady
    state never retraces regardless of where the ladder moves."""

    def __init__(self, mesh) -> None:
        super().__init__()
        self.mesh = mesh
        from kube_batch_tpu.parallel.mesh import NODE_AXIS

        # the SCATTER shard count is the node-axis extent — on a 2-D
        # (tasks, nodes) mesh the node columns replicate across the task
        # axis, so the [n_shards, slots] payload splits by node shard only
        self.n_shards = int(dict(mesh.shape)[NODE_AXIS])
        self.churn_ewma = 0.0
        self._ladder: Tuple[int, ...] = adaptive_ladder(
            0.0, SHARD_SCATTER_SLOTS
        )
        self._warm: Dict[str, set] = {}   # field → warmed bucket widths
        self._cycle_max = 0
        self.ladder_retargets = 0

    def counters(self) -> Dict[str, int]:
        out = super().counters()
        out["churn_ewma"] = round(self.churn_ewma, 2)
        out["slot_ladder"] = list(self._ladder)
        out["ladder_retargets"] = self.ladder_retargets
        return out

    def _sharding(self, field: str):
        from kube_batch_tpu.parallel.mesh import snapshot_shardings

        return getattr(snapshot_shardings(self.mesh), field)

    def _host_fraction(self) -> float:
        """This process's addressable share of the mesh — the per-host
        byte-counter scale for sharded payloads."""
        import jax

        pc = jax.process_count()
        return 1.0 / pc if pc > 1 else 1.0

    def _put(self, host: np.ndarray, sharding):
        """Placed upload: single-process goes through device_put; on a
        multi-host mesh each process materializes only its addressable
        shards via make_array_from_callback (the per-host scatter/upload
        contract above)."""
        import jax

        if jax.process_count() > 1:
            return jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx]
            )
        return jax.device_put(host, sharding)

    def _put_payload(self, arr: np.ndarray):
        """Per-shard scatter payload ([n_shards, slots, ...], leading axis
        sharded over the node axis): pre-placed per host on multi-process
        meshes so only the local shards' slices upload; single-process
        passes the numpy array straight to the jitted scatter (whose
        in_shardings place it)."""
        import jax

        if jax.process_count() == 1:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kube_batch_tpu.parallel.mesh import NODE_AXIS

        return jax.make_array_from_callback(
            arr.shape, NamedSharding(self.mesh, P(NODE_AXIS)),
            lambda idx: arr[idx],
        )

    def _prewarm_shard_field(self, field: str, dev, n_rows: int):
        """Compile every not-yet-warm per-shard bucket for `field` — the
        FULL reachable set (:func:`all_shard_buckets`), not just the live
        ladder — with no-op scatters (all padding indices → zero writes,
        two passes so the scatter-OUTPUT buffer layout is covered too).
        Returns the (donated and rebound) device buffer."""
        host = self._mirror.get(field)
        dtype = host.dtype if host is not None else np.float32
        tail = host.shape[1:] if host is not None else ()
        s = n_rows // self.n_shards
        warm = self._warm.setdefault(field, set())
        todo = [
            b for b in all_shard_buckets(SHARD_SCATTER_SLOTS)
            if b not in warm
        ]
        for _ in range(2):
            for slots in todo:
                rows = np.full((self.n_shards, slots), s, np.int32)
                vals = np.zeros((self.n_shards, slots) + tail, dtype)
                dev = _mesh_shard_scatter_fn(self.mesh)(
                    dev, self._put_payload(rows), self._put_payload(vals)
                )
        warm.update(todo)
        return dev

    def _note_churn(self, per_shard_max: int) -> None:
        self._cycle_max = max(self._cycle_max, per_shard_max)

    def _retarget_ladder(self) -> None:
        """EWMA update + ladder retarget at swap end.  Retargeting only
        changes which payload widths later deltas ship — every reachable
        bucket was compiled at cold-upload prewarm, so this costs nothing
        and can never retrace a steady-state cycle."""
        self.churn_ewma = (
            CHURN_EWMA_DECAY * self.churn_ewma
            + (1.0 - CHURN_EWMA_DECAY) * self._cycle_max
        )
        self._cycle_max = 0
        new = adaptive_ladder(self.churn_ewma, SHARD_SCATTER_SLOTS)
        if new != self._ladder:
            self._ladder = new
            self.ladder_retargets += 1

    def swap(self, snap):
        if snap is self._last_in:
            return self._last_out
        out = super().swap(snap)
        self._retarget_ladder()
        return out

    def _full_upload(self, field: str, host: np.ndarray,
                     prewarm: bool = True):
        """Sharded full upload; on cold/shape-change uploads (`prewarm`)
        every scatter slot bucket is pre-compiled so later deltas never
        retrace.  A node axis the mesh cannot divide would make per-shard
        indexing undefined — but jax itself rejects such a placement
        (NamedSharding divisibility), so the sharded solve path never
        reaches here with one; the shape buckets (snapshot.bucket) are
        divisible by any power-of-two mesh."""
        sharded_axis = field in NODE_AXIS_FIELDS
        self.full_uploads += 1
        # a full upload with no recorded row delta invalidates wholesale
        # (the warm-table carry treats an unrecorded field as all-moved)
        self.delta_record.setdefault(field, None)
        self.bytes_full += int(
            host.nbytes * (self._host_fraction() if sharded_axis else 1.0)
        )
        dev = self._put(host, self._sharding(field))
        if not prewarm:
            self._mirror[field] = host.copy()
            self._dev[field] = dev
            return dev
        # two prewarm passes — see PerCycleDeviceCache._refresh: real deltas
        # see scatter-OUTPUT buffers, whose (sharded) layout can key a fresh
        # specialization vs the device_put-placed first input
        self._mirror[field] = host.copy()
        if sharded_axis:
            self._warm.pop(field, None)  # shape may have changed — rewarm
            dev = self._prewarm_shard_field(field, dev, host.shape[0])
        else:
            for _ in range(2):
                for slots in SCATTER_SLOT_BUCKETS:
                    rows = np.full(slots, host.shape[0], np.int32)
                    vals = np.zeros((slots,) + host.shape[1:], host.dtype)
                    dev = _mesh_repl_scatter_fn(self.mesh)(dev, rows, vals)
        self._dev[field] = dev
        return dev

    def _refresh(self, field: str, host: np.ndarray):
        sharded_axis = field in NODE_AXIS_FIELDS
        # per-host accounting on sharded fields must scale the DENOMINATOR
        # too, or upload_reduction would read inflated on multi-host meshes
        self.bytes_if_full += int(
            host.nbytes * (self._host_fraction() if sharded_axis else 1.0)
        )
        mirror = self._mirror.get(field)
        if (
            mirror is None
            or mirror.shape != host.shape
            or mirror.dtype != host.dtype
        ):
            return self._full_upload(field, host)
        changed = changed_rows(mirror, host)
        if changed.size == 0:
            self.clean_hits += 1
            return self._dev[field]
        # row-exact delta known from here down (warm-table invalidation)
        self.delta_record[field] = changed
        if sharded_axis:
            s = host.shape[0] // self.n_shards
            shard_ids = changed // s  # ascending: flatnonzero sorts rows
            counts = np.bincount(shard_ids, minlength=self.n_shards)
            widest = int(counts.max())
            self._note_churn(widest)
            if widest > min(self._ladder[-1], SHARD_SCATTER_SLOTS):
                # over the LIVE ladder's cap — full re-upload; the churn
                # note above grows the EWMA so a sustained regime retargets
                # (and pre-warms) a wider ladder instead of thrashing
                return self._full_upload(field, host, prewarm=False)
            slots = _slot_bucket(widest, self._ladder)
            if self._payload_bytes(slots, host) * self.n_shards >= host.nbytes:
                # tiny sharded column: the whole upload is cheaper than the
                # smallest per-shard scatter payload
                return self._full_upload(field, host, prewarm=False)
            rows = np.full((self.n_shards, slots), s, np.int32)
            offs = np.concatenate(([0], np.cumsum(counts)[:-1]))
            pos = np.arange(changed.size) - np.repeat(offs, counts)
            rows[shard_ids, pos] = (changed % s).astype(np.int32)
            vals = np.zeros(
                (self.n_shards, slots) + host.shape[1:], host.dtype
            )
            vals[shard_ids, pos] = host[changed]
            dev = _mesh_shard_scatter_fn(self.mesh)(
                self._dev[field], self._put_payload(rows),
                self._put_payload(vals),
            )
            mirror[changed] = host[changed]
            self._dev[field] = dev
            self.scatter_updates += 1
            self.bytes_scatter += int(
                (rows.nbytes + vals.nbytes) * self._host_fraction()
            )
            return dev
        else:
            if changed.size > SCATTER_SLOTS:
                return self._full_upload(field, host, prewarm=False)
            slots = _slot_bucket(changed.size, SCATTER_SLOT_BUCKETS)
            if self._payload_bytes(slots, host) >= host.nbytes:
                return self._full_upload(field, host, prewarm=False)
            rows = np.full(slots, host.shape[0], np.int32)
            rows[: changed.size] = changed
            vals = np.zeros((slots,) + host.shape[1:], host.dtype)
            vals[: changed.size] = host[changed]
            dev = _mesh_repl_scatter_fn(self.mesh)(
                self._dev[field], rows, vals
            )
        mirror[changed] = host[changed]
        self._dev[field] = dev
        self.scatter_updates += 1
        self.bytes_scatter += rows.nbytes + vals.nbytes
        return dev


# ==========================================================================
# Warm-started allocate: the cross-cycle candidate-table planner (KB_WARM)
# ==========================================================================
#
# The device side (ops/assignment.py warm_allocate_solve) carries the
# [P, W] candidate table between solves; this is the HOST side — the
# per-row invalidation bookkeeping that turns "what moved since the last
# solve" into the (row_map, changed_nodes, rerank_rows, rerank_slots)
# plan the warm program consumes.  The invalidation sources:
#
#   per-cycle node columns (ledgers, valid, sched) — the resident scatter
#     delta records above (``delta_record``): the diff that sizes the
#     scatter IS the row-exact "these nodes moved" set, absorbed into the
#     state between solves (multiple swaps per cycle accumulate);
#   ingest-static features (task requests/bitsets, node allocatable /
#     label / taint bits) — version-keyed uploads carry no row deltas, so
#     the state keeps its own mirrors and diffs them at plan time;
#   a row's own bucket churn — membership/position handled by row_map;
#   sparse affinity/preference rows — conservatively re-ranked every
#     cycle (their score/predicate corrections are rebuilt per cycle from
#     object state, invisible to both sources above);
#   erosion — the solve's per-row ``eroded`` output (θ-cut rows whose
#     valid prefix fell below the nominal K) re-ranks next cycle.
#
# Any wholesale movement (full upload, version gap, shape change, config
# change) escalates to a COLD plan: every live bucket row re-ranks, which
# is the carry's self-rebuild — bit-exact like every other path.

#: changed-node slot rungs of the warm merge's fresh [P, C] block —
#: coarse ×8 steps (the scatter-slot discipline) so steady churn cannot
#: flap a shape boundary; churn past the top rung escalates to cold
WARM_CHANGED_BUCKETS: Tuple[int, ...] = (64, 512, 4096)

#: stored-width margin: the carried table keeps W = K + margin entries so
#: θ/φ-cut erosion rarely reaches the nominal K before the re-rank
#: catches up.  Additive, not multiplicative: every extraction step of
#: the re-rank build costs ~the same regardless of M, so doubling W would
#: double the one genuinely extraction-bound piece of a warm cycle
WARM_WIDTH_MARGIN = 16


def warm_rerank_rungs(P: int) -> Tuple[int, ...]:
    """The sub-bucket rungs for a [P] pending bucket — ×2 steps from 128
    up to P (always ending at P).  Shared by the invalidated-row re-rank
    rung and the merge rung (the [M] live prefix the table refresh
    operates on — padding rows past the live count pay nothing).  The
    ratchets make each rung a one-time compile, so the finer ladder buys
    tighter compute without steady-state retrace risk."""
    out = []
    v = min(128, P)
    while v < P:
        out.append(v)
        v = min(v * 2, P)
    out.append(P)
    return tuple(out)


def _rung(n: int, rungs: Tuple[int, ...]) -> int:
    for r in rungs:
        if n <= r:
            return r
    return rungs[-1]


#: consecutive under-rung plans before a ratcheted rung drops back to fit
WARM_RUNG_DECAY = 3


def _ratchet(current: int, needed: int, low_streak: int, floor: int = 0):
    """Sticky rung with hysteresis decay: grow immediately, drop straight
    to the needed rung after WARM_RUNG_DECAY consecutive plans that
    needed less — a burst pins its rung only until the regime provably
    ended, so steady cycles stop paying burst-sized compute.  Every rung
    ever visited stays in the jit cache, so later oscillation between
    known rungs compiles nothing; the hysteresis only bounds how many
    DISTINCT rungs a noisy workload visits.  Returns (rung, streak')."""
    if needed >= current:
        return needed, 0
    low_streak += 1
    if low_streak < WARM_RUNG_DECAY:
        return current, low_streak
    return max(needed, floor), 0


class WarmTableState:
    """One solve path's carried candidate table + invalidation planner.

    Owned by the ColumnStore (one per (mesh, impl) dispatch slot — see
    ``ColumnStore.warm_table_state``); dropped wholesale on axis growth,
    resident-cache drops (guard heals), and mesh changes, so a carried
    table can never outlive the coordinate system its indices live in."""

    #: per-cycle snapshot fields whose row deltas invalidate node keys
    NODE_DELTA_FIELDS = (
        "node_idle", "node_releasing", "node_used", "node_valid",
        "node_sched",
    )

    def __init__(self, mesh=None, impl=None):
        self.mesh = mesh
        self.impl = impl
        self._reset()
        # lifetime counters (bench incremental_solve / sim evidence)
        self.plans = 0
        self.cold_builds = 0
        self.reranked_total = 0
        self.changed_total = 0

    def _reset(self) -> None:
        self.shape_key = None       # (P, W, capN, capT, config)
        self.rows: Optional[np.ndarray] = None
        self.table = None           # (idx, skey, hash, trunc) device
        self.eroded_dev = None
        self._changed: Optional[np.ndarray] = None  # np bool [capN]
        self._node_full = True
        self._absorbed_version = -1
        self._consumed_version = -1
        self._t_mirror: Optional[Dict[str, np.ndarray]] = None
        self._n_mirror: Optional[Dict[str, np.ndarray]] = None
        self._t_feat_ver = -1   # mirror-diff short circuits (see plan)
        self._n_feat_ver = -1
        # sticky rung ratchets (the TOPK bucket-ratchet discipline): a
        # rung, once visited, stays — churn oscillating across a rung
        # boundary must not retrace every other steady cycle.  The
        # rerank ratchet excludes the top (=P, cold-plan) rung: pinning
        # it would make every later merge cycle pay a cold-sized build.
        # The merge rung additionally may only decay down to the PREVIOUS
        # bucket's live count: carried row_map values index old live
        # slots, which must stay inside the sliced prefix.
        self._c_rung = 0
        self._r_rung = 0
        self._m_rung = 0
        self._c_low = 0   # consecutive plans under the current rung
        self._r_low = 0
        self._m_low = 0
        self.last: Dict = {}

    # ------------------------------------------------------------------
    def absorb(self, record: Dict, version: int) -> None:
        """Fold one resident swap's delta record into the pending
        invalidation (called from ColumnStore.per_cycle_resident after
        every swap of this state's mesh path).  A version the planner has
        already CONSUMED is skipped — the same cycle's later dispatches
        (the failure-histogram re-swap is memoized at the same version)
        re-notify the same record, and re-marking it after plan() cleared
        the accumulators would double every delta into the next merge."""
        if version <= self._consumed_version:
            return
        for field in self.NODE_DELTA_FIELDS:
            if field not in record:
                continue
            rows = record[field]
            if rows is None:
                self._node_full = True
            elif self._changed is not None:
                if rows.size and rows[-1] < self._changed.shape[0]:
                    self._changed[rows] = True
                else:
                    self._node_full = True  # shape drift — cold
        self._absorbed_version = version

    # ------------------------------------------------------------------
    def _ensure(self, key, cols) -> None:
        if key != self.shape_key:
            self._reset()
            self.shape_key = key

    def _diff_mirror(self, mirror_slot: str, ver_slot: str, version: int,
                     sources) -> np.ndarray:
        """Union of changed-row masks across the named ColumnStore arrays
        (ingest-static features carry no scatter deltas — the state keeps
        its own mirrors).  Returns a bool mask over the axis; a shape
        change (bitset width growth, axis growth) reads as all-changed.
        Short-circuits on the ColumnStore's per-axis feature VERSION (the
        resident_features upload-cache key): an unmoved version means no
        ingest-static column changed, so the megabytes of copy+compare
        are skipped on every steady cycle."""
        mirror = getattr(self, mirror_slot)
        n = sources[0][1].shape[0]
        if mirror is not None and getattr(self, ver_slot) == version:
            return np.zeros(n, bool)
        out = np.zeros(n, bool)
        fresh = {}
        for name, arr in sources:
            fresh[name] = arr.copy()
            if mirror is None:
                out[:] = True
                continue
            old = mirror.get(name)
            if old is None or old.shape != arr.shape:
                out[:] = True
                continue
            if arr.ndim == 1:
                out |= old != arr
            else:
                out |= np.any(old != arr, axis=1)
        setattr(self, mirror_slot, fresh)
        setattr(self, ver_slot, version)
        return out

    # ------------------------------------------------------------------
    def plan(self, cols, pend_rows: np.ndarray, k: int,
             config) -> Optional[Dict]:
        """The per-solve invalidation plan, or None when warm cannot run
        this cycle (no per-cycle resident cache, or a swap this state did
        not absorb — both mean the delta chain is broken).

        Returns {"row_map", "changed", "rerank_rows", "rerank_slots",
        "table", "w", "cold"} — numpy plan arrays, the carried (or
        freshly zeroed) table, and the stored width."""
        cache = cols._per_cycle_dev.get(self.mesh)
        if cache is None or cache.version != self._absorbed_version:
            return None
        P = int(pend_rows.shape[0])
        capN = cols.nodes.cap
        capT = cols.tasks.cap
        W = k + WARM_WIDTH_MARGIN
        key = (P, W, capN, capT, config)
        self._ensure(key, cols)
        self.plans += 1
        if self._changed is None:
            self._changed = np.zeros(capN, bool)

        new_live = pend_rows[pend_rows >= 0]
        # ---- ingest-static feature diffs (no scatter deltas to ride) --
        task_dirty = self._diff_mirror(
            "_t_mirror", "_t_feat_ver", cols.task_feature_version, (
                ("t_init32", cols.t_init32),
                ("t_sel_bits", cols.t_sel_bits),
                ("t_sel_impossible", cols.t_sel_impossible),
                ("t_tol_bits", cols.t_tol_bits),
            ))
        node_feat_dirty = self._diff_mirror(
            "_n_mirror", "_n_feat_ver", cols.node_feature_version, (
                ("n_alloc32", cols.n_alloc32),
                ("n_label_bits", cols.n_label_bits),
                ("n_taint_bits", cols.n_taint_bits),
            ))

        # C rungs past the node capacity would make the fresh block wider
        # than the cold build it replaces — they escalate to cold instead
        c_rungs = tuple(
            r for r in WARM_CHANGED_BUCKETS if r < capN
        ) or (WARM_CHANGED_BUCKETS[0],)
        cold = (
            self.table is None
            or self.rows is None
            or self._node_full
            or bool(node_feat_dirty.all())
        )
        changed_mask = self._changed
        if not cold:
            changed_mask = changed_mask | node_feat_dirty
            n_changed = int(changed_mask.sum())
            if n_changed > min(c_rungs[-1], capN - 1):
                cold = True

        rerank_mask = np.zeros(P, bool)
        n_live = int(new_live.size)
        rungs = warm_rerank_rungs(P)
        # the merge rung: the [M] live prefix the device-side refresh
        # slices to (row_map's length IS the rung) — ratcheted with decay;
        # the decay floor covers the PREVIOUS bucket's live count so
        # carried old-slot indices always stay inside the prefix
        old_live = (
            int((self.rows >= 0).sum()) if self.rows is not None else 0
        )
        m_need = _rung(max(n_live, old_live, 1), rungs)
        self._m_rung, self._m_low = _ratchet(
            self._m_rung, m_need, self._m_low
        )
        m_rung = self._m_rung
        n_new = n_dirty = n_eroded = 0
        if cold:
            self.cold_builds += 1
            row_map = np.full(m_rung, -1, np.int32)
            rerank_mask[:n_live] = True
            changed = np.full(max(self._c_rung, c_rungs[0]), -1, np.int32)
            n_changed = 0
        else:
            # ---- bucket permutation (old slot per new slot) ----------
            old_live = self.rows[self.rows >= 0]
            pos = np.searchsorted(old_live, new_live)
            safe = np.minimum(pos, max(old_live.size - 1, 0))
            carried = (
                (pos < old_live.size) & (old_live[safe] == new_live)
                if old_live.size else np.zeros(n_live, bool)
            )
            row_map = np.full(m_rung, -1, np.int32)
            row_map[:n_live][carried] = pos[carried].astype(np.int32)
            # ---- the re-rank set -------------------------------------
            rerank_mask[:n_live] = ~carried                 # new rows
            n_new = int(np.sum(~carried))
            rerank_mask[:n_live] |= task_dirty[new_live]    # own features
            n_dirty = int(np.sum(task_dirty[new_live]))
            sparse = cols._aff_rows | cols._pref_rows       # conservative
            if sparse:
                rerank_mask[:n_live] |= np.isin(
                    new_live, np.fromiter(sparse, np.int64)
                )
            if self.eroded_dev is not None:
                # kbt: allow[KBT010] tiny [P]-bool readback of LAST cycle's
                # erosion flags at plan time — long since computed, so the
                # sync is free; riding the action readback would thread
                # warm state through every consumer for no transfer win
                eroded = np.asarray(self.eroded_dev)
                er_rows = self.rows[np.flatnonzero(eroded)]
                er_rows = er_rows[er_rows >= 0]
                n_eroded = int(er_rows.size)
                if er_rows.size:
                    # SPARE-FILL budget: erosion refresh only occupies the
                    # re-rank rung's padding slots, never grows the rung —
                    # the mandatory set (new/dirty rows) prices the rung,
                    # and refreshing eroded rows inside it is free compute.
                    # Deferred rows stay EXACT (a thin table answers via
                    # the prefix/exhaustion contract) and retry next cycle.
                    base = int(rerank_mask.sum())
                    spare = _rung(max(base, 1), warm_rerank_rungs(P)) - base
                    if spare > 0:
                        admit = np.isin(new_live, er_rows)
                        admit &= ~rerank_mask[:n_live]
                        extra = np.flatnonzero(admit)[:spare]
                        rerank_mask[extra] = True
            # changed-node list at its (ratcheted, decaying) rung
            ch_rows = np.flatnonzero(changed_mask)
            n_changed = int(ch_rows.size)
            self._c_rung, self._c_low = _ratchet(
                self._c_rung, _rung(max(n_changed, 1), c_rungs),
                self._c_low, floor=c_rungs[0],
            )
            changed = np.full(self._c_rung, -1, np.int32)
            changed[:n_changed] = ch_rows.astype(np.int32)

        n_rerank = int(rerank_mask.sum())
        rrung = _rung(max(n_rerank, 1), rungs)
        if rrung < P:
            # sub-P rungs ratchet with decay; a cold-sized rung (=P)
            # never pins the ratchet
            self._r_rung, self._r_low = _ratchet(
                self._r_rung, rrung, self._r_low, floor=rungs[0]
            )
            rrung = min(self._r_rung, m_rung)
        rerank_slots = np.full(rrung, -1, np.int32)
        slots = np.flatnonzero(rerank_mask)
        rerank_slots[:n_rerank] = slots.astype(np.int32)
        rerank_rows = np.full(rrung, -1, np.int32)
        rerank_rows[:n_rerank] = pend_rows[slots]

        table = self.table
        if table is None:
            table = self._init_table(P, W)
        # plan consumed: clear the accumulators (and mark the consumed
        # swap version so same-version re-notifies can't re-mark them);
        # the next swaps rebuild
        self._changed = np.zeros(capN, bool)
        self._node_full = False
        self._consumed_version = self._absorbed_version
        self.rows = pend_rows.copy()
        self.reranked_total += n_rerank
        self.changed_total += n_changed
        self.last = {
            "cold": cold, "reranked": n_rerank, "changed": n_changed,
            "bucket_live": n_live, "w": W,
            # re-rank attribution (bench/sim evidence): fresh bucket rows,
            # rows whose own features moved, θ/φ-eroded rows
            "new": n_new, "dirty": n_dirty, "eroded": n_eroded,
        }
        return {
            "row_map": row_map, "changed": changed,
            "rerank_rows": rerank_rows, "rerank_slots": rerank_slots,
            "table": table, "w": W, "cold": cold,
        }

    def _init_table(self, P: int, W: int):
        import jax
        import jax.numpy as jnp

        idx = np.zeros((P, W), np.int32)
        skey = np.full((P, W), -(2 ** 31), np.int32)
        hsh = np.full((P, W), -1, np.int32)
        trunc = np.zeros(P, bool)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P_

            repl = NamedSharding(self.mesh, P_())
            return tuple(
                jax.device_put(a, repl) for a in (idx, skey, hsh, trunc)
            )
        return tuple(map(jnp.asarray, (idx, skey, hsh, trunc)))

    def commit(self, table, eroded) -> None:
        """Adopt the refreshed table + erosion flags the solve returned
        (the stale buffers were donated into the refresh off-CPU)."""
        self.table = table
        self.eroded_dev = eroded

    def drop(self) -> None:
        """Abandon the carry (next plan cold-builds).  The dispatch calls
        this when a warm solve raises between plan() and commit():
        plan() already consumed the invalidation accumulators and — off
        CPU — the solve donated the table buffers, so carrying on would
        pair a stale (or deleted) table with the new bucket order."""
        self._reset()

    def counters(self) -> Dict:
        return {
            "plans": self.plans,
            "cold_builds": self.cold_builds,
            "reranked_total": self.reranked_total,
            "changed_total": self.changed_total,
            "last": dict(self.last),
        }
