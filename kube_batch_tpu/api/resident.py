"""Per-cycle device-resident snapshot columns with scatter-delta refresh.

``ColumnStore.resident_features`` already keeps the ingest-static columns
(task requests/bitsets, node allocatable) alive on device across cycles.
This module extends residency to the *per-cycle* columns — statuses, node
ledgers, job/queue rows — which until now were re-uploaded wholesale by
every solve dispatch even when a steady-state cycle changed a few hundred
rows out of 50k.

Mechanism: for each cached field the host keeps a mirror of what the device
holds.  Each cycle the freshly built host column is diffed against the
mirror (one vectorized compare — cheaper than the upload it replaces):

- no rows changed  → the cached device array is handed to the solve as-is;
- a small delta    → the (rows, values) pair is padded to a FIXED slot
  count and applied on device as one scatter (``.at[rows].set(mode="drop")``
  with out-of-range padding indices), with the stale device buffer DONATED
  to the update so XLA writes in place instead of allocating;
- a large delta or a shape change (axis growth) → full re-upload.

The fixed slot width keeps the scatter's jit cache to one specialization
per (field shape, dtype): steady-state cycles compile nothing (the
bench's retrace counters prove it).  Values are bit-identical to a full
upload by construction — the scatter writes exactly the host rows — and
tests/test_snapshot_delta.py checks the round-trip.

Donation is skipped on the CPU backend (unsupported there; jax would warn
every cycle).  The mesh-sharded solve path keeps full uploads — sharded
scatter residency is a follow-on (ROADMAP).

Donation audit (PR 4): every donating call site in this module rebinds the
donated name to the call's result (``dev = _scatter_fn()(dev, ...)``) —
the shape KBT006 (analysis/flowrules.py) verifies package-wide, so a
post-donation read introduced later fails the tier-1 self-enforcement
test.  The scatter itself is registered in the jaxpr audit
(analysis/jaxpr_audit.py), which asserts its donation wiring per backend
(KBT104) and that no f64/transfer/callback sneaks into the traced update.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from kube_batch_tpu.utils import jitstats

# snapshot fields refreshed per cycle (everything the static feature cache
# does not own, minus the variable-K sparse affinity rows)
PER_CYCLE_FIELDS: Tuple[str, ...] = (
    "task_status", "task_node", "task_valid", "task_pending",
    "task_best_effort",
    "node_idle", "node_releasing", "node_used", "node_valid", "node_sched",
    "job_min_avail", "job_ready", "job_queue", "job_prio", "job_creation",
    "job_valid", "job_schedulable", "job_allocated",
    "queue_weight", "queue_capability", "queue_alloc", "queue_request",
    "queue_valid",
    "total",
)

#: fixed scatter width — one compiled scatter per (field shape, dtype);
#: deltas wider than this take the full-upload path (at which point the
#: transfer is no longer the bottleneck anyway)
SCATTER_SLOTS = 4096


_SCATTER = None


def _scatter_fn():
    """The shared jitted scatter — ONE module-level function so every cache
    instance (simulator multi-scheduler runs, bench pairs, the test suite)
    reuses the same compiled specializations and jitstats tracks a single
    entry instead of retaining one wrapper per dead instance."""
    global _SCATTER
    if _SCATTER is None:
        import jax

        def scatter(dev, rows, vals):
            return dev.at[rows].set(vals, mode="drop")

        # donate the stale device buffer on real accelerators so the
        # update writes in place; CPU ignores donation (and warns), so
        # skip it there
        donate = () if jax.default_backend() == "cpu" else (0,)
        _SCATTER = jitstats.register(
            "resident_scatter", jax.jit(scatter, donate_argnums=donate)
        )
    return _SCATTER


class PerCycleDeviceCache:
    def __init__(self) -> None:
        self._mirror: Dict[str, np.ndarray] = {}
        self._dev: Dict[str, object] = {}
        # last (input snap, swapped result): the failure-histogram dispatch
        # re-swaps the SAME snap the solve dispatch just synced — a
        # guaranteed all-clean diff over every field, skipped by identity
        self._last_in = None
        self._last_out = None
        # diagnostics for the bench / tests
        self.full_uploads = 0
        self.scatter_updates = 0
        self.clean_hits = 0

    def _refresh(self, field: str, host: np.ndarray):
        import jax

        mirror = self._mirror.get(field)
        if (
            mirror is None
            or mirror.shape != host.shape
            or mirror.dtype != host.dtype
        ):
            self.full_uploads += 1
            dev = jax.device_put(host)
            # pre-warm the scatter specialization for this (shape, dtype)
            # NOW — an all-out-of-range index vector writes nothing, so the
            # values are untouched, but the first real delta in a later
            # steady-state cycle becomes a cache hit instead of a retrace
            rows = np.full(SCATTER_SLOTS, host.shape[0], np.int32)
            vals = np.zeros((SCATTER_SLOTS,) + host.shape[1:], host.dtype)
            dev = _scatter_fn()(dev, rows, vals)
            self._mirror[field] = host.copy()
            self._dev[field] = dev
            return dev
        if host.ndim == 1:
            changed = np.flatnonzero(mirror != host)
        else:
            changed = np.flatnonzero(np.any(mirror != host, axis=1))
        if changed.size == 0:
            self.clean_hits += 1
            return self._dev[field]
        if changed.size > SCATTER_SLOTS:
            self.full_uploads += 1
            dev = jax.device_put(host)
            self._mirror[field] = host.copy()
            self._dev[field] = dev
            return dev
        n = host.shape[0]
        # pad with an out-of-range row index — mode="drop" discards the
        # padding writes, so the scatter shape never depends on delta size
        rows = np.full(SCATTER_SLOTS, n, np.int32)
        rows[: changed.size] = changed
        vals = np.zeros((SCATTER_SLOTS,) + host.shape[1:], host.dtype)
        vals[: changed.size] = host[changed]
        dev = _scatter_fn()(self._dev[field], rows, vals)
        mirror[changed] = host[changed]
        self._dev[field] = dev
        self.scatter_updates += 1
        return dev

    def swap(self, snap):
        """`snap` with every per-cycle field replaced by its device-resident
        copy (refreshed by delta).  The caller keeps using the ORIGINAL
        host-backed snap for numpy reads — only the returned copy feeds the
        solve, mirroring the resident_features contract.  A repeat call
        with the identical snap object (the same cycle's second dispatch)
        returns the memoized result without re-diffing."""
        if snap is self._last_in:
            return self._last_out
        updates = {
            field: self._refresh(field, np.asarray(getattr(snap, field)))
            for field in PER_CYCLE_FIELDS
        }
        out = snap._replace(**updates)
        self._last_in, self._last_out = snap, out
        return out
