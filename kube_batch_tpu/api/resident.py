"""Per-cycle device-resident snapshot columns with scatter-delta refresh.

``ColumnStore.resident_features`` already keeps the ingest-static columns
(task requests/bitsets, node allocatable) alive on device across cycles.
This module extends residency to the *per-cycle* columns — statuses, node
ledgers, job/queue rows — which until now were re-uploaded wholesale by
every solve dispatch even when a steady-state cycle changed a few hundred
rows out of 50k.

Mechanism: for each cached field the host keeps a mirror of what the device
holds.  Each cycle the freshly built host column is diffed against the
mirror (one vectorized compare — cheaper than the upload it replaces):

- no rows changed  → the cached device array is handed to the solve as-is;
- a small delta    → the (rows, values) pair is padded to a FIXED slot
  count and applied on device as one scatter (``.at[rows].set(mode="drop")``
  with out-of-range padding indices), with the stale device buffer DONATED
  to the update so XLA writes in place instead of allocating;
- a large delta or a shape change (axis growth) → full re-upload.

The fixed slot width keeps the scatter's jit cache to one specialization
per (field shape, dtype): steady-state cycles compile nothing (the
bench's retrace counters prove it).  Values are bit-identical to a full
upload by construction — the scatter writes exactly the host rows — and
tests/test_snapshot_delta.py checks the round-trip.

Donation is skipped on the CPU backend (unsupported there; jax would warn
every cycle).

Mesh-sharded residency (:class:`ShardedPerCycleDeviceCache`): the sharded
solve keeps the same columns alive as ``NamedSharding``-placed buffers —
node-axis columns sharded over the mesh, everything else replicated — and
refreshes them with PER-SHARD fixed-width donated scatter deltas.  The
changed rows are partitioned by owning shard on the host and shipped as
``[n_shards, slots]`` LOCAL indices + values whose leading axis carries the
mesh sharding, so the jitted update (a vmapped per-shard scatter with
explicit ``in_shardings``/``out_shardings``) routes each delta slice
straight to its owning chip — no gather, no reshard, no cross-chip traffic.
Fallbacks to a full (sharded) re-upload: cold cache, axis growth, a delta
wider than the per-shard slot budget (high churn), or a mesh change (the
ColumnStore drops the old mesh's cache wholesale — see
``per_cycle_resident``; the shape buckets are divisible by any
power-of-two mesh axis, and jax itself rejects an indivisible placement
before any solve could run).

Donation audit (PR 4): every donating call site in this module rebinds the
donated name to the call's result (``dev = _scatter_fn()(dev, ...)``) —
the shape KBT006 (analysis/flowrules.py) verifies package-wide, so a
post-donation read introduced later fails the tier-1 self-enforcement
test.  The scatters (single-device AND per-mesh) are registered in the
jaxpr audit (analysis/jaxpr_audit.py), which asserts their donation wiring
per backend (KBT104) and that no f64/transfer/callback sneaks into the
traced update.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from kube_batch_tpu.utils import jitstats

# snapshot fields refreshed per cycle (everything the static feature cache
# does not own, minus the variable-K sparse affinity rows)
PER_CYCLE_FIELDS: Tuple[str, ...] = (
    "task_status", "task_node", "task_valid", "task_pending",
    "task_best_effort",
    "node_idle", "node_releasing", "node_used", "node_valid", "node_sched",
    "job_min_avail", "job_ready", "job_queue", "job_prio", "job_creation",
    "job_valid", "job_schedulable", "job_allocated",
    "queue_weight", "queue_capability", "queue_alloc", "queue_request",
    "queue_valid",
    "total",
)

#: the subset whose leading axis is the node axis — sharded over the mesh
#: on the sharded solve path (parallel/mesh.snapshot_shardings); everything
#: else replicates
NODE_AXIS_FIELDS = frozenset((
    "node_idle", "node_releasing", "node_used", "node_valid", "node_sched",
))

#: fixed scatter width buckets — a delta ships at the smallest bucket that
#: holds it, so tiny steady-state deltas don't pay the worst-case payload;
#: every bucket is pre-warmed at full-upload time, so the bounded set of
#: specializations per (field shape, dtype) never retraces mid-steady-state.
#: Deltas wider than the largest bucket take the full-upload path (at which
#: point the transfer is no longer the bottleneck anyway).
SCATTER_SLOT_BUCKETS: Tuple[int, ...] = (64, 512, 4096)
SCATTER_SLOTS = SCATTER_SLOT_BUCKETS[-1]

#: per-shard slot-width buckets of the mesh scatter: the [n_shards, slots]
#: delta is sharded on its leading axis, so each chip receives exactly its
#: own slice.  This static ladder is the DEFAULT (zero observed churn);
#: the sharded cache retargets its live ladder from the churn EWMA
#: (:func:`adaptive_ladder`), capped by SHARD_SCATTER_SLOTS.
SHARD_SCATTER_SLOT_BUCKETS: Tuple[int, ...] = (16, 128, 1024)
SHARD_SCATTER_SLOTS = SHARD_SCATTER_SLOT_BUCKETS[-1]

#: churn EWMA smoothing for the adaptive per-shard ladder
CHURN_EWMA_DECAY = 0.8


def _slot_bucket(n: int, buckets: Tuple[int, ...]) -> int:
    """Smallest slot bucket holding an n-row delta (caller guarantees
    n ≤ buckets[-1])."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def all_shard_buckets(max_slots: int) -> Tuple[int, ...]:
    """Every per-shard bucket width the adaptive ladder can ever select
    (powers of two from 16 up to the hard cap).  The cold-upload prewarm
    compiles ALL of them, so a later ladder retarget is pure payload-
    sizing bookkeeping — no compile can ever land in a steady-state
    cycle, no matter where the churn EWMA moves."""
    out = []
    v = min(16, max_slots)
    while True:
        out.append(v)
        if v >= max_slots:
            return tuple(out)
        v = min(v * 2, max_slots)


def adaptive_ladder(ewma: float, max_slots: int) -> Tuple[int, ...]:
    """Per-shard slot-bucket ladder sized from the observed churn EWMA
    (replacing the static 16/128/1024 cap): the base bucket is the
    smallest power of two ≥ max(16, 2×ewma) — 2× headroom so the typical
    steady-state delta lands in the FIRST bucket instead of climbing the
    ladder — then ×8 steps up to the hard cap.  Zero churn reproduces the
    static default exactly; a steady high-churn regime drops the
    too-small buckets (their payloads would never be used) and starts at
    a bucket the observed deltas actually fit."""
    base = 16
    target = max(16.0, 2.0 * ewma)
    while base < target and base < max_slots:
        base *= 2
    base = min(base, max_slots)
    ladder = [base]
    while ladder[-1] < max_slots:
        ladder.append(min(ladder[-1] * 8, max_slots))
    return tuple(ladder)


_SCATTER = None


def _scatter_fn():
    """The shared jitted scatter — ONE module-level function so every cache
    instance (simulator multi-scheduler runs, bench pairs, the test suite)
    reuses the same compiled specializations and jitstats tracks a single
    entry instead of retaining one wrapper per dead instance."""
    global _SCATTER
    if _SCATTER is None:
        import jax

        def scatter(dev, rows, vals):
            return dev.at[rows].set(vals, mode="drop")

        # donate the stale device buffer on real accelerators so the
        # update writes in place; CPU ignores donation (and warns), so
        # skip it there
        donate = () if jax.default_backend() == "cpu" else (0,)
        _SCATTER = jitstats.register(
            "resident_scatter", jax.jit(scatter, donate_argnums=donate)
        )
    return _SCATTER


# per-(mesh, sharded?) jitted scatters — memoized so steady-state sharded
# cycles reuse one compiled specialization per (field shape, dtype), same
# contract as the single-device _scatter_fn
_MESH_SCATTER: dict = {}


def _mesh_repl_scatter_fn(mesh):
    """The replicated-placement scatter for `mesh`: same update as the
    single-device one, with explicit replicated in/out shardings so the
    result stays a committed mesh array the sharded solve accepts as-is."""
    fn = _MESH_SCATTER.get((mesh, "repl"))
    if fn is None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(mesh, P())

        def scatter(dev, rows, vals):
            return dev.at[rows].set(vals, mode="drop")

        donate = () if jax.default_backend() == "cpu" else (0,)
        fn = jitstats.register(
            "resident_scatter_repl",
            jax.jit(scatter, donate_argnums=donate,
                    in_shardings=(repl, repl, repl), out_shardings=repl),
        )
        _MESH_SCATTER[(mesh, "repl")] = fn
    return fn


def _mesh_shard_scatter_fn(mesh):
    """The per-shard scatter for node-axis columns: `dev` is [N, ...]
    sharded over the node axis, `rows`/`vals` are [n_shards, slots(, ...)]
    sharded on their LEADING axis with shard-LOCAL row indices — the vmap
    over the shard axis makes each chip scatter only its own delta slice
    (out-of-range padding rows drop), and the explicit shardings keep GSPMD
    from inserting any gather/reshard around the update."""
    fn = _MESH_SCATTER.get((mesh, "shard"))
    if fn is None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kube_batch_tpu.parallel.mesh import NODE_AXIS

        shard = NamedSharding(mesh, P(NODE_AXIS))

        def scatter_sharded(dev, rows, vals):
            n_shards = rows.shape[0]
            dev3 = dev.reshape(
                (n_shards, dev.shape[0] // n_shards) + dev.shape[1:]
            )
            out = jax.vmap(
                lambda d, r, v: d.at[r].set(v, mode="drop")
            )(dev3, rows, vals)
            return out.reshape(dev.shape)

        donate = () if jax.default_backend() == "cpu" else (0,)
        fn = jitstats.register(
            "resident_scatter_sharded",
            jax.jit(scatter_sharded, donate_argnums=donate,
                    in_shardings=(shard, shard, shard), out_shardings=shard),
        )
        _MESH_SCATTER[(mesh, "shard")] = fn
    return fn


def scatter_summary(per_path_counters: Dict[str, Dict[str, int]]
                    ) -> Dict[str, Dict]:
    """Per-path counter summary with the delta-vs-full bytes-moved
    reduction — the ONE derivation behind the bench artifact and the sim's
    longitudinal report (`ColumnStore.resident_counters()` feeds it)."""
    out: Dict[str, Dict] = {}
    for path, c in per_path_counters.items():
        moved = c["bytes_full"] + c["bytes_scatter"]
        rec = dict(c)
        rec["bytes_moved"] = moved
        if c["bytes_if_full"]:
            rec["upload_reduction"] = round(
                1.0 - moved / c["bytes_if_full"], 3
            )
        out[path] = rec
    return out


class PerCycleDeviceCache:
    def __init__(self) -> None:
        self._mirror: Dict[str, np.ndarray] = {}
        self._dev: Dict[str, object] = {}
        # last (input snap, swapped result): the failure-histogram dispatch
        # re-swaps the SAME snap the solve dispatch just synced — a
        # guaranteed all-clean diff over every field, skipped by identity
        self._last_in = None
        self._last_out = None
        # monotonic swap version — the warm-standby revalidation's token:
        # a cache that has synced at least one snapshot (version > 0) and
        # passes the store's consistency check after a failover rebuild is
        # kept (buffers + compiled specializations survive; the next swap's
        # mirror diff absorbs any residual divergence as ordinary deltas)
        self.version = 0
        # diagnostics for the bench / tests
        self.full_uploads = 0
        self.scatter_updates = 0
        self.clean_hits = 0
        # bytes actually shipped host→device vs what full per-cycle uploads
        # would have shipped — the bench's delta-vs-full reduction evidence
        self.bytes_full = 0
        self.bytes_scatter = 0
        self.bytes_if_full = 0

    def counters(self) -> Dict[str, int]:
        return {
            "version": self.version,
            "full_uploads": self.full_uploads,
            "scatter_updates": self.scatter_updates,
            "clean_hits": self.clean_hits,
            "bytes_full": self.bytes_full,
            "bytes_scatter": self.bytes_scatter,
            "bytes_if_full": self.bytes_if_full,
        }

    @staticmethod
    def _payload_bytes(slots: int, host: np.ndarray) -> int:
        """Scatter payload size for a `slots`-wide delta of `host`'s row
        shape (int32 index + one value row per slot)."""
        row = host.dtype.itemsize * int(
            np.prod(host.shape[1:], dtype=np.int64)
        )
        return slots * (4 + row)

    def _refresh(self, field: str, host: np.ndarray):
        import jax

        self.bytes_if_full += host.nbytes
        mirror = self._mirror.get(field)
        if (
            mirror is None
            or mirror.shape != host.shape
            or mirror.dtype != host.dtype
        ):
            self.full_uploads += 1
            self.bytes_full += host.nbytes
            dev = jax.device_put(host)
            # pre-warm EVERY slot-bucket specialization for this (shape,
            # dtype) NOW — an all-out-of-range index vector writes nothing,
            # so the values are untouched, but any real delta width in a
            # later steady-state cycle becomes a cache hit, never a
            # retrace.  TWO passes: the first bucket's first call sees the
            # device_put-placed buffer, while real deltas always see a
            # scatter OUTPUT buffer — whose layout can key a fresh
            # specialization; the second pass compiles every bucket against
            # the output-typed buffer too
            for _ in range(2):
                for slots in SCATTER_SLOT_BUCKETS:
                    rows = np.full(slots, host.shape[0], np.int32)
                    vals = np.zeros((slots,) + host.shape[1:], host.dtype)
                    dev = _scatter_fn()(dev, rows, vals)
            self._mirror[field] = host.copy()
            self._dev[field] = dev
            return dev
        if host.ndim == 1:
            changed = np.flatnonzero(mirror != host)
        else:
            changed = np.flatnonzero(np.any(mirror != host, axis=1))
        if changed.size == 0:
            self.clean_hits += 1
            return self._dev[field]
        slots = _slot_bucket(changed.size, SCATTER_SLOT_BUCKETS)
        if (
            changed.size > SCATTER_SLOTS
            # a tiny column: shipping the whole thing is cheaper than the
            # smallest fixed-width scatter payload
            or self._payload_bytes(slots, host) >= host.nbytes
        ):
            # specializations are already warm — no prewarm on this path
            self.full_uploads += 1
            self.bytes_full += host.nbytes
            dev = jax.device_put(host)
            self._mirror[field] = host.copy()
            self._dev[field] = dev
            return dev
        n = host.shape[0]
        # pad with an out-of-range row index — mode="drop" discards the
        # padding writes, so the scatter shape depends only on the (pre-
        # warmed) slot bucket, never on the exact delta size
        rows = np.full(slots, n, np.int32)
        rows[: changed.size] = changed
        vals = np.zeros((slots,) + host.shape[1:], host.dtype)
        vals[: changed.size] = host[changed]
        dev = _scatter_fn()(self._dev[field], rows, vals)
        mirror[changed] = host[changed]
        self._dev[field] = dev
        self.scatter_updates += 1
        self.bytes_scatter += rows.nbytes + vals.nbytes
        return dev

    def swap(self, snap):
        """`snap` with every per-cycle field replaced by its device-resident
        copy (refreshed by delta).  The caller keeps using the ORIGINAL
        host-backed snap for numpy reads — only the returned copy feeds the
        solve, mirroring the resident_features contract.  A repeat call
        with the identical snap object (the same cycle's second dispatch)
        returns the memoized result without re-diffing."""
        if snap is self._last_in:
            return self._last_out
        self.version += 1
        updates = {
            field: self._refresh(field, np.asarray(getattr(snap, field)))
            for field in PER_CYCLE_FIELDS
        }
        out = snap._replace(**updates)
        self._last_in, self._last_out = snap, out
        return out


class ShardedPerCycleDeviceCache(PerCycleDeviceCache):
    """Per-cycle residency for the mesh-sharded solve path (module
    docstring): node-axis columns live sharded over `mesh`, everything else
    replicated across it, refreshed by per-shard donated scatter deltas.

    Multi-host meshes: each process materializes and ships only its own
    ADDRESSABLE shards — uploads and per-shard payloads go through
    ``jax.make_array_from_callback`` (the callback is invoked per local
    shard only), so a host's cross-DCN upstream per cycle is its own
    shard's delta rows, never the full column.  The byte counters record
    the per-HOST share on sharded fields.

    The per-shard slot ladder is ADAPTIVE (:func:`adaptive_ladder`): a
    churn EWMA over the per-cycle max per-shard delta width retargets the
    bucket set, replacing the static 16/128/1024 sizing.  The cold-upload
    prewarm compiles the FULL reachable bucket set up front
    (:func:`all_shard_buckets`, no-op scatters with all-out-of-range
    padding indices), so a retarget is pure payload-sizing bookkeeping
    and a real delta of any admissible width is a jit cache hit — steady
    state never retraces regardless of where the ladder moves."""

    def __init__(self, mesh) -> None:
        super().__init__()
        self.mesh = mesh
        from kube_batch_tpu.parallel.mesh import NODE_AXIS

        # the SCATTER shard count is the node-axis extent — on a 2-D
        # (tasks, nodes) mesh the node columns replicate across the task
        # axis, so the [n_shards, slots] payload splits by node shard only
        self.n_shards = int(dict(mesh.shape)[NODE_AXIS])
        self.churn_ewma = 0.0
        self._ladder: Tuple[int, ...] = adaptive_ladder(
            0.0, SHARD_SCATTER_SLOTS
        )
        self._warm: Dict[str, set] = {}   # field → warmed bucket widths
        self._cycle_max = 0
        self.ladder_retargets = 0

    def counters(self) -> Dict[str, int]:
        out = super().counters()
        out["churn_ewma"] = round(self.churn_ewma, 2)
        out["slot_ladder"] = list(self._ladder)
        out["ladder_retargets"] = self.ladder_retargets
        return out

    def _sharding(self, field: str):
        from kube_batch_tpu.parallel.mesh import snapshot_shardings

        return getattr(snapshot_shardings(self.mesh), field)

    def _host_fraction(self) -> float:
        """This process's addressable share of the mesh — the per-host
        byte-counter scale for sharded payloads."""
        import jax

        pc = jax.process_count()
        return 1.0 / pc if pc > 1 else 1.0

    def _put(self, host: np.ndarray, sharding):
        """Placed upload: single-process goes through device_put; on a
        multi-host mesh each process materializes only its addressable
        shards via make_array_from_callback (the per-host scatter/upload
        contract above)."""
        import jax

        if jax.process_count() > 1:
            return jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx]
            )
        return jax.device_put(host, sharding)

    def _put_payload(self, arr: np.ndarray):
        """Per-shard scatter payload ([n_shards, slots, ...], leading axis
        sharded over the node axis): pre-placed per host on multi-process
        meshes so only the local shards' slices upload; single-process
        passes the numpy array straight to the jitted scatter (whose
        in_shardings place it)."""
        import jax

        if jax.process_count() == 1:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kube_batch_tpu.parallel.mesh import NODE_AXIS

        return jax.make_array_from_callback(
            arr.shape, NamedSharding(self.mesh, P(NODE_AXIS)),
            lambda idx: arr[idx],
        )

    def _prewarm_shard_field(self, field: str, dev, n_rows: int):
        """Compile every not-yet-warm per-shard bucket for `field` — the
        FULL reachable set (:func:`all_shard_buckets`), not just the live
        ladder — with no-op scatters (all padding indices → zero writes,
        two passes so the scatter-OUTPUT buffer layout is covered too).
        Returns the (donated and rebound) device buffer."""
        host = self._mirror.get(field)
        dtype = host.dtype if host is not None else np.float32
        tail = host.shape[1:] if host is not None else ()
        s = n_rows // self.n_shards
        warm = self._warm.setdefault(field, set())
        todo = [
            b for b in all_shard_buckets(SHARD_SCATTER_SLOTS)
            if b not in warm
        ]
        for _ in range(2):
            for slots in todo:
                rows = np.full((self.n_shards, slots), s, np.int32)
                vals = np.zeros((self.n_shards, slots) + tail, dtype)
                dev = _mesh_shard_scatter_fn(self.mesh)(
                    dev, self._put_payload(rows), self._put_payload(vals)
                )
        warm.update(todo)
        return dev

    def _note_churn(self, per_shard_max: int) -> None:
        self._cycle_max = max(self._cycle_max, per_shard_max)

    def _retarget_ladder(self) -> None:
        """EWMA update + ladder retarget at swap end.  Retargeting only
        changes which payload widths later deltas ship — every reachable
        bucket was compiled at cold-upload prewarm, so this costs nothing
        and can never retrace a steady-state cycle."""
        self.churn_ewma = (
            CHURN_EWMA_DECAY * self.churn_ewma
            + (1.0 - CHURN_EWMA_DECAY) * self._cycle_max
        )
        self._cycle_max = 0
        new = adaptive_ladder(self.churn_ewma, SHARD_SCATTER_SLOTS)
        if new != self._ladder:
            self._ladder = new
            self.ladder_retargets += 1

    def swap(self, snap):
        if snap is self._last_in:
            return self._last_out
        out = super().swap(snap)
        self._retarget_ladder()
        return out

    def _full_upload(self, field: str, host: np.ndarray,
                     prewarm: bool = True):
        """Sharded full upload; on cold/shape-change uploads (`prewarm`)
        every scatter slot bucket is pre-compiled so later deltas never
        retrace.  A node axis the mesh cannot divide would make per-shard
        indexing undefined — but jax itself rejects such a placement
        (NamedSharding divisibility), so the sharded solve path never
        reaches here with one; the shape buckets (snapshot.bucket) are
        divisible by any power-of-two mesh."""
        sharded_axis = field in NODE_AXIS_FIELDS
        self.full_uploads += 1
        self.bytes_full += int(
            host.nbytes * (self._host_fraction() if sharded_axis else 1.0)
        )
        dev = self._put(host, self._sharding(field))
        if not prewarm:
            self._mirror[field] = host.copy()
            self._dev[field] = dev
            return dev
        # two prewarm passes — see PerCycleDeviceCache._refresh: real deltas
        # see scatter-OUTPUT buffers, whose (sharded) layout can key a fresh
        # specialization vs the device_put-placed first input
        self._mirror[field] = host.copy()
        if sharded_axis:
            self._warm.pop(field, None)  # shape may have changed — rewarm
            dev = self._prewarm_shard_field(field, dev, host.shape[0])
        else:
            for _ in range(2):
                for slots in SCATTER_SLOT_BUCKETS:
                    rows = np.full(slots, host.shape[0], np.int32)
                    vals = np.zeros((slots,) + host.shape[1:], host.dtype)
                    dev = _mesh_repl_scatter_fn(self.mesh)(dev, rows, vals)
        self._dev[field] = dev
        return dev

    def _refresh(self, field: str, host: np.ndarray):
        sharded_axis = field in NODE_AXIS_FIELDS
        # per-host accounting on sharded fields must scale the DENOMINATOR
        # too, or upload_reduction would read inflated on multi-host meshes
        self.bytes_if_full += int(
            host.nbytes * (self._host_fraction() if sharded_axis else 1.0)
        )
        mirror = self._mirror.get(field)
        if (
            mirror is None
            or mirror.shape != host.shape
            or mirror.dtype != host.dtype
        ):
            return self._full_upload(field, host)
        if host.ndim == 1:
            changed = np.flatnonzero(mirror != host)
        else:
            changed = np.flatnonzero(np.any(mirror != host, axis=1))
        if changed.size == 0:
            self.clean_hits += 1
            return self._dev[field]
        if sharded_axis:
            s = host.shape[0] // self.n_shards
            shard_ids = changed // s  # ascending: flatnonzero sorts rows
            counts = np.bincount(shard_ids, minlength=self.n_shards)
            widest = int(counts.max())
            self._note_churn(widest)
            if widest > min(self._ladder[-1], SHARD_SCATTER_SLOTS):
                # over the LIVE ladder's cap — full re-upload; the churn
                # note above grows the EWMA so a sustained regime retargets
                # (and pre-warms) a wider ladder instead of thrashing
                return self._full_upload(field, host, prewarm=False)
            slots = _slot_bucket(widest, self._ladder)
            if self._payload_bytes(slots, host) * self.n_shards >= host.nbytes:
                # tiny sharded column: the whole upload is cheaper than the
                # smallest per-shard scatter payload
                return self._full_upload(field, host, prewarm=False)
            rows = np.full((self.n_shards, slots), s, np.int32)
            offs = np.concatenate(([0], np.cumsum(counts)[:-1]))
            pos = np.arange(changed.size) - np.repeat(offs, counts)
            rows[shard_ids, pos] = (changed % s).astype(np.int32)
            vals = np.zeros(
                (self.n_shards, slots) + host.shape[1:], host.dtype
            )
            vals[shard_ids, pos] = host[changed]
            dev = _mesh_shard_scatter_fn(self.mesh)(
                self._dev[field], self._put_payload(rows),
                self._put_payload(vals),
            )
            mirror[changed] = host[changed]
            self._dev[field] = dev
            self.scatter_updates += 1
            self.bytes_scatter += int(
                (rows.nbytes + vals.nbytes) * self._host_fraction()
            )
            return dev
        else:
            if changed.size > SCATTER_SLOTS:
                return self._full_upload(field, host, prewarm=False)
            slots = _slot_bucket(changed.size, SCATTER_SLOT_BUCKETS)
            if self._payload_bytes(slots, host) >= host.nbytes:
                return self._full_upload(field, host, prewarm=False)
            rows = np.full(slots, host.shape[0], np.int32)
            rows[: changed.size] = changed
            vals = np.zeros((slots,) + host.shape[1:], host.dtype)
            vals[: changed.size] = host[changed]
            dev = _mesh_repl_scatter_fn(self.mesh)(
                self._dev[field], rows, vals
            )
        mirror[changed] = host[changed]
        self._dev[field] = dev
        self.scatter_updates += 1
        self.bytes_scatter += rows.nbytes + vals.nbytes
        return dev
