"""The framework's ingest object model: Pod, PodGroup, Queue, Node specs.

The reference consumes Kubernetes API objects (v1.Pod, v1.Node, the PodGroup
and Queue CRDs in pkg/apis/scheduling/v1alpha1/types.go:93-223). This
framework is standalone — there is no apiserver in the loop — so these are
lightweight first-class dataclasses with exactly the fields the scheduler
reads. A k8s front-end (or any other cluster manager) adapts its objects into
these before feeding the cache.
"""

from __future__ import annotations

import copy
import dataclasses
import itertools
from typing import Dict, List, Mapping, Optional, Tuple

from kube_batch_tpu.api.types import PodGroupPhase, PodPhase

# Annotation linking a Pod to its PodGroup (apis/scheduling/v1alpha1/labels.go:21).
GROUP_NAME_ANNOTATION = "scheduling.k8s.io/group-name"

_uid_counter = itertools.count()


def _auto_uid(prefix: str) -> str:
    return f"{prefix}-{next(_uid_counter)}"


@dataclasses.dataclass
class Toleration:
    """Pod toleration (subset of v1.Toleration the predicates read)."""

    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" tolerates all effects

    def tolerates(self, taint: "Taint") -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


@dataclasses.dataclass
class Taint:
    """Node taint (v1.Taint subset; effects NoSchedule/PreferNoSchedule/NoExecute)."""

    key: str
    value: str = ""
    effect: str = "NoSchedule"


HOSTNAME_TOPOLOGY = "kubernetes.io/hostname"


def node_selector_terms_match(
    terms, labels: Mapping[str, str]
) -> bool:
    """Evaluate v1.NodeSelectorTerms against a node's labels: terms are OR'd,
    the (key, operator, values) requirements within a term are AND'd — the
    vendored MatchNodeSelector semantics (predicates.go:194-205). Shared by
    the host predicate (plugins/predicates.py) and the PV ledger's node
    reachability check (cache/volume.py).

    Operators: In / NotIn / Exists / DoesNotExist / Gt / Lt. An operator
    outside that set fails its requirement (fail closed) — the reference's
    selector constructor errors on unknown operators rather than matching."""

    def _req_ok(key: str, op: str, values) -> bool:
        present = key in labels
        val = labels.get(key)
        if op == "In":
            return val in values
        if op == "NotIn":
            return val not in values
        if op == "Exists":
            return present
        if op == "DoesNotExist":
            return not present
        if op in ("Gt", "Lt"):
            if not present or not values:
                return False
            try:
                lv, rv = int(val), int(values[0])
            except (TypeError, ValueError):
                return False
            return lv > rv if op == "Gt" else lv < rv
        return False

    return any(
        all(_req_ok(key, op, values) for key, op, values in term)
        for term in terms
    )


@dataclasses.dataclass
class PodAffinityTerm:
    """Required inter-pod (anti-)affinity term (the
    InterPodAffinityMatches predicate's input, predicates.go:278-296):
    match_labels select existing pods; topology_key partitions nodes into
    domains (hostname ⇒ per-node; any other key ⇒ nodes sharing that node
    label's value)."""

    match_labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    topology_key: str = HOSTNAME_TOPOLOGY

    def matches(self, labels: Mapping[str, str]) -> bool:
        return all(labels.get(k) == v for k, v in self.match_labels.items())


@dataclasses.dataclass
class Affinity:
    """Required-node-affinity as match-expression terms, plus required
    inter-pod affinity/anti-affinity.

    Each node term is a list of (key, operator, values) requirements; terms
    are OR'd, requirements within a term are AND'd — the same shape as
    v1.NodeSelectorTerms consumed by the vendored MatchNodeSelector predicate
    (predicates.go:194-205).
    """

    node_terms: List[List[Tuple[str, str, Tuple[str, ...]]]] = dataclasses.field(
        default_factory=list
    )
    pod_affinity: List[PodAffinityTerm] = dataclasses.field(default_factory=list)
    pod_anti_affinity: List[PodAffinityTerm] = dataclasses.field(default_factory=list)
    # preferred (soft) terms, each (weight, term) — the Priority-function
    # inputs (CalculateNodeAffinityPriorityMap / InterPodAffinityPriority,
    # nodeorder.go:188-247): matching terms add weight to the node's score
    preferred_node_terms: List[
        Tuple[float, List[Tuple[str, str, Tuple[str, ...]]]]
    ] = dataclasses.field(default_factory=list)
    preferred_pod_affinity: List[Tuple[float, PodAffinityTerm]] = dataclasses.field(
        default_factory=list
    )
    preferred_pod_anti_affinity: List[Tuple[float, PodAffinityTerm]] = (
        dataclasses.field(default_factory=list)
    )

    def has_preferences(self) -> bool:
        return bool(
            self.preferred_node_terms
            or self.preferred_pod_affinity
            or self.preferred_pod_anti_affinity
        )


@dataclasses.dataclass
class PodDisruptionBudget:
    """The legacy gang source (event_handlers.go:484-594): a PDB owned by
    the same controller as a set of pods turns that owner's job into a gang
    of min_available, always in the default queue. Jobs defined only by a
    PDB get events-only status updates (job_updater.go:108-111)."""

    name: str
    namespace: str = "default"
    min_available: int = 1
    # controller/owner UID linking the PDB to its pods' job
    owner: Optional[str] = None
    creation_index: int = 0


@dataclasses.dataclass
class PersistentVolume:
    """Standalone PersistentVolume analog (the reference wraps the k8s
    volumebinder over PV/PVC/StorageClass informers, cache.go:189-209). A
    named volume, optionally reachable from a single node only (local PV),
    optionally pre-bound to a claim (static provisioning)."""

    name: str
    node: Optional[str] = None   # None = accessible from every node
    claim: Optional[str] = None  # pre-bound PVC name; None = matches any claim
    # k8s mode: PVs bind only claims of the same storage class; standalone
    # ingest leaves it empty (matches empty-class claims)
    storage_class: str = ""
    # full spec.nodeAffinity.required nodeSelectorTerms (same (key, op,
    # values) shape as Affinity.node_terms): carried whenever the PV has
    # required affinity, so the ledger can evaluate zonal/regional topology
    # against candidate node labels instead of failing closed on anything
    # beyond a single-node pin (`node` stays the recognized-pin fast path)
    node_terms: Tuple = ()


@dataclasses.dataclass
class PersistentVolumeClaim:
    """The claim side of the PV ledger in --master mode: carries the durable
    PVC→PV binding (spec.volumeName) and the storage class that decides
    whether an unbound claim is dynamically provisionable
    (cache.go:258-269 feeds the k8s volumebinder from the pvc informer)."""

    name: str
    namespace: str = "default"
    volume_name: Optional[str] = None  # spec.volumeName — bound PV
    storage_class: str = ""
    phase: str = "Pending"

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclasses.dataclass
class Pod:
    """The scheduler-visible slice of a pod spec + status."""

    name: str
    namespace: str = "default"
    uid: str = ""
    # resource requests: sum over app containers; init-containers folded into
    # InitResreq by TaskInfo (pod_info.go:53-73)
    requests: Dict[str, float] = dataclasses.field(default_factory=dict)
    init_requests: Dict[str, float] = dataclasses.field(default_factory=dict)
    node_name: Optional[str] = None
    phase: PodPhase = PodPhase.PENDING
    deleting: bool = False  # DeletionTimestamp set
    priority: int = 0
    priority_class: str = ""
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    node_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    tolerations: List[Toleration] = dataclasses.field(default_factory=list)
    affinity: Optional[Affinity] = None
    host_ports: Tuple[int, ...] = ()
    scheduler_name: str = "volcano"
    creation_index: int = 0  # monotone stand-in for CreationTimestamp
    # names of PersistentVolumeClaims the pod mounts (the standalone analog
    # of pod.spec.volumes[*].persistentVolumeClaim.claimName); resolved
    # against the PV ledger at allocate time (cache.go:189-209)
    volume_claims: Tuple[str, ...] = ()
    # controller/owner UID (metav1.GetControllerOf analog): pods sharing an
    # owner share a job when no group-name annotation is set
    # (cache/util.go:42-46, apis/utils/utils.go:25-37)
    owner: Optional[str] = None

    def __post_init__(self):
        if not self.uid:
            self.uid = _auto_uid(f"pod-{self.namespace}-{self.name}")

    @property
    def group_name(self) -> Optional[str]:
        return self.annotations.get(GROUP_NAME_ANNOTATION)

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclasses.dataclass
class PodGroup:
    """PodGroup CRD (apis/scheduling/v1alpha1/types.go:93-171)."""

    name: str
    namespace: str = "default"
    uid: str = ""
    min_member: int = 1
    queue: str = ""
    priority_class: str = ""
    min_resources: Optional[Dict[str, float]] = None
    # None = zero-value phase: a PodGroup created without status passes the
    # allocate action's Pending-phase gate (allocate.go:50-52 only skips an
    # explicit PodGroupPending; the enqueue action only promotes explicit
    # Pending to Inqueue, enqueue.go:66,115)
    phase: Optional[PodGroupPhase] = None
    conditions: List["PodGroupCondition"] = dataclasses.field(default_factory=list)
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    creation_index: int = 0
    shadow: bool = False  # synthesized for a plain pod (cache/util.go:42-60)

    def __post_init__(self):
        if not self.uid:
            self.uid = _auto_uid(f"pg-{self.namespace}-{self.name}")

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def clone(self) -> "PodGroup":
        # copy.copy + manual deep bits: dataclasses.replace re-runs field
        # resolution and __post_init__ (~10x slower; hot in cache.snapshot)
        pg = copy.copy(self)
        pg.conditions = [copy.copy(c) for c in self.conditions]
        pg.min_resources = dict(self.min_resources) if self.min_resources else None
        return pg


@dataclasses.dataclass
class PodGroupCondition:
    """(types.go:55-73)"""

    type: str
    status: str = "True"
    transition_id: str = ""
    reason: str = ""
    message: str = ""


@dataclasses.dataclass
class Queue:
    """Queue CRD (types.go:178-223): weighted share + optional capability cap."""

    name: str
    uid: str = ""
    weight: int = 1
    capability: Optional[Dict[str, float]] = None

    def __post_init__(self):
        if not self.uid:
            self.uid = _auto_uid(f"queue-{self.name}")


@dataclasses.dataclass
class Node:
    """The scheduler-visible slice of a v1.Node."""

    name: str
    allocatable: Dict[str, float] = dataclasses.field(default_factory=dict)
    capacity: Dict[str, float] = dataclasses.field(default_factory=dict)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    taints: List[Taint] = dataclasses.field(default_factory=list)
    ready: bool = True
    unschedulable: bool = False
    conditions: Dict[str, bool] = dataclasses.field(default_factory=dict)
    # conditions: e.g. {"MemoryPressure": True}; consumed by the optional
    # pressure predicates (predicates.go:233-276)

    def __post_init__(self):
        if not self.capacity:
            self.capacity = dict(self.allocatable)


@dataclasses.dataclass
class PriorityClass:
    name: str
    value: int
    global_default: bool = False
