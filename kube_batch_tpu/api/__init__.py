from kube_batch_tpu.api.resources import Resource, ResourceSpec, DEFAULT_SPEC
from kube_batch_tpu.api.types import (
    TaskStatus,
    ALLOCATED_STATUSES,
    PodGroupPhase,
    PodGroupConditionType,
    pod_phase_to_status,
)
from kube_batch_tpu.api.pod import Pod, PodGroup, Queue, Toleration, Taint, GROUP_NAME_ANNOTATION
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.api.job_info import JobInfo, FitError, FitErrors
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.queue_info import QueueInfo
from kube_batch_tpu.api.cluster_info import ClusterInfo

__all__ = [
    "Resource",
    "ResourceSpec",
    "DEFAULT_SPEC",
    "TaskStatus",
    "ALLOCATED_STATUSES",
    "PodGroupPhase",
    "PodGroupConditionType",
    "pod_phase_to_status",
    "Pod",
    "PodGroup",
    "Queue",
    "Toleration",
    "Taint",
    "GROUP_NAME_ANNOTATION",
    "TaskInfo",
    "JobInfo",
    "FitError",
    "FitErrors",
    "NodeInfo",
    "QueueInfo",
    "ClusterInfo",
]
