"""Status enums and phase mappings.

Mirrors pkg/scheduler/api/types.go:24-58 (TaskStatus), helpers.go:35-71
(pod-phase mapping and AllocatedStatus), and
pkg/apis/scheduling/v1alpha1/types.go:28-73 (PodGroup phases/conditions).

TaskStatus values are stable small ints on purpose: they are embedded directly
into the device snapshot's ``task_status`` int8 array, and the assignment
kernel's status algebra (ops/assignment.py) branches on them numerically.
"""

from __future__ import annotations

import enum


class TaskStatus(enum.IntEnum):
    """Task lifecycle states (types.go:24-58)."""

    PENDING = 0      # not scheduled
    ALLOCATED = 1    # resources assigned this session, not yet dispatched
    PIPELINED = 2    # assigned onto resources that are still being released
    BINDING = 3      # bind RPC in flight
    BOUND = 4        # bind acknowledged
    RUNNING = 5
    RELEASING = 6    # eviction/deletion in flight
    SUCCEEDED = 7
    FAILED = 8
    UNKNOWN = 9


# Statuses that occupy real (not future) node resources, helpers.go:63-71.
ALLOCATED_STATUSES = frozenset(
    {TaskStatus.BOUND, TaskStatus.BINDING, TaskStatus.RUNNING, TaskStatus.ALLOCATED}
)


def is_allocated(status: TaskStatus) -> bool:
    return status in ALLOCATED_STATUSES


class PodPhase(str, enum.Enum):
    """The subset of pod phases the cache consumes (helpers.go:35-61)."""

    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


def pod_phase_to_status(phase: "PodPhase", node_name: str | None, deleting: bool = False) -> TaskStatus:
    """Map an ingested pod's phase+nodeName+DeletionTimestamp to a TaskStatus
    (helpers.go:35-61 getTaskStatus): the deletion override applies only to
    Running and Pending pods; Succeeded/Failed keep their terminal status."""
    if phase == PodPhase.RUNNING:
        return TaskStatus.RELEASING if deleting else TaskStatus.RUNNING
    if phase == PodPhase.PENDING:
        if deleting:
            return TaskStatus.RELEASING
        return TaskStatus.BOUND if node_name else TaskStatus.PENDING
    if phase == PodPhase.SUCCEEDED:
        return TaskStatus.SUCCEEDED
    if phase == PodPhase.FAILED:
        return TaskStatus.FAILED
    return TaskStatus.UNKNOWN


# conformance's critical-pod rule (conformance.go:42-59) — shared by the
# host plugin and the device snapshot's task_critical bit
CRITICAL_PRIORITY_CLASSES = ("system-cluster-critical", "system-node-critical")
CRITICAL_NAMESPACE = "kube-system"


class PodGroupPhase(str, enum.Enum):
    """PodGroup lifecycle (apis/scheduling/v1alpha1/types.go:28-43)."""

    PENDING = "Pending"
    RUNNING = "Running"
    UNKNOWN = "Unknown"
    INQUEUE = "Inqueue"


def queue_phase_counts() -> dict:
    """A zeroed QueueStatus phase-count dict (types.go:195-204), keys
    derived from the enum — the single source for the close-pass
    accumulators, the writeback's zero record, and the admin API."""
    return {p.value.lower(): 0 for p in PodGroupPhase}


class PodGroupConditionType(str, enum.Enum):
    """(types.go:45-52)"""

    UNSCHEDULABLE = "Unschedulable"


# Canonical unschedulable-event reasons (unschedule_info.go:11-19).
NODE_POD_NUMBER_EXCEEDED = "node(s) pod number exceeded"
NODE_RESOURCE_FIT_FAILED = "node(s) resource fit failed"
ALL_NODES_UNAVAILABLE = "all nodes are unavailable"
