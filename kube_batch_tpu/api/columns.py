"""ColumnStore — the persistent columnar host model.

Round-2 verdict: rebuilding 50k-row SoA arrays from Python TaskInfo objects
every cycle (and re-materializing per-job/per-node bookkeeping on replay) was
the reference's deep-clone cost (cache.go:584-654) reborn in Python — ~940 ms
of host work per cycle around a ~310 ms device solve.  This module makes the
host model itself columnar and persistent:

- The cache owns one ColumnStore.  Rows are assigned when objects are
  ingested (pods → task rows, jobs → job rows, nodes/queues likewise) and
  freed when they leave; row indices are stable for an object's lifetime.
- The object model's *ledgers* (JobInfo.allocated/total/pending_request,
  NodeInfo.idle/used/releasing/allocatable/capability) become views into
  [cap, R] float64 matrices: every in-place `add_`/`sub_` through the object
  API writes the column, and every vectorized column op is seen by the
  objects.  Single source of truth, no double bookkeeping.
- Per-job *status counts* ([capJ, n_statuses] int32) are maintained by
  JobInfo's index choke points, so gang readiness / job phase derivation /
  session-open validity become one matrix expression instead of 12.5k
  Python property chains.
- TaskInfo.status / .node_name become properties whose setters mirror into
  the t_status / t_node columns — every status flip anywhere in the tree
  (statements, replay, residue revert, ingest) keeps the columns current.

The per-cycle device snapshot then degenerates to: a cheap job-metadata scan,
a handful of [cap, R] casts, and derived masks — O(columns), not O(objects).
Capacities grow in the same shape buckets the device snapshot pads to
(snapshot.bucket), so the row space IS the padded device axis and the solve's
assignment vector indexes rows directly.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Set

import numpy as np

from kube_batch_tpu.api.resources import Resource, ResourceSpec
from kube_batch_tpu.api.snapshot import (
    BITS,
    HARD_TAINT_EFFECTS,
    UNBOUNDED,
    DeviceSnapshot,
    SnapshotMeta,
    _pack_bits,
    _TaintView,
    bucket,
)
from kube_batch_tpu.api.types import (
    CRITICAL_NAMESPACE,
    CRITICAL_PRIORITY_CLASSES,
    PodGroupPhase,
    TaskStatus,
)

logger = logging.getLogger("kube_batch_tpu")

N_STATUS = len(TaskStatus)
# columns summed for gang readiness (job_info.go:367-380 ReadyTaskNum)
READY_STATUSES = (
    int(TaskStatus.BOUND), int(TaskStatus.BINDING), int(TaskStatus.RUNNING),
    int(TaskStatus.ALLOCATED), int(TaskStatus.SUCCEEDED),
)
# ValidTaskNum statuses (job_info.go:394-409)
VALID_STATUSES = READY_STATUSES + (
    int(TaskStatus.PENDING), int(TaskStatus.PIPELINED),
)

# PodGroup phase ↔ int code for the j_phase column (−1 = no phase yet)
PHASE_CODE: Dict[PodGroupPhase, int] = {
    p: i for i, p in enumerate(PodGroupPhase)
}
CODE_PHASE: List[PodGroupPhase] = list(PodGroupPhase)
N_PHASES = len(CODE_PHASE)


def resident_snap(cols, snap, mesh=None):
    """The call-site shape for the device-resident snapshot cache: swap in
    cached device arrays when a ColumnStore backs the session, pass the
    snapshot through untouched otherwise.  Static ingest features ride the
    version-keyed cache (resident_features); the per-cycle columns ride the
    scatter-delta cache (api/resident.py) — single-device scatters when
    `mesh` is None, per-shard NamedSharding-placed scatters on the
    mesh-sharded solve path."""
    if cols is None:
        return snap
    snap = cols.resident_features(snap, mesh=mesh)
    return cols.per_cycle_resident(snap, mesh=mesh)


def _grow(arr: np.ndarray, cap: int) -> np.ndarray:
    new = np.zeros((cap,) + arr.shape[1:], arr.dtype)
    new[: arr.shape[0]] = arr
    return new


class _Axis:
    """Row allocator: stable rows + LIFO free list, capacities in the same
    buckets the device snapshot pads to."""

    def __init__(self, floor: int = 8):
        self.cap = bucket(0, floor)
        self.n_live = 0
        self._free: List[int] = list(range(self.cap - 1, -1, -1))

    def alloc(self) -> Optional[int]:
        """Next free row, or None when the axis must grow first."""
        if not self._free:
            return None
        self.n_live += 1
        return self._free.pop()

    def grown_cap(self) -> int:
        return bucket(self.cap + 1)

    def on_grown(self, new_cap: int) -> None:
        self._free.extend(range(new_cap - 1, self.cap - 1, -1))
        self.cap = new_cap

    def free(self, row: int) -> None:
        self.n_live -= 1
        self._free.append(row)

    def peek(self, k: int) -> List[int]:
        """The rows the next k ``alloc()`` calls would hand out, WITHOUT
        mutating the allocator — the query plane's tie-hash oracle: a gang
        submitted against a frozen cache lands exactly on these rows
        (alloc pops the free list LIFO; growth extends it so grown rows
        hand out ascending from the old capacity)."""
        out: List[int] = []
        i = len(self._free) - 1
        grown = self.cap
        for _ in range(k):
            if i >= 0:
                out.append(self._free[i])
                i -= 1
            else:
                out.append(grown)
                grown += 1
        return out


class ColumnStore:
    def __init__(self, spec: ResourceSpec):
        self.spec = spec
        R = spec.n
        self.R = R

        # ---- task axis --------------------------------------------------
        self.tasks = _Axis()
        capT = self.tasks.cap
        self.t_init32 = np.zeros((capT, R), np.float32)   # InitResreq
        self.t_res32 = np.zeros((capT, R), np.float32)    # Resreq
        self.t_resreq64 = np.zeros((capT, R), np.float64)  # exact ledger rows
        self.t_job = np.zeros(capT, np.int32)
        self.t_prio = np.zeros(capT, np.int32)
        self.t_creation = np.zeros(capT, np.int32)
        self.t_status = np.zeros(capT, np.int32)
        self.t_node = np.full(capT, -1, np.int32)
        self.t_valid = np.zeros(capT, bool)
        self.t_best_effort = np.zeros(capT, bool)
        self.t_critical = np.zeros(capT, bool)
        self.t_needs_host = np.zeros(capT, bool)
        self.t_sel_bits = np.zeros((capT, 1), np.uint32)
        self.t_sel_impossible = np.zeros(capT, bool)
        self.t_tol_bits = np.zeros((capT, 1), np.uint32)
        self.task_by_row: List = [None] * capT
        # sparse feature registries: rows whose pods carry selectors /
        # tolerations / required pod-(anti)affinity / preferred terms
        self._sel_rows: Set[int] = set()
        self._tol_rows: Set[int] = set()
        self._aff_rows: Set[int] = set()
        self._pref_rows: Set[int] = set()
        self._ported_rows: Set[int] = set()  # tasks carrying hostPorts

        # ---- job axis ---------------------------------------------------
        self.jobs = _Axis()
        capJ = self.jobs.cap
        self.j_alloc = np.zeros((capJ, R), np.float64)
        self.j_total = np.zeros((capJ, R), np.float64)
        self.j_pend = np.zeros((capJ, R), np.float64)
        # persistent float32 twin of j_alloc, refreshed only at rows the
        # dirty choke points touched (JobInfo's allocated add_/sub_, the
        # columnar replay's vectorized += , row bind/free) — the device
        # snapshot reads this instead of paying a full [capJ, R] cast every
        # cycle (the node ledgers' dirty-row treatment, applied to jobs)
        self.j_alloc32 = np.zeros((capJ, R), np.float32)
        self._j_alloc_dirty = np.ones(capJ, bool)
        self.j_counts = np.zeros((capJ, N_STATUS), np.int32)
        self.job_by_row: List = [None] * capJ
        # per-cycle scratch (filled by the job scan in device_snapshot)
        self.j_min = np.zeros(capJ, np.int32)
        self.j_queue = np.zeros(capJ, np.int32)
        self.j_prio = np.zeros(capJ, np.int32)
        self.j_creation = np.zeros(capJ, np.int32)
        self.j_sess = np.zeros(capJ, bool)
        self.j_sched = np.zeros(capJ, bool)
        # PodGroup metadata rows, maintained by the same session row sync
        # (delta across cycles) — the enqueue admission gate and the delta
        # close-session status pass read these instead of walking objects
        self.j_has_pg = np.zeros(capJ, bool)
        self.j_shadow = np.zeros(capJ, bool)
        self.j_pdb = np.zeros(capJ, bool)
        self.j_phase = np.full(capJ, -1, np.int8)   # PHASE_CODE, -1 = none
        self.j_has_conds = np.zeros(capJ, bool)
        self.j_has_minres = np.zeros(capJ, bool)
        self.j_minres = np.zeros((capJ, R), np.float32)
        # rows whose close-pass inputs may have moved since the last status
        # pass: every j_counts choke point (api/job_info.py), the columnar
        # replay's vectorized count update, the session row sync, and
        # mid-cycle phase/condition writes stamp it; close_session visits
        # exactly these rows (plus the standing need-record set) and clears
        self.j_touched = np.zeros(capJ, bool)

        # ---- node axis --------------------------------------------------
        self.nodes = _Axis()
        capN = self.nodes.cap
        self.n_idle = np.zeros((capN, R), np.float64)
        self.n_rel = np.zeros((capN, R), np.float64)
        self.n_used = np.zeros((capN, R), np.float64)
        self.n_alloc = np.zeros((capN, R), np.float64)
        self.n_cap = np.zeros((capN, R), np.float64)
        # persistent float32 twins of the ledger matrices, refreshed only at
        # rows the dirty choke points touched (NodeInfo's task algebra, the
        # columnar replay, bind/free/set_node) — the device snapshot reads
        # these instead of paying four full [capN, R] casts every cycle
        self.n_idle32 = np.zeros((capN, R), np.float32)
        self.n_rel32 = np.zeros((capN, R), np.float32)
        self.n_used32 = np.zeros((capN, R), np.float32)
        self.n_alloc32 = np.zeros((capN, R), np.float32)
        self._node_ledger_dirty = np.ones(capN, bool)
        self.n_valid = np.zeros(capN, bool)   # Ready
        self.n_sched = np.zeros(capN, bool)   # not Unschedulable
        self.n_label_bits = np.zeros((capN, 1), np.uint32)
        self.n_taint_bits = np.zeros((capN, 1), np.uint32)
        self.node_by_row: List = [None] * capN
        self.node_rows: Dict[str, int] = {}   # name → row
        self.node_names: List[str] = [""] * capN

        # ---- queue axis -------------------------------------------------
        self.queues = _Axis()
        capQ = self.queues.cap
        self.q_weight = np.ones(capQ, np.float32)
        self.q_cap = np.full((capQ, R), UNBOUNDED, np.float32)
        self.q_valid = np.zeros(capQ, bool)
        self.queue_by_row: List = [None] * capQ
        self.queue_rows: Dict[str, int] = {}
        self.queue_names: List[str] = [""] * capQ

        # ---- label / taint interning (monotone tables) ------------------
        self.label_pair_bit: Dict[tuple, int] = {}
        self.taint_bit: Dict[tuple, int] = {}
        # set when the label/taint universe changed in a way that can affect
        # already-packed task bitsets (new pair/taint interned, node labels
        # changed): next device_snapshot recomputes the sparse task rows
        self._task_bits_dirty = False

        # ---- device-resident feature cache ------------------------------
        # The ingest-static snapshot columns (task requests/bits/priorities,
        # node allocatable/bits) change only at the ingest choke points that
        # bump the per-axis feature versions; resident_features() re-uploads them to the
        # device ONLY when it moved — per-cycle host→device traffic drops to
        # the genuinely per-cycle columns (statuses, node ledgers, job rows),
        # the SURVEY §7.3 one-transfer-in budget.  Disabled with
        # KB_DEVICE_CACHE=0.
        self.task_feature_version = 0
        self.node_feature_version = 0
        self._dev_cache: Dict = {}
        # per-cycle device-resident caches (api/resident.py), keyed by mesh
        # (None = the single-device scatter cache): the truly per-cycle
        # snapshot columns stay alive on device between cycles — sharded
        # NamedSharding placements on the mesh path — and are refreshed by
        # scatter deltas instead of full uploads.  A mesh CHANGE drops the
        # old mesh's cache wholesale (the reshard/mesh-change fallback: the
        # fresh cache full-uploads once, then deltas resume).
        self._per_cycle_dev: Dict = {}
        # serve/ query-plane seam: a context-manager factory the resident
        # swap runs inside (serve/lease.LeaseBroker.swap_guard) — it
        # serializes the swap's donating scatters against in-flight probe
        # dispatches and retires the published lease whose buffers the
        # donation would invalidate.  None (the default) is a no-op: the
        # write path pays nothing until a query plane attaches.
        self.resident_swap_guard = None
        # which path the most recent session row-sync took ("delta"|"full")
        # — surfaced in the bench JSON and the sim's longitudinal report
        self.last_snapshot_path = "full"
        # warm-started allocate (KB_WARM): carried candidate-table states,
        # one per (mesh, impl) dispatch slot (api/resident.WarmTableState).
        # Dropped wholesale on axis growth, resident drops, and mesh
        # changes — the table's node/task indices must never outlive the
        # coordinate system they were ranked in (ISSUE 14 satellite: a
        # reserve()-triggered re-grow must invalidate, never index-shift).
        self._warm_tables: Dict = {}

    # ==================================================================
    # task axis
    # ==================================================================
    def bind_task(self, task, job) -> None:
        """Assign a row and fill the static columns. Called by the cache
        after job.add_task; `job` must already be bound."""
        row = self.tasks.alloc()
        if row is None:
            self._grow_tasks()
            row = self.tasks.alloc()
        pod = task.pod
        self.t_init32[row] = task.init_resreq.vec
        self.t_res32[row] = task.resreq.vec
        self.t_resreq64[row] = task.resreq.vec
        self.t_job[row] = job._row
        self.t_prio[row] = task.priority
        self.t_creation[row] = pod.creation_index
        self.t_status[row] = int(task.status)
        self.t_node[row] = (
            self.node_rows.get(task.node_name, -1)
            if task.node_name is not None else -1
        )
        self.t_valid[row] = True
        self.t_best_effort[row] = task.best_effort
        self.t_critical[row] = (
            pod.priority_class in CRITICAL_PRIORITY_CLASSES
            or task.namespace == CRITICAL_NAMESPACE
        )
        self.t_needs_host[row] = task.needs_host_predicate
        # sparse features
        if pod.node_selector or pod.affinity is not None:
            self._sel_rows.add(row)
            self._fill_sel_bits(row, task)
        if pod.tolerations:
            self._tol_rows.add(row)
            self._fill_tol_bits(row, task)
        if pod.affinity is not None:
            if pod.affinity.pod_affinity or pod.affinity.pod_anti_affinity:
                self._aff_rows.add(row)
            if pod.affinity.has_preferences():
                self._pref_rows.add(row)
        if pod.host_ports:
            self._ported_rows.add(row)
        self.task_by_row[row] = task
        # bind LAST: property setters (status/node_name) skip the store
        # until both row and store are attached.  The job's status counts
        # were already incremented by job.add_task's index choke point.
        task._row = row
        task._store = self
        self.task_feature_version += 1

    def free_task(self, task) -> None:
        row = getattr(task, "_row", -1)
        if row < 0 or task._store is not self:
            return
        task._store = None
        task._row = -1
        self.t_valid[row] = False
        self.t_status[row] = 0
        self.t_node[row] = -1
        self.t_best_effort[row] = False
        if row in self._sel_rows:
            self._sel_rows.discard(row)
            self.t_sel_bits[row] = 0
            self.t_sel_impossible[row] = False
        if row in self._tol_rows:
            self._tol_rows.discard(row)
            self.t_tol_bits[row] = 0
        self._aff_rows.discard(row)
        self._pref_rows.discard(row)
        self._ported_rows.discard(row)
        self.task_by_row[row] = None
        self.tasks.free(row)
        self.task_feature_version += 1

    def _grow_tasks(self) -> None:
        cap = self.tasks.grown_cap()
        for name in ("t_init32", "t_res32", "t_resreq64", "t_job", "t_prio",
                     "t_creation", "t_status", "t_valid", "t_best_effort",
                     "t_critical", "t_needs_host", "t_sel_bits",
                     "t_sel_impossible", "t_tol_bits"):
            setattr(self, name, _grow(getattr(self, name), cap))
        tn = np.full(cap, -1, np.int32)
        tn[: self.t_node.shape[0]] = self.t_node
        self.t_node = tn
        self.task_by_row.extend([None] * (cap - self.tasks.cap))
        self.tasks.on_grown(cap)
        # a task-axis re-grow moves the bucket rung the warm allocate
        # compacts into — drop the carried candidate tables wholesale
        # rather than index-shift them (plan_topk_bucket lifetime gap)
        self.drop_warm_tables()

    def _fill_sel_bits(self, row: int, task) -> None:
        """Required label pairs → bits (the device predicate's sound
        over-approximation; see snapshot.build_snapshot for the encoding
        contract)."""
        pod = task.pod
        required_pairs = list(pod.node_selector.items()) if pod.node_selector else []
        aff = pod.affinity
        if aff is not None and len(aff.node_terms) == 1:
            required_pairs += [
                (key, values[0])
                for key, op, values in aff.node_terms[0]
                if op == "In" and len(values) == 1
            ]
        bits: List[int] = []
        impossible = False
        for kv in required_pairs:
            b = self.label_pair_bit.get(kv)
            if b is None:
                impossible = True  # no node carries this pair (yet)
            else:
                bits.append(b)
        self.t_sel_bits[row] = _pack_bits(bits, self.t_sel_bits.shape[1])
        self.t_sel_impossible[row] = impossible

    def _fill_tol_bits(self, row: int, task) -> None:
        tols = task.pod.tolerations
        bits = [
            bit
            for (tk, tv, te), bit in self.taint_bit.items()
            if any(tol.tolerates(_TaintView(tk, tv, te)) for tol in tols)
        ]
        self.t_tol_bits[row] = _pack_bits(bits, self.t_tol_bits.shape[1])

    def adopt_task_row(self, old, new) -> None:
        """Transfer a row binding when a clone replaces the resident task
        object under the same key (update_task_status with a session copy).
        Static columns stay valid — the clone shares the pod and the resreq
        Resources; the mutable columns re-sync from the adopter."""
        row = old._row
        old._store = None
        old._row = -1
        new._row = row
        new._store = self
        self.task_by_row[row] = new
        self.t_status[row] = int(new._status)
        self.task_node_changed(row, new._node_name)

    # called by TaskInfo property setters ------------------------------
    def task_status_changed(self, row: int, status: int) -> None:
        self.t_status[row] = status

    def task_node_changed(self, row: int, node_name) -> None:
        self.t_node[row] = (
            self.node_rows.get(node_name, -1) if node_name is not None else -1
        )

    # ==================================================================
    # job axis
    # ==================================================================
    def bind_job(self, job) -> None:
        row = self.jobs.alloc()
        if row is None:
            self._grow_jobs()
            row = self.jobs.alloc()
        # copy current ledgers into the rows, then rebind the job's Resource
        # objects as views (contiguous f64 rows — the .vec setter keeps them
        # zero-copy)
        self.j_alloc[row] = job.allocated.vec
        self._j_alloc_dirty[row] = True
        self.j_total[row] = job.total_request.vec
        self.j_pend[row] = job.pending_request.vec
        job.allocated.vec = self.j_alloc[row]
        job.total_request.vec = self.j_total[row]
        job.pending_request.vec = self.j_pend[row]
        counts = self.j_counts[row]
        counts[:] = 0
        for status, bucket_ in job.task_status_index.items():
            counts[int(status)] = len(bucket_)
        self.job_by_row[row] = job
        job._row = row
        job._cols = self
        self.j_touched[row] = True

    def free_job(self, job) -> None:
        row = getattr(job, "_row", -1)
        if row < 0 or job._cols is not self:
            return
        job._cols = None
        job._row = -1
        # session-row state must not leak onto the row's next tenant (the
        # delta row-sync only rewrites rows of dirty jobs)
        self.j_sess[row] = False
        self.j_sched[row] = False
        self.j_has_pg[row] = False
        self.j_shadow[row] = False
        self.j_pdb[row] = False
        self.j_phase[row] = -1
        self.j_has_conds[row] = False
        self.j_has_minres[row] = False
        self.j_minres[row] = 0.0
        self.j_touched[row] = True
        # give the job back private buffers (copies of its final state)
        job.allocated.vec = self.j_alloc[row].copy()
        job.total_request.vec = self.j_total[row].copy()
        job.pending_request.vec = self.j_pend[row].copy()
        self.j_alloc[row] = 0.0
        self._j_alloc_dirty[row] = True
        self.j_total[row] = 0.0
        self.j_pend[row] = 0.0
        self.j_counts[row] = 0
        self.job_by_row[row] = None
        self.jobs.free(row)

    def _grow_jobs(self) -> None:
        cap = self.jobs.grown_cap()
        for name in ("j_alloc", "j_alloc32", "j_total", "j_pend", "j_counts",
                     "j_min",
                     "j_queue", "j_prio", "j_creation", "j_sess", "j_sched",
                     "j_has_pg", "j_shadow", "j_pdb",
                     "j_has_conds", "j_has_minres", "j_minres", "j_touched"):
            setattr(self, name, _grow(getattr(self, name), cap))
        dirty = np.ones(cap, bool)  # grown rows refresh on first read
        dirty[: self._j_alloc_dirty.shape[0]] = self._j_alloc_dirty
        self._j_alloc_dirty = dirty
        j_phase = np.full(cap, -1, np.int8)
        j_phase[: self.j_phase.shape[0]] = self.j_phase
        self.j_phase = j_phase
        self.job_by_row.extend([None] * (cap - self.jobs.cap))
        self.jobs.on_grown(cap)
        # rebind every bound job's ledger views onto the new buffers
        for row, job in enumerate(self.job_by_row):
            if job is not None:
                job.allocated.vec = self.j_alloc[row]
                job.total_request.vec = self.j_total[row]
                job.pending_request.vec = self.j_pend[row]

    # ==================================================================
    # node axis
    # ==================================================================
    def bind_node(self, node) -> None:
        row = self.nodes.alloc()
        if row is None:
            self._grow_nodes()
            row = self.nodes.alloc()
        self.node_by_row[row] = node
        self.node_rows[node.name] = row
        self.node_names[row] = node.name
        node._row = row
        node._cols = self
        self.n_idle[row] = node.idle.vec
        self.n_rel[row] = node.releasing.vec
        self.n_used[row] = node.used.vec
        self.n_alloc[row] = node.allocatable.vec
        self.n_cap[row] = node.capability.vec
        node.idle.vec = self.n_idle[row]
        node.releasing.vec = self.n_rel[row]
        node.used.vec = self.n_used[row]
        node.allocatable.vec = self.n_alloc[row]
        node.capability.vec = self.n_cap[row]
        self.node_feature_version += 1  # fresh n_alloc / bit rows on this row
        self._node_ledger_dirty[row] = True
        self.sync_node_meta(node)
        # resident tasks bound before their node rows resolve to -1;
        # repoint them now that the name has a row
        for t in node.tasks.values():
            if getattr(t, "_row", -1) >= 0 and t._store is self:
                self.t_node[t._row] = row

    def free_node(self, node) -> None:
        row = getattr(node, "_row", -1)
        if row < 0 or node._cols is not self:
            return
        node._cols = None
        node._row = -1
        node.idle.vec = self.n_idle[row].copy()
        node.releasing.vec = self.n_rel[row].copy()
        node.used.vec = self.n_used[row].copy()
        node.allocatable.vec = self.n_alloc[row].copy()
        node.capability.vec = self.n_cap[row].copy()
        for arr in (self.n_idle, self.n_rel, self.n_used, self.n_alloc, self.n_cap):
            arr[row] = 0.0
        self._node_ledger_dirty[row] = True
        self.n_valid[row] = False
        self.n_sched[row] = False
        self.n_label_bits[row] = 0
        self.n_taint_bits[row] = 0
        self.node_by_row[row] = None
        self.node_rows.pop(node.name, None)
        self.node_names[row] = ""
        # tasks still referencing the freed row (bound pods of a deleted
        # node) must not alias whatever node reuses it
        self.t_node[self.t_node == row] = -1
        self.nodes.free(row)
        self.node_feature_version += 1

    def _grow_nodes(self) -> None:
        cap = self.nodes.grown_cap()
        for name in ("n_idle", "n_rel", "n_used", "n_alloc", "n_cap",
                     "n_valid", "n_sched", "n_label_bits", "n_taint_bits",
                     "n_idle32", "n_rel32", "n_used32", "n_alloc32"):
            setattr(self, name, _grow(getattr(self, name), cap))
        dirty = np.ones(cap, bool)
        dirty[: self._node_ledger_dirty.shape[0]] = self._node_ledger_dirty
        self._node_ledger_dirty = dirty
        self.node_by_row.extend([None] * (cap - self.nodes.cap))
        self.node_names.extend([""] * (cap - self.nodes.cap))
        self.nodes.on_grown(cap)
        # node-axis growth changes the node-index space the carried
        # candidate tables rank over — wholesale drop, never index-shift
        self.drop_warm_tables()
        for row, node in enumerate(self.node_by_row):
            if node is not None:
                node.idle.vec = self.n_idle[row]
                node.releasing.vec = self.n_rel[row]
                node.used.vec = self.n_used[row]
                node.allocatable.vec = self.n_alloc[row]
                node.capability.vec = self.n_cap[row]

    def sync_node_meta(self, node) -> None:
        """Refresh validity/schedulability/label/taint bits after set_node
        (or bind). Interns new label pairs / taints; growth of the universe
        marks task bitsets dirty for recompute at next snapshot.

        the node feature version bumps only when a CACHED node column
        (label/taint bits; n_alloc via set_node's own change check) actually
        changed —
        kubelet heartbeats with unchanged content must not flush the
        device-resident cache every cycle."""
        row = node._row
        self.n_valid[row] = node.ready
        obj = node.node
        self.n_sched[row] = obj is not None and not obj.unschedulable
        if obj is None:
            return
        before_labels = len(self.label_pair_bit)
        before_taints = len(self.taint_bit)
        for kv in obj.labels.items():
            self.label_pair_bit.setdefault(kv, len(self.label_pair_bit))
        for t in obj.taints:
            if t.effect in HARD_TAINT_EFFECTS:
                self.taint_bit.setdefault(
                    (t.key, t.value, t.effect), len(self.taint_bit)
                )
        W = max(1, -(-len(self.label_pair_bit) // BITS))
        Wt = max(1, -(-len(self.taint_bit) // BITS))
        if W > self.n_label_bits.shape[1]:
            self.n_label_bits = _grow_width(self.n_label_bits, W)
            self.t_sel_bits = _grow_width(self.t_sel_bits, W)
        if Wt > self.n_taint_bits.shape[1]:
            self.n_taint_bits = _grow_width(self.n_taint_bits, Wt)
            self.t_tol_bits = _grow_width(self.t_tol_bits, Wt)
        if len(self.label_pair_bit) != before_labels or len(self.taint_bit) != before_taints:
            self._task_bits_dirty = True
        label_row = _pack_bits(
            [self.label_pair_bit[kv] for kv in obj.labels.items()],
            self.n_label_bits.shape[1],
        )
        taint_row = _pack_bits(
            [
                self.taint_bit[(t.key, t.value, t.effect)]
                for t in obj.taints
                if t.effect in HARD_TAINT_EFFECTS
            ],
            self.n_taint_bits.shape[1],
        )
        if not (
            np.array_equal(self.n_label_bits[row], label_row)
            and np.array_equal(self.n_taint_bits[row], taint_row)
        ):
            self.node_feature_version += 1
        self.n_label_bits[row] = label_row
        self.n_taint_bits[row] = taint_row

    # ==================================================================
    # queue axis
    # ==================================================================
    def bind_queue(self, qinfo) -> None:
        existing = self.queue_rows.get(qinfo.name)
        if existing is not None:
            row = existing
            old = self.queue_by_row[row]
            if old is not None and old is not qinfo:
                old._row, old._cols = -1, None
        else:
            row = self.queues.alloc()
            if row is None:
                self._grow_queues()
                row = self.queues.alloc()
            self.queue_rows[qinfo.name] = row
            self.queue_names[row] = qinfo.name
        self.queue_by_row[row] = qinfo
        qinfo._row = row
        qinfo._cols = self
        self.q_weight[row] = qinfo.weight
        self.q_valid[row] = True
        if qinfo.queue.capability:
            # dims a capability dict does not name are capped at 0 — the
            # JobEnqueueable closure's exact encoding (plugins/proportion.py,
            # mirrored by build_snapshot), consumed by the probe's admission
            # veto; only a cap-less queue is UNBOUNDED
            cap = np.zeros(self.R, np.float32)
            for name, v in qinfo.queue.capability.items():
                if name in self.spec:
                    cap[self.spec.index(name)] = v
        else:
            cap = np.full(self.R, UNBOUNDED, np.float32)
        self.q_cap[row] = cap

    def free_queue(self, name: str) -> None:
        row = self.queue_rows.pop(name, None)
        if row is None:
            return
        q = self.queue_by_row[row]
        if q is not None:
            q._row, q._cols = -1, None
        self.queue_by_row[row] = None
        self.q_valid[row] = False
        self.q_weight[row] = 1.0
        self.q_cap[row] = UNBOUNDED
        self.queue_names[row] = ""
        self.queues.free(row)

    def _grow_queues(self) -> None:
        cap = self.queues.grown_cap()
        q_weight = np.ones(cap, np.float32)
        q_weight[: self.queues.cap] = self.q_weight
        self.q_weight = q_weight
        q_cap = np.full((cap, self.R), UNBOUNDED, np.float32)
        q_cap[: self.queues.cap] = self.q_cap
        self.q_cap = q_cap
        self.q_valid = _grow(self.q_valid, cap)
        self.queue_by_row.extend([None] * (cap - self.queues.cap))
        self.queue_names.extend([""] * (cap - self.queues.cap))
        self.queues.on_grown(cap)

    # ==================================================================
    # capacity reservation
    # ==================================================================
    def reserve(self, n_tasks: int = 0, n_nodes: int = 0, n_jobs: int = 0,
                n_queues: int = 0) -> None:
        """Pre-grow axes to cover an expected peak so steady-state count
        wobble stays inside one shape bucket — the jit cache then hits every
        cycle (zero retraces after warmup).  Axis capacity never shrinks, so
        this is a one-way warmup knob."""
        while self.tasks.cap < n_tasks:
            self._grow_tasks()
        while self.nodes.cap < n_nodes:
            self._grow_nodes()
        while self.jobs.cap < n_jobs:
            self._grow_jobs()
        while self.queues.cap < n_queues:
            self._grow_queues()

    # ==================================================================
    # per-session job-row sync (delta or full)
    # ==================================================================
    def _sync_job_row(self, job, queue_rows_get) -> None:
        """Derive one session job's row state (shared by both sync paths —
        the delta path is bit-exact because it IS this same derivation)."""
        row = job._row
        if row < 0 or job._cols is not self:
            return  # foreign/unbound job (isolated-session object)
        self.j_touched[row] = True  # re-synced ⇒ the close pass must visit
        qi = queue_rows_get(job.queue, -1)
        if qi < 0:
            self.j_sess[row] = False
            return
        self.j_sess[row] = True
        self.j_min[row] = job.min_available
        self.j_queue[row] = qi
        self.j_prio[row] = job.priority
        self.j_creation[row] = job.creation_index
        pg = job.pod_group
        self.j_sched[row] = pg is None or pg.phase != PodGroupPhase.PENDING
        # PodGroup metadata for the enqueue gate + delta close status pass
        self.j_has_pg[row] = pg is not None
        self.j_pdb[row] = job.pdb is not None
        if pg is None:
            self.j_shadow[row] = False
            self.j_phase[row] = -1
            self.j_has_conds[row] = False
            self.j_has_minres[row] = False
            self.j_minres[row] = 0.0
            return
        self.j_shadow[row] = pg.shadow
        self.j_phase[row] = (
            PHASE_CODE[pg.phase] if pg.phase is not None else -1
        )
        self.j_has_conds[row] = bool(pg.conditions)
        mr = pg.min_resources
        # `is None`, NOT truthiness: an EMPTY min_resources dict takes the
        # walk's budgeted branch (zero request — always fits, but still
        # subject to JobEnqueueable), only a missing one promotes
        # unconditionally (enqueue.go:102-105)
        if mr is not None:
            self.j_has_minres[row] = True
            vec = np.zeros(self.R, np.float32)
            spec = self.spec
            for name, v in mr.items():
                if name in spec:
                    vec[spec.index(name)] = float(v)
            self.j_minres[row] = vec
        else:
            self.j_has_minres[row] = False
            self.j_minres[row] = 0.0

    def sync_session_rows(self, ssn, dirty_uids=None, restore_rows=()) -> None:
        """Fill the session-scoped job-row arrays (j_sess membership, j_min,
        j_queue, j_prio, j_creation, j_sched) for an exclusive session.

        ``dirty_uids=None`` is the full rescan (one Python pass over every
        session job — the previous per-cycle cost).  A set re-derives ONLY
        those uids against the live objects: rows of jobs that left the
        session clear, dirty members re-fill, everything else keeps last
        cycle's values — which are still exact because every input
        (membership, min_available, queue row, priority, creation, phase)
        moves only through choke points that stamp the dirty set.
        ``restore_rows`` re-admits rows the previous gate dropped; this
        cycle's gate re-votes on them immediately after."""
        queue_rows_get = self.queue_rows.get
        if dirty_uids is None:
            self.last_snapshot_path = "full"
            self.j_sess[:] = False
            self.j_sched[:] = False
            for job in ssn.jobs.values():
                self._sync_job_row(job, queue_rows_get)
            return
        self.last_snapshot_path = "delta"
        jobs_get = ssn.jobs.get
        job_by_row = self.job_by_row
        for row in restore_rows:
            job = job_by_row[row]
            if job is not None and jobs_get(job.uid) is job:
                self.j_sess[row] = True
        cache_jobs_get = ssn.cache.jobs.get
        for uid in dirty_uids:
            job = jobs_get(uid)
            if job is None:
                # left the session (deleted, or membership lost): clear the
                # row it may still hold on the authoritative cache object
                job = cache_jobs_get(uid)
                if job is not None and job._cols is self and job._row >= 0:
                    self.j_sess[job._row] = False
                continue
            self._sync_job_row(job, queue_rows_get)

    # ==================================================================
    # per-cycle device snapshot
    # ==================================================================
    def schedulable_pending_mask(self) -> np.ndarray:
        """[capT] bool — tasks the allocate/evict solves can act on (Pending,
        not BestEffort, live row). The single definition behind both the
        device snapshot's task_pending and the actions' idle-cycle skip —
        the skip is sound precisely because it is this same mask."""
        return (
            (self.t_status == int(TaskStatus.PENDING))
            & ~self.t_best_effort
            & self.t_valid
        )

    def has_schedulable_pending(self) -> bool:
        return bool(np.any(self.schedulable_pending_mask()))

    def peek_task_rows(self, k: int) -> List[int]:
        """The task rows the next k ingested pods would occupy (no
        mutation) — the what-if probe's tie-hash oracle (ops/probe.py):
        score ties in the solve break on a per-(task-row, node) hash, so a
        probe that answers for rows the gang will NOT get could report a
        different max-score node than the committed solve picks.  Exact
        against a frozen cache; concurrent ingest shifts the allocator and
        the probe's answer degrades to any-of-the-tied-nodes (the verdict
        and score are row-independent)."""
        return self.tasks.peek(k)

    def excluded_node_rows(self, ssn) -> List[int]:
        """Row indices of the session's excluded nodes (pressure gates) —
        the single fold every columnar placement path uses, so a new path
        can't silently miss the exclusion."""
        if not ssn.session_excluded_nodes:
            return []
        rows_get = self.node_rows.get
        return [
            r for r in (rows_get(n) for n in ssn.session_excluded_nodes)
            if r is not None
        ]

    def has_running_victims(self) -> bool:
        """True when any live task is RUNNING on a node — the necessary
        condition for the evict solve to produce a claim (victims must be
        running, ops/eviction.py's `running` mask)."""
        return bool(np.any(
            (self.t_status == int(TaskStatus.RUNNING))
            & self.t_valid
            & (self.t_node >= 0)
        ))

    def refresh_task_bits(self) -> None:
        """Recompute sparse task bitsets after the label/taint universe
        changed (new pair can un-impossible a selector; new taint needs a
        toleration verdict). Only the sparse rows pay."""
        if not self._task_bits_dirty:
            return
        self._task_bits_dirty = False
        self.task_feature_version += 1
        for row in self._sel_rows:
            self._fill_sel_bits(row, self.task_by_row[row])
        for row in self._tol_rows:
            self._fill_tol_bits(row, self.task_by_row[row])

    # snapshot field → (backing column, version axis): per-axis versions
    # keep pod churn (every successful bind produces a pod update) from
    # flushing the node columns and vice versa
    FEATURE_FIELDS = {
        "task_req": ("t_init32", "task"),
        "task_resreq": ("t_res32", "task"),
        "task_job": ("t_job", "task"),
        "task_prio": ("t_prio", "task"),
        "task_creation": ("t_creation", "task"),
        "task_best_effort": ("t_best_effort", "task"),
        "task_critical": ("t_critical", "task"),
        "task_needs_host": ("t_needs_host", "task"),
        "task_sel_bits": ("t_sel_bits", "task"),
        "task_sel_impossible": ("t_sel_impossible", "task"),
        "task_tol_bits": ("t_tol_bits", "task"),
        # n_alloc32: the dirty-row-refreshed f32 twin (node_ledgers32) — the
        # device snapshot build always refreshes it before any dispatch
        "node_alloc": ("n_alloc32", "node"),
        "node_label_bits": ("n_label_bits", "node"),
        "node_taint_bits": ("n_taint_bits", "node"),
    }

    def bump_node_features(self) -> None:
        self.node_feature_version += 1

    # ---- node-ledger dirty rows (the f32 cast choke point) -----------
    def note_node_ledger(self, row: int) -> None:
        """Mark one node row's ledgers (idle/releasing/used/allocatable)
        changed — every write path calls this (NodeInfo's task algebra and
        set_node, bind/free, the columnar replay's matrix updates), so the
        per-cycle float32 refresh pays exactly the touched rows instead of
        four full-matrix casts."""
        self._node_ledger_dirty[row] = True

    def note_node_ledger_rows(self, rows) -> None:
        self._node_ledger_dirty[rows] = True

    # ---- job-alloc dirty rows (the j_alloc f32 cast choke point) -----
    def note_job_alloc(self, row: int) -> None:
        """Mark one job row's allocated ledger changed — every write path
        calls this (JobInfo's allocated add_/sub_ via _note_alloc, the
        columnar replay's vectorized +=, bind/free/grow, the cache's
        snapshot-less resets), so the per-cycle float32 refresh pays
        exactly the touched rows instead of a full [capJ, R] cast."""
        self._j_alloc_dirty[row] = True

    def note_job_alloc_rows(self, rows) -> None:
        self._j_alloc_dirty[rows] = True

    def job_alloc32(self) -> np.ndarray:
        """The persistent float32 twin of j_alloc, refreshed at exactly the
        dirty rows (the node-ledger twin treatment applied to the job
        axis — previously a full-matrix astype every device_snapshot)."""
        dirty = self._j_alloc_dirty
        if dirty.any():
            rows = np.flatnonzero(dirty)
            self.j_alloc32[rows] = self.j_alloc[rows]
            dirty[:] = False
        return self.j_alloc32

    def node_ledgers32(self):
        """(idle32, rel32, used32, alloc32) — the persistent float32 ledger
        twins, refreshed at exactly the dirty rows."""
        dirty = self._node_ledger_dirty
        if dirty.any():
            rows = np.flatnonzero(dirty)
            self.n_idle32[rows] = self.n_idle[rows]
            self.n_rel32[rows] = self.n_rel[rows]
            self.n_used32[rows] = self.n_used[rows]
            self.n_alloc32[rows] = self.n_alloc[rows]
            dirty[:] = False
        return self.n_idle32, self.n_rel32, self.n_used32, self.n_alloc32

    def per_cycle_resident(self, snap, mesh=None):
        """Swap the per-cycle snapshot columns for their device-resident
        copies, refreshed by scatter deltas (api/resident.py) — sharded
        placements when `mesh` is given.  Shares the KB_DEVICE_CACHE kill
        switch with the static feature cache."""
        import os

        if os.environ.get("KB_DEVICE_CACHE", "").strip().lower() in (
            "0", "false", "off", "no"
        ):
            return snap
        cache = self._per_cycle_dev.get(mesh)
        if cache is None:
            from kube_batch_tpu.api.resident import (
                PerCycleDeviceCache,
                ShardedPerCycleDeviceCache,
            )

            cache = (
                PerCycleDeviceCache() if mesh is None
                else ShardedPerCycleDeviceCache(mesh)
            )
            # keep at most ONE resident cache — the dispatch path that just
            # ran.  A mesh change (reshard / device-set change) drops the
            # old mesh's residency so stale placements never feed a solve;
            # a path flip (node axis crossing the shard gate, KB_SHARD
            # toggles) likewise frees the abandoned path's device copies
            # instead of holding a dead full set of per-cycle columns for
            # the process lifetime.  Either way the fresh cache
            # full-uploads once and deltas resume.
            for stale in [k for k in self._per_cycle_dev if k is not mesh]:
                del self._per_cycle_dev[stale]
                # the abandoned path's carried candidate tables rank over
                # the dropped cache's coordinate system — drop with it
                for wkey in [k for k, st in self._warm_tables.items()
                             if st.mesh is stale]:
                    del self._warm_tables[wkey]
            self._per_cycle_dev[mesh] = cache
        guard = self.resident_swap_guard
        if guard is not None:
            # the swap's scatters DONATE the resident buffers a published
            # lease may still reference — the guard (serve/lease.py)
            # excludes probe dispatches for the swap's duration and retires
            # the stale lease on donating backends
            with guard():
                out = cache.swap(snap)
        else:
            out = cache.swap(snap)
        # feed this swap's row-exact delta record to the warm-table carry
        # (idempotent per cache version — the memoized repeat swap above
        # re-notifies the same record harmlessly)
        for st in self._warm_tables.values():
            if st.mesh is mesh:
                st.absorb(cache.delta_record, cache.version)
        return out

    def resident_counters(self) -> Dict[str, Dict[str, int]]:
        """Per-path scatter-delta counters ("single" / "sharded") for the
        bench artifact and the sim's longitudinal report."""
        out: Dict[str, Dict[str, int]] = {}
        for key, cache in self._per_cycle_dev.items():
            out["single" if key is None else "sharded"] = cache.counters()
        return out

    def export_delta_record(self, mesh=None):
        """The last resident swap's row-exact delta record + dirty-tracker
        version token, for the replication publisher
        (replicate/publisher.py) — the same knowledge the warm-table carry
        absorbs, so the wire stream rides the scatter diff instead of
        re-deriving it.  ``(None, 0)`` when this path has no resident
        cache (KB_DEVICE_CACHE=0, or no solve dispatched yet); the
        publisher then self-diffs against its own mirrors."""
        cache = self._per_cycle_dev.get(mesh)
        if cache is None:
            return None, 0
        return dict(cache.delta_record), int(cache.version)

    def drop_resident(self) -> None:
        """Cold-start the device residency — the per-cycle scatter caches
        AND the version-keyed static feature cache: the next solve dispatch
        pays a full upload + prewarm.  The warm-standby path calls this
        only when revalidation FAILS; the guard plane calls it on every
        integrity trip (the self-heal for a corrupted resident buffer —
        a static feature column is as corruptible as a per-cycle one, so
        both caches go).  The carried warm-allocate candidate tables go
        with them: they were ranked against the dropped buffers, and a
        guard heal must not leave a possibly-corrupt ranking behind."""
        self._per_cycle_dev.clear()
        self._dev_cache.clear()
        self.drop_warm_tables()

    # ---- warm-started allocate: carried candidate tables (KB_WARM) ----
    def warm_table_state(self, mesh=None, impl=None):
        """The carried candidate-table state for one (mesh, impl) dispatch
        slot — created lazily; the state self-resets on shape/config key
        changes (api/resident.WarmTableState)."""
        from kube_batch_tpu.api.resident import WarmTableState

        key = (mesh, impl)
        st = self._warm_tables.get(key)
        if st is None:
            st = self._warm_tables[key] = WarmTableState(mesh=mesh,
                                                         impl=impl)
        return st

    def drop_warm_tables(self) -> None:
        """Wholesale drop of every carried candidate table (axis growth,
        resident drops, guard heals): the next warm dispatch cold-builds."""
        self._warm_tables.clear()

    def warm_counters(self) -> Dict[str, Dict]:
        """Per-slot warm-table counters for the bench / sim evidence."""
        return {
            f"{'single' if mesh is None else 'sharded'}"
            f"{'' if impl is None else ':' + impl}": st.counters()
            for (mesh, impl), st in self._warm_tables.items()
        }

    def revalidate_resident(self, cache) -> Dict:
        """Warm-standby revalidation (leader failover): decide whether the
        surviving per-cycle device caches may keep serving after the host
        model was rebuilt from the pod store.

        KEEP when every resident cache has synced at least one snapshot
        (version token > 0) and the rebuilt store passes
        ``check_consistency`` — the mirrors then describe a state the next
        swap's vectorized diff can reconcile with ordinary scatter deltas,
        so the compiled executables and resident buffers survive and
        failover pays no recompile/re-upload. DROP (cold start) on any
        consistency error or an unsynced cache — a mirror of unknown
        provenance must not feed a solve.  (The replication follower's
        restart re-adoption — replicate/follower.py
        ``FollowerApplier.revalidate_resident`` — applies the same
        keep-iff-synced contract to its wire-fed resident cache.)"""
        errors = [str(e) for e in self.check_consistency(cache)]
        tokens = {
            ("single" if key is None else "sharded"): rc.version
            for key, rc in self._per_cycle_dev.items()
        }
        ok = not errors and all(v > 0 for v in tokens.values())
        if not ok and self._per_cycle_dev:
            self.drop_resident()
        return {
            "mode": "warm" if ok else "cold",
            "resident_tokens": tokens,
            "errors": errors,
        }

    def resident_features(self, snap, mesh=None):
        """`snap` with the ingest-static feature arrays swapped for cached
        DEVICE-RESIDENT copies, re-uploaded only when the column's axis
        version moved since the last call — steady-state cycles then ship only the truly
        per-cycle columns (statuses, node ledgers, job/queue rows) to the
        device (SURVEY §7.3's one-transfer-in budget; decisive on a
        network-tunneled TPU).  `shardings`/`key` select a placement (the
        mesh solve needs mesh-sharded uploads; committed single-device
        arrays would be rejected by its in_shardings).  Callers keep using
        the ORIGINAL host-backed snap for numpy reads — only the returned
        copy goes to the solve.  KB_DEVICE_CACHE=0 disables."""
        import os

        if os.environ.get("KB_DEVICE_CACHE", "").strip().lower() in (
            "0", "false", "off", "no"
        ):
            return snap
        import jax

        shardings = None
        if mesh is not None:
            from kube_batch_tpu.parallel.mesh import snapshot_shardings

            shardings = snapshot_shardings(mesh)
        cache = self._dev_cache.setdefault(mesh, {})
        versions = {"task": self.task_feature_version,
                    "node": self.node_feature_version}
        updates = {}
        for field, (col, axis) in self.FEATURE_FIELDS.items():
            version = versions[axis]
            ver, arr = cache.get(field, (-1, None))
            host = getattr(self, col)
            if ver != version or arr.shape != host.shape:
                sharding = (
                    getattr(shardings, field) if shardings is not None else None
                )
                arr = (
                    jax.device_put(host, sharding)
                    if sharding is not None else jax.device_put(host)
                )
                cache[field] = (version, arr)
            updates[field] = arr
        return snap._replace(**updates)

    def device_snapshot(self, ssn):
        """Build the (DeviceSnapshot, SnapshotMeta) pair for an EXCLUSIVE
        session straight from the columns.  Row space == device axis: the
        assignment vector indexes task rows; node/job indices are rows.

        Per-cycle work: the session job-row sync (already done by
        open_session for exclusive sessions — delta when churn allows; the
        full rescan runs here only for sessions that skipped it), the
        sparse affinity/preference rows, a few [cap, R] float32 casts, and
        vectorized derived masks.  Everything else is already columnar.
        """
        self.refresh_task_bits()
        spec = self.spec
        capT, capN = self.tasks.cap, self.nodes.cap
        capJ, capQ = self.jobs.cap, self.queues.cap

        # ---- job rows (session membership + object-owned metadata) ------
        # open_session syncs these (delta against the previous cycle when
        # churn is low) and marks the session; direct callers — tests, the
        # backfill real-request pass on hand-built sessions — get the full
        # rescan here
        if not getattr(ssn, "rows_synced", False):
            self.sync_session_rows(ssn)
        j_min, j_queue, j_prio = self.j_min, self.j_queue, self.j_prio
        j_creation, j_sess, j_sched = self.j_creation, self.j_sess, self.j_sched

        counts = self.j_counts
        job_ready = counts[:, READY_STATUSES].sum(axis=1, dtype=np.int32)

        # ---- queue aggregates (proportion.go:84-99 semantics) -----------
        sess_rows = np.flatnonzero(j_sess)
        queue_alloc = np.zeros((capQ, self.R), np.float32)
        queue_request = np.zeros((capQ, self.R), np.float32)
        if sess_rows.size:
            qr = j_queue[sess_rows]
            np.add.at(queue_alloc, qr, self.j_alloc[sess_rows].astype(np.float32))
            np.add.at(
                queue_request, qr,
                (self.j_alloc[sess_rows] + self.j_pend[sess_rows]).astype(np.float32),
            )

        # ---- derived task masks -----------------------------------------
        t_status = self.t_status
        task_pending = self.schedulable_pending_mask()

        # ---- sparse affinity / preference rows --------------------------
        aff_live = [r for r in self._aff_rows if self.t_valid[r]]
        K = max(1, len(aff_live))
        task_aff_idx = np.full(K, -1, np.int32)
        task_aff_mask = np.ones((K, capN), bool)
        node_objs_cache = None
        if aff_live:
            from kube_batch_tpu.plugins.predicates import pod_affinity_ok

            node_objs_cache = [n for n in self.node_by_row if n is not None]
            for k, row in enumerate(aff_live):
                task_aff_idx[k] = row
                t = self.task_by_row[row]
                for n in node_objs_cache:
                    task_aff_mask[k, n._row] = pod_affinity_ok(
                        t, n, node_objs_cache
                    )
        pref_live = [r for r in self._pref_rows if self.t_valid[r]]
        Kp = max(1, len(pref_live))
        task_pref_idx = np.full(Kp, -1, np.int32)
        task_pref_node = np.zeros((Kp, capN), np.float32)
        task_pref_pod = np.zeros((Kp, capN), np.float32)
        if pref_live:
            from kube_batch_tpu.plugins.nodeorder import (
                minmax_scale_rows,
                preferred_node_affinity_score,
                preferred_pod_affinity_score,
            )

            if node_objs_cache is None:
                node_objs_cache = [n for n in self.node_by_row if n is not None]
            for k, row in enumerate(pref_live):
                task_pref_idx[k] = row
                t = self.task_by_row[row]
                for n in node_objs_cache:
                    task_pref_node[k, n._row] = preferred_node_affinity_score(t, n)
                    task_pref_pod[k, n._row] = preferred_pod_affinity_score(
                        t, n, node_objs_cache
                    )
            task_pref_pod = minmax_scale_rows(task_pref_pod)

        node_valid = self.n_valid
        # node ledgers: persistent f32 twins refreshed at the dirty rows
        # only (the per-cycle full-matrix casts this replaces were the last
        # O(nodes) host cost of the snapshot build)
        idle32, rel32, used32, alloc32 = self.node_ledgers32()
        # session-level node exclusions (pressure gates): fold into
        # node_sched so the device predicate is exact
        node_sched = self.n_sched
        excluded_rows = self.excluded_node_rows(ssn)
        if excluded_rows:
            node_sched = node_sched.copy()
            node_sched[excluded_rows] = False
        total = (
            self.n_alloc[node_valid].sum(axis=0).astype(np.float32)
            if node_valid.any() else np.zeros(self.R, np.float32)
        )

        snap = DeviceSnapshot(
            task_req=self.t_init32,
            task_resreq=self.t_res32,
            task_job=self.t_job,
            task_prio=self.t_prio,
            task_creation=self.t_creation,
            task_status=t_status,
            task_valid=self.t_valid,
            task_pending=task_pending,
            task_best_effort=self.t_best_effort,
            task_sel_bits=self.t_sel_bits,
            task_sel_impossible=self.t_sel_impossible,
            task_tol_bits=self.t_tol_bits,
            task_node=self.t_node,
            task_critical=self.t_critical,
            task_needs_host=self.t_needs_host,
            task_aff_idx=task_aff_idx,
            task_aff_mask=task_aff_mask,
            task_pref_idx=task_pref_idx,
            task_pref_node=task_pref_node,
            task_pref_pod=task_pref_pod,
            node_idle=idle32,
            node_releasing=rel32,
            node_used=used32,
            node_alloc=alloc32,
            node_valid=node_valid,
            node_sched=node_sched,
            node_label_bits=self.n_label_bits,
            node_taint_bits=self.n_taint_bits,
            job_min_avail=j_min,
            job_ready=job_ready,
            job_queue=j_queue,
            job_prio=j_prio,
            job_creation=j_creation,
            job_valid=j_sess,
            job_schedulable=j_sched,
            job_allocated=self.job_alloc32(),
            queue_weight=self.q_weight,
            queue_capability=self.q_cap,
            queue_alloc=queue_alloc,
            queue_request=queue_request,
            queue_valid=self.q_valid,
            total=total,
            quanta=spec.quanta.astype(np.float32),
        )
        meta = SnapshotMeta(
            spec=spec,
            task_keys=[t._key if t is not None else "" for t in self.task_by_row],
            node_names=self.node_names,
            job_uids=[j.uid if j is not None else "" for j in self.job_by_row],
            queue_names=self.queue_names,
            label_pair_bit=self.label_pair_bit,
            taint_bit=self.taint_bit,
            n_tasks=capT,
            n_nodes=capN,
            n_jobs=capJ,
            n_queues=capQ,
            task_objs=self.task_by_row,
            job_objs=self.job_by_row,
            node_objs=self.node_by_row,
            task_resreq64=self.t_resreq64,
            task_needs_host=self.t_needs_host,
        )
        meta.live_nodes = int(node_valid.sum())
        return snap, meta

    # ==================================================================
    # debug / test support
    # ==================================================================
    def check_consistency(self, cache) -> List[str]:
        """Compare the columns against the object model; returns a list of
        discrepancy descriptions (empty = consistent).  O(objects) — test
        and debug use only."""
        errs: List[str] = []
        seen_rows = set()
        for uid, job in cache.jobs.items():
            row = getattr(job, "_row", -1)
            if row < 0:
                errs.append(f"job {uid} unbound")
                continue
            if not np.allclose(self.j_alloc[row], job.allocated.vec):
                errs.append(f"job {uid} allocated mismatch")
            if not np.allclose(self.j_pend[row], job.pending_request.vec):
                errs.append(f"job {uid} pending mismatch")
            if not np.allclose(self.j_total[row], job.total_request.vec):
                errs.append(f"job {uid} total mismatch")
            for s in TaskStatus:
                want = len(job.task_status_index.get(s, {}))
                got = int(self.j_counts[row, int(s)])
                if want != got:
                    errs.append(
                        f"job {uid} count[{s.name}] = {got}, objects say {want}"
                    )
            for t in job.tasks.values():
                trow = getattr(t, "_row", -1)
                if trow < 0:
                    errs.append(f"task {t._key} unbound")
                    continue
                seen_rows.add(trow)
                if int(self.t_status[trow]) != int(t.status):
                    errs.append(f"task {t._key} status col {self.t_status[trow]} != {int(t.status)}")
                # t_node means "node row the task is ACCOUNTED on": a task
                # whose node was deleted and re-added keeps its node_name but
                # is not resident on the fresh NodeInfo until its next pod
                # event re-attaches it (the reference's convergence), so the
                # column is rightly -1 there.  The expectation derives from
                # the OBJECT model (cache.nodes), not the store's own
                # indexes, so index corruption can't self-validate.
                want_node = -1
                if t.node_name:
                    node_obj = cache.nodes.get(t.node_name)
                    if node_obj is not None and t._key in node_obj.tasks:
                        want_node = getattr(node_obj, "_row", -1)
                if int(self.t_node[trow]) != want_node:
                    errs.append(f"task {t._key} node col {self.t_node[trow]} != {want_node}")
                if self.t_job[trow] != row:
                    errs.append(f"task {t._key} job col {self.t_job[trow]} != {row}")
                if not self.t_valid[trow]:
                    errs.append(f"task {t._key} row not valid")
        if int(self.t_valid.sum()) != len(seen_rows):
            errs.append(
                f"{int(self.t_valid.sum())} valid task rows but {len(seen_rows)} live tasks"
            )
        for name, node in cache.nodes.items():
            row = getattr(node, "_row", -1)
            if row < 0:
                errs.append(f"node {name} unbound")
                continue
            for label, col, vec in (
                ("idle", self.n_idle, node.idle.vec),
                ("used", self.n_used, node.used.vec),
                ("releasing", self.n_rel, node.releasing.vec),
                ("allocatable", self.n_alloc, node.allocatable.vec),
            ):
                if not np.allclose(col[row], vec):
                    errs.append(f"node {name} {label} mismatch")
            if bool(self.n_valid[row]) != node.ready:
                errs.append(f"node {name} valid flag mismatch")
        for name, q in cache.queues.items():
            if self.queue_rows.get(name) is None:
                errs.append(f"queue {name} unbound")
        # the f32 ledger twins must track the f64 ledgers exactly once the
        # dirty rows are flushed — a missed note_node_ledger choke point
        # (a new ledger write path) shows up here
        self.node_ledgers32()
        for label, f32, f64 in (
            ("idle32", self.n_idle32, self.n_idle),
            ("rel32", self.n_rel32, self.n_rel),
            ("used32", self.n_used32, self.n_used),
            ("alloc32", self.n_alloc32, self.n_alloc),
        ):
            if not np.array_equal(f32, f64.astype(np.float32)):
                rows = np.flatnonzero(
                    np.any(f32 != f64.astype(np.float32), axis=1)
                )[:8]
                errs.append(
                    f"node ledger twin {label} stale at rows {rows.tolist()}"
                    " (missed note_node_ledger choke point)"
                )
        # same contract for the job-alloc twin (note_job_alloc choke)
        self.job_alloc32()
        if not np.array_equal(self.j_alloc32, self.j_alloc.astype(np.float32)):
            rows = np.flatnonzero(np.any(
                self.j_alloc32 != self.j_alloc.astype(np.float32), axis=1
            ))[:8]
            errs.append(
                f"job alloc twin stale at rows {rows.tolist()}"
                " (missed note_job_alloc choke point)"
            )
        return errs


def _grow_width(arr: np.ndarray, words: int) -> np.ndarray:
    new = np.zeros((arr.shape[0], words), arr.dtype)
    new[:, : arr.shape[1]] = arr
    return new
