"""ClusterInfo — the per-session snapshot triple (cluster_info.go:22-26)."""

from __future__ import annotations

from typing import Dict

from kube_batch_tpu.api.job_info import JobInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.queue_info import QueueInfo
from kube_batch_tpu.api.resources import ResourceSpec


class ClusterInfo:
    def __init__(self, spec: ResourceSpec):
        self.spec = spec
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}

    def __repr__(self) -> str:
        return (
            f"ClusterInfo(jobs={len(self.jobs)}, nodes={len(self.nodes)}, "
            f"queues={len(self.queues)})"
        )
