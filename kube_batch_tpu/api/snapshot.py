"""DeviceSnapshot — the cluster state as device-resident SoA tensors.

This is the TPU-native replacement for the reference's per-session object
snapshot (cache.go:584-654 Snapshot + cluster_info.go). Instead of deep-cloned
Go object graphs walked by 16-worker loops, one scheduling cycle ships a
structure-of-arrays image of (tasks × R, nodes × R, jobs, queues) to the
device once, runs the compiled feasibility/score/fairness/assignment programs
on it, and ships one assignment vector back (SURVEY.md §7.1).

Label/selector/taint matching is pre-compiled host-side into bitsets
(SURVEY.md §7.3 "string/label matching on device"): every distinct (key,value)
label pair carried by any node gets a bit; a task's node-selector becomes a
required-bits mask; every distinct node taint gets a bit and a task's
tolerations become a tolerated-bits mask. The device then evaluates
selector/taint predicates as pure bitwise ops.

All axes are padded to power-of-two buckets so jit specializes on a small set
of shapes (SURVEY.md §7.3 "dynamic shapes").
"""

from __future__ import annotations

import dataclasses
import math
import operator
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from kube_batch_tpu.api.cluster_info import ClusterInfo
from kube_batch_tpu.api.resources import ResourceSpec
from kube_batch_tpu.api.types import (
    CRITICAL_NAMESPACE,
    CRITICAL_PRIORITY_CLASSES,
    PodGroupPhase,
    TaskStatus,
    is_allocated,
)

BITS = 32
# Effects that hard-exclude a node (PreferNoSchedule is a soft preference the
# reference handles in scoring, not predicates).
HARD_TAINT_EFFECTS = ("NoSchedule", "NoExecute")
# Capability value meaning "unbounded" (queue without a Capability cap).
UNBOUNDED = np.float32(3.4e38)


_task_key = operator.attrgetter("_key")


def bucket(n: int, floor: int = 8) -> int:
    """Shape bucket ≥ max(n, floor) — bounds jit recompiles while keeping
    padding waste low at scale: powers of two up to 4096, then multiples of
    1024 (divisible by any power-of-two mesh axis ≤ 1024, and ≤2.5% waste
    at the 50k/5k north-star sizes vs 64%/23% for pure powers of two)."""
    n = max(n, floor)
    if n <= 4096:
        return max(floor, 1 << max(0, math.ceil(math.log2(n))))
    return -(-n // 1024) * 1024


class DeviceSnapshot(NamedTuple):
    """The per-cycle tensor image. All arrays live on device; rows beyond the
    live count are padding with their `*_valid` bit off."""

    # tasks [T, ...]
    task_req: "np.ndarray"          # [T, R] f32 — InitResreq (allocate fits on this)
    task_resreq: "np.ndarray"       # [T, R] f32 — Resreq (node accounting uses this)
    task_job: "np.ndarray"          # [T] i32 — index into job axis (0 for padding)
    task_prio: "np.ndarray"         # [T] i32
    task_creation: "np.ndarray"     # [T] i32
    task_status: "np.ndarray"       # [T] i32 — TaskStatus values
    task_valid: "np.ndarray"        # [T] bool
    task_pending: "np.ndarray"      # [T] bool — Pending and not BestEffort
    task_best_effort: "np.ndarray"  # [T] bool
    task_sel_bits: "np.ndarray"     # [T, W] u32 — required label bits
    task_sel_impossible: "np.ndarray"  # [T] bool — selector wants a pair no node has
    task_tol_bits: "np.ndarray"     # [T, Wt] u32 — tolerated taint bits
    task_node: "np.ndarray"         # [T] i32 — bound node index, -1 unbound
    task_critical: "np.ndarray"     # [T] bool — conformance-protected
    #                                 (conformance.go:42-59)
    task_needs_host: "np.ndarray"   # [T] bool — carries host-only constraints
    #                                 (ports/rich affinity); the reclaim
    #                                 idle-fit gate exempts these (their
    #                                 device fit is approximate)
    # sparse inter-pod-affinity correction (predicates.go:278-296): rows of
    # a [K, N] allow mask for the K tasks carrying required pod
    # (anti-)affinity terms, evaluated against snapshot-time placements;
    # the host predicate re-validates against live state at replay
    task_aff_idx: "np.ndarray"      # [K] i32 — task index, -1 padding
    task_aff_mask: "np.ndarray"     # [K, N] bool — allowed nodes (padding: True)
    # sparse preferred-affinity score rows (nodeorder.go:188-247 priorities)
    # for the Kp tasks carrying preferred node/pod terms
    task_pref_idx: "np.ndarray"     # [Kp] i32 — task index, -1 padding
    task_pref_node: "np.ndarray"    # [Kp, N] f32 — preferred-node-affinity score
    task_pref_pod: "np.ndarray"     # [Kp, N] f32 — preferred-pod-(anti)affinity score
    # nodes [N, ...]
    node_idle: "np.ndarray"         # [N, R] f32
    node_releasing: "np.ndarray"    # [N, R] f32
    node_used: "np.ndarray"         # [N, R] f32
    node_alloc: "np.ndarray"        # [N, R] f32 — allocatable
    node_valid: "np.ndarray"        # [N] bool — Ready (node_info.go:110-134)
    node_sched: "np.ndarray"        # [N] bool — not Unschedulable (predicates.go:181-192)
    node_label_bits: "np.ndarray"   # [N, W] u32
    node_taint_bits: "np.ndarray"   # [N, Wt] u32 — hard-effect taints present
    # jobs [J, ...]
    job_min_avail: "np.ndarray"     # [J] i32
    job_ready: "np.ndarray"         # [J] i32 — ReadyTaskNum at snapshot time
    job_queue: "np.ndarray"         # [J] i32 — index into queue axis
    job_prio: "np.ndarray"          # [J] i32
    job_creation: "np.ndarray"      # [J] i32
    job_valid: "np.ndarray"         # [J] bool — gang-valid and in a known queue
    job_schedulable: "np.ndarray"   # [J] bool — passes the Pending-phase gate
    job_allocated: "np.ndarray"     # [J, R] f32 — for DRF shares
    # queues [Q, ...]
    queue_weight: "np.ndarray"      # [Q] f32
    queue_capability: "np.ndarray"  # [Q, R] f32 (UNBOUNDED iff no Capability;
    #                                 a capability dict zeroes unnamed dims —
    #                                 the JobEnqueueable closure's encoding)
    queue_alloc: "np.ndarray"       # [Q, R] f32
    queue_request: "np.ndarray"     # [Q, R] f32 — total request of queue's jobs
    queue_valid: "np.ndarray"       # [Q] bool
    # cluster
    total: "np.ndarray"             # [R] f32 — Σ allocatable over valid nodes
    quanta: "np.ndarray"            # [R] f32 — comparison quanta


@dataclasses.dataclass
class SnapshotMeta:
    """Host-side index maps for decoding device results back to objects."""

    spec: ResourceSpec
    task_keys: List[str]            # task index → "ns/name"
    node_names: List[str]           # node index → name
    job_uids: List[str]             # job index → JobInfo.uid
    queue_names: List[str]          # queue index → name
    label_pair_bit: Dict[Tuple[str, str], int]
    taint_bit: Dict[Tuple[str, str, str], int]
    n_tasks: int
    n_nodes: int
    n_jobs: int
    n_queues: int
    # direct object references in device-index order (the session's own
    # objects) — the vectorized allocate replay addresses placements by index
    # instead of per-placement dict lookups
    task_objs: List = dataclasses.field(default_factory=list)
    job_objs: List = dataclasses.field(default_factory=list)
    node_objs: List = dataclasses.field(default_factory=list)
    # [nT, R] float64 resreq (NOT init_resreq, and not the f32 device cast) —
    # segment sums over this match the host Resource ledgers bit-exactly
    task_resreq64: "np.ndarray" = None
    # [nT] bool — task carries host-only constraints (ports, rich affinity)
    task_needs_host: "np.ndarray" = None

    @property
    def shape(self) -> Tuple[int, int, int, int]:
        return (len(self.task_keys), len(self.node_names), len(self.job_uids), len(self.queue_names))


def _pad_bool(arr: "np.ndarray", n: int) -> "np.ndarray":
    """[k] bool → [n] bool, padding False."""
    out = np.zeros(n, bool)
    out[: arr.shape[0]] = arr
    return out


def _pack_bits(bit_indices: List[int], words: int) -> np.ndarray:
    out = np.zeros(words, dtype=np.uint32)
    for b in bit_indices:
        out[b // BITS] |= np.uint32(1 << (b % BITS))
    return out


def build_snapshot(
    cluster: ClusterInfo,
    pad: bool = True,
    excluded_nodes=(),
) -> Tuple[DeviceSnapshot, SnapshotMeta]:
    """Flatten a host ClusterInfo into the SoA tensor image.

    Only gang-valid jobs in known queues contribute schedulable tasks (the
    session-open drop of invalid jobs, session.go:107-124, is applied by the
    caller; here job_valid additionally guards padding). Every task of every
    job is included (the kernels need resident tasks for accounting), but only
    Pending non-BestEffort tasks are marked task_pending.
    """
    spec = cluster.spec
    R = spec.n

    queues = sorted(cluster.queues.values(), key=lambda q: q.name)
    queue_idx = {q.name: i for i, q in enumerate(queues)}
    jobs = sorted(cluster.jobs.values(), key=lambda j: j.uid)
    nodes = sorted((n for n in cluster.nodes.values()), key=lambda n: n.name)
    node_idx = {n.name: i for i, n in enumerate(nodes)}

    tasks = []
    for ji, j in enumerate(jobs):
        for t in sorted(j.tasks.values(), key=_task_key):
            tasks.append((t, ji))

    nT, nN, nJ, nQ = len(tasks), len(nodes), len(jobs), len(queues)
    T = bucket(nT) if pad else max(nT, 1)
    N = bucket(nN) if pad else max(nN, 1)
    J = bucket(nJ) if pad else max(nJ, 1)
    Q = bucket(nQ) if pad else max(nQ, 1)

    # ---- label / taint interning over the node universe -----------------
    label_pair_bit: Dict[Tuple[str, str], int] = {}
    taint_bit: Dict[Tuple[str, str, str], int] = {}
    for n in nodes:
        if n.node is None:
            continue
        for k, v in n.node.labels.items():
            label_pair_bit.setdefault((k, v), len(label_pair_bit))
        for taint in n.node.taints:
            if taint.effect in HARD_TAINT_EFFECTS:
                taint_bit.setdefault((taint.key, taint.value, taint.effect), len(taint_bit))
    W = max(1, -(-len(label_pair_bit) // BITS))
    Wt = max(1, -(-len(taint_bit) // BITS))

    # ---- tasks ----------------------------------------------------------
    task_req = np.zeros((T, R), np.float32)
    task_resreq = np.zeros((T, R), np.float32)
    task_job = np.zeros(T, np.int32)
    task_prio = np.zeros(T, np.int32)
    task_creation = np.zeros(T, np.int32)
    task_status = np.full(T, int(TaskStatus.UNKNOWN), np.int32)
    task_valid = np.zeros(T, bool)
    task_pending = np.zeros(T, bool)
    task_best_effort = np.zeros(T, bool)
    task_sel_bits = np.zeros((T, W), np.uint32)
    task_sel_impossible = np.zeros(T, bool)
    task_tol_bits = np.zeros((T, Wt), np.uint32)
    task_node = np.full(T, -1, np.int32)
    task_critical = np.zeros(T, bool)
    aff_tasks: List[int] = []   # tasks needing an inter-pod-affinity row
    pref_tasks: List[int] = []  # tasks with preferred (soft) affinity terms
    task_keys: List[str] = []

    taint_list = list(taint_bit.items())  # [((k,v,effect), bit)]
    # columnar bulk fill (list comprehensions + one numpy write per column —
    # ~5× faster than a per-task field loop at the 50k scale)
    task_objs: List = []
    task_resreq64 = np.zeros((nT, R), np.float64)
    task_needs_host = np.zeros(nT, bool)
    if nT:
        task_objs = [t for t, _ in tasks]
        task_keys.extend(t._key for t in task_objs)
        resreq_rows = [t.resreq.vec for t in task_objs]
        task_resreq64 = np.stack(resreq_rows)  # .vec is already float64
        task_resreq[:nT] = task_resreq64
        # init_resreq is the same Resource object as resreq for pods without
        # init containers (task_info.py) — reuse the stack when nothing differs
        if all(t.init_resreq is t.resreq for t in task_objs):
            task_req[:nT] = task_resreq[:nT]
        else:
            task_req[:nT] = np.stack([t.init_resreq.vec for t in task_objs])
        task_needs_host = np.fromiter(
            (t.needs_host_predicate for t in task_objs), bool, count=nT
        )
        task_job[:nT] = [ji for _, ji in tasks]
        task_prio[:nT] = [t.priority for t in task_objs]
        task_creation[:nT] = [t.pod.creation_index for t in task_objs]
        statuses = np.fromiter(
            (int(t.status) for t in task_objs), np.int32, count=nT
        )
        task_status[:nT] = statuses
        task_valid[:nT] = True
        # BestEffort = empty semantic InitResreq (vectorized is_empty)
        m = spec.semantic_mask
        task_best_effort[:nT] = np.all(
            task_req[:nT][:, m] < spec.quanta[None, m], axis=1
        )
        task_pending[:nT] = (statuses == int(TaskStatus.PENDING)) & ~task_best_effort[:nT]
        task_node[:nT] = [
            node_idx.get(t.node_name, -1) if t.node_name is not None else -1
            for t in task_objs
        ]
        task_critical[:nT] = [
            t.pod.priority_class in CRITICAL_PRIORITY_CLASSES
            or t.namespace == CRITICAL_NAMESPACE
            for t in task_objs
        ]
    # sparse per-task features: bitsets, affinity and preference rows — only
    # tasks actually carrying selectors/tolerations/affinity walk this path;
    # one cheap comprehension picks them so the plain-pod common case pays a
    # single attribute read instead of the full branch ladder
    sparse = [
        (i, t) for i, (t, _) in enumerate(tasks)
        if t.pod.affinity is not None or t.pod.node_selector or t.pod.tolerations
    ]
    for i, t in sparse:
        pod = t.pod
        if pod.affinity is not None and (
            pod.affinity.pod_affinity or pod.affinity.pod_anti_affinity
        ):
            aff_tasks.append(i)
        if pod.affinity is not None and pod.affinity.has_preferences():
            pref_tasks.append(i)
        # required label pairs → bits: node-selector terms (MatchNodeSelector,
        # predicates.go:194-205) plus single-term node-affinity whose
        # In-requirements carry one value (necessary AND sufficient for that
        # term). Multi-term affinity (OR) or richer operators stay host-side —
        # the allocate replay re-validates every proposed placement through
        # the predicates plugin, so the device mask only needs to be a sound
        # over-approximation of feasibility.
        if pod.node_selector or pod.affinity is not None:
            required_pairs = list(pod.node_selector.items())
            if pod.affinity is not None and len(pod.affinity.node_terms) == 1:
                required_pairs += [
                    (key, values[0])
                    for key, op, values in pod.affinity.node_terms[0]
                    if op == "In" and len(values) == 1
                ]
            sel_bits: List[int] = []
            for k, v in required_pairs:
                b = label_pair_bit.get((k, v))
                if b is None:
                    task_sel_impossible[i] = True  # no node carries this pair
                else:
                    sel_bits.append(b)
            if sel_bits:
                task_sel_bits[i] = _pack_bits(sel_bits, W)
        # tolerations → tolerated-taint bits (PodToleratesNodeTaints,
        # predicates.go:220-231): bit set iff some toleration tolerates taint
        if pod.tolerations and taint_list:
            tol_bits = [
                bit
                for (tk, tv, te), bit in taint_list
                if any(
                    tol.tolerates(_TaintView(tk, tv, te)) for tol in pod.tolerations
                )
            ]
            task_tol_bits[i] = _pack_bits(tol_bits, Wt)

    # ---- nodes ----------------------------------------------------------
    node_idle = np.zeros((N, R), np.float32)
    node_releasing = np.zeros((N, R), np.float32)
    node_used = np.zeros((N, R), np.float32)
    node_alloc = np.zeros((N, R), np.float32)
    node_valid = np.zeros(N, bool)
    node_sched = np.zeros(N, bool)
    node_label_bits = np.zeros((N, W), np.uint32)
    node_taint_bits = np.zeros((N, Wt), np.uint32)
    node_names: List[str] = []
    for i, n in enumerate(nodes):
        node_names.append(n.name)
        node_idle[i] = n.idle.vec
        node_releasing[i] = n.releasing.vec
        node_used[i] = n.used.vec
        node_alloc[i] = n.allocatable.vec
        node_valid[i] = n.ready
        if n.node is not None:
            # session-level exclusions (pressure gates) fold into the
            # schedulability bit like Unschedulable (predicates.go:233-276)
            node_sched[i] = (
                not n.node.unschedulable and n.name not in excluded_nodes
            )
            node_label_bits[i] = _pack_bits(
                [label_pair_bit[(k, v)] for k, v in n.node.labels.items()], W
            )
            node_taint_bits[i] = _pack_bits(
                [
                    taint_bit[(t.key, t.value, t.effect)]
                    for t in n.node.taints
                    if t.effect in HARD_TAINT_EFFECTS
                ],
                Wt,
            )

    # ---- jobs -----------------------------------------------------------
    job_min_avail = np.zeros(J, np.int32)
    job_ready = np.zeros(J, np.int32)
    job_queue = np.zeros(J, np.int32)
    job_prio = np.zeros(J, np.int32)
    job_creation = np.zeros(J, np.int32)
    job_valid = np.zeros(J, bool)
    job_schedulable = np.zeros(J, bool)
    job_allocated = np.zeros((J, R), np.float32)
    job_uids: List[str] = []
    for i, j in enumerate(jobs):
        job_uids.append(j.uid)
        job_min_avail[i] = j.min_available
        job_ready[i] = j.ready_task_num
        job_queue[i] = queue_idx.get(j.queue, 0)
        job_prio[i] = j.priority
        job_creation[i] = j.creation_index
        job_valid[i] = j.queue in queue_idx
        phase = j.pod_group.phase if j.pod_group else None
        job_schedulable[i] = phase != PodGroupPhase.PENDING
        job_allocated[i] = j.allocated.vec

    # ---- queues ---------------------------------------------------------
    queue_weight = np.ones(Q, np.float32)
    queue_capability = np.full((Q, R), UNBOUNDED, np.float32)
    queue_alloc = np.zeros((Q, R), np.float32)
    queue_request = np.zeros((Q, R), np.float32)
    queue_valid = np.zeros(Q, bool)
    queue_names: List[str] = []
    for i, q in enumerate(queues):
        queue_names.append(q.name)
        queue_weight[i] = q.weight
        queue_valid[i] = True
        if q.queue.capability:
            # a capability dict caps every dim it does NOT name at 0 — the
            # JobEnqueueable closure builds its cap from spec.empty()
            # (plugins/proportion.py), and the probe's admission veto must
            # read the same encoding; only a cap-less queue is unbounded
            queue_capability[i] = 0.0
            for name, v in q.queue.capability.items():
                if name in spec:
                    queue_capability[i, spec.index(name)] = v
    for i, j in enumerate(jobs):
        qi = job_queue[i]
        queue_alloc[qi] += job_allocated[i]
        # proportion's request counts AllocatedStatus + Pending tasks only
        # (proportion.go:84-99), not the job's whole total_request
        for t in j.tasks.values():
            if t.status == TaskStatus.PENDING or is_allocated(t.status):
                queue_request[qi] += t.resreq.vec

    # sparse inter-pod-affinity rows, evaluated host-side at snapshot time
    # (the string/label matching stays host-precompiled, SURVEY.md §7.3)
    K = max(1, len(aff_tasks))
    task_aff_idx = np.full(K, -1, np.int32)
    task_aff_mask = np.ones((K, N), bool)
    if aff_tasks:
        from kube_batch_tpu.plugins.predicates import pod_affinity_ok

        node_objs = list(nodes)
        for k, ti in enumerate(aff_tasks):
            task_aff_idx[k] = ti
            t = tasks[ti][0]
            for ni, n in enumerate(node_objs):
                task_aff_mask[k, ni] = pod_affinity_ok(t, n, node_objs)

    Kp = max(1, len(pref_tasks))
    task_pref_idx = np.full(Kp, -1, np.int32)
    task_pref_node = np.zeros((Kp, N), np.float32)
    task_pref_pod = np.zeros((Kp, N), np.float32)
    if pref_tasks:
        from kube_batch_tpu.plugins.nodeorder import (
            preferred_node_affinity_score,
            preferred_pod_affinity_score,
        )

        node_objs = list(nodes)
        for k, ti in enumerate(pref_tasks):
            task_pref_idx[k] = ti
            t = tasks[ti][0]
            for ni, n in enumerate(node_objs):
                task_pref_node[k, ni] = preferred_node_affinity_score(t, n)
                task_pref_pod[k, ni] = preferred_pod_affinity_score(t, n, node_objs)
        # min-max normalize the pod-affinity row to the 0..10 priority scale
        # per task across real nodes (InterPodAffinityPriority's reduce) so a
        # large term weight can't dominate the other bounded score rows
        from kube_batch_tpu.plugins.nodeorder import minmax_scale_rows

        nreal = len(node_objs)
        task_pref_pod[:, :nreal] = minmax_scale_rows(task_pref_pod[:, :nreal])

    total = node_alloc[node_valid].sum(axis=0).astype(np.float32) if nN else np.zeros(R, np.float32)

    snap = DeviceSnapshot(
        task_req=task_req,
        task_resreq=task_resreq,
        task_job=task_job,
        task_prio=task_prio,
        task_creation=task_creation,
        task_status=task_status,
        task_valid=task_valid,
        task_pending=task_pending,
        task_best_effort=task_best_effort,
        task_sel_bits=task_sel_bits,
        task_sel_impossible=task_sel_impossible,
        task_tol_bits=task_tol_bits,
        task_node=task_node,
        task_critical=task_critical,
        task_needs_host=_pad_bool(task_needs_host, T),
        task_aff_idx=task_aff_idx,
        task_aff_mask=task_aff_mask,
        task_pref_idx=task_pref_idx,
        task_pref_node=task_pref_node,
        task_pref_pod=task_pref_pod,
        node_idle=node_idle,
        node_releasing=node_releasing,
        node_used=node_used,
        node_alloc=node_alloc,
        node_valid=node_valid,
        node_sched=node_sched,
        node_label_bits=node_label_bits,
        node_taint_bits=node_taint_bits,
        job_min_avail=job_min_avail,
        job_ready=job_ready,
        job_queue=job_queue,
        job_prio=job_prio,
        job_creation=job_creation,
        job_valid=job_valid,
        job_schedulable=job_schedulable,
        job_allocated=job_allocated,
        queue_weight=queue_weight,
        queue_capability=queue_capability,
        queue_alloc=queue_alloc,
        queue_request=queue_request,
        queue_valid=queue_valid,
        total=total,
        quanta=spec.quanta.astype(np.float32),
    )
    meta = SnapshotMeta(
        spec=spec,
        task_keys=task_keys,
        node_names=node_names,
        job_uids=job_uids,
        queue_names=queue_names,
        label_pair_bit=label_pair_bit,
        taint_bit=taint_bit,
        n_tasks=nT,
        n_nodes=nN,
        n_jobs=nJ,
        n_queues=nQ,
        task_objs=task_objs,
        job_objs=list(jobs),
        node_objs=list(nodes),
        task_resreq64=task_resreq64,
        task_needs_host=task_needs_host,
    )
    return snap, meta


class _TaintView:
    """Duck-typed taint for Toleration.tolerates during interning."""

    __slots__ = ("key", "value", "effect")

    def __init__(self, key: str, value: str, effect: str):
        self.key = key
        self.value = value
        self.effect = effect
