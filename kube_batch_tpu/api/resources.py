"""Vectorized multi-resource arithmetic with the reference's epsilon semantics.

Mirrors pkg/scheduler/api/resource_info.go. The reference models a resource
amount as {MilliCPU float64, Memory float64, ScalarResources map[name]float64,
MaxTaskNum int} with minimum comparison quanta of 10 milliCPU / 10 MiB /
10 milli-scalar (resource_info.go:70-72) so that sub-quantum residues never
flip a fit decision.

The TPU-native design replaces the struct+map with a dense float64 vector over
a fixed, cluster-wide ``ResourceSpec`` axis so that a whole cluster snapshot
becomes a [N, R] array that kernels can consume directly. Two deliberate
deviations, both documented where they matter:

- "pods" (the reference's separate ``MaxTaskNum``, resource_info.go:36) is an
  ordinary dimension here with a per-task request of 1, so the max-pods
  predicate (predicates.go:162-166) falls out of the same resource-fit kernel.
- scalar resources (nvidia.com/gpu etc.) are stored in *milli* units just like
  the reference (resource_info.go:111 value.MilliValue()).
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping, Optional, Sequence, Tuple

import numpy as np

from kube_batch_tpu.native import fast as _native
from kube_batch_tpu.utils.assertions import graft_assert

_LIB = _native.resource_lib  # None → numpy fallback (semantics identical)

# Minimum comparison quanta, resource_info.go:66-72.
_F64 = np.dtype(np.float64)

MIN_MILLI_CPU = 10.0
MIN_MEMORY = 10.0 * 1024 * 1024  # 10 MiB
MIN_MILLI_SCALAR = 10.0
MIN_PODS = 0.1  # pods are integral; anything below one pod is "empty"

CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
GPU = "nvidia.com/gpu"

# The fixed head of every ResourceSpec axis is (cpu, memory, pods, *scalars);
# PODS_INDEX is the one capacity-only dimension excluded from semantic
# comparisons (Less/IsEmpty/Share and every fairness verdict — the
# reference's Resource has no pods dim, resource_info.go:30-40).  Device-side
# code (ops/fairness.py) masks the same index; this constant is the single
# source of truth for that layout fact.
PODS_INDEX = 2


class ResourceSpec:
    """The fixed resource axis of a cluster: (cpu, memory, pods, *scalars).

    All Resource vectors, snapshot tensors, and kernels in one cluster share a
    single spec so that dimension k always means the same resource. The
    reference gets this implicitly from its struct fields + scalar map; we need
    it explicit to build dense [T, R] / [N, R] arrays.
    """

    def __init__(self, scalar_names: Sequence[str] = (GPU,)):
        names = [CPU, MEMORY, PODS]
        for s in scalar_names:
            if s in names:
                raise ValueError(f"duplicate resource name {s!r}")
            names.append(s)
        self.names: Tuple[str, ...] = tuple(names)
        self._index: Dict[str, int] = {n: i for i, n in enumerate(self.names)}
        quanta = [MIN_MILLI_CPU, MIN_MEMORY, MIN_PODS]
        quanta += [MIN_MILLI_SCALAR] * len(scalar_names)
        self.quanta: np.ndarray = np.ascontiguousarray(quanta, dtype=np.float64)
        self._quanta_addr = self.quanta.ctypes.data
        # "pods" is a capacity-only dimension we add on top of the reference's
        # model (its MaxTaskNum field); it participates in fit arithmetic
        # (add/sub/less_equal) but not in the semantic comparisons the
        # reference defines over {cpu, memory, scalars} (Less / IsEmpty /
        # Share), where an always-equal dimension would change the answer.
        self.semantic_mask: np.ndarray = np.ones(len(names), dtype=bool)
        self.semantic_mask[PODS_INDEX] = False
        self._mask_addr = self.semantic_mask.ctypes.data

    @property
    def n(self) -> int:
        return len(self.names)

    def index(self, name: str) -> int:
        return self._index[name]

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def __eq__(self, other) -> bool:
        return isinstance(other, ResourceSpec) and self.names == other.names

    def __hash__(self) -> int:
        return hash(self.names)

    def __repr__(self) -> str:
        return f"ResourceSpec({self.names})"

    def __reduce__(self):
        # rebuild through __init__ so cached buffer addresses are fresh
        return (ResourceSpec, (self.names[3:],))

    # -- constructors -----------------------------------------------------
    def empty(self) -> "Resource":
        # np.zeros is already contiguous f64 — take the raw path
        # (hot: every JobInfo/NodeInfo construction allocates empties)
        return _raw_resource(np.zeros(self.n), self)

    def build(
        self,
        cpu_milli: float = 0.0,
        memory: float = 0.0,
        pods: float = 0.0,
        scalars: Optional[Mapping[str, float]] = None,
    ) -> "Resource":
        """Build a Resource (the NewResource analog, resource_info.go:99-127).

        ``cpu_milli`` in milli-cores, ``memory`` in bytes, scalars in milli
        units keyed by spec name.
        """
        vec = np.zeros(self.n)
        vec[0] = float(cpu_milli)
        vec[1] = float(memory)
        vec[2] = float(pods)
        if scalars:
            for name, v in scalars.items():
                if name not in self._index:
                    raise KeyError(
                        f"scalar resource {name!r} not in cluster ResourceSpec {self.names}"
                    )
                vec[self._index[name]] = float(v)
        return Resource(vec, self)

    def from_vec(self, vec: np.ndarray) -> "Resource":
        return Resource(np.asarray(vec, dtype=np.float64).copy(), self)

    def wrap_vec(self, vec: np.ndarray) -> "Resource":
        """Resource over `vec` WITHOUT copying — for freshly-computed rows the
        caller owns and will not mutate (the allocate replay's segment sums).
        Use from_vec for foreign arrays. The row must already be contiguous
        float64 (rows of C-order float64 matrices are) — the slow setter
        normalizes anything else."""
        if vec.dtype == _F64 and vec.flags.c_contiguous:
            return _raw_resource(vec, self)
        return Resource(vec, self)


def _raw_resource(vec: np.ndarray, spec: "ResourceSpec") -> "Resource":
    """Construct a Resource over an already-contiguous float64 buffer,
    bypassing __init__'s normalization. The ONLY place (besides the .vec
    setter) that maintains the __slots__ triple and the _addr↔buffer
    invariant the native C fast path depends on."""
    r = Resource.__new__(Resource)
    r._vec = vec
    r.spec = spec
    r._addr = vec.ctypes.data
    return r


DEFAULT_SPEC = ResourceSpec()


class Resource:
    """A point on the resource-spec axis; arithmetic mirrors resource_info.go.

    Immutable-by-convention: operators return new Resources; the in-place
    mutators (add_, sub_, set_max_) are explicit and used only by the
    accounting algebra in NodeInfo/JobInfo, like the reference's pointer
    receivers.
    """

    __slots__ = ("_vec", "spec", "_addr")

    def __init__(self, vec: np.ndarray, spec: ResourceSpec):
        self.vec = vec
        self.spec = spec

    @property
    def vec(self) -> np.ndarray:
        return self._vec

    @vec.setter
    def vec(self, value) -> None:
        # contiguous float64 — the native fast path reads the raw buffer via
        # the cached address, which this setter keeps in sync on rebinding
        self._vec = np.ascontiguousarray(value, dtype=np.float64)
        self._addr = self._vec.ctypes.data

    def __reduce__(self):
        # pickle/deepcopy rebuild through __init__ so _addr points at the
        # new process/copy's buffer, never the original's
        return (Resource, (self._vec.copy(), self.spec))

    # -- accessors --------------------------------------------------------
    @property
    def milli_cpu(self) -> float:
        return float(self.vec[0])

    @property
    def memory(self) -> float:
        return float(self.vec[1])

    @property
    def pods(self) -> float:
        return float(self.vec[2])

    def get(self, name: str) -> float:
        return float(self.vec[self.spec.index(name)])

    def clone(self) -> "Resource":
        # hot in cache.snapshot's deep clone — a copy of a contiguous f64
        # buffer is already one; take the raw path
        return _raw_resource(self._vec.copy(), self.spec)

    # -- predicates (resource_info.go:134-160) ----------------------------
    def is_empty(self) -> bool:
        """True iff every semantic dimension (cpu/mem/scalars, not pods) is
        below its minimum quantum (resource_info.go:134-148)."""
        m = self.spec.semantic_mask
        return bool(np.all(self.vec[m] < self.spec.quanta[m]))

    def is_zero(self, name: str) -> bool:
        """True iff the named dimension is below its quantum
        (resource_info.go:151-160)."""
        i = self.spec.index(name)
        return bool(self.vec[i] < self.spec.quanta[i])

    # -- arithmetic -------------------------------------------------------
    def _check(self, other: "Resource") -> None:
        if self.spec is not other.spec:  # identity fast path — specs are shared
            graft_assert(self.spec == other.spec, "resource spec mismatch")

    def add(self, other: "Resource") -> "Resource":
        self._check(other)
        return Resource(self.vec + other.vec, self.spec)

    def add_(self, other: "Resource") -> "Resource":
        self._check(other)
        if _LIB is not None:
            _LIB.kb_add_(self._addr, other._addr, self.vec.size)
        else:
            np.add(self.vec, other.vec, out=self.vec)
        return self

    def sub(self, other: "Resource") -> "Resource":
        """Subtract, asserting no dimension underflows (resource_info.go:180-190:
        Sub panics via assert when left < right)."""
        return self.clone().sub_(other)

    def sub_(self, other: "Resource") -> "Resource":
        self._check(other)
        if not other.less_equal(self):  # message built only on failure
            graft_assert(False, f"resource underflow: {other} not <= {self}")
        if _LIB is not None:
            _LIB.kb_sub_clamped_(self._addr, other._addr, self.vec.size)
        else:
            np.subtract(self.vec, other.vec, out=self.vec)
            np.maximum(self.vec, 0.0, out=self.vec)
        return self

    def multi(self, ratio: float) -> "Resource":
        """Scale every dimension (resource_info.go:193-202)."""
        return Resource(self.vec * ratio, self.spec)

    def set_max_(self, other: "Resource") -> "Resource":
        """Elementwise max, in place (resource_info.go:205-221 SetMaxResource)."""
        self._check(other)
        if _LIB is not None:
            _LIB.kb_set_max_(self._addr, other._addr, self.vec.size)
        else:
            np.maximum(self.vec, other.vec, out=self.vec)
        return self

    def min(self, other: "Resource") -> "Resource":
        """Elementwise min (resource_info.go:330-341 MinDimensionResource-ish)."""
        self._check(other)
        return Resource(np.minimum(self.vec, other.vec), self.spec)

    def fit_delta(self, other: "Resource") -> "Resource":
        """Per-dimension shortfall of self (request) vs other (idle), used for
        NodesFitDelta diagnostics (resource_info.go:224-250 FitDelta): for each
        requested dimension that doesn't fit, record request − idle + quantum."""
        self._check(other)
        short = np.where(
            (self.vec > 0) & (self.vec > other.vec),
            self.vec - other.vec + self.spec.quanta,
            0.0,
        )
        return Resource(short, self.spec)

    def diff(self, other: "Resource") -> Tuple["Resource", "Resource"]:
        """(increased, decreased) per dimension (resource_info.go:300-327)."""
        self._check(other)
        d = self.vec - other.vec
        return (
            Resource(np.maximum(d, 0.0), self.spec),
            Resource(np.maximum(-d, 0.0), self.spec),
        )

    # -- comparisons (epsilon-tolerant, resource_info.go:253-297) ---------
    def less(self, other: "Resource") -> bool:
        """Strictly less in every semantic dimension (resource_info.go:253-266
        Less). cpu/mem always compare; a scalar dim participates only when the
        left side actually has some (the reference iterates the left's scalar
        map, so absent scalars are skipped — a dense vector can't distinguish
        absent from zero, and zero-vs-zero must not fail the comparison).
        pods is excluded entirely (ResourceSpec.semantic_mask)."""
        self._check(other)
        m = self.spec.semantic_mask.copy()
        m[3:] &= self.vec[3:] > 0
        return bool(np.all(self.vec[m] < other.vec[m]))

    def less_equal(self, other: "Resource") -> bool:
        """<= in every dimension, tolerating sub-quantum excess
        (resource_info.go:269-284 LessEqual: a dim passes if value <= other's
        or the difference is below the minimum quantum)."""
        self._check(other)
        if _LIB is not None:
            return bool(
                _LIB.kb_less_equal(
                    self._addr, other._addr, self.spec._quanta_addr, self.vec.size
                )
            )
        return bool(np.all((self.vec <= other.vec) | (self.vec - other.vec < self.spec.quanta)))

    def less_equal_semantic(self, other: "Resource") -> bool:
        """LessEqual over the semantic dims only (cpu/mem/scalars) — the
        reference's Resource has no pods dimension
        (resource_info.go:252-285), so fairness comparisons (proportion
        overused/reclaimable) must not let the capacity-only pods dim flip
        the verdict."""
        self._check(other)
        m = self.spec.semantic_mask
        d = self.vec[m]
        o = other.vec[m]
        return bool(np.all((d <= o) | (d - o < self.spec.quanta[m])))

    def less_equal_strict(self, other: "Resource") -> bool:
        self._check(other)
        if _LIB is not None:
            return bool(
                _LIB.kb_less_equal_strict(self._addr, other._addr, self.vec.size)
            )
        return bool(np.all(self.vec <= other.vec))

    def share(self, total: "Resource") -> float:
        """Dominant share: max over dimensions of self/total, ignoring empty
        totals (helpers/helpers.go:28-60 GetShare + drf.go:161-171)."""
        self._check(total)
        m = self.spec.semantic_mask
        if _LIB is not None:
            return float(
                _LIB.kb_share(
                    self._addr, total._addr, self.spec._mask_addr, self.vec.size
                )
            )
        with np.errstate(divide="ignore", invalid="ignore"):
            ratios = np.where(total.vec[m] > 0, self.vec[m] / total.vec[m], 0.0)
        return float(np.max(ratios)) if ratios.size else 0.0

    # -- dunder sugar -----------------------------------------------------
    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Resource)
            and self.spec == other.spec
            and bool(np.all(np.abs(self.vec - other.vec) < 1e-9))
        )

    def __hash__(self):
        raise TypeError("Resource is not hashable")

    def __repr__(self) -> str:
        parts = [
            f"{n}={self.vec[i]:g}"
            for i, n in enumerate(self.spec.names)
            if self.vec[i] != 0
        ]
        return f"Resource({', '.join(parts) or 'empty'})"
