"""TaskInfo — the scheduler's view of one pod.

Mirrors pkg/scheduler/api/job_info.go:36-124: UID, owning Job, Resreq (sum of
container requests), InitResreq (max of that sum with each init container,
pod_info.go:53-73), NodeName, Status, Priority, and a backref to the ingested
Pod object.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Set, Tuple

from kube_batch_tpu.api.pod import Pod, GROUP_NAME_ANNOTATION
from kube_batch_tpu.api.resources import Resource, ResourceSpec, PODS
from kube_batch_tpu.api.types import TaskStatus, pod_phase_to_status

logger = logging.getLogger("kube_batch_tpu")
_warned_unknown_scalars: Set[Tuple[Tuple[str, ...], str]] = set()


def job_id_for_pod(pod: Pod) -> str:
    """JobID for a pod (job_info.go:56-66): namespace/group-name if the
    group annotation is present; else the pod's controller UID
    (cache/util.go:42-46 — pods sharing an owner share a job, which is how a
    PodDisruptionBudget on the owner gangs them); else the pod's own
    namespace/name (a shadow single-task job will be synthesized)."""
    group = pod.group_name
    if group:
        return f"{pod.namespace}/{group}"
    if pod.owner:
        return f"{pod.namespace}/{pod.owner}"
    return f"{pod.namespace}/{pod.name}"


def _requests_to_resource(requests: Dict[str, float], spec: ResourceSpec) -> Resource:
    vec = spec.empty()
    for name, v in requests.items():
        if name in spec:
            vec.vec[spec.index(name)] = float(v)
        else:
            # The reference models every scalar it sees (resource_info.go:99-127);
            # our dense axis is fixed at cache construction, so an unmodeled
            # scalar can't gate placement — warn once so misconfigured specs
            # don't silently overcommit that resource.
            key = (spec.names, name)
            if key not in _warned_unknown_scalars:
                _warned_unknown_scalars.add(key)
                logger.warning(
                    "dropping request for resource %r not in cluster ResourceSpec %s",
                    name,
                    spec.names,
                )
    vec.vec[spec.index(PODS)] = 1.0  # every task occupies one pod slot
    return vec


class TaskInfo:
    __slots__ = (
        "uid",
        "job",
        "name",
        "namespace",
        "resreq",
        "init_resreq",
        "_node_name",
        "_status",
        "priority",
        "volume_ready",
        "pod",
        "_key",
        "_row",
        "_store",
    )

    def __init__(self, pod: Pod, spec: ResourceSpec):
        # column binding first: the status/node_name property setters below
        # mirror into the cache's ColumnStore once bound (api/columns.py)
        self._row: int = -1
        self._store = None
        self.uid: str = pod.uid
        self.job: str = job_id_for_pod(pod)
        self.name: str = pod.name
        self.namespace: str = pod.namespace
        # Resreq = sum of app-container requests (job_info.go:73-80)
        self.resreq: Resource = _requests_to_resource(pod.requests, spec)
        # InitResreq = max(Resreq, each init container) (pod_info.go:53-73);
        # ingest supplies the already-maxed init_requests map. Without init
        # containers InitResreq IS Resreq — share the object (Resources are
        # immutable-by-convention; snapshot build exploits the identity)
        if pod.init_requests:
            self.init_resreq: Resource = self.resreq.clone()
            self.init_resreq.set_max_(_requests_to_resource(pod.init_requests, spec))
        else:
            self.init_resreq = self.resreq
        self._node_name: Optional[str] = pod.node_name
        self._status: TaskStatus = pod_phase_to_status(pod.phase, pod.node_name, pod.deleting)
        self.priority: int = pod.priority
        self.volume_ready: bool = False
        self.pod: Pod = pod
        self._key: str = f"{pod.namespace}/{pod.name}"

    # ---- column-mirrored mutable state ----------------------------------
    # status and node_name are the two fields that change after ingest;
    # routing every write through these setters is what keeps the persistent
    # ColumnStore current no matter which code path mutates a task
    # (statements, bulk replay, residue revert, resync).
    @property
    def status(self) -> TaskStatus:
        return self._status

    @status.setter
    def status(self, value: TaskStatus) -> None:
        self._status = value
        store = self._store
        if store is not None:
            store.t_status[self._row] = int(value)

    @property
    def node_name(self) -> Optional[str]:
        return self._node_name

    @node_name.setter
    def node_name(self, value: Optional[str]) -> None:
        self._node_name = value
        store = self._store
        if store is not None:
            store.task_node_changed(self._row, value)

    @property
    def best_effort(self) -> bool:
        """BestEffort = empty InitResreq (is_empty already ignores the pods
        dimension) — these are skipped by allocate (allocate.go:126-129) and
        placed by backfill (backfill.go:55-89)."""
        return self.init_resreq.is_empty()

    @property
    def needs_host_predicate(self) -> bool:
        """True when the task carries constraints the device mask only
        approximates (snapshot.py's encoding notes): host ports, inter-pod
        (anti-)affinity, or node-affinity terms richer than one single-value
        In term. The allocate replay re-validates only these — everything
        else (ready/unschedulable nodes, selectors, taints, resource fit,
        max-pods) is exact on device."""
        pod = self.pod
        if pod.host_ports:
            return True
        aff = pod.affinity
        if aff is None:
            return False
        if aff.pod_affinity or aff.pod_anti_affinity:
            return True
        terms = aff.node_terms
        if not terms:
            return False
        if len(terms) > 1:
            return True
        return any(
            op != "In" or len(values) != 1 for (_, op, values) in terms[0]
        )

    def clone(self) -> "TaskInfo":
        """Copy with value semantics for the mutable fields (status,
        node_name).  resreq/init_resreq are SHARED, not copied: a task's
        request vectors are frozen at ingest (nothing in the tree mutates
        them in place — accounting always happens on node/job ledgers), and
        cloning them was the dominant cost of the cache snapshot and of the
        node-side task copies at the 50k scale.  Anyone adding in-place
        mutation of task resreq must restore the deep copy here."""
        t = TaskInfo.__new__(TaskInfo)
        t._row = -1       # clones are never column-bound (isolated sessions)
        t._store = None
        t.uid = self.uid
        t.job = self.job
        t.name = self.name
        t.namespace = self.namespace
        t.resreq = self.resreq
        t.init_resreq = self.init_resreq
        t._node_name = self._node_name
        t._status = self._status
        t.priority = self.priority
        t.volume_ready = self.volume_ready
        t.pod = self.pod
        t._key = self._key
        return t

    def key(self) -> str:
        return self._key

    def __repr__(self) -> str:
        return (
            f"TaskInfo({self.namespace}/{self.name} job={self.job} "
            f"status={self.status.name} node={self.node_name} req={self.resreq})"
        )
