"""NodeInfo — per-node resource accounting with the three-way status algebra.

Mirrors pkg/scheduler/api/node_info.go:28-222. The critical piece is the
AddTask/RemoveTask algebra (node_info.go:165-222): a task's effect on the
node's (Idle, Used, Releasing) triple depends on its status —

    Releasing task:  Releasing += r ; Idle -= r ; Used += r
    Pipelined task:  Releasing -= r            ; Used += r
    other allocated: Idle -= r                 ; Used += r

so that "fits in Releasing" (allocate.go:176-184) means: the request fits in
resources that are on their way back. The same algebra is replicated
tensor-side in ops/assignment.py; this host copy is authoritative for ingest
and for the host-path actions (preempt/reclaim/backfill).
"""

from __future__ import annotations

import itertools
from typing import Dict, Optional

import numpy as np

from kube_batch_tpu.api.pod import Node
from kube_batch_tpu.api.resources import Resource, ResourceSpec, PODS
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.api.types import TaskStatus, is_allocated
from kube_batch_tpu.utils.assertions import graft_assert


def _node_resource(node: Node, spec: ResourceSpec, which: str) -> Resource:
    src = node.allocatable if which == "allocatable" else node.capacity
    r = spec.empty()
    for name, v in src.items():
        if name in spec:
            r.vec[spec.index(name)] = float(v)
    return r


class NodeInfo:
    def __init__(self, node: Optional[Node], spec: ResourceSpec):
        self.spec = spec
        self.name: str = node.name if node else ""
        self.node: Optional[Node] = node
        self.tasks: Dict[str, TaskInfo] = {}
        if node is not None:
            self.allocatable = _node_resource(node, spec, "allocatable")
            self.capability = _node_resource(node, spec, "capacity")
        else:
            self.allocatable = spec.empty()
            self.capability = spec.empty()
        self.idle = self.allocatable.clone()
        self.used = spec.empty()
        self.releasing = spec.empty()
        # status each resident task was ACCOUNTED under (see task algebra)
        self._acct: Dict[str, TaskStatus] = {}
        # ColumnStore binding (api/columns.py): when bound, the five ledger
        # Resources above are views into the store's [N, R] matrices
        self._cols = None
        self._row: int = -1
        self._set_state()

    # -- state machine (node_info.go:110-134) -----------------------------
    def _set_state(self) -> None:
        """setNodeState (node_info.go:110-134): UnInitialized when no node
        object yet, OutOfSync when resident pods use more than the node's
        allocatable, else Ready — NotReady nodes are excluded from snapshots
        (cache.go:595-597). The state is STORED, recomputed only on set_node
        like the reference: mid-session task churn must not flip readiness
        (a Pipelined overlay legitimately pushes used above allocatable
        while the capacity it borrows is still Releasing)."""
        if self.node is None:
            self._state = "UnInitialized"
        elif not self.used.less_equal(self.allocatable):
            self._state = "OutOfSync"
        elif not self.node.ready:
            self._state = "NotReady"
        else:
            self._state = "Ready"

    @property
    def state(self) -> str:
        return self._state

    @property
    def ready(self) -> bool:
        return self._state == "Ready"

    def set_node(self, node: Node) -> None:
        """Update the node object, rebuilding (Idle, Used, Releasing) from the
        new allocatable and replaying every resident task's status algebra
        (node_info.go:137-162 SetNode). The replay matters when pods were
        ingested before their node: their add_task skipped accounting because
        node was None.

        The replay is underflow-tolerant: when resident tasks use more than
        the new allocatable (pods landed before a smaller node object, or the
        node shrank), Idle clamps at zero and the `state` property reports
        OutOfSync — excluding the node from snapshots until usage reconciles
        (node_info.go:110-134; the reference instead skips the rebuild and
        keeps stale accounting — same observable contract, NotReady node)."""
        self.name = node.name
        self.node = node
        alloc = _node_resource(node, self.spec, "allocatable")
        cap = _node_resource(node, self.spec, "capacity")
        idle_v = alloc.vec.copy()
        used_v = self.spec.empty().vec
        rel_v = self.spec.empty().vec
        acct = self._acct
        for key, t in self.tasks.items():
            r = t.resreq.vec
            acct[key] = t.status  # re-account under the live status
            if t.status == TaskStatus.RELEASING:
                rel_v += r
                idle_v -= r
                used_v += r
            elif t.status == TaskStatus.PIPELINED:
                rel_v -= r
                used_v += r
            elif is_allocated(t.status):
                idle_v -= r
                used_v += r
            t.node_name = node.name
        np.maximum(idle_v, 0.0, out=idle_v)
        np.maximum(rel_v, 0.0, out=rel_v)
        if self._cols is None:
            self.allocatable = alloc
            self.capability = cap
            self.idle = Resource(idle_v, self.spec)
            self.used = Resource(used_v, self.spec)
            self.releasing = Resource(rel_v, self.spec)
        else:
            # column-bound: write through the ledger views in place so the
            # store's matrices stay the single source of truth; an actual
            # allocatable change invalidates the device-resident n_alloc
            if not np.array_equal(self.allocatable.vec, alloc.vec):
                self._cols.bump_node_features()
            self._note_ledger()
            self.allocatable.vec[:] = alloc.vec
            self.capability.vec[:] = cap.vec
            self.idle.vec[:] = idle_v
            self.used.vec[:] = used_v
            self.releasing.vec[:] = rel_v
        self._set_state()
        if self._cols is not None:
            self._cols.sync_node_meta(self)

    # -- task algebra (node_info.go:165-222) ------------------------------
    # The reference clones each task into the node ("Node will hold a copy
    # of task to make sure the status change will not impact resource in
    # node", node_info.go:165-168). Here the node stores the caller's task
    # object directly and records the status it ACCOUNTED under in the
    # `_acct` side table — remove_task reverses from _acct, so a later
    # in-place status mutation on the task still can't desynchronize the
    # algebra, and the 50k-placement replay skips 50k task clones. Readers
    # of node.tasks see live status (the reference's SetNode replay reads
    # live status the same way).
    def demote_to_placeholder(self) -> None:
        """Forget the Node object but KEEP the resident task registrations —
        the inverse of the pod-before-node ingest placeholder. Used when a
        node is deleted while pods are still bound to it: the tasks outlive
        the Node (the reference keeps their NodeName too), accounting zeroes
        out, the node drops out of snapshots (state UnInitialized), and a later
        re-add replays everything through set_node."""
        self.node = None
        if self._cols is None:
            # unbound: rebind fresh Resources — clones share allocatable/
            # capability objects and must not see the zeroing
            self.allocatable = self.spec.empty()
            self.capability = self.spec.empty()
            self.idle = self.spec.empty()
            self.used = self.spec.empty()
            self.releasing = self.spec.empty()
        else:
            # column-bound: the ledger views are the store's matrices —
            # zero them in place.  n_alloc is a CACHED feature column and
            # sync_node_meta early-returns below (no Node object), so the
            # invalidation must happen here
            for res in (self.idle, self.used, self.releasing,
                        self.allocatable, self.capability):
                res.vec[:] = 0.0
            self._cols.bump_node_features()
            self._note_ledger()
        self._set_state()
        if self._cols is not None:
            self._cols.sync_node_meta(self)

    def _note_ledger(self) -> None:
        """Dirty-row choke point: every (Idle, Used, Releasing, Allocatable)
        write funnels one mark to the ColumnStore so the device snapshot's
        float32 twins refresh exactly the touched rows
        (columns.node_ledgers32)."""
        if self._cols is not None:
            self._cols.note_node_ledger(self._row)

    def add_task(self, task: TaskInfo) -> None:
        key = task.key()
        graft_assert(key not in self.tasks, f"duplicate task {key} on node {self.name}")
        status = task.status
        if self.node is not None:
            self._note_ledger()
            r = task.resreq
            if status == TaskStatus.RELEASING:
                self.releasing.add_(r)
                self.idle.sub_(r)
                self.used.add_(r)
            elif status == TaskStatus.PIPELINED:
                self.releasing.sub_(r)
                self.used.add_(r)
            elif is_allocated(status):
                self.idle.sub_(r)
                self.used.add_(r)
            # terminal/pending statuses don't touch accounting
        task.node_name = self.name
        self.tasks[key] = task
        self._acct[key] = status

    def remove_task(self, task: TaskInfo) -> None:
        key = task.key()
        existing = self.tasks.get(key)
        graft_assert(existing is not None, f"task {key} not on node {self.name}")
        if existing is not None:
            status = self._acct.pop(key, existing.status)
            if self.node is not None:
                self._note_ledger()
                r = existing.resreq
                if status == TaskStatus.RELEASING:
                    self.releasing.sub_(r)
                    self.idle.add_(r)
                    self.used.sub_(r)
                elif status == TaskStatus.PIPELINED:
                    self.releasing.add_(r)
                    self.used.sub_(r)
                elif is_allocated(status):
                    self.idle.add_(r)
                    self.used.sub_(r)
        self.tasks.pop(key, None)

    def update_task(self, task: TaskInfo) -> None:
        """delete + add (node_info.go:225-233)."""
        self.remove_task(task)
        self.add_task(task)

    def bulk_add_tasks(self, alloc_tasks, pipe_tasks, alloc_sum, pipe_sum) -> None:
        """Batched add_task for the vectorized allocate replay.  `alloc_tasks`
        carry an AllocatedStatus, `pipe_tasks` are Pipelined; `alloc_sum` /
        `pipe_sum` are the presummed Resources over each group.  The status
        algebra (node_info.go:165-222) collapses to two vector ops per group;
        per-task work is the dict insert + _acct record."""
        tasks = self.tasks
        acct = self._acct
        name = self.name
        for group in (alloc_tasks, pipe_tasks):
            for task in group:
                key = task._key
                if key in tasks:  # avoid building the message on the hot path
                    graft_assert(False, f"duplicate task {key} on node {self.name}")
                task.node_name = name
                tasks[key] = task
                acct[key] = task.status
        if self.node is not None:
            self._note_ledger()
            self.idle.sub_(alloc_sum)
            self.used.add_(alloc_sum)
            self.used.add_(pipe_sum)
            self.releasing.sub_(pipe_sum)

    def bulk_register_tasks(self, alloc_tasks, pipe_tasks) -> None:
        """Task-dict/acct registration ONLY, for the columnar allocate
        replay: the (Idle, Used, Releasing) algebra was already applied to
        this node's ledger views by whole-matrix column ops.  End state
        equals bulk_add_tasks'."""
        tasks = self.tasks
        acct = self._acct
        name = self.name
        for group, status in (
            (alloc_tasks, TaskStatus.BINDING),
            (pipe_tasks, TaskStatus.PIPELINED),
        ):
            for task in group:
                key = task._key
                if key in tasks:
                    graft_assert(False, f"duplicate task {key} on node {name}")
                task._node_name = name
                tasks[key] = task
                acct[key] = status

    def clone(self) -> "NodeInfo":
        # direct copy of the accounting triple instead of replaying every
        # resident task's status algebra (the triple already reflects it);
        # skips __init__ (which would rebuild allocatable/capability from
        # the node dicts) — allocatable/capability are rebound on set_node,
        # never mutated in place, so the clone shares them. Tasks ARE cloned:
        # the session mutates its copies' statuses in place.
        n = NodeInfo.__new__(NodeInfo)
        n._cols = None    # clones are never column-bound
        n._row = -1
        n.spec = self.spec
        n.name = self.name
        n.node = self.node
        # a bound node's allocatable/capability are live column views that
        # set_node mutates in place — the clone needs value semantics
        if self._cols is None:
            n.allocatable = self.allocatable
            n.capability = self.capability
        else:
            n.allocatable = self.allocatable.clone()
            n.capability = self.capability.clone()
        n.idle = self.idle.clone()
        n.used = self.used.clone()
        n.releasing = self.releasing.clone()
        n.tasks = {key: t.clone() for key, t in self.tasks.items()}
        n._acct = dict(self._acct)
        n._state = self._state  # stored state carries over (not recomputed)
        return n

    @property
    def pod_count(self) -> int:
        return len(self.tasks)

    def __repr__(self) -> str:
        return f"NodeInfo({self.name} idle={self.idle} used={self.used} releasing={self.releasing})"
