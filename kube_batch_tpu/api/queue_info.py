"""QueueInfo (pkg/scheduler/api/queue_info.go:29-48): UID=name, Weight, and a
backref to the Queue object (whose Capability caps the queue in proportion's
JobEnqueueable check, proportion.go:211-233)."""

from __future__ import annotations

from kube_batch_tpu.api.pod import Queue


class QueueInfo:
    def __init__(self, queue: Queue):
        self.uid: str = queue.name
        self.name: str = queue.name
        self.weight: int = max(int(queue.weight), 1)
        self.queue: Queue = queue
        self._cols = None  # ColumnStore binding (api/columns.py)
        self._row: int = -1

    def clone(self) -> "QueueInfo":
        return QueueInfo(self.queue)

    def __repr__(self) -> str:
        return f"QueueInfo({self.name} weight={self.weight})"
