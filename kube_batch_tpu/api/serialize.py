"""JSON (de)serialization for the ingest object model.

The reference's ingest protocol is the Kubernetes API server's watch/write
JSON (cache.go:256-336). The standalone analog is this module: every object
the cache consumes (Pod, PodGroup, Queue, Node, PriorityClass) round-trips
through plain JSON dicts, used by the HTTP ingest API (cmd/server.py) and the
queue CLI (cli/queue.py)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

from kube_batch_tpu.api.pod import (
    Affinity,
    Node,
    Pod,
    PodAffinityTerm,
    PodGroup,
    PodGroupCondition,
    PriorityClass,
    Queue,
    Taint,
    Toleration,
)
from kube_batch_tpu.api.types import PodGroupPhase, PodPhase


def _clean(d: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in d.items() if v not in (None, {}, [], (), "")}


def pod_to_dict(pod: Pod) -> Dict[str, Any]:
    d = dataclasses.asdict(pod)
    d["phase"] = pod.phase.value if pod.phase else None
    if pod.affinity is not None:
        d["affinity"] = {
            "node_terms": [
                [[k, op, list(vals)] for (k, op, vals) in term]
                for term in pod.affinity.node_terms
            ],
            "pod_affinity": [dataclasses.asdict(t) for t in pod.affinity.pod_affinity],
            "pod_anti_affinity": [
                dataclasses.asdict(t) for t in pod.affinity.pod_anti_affinity
            ],
            "preferred_node_terms": [
                [w, [[k, op, list(vals)] for (k, op, vals) in term]]
                for (w, term) in pod.affinity.preferred_node_terms
            ],
            "preferred_pod_affinity": [
                [w, dataclasses.asdict(t)]
                for (w, t) in pod.affinity.preferred_pod_affinity
            ],
            "preferred_pod_anti_affinity": [
                [w, dataclasses.asdict(t)]
                for (w, t) in pod.affinity.preferred_pod_anti_affinity
            ],
        }
    d["host_ports"] = list(pod.host_ports)
    return _clean(d)


def pod_from_dict(d: Dict[str, Any]) -> Pod:
    d = dict(d)
    if "phase" in d:
        d["phase"] = PodPhase(d["phase"])
    if "tolerations" in d:
        d["tolerations"] = [Toleration(**t) for t in d["tolerations"]]
    if "affinity" in d and d["affinity"] is not None:
        d["affinity"] = Affinity(
            node_terms=[
                [(k, op, tuple(vals)) for (k, op, vals) in term]
                for term in d["affinity"].get("node_terms", [])
            ],
            pod_affinity=[
                PodAffinityTerm(**t) for t in d["affinity"].get("pod_affinity", [])
            ],
            pod_anti_affinity=[
                PodAffinityTerm(**t)
                for t in d["affinity"].get("pod_anti_affinity", [])
            ],
            preferred_node_terms=[
                (w, [(k, op, tuple(vals)) for (k, op, vals) in term])
                for (w, term) in d["affinity"].get("preferred_node_terms", [])
            ],
            preferred_pod_affinity=[
                (w, PodAffinityTerm(**t))
                for (w, t) in d["affinity"].get("preferred_pod_affinity", [])
            ],
            preferred_pod_anti_affinity=[
                (w, PodAffinityTerm(**t))
                for (w, t) in d["affinity"].get("preferred_pod_anti_affinity", [])
            ],
        )
    if "host_ports" in d:
        d["host_ports"] = tuple(d["host_ports"])
    if "volume_claims" in d:
        d["volume_claims"] = tuple(d["volume_claims"])
    return Pod(**d)


def node_to_dict(node: Node) -> Dict[str, Any]:
    return _clean(dataclasses.asdict(node))  # _clean keeps booleans


def node_from_dict(d: Dict[str, Any]) -> Node:
    d = dict(d)
    if "taints" in d:
        d["taints"] = [Taint(**t) for t in d["taints"]]
    return Node(**d)


def pod_group_to_dict(pg: PodGroup) -> Dict[str, Any]:
    d = dataclasses.asdict(pg)
    d["phase"] = pg.phase.value if pg.phase is not None else None
    return _clean(d)


def pod_group_from_dict(d: Dict[str, Any]) -> PodGroup:
    d = dict(d)
    if d.get("phase") is not None:
        d["phase"] = PodGroupPhase(d["phase"])
    if "conditions" in d:
        d["conditions"] = [PodGroupCondition(**c) for c in d["conditions"]]
    return PodGroup(**d)


def queue_to_dict(q: Queue) -> Dict[str, Any]:
    return _clean(dataclasses.asdict(q))


def queue_from_dict(d: Dict[str, Any]) -> Queue:
    return Queue(**d)


def priority_class_from_dict(d: Dict[str, Any]) -> PriorityClass:
    return PriorityClass(**d)
