"""JobInfo — a gang (PodGroup) of tasks with status-indexed accounting.

Mirrors pkg/scheduler/api/job_info.go:127-418 and unschedule_info.go:22-112:
the per-status task index, allocated/total-request aggregates, MinAvailable
gang threshold, Ready()/Pipelined() predicates, and fit-error bookkeeping.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Optional

from kube_batch_tpu.api.pod import PodGroup, PodGroupCondition
from kube_batch_tpu.api.resources import Resource, ResourceSpec
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.api.types import TaskStatus, is_allocated
from kube_batch_tpu.utils.assertions import graft_assert


class FitError:
    """Why one task failed on one node (unschedule_info.go:40-71)."""

    def __init__(self, task: TaskInfo, node_name: str, reasons: list[str]):
        self.task_namespace = task.namespace
        self.task_name = task.name
        self.node_name = node_name
        self.reasons = reasons

    def error(self) -> str:
        return f"task {self.task_namespace}/{self.task_name} on node {self.node_name} fit failed: {', '.join(self.reasons)}"


class FitErrors:
    """Per-task node→FitError map with a reason histogram rendering
    (unschedule_info.go:74-112). Two fill paths: per-node errors from host
    predicate loops, or a pre-aggregated reason histogram straight from the
    device solve (ops/feasibility.failure_histogram)."""

    def __init__(self):
        self.nodes: Dict[str, FitError] = {}
        self._hist: Dict[str, int] = {}
        self._n_nodes = 0

    def set_node_error(self, node_name: str, err: FitError) -> None:
        self.nodes[node_name] = err

    def set_histogram(self, counts: Dict[str, int], n_nodes: int) -> None:
        self._hist = {r: int(n) for r, n in counts.items() if n}
        self._n_nodes = n_nodes

    def error(self) -> str:
        hist: Dict[str, int] = defaultdict(int, self._hist)
        for fe in self.nodes.values():
            for r in fe.reasons:
                hist[r] += 1
        n = max(len(self.nodes), self._n_nodes)
        reasons = "; ".join(f"{n_} {r}" for r, n_ in sorted(hist.items(), key=lambda kv: kv[0]))
        return f"0/{n} nodes are available, {reasons}." if hist else ""


class JobInfo:
    def __init__(self, uid: str, spec: ResourceSpec, pod_group: Optional[PodGroup] = None):
        self.uid = uid
        self.spec = spec
        self.name = ""
        self.namespace = ""
        self.queue: str = ""
        self.priority: int = 0
        self.min_available: int = 0
        self.tasks: Dict[str, TaskInfo] = {}
        # TaskStatusIndex (job_info.go:141): status → {taskKey: task}
        self.task_status_index: Dict[TaskStatus, Dict[str, TaskInfo]] = defaultdict(dict)
        self.allocated: Resource = spec.empty()
        self.total_request: Resource = spec.empty()
        # sum of Pending tasks' resreq — the ledger proportion's session-open
        # reads instead of walking every task (proportion.go:87-99)
        self.pending_request: Resource = spec.empty()
        self.nodes_fit_delta: Dict[str, Resource] = {}
        self.nodes_fit_errors: Dict[str, FitErrors] = {}  # taskUID → FitErrors
        self.job_fit_errors: str = ""
        self.pod_group: Optional[PodGroup] = None
        self.pdb = None  # legacy gang source (job_info.go:199-212 SetPDB)
        self.creation_index: int = 0
        # ColumnStore binding (api/columns.py): when bound, the three ledger
        # Resources above are views into the store's [J, R] matrices and the
        # index choke points mirror per-status counts into j_counts
        self._cols = None
        self._row: int = -1
        if pod_group is not None:
            self.set_pod_group(pod_group)

    # -- podgroup wiring (job_info.go:171-208) ----------------------------
    def set_pod_group(self, pg: PodGroup) -> None:
        self.name = pg.name
        self.namespace = pg.namespace
        self.min_available = pg.min_member
        self.queue = pg.queue
        self.creation_index = pg.creation_index
        self.pod_group = pg

    # -- pdb wiring (job_info.go:199-212) ---------------------------------
    def set_pdb(self, pdb) -> None:
        self.name = pdb.name
        self.namespace = pdb.namespace
        self.min_available = pdb.min_available
        self.creation_index = pdb.creation_index
        self.pdb = pdb

    def unset_pdb(self) -> None:
        self.pdb = None

    def _note_alloc(self) -> None:
        """Allocated-ledger dirty choke: the job's `allocated` Resource is
        a zero-copy view of its ColumnStore j_alloc row, so every add_/sub_
        writes the column directly — this note keeps the device snapshot's
        f32 twin (columns.job_alloc32) refreshing exactly the touched
        rows."""
        if self._cols is not None and self._row >= 0:
            self._cols.note_job_alloc(self._row)

    # -- task bookkeeping (job_info.go:211-263) ---------------------------
    def _index_add(self, task: TaskInfo) -> None:
        self.task_status_index[task.status][task.key()] = task
        if self._cols is not None:
            self._cols.j_counts[self._row, int(task.status)] += 1
            self._cols.j_touched[self._row] = True

    def _index_remove(self, task: TaskInfo) -> None:
        bucket = self.task_status_index.get(task.status)
        if bucket is not None:
            popped = bucket.pop(task.key(), None)
            if not bucket:
                del self.task_status_index[task.status]
            if popped is not None and self._cols is not None:
                self._cols.j_counts[self._row, int(task.status)] -= 1
                self._cols.j_touched[self._row] = True

    def add_task(self, task: TaskInfo) -> None:
        key = task.key()
        graft_assert(key not in self.tasks, f"duplicate task {key} in job {self.uid}")
        self.tasks[key] = task
        self._index_add(task)
        if is_allocated(task.status):
            self.allocated.add_(task.resreq)
            self._note_alloc()
        elif task.status == TaskStatus.PENDING:
            self.pending_request.add_(task.resreq)
        self.total_request.add_(task.resreq)

    def delete_task(self, task: TaskInfo) -> None:
        key = task.key()
        existing = self.tasks.get(key)
        graft_assert(existing is not None, f"task {key} not in job {self.uid}")
        if existing is None:
            return
        if is_allocated(existing.status):
            self.allocated.sub_(existing.resreq)
            self._note_alloc()
        elif existing.status == TaskStatus.PENDING:
            self.pending_request.sub_(existing.resreq)
        self.total_request.sub_(existing.resreq)
        self._index_remove(existing)
        del self.tasks[key]

    def update_task_status(self, task: TaskInfo, status: TaskStatus) -> None:
        """delete + re-add under the new status so indices and aggregates stay
        consistent (job_info.go:250-263).

        `task` may be a clone of the resident object (preempt/reclaim evict
        cloned victims, like the reference's session copies) — the clone then
        becomes the canonical object, so it inherits the replaced object's
        ColumnStore row."""
        key = task.key()
        existing = self.tasks.get(key)
        if existing is not None:
            self.delete_task(existing)
            if existing is not task:
                store = getattr(existing, "_store", None)
                if store is not None and task._store is None:
                    store.adopt_task_row(existing, task)
        task.status = status
        self.add_task(task)

    def bulk_transition(self, tasks, status: TaskStatus, resreq_sum,
                        pending_sum=None) -> None:
        """Batched update_task_status for the vectorized allocate replay:
        move `tasks` (members of this job) to `status`, with `resreq_sum` the
        presummed Resource over those whose allocated-ness flips.  End state
        is identical to calling update_task_status per task; the per-task
        Resource add_/sub_ churn (delete+add cancels on total_request, and
        allocated changes only on the is_allocated flip) collapses into one
        vector op. `pending_sum` optionally presums the resreq of moved tasks
        that were Pending (for the pending_request ledger); computed here
        when absent."""
        if not tasks:
            return
        new_alloc = is_allocated(status)
        idx = self.task_status_index
        new_bucket = idx[status]
        pend_delta = None  # resreq sum of tasks leaving/entering Pending
        # wholesale fast path: the batch IS an entire source bucket moving
        # into an empty destination (the common shape — a fully-placed gang's
        # Pending bucket becoming Binding): rebind the dict instead of
        # popping/inserting per task
        src_status = tasks[0].status
        src_bucket = idx.get(src_status)
        if (
            not new_bucket
            and src_bucket is not None
            and len(src_bucket) == len(tasks)
            and src_status != status
            and all(t.status == src_status for t in tasks)
        ):
            del idx[src_status]
            idx[status] = src_bucket
            if self._cols is not None:
                counts = self._cols.j_counts[self._row]
                counts[int(src_status)] -= len(tasks)
                counts[int(status)] += len(tasks)
                self._cols.j_touched[self._row] = True
            flipped = len(tasks) if is_allocated(src_status) != new_alloc else 0
            pend_src = src_status == TaskStatus.PENDING
            new_pend = status == TaskStatus.PENDING
            for task in tasks:
                task.status = status
            if pend_src != new_pend:
                acc = pending_sum
                if acc is None:
                    acc = self.spec.empty()
                    for task in tasks:
                        acc.add_(task.resreq)
                if pend_src:
                    pend_delta = acc        # leaving Pending
                else:
                    self.pending_request.add_(acc)  # entering Pending
        else:
            flipped = 0
            new_pend = status == TaskStatus.PENDING
            pend_acc = None
            counts = (
                self._cols.j_counts[self._row] if self._cols is not None else None
            )
            if self._cols is not None:
                self._cols.j_touched[self._row] = True
            for task in tasks:
                key = task._key
                was_pend = task.status == TaskStatus.PENDING
                bucket = idx.get(task.status)
                if bucket is not None:
                    popped = bucket.pop(key, None)
                    if not bucket and bucket is not new_bucket:
                        del idx[task.status]
                    if popped is not None and counts is not None:
                        counts[int(task.status)] -= 1
                if counts is not None:
                    counts[int(status)] += 1
                if is_allocated(task.status) != new_alloc:
                    flipped += 1
                if was_pend != new_pend:
                    if new_pend:
                        self.pending_request.add_(task.resreq)
                    else:
                        if pend_acc is None:
                            pend_acc = self.spec.empty()
                        pend_acc.add_(task.resreq)
                task.status = status
                new_bucket[key] = task
            if pend_acc is not None:
                pend_delta = pend_acc
        if pend_delta is not None:
            self.pending_request.sub_(pend_delta)
        if flipped:
            graft_assert(
                flipped == len(tasks),
                f"bulk_transition: mixed allocated-ness flip in job {self.uid}",
            )
            if new_alloc:
                self.allocated.add_(resreq_sum)
            else:
                self.allocated.sub_(resreq_sum)
            self._note_alloc()

    def rebucket_moved(self, tasks, status: TaskStatus) -> None:
        """Status-index bucket moves ONLY, for the columnar allocate replay:
        ledgers, counts, and the t_status column were already updated by
        whole-matrix ops (actions/allocate.py), so this touches nothing but
        the bucket dicts and the raw _status attrs.  End state equals
        bulk_transition's."""
        if not tasks:
            return
        idx = self.task_status_index
        new_bucket = idx[status]
        src_status = tasks[0]._status
        src_bucket = idx.get(src_status)
        if (
            not new_bucket
            and src_bucket is not None
            and len(src_bucket) == len(tasks)
            and src_status != status
        ):
            del idx[src_status]
            idx[status] = src_bucket
            for t in tasks:
                t._status = status
        else:
            for t in tasks:
                b = idx.get(t._status)
                if b is not None:
                    b.pop(t._key, None)
                    if not b and b is not new_bucket:
                        del idx[t._status]
                t._status = status
                new_bucket[t._key] = t

    # -- gang predicates (job_info.go:367-418) ----------------------------
    def task_num(self, *statuses: TaskStatus) -> int:
        idx = self.task_status_index
        n = 0
        for s in statuses:
            bucket = idx.get(s)
            if bucket is not None:
                n += len(bucket)
        return n

    @property
    def ready_task_num(self) -> int:
        """Tasks counting toward gang readiness (job_info.go:367-380
        ReadyTaskNum): AllocatedStatus (Bound+Binding+Running+Allocated) plus
        Succeeded."""
        return self.task_num(
            TaskStatus.BOUND,
            TaskStatus.BINDING,
            TaskStatus.RUNNING,
            TaskStatus.ALLOCATED,
            TaskStatus.SUCCEEDED,
        )

    @property
    def waiting_task_num(self) -> int:
        """Pipelined tasks (job_info.go:383-391)."""
        return self.task_num(TaskStatus.PIPELINED)

    @property
    def valid_task_num(self) -> int:
        """Tasks that can count toward the gang (job_info.go:394-409
        ValidTaskNum): AllocatedStatus + Succeeded + Pipelined + Pending.
        Releasing/Failed/Unknown tasks are not valid gang members."""
        return self.task_num(
            TaskStatus.PENDING,
            TaskStatus.ALLOCATED,
            TaskStatus.PIPELINED,
            TaskStatus.BINDING,
            TaskStatus.BOUND,
            TaskStatus.RUNNING,
            TaskStatus.SUCCEEDED,
        )

    def ready(self) -> bool:
        return self.ready_task_num >= self.min_available

    def pipelined(self) -> bool:
        return self.ready_task_num + self.waiting_task_num >= self.min_available

    # -- diagnostics ------------------------------------------------------
    def fit_error(self) -> str:
        """Histogram of task statuses (job_info.go:347-364)."""
        counts = {s.name: len(m) for s, m in sorted(self.task_status_index.items())}
        body = ", ".join(f"{n} {s}" for s, n in counts.items())
        return f"job is not ready, {body}"

    def clone(self) -> "JobInfo":
        # fully manual copy, skipping __init__ (whose fresh Resource empties
        # and defaultdict would be immediately overwritten) — hot in
        # cache.snapshot at 50k tasks / 12.5k jobs
        j = JobInfo.__new__(JobInfo)
        j._cols = None    # clones are never column-bound
        j._row = -1
        j.uid = self.uid
        j.spec = self.spec
        j.name = self.name
        j.namespace = self.namespace
        j.queue = self.queue
        j.priority = self.priority
        j.min_available = self.min_available
        j.creation_index = self.creation_index
        j.pod_group = self.pod_group.clone() if self.pod_group else None
        j.pdb = self.pdb  # immutable-by-convention after ingest
        j.nodes_fit_delta = {}
        j.nodes_fit_errors = {}
        j.job_fit_errors = ""
        # direct index rebuild: add_task's per-task aggregate arithmetic
        # telescopes to a wholesale copy of the two ledgers (the clone is
        # exact by construction). Bucket-wise comprehensions beat per-task
        # defaultdict inserts.
        new_tasks = {key: t.clone() for key, t in self.tasks.items()}
        j.tasks = new_tasks
        j.task_status_index = defaultdict(dict)
        for status, bucket in self.task_status_index.items():
            if bucket:
                j.task_status_index[status] = {k: new_tasks[k] for k in bucket}
        j.allocated = self.allocated.clone()
        j.total_request = self.total_request.clone()
        j.pending_request = self.pending_request.clone()
        return j

    def __repr__(self) -> str:
        return (
            f"JobInfo({self.uid} queue={self.queue} min={self.min_available} "
            f"tasks={len(self.tasks)} ready={self.ready_task_num})"
        )
