"""List+watch front end: a Kubernetes API server → the SchedulerCache.

The standalone analog of the reference's informer wiring (cache.go:256-339):
for each resource, LIST once to seed the cache, then WATCH from the list's
resourceVersion, translating every event through k8s/translate.apply_event.
Reconnects with backoff on stream errors; a 410 Gone (stale resourceVersion)
re-lists, which is also how a restarted scheduler converges — the cache is
reconstructible from the API server exactly like the reference's
(SURVEY.md §5.4).

Transport is stdlib urllib with bearer-token + CA options, so the shim runs
in-cluster (serviceaccount token) or against a kubeconfig-style endpoint
without any Kubernetes client dependency.  The stream layer is injectable
(`stream_factory`) so tests drive recorded event lines through the exact
dispatch path.
"""

from __future__ import annotations

import json
import logging
import ssl
import threading
import urllib.request
from typing import Callable, Dict, Iterable, Optional, Tuple

from kube_batch_tpu.k8s.translate import apply_event

logger = logging.getLogger("kube_batch_tpu")

# resource kind → API path
RESOURCES: Dict[str, str] = {
    "pods": "/api/v1/pods",
    "nodes": "/api/v1/nodes",
    "podgroups": "/apis/scheduling.incubator.k8s.io/v1alpha1/podgroups",
    "queues": "/apis/scheduling.incubator.k8s.io/v1alpha1/queues",
    "poddisruptionbudgets": "/apis/policy/v1/poddisruptionbudgets",
    "priorityclasses": "/apis/scheduling.k8s.io/v1/priorityclasses",
}


class WatchAdapter:
    """Replays a cluster's state + changes into a SchedulerCache."""

    def __init__(
        self,
        cache,
        api_server: str = "https://kubernetes.default.svc",
        token: Optional[str] = None,
        token_file: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
        resources: Iterable[str] = tuple(RESOURCES),
        stream_factory: Optional[Callable] = None,
    ):
        self.cache = cache
        self.api_server = api_server.rstrip("/")
        self._token = token
        self._token_file = token_file
        self._ctx: Optional[ssl.SSLContext] = None
        if api_server.startswith("https"):
            self._ctx = ssl.create_default_context(cafile=ca_file)
            if insecure:
                self._ctx.check_hostname = False
                self._ctx.verify_mode = ssl.CERT_NONE
        self.resources = tuple(resources)
        # injectable for tests: kind → iterable of (event_type, object);
        # replaces the LIST+WATCH transport, not the dispatch
        self._stream_factory = stream_factory
        self._stop = threading.Event()
        self._threads: list = []

    # ---- transport ----------------------------------------------------
    def _headers(self) -> Dict[str, str]:
        tok = self._token
        if tok is None and self._token_file:
            with open(self._token_file) as f:
                tok = f.read().strip()
        return {"Authorization": f"Bearer {tok}"} if tok else {}

    def _get_json(self, path: str):
        req = urllib.request.Request(
            self.api_server + path, headers=self._headers()
        )
        with urllib.request.urlopen(req, context=self._ctx, timeout=60) as r:
            return json.load(r)

    def _watch_events(self, path: str):
        req = urllib.request.Request(
            self.api_server + path, headers=self._headers()
        )
        with urllib.request.urlopen(req, context=self._ctx, timeout=330) as r:
            for line in r:
                if line.strip():
                    yield json.loads(line)

    # ---- per-resource loop --------------------------------------------
    def _seed(self, kind: str) -> Optional[str]:
        """LIST → RECONCILE the cache against the listing; returns the
        collection's resourceVersion to watch from.

        A seed also runs after a 410 Gone against an already-populated
        cache, so items apply as upserts (MODIFIED — the cache handlers are
        add-or-update) and objects that vanished during the disconnect are
        deleted, or the scheduler would keep placing against phantom
        capacity."""
        listing = self._get_json(RESOURCES[kind])
        items = listing.get("items") or []
        for item in items:
            apply_event(self.cache, kind, "MODIFIED", item)
        self._reconcile_deletions(kind, items)
        return (listing.get("metadata") or {}).get("resourceVersion")

    def _reconcile_deletions(self, kind: str, items) -> None:
        def names():
            return {
                (i.get("metadata") or {}).get("namespace", "default")
                + "/" + (i.get("metadata") or {}).get("name", "")
                for i in items
            }

        cache = self.cache
        if kind == "pods":
            listed = names()
            for key in [k for k in cache.pods if k not in listed]:
                apply_event(cache, kind, "DELETED", {
                    "metadata": {"namespace": key.split("/", 1)[0],
                                 "name": key.split("/", 1)[1]},
                })
        elif kind == "nodes":
            listed = {(i.get("metadata") or {}).get("name", "") for i in items}
            for name in [n for n in cache.nodes if n not in listed]:
                cache.delete_node(name)
        elif kind == "queues":
            listed = {(i.get("metadata") or {}).get("name", "") for i in items}
            for name in [q for q in cache.queues if q not in listed]:
                cache.delete_queue(name)
        elif kind == "podgroups":
            listed = names()
            stale = [
                uid for uid, job in cache.jobs.items()
                if job.pod_group is not None and not job.pod_group.shadow
                and uid not in listed
            ]
            for uid in stale:
                cache.delete_pod_group(uid)
        # priorityclasses/pdbs: stale entries are harmless until their next
        # watch event; deletions reconcile through the objects they affect

    def _run_resource(self, kind: str, on_seeded: Callable[[], None]) -> None:
        if self._stream_factory is not None:
            for etype, obj in self._stream_factory(kind):
                if self._stop.is_set():
                    return
                apply_event(self.cache, kind, etype, obj)
            on_seeded()
            return
        backoff = 1.0
        rv: Optional[str] = None
        seeded = False
        while not self._stop.is_set():
            try:
                if rv is None:
                    rv = self._seed(kind)
                    if not seeded:
                        seeded = True
                        on_seeded()
                path = (
                    f"{RESOURCES[kind]}?watch=true&allowWatchBookmarks=true"
                    + (f"&resourceVersion={rv}" if rv else "")
                )
                for event in self._watch_events(path):
                    if self._stop.is_set():
                        return
                    etype = event.get("type")
                    obj = event.get("object") or {}
                    new_rv = (obj.get("metadata") or {}).get("resourceVersion")
                    if new_rv:
                        rv = new_rv
                    if etype == "BOOKMARK":
                        continue
                    if etype == "ERROR":
                        if obj.get("code") == 410:  # Gone → re-list
                            rv = None
                            break
                        raise RuntimeError(f"watch error for {kind}: {obj}")
                    apply_event(self.cache, kind, etype, obj)
                backoff = 1.0
            except Exception as e:  # noqa: BLE001 — reconnect with backoff
                logger.warning("watch %s failed (%s); reconnecting in %.0fs",
                               kind, e, backoff)
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 30.0)

    # ---- lifecycle ----------------------------------------------------
    def replay(self, events: Iterable[Tuple[str, str, dict]]) -> None:
        """Feed (kind, event_type, object) triples straight through the
        dispatch path — what the watch threads do, minus the transport."""
        for kind, etype, obj in events:
            apply_event(self.cache, kind, etype, obj)

    def start(self) -> None:
        """One daemon thread per resource (the informer goroutines);
        mark_synced once every resource finished its initial LIST — the
        WaitForCacheSync barrier (cache.go:363-384)."""
        remaining = set(self.resources)
        lock = threading.Lock()
        all_seeded = threading.Event()

        def make_on_seeded(kind):
            def on_seeded():
                with lock:
                    remaining.discard(kind)
                    if not remaining:
                        all_seeded.set()
            return on_seeded

        for kind in self.resources:
            t = threading.Thread(
                target=self._run_resource, args=(kind, make_on_seeded(kind)),
                name=f"kb-watch-{kind}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        if not all_seeded.wait(timeout=600):
            logger.warning("not every watch seeded in time; proceeding")
        self.cache.mark_synced()

    def stop(self) -> None:
        self._stop.set()
