"""List+watch front end: a Kubernetes API server → the SchedulerCache.

The standalone analog of the reference's informer wiring (cache.go:256-339):
for each resource, LIST once to seed the cache, then WATCH from the list's
resourceVersion, translating every event through k8s/translate.apply_event.
Reconnects with backoff on stream errors; a 410 Gone (stale resourceVersion)
re-lists, which is also how a restarted scheduler converges — the cache is
reconstructible from the API server exactly like the reference's
(SURVEY.md §5.4).

Transport is stdlib urllib with bearer-token + CA options, so the shim runs
in-cluster (serviceaccount token) or against a kubeconfig-style endpoint
without any Kubernetes client dependency.  The stream layer is injectable
(`stream_factory`) so tests drive recorded event lines through the exact
dispatch path.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Iterable, Optional, Tuple

from kube_batch_tpu.k8s.translate import apply_event
from kube_batch_tpu.k8s.transport import ApiTransport

logger = logging.getLogger("kube_batch_tpu")

# resource kind → API path
RESOURCES: Dict[str, str] = {
    "pods": "/api/v1/pods",
    "nodes": "/api/v1/nodes",
    "podgroups": "/apis/scheduling.incubator.k8s.io/v1alpha1/podgroups",
    "queues": "/apis/scheduling.incubator.k8s.io/v1alpha1/queues",
    "poddisruptionbudgets": "/apis/policy/v1/poddisruptionbudgets",
    "priorityclasses": "/apis/scheduling.k8s.io/v1/priorityclasses",
    # the volume-binder feed (cache.go:189-209,258-269,311-320)
    "persistentvolumes": "/api/v1/persistentvolumes",
    "persistentvolumeclaims": "/api/v1/persistentvolumeclaims",
    "storageclasses": "/apis/storage.k8s.io/v1/storageclasses",
}


class WatchAdapter:
    """Replays a cluster's state + changes into a SchedulerCache."""

    def __init__(
        self,
        cache,
        api_server: str = "https://kubernetes.default.svc",
        token: Optional[str] = None,
        token_file: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
        resources: Iterable[str] = tuple(RESOURCES),
        stream_factory: Optional[Callable] = None,
    ):
        self.cache = cache
        self.transport = ApiTransport(
            api_server, token=token, token_file=token_file,
            ca_file=ca_file, insecure=insecure, role="watch",
        )
        self.resources = tuple(resources)
        # injectable for tests: kind → iterable of (event_type, object);
        # replaces the LIST+WATCH transport, not the dispatch
        self._stream_factory = stream_factory
        self._stop = threading.Event()
        self._threads: list = []

    # ---- transport ----------------------------------------------------
    def _get_json(self, path: str):
        return self.transport.get_json(path)

    def _watch_events(self, path: str):
        return self.transport.stream_lines(path)

    # ---- per-resource loop --------------------------------------------
    def _seed(self, kind: str) -> Optional[str]:
        """LIST → RECONCILE the cache against the listing; returns the
        collection's resourceVersion to watch from.

        A seed also runs after a 410 Gone against an already-populated
        cache, so items apply as upserts (MODIFIED — the cache handlers are
        add-or-update) and objects that vanished during the disconnect are
        deleted, or the scheduler would keep placing against phantom
        capacity."""
        listing = self._get_json(RESOURCES[kind])
        items = listing.get("items") or []
        for item in items:
            try:
                apply_event(self.cache, kind, "MODIFIED", item)
            except Exception:  # noqa: BLE001 — one bad object must not
                # poison the whole resource's seed (and so the sync barrier)
                logger.exception(
                    "seed: dropping unparseable %s object %s", kind,
                    (item.get("metadata") or {}).get("name"),
                )
        self._reconcile_deletions(kind, items)
        return (listing.get("metadata") or {}).get("resourceVersion")

    def _reconcile_deletions(self, kind: str, items) -> None:
        def names():
            return {
                (i.get("metadata") or {}).get("namespace", "default")
                + "/" + (i.get("metadata") or {}).get("name", "")
                for i in items
            }

        cache = self.cache
        # snapshot the key sets under the cache lock: other threads (admin
        # ingest, resync repair) mutate these dicts concurrently
        if kind == "pods":
            listed = names()
            with cache._lock:
                # the STORED pod objects, not synthetic ones — deletion must
                # resolve the real job key (group annotation / owner) or the
                # task leaks in its job and on its node
                stale = [p for k, p in cache.pods.items() if k not in listed]
            for pod in stale:
                cache.delete_pod(pod)
        elif kind == "nodes":
            listed = {(i.get("metadata") or {}).get("name", "") for i in items}
            with cache._lock:
                stale_names = [n for n in cache.nodes if n not in listed]
            for name in stale_names:
                cache.delete_node(name)
        elif kind == "queues":
            listed = {(i.get("metadata") or {}).get("name", "") for i in items}
            with cache._lock:
                stale_names = [q for q in cache.queues if q not in listed]
            for name in stale_names:
                cache.delete_queue(name)
        elif kind == "podgroups":
            listed = names()
            with cache._lock:
                stale_uids = [
                    uid for uid, job in cache.jobs.items()
                    if job.pod_group is not None and not job.pod_group.shadow
                    and uid not in listed
                ]
            for uid in stale_uids:
                cache.delete_pod_group(uid)
        elif kind == "persistentvolumes":
            binder = cache.volume_binder
            # kbt: allow[KBT008] capability probe, not an event drop: a
            # binder without a pv ledger has nothing to reconcile; ingest
            # misses are separately surfaced by translate._volume_ingest
            pvs = getattr(binder, "pvs", None)
            if pvs is not None:
                listed = {(i.get("metadata") or {}).get("name", "") for i in items}
                for name in [n for n in list(pvs) if n not in listed]:
                    binder.delete_pv(name)
        elif kind == "persistentvolumeclaims":
            binder = cache.volume_binder
            # kbt: allow[KBT008] capability probe (see the pv branch above)
            claims = getattr(binder, "claims", None)
            if claims is not None:
                listed = names()
                for key in [k for k in list(claims) if k not in listed]:
                    binder.delete_pvc(key)
        elif kind == "storageclasses":
            # no other object's events touch the class ledger — a stale
            # provisioner entry would keep its claims "dynamically
            # provisionable" forever
            binder = cache.volume_binder
            # kbt: allow[KBT008] capability probe (see the pv branch above)
            classes = getattr(binder, "storage_classes", None)
            if classes is not None:
                listed = {(i.get("metadata") or {}).get("name", "") for i in items}
                for name in [n for n in list(classes) if n not in listed]:
                    binder.delete_storage_class(name)
        # priorityclasses/pdbs: stale entries are harmless until their next
        # watch event; deletions reconcile through the objects they affect

    def _run_resource(self, kind: str, on_seeded: Callable[[], None]) -> None:
        if self._stream_factory is not None:
            for etype, obj in self._stream_factory(kind):
                if self._stop.is_set():
                    return
                apply_event(self.cache, kind, etype, obj)
            on_seeded()
            return
        # reconnect delays come from the transport's shared RetryPolicy
        # (decorrelated jitter, capped) — the watch's old private 1→30s
        # doubling marched every resource's reconnect in lockstep
        backoff = self.transport.retry.backoff_state()
        rv: Optional[str] = None
        seeded = False
        while not self._stop.is_set():
            try:
                if rv is None:
                    rv = self._seed(kind)
                    if not seeded:
                        seeded = True
                        on_seeded()
                path = (
                    f"{RESOURCES[kind]}?watch=true&allowWatchBookmarks=true"
                    + (f"&resourceVersion={rv}" if rv else "")
                )
                for event in self._watch_events(path):
                    if self._stop.is_set():
                        return
                    etype = event.get("type")
                    obj = event.get("object") or {}
                    new_rv = (obj.get("metadata") or {}).get("resourceVersion")
                    if new_rv:
                        rv = new_rv
                    if etype == "BOOKMARK":
                        continue
                    if etype == "ERROR":
                        if obj.get("code") == 410:  # Gone → re-list
                            rv = None
                            break
                        raise RuntimeError(f"watch error for {kind}: {obj}")
                    apply_event(self.cache, kind, etype, obj)
                backoff.reset()
            except Exception as e:  # noqa: BLE001 — reconnect with backoff
                delay = backoff.next()
                logger.warning("watch %s failed (%s); reconnecting in %.1fs",
                               kind, e, delay)
                if self._stop.wait(delay):
                    return

    # ---- lifecycle ----------------------------------------------------
    def replay(self, events: Iterable[Tuple[str, str, dict]]) -> None:
        """Feed (kind, event_type, object) triples straight through the
        dispatch path — what the watch threads do, minus the transport."""
        for kind, etype, obj in events:
            apply_event(self.cache, kind, etype, obj)

    def start(self) -> None:
        """One daemon thread per resource (the informer goroutines);
        mark_synced once every resource finished its initial LIST — the
        WaitForCacheSync barrier (cache.go:363-384)."""
        remaining = set(self.resources)
        lock = threading.Lock()
        all_seeded = threading.Event()

        def make_on_seeded(kind):
            def on_seeded():
                with lock:
                    remaining.discard(kind)
                    if not remaining:
                        all_seeded.set()
            return on_seeded

        for kind in self.resources:
            t = threading.Thread(
                target=self._run_resource, args=(kind, make_on_seeded(kind)),
                name=f"kb-watch-{kind}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        if not all_seeded.wait(timeout=600):
            logger.warning("not every watch seeded in time; proceeding")
        self.cache.mark_synced()

    def stop(self) -> None:
        self._stop.set()
