"""Binding/eviction writeback to a Kubernetes API server.

The egress half of the front end (the reference's default binder/evictor,
cache.go:110-150): placements POST the pods/binding subresource, evictions
DELETE the pod.  Errors raise, which routes the task into the cache's resync
repair queue exactly like a failed client-go call (cache.go:478-484)."""

from __future__ import annotations

import json
import logging
import ssl
import urllib.error
import urllib.request
from typing import Optional

logger = logging.getLogger("kube_batch_tpu")


class K8sBackend:
    """Binder + Evictor against an apiserver (duck-typed for both cache
    seams; per-pod calls are idempotent, so no bind_many is exposed — see
    the Binder contract in cache/interface.py)."""

    def __init__(
        self,
        api_server: str,
        token: Optional[str] = None,
        token_file: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
    ):
        self.api_server = api_server.rstrip("/")
        self._token = token
        self._token_file = token_file
        self._ctx: Optional[ssl.SSLContext] = None
        if api_server.startswith("https"):
            self._ctx = ssl.create_default_context(cafile=ca_file)
            if insecure:
                self._ctx.check_hostname = False
                self._ctx.verify_mode = ssl.CERT_NONE

    def _headers(self):
        tok = self._token
        if tok is None and self._token_file:
            with open(self._token_file) as f:
                tok = f.read().strip()
        h = {"Content-Type": "application/json"}
        if tok:
            h["Authorization"] = f"Bearer {tok}"
        return h

    def _request(self, method: str, path: str, body: Optional[dict] = None) -> None:
        req = urllib.request.Request(
            self.api_server + path,
            data=json.dumps(body).encode() if body is not None else None,
            headers=self._headers(),
            method=method,
        )
        with urllib.request.urlopen(req, context=self._ctx, timeout=30) as r:
            r.read()

    # ---- Binder seam ---------------------------------------------------
    def bind(self, pod, hostname: str) -> None:
        """POST the Binding subresource (the defaultBinder, cache.go:115-126)."""
        self._request(
            "POST",
            f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}/binding",
            {
                "apiVersion": "v1",
                "kind": "Binding",
                "metadata": {"name": pod.name, "namespace": pod.namespace,
                             "uid": pod.uid},
                "target": {"apiVersion": "v1", "kind": "Node", "name": hostname},
            },
        )

    # ---- Evictor seam --------------------------------------------------
    def evict(self, pod) -> None:
        """DELETE the pod (the defaultEvictor, cache.go:128-140)."""
        try:
            self._request(
                "DELETE",
                f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}",
            )
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return  # already gone — eviction's goal is met
            raise
