"""Binding/eviction writeback to a Kubernetes API server.

The egress half of the front end (the reference's default binder/evictor,
cache.go:110-150): placements POST the pods/binding subresource, evictions
DELETE the pod.  Errors raise, which routes the task into the cache's resync
repair queue exactly like a failed client-go call (cache.go:478-484)."""

from __future__ import annotations

import logging
import urllib.error
from typing import Optional

from kube_batch_tpu.k8s.transport import ApiTransport

logger = logging.getLogger("kube_batch_tpu")


class K8sBackend:
    """Binder + Evictor against an apiserver (duck-typed for both cache
    seams; per-pod calls are idempotent, so no bind_many is exposed — see
    the Binder contract in cache/interface.py)."""

    def __init__(
        self,
        api_server: str,
        token: Optional[str] = None,
        token_file: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
    ):
        self.transport = ApiTransport(
            api_server, token=token, token_file=token_file,
            ca_file=ca_file, insecure=insecure,
        )

    # ---- Binder seam ---------------------------------------------------
    def bind(self, pod, hostname: str) -> None:
        """POST the Binding subresource (the defaultBinder, cache.go:115-126)."""
        self.transport.request(
            "POST",
            f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}/binding",
            {
                "apiVersion": "v1",
                "kind": "Binding",
                "metadata": {"name": pod.name, "namespace": pod.namespace,
                             "uid": pod.uid},
                "target": {"apiVersion": "v1", "kind": "Node", "name": hostname},
            },
        )

    # ---- Evictor seam --------------------------------------------------
    def evict(self, pod) -> None:
        """DELETE the pod (the defaultEvictor, cache.go:128-140)."""
        try:
            self.transport.request(
                "DELETE",
                f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}",
            )
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return  # already gone — eviction's goal is met
            raise
