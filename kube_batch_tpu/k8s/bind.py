"""Binding/eviction writeback to a Kubernetes API server.

The egress half of the front end (the reference's default binder/evictor,
cache.go:110-150): placements POST the pods/binding subresource, evictions
DELETE the pod.  Errors raise, which routes the task into the cache's resync
repair queue exactly like a failed client-go call (cache.go:478-484)."""

from __future__ import annotations

import logging
import urllib.error
from typing import Optional

from kube_batch_tpu.k8s.transport import ApiTransport

logger = logging.getLogger("kube_batch_tpu")


class K8sBackend:
    """Binder + Evictor against an apiserver (duck-typed for both cache
    seams; per-pod calls are idempotent, so no bind_many is exposed — see
    the Binder contract in cache/interface.py)."""

    def __init__(
        self,
        api_server: str,
        token: Optional[str] = None,
        token_file: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
    ):
        self.transport = ApiTransport(
            api_server, token=token, token_file=token_file,
            ca_file=ca_file, insecure=insecure, role="writeback",
        )

    # ---- Binder seam ---------------------------------------------------
    def bind(self, pod, hostname: str) -> None:
        """POST the Binding subresource (the defaultBinder, cache.go:115-126).

        A 409 Conflict is idempotent success: the pod is already bound —
        almost always by our OWN earlier request that timed out client-side
        but landed server-side (the retrying transport makes this window
        routine). Raising would loop the task through resync for a bind
        that already happened; mirrors the evict 404 handling below."""
        try:
            self.transport.request(
                "POST",
                f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}/binding",
                {
                    "apiVersion": "v1",
                    "kind": "Binding",
                    "metadata": {"name": pod.name, "namespace": pod.namespace,
                                 "uid": pod.uid},
                    "target": {"apiVersion": "v1", "kind": "Node",
                               "name": hostname},
                },
            )
        except urllib.error.HTTPError as e:
            if e.code == 409:
                logger.info("bind of %s/%s: already bound (409) — treating "
                            "as success", pod.namespace, pod.name)
                return
            raise

    def degraded(self) -> bool:
        """True while the transport's writeback breaker is failing fast —
        the cache's degraded-cycle checks (status shedding) read this."""
        return self.transport.degraded()

    # ---- Evictor seam --------------------------------------------------
    def evict(self, pod) -> None:
        """DELETE the pod (the defaultEvictor, cache.go:128-140)."""
        try:
            self.transport.request(
                "DELETE",
                f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}",
            )
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return  # already gone — eviction's goal is met
            raise

    # ---- StatusUpdater seam --------------------------------------------
    # Status writes are independent per object, so the cache's close-time
    # jobUpdater pool (job_updater.go:18,51-53) may fan them out over
    # threads; the transport opens a connection per request.
    parallel_safe = True

    def update_pod_group(self, pg) -> None:
        """PATCH the PodGroup status subresource (the defaultStatusUpdater's
        UpdatePodGroup, cache.go:176-187; CRD group per config/crds)."""
        if getattr(pg, "shadow", False):
            return  # synthesized for a plain pod — no CRD object exists
        self.transport.request(
            "PATCH",
            "/apis/scheduling.incubator.k8s.io/v1alpha1/namespaces/"
            f"{pg.namespace}/podgroups/{pg.name}/status",
            {
                "status": {
                    "phase": pg.phase.value if pg.phase is not None else None,
                    "running": pg.running,
                    "succeeded": pg.succeeded,
                    "failed": pg.failed,
                    "conditions": [
                        {
                            "type": c.type,
                            "status": c.status,
                            "transitionID": c.transition_id,
                            "reason": c.reason,
                            "message": c.message,
                        }
                        for c in pg.conditions
                    ],
                }
            },
            content_type="application/merge-patch+json",
        )

    def update_pod_condition(self, pod, cond: dict) -> None:
        """PATCH the pod's PodScheduled condition (taskUnschedulable,
        cache.go:500-525)."""
        self.transport.request(
            "PATCH",
            f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}/status",
            {"status": {"conditions": [cond]}},
            content_type="application/strategic-merge-patch+json",
        )

    def update_queue_status(self, name: str, counts: dict) -> None:
        """PATCH the Queue CRD's podgroup-phase counts (QueueStatus,
        types.go:195-204). BEYOND the reference: kube-batch declares the
        status fields but nothing populates them (the filler controller
        arrived later, in Volcano) — writing them here makes
        `kb-ctl queue --master ... list` show live counts."""
        self.transport.request(
            "PATCH",
            "/apis/scheduling.incubator.k8s.io/v1alpha1/queues/"
            f"{name}/status",
            {"status": counts},
            content_type="application/merge-patch+json",
        )
