"""Kubernetes API JSON → framework objects.

The reference is wired to a live cluster through 10 informers
(cache.go:256-339) consuming v1.Pod / v1.Node / the scheduling.incubator.k8s.io
PodGroup and Queue CRDs / policy PDBs / scheduling.k8s.io PriorityClasses.
This module is the standalone rebuild's equivalent seam: it translates the
raw JSON those watch streams carry into the framework's ingest dataclasses
(api/pod.py), unit-for-unit compatible with the reference's readings —
cpu in millicores (resource_info.go:99-111 value.MilliValue), memory in
bytes, scalar resources in milli units, quantities parsed with Kubernetes
suffix semantics.

`apply_event` dispatches a (kind, watch-event-type, object) triple into the
SchedulerCache's handlers — the informer AddFunc/UpdateFunc/DeleteFunc
analog (event_handlers.go).  kube_batch_tpu/k8s/watch.py drives it from live
list+watch streams.
"""

from __future__ import annotations

import datetime
import logging
from typing import Dict, List, Optional, Tuple

from kube_batch_tpu.api.pod import (
    GROUP_NAME_ANNOTATION,
    Affinity,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodAffinityTerm,
    PodDisruptionBudget,
    PodGroup,
    PriorityClass,
    Queue,
    Taint,
    Toleration,
)
from kube_batch_tpu.api.types import PodGroupPhase, PodPhase

logger = logging.getLogger("kube_batch_tpu")

_SUFFIX = {
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60,
}


def parse_quantity(q) -> float:
    """A Kubernetes resource.Quantity string → float (base units).
    Handles sub-unit ('100m', '500u', '50n' — the apiserver canonicalizes
    sub-milli values to u/n), binary ('1Gi') and decimal ('2G') suffixes,
    plain and exponent forms ('0.5', '1e3')."""
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q).strip()
    if not s:
        return 0.0
    if s.endswith("n"):
        return float(s[:-1]) / 1e9
    if s.endswith("u"):
        return float(s[:-1]) / 1e6
    if s.endswith("m"):
        return float(s[:-1]) / 1000.0
    for suf in ("Ki", "Mi", "Gi", "Ti", "Pi", "Ei"):
        if s.endswith(suf):
            return float(s[: -len(suf)]) * _SUFFIX[suf]
    if s[-1] in _SUFFIX:
        return float(s[:-1]) * _SUFFIX[s[-1]]
    return float(s)


def _requests_to_framework(requests: Dict[str, str]) -> Dict[str, float]:
    """k8s requests map → framework units: cpu→millicores, memory→bytes,
    every other (scalar) resource→milli units (resource_info.go:99-127)."""
    out: Dict[str, float] = {}
    for name, q in (requests or {}).items():
        v = parse_quantity(q)
        if name == "cpu":
            out["cpu"] = out.get("cpu", 0.0) + v * 1000.0
        elif name == "memory":
            out["memory"] = out.get("memory", 0.0) + v
        elif name == "pods":
            out["pods"] = out.get("pods", 0.0) + v
        else:
            out[name] = out.get(name, 0.0) + v * 1000.0
    return out


def _sum_requests(containers: List[dict]) -> Dict[str, float]:
    total: Dict[str, float] = {}
    for c in containers or []:
        for name, v in _requests_to_framework(
            (c.get("resources") or {}).get("requests") or {}
        ).items():
            total[name] = total.get(name, 0.0) + v
    return total


def _max_requests(containers: List[dict]) -> Dict[str, float]:
    """Per-dimension max over init containers (pod_info.go:53-73)."""
    out: Dict[str, float] = {}
    for c in containers or []:
        for name, v in _requests_to_framework(
            (c.get("resources") or {}).get("requests") or {}
        ).items():
            out[name] = max(out.get(name, 0.0), v)
    return out


def creation_index_of(meta: dict) -> int:
    """creationTimestamp → monotone int (epoch seconds)."""
    ts = (meta or {}).get("creationTimestamp")
    if not ts:
        return 0
    try:
        return int(
            datetime.datetime.fromisoformat(ts.replace("Z", "+00:00")).timestamp()
        )
    except ValueError:
        return 0


def _controller_uid(meta: dict) -> Optional[str]:
    for ref in (meta or {}).get("ownerReferences") or []:
        if ref.get("controller"):
            return ref.get("uid") or ref.get("name")
    # kbt: allow[KBT004] ownerless pods are a valid spec state (bare pods),
    # not unrecognized input; None means "no controller", never a guess
    return None


def _match_expressions(term: dict) -> List[Tuple[str, str, Tuple[str, ...]]]:
    out = []
    for e in term.get("matchExpressions") or []:
        out.append((e.get("key", ""), e.get("operator", "In"),
                    tuple(e.get("values") or ())))
    # matchFields (metadata.name) are encoded as In terms on the hostname
    # label, which every kubelet sets — a sound approximation the host
    # predicate re-validates
    for e in term.get("matchFields") or []:
        if e.get("key") == "metadata.name":
            out.append(("kubernetes.io/hostname", e.get("operator", "In"),
                        tuple(e.get("values") or ())))
    return out


def _pod_terms(spec: dict, key: str) -> List[PodAffinityTerm]:
    out = []
    for t in (spec or {}).get(key) or []:
        sel = (t.get("labelSelector") or {}).get("matchLabels") or {}
        out.append(PodAffinityTerm(
            match_labels=dict(sel),
            topology_key=t.get("topologyKey", "kubernetes.io/hostname"),
        ))
    return out


def _weighted_pod_terms(spec: dict, key: str):
    out = []
    for t in (spec or {}).get(key) or []:
        term = t.get("podAffinityTerm") or {}
        sel = (term.get("labelSelector") or {}).get("matchLabels") or {}
        out.append((float(t.get("weight", 1)), PodAffinityTerm(
            match_labels=dict(sel),
            topology_key=term.get("topologyKey", "kubernetes.io/hostname"),
        )))
    return out


def _affinity_from_k8s(aff: Optional[dict]) -> Optional[Affinity]:
    if not aff:
        # kbt: allow[KBT004] absent affinity stanza = unconstrained pod by
        # k8s spec; None is the documented "no affinity" value, not a default
        return None
    out = Affinity()
    node_aff = aff.get("nodeAffinity") or {}
    required = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    for term in required.get("nodeSelectorTerms") or []:
        reqs = _match_expressions(term)
        if reqs:
            out.node_terms.append(reqs)
    for pref in node_aff.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
        reqs = _match_expressions(pref.get("preference") or {})
        if reqs:
            out.preferred_node_terms.append((float(pref.get("weight", 1)), reqs))
    pod_aff = aff.get("podAffinity") or {}
    out.pod_affinity = _pod_terms(
        pod_aff, "requiredDuringSchedulingIgnoredDuringExecution"
    )
    out.preferred_pod_affinity = _weighted_pod_terms(
        pod_aff, "preferredDuringSchedulingIgnoredDuringExecution"
    )
    anti = aff.get("podAntiAffinity") or {}
    out.pod_anti_affinity = _pod_terms(
        anti, "requiredDuringSchedulingIgnoredDuringExecution"
    )
    out.preferred_pod_anti_affinity = _weighted_pod_terms(
        anti, "preferredDuringSchedulingIgnoredDuringExecution"
    )
    if (
        not out.node_terms and not out.pod_affinity and not out.pod_anti_affinity
        and not out.has_preferences()
    ):
        # kbt: allow[KBT004] an affinity stanza that parses to zero terms is
        # an empty selector (matches everything) per MatchNodeSelector
        # semantics, predicates.go:194-205 — open IS the reference behavior
        return None
    return out


def pod_from_k8s(obj: dict) -> Pod:
    """v1.Pod JSON → framework Pod."""
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    containers = spec.get("containers") or []
    host_ports = tuple(
        int(p["hostPort"])
        for c in containers
        for p in c.get("ports") or []
        if p.get("hostPort")
    )
    tolerations = [
        Toleration(
            key=t.get("key", ""),
            operator=t.get("operator", "Equal"),
            value=t.get("value", ""),
            effect=t.get("effect", ""),
        )
        for t in spec.get("tolerations") or []
    ]
    volume_claims = tuple(
        v["persistentVolumeClaim"]["claimName"]
        for v in spec.get("volumes") or []
        if v.get("persistentVolumeClaim", {}).get("claimName")
    )
    try:
        phase = PodPhase(status.get("phase", "Pending"))
    except ValueError:
        phase = PodPhase.UNKNOWN
    return Pod(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid", ""),
        requests=_sum_requests(containers),
        init_requests=_max_requests(spec.get("initContainers")),
        node_name=spec.get("nodeName") or None,
        phase=phase,
        deleting=bool(meta.get("deletionTimestamp")),
        priority=int(spec.get("priority") or 0),
        priority_class=spec.get("priorityClassName", ""),
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        node_selector=dict(spec.get("nodeSelector") or {}),
        tolerations=tolerations,
        affinity=_affinity_from_k8s(spec.get("affinity")),
        host_ports=host_ports,
        scheduler_name=spec.get("schedulerName", "default-scheduler"),
        creation_index=creation_index_of(meta),
        volume_claims=volume_claims,
        owner=_controller_uid(meta),
    )


def node_from_k8s(obj: dict) -> Node:
    """v1.Node JSON → framework Node."""
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    taints = [
        Taint(key=t.get("key", ""), value=t.get("value", ""),
              effect=t.get("effect", "NoSchedule"))
        for t in spec.get("taints") or []
    ]
    ready = True
    conditions: Dict[str, bool] = {}
    for c in status.get("conditions") or []:
        truthy = c.get("status") == "True"
        if c.get("type") == "Ready":
            ready = truthy
        else:
            conditions[c.get("type", "")] = truthy
    return Node(
        name=meta.get("name", ""),
        allocatable=_requests_to_framework(status.get("allocatable") or {}),
        capacity=_requests_to_framework(status.get("capacity") or {}),
        labels=dict(meta.get("labels") or {}),
        taints=taints,
        ready=ready,
        unschedulable=bool(spec.get("unschedulable")),
        conditions=conditions,
    )


def pod_group_from_k8s(obj: dict) -> PodGroup:
    """PodGroup CRD JSON (scheduling.incubator.k8s.io/v1alpha1,
    types.go:93-171) → framework PodGroup."""
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    min_resources = spec.get("minResources")
    phase = None
    if status.get("phase"):
        try:
            phase = PodGroupPhase(status["phase"])
        except ValueError:
            phase = None
    return PodGroup(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid", ""),
        min_member=int(spec.get("minMember") or 1),
        queue=spec.get("queue", ""),
        priority_class=spec.get("priorityClassName", ""),
        min_resources=(
            _requests_to_framework(min_resources) if min_resources else None
        ),
        phase=phase,
        running=int(status.get("running") or 0),
        succeeded=int(status.get("succeeded") or 0),
        failed=int(status.get("failed") or 0),
        creation_index=creation_index_of(meta),
    )


def queue_from_k8s(obj: dict) -> Queue:
    """Queue CRD JSON (types.go:178-223) → framework Queue."""
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    capability = spec.get("capability")
    return Queue(
        name=meta.get("name", ""),
        uid=meta.get("uid", ""),
        weight=int(spec.get("weight") or 1),
        capability=(
            _requests_to_framework(capability) if capability else None
        ),
    )


def pdb_from_k8s(obj: dict) -> Optional[PodDisruptionBudget]:
    """policy PodDisruptionBudget JSON → framework PDB (the legacy gang
    source, event_handlers.go:484-594). Only integer minAvailable is a gang
    signal; percentage PDBs are skipped like unparseable ones."""
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    min_available = spec.get("minAvailable")
    if not isinstance(min_available, int):
        # kbt: allow[KBT004] percentage/unparseable minAvailable is not a
        # gang signal; skipping matches the reference (event_handlers.go:
        # 484-594) and only forgoes gang semantics, never placement safety
        return None
    return PodDisruptionBudget(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        min_available=min_available,
        owner=_controller_uid(meta),
        creation_index=creation_index_of(meta),
    )


def priority_class_from_k8s(obj: dict) -> PriorityClass:
    meta = obj.get("metadata") or {}
    return PriorityClass(
        name=meta.get("name", ""),
        value=int(obj.get("value") or 0),
        global_default=bool(obj.get("globalDefault")),
    )


# Sentinel "node" for a PV whose required nodeAffinity exists but isn't a
# recognizable single-node pin: it never equals a real hostname, so a ledger
# with no label knowledge treats the PV as reachable from NO node
# (fail-closed). The full nodeSelectorTerms now ride along on
# PersistentVolume.node_terms, and the ledger evaluates them against
# candidate node labels (the reference volumebinder's behavior) — the
# sentinel only bites when labels for the candidate are unknown, keeping
# ADVICE.md #1's fail-closed floor without its zonal over-restriction.
PV_NODE_RESTRICTED_UNKNOWN = "__pv-node-affinity-unrecognized__"


def _pv_node_affinity(spec: dict) -> Tuple[Optional[str], tuple]:
    """A PV's (single-node pin, full required terms) from
    spec.nodeAffinity.required.

    The pin fast path reads the kubernetes.io/hostname / metadata.name In
    expression local-storage provisioning writes, so the common local-PV
    case never needs node labels. Terms are returned whenever required
    affinity exists — OR'd, in Affinity.node_terms shape — and the ledger
    evaluates them against candidate node labels; with affinity but no
    recognized pin the `node` field gets the fail-closed sentinel."""
    required = ((spec.get("nodeAffinity") or {}).get("required") or {})
    raw_terms = required.get("nodeSelectorTerms") or []
    if not raw_terms:
        # kbt: allow[KBT004] no required affinity = a network volume
        # reachable from every node (spec semantics, not unrecognized input)
        return None, ()
    terms = tuple(
        tuple(reqs) for reqs in (_match_expressions(t) for t in raw_terms) if reqs
    )
    pin = None
    for term in terms:
        # the pin fast path must only bypass term evaluation when the term
        # is NOTHING BUT the single-node expression: requirements within a
        # term are AND'd, so a term pairing a hostname pin with e.g. a zone
        # requirement pins conditionally and must evaluate in full — taking
        # the hostname alone would fail open on a node whose other labels
        # don't match (the ADVICE.md #1 bug class again)
        if len(term) != 1:
            continue
        key, op, values = term[0]
        # _match_expressions folds matchFields metadata.name In onto the
        # hostname label (every kubelet sets it to the node name); some
        # provisioners put metadata.name in matchExpressions instead
        if (
            key in ("kubernetes.io/hostname", "metadata.name")
            and op == "In"
            and values
        ):
            pin = values[0]
            break
    return (pin if pin is not None else PV_NODE_RESTRICTED_UNKNOWN), terms


def pv_from_k8s(obj: dict) -> PersistentVolume:
    """v1.PersistentVolume JSON → ledger PV (cache.go:189-209 pv informer)."""
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    claim_ref = spec.get("claimRef") or {}
    claim = None
    if claim_ref.get("name"):
        claim = f"{claim_ref.get('namespace', 'default')}/{claim_ref['name']}"
    node, node_terms = _pv_node_affinity(spec)
    return PersistentVolume(
        name=meta.get("name", ""),
        node=node,
        claim=claim,
        storage_class=spec.get("storageClassName", ""),
        node_terms=node_terms,
    )


def pvc_from_k8s(obj: dict) -> PersistentVolumeClaim:
    """v1.PersistentVolumeClaim JSON → ledger claim (pvc informer)."""
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    return PersistentVolumeClaim(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        volume_name=spec.get("volumeName") or None,
        storage_class=spec.get("storageClassName", ""),
        phase=status.get("phase", "Pending"),
    )


# watch "kind" → (translator, cache add, cache update, cache delete)
# (binder type, method) pairs whose missing-ingest drop already logged —
# one loud line per combination, not one per event storm
_MISSING_INGEST_WARNED: set = set()


def _volume_ingest(binder, method: str, *args) -> None:
    """Dispatch one PV/PVC/StorageClass ingest event to the volume-binder
    seam.  The surface is declared on cache/interface.VolumeBinder; a
    binder lacking the method cannot ingest the event, and that is a REAL
    drop (a standalone ledger fed --master PVC events loses bindings), so
    it logs loudly once per (binder type, method) instead of silently
    failing open — the round-5 PV bug shape, one layer up (KBT008)."""
    # kbt: allow[KBT008] the one audited seam probe: a miss is logged below
    # (observable drop), never silently swallowed
    fn = getattr(binder, method, None)
    if fn is None:
        key = (type(binder).__name__, method)
        if key not in _MISSING_INGEST_WARNED:
            _MISSING_INGEST_WARNED.add(key)
            logger.warning(
                "volume binder %s has no %s(); dropping these ingest "
                "events (volume topology decisions will not see them)",
                type(binder).__name__, method,
            )
        return
    fn(*args)


def apply_event(cache, kind: str, event_type: str, obj: dict) -> None:
    """Dispatch one watch event into the cache — the informer handler seam
    (event_handlers.go). `kind` is the lowercase resource (pods, nodes,
    podgroups, queues, poddisruptionbudgets, priorityclasses); `event_type`
    is ADDED | MODIFIED | DELETED."""
    deleted = event_type == "DELETED"
    if kind == "pods":
        pod = pod_from_k8s(obj)
        if deleted:
            cache.delete_pod(pod)
        elif event_type == "ADDED":
            cache.add_pod(pod)
        else:
            cache.update_pod(pod)
    elif kind == "nodes":
        if deleted:
            cache.delete_node((obj.get("metadata") or {}).get("name", ""))
        else:
            cache.add_node(node_from_k8s(obj))
    elif kind == "podgroups":
        pg = pod_group_from_k8s(obj)
        if deleted:
            cache.delete_pod_group(pg.key())
        else:
            cache.add_pod_group(pg)
    elif kind == "queues":
        q = queue_from_k8s(obj)
        if deleted:
            cache.delete_queue(q.name)
        else:
            cache.add_queue(q)
    elif kind == "poddisruptionbudgets":
        pdb = pdb_from_k8s(obj)
        if pdb is None:
            return
        if deleted:
            cache.delete_pdb(pdb)
        else:
            cache.add_pdb(pdb)
    elif kind == "priorityclasses":
        if deleted:
            cache.delete_priority_class(
                (obj.get("metadata") or {}).get("name", "")
            )
        else:
            cache.add_priority_class(priority_class_from_k8s(obj))
    elif kind == "persistentvolumes":
        # PV ledger seam (cache.go:189-209), dispatched through
        # _volume_ingest so a binder without the method drops LOUDLY
        binder = cache.volume_binder
        if deleted:
            _volume_ingest(
                binder, "delete_pv", (obj.get("metadata") or {}).get("name", "")
            )
        else:
            _volume_ingest(binder, "add_pv", pv_from_k8s(obj))
    elif kind == "persistentvolumeclaims":
        binder = cache.volume_binder
        pvc = pvc_from_k8s(obj)
        if deleted:
            _volume_ingest(binder, "delete_pvc", pvc.key())
        else:
            _volume_ingest(binder, "add_pvc", pvc)
    elif kind == "storageclasses":
        binder = cache.volume_binder
        name = (obj.get("metadata") or {}).get("name", "")
        if deleted:
            _volume_ingest(binder, "delete_storage_class", name)
        else:
            _volume_ingest(
                binder, "add_storage_class", name, obj.get("provisioner", "")
            )
    else:
        logger.warning("unknown watch kind %r ignored", kind)
