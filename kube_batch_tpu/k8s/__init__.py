from kube_batch_tpu.k8s.translate import (
    apply_event,
    node_from_k8s,
    parse_quantity,
    pdb_from_k8s,
    pod_from_k8s,
    pod_group_from_k8s,
    priority_class_from_k8s,
    queue_from_k8s,
)
from kube_batch_tpu.k8s.watch import RESOURCES, WatchAdapter

__all__ = [
    "apply_event",
    "node_from_k8s",
    "parse_quantity",
    "pdb_from_k8s",
    "pod_from_k8s",
    "pod_group_from_k8s",
    "priority_class_from_k8s",
    "queue_from_k8s",
    "RESOURCES",
    "WatchAdapter",
]
