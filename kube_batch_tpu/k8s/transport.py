"""Shared apiserver transport: bearer-token auth + TLS context + request
helpers used by both the watch ingest (k8s/watch.py) and the binding
writeback (k8s/bind.py) — one copy of the in-cluster auth logic."""

from __future__ import annotations

import json
import logging
import os
import ssl
import urllib.request
from typing import Dict, Optional

logger = logging.getLogger("kube_batch_tpu")

SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def in_cluster_auth() -> Dict[str, Optional[str]]:
    """token_file/ca_file/insecure kwargs for the cluster transport: the
    mounted serviceaccount when present, overridable out-of-cluster via
    KB_KUBE_TOKEN_FILE / KB_KUBE_CA_FILE / KB_KUBE_INSECURE (how the e2e
    driver hands the scheduler subprocess its credentials)."""
    token = os.environ.get("KB_KUBE_TOKEN_FILE") or f"{SERVICEACCOUNT_DIR}/token"
    ca = os.environ.get("KB_KUBE_CA_FILE") or f"{SERVICEACCOUNT_DIR}/ca.crt"
    auth: Dict[str, Optional[str]] = {
        "token_file": token if os.path.exists(token) else None,
        "ca_file": ca if os.path.exists(ca) else None,
    }
    if os.environ.get("KB_KUBE_INSECURE", "").lower() in ("1", "true", "yes"):
        auth["insecure"] = True  # type: ignore[assignment]
    return auth


class ApiTransport:
    def __init__(
        self,
        api_server: str,
        token: Optional[str] = None,
        token_file: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
    ):
        self.api_server = api_server.rstrip("/")
        self._token = token
        self._token_file = token_file
        self._ctx: Optional[ssl.SSLContext] = None
        if api_server.startswith("https"):
            self._ctx = ssl.create_default_context(cafile=ca_file)
            if insecure:
                self._ctx.check_hostname = False
                self._ctx.verify_mode = ssl.CERT_NONE

    def headers(self, content_type: Optional[str] = None) -> Dict[str, str]:
        tok = self._token
        if tok is None and self._token_file:
            # re-read per request: kubelet rotates projected tokens
            with open(self._token_file) as f:
                tok = f.read().strip()
        h: Dict[str, str] = {}
        if content_type:
            h["Content-Type"] = content_type
        if tok:
            h["Authorization"] = f"Bearer {tok}"
        return h

    def get_json(self, path: str, timeout: float = 60):
        req = urllib.request.Request(
            self.api_server + path, headers=self.headers()
        )
        with urllib.request.urlopen(req, context=self._ctx, timeout=timeout) as r:
            return json.load(r)

    def stream_lines(self, path: str, timeout: float = 330):
        """Yield decoded JSON objects from a chunked watch stream."""
        req = urllib.request.Request(
            self.api_server + path, headers=self.headers()
        )
        with urllib.request.urlopen(req, context=self._ctx, timeout=timeout) as r:
            for line in r:
                if line.strip():
                    yield json.loads(line)

    def request(self, method: str, path: str, body: Optional[dict] = None,
                timeout: float = 30,
                content_type: Optional[str] = None) -> None:
        if content_type is None and body is not None:
            content_type = "application/json"
        req = urllib.request.Request(
            self.api_server + path,
            data=json.dumps(body).encode() if body is not None else None,
            headers=self.headers(content_type),
            method=method,
        )
        with urllib.request.urlopen(req, context=self._ctx, timeout=timeout) as r:
            r.read()
