"""Shared apiserver transport: bearer-token auth + TLS context + request
helpers used by both the watch ingest (k8s/watch.py) and the binding
writeback (k8s/bind.py) — one copy of the in-cluster auth logic.

Fault hardening (this PR): every call through the transport rides ONE
classified retry policy — the standalone analog of client-go's rate-limited
workqueues + informer relist resilience that the reference leans on:

- :func:`classify_error` sorts failures into ``transient`` (connection
  refused/reset, timeouts, 5xx), ``throttle`` (429/503 — the apiserver is
  telling us to back off; ``Retry-After`` is honored), and ``fatal``
  (other 4xx — the server answered, retrying can't change the verdict).
- :class:`RetryPolicy` owns capped decorrelated-jitter exponential backoff
  (AWS-style: ``sleep = min(cap, U(base, prev*3))``) and per-endpoint-class
  attempt budgets (``read`` LISTs, ``write`` bind/evict/status, ``watch``
  stream connects — the watch loop is its own outer retry, so its budget
  is 1 and the loop draws its reconnect delays from the same policy).
- :class:`CircuitBreaker` guards each transport (≈ per-host): N consecutive
  failures open it, calls then fail fast with :class:`CircuitOpenError`
  (an ``OSError`` — existing "unreachable" handlers classify it right)
  until a cooldown elapses and a half-open probe decides. A fast-failing
  breaker is what lets the scheduling cycle keep ticking through an
  apiserver brownout instead of eating a connect timeout per pod.

Retry/breaker state is surfaced through ``kube_batch_tpu.metrics``
(transport_retries_total, circuit_breaker_transitions_total).
"""

from __future__ import annotations

import json
import logging
import os
import random
import socket
import ssl
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional, Tuple

from kube_batch_tpu import metrics

logger = logging.getLogger("kube_batch_tpu")

SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# ---------------------------------------------------------------------------
# error classification
# ---------------------------------------------------------------------------

TRANSIENT = "transient"  # retry with backoff
THROTTLE = "throttle"    # retry after the server-directed delay
FATAL = "fatal"          # the server answered; retrying cannot help


def _retry_after_seconds(err: urllib.error.HTTPError) -> Optional[float]:
    """Parse a Retry-After header (delta-seconds form; HTTP-date is rare
    from an apiserver and falls back to policy backoff)."""
    try:
        raw = err.headers.get("Retry-After") if err.headers else None
    except AttributeError:
        return None
    if not raw:
        return None
    try:
        return max(0.0, float(raw))
    except ValueError:
        return None  # HTTP-date form: let the policy backoff decide


def classify_error(exc: BaseException) -> Tuple[str, Optional[float]]:
    """(kind, retry_after_seconds) for one transport failure.

    The table (tests/test_transport.py pins it):
    429/503 → throttle (Retry-After honored); 408 and other 5xx →
    transient; remaining 4xx (and 501) → fatal; connection refused/reset,
    timeouts, unreachable sockets, mid-response drops (IncompleteRead /
    BadStatusLine and truncated JSON bodies) → transient; TLS certificate
    verification failures → fatal (retrying a bad cert is noise);
    everything unrecognized → fatal, because retrying an unknown
    programming error just hides it."""
    import http.client

    if isinstance(exc, urllib.error.HTTPError):
        code = exc.code
        if code in (429, 503):
            return THROTTLE, _retry_after_seconds(exc)
        if code == 408 or (500 <= code < 600 and code != 501):
            return TRANSIENT, None
        return FATAL, None
    if isinstance(exc, ssl.SSLCertVerificationError):
        return FATAL, None
    if isinstance(exc, urllib.error.URLError):
        # the wrapped reason carries the socket-level truth
        reason = exc.reason
        if isinstance(reason, BaseException):
            return classify_error(reason)
        return TRANSIENT, None
    if isinstance(exc, (ConnectionError, socket.timeout, TimeoutError,
                        ssl.SSLError, OSError)):
        return TRANSIENT, None
    if isinstance(exc, (http.client.HTTPException, json.JSONDecodeError)):
        # a connection dropped mid-response: IncompleteRead/BadStatusLine
        # (not OSError subclasses) or a truncated JSON body — network
        # symptoms, not server verdicts
        return TRANSIENT, None
    return FATAL, None


# ---------------------------------------------------------------------------
# retry policy: budgets + decorrelated-jitter backoff
# ---------------------------------------------------------------------------

#: attempt budgets per endpoint class; the watch's budget is 1 because its
#: caller (the per-resource reconnect loop) IS the outer retry
DEFAULT_BUDGETS: Dict[str, int] = {"read": 5, "write": 4, "watch": 1}


class Backoff:
    """Decorrelated-jitter backoff state: each delay is drawn uniformly
    from [base, prev*3], capped — retries desynchronize across callers
    instead of marching in lockstep against a recovering apiserver."""

    def __init__(self, base: float, cap: float, rng: random.Random):
        self.base = base
        self.cap = cap
        self._rng = rng
        self._prev = base

    def next(self) -> float:
        delay = min(self.cap, self._rng.uniform(self.base, self._prev * 3.0))
        self._prev = max(self.base, delay)
        return delay

    def reset(self) -> None:
        self._prev = self.base


class RetryPolicy:
    """Classification-aware retry budgets + backoff for one transport.

    ``rng`` is injectable so tests pin the jitter; ``budgets`` maps
    endpoint classes to max attempts (missing classes default to the
    ``read`` budget)."""

    def __init__(
        self,
        base: float = 0.25,
        cap: float = 30.0,
        budgets: Optional[Dict[str, int]] = None,
        rng: Optional[random.Random] = None,
    ):
        self.base = base
        self.cap = cap
        self.budgets = dict(DEFAULT_BUDGETS)
        if budgets:
            self.budgets.update(budgets)
        self._rng = rng if rng is not None else random.Random()

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        """Env knobs: KB_RETRY_BASE / KB_RETRY_CAP (seconds) and
        KB_RETRY_BUDGET_{READ,WRITE,WATCH} (attempts)."""
        budgets = {}
        for klass in DEFAULT_BUDGETS:
            raw = os.environ.get(f"KB_RETRY_BUDGET_{klass.upper()}")
            if raw:
                budgets[klass] = max(1, int(raw))
        return cls(
            base=float(os.environ.get("KB_RETRY_BASE", "0.25")),
            cap=float(os.environ.get("KB_RETRY_CAP", "30")),
            budgets=budgets or None,
        )

    def budget(self, endpoint_class: str) -> int:
        return self.budgets.get(endpoint_class, self.budgets["read"])

    def backoff_state(self) -> Backoff:
        return Backoff(self.base, self.cap, self._rng)

    def delay(self, kind: str, retry_after: Optional[float],
              backoff: Backoff) -> float:
        """Next sleep for a retryable failure: the server-directed
        Retry-After when the throttle carries one (capped — a hostile or
        confused header must not park the caller for minutes), the jittered
        backoff otherwise."""
        if kind == THROTTLE and retry_after is not None:
            return min(retry_after, self.cap)
        return backoff.next()


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class CircuitOpenError(OSError):
    """Raised instead of dialing when the breaker is open. An OSError so
    existing classify-as-unreachable handlers (the lease elector, the
    resync repair path) treat it as the transient outage it represents."""


class CircuitBreaker:
    """closed → open after ``threshold`` consecutive failures; open fails
    fast until ``cooldown`` elapses; then half-open admits ONE probe whose
    outcome closes or re-opens. The clock is injectable (the simulator
    passes its virtual clock). State flips happen under a lock; nothing
    blocks under it."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    def __init__(
        self,
        threshold: int = 5,
        cooldown: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
        name: str = "apiserver",
    ):
        self.threshold = max(1, threshold)
        self.cooldown = cooldown
        self._clock = clock
        self.name = name
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        # observability
        self.transitions: Dict[str, int] = {}

    @classmethod
    def from_env(cls, clock: Callable[[], float] = time.monotonic,
                 name: str = "apiserver") -> "CircuitBreaker":
        """Env knobs: KB_BREAKER_THRESHOLD / KB_BREAKER_COOLDOWN."""
        return cls(
            threshold=int(os.environ.get("KB_BREAKER_THRESHOLD", "5")),
            cooldown=float(os.environ.get("KB_BREAKER_COOLDOWN", "10")),
            clock=clock, name=name,
        )

    # -- state ----------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def is_open(self) -> bool:
        """True while calls would fail fast (open, cooldown not elapsed)."""
        with self._lock:
            return (self._state == self.OPEN
                    and self._clock() - self._opened_at < self.cooldown)

    def _transition(self, state: str) -> None:
        # lock held by caller
        if state == self._state:
            return
        self._state = state
        self.transitions[state] = self.transitions.get(state, 0) + 1
        metrics.register_breaker_transition(self.name, state)
        metrics.set_breaker_open(self.name, 1 if state == self.OPEN else 0)
        logger.warning("circuit breaker %s → %s", self.name, state)

    def allow(self) -> bool:
        """May a call go out now? Open breakers admit exactly one probe
        once the cooldown elapsed (half-open)."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at >= self.cooldown:
                    self._transition(self.HALF_OPEN)
                    self._probe_inflight = True
                    return True
                return False
            # half-open: one probe at a time
            if not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            self._transition(self.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probe_inflight = False
            if self._state == self.HALF_OPEN:
                self._opened_at = self._clock()
                self._transition(self.OPEN)
                return
            self._failures += 1
            if self._failures >= self.threshold:
                self._opened_at = self._clock()
                self._transition(self.OPEN)


class GuardedBackend:
    """Binder/Evictor seam wrapper that routes calls through a
    :class:`CircuitBreaker` — used where the backend is NOT an
    ApiTransport-backed K8sBackend (whose transport already carries its
    own breaker), e.g. the simulator's kubelet, so chaos runs exercise the
    exact breaker the production transport uses."""

    def __init__(self, backend, breaker: CircuitBreaker):
        self._backend = backend
        self.breaker = breaker
        # mirror the backend's batch capability: cache._dispatch_async
        # probes for bind_many and must not find one we can't honor
        # kbt: allow[KBT008] capability probe mirrors cache._dispatch_async's
        if getattr(backend, "bind_many", None) is None:
            self.bind_many = None  # type: ignore[assignment]

    def _guard(self, fn, *args):
        if not self.breaker.allow():
            raise CircuitOpenError(
                f"circuit breaker {self.breaker.name} is open")
        try:
            out = fn(*args)
        except Exception:
            self.breaker.record_failure()
            raise
        self.breaker.record_success()
        return out

    def bind(self, pod, hostname):
        return self._guard(self._backend.bind, pod, hostname)

    def bind_many(self, pairs):
        return self._guard(self._backend.bind_many, pairs)

    def evict(self, pod):
        return self._guard(self._backend.evict, pod)

    def degraded(self) -> bool:
        return self.breaker.is_open


# ---------------------------------------------------------------------------
# auth + transport
# ---------------------------------------------------------------------------


def in_cluster_auth() -> Dict[str, Optional[str]]:
    """token_file/ca_file/insecure kwargs for the cluster transport: the
    mounted serviceaccount when present, overridable out-of-cluster via
    KB_KUBE_TOKEN_FILE / KB_KUBE_CA_FILE / KB_KUBE_INSECURE (how the e2e
    driver hands the scheduler subprocess its credentials)."""
    token = os.environ.get("KB_KUBE_TOKEN_FILE") or f"{SERVICEACCOUNT_DIR}/token"
    ca = os.environ.get("KB_KUBE_CA_FILE") or f"{SERVICEACCOUNT_DIR}/ca.crt"
    auth: Dict[str, Optional[str]] = {
        "token_file": token if os.path.exists(token) else None,
        "ca_file": ca if os.path.exists(ca) else None,
    }
    if os.environ.get("KB_KUBE_INSECURE", "").lower() in ("1", "true", "yes"):
        auth["insecure"] = True  # type: ignore[assignment]
    return auth


class ApiTransport:
    def __init__(
        self,
        api_server: str,
        token: Optional[str] = None,
        token_file: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
        retry_policy: Optional[RetryPolicy] = None,
        breaker: Optional[CircuitBreaker] = None,
        role: str = "",
    ):
        self.api_server = api_server.rstrip("/")
        # `role` disambiguates the breaker metric label when several
        # transports target the same host (writeback / watch / lease each
        # have their own breaker; a shared label would be last-writer-wins)
        self.role = role
        self._token = token
        self._token_file = token_file
        self._ctx: Optional[ssl.SSLContext] = None
        if api_server.startswith("https"):
            self._ctx = ssl.create_default_context(cafile=ca_file)
            if insecure:
                self._ctx.check_hostname = False
                self._ctx.verify_mode = ssl.CERT_NONE
        # one transport ↔ one host: the breaker is effectively per-host
        # (per host+role when several transports share the host)
        self.retry = retry_policy if retry_policy is not None \
            else RetryPolicy.from_env()
        name = f"{self.api_server}/{role}" if role else self.api_server
        self.breaker = breaker if breaker is not None \
            else CircuitBreaker.from_env(name=name)
        self._sleep = time.sleep  # injectable for tests

    def degraded(self) -> bool:
        """Is the writeback path failing fast right now? (The cache's
        status-shed / degraded-cycle checks read this.)"""
        return self.breaker.is_open

    def headers(self, content_type: Optional[str] = None) -> Dict[str, str]:
        tok = self._token
        if tok is None and self._token_file:
            # re-read per request: kubelet rotates projected tokens
            with open(self._token_file) as f:
                tok = f.read().strip()
        h: Dict[str, str] = {}
        if content_type:
            h["Content-Type"] = content_type
        if tok:
            h["Authorization"] = f"Bearer {tok}"
        return h

    # -- the one retry loop every apiserver call rides ------------------
    def _call(self, endpoint_class: str, fn: Callable, retry: bool = True):
        """Run ``fn`` under the classified retry policy + breaker.

        ``retry=False`` keeps the breaker accounting but makes one attempt
        only — for callers whose outer loop IS the retry policy (lease
        renewal, the watch reconnect loop)."""
        attempts = self.retry.budget(endpoint_class) if retry else 1
        backoff = self.retry.backoff_state()
        last: Optional[BaseException] = None
        for attempt in range(1, attempts + 1):
            if not self.breaker.allow():
                raise CircuitOpenError(
                    f"{self.api_server}: circuit breaker open "
                    f"({endpoint_class})")
            try:
                out = fn()
            except Exception as e:  # noqa: BLE001 — classified right below
                kind, retry_after = classify_error(e)
                if kind == FATAL:
                    # the server answered; it is healthy — a 4xx must not
                    # trip the breaker or burn retry budget
                    self.breaker.record_success()
                    raise
                self.breaker.record_failure()
                last = e
                if attempt >= attempts:
                    raise
                delay = self.retry.delay(kind, retry_after, backoff)
                metrics.register_transport_retry(endpoint_class, kind)
                logger.warning(
                    "%s %s failed (%s, %s); retry %d/%d in %.2fs",
                    endpoint_class, self.api_server, kind, e, attempt,
                    attempts - 1, delay,
                )
                self._sleep(delay)
            else:
                self.breaker.record_success()
                return out
        raise last if last is not None else RuntimeError("unreachable")

    def get_json(self, path: str, timeout: float = 60, retry: bool = True):
        def attempt():
            req = urllib.request.Request(
                self.api_server + path, headers=self.headers()
            )
            with urllib.request.urlopen(
                req, context=self._ctx, timeout=timeout
            ) as r:
                return json.load(r)

        return self._call("read", attempt, retry=retry)

    def get_bytes(self, path: str, timeout: float = 60,
                  retry: bool = True) -> bytes:
        """Raw-bytes GET under the same ``read`` retry class — the
        replication follower's frame pull (replicate/follower.py) and the
        flight-recorder dump fetch; JSON endpoints use :meth:`get_json`."""
        def attempt():
            req = urllib.request.Request(
                self.api_server + path, headers=self.headers()
            )
            with urllib.request.urlopen(
                req, context=self._ctx, timeout=timeout
            ) as r:
                return r.read()

        return self._call("read", attempt, retry=retry)

    def stream_lines(self, path: str, timeout: float = 330):
        """Yield decoded JSON objects from a chunked watch stream. The
        CONNECT rides the policy/breaker (class ``watch``, budget 1 — the
        watch loop is the outer retry); mid-stream errors propagate to
        that loop."""
        def connect():
            req = urllib.request.Request(
                self.api_server + path, headers=self.headers()
            )
            return urllib.request.urlopen(
                req, context=self._ctx, timeout=timeout
            )

        with self._call("watch", connect) as r:
            for line in r:
                if line.strip():
                    yield json.loads(line)

    def request(self, method: str, path: str, body: Optional[dict] = None,
                timeout: float = 30,
                content_type: Optional[str] = None,
                retry: bool = True) -> None:
        if content_type is None and body is not None:
            content_type = "application/json"
        data = json.dumps(body).encode() if body is not None else None

        def attempt():
            req = urllib.request.Request(
                self.api_server + path,
                data=data,
                headers=self.headers(content_type),
                method=method,
            )
            with urllib.request.urlopen(
                req, context=self._ctx, timeout=timeout
            ) as r:
                r.read()

        self._call("write", attempt, retry=retry)
