"""Hardened-CPU environment recipe — the single owner of the axon workaround.

With a wedged TPU tunnel, any jax dispatch in an unhardened process hangs
inside axon backend init (make_c_api_client), even work that would run on
CPU.  The recipe: JAX_PLATFORMS=cpu + PALLAS_AXON_POOL_IPS="" (so
sitecustomize skips axon registration) + optionally a forced virtual CPU
device count — all in place before the process's first jax import.

Shared by bench.py, __graft_entry__.py and tests/conftest.py.  This module
(and the package __init__) must stay jax-free so it can be imported before
env hardening takes effect.
"""

from __future__ import annotations

import logging
import os

_DEVCOUNT_FLAG = "--xla_force_host_platform_device_count"

_logger = logging.getLogger("kube_batch_tpu")


def env_int(name: str, default: int) -> int:
    """Parse an integer knob; an unparsable value logs and keeps the
    default (the ONE shared implementation — guard/plane, serve/batcher,
    and the obs/ modules all read knobs this way)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        _logger.warning("unparsable %s=%r; using %d", name, raw, default)
        return default


def env_flag(name: str, default: bool) -> bool:
    """Parse a boolean knob: unset → default; anything but
    0/false/off/no → True."""
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    return raw not in ("0", "false", "off", "no")


def hardened_cpu_env(n_devices: int | None = None, base: dict | None = None) -> dict:
    """A copy of `base` (default os.environ) with the CPU hardening applied."""
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    if n_devices is not None:
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if not f.startswith(_DEVCOUNT_FLAG)]
        flags.append(f"{_DEVCOUNT_FLAG}={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    return env


def apply_hardened_cpu_env(n_devices: int | None = None) -> None:
    """Mutate os.environ in place; call before the first jax import."""
    os.environ.update(hardened_cpu_env(n_devices))


def enable_persistent_compilation_cache(cache_dir: str | None = None) -> None:
    """Point jax at an on-disk compilation cache so solve compiles survive
    process restarts — the driver's bench run then re-pays only the first
    round's 20-40s compiles, not every invocation's.  Safe to call multiple
    times; opt-out with KB_COMPILE_CACHE=0/false/off/no.  Call after the
    env hardening but before the first compile (it only configures jax, it
    does not trigger backend init)."""
    toggle = os.environ.get("KB_COMPILE_CACHE", "").strip().lower()
    if toggle in ("0", "false", "off", "no"):
        return
    forced_on = toggle in ("1", "true", "on", "yes")
    # CPU-pinned processes (the hardened fallback, tests) skip the disk
    # cache unless forced: XLA:CPU AOT reload warns about target-feature
    # mismatches and risks SIGILL if ~/.cache ever moves across hosts; the
    # compiles worth persisting are the TPU ones
    if os.environ.get("JAX_PLATFORMS", "") == "cpu" and not forced_on:
        return
    import logging

    if cache_dir is None:
        cache_dir = os.environ.get("KB_COMPILE_CACHE_DIR") or os.path.join(
            os.path.expanduser("~"), ".cache", "kube_batch_tpu", "jax_cache"
        )
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError as e:
        # misconfiguration must be visible — silently re-paying every
        # compile is exactly what this feature exists to avoid
        logging.getLogger("kube_batch_tpu").warning(
            "compilation cache dir %s unusable (%s); compiles will not persist",
            cache_dir, e,
        )
        return
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache every compile that takes noticeable time (default only
        # caches >1s compiles; the solves are all above that, but the many
        # small host-jnp helpers benefit too)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    except Exception as e:  # noqa: BLE001 — an old jax without the knob
        logging.getLogger("kube_batch_tpu").warning(
            "persistent compilation cache unavailable: %s", e
        )


def deregister_axon_backend() -> None:
    """Force the CPU backend in a process whose interpreter already started
    with the axon tunnel configured.  The env hardening above cannot help such
    a process: sitecustomize runs before any user code, imports jax (so
    JAX_PLATFORMS=axon is captured into jax's config defaults) and registers
    the axon PJRT factory, whose init hangs when the tunnel is wedged.  Two
    counter-measures, both only effective before jax's first backend init:
    pop the axon factory, and point jax's (already-snapshotted) platform
    config back at cpu."""
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    try:
        # Private API — kept separate so drift here can't disable the public
        # config update above.
        from jax._src import xla_bridge

        xla_bridge._backend_factories.pop("axon", None)
    except Exception:
        pass
