"""Hardened-CPU environment recipe — the single owner of the axon workaround.

With a wedged TPU tunnel, any jax dispatch in an unhardened process hangs
inside axon backend init (make_c_api_client), even work that would run on
CPU.  The recipe: JAX_PLATFORMS=cpu + PALLAS_AXON_POOL_IPS="" (so
sitecustomize skips axon registration) + optionally a forced virtual CPU
device count — all in place before the process's first jax import.

Shared by bench.py, __graft_entry__.py and tests/conftest.py.  This module
(and the package __init__) must stay jax-free so it can be imported before
env hardening takes effect.
"""

from __future__ import annotations

import os

_DEVCOUNT_FLAG = "--xla_force_host_platform_device_count"


def hardened_cpu_env(n_devices: int | None = None, base: dict | None = None) -> dict:
    """A copy of `base` (default os.environ) with the CPU hardening applied."""
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    if n_devices is not None:
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if not f.startswith(_DEVCOUNT_FLAG)]
        flags.append(f"{_DEVCOUNT_FLAG}={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    return env


def apply_hardened_cpu_env(n_devices: int | None = None) -> None:
    """Mutate os.environ in place; call before the first jax import."""
    os.environ.update(hardened_cpu_env(n_devices))


def deregister_axon_backend() -> None:
    """Force the CPU backend in a process whose interpreter already started
    with the axon tunnel configured.  The env hardening above cannot help such
    a process: sitecustomize runs before any user code, imports jax (so
    JAX_PLATFORMS=axon is captured into jax's config defaults) and registers
    the axon PJRT factory, whose init hangs when the tunnel is wedged.  Two
    counter-measures, both only effective before jax's first backend init:
    pop the axon factory, and point jax's (already-snapshotted) platform
    config back at cpu."""
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    try:
        # Private API — kept separate so drift here can't disable the public
        # config update above.
        from jax._src import xla_bridge

        xla_bridge._backend_factories.pop("axon", None)
    except Exception:
        pass
