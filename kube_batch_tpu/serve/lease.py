"""SnapshotLease — the query plane's consistent read handle.

The per-cycle resident cache (api/resident.py) refreshes device columns
with DONATING scatters: the cycle's swap invalidates the very buffers a
concurrent reader might hold.  The broker makes reads safe anyway:

- the cycle publishes a lease AFTER its swap completes (the snapshot the
  solve consumed, whole — never a half-applied delta), stamped with the
  dirty-tracker version token of the open that built it;
- probe dispatches run inside :meth:`LeaseBroker.dispatch`, which counts
  the dispatch as an in-flight READER for the device round-trip;
- the cycle's swap runs inside :meth:`LeaseBroker.swap_guard`, which
  excludes new dispatches for the swap's duration and — on donating
  backends only — waits out in-flight readers before the scatters donate
  the buffers they may still reference.  On CPU, where api/resident.py
  skips donation, the old lease's arrays stay valid: the swap neither
  waits for readers nor retires the lease, and serving continues right
  through the cycle.

The broker's condition lock is held only for bookkeeping — never across a
device round-trip or a probe compile — so the cycle's publish path cannot
stall behind a cold dispatch.  (A COLD probe shape compiling inside a
dispatch still delays a donating swap that arrives mid-compile: the swap
must wait for the reader either way.  Steady-state shapes are jit-stable,
so this is a first-request cost per (B, G, evictions) bucket, not a
recurring one.)

Version tokens are monotonic: a query answered against lease N reports
``snapshot_version: N``, and N never decreases across responses.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, NamedTuple, Optional


class SnapshotLease(NamedTuple):
    """One published read handle — everything a probe dispatch needs."""

    snap: object          # DeviceSnapshot — per-cycle RESIDENT device columns
    meta: object          # SnapshotMeta — decode tables (names, bit maps)
    version: int          # dirty-tracker version token at the open
    config: object        # AllocateConfig the session implies
    evict_config: object  # EvictConfig (preempt) for the eviction probe
    mesh: object          # the solve mesh (None = single-device)
    probe_rows: tuple     # next-free task rows (the tie-hash oracle)
    queue_rows: Dict[str, int]  # queue name → row
    #: preempt victim gates the session's conf carries that the eviction
    #: probe does NOT model (drf/proportion) — surfaced per response as
    #: `unmodeled: [...]` so clients can't silently over-trust a verdict
    unmodeled_gates: tuple = ()
    #: replication-stream record sequence number this lease's state
    #: corresponds to (replicate/); 0 = unreplicated.  Every verdict's
    #: staleness block is ``head_seq - seq`` in cycles.
    seq: int = 0


def _donation_active() -> bool:
    """api/resident.py donates the stale resident buffers everywhere but
    CPU — mirror its gate, so the broker retires leases and waits out
    readers exactly when a swap would invalidate their buffers."""
    import jax

    return jax.default_backend() != "cpu"


class LeaseBroker:
    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._lease: Optional[SnapshotLease] = None
        self._readers = 0       # in-flight probe dispatches
        self._swapping = False  # a resident swap holds exclusivity
        self.published = 0   # publish count (diagnostics)
        self.retired = 0     # swap-guard retirements (donating backends)

    # ---- write side (the cycle) -----------------------------------------
    def publish(self, lease: SnapshotLease) -> None:
        """Install a new lease.  Version must not regress — the dirty
        tracker is monotonic, so a regression means a stale publisher."""
        with self._cond:
            if self._lease is not None and lease.version < self._lease.version:
                return  # stale publisher (e.g. a re-entrant idle publish)
            self._lease = lease
            self.published += 1
            self._cond.notify_all()

    def retire(self) -> None:
        """Drop the published lease without a swap — the guard plane's
        condemned-snapshot path: a solve whose sentinel tripped must not
        keep serving what-ifs from the very columns it condemned.  Readers
        already inside a dispatch finish against their held reference; new
        dispatches wait for the next clean cycle's publish (or 503 on
        timeout) — failing closed beats answering from corrupt state."""
        with self._cond:
            if self._lease is not None:
                self._lease = None
                self.retired += 1

    @contextmanager
    def swap_guard(self):
        """The resident swap's exclusion region (wired through
        ``ColumnStore.resident_swap_guard``): new probe dispatches park
        for the swap's duration, and on donating backends the swap first
        waits out in-flight readers and retires the published lease whose
        buffers the scatters are about to invalidate (republished by the
        cycle after its solve dispatch)."""
        with self._cond:
            self._cond.wait_for(lambda: not self._swapping)
            self._swapping = True
            if _donation_active():
                # readers may hold the very buffers the swap donates
                self._cond.wait_for(lambda: self._readers == 0)
                if self._lease is not None:
                    self._lease = None
                    self.retired += 1
        try:
            yield
        finally:
            with self._cond:
                self._swapping = False
                self._cond.notify_all()

    # ---- read side (the batcher's flush) --------------------------------
    def current(self, timeout: Optional[float] = None) -> Optional[SnapshotLease]:
        """The live lease, waiting up to ``timeout`` for one to be
        published (None on timeout — the server maps it to 503)."""
        with self._cond:
            if self._lease is None and timeout:
                self._cond.wait_for(lambda: self._lease is not None,
                                    timeout=timeout)
            return self._lease

    @contextmanager
    def dispatch(self, timeout: Optional[float] = None):
        """Probe-dispatch region: yields the lease (or None on timeout)
        registered as an in-flight reader, so a concurrent swap cannot
        donate the buffers mid-read.  The broker lock itself is NOT held
        across the device round-trip — publish() and other dispatches
        proceed concurrently."""
        with self._cond:
            if timeout:
                self._cond.wait_for(
                    lambda: self._lease is not None and not self._swapping,
                    timeout=timeout,
                )
            # a swap in flight parks the dispatch regardless of timeout —
            # the pre-rewrite lock gave exactly this unconditional wait
            self._cond.wait_for(lambda: not self._swapping)
            lease = self._lease
            if lease is not None:
                self._readers += 1
        try:
            yield lease
        finally:
            if lease is not None:
                with self._cond:
                    self._readers -= 1
                    self._cond.notify_all()
