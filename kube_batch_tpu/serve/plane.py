"""QueryPlane — request encoding, probe dispatch, decode, publication.

One instance rides a SchedulerCache (``cache.query_plane``).  The
scheduling cycle publishes a :class:`serve.lease.SnapshotLease` after its
resident swap (actions/allocate.py calls :meth:`publish_session` on both
the solve path and the idle-cycle path, so an idle cluster still serves);
HTTP handler threads :meth:`submit` requests; the micro-batcher flushes
them as ONE :func:`ops.probe.probe_solve` dispatch against the lease's
device-resident columns — the shard_map variant when the lease's solve ran
sharded.

Probe answers are oracle-exact on a frozen snapshot (ops/probe.py module
docstring); the lease's ``snapshot_version`` tells clients which cache
state answered them, and every verdict carries a ``staleness`` block
(lease seq/version vs the publisher head — replicate/) bounding how far
behind the leader the serving state is.  The same plane serves follower
processes: replicate/follower.py publishes wire-rebuilt leases into this
broker and points :attr:`QueryPlane.head_fn` at the stream head.
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import Future
from typing import Dict, List, Optional

import numpy as np

from kube_batch_tpu import metrics
from kube_batch_tpu.serve.batcher import MicroBatcher, _env_float
from kube_batch_tpu.serve.lease import LeaseBroker, SnapshotLease
from kube_batch_tpu.utils import telemetry

logger = logging.getLogger("kube_batch_tpu")

#: hard cap on speculative gang size (the G bucket ceiling); larger gangs
#: are rejected 400 — a capacity-planning sweep should batch smaller asks
MAX_GANG = 64

#: the probe batch's integer columns are i32 — out-of-range values must
#: 400 their own request at parse time, never overflow inside the flush
_I32_MAX = 2**31 - 1

#: /v1/whatif/sweep: the geometric count grid the first dispatch pass
#: probes to bracket the feasibility boundary before binary search
_SWEEP_GRID = (1, 2, 4, 8, 16, 32, 64)


class WhatifError(Exception):
    """Request-level failure with an HTTP status (the handler maps it)."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


def _parse_request(body: dict, spec) -> dict:
    """Validate and normalize one /v1/whatif body.  Schema (README "Query
    plane"): queue, count, requests{cpu,memory,...}, and optional
    min_available / priority / node_selector / tolerations /
    min_resources / evictions."""
    if not isinstance(body, dict):
        raise WhatifError(400, "body must be a JSON object")
    queue = body.get("queue", "default")
    try:
        count = int(body.get("count", 1))
    except (TypeError, ValueError):
        raise WhatifError(400, "count must be an integer")
    if count < 1:
        raise WhatifError(400, "count must be >= 1")
    if count > MAX_GANG:
        raise WhatifError(400, f"count {count} exceeds the gang cap {MAX_GANG}")
    requests = body.get("requests") or {}
    if not isinstance(requests, dict):
        raise WhatifError(400, "requests must be a resource map")
    try:
        min_avail = int(body.get("min_available", count))
    except (TypeError, ValueError):
        raise WhatifError(400, "min_available must be an integer")
    # NO upper clamp to count: min_available > count is a gang that can
    # never reach readiness, and the real scheduler's gang discard reverts
    # exactly such placements — clamping would fabricate committed=true
    # where submission binds nothing (the commit gate must see the real
    # value).  The int32 bound IS enforced: the batch arrays are i32, and
    # an overflow there would 500 the whole flush window instead of
    # 400-ing this request
    min_avail = max(1, min_avail)
    if min_avail > _I32_MAX:
        raise WhatifError(400, "min_available out of range")
    selector = body.get("node_selector") or {}
    if not isinstance(selector, dict):
        raise WhatifError(400, "node_selector must be a label map")
    # tolerations/min_resources/priority are validated HERE, per request —
    # a malformed field must 400 its own request at submit time, never
    # surface inside the batch flush where it would 500 the whole window
    raw_tol = body.get("tolerations") or []
    if not isinstance(raw_tol, list):
        raise WhatifError(400, "tolerations must be a list")
    from kube_batch_tpu.api.pod import Toleration

    try:
        tolerations = [Toleration(**d) for d in raw_tol]
    except TypeError:
        raise WhatifError(400, "malformed toleration")
    min_resources = body.get("min_resources")
    if min_resources is not None:
        if not isinstance(min_resources, dict):
            raise WhatifError(400, "min_resources must be a resource map")
        try:
            min_resources = {str(k): float(v) for k, v in min_resources.items()}
        except (TypeError, ValueError):
            raise WhatifError(400, "min_resources values must be numeric")
    try:
        priority = int(body.get("priority", 0) or 0)
    except (TypeError, ValueError):
        raise WhatifError(400, "priority must be an integer")
    if not -_I32_MAX - 1 <= priority <= _I32_MAX:
        raise WhatifError(400, "priority out of range")
    # per-member resource vector — the SAME conversion an ingested pod's
    # TaskInfo applies (pods dim included), so the probe's rows carry
    # exactly what submission would
    from kube_batch_tpu.api.task_info import _requests_to_resource

    try:
        res = _requests_to_resource(
            {k: float(v) for k, v in requests.items()}, spec
        )
        req_vec = res.vec.astype(np.float32)
        # BestEffort member (empty InitResreq, the backfill path's pods):
        # the probe never models backfill binds, so the verdict carries an
        # explicit `unmodeled` entry instead of a silently-wrong verdict
        best_effort = bool(res.is_empty())
    except (TypeError, ValueError):
        raise WhatifError(400, "requests values must be numeric")
    return {
        "queue": str(queue),
        "count": count,
        "min_avail": min_avail,
        "priority": priority,
        "selector": {str(k): str(v) for k, v in selector.items()},
        "tolerations": tolerations,  # parsed Toleration objects
        "min_resources": min_resources,
        "req_vec": req_vec,
        "best_effort": best_effort,
        "evictions": bool(body.get("evictions", False)),
        "_t0": telemetry.perf_counter(),
    }


class QueryPlane:
    def __init__(self, cache, max_batch: Optional[int] = None,
                 window_s: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 dispatch_timeout: Optional[float] = None,
                 start_thread: bool = True, prewarm: bool = False):
        cols = getattr(cache, "columns", None)
        if cols is None:
            raise ValueError("QueryPlane requires a columnar SchedulerCache")
        self.cache = cache
        self.broker = LeaseBroker()
        # KB_WHATIF_TIMEOUT_S bounds the wait for a lease inside a flush;
        # the HTTP handler derives its request timeout from it, so raising
        # the knob also buys a cold probe compile more headroom
        self.dispatch_timeout = (
            dispatch_timeout if dispatch_timeout is not None
            else _env_float("KB_WHATIF_TIMEOUT_S", 2.0)
        )
        self.max_gang = MAX_GANG
        # prewarm=True (the production server path) compiles the serving
        # floor bucket off the request path at each new lease shape, so the
        # first real window hits a warm jit cache instead of timing out
        # behind a cold compile
        self._prewarm = prewarm
        self._warm_lock = threading.Lock()
        self._warmed: set = set()
        self._warm_threads: List[threading.Thread] = []
        self._gate_gap_warned = False  # one-shot victim-gate divergence log
        # probe dispatches and the cycle's donating resident swaps exclude
        # each other through the broker (serve/lease.py module docstring).
        # The bound method is captured ONCE: attribute access creates a
        # fresh bound-method object each time, so close()'s identity check
        # needs this exact object to detach cleanly.
        self._swap_guard = self.broker.swap_guard
        cols.resident_swap_guard = self._swap_guard
        cache.query_plane = self
        # replication head source for the staleness block: None (the
        # leader — the lease IS the head) or a () -> (head_seq,
        # head_version) callable (followers point it at their applier)
        self.head_fn = None
        self.batcher = MicroBatcher(
            self._flush, max_batch=max_batch, window_s=window_s,
            max_queue=max_queue, start_thread=start_thread,
        )
        self.dispatches = 0
        self.requests_served = 0

    def close(self) -> None:
        self.batcher.stop()
        # bounded join on the prewarm workers: they are daemon threads, but
        # a closed plane must be quiescent (no compile racing teardown) —
        # each warm is one probe, so a short timeout covers the honest case
        # and a wedged compile can't hang close()
        for t in self._warm_threads:
            t.join(timeout=5.0)
        self._warm_threads = []
        cols = getattr(self.cache, "columns", None)
        if cols is not None and cols.resident_swap_guard is self._swap_guard:
            cols.resident_swap_guard = None
        if getattr(self.cache, "query_plane", None) is self:
            self.cache.query_plane = None

    # ------------------------------------------------------------------
    # publication (called from the cycle — actions/allocate.py)
    # ------------------------------------------------------------------
    def needs_publish(self, version: int) -> bool:
        """False when the live lease already carries ``version`` — an idle
        cycle with no ingest since the last publish can skip the snapshot
        build + resident swap entirely (the existing lease describes the
        exact same cache state)."""
        lease = self.broker.current()
        return lease is None or lease.version < version

    def publish_session(self, ssn, snap, meta) -> None:
        """Publish the lease for this cycle: the device-resident snapshot
        the solve consumed (memoized — the swap already ran for the solve
        dispatch), the session's solve configs, the dirty-tracker version
        token, and the row-allocator peek that keys the tie-hash oracle."""
        cols = ssn.columns
        if cols is None:
            return  # isolated/object session — nothing resident to lease
        from kube_batch_tpu.actions.allocate import session_allocate_config
        from kube_batch_tpu.actions.reclaim import victim_gates
        from kube_batch_tpu.api.columns import resident_snap
        from kube_batch_tpu.ops.eviction import EvictConfig
        from kube_batch_tpu.parallel.mesh import default_mesh, should_shard

        mesh = (
            default_mesh() if should_shard(snap.node_alloc.shape[0]) else None
        )
        dev = resident_snap(cols, snap, mesh=mesh)
        # the probe never runs the Pallas head (bit-exact either way; G is
        # far below the kernel tile) — strip the flag so serving shares one
        # compile cache regardless of the write path's opt-in
        config = session_allocate_config(ssn)._replace(use_pallas=False)
        gates = victim_gates(ssn, "preempt")
        if not self._gate_gap_warned and gates & {"drf", "proportion"}:
            # a conf whose first voting preempt tier includes drf or
            # proportion victim gates is outside the eviction probe's
            # model (README "Query plane" modeled scope) — its victim
            # answers can diverge from the committed preempt solve.  Say
            # so once instead of silently serving wrong eviction sets.
            self._gate_gap_warned = True
            logger.warning(
                "whatif eviction probe does not model the conf's %s victim "
                "gate(s): /v1/whatif evictions answers may diverge from "
                "the committed preempt solve under this conf",
                sorted(gates & {"drf", "proportion"}),
            )
        evict_config = EvictConfig(
            mode="preempt",
            gang=ssn.plugin_enabled("gang"),
            drf=ssn.plugin_enabled("drf"),
            proportion=ssn.plugin_enabled("proportion"),
            victim_gang="gang" in gates,
            victim_conformance="conformance" in gates,
            # victim_drf/victim_proportion are not modeled by the eviction
            # probe (they never bind under the shipped two-tier conf, whose
            # first voting tier is gang+conformance; non-default confs get
            # the one-shot divergence warning above — README modeled scope)
            victim_drf=False,
            victim_proportion=False,
            weights=ssn.score_weights,
        )
        queue_rows = {
            name: i for i, name in enumerate(meta.queue_names) if name
        }
        lease = SnapshotLease(
            snap=dev,
            meta=meta,
            version=int(getattr(ssn.cache, "last_open_version", 0)),
            config=config,
            evict_config=evict_config,
            mesh=mesh,
            probe_rows=tuple(cols.peek_task_rows(self.max_gang)),
            queue_rows=queue_rows,
            unmodeled_gates=tuple(sorted(gates & {"drf", "proportion"})),
        )
        pub = getattr(self.cache, "replication", None)
        if pub is not None:
            # publish the cycle onto the replication stream BEFORE the
            # broker install, so the lease carries the record's seq and
            # leader verdicts report the same staleness coordinates a
            # caught-up follower's do.  The resident swap's own delta
            # record rides along as the diff fast path.
            try:
                hint, hint_version = cols.export_delta_record(mesh)
                seq = pub.publish_cycle(
                    snap, meta, lease, delta_hint=hint,
                    cache_version=hint_version,
                )
                lease = lease._replace(seq=seq)
            except Exception:  # noqa: BLE001 — replication must never stall the cycle
                logger.exception(
                    "replication publish failed; followers will resync")
        self.broker.publish(lease)
        metrics.set_whatif_snapshot_version(lease.version)
        if self._prewarm:
            self._maybe_prewarm(lease)

    def _maybe_prewarm(self, lease: SnapshotLease) -> None:
        """Compile the serving floor bucket — (B, G=8, no evictions) — in a
        background thread the first time a lease with this (mesh, config,
        snapshot-shape) signature is published.  A cold probe compile at
        real serving scale outlasts the request timeout, so without this
        the first window after startup (and after every shape-bucket
        growth) would 503 through a healthy system.  The eviction variant
        stays lazily compiled: it runs in its own dispatch (see _flush),
        so only its first requester waits on it.

        The warm dispatch probes a ZEROS TWIN of the lease snapshot, not
        the lease itself: the jit cache keys on shapes/dtypes/shardings,
        never values, and a warm thread registered as a broker reader for
        the compile's duration would block a donating resident swap — and
        with it the scheduling cycle — for that whole time, inverting
        "the write path outranks serving"."""
        key = (
            lease.mesh, lease.config, lease.evict_config,
            tuple(tuple(getattr(a, "shape", ())) for a in lease.snap),
        )
        with self._warm_lock:
            if key in self._warmed:
                return
            self._warmed.add(key)

        def warm():
            import jax
            import jax.numpy as jnp

            req = {
                "queue": "", "count": 1, "min_avail": 1, "priority": 0,
                "selector": {}, "tolerations": [], "min_resources": None,
                "req_vec": np.zeros(
                    int(lease.snap.task_req.shape[1]), np.float32),
                "evictions": False, "_t0": telemetry.perf_counter(),
            }
            try:
                # the twin's columns are task/node VECTORS (a few MB), not
                # the solve's [T, N] intermediates, so the clone is cheap;
                # the lease's own buffers are never read, so a concurrent
                # swap can donate them mid-warm without consequence (shape
                # and sharding are metadata — readable even off a donated
                # array)
                twin = jax.tree_util.tree_map(
                    lambda a: jax.device_put(
                        jnp.zeros(a.shape, a.dtype), a.sharding),
                    lease.snap,
                )
                self._probe(lease._replace(snap=twin), [req], record=False)
            except Exception:  # noqa: BLE001 — warm-up only; serving still works cold
                logger.exception("whatif probe pre-warm failed")

        t = threading.Thread(target=warm, daemon=True, name="whatif-prewarm")
        # prune finished warms: a long-lived server crosses shape buckets
        # repeatedly, and an append-only list would retain every dead
        # thread (and its closure) for the process lifetime
        self._warm_threads = [w for w in self._warm_threads if w.is_alive()]
        self._warm_threads.append(t)
        t.start()

    # ------------------------------------------------------------------
    # request intake (HTTP handler threads)
    # ------------------------------------------------------------------
    def submit(self, body: dict) -> Future:
        """Validate and enqueue one request; the future resolves to the
        response dict (WhatifError for request-level failures)."""
        req = _parse_request(body, self.cache.spec)  # raises WhatifError(400)
        # overflow/stopped comes back as a QueueFull already set ON the
        # future (batcher.submit never raises)
        return self.batcher.submit(req)

    def submit_sweep(self, body: dict) -> Future:
        """Validate and enqueue one /v1/whatif/sweep request — the
        server-side "how many replicas of this gang fit" binary search.
        The body is a normal whatif body plus ``max_count`` (default the
        gang cap); ``count``/``min_available`` are ignored — each probed
        point c asks for a gang of c members, all required
        (min_available=c).  The future resolves to the sweep response."""
        req = _parse_request(body, self.cache.spec)
        if req["evictions"]:
            raise WhatifError(400, "sweep does not support evictions")
        try:
            max_count = int(body.get("max_count", MAX_GANG))
        except (TypeError, ValueError):
            raise WhatifError(400, "max_count must be an integer")
        if not 1 <= max_count <= MAX_GANG:
            raise WhatifError(
                400, f"max_count must be in [1, {MAX_GANG}]")
        req["max_count"] = max_count
        req["_sweep"] = True
        return self.batcher.submit(req)

    # ------------------------------------------------------------------
    # batch flush — ONE device dispatch for every queued request
    # ------------------------------------------------------------------
    def _flush(self, batch) -> None:
        # a client that timed out already 503'd and CANCELLED its future
        # (cmd/server.py) — don't spend device time on abandoned probes,
        # and don't let them into the verdict/latency metrics: a stalled
        # window would otherwise record N "successes" nobody received,
        # masking the outage in exactly the serving SLO series
        batch = [(r, f) for r, f in batch if not f.cancelled()]
        if not batch:
            return
        metrics.observe_whatif_batch(len(batch), self.batcher.depth())
        # a mixed window splits by the evictions flag: with_evictions is a
        # static jit arg selecting a superset program, so one --evictions
        # request must not make every co-batched plain probe pay the
        # eviction pass's device time (each sub-batch is still a jit-stable
        # (B, G) bucket — at most two dispatches per window, answered
        # against the SAME lease).  Sweeps run their own multi-dispatch
        # search, still inside the single held dispatch region, so every
        # probed point answers against one snapshot.
        sweeps = [(r, f) for r, f in batch if r.get("_sweep")]
        plain = [(r, f) for r, f in batch if not r.get("_sweep")]
        subs = [
            [(r, f) for r, f in plain if not r["evictions"]],
            [(r, f) for r, f in plain if r["evictions"]],
        ]
        done = []
        done_sweeps = []
        with self.broker.dispatch(timeout=self.dispatch_timeout) as lease:
            if lease is None:
                err = WhatifError(
                    503, "no snapshot lease published yet (scheduler warming)"
                )
                for _req, fut in batch:
                    if self._deliver(fut, error=err):
                        metrics.register_whatif_request("error")
                return
            for sub in subs:
                if not sub:
                    continue
                try:
                    done.append(
                        (sub, self._probe(lease, [req for req, _ in sub]))
                    )
                except Exception as e:  # noqa: BLE001 — fail THIS sub-batch, keep serving
                    logger.exception("whatif probe dispatch failed")
                    for _req, fut in sub:
                        if self._deliver(
                            fut, error=WhatifError(500, f"probe failed: {e}")
                        ):
                            metrics.register_whatif_request("error")
            for req, fut in sweeps:
                try:
                    done_sweeps.append((req, fut, self._sweep(lease, req)))
                except Exception as e:  # noqa: BLE001 — fail THIS sweep, keep serving
                    logger.exception("whatif sweep failed")
                    if self._deliver(
                        fut, error=WhatifError(500, f"sweep failed: {e}")
                    ):
                        metrics.register_whatif_request("error")
        for req, fut, resp in done_sweeps:
            if not self._deliver(fut, result=resp):
                continue
            metrics.register_whatif_sweep()
            metrics.observe_whatif_latency(
                (telemetry.perf_counter() - req["_t0"]) * 1e3
            )
            self.requests_served += 1
        for sub, results in done:
            for (req, fut), resp in zip(sub, results):
                if not self._deliver(fut, result=resp):
                    continue  # client gave up mid-dispatch
                verdict = "feasible" if resp["feasible"] else "infeasible"
                metrics.register_whatif_request(verdict)
                metrics.observe_whatif_latency(
                    (telemetry.perf_counter() - req["_t0"]) * 1e3
                )
                self.requests_served += 1

    def _sweep(self, lease: SnapshotLease, req: dict) -> dict:
        """Binary-search the largest replica count whose gang fits,
        against ONE lease: a geometric grid pass brackets the feasibility
        boundary (one or two chunked probe dispatches), then classic
        binary search refines it — the server does the log(N) probes the
        client would otherwise issue as round-trips, and every point
        answers against the same snapshot (feasibility is monotone in
        count on a frozen snapshot: a (c+1)-gang placement contains a
        c-gang placement)."""
        max_count = req["max_count"]
        feasible: Dict[int, bool] = {}
        probes = 0

        def probe(counts: List[int]) -> None:
            nonlocal probes
            for i in range(0, len(counts), self.batcher.max_batch):
                chunk = counts[i:i + self.batcher.max_batch]
                reqs = [dict(req, count=c, min_avail=c) for c in chunk]
                for c, r in zip(chunk, self._probe(lease, reqs)):
                    feasible[c] = bool(r["feasible"])
                probes += len(chunk)

        grid = sorted({c for c in _SWEEP_GRID if c < max_count}
                      | {max_count})
        probe(grid)
        if not feasible[grid[0]]:
            lo = 0
        elif feasible[max_count]:
            lo = max_count
        else:
            lo = max(c for c in grid if feasible[c])
            hi = min(c for c in grid if not feasible[c])
            while hi - lo > 1:
                mid = (lo + hi) // 2
                probe([mid])
                if feasible[mid]:
                    lo = mid
                else:
                    hi = mid
        return {
            "snapshot_version": lease.version,
            "max_fit": lo,
            "feasible": lo >= 1,
            "max_count": max_count,
            "probes": probes,
            "staleness": self._staleness(lease),
        }

    @staticmethod
    def _deliver(fut: Future, result=None, error=None) -> bool:
        """Resolve a request future, tolerating a concurrent client
        cancellation (the handler cancels on its timeout) — returns
        whether the answer was actually delivered, so abandoned requests
        stay out of the serving counters."""
        try:
            if error is not None:
                fut.set_exception(error)
            else:
                fut.set_result(result)
            return True
        except Exception:  # noqa: BLE001 — cancelled between check and set
            return False

    # ---- encoding ----------------------------------------------------
    def _encode(self, lease: SnapshotLease, reqs: List[dict]):
        from kube_batch_tpu.api.snapshot import _TaintView, _pack_bits, bucket
        from kube_batch_tpu.ops.probe import ProbeBatch

        snap, meta = lease.snap, lease.meta
        R = int(snap.task_req.shape[1])
        W = int(snap.task_sel_bits.shape[1])
        Wt = int(snap.task_tol_bits.shape[1])
        B = self.batcher.max_batch      # FIXED bucket — no retrace on fill
        G = min(self.max_gang,
                bucket(max(r["count"] for r in reqs), floor=8))
        spec = self.cache.spec

        req_arr = np.zeros((B, G, R), np.float32)
        valid = np.zeros((B, G), bool)
        min_avail = np.ones(B, np.int32)
        queue = np.full(B, -1, np.int32)
        prio = np.zeros(B, np.int32)
        sel_bits = np.zeros((B, W), np.uint32)
        sel_imp = np.zeros(B, bool)
        tol_bits = np.zeros((B, Wt), np.uint32)
        min_res = np.zeros((B, R), np.float32)
        has_min_res = np.zeros(B, bool)
        taint_list = list(meta.taint_bit.items())
        for b, r in enumerate(reqs):
            n = r["count"]
            req_arr[b, :n] = r["req_vec"]
            valid[b, :n] = True
            min_avail[b] = r["min_avail"]
            queue[b] = lease.queue_rows.get(r["queue"], -1)
            prio[b] = r["priority"]
            # selector pairs → required label bits (build_snapshot's exact
            # encoding: a pair no node carries makes the selector impossible)
            bits: List[int] = []
            for k, v in r["selector"].items():
                bit = meta.label_pair_bit.get((k, v))
                if bit is None:
                    sel_imp[b] = True
                else:
                    bits.append(bit)
            if bits:
                sel_bits[b] = _pack_bits(bits, W)
            if r["tolerations"] and taint_list:
                # already-parsed Toleration objects (_parse_request)
                tb = [
                    bit for (tk, tv, te), bit in taint_list
                    if any(t.tolerates(_TaintView(tk, tv, te))
                           for t in r["tolerations"])
                ]
                if tb:
                    tol_bits[b] = _pack_bits(tb, Wt)
            mr = r["min_resources"]
            if mr is not None:
                has_min_res[b] = True
                for name, v in mr.items():
                    if name in spec:
                        min_res[b, spec.index(name)] = v
        pbatch = ProbeBatch(
            req=req_arr, valid=valid, min_avail=min_avail, queue=queue,
            prio=prio, sel_bits=sel_bits, sel_impossible=sel_imp,
            tol_bits=tol_bits, min_res=min_res, has_min_res=has_min_res,
        )
        rows = np.asarray(lease.probe_rows[:G], np.int32)
        return pbatch, rows

    # ---- dispatch + decode -------------------------------------------
    def _probe(self, lease: SnapshotLease, reqs: List[dict],
               record: bool = True) -> List[dict]:
        import jax

        from kube_batch_tpu.ops.probe import probe_solve

        pbatch, rows = self._encode(lease, reqs)
        with_evictions = any(r["evictions"] for r in reqs)
        if lease.mesh is not None:
            from kube_batch_tpu.parallel.mesh import sharded_probe_solve

            res = sharded_probe_solve(
                lease.snap, pbatch, rows, lease.mesh, lease.config,
                lease.evict_config, with_evictions,
            )
        else:
            res = probe_solve(
                lease.snap, pbatch, rows, lease.config,
                lease.evict_config, with_evictions,
            )
        if record:  # pre-warm dispatches stay out of the serving counters
            self.dispatches += 1
            metrics.register_whatif_dispatch()
        if not with_evictions:
            # the eviction fields are all-zeros placeholders on this
            # program, and victims is [B, T]-sized — at big snapshots that
            # dead transfer would rival the batch window itself.  None is
            # an empty pytree: device_get skips it, and _decode only reads
            # these fields for evictions requests (the flush partitions
            # windows by that flag, so the sub-batch is uniform)
            res = res._replace(
                claim_node=None, victims=None, evict_covered=None
            )
        # kbt: allow[KBT010] THE sanctioned serving choke point: one
        # blocking transfer per batch window — the whole point of the
        # micro-batcher is that every queued request shares it
        host = jax.device_get(res)
        return [
            self._decode(lease, r, host, b) for b, r in enumerate(reqs)
        ]

    def _staleness(self, lease: SnapshotLease) -> dict:
        """The version-token-bounded staleness block every verdict
        carries: this lease's replication coordinates vs the stream head.
        On the leader (``head_fn`` unset) the lease IS the head — lag 0
        by construction; a follower reports the head of its last fetched
        frame, so ``lag_cycles`` bounds how many cycles behind the
        answering state is."""
        head_seq, head_version = (
            self.head_fn() if self.head_fn is not None
            else (lease.seq, lease.version)
        )
        return {
            "seq": lease.seq,
            "version": lease.version,
            "head_seq": head_seq,
            "head_version": head_version,
            "lag_cycles": max(0, head_seq - lease.seq),
        }

    def _decode(self, lease: SnapshotLease, req: dict, host, b: int) -> dict:
        from kube_batch_tpu.ops.feasibility import REASON_MESSAGES

        meta = lease.meta
        n = req["count"]
        assigned = np.asarray(host.assigned[b][:n])
        pipelined = np.asarray(host.pipelined[b][:n])
        node_names = meta.node_names
        nodes = [
            node_names[i] if 0 <= i < len(node_names) else None
            for i in assigned.tolist()
        ]
        feasible = bool(host.feasible[b])
        unplaced = int(np.sum(assigned < 0))
        # verdict honesty: every gap between this probe's model and the
        # committed pipeline that APPLIES to this request is surfaced per
        # response — a client must never silently over-trust a verdict
        # (these were one-shot process logs before; a log line is invisible
        # to the caller who needs it)
        unmodeled = []
        if req["evictions"]:
            unmodeled += [
                f"preempt victim gate '{g}' (conf tier) is not modeled by "
                "the eviction probe — victim sets may diverge from the "
                "committed preempt solve"
                for g in lease.unmodeled_gates
            ]
        if req.get("best_effort"):
            unmodeled.append(
                "all members are BestEffort (sub-quanta requests): the "
                "committed pipeline binds them via backfill, which this "
                "probe does not model — 'infeasible' here is expected"
            )
        out = {
            "snapshot_version": lease.version,
            "feasible": feasible,
            "committed": bool(host.committed[b]),
            "enqueue_admitted": bool(host.enqueue_ok[b]),
            "nodes": nodes,
            "pipelined": [bool(p) for p in pipelined.tolist()],
            "unplaced": unplaced,
            "unmodeled": unmodeled,
            "staleness": self._staleness(lease),
        }
        if unplaced:
            # fit-error reasons summed over the unplaced members — the same
            # histogram rows the committed cycle would record as FitErrors
            hist = np.asarray(host.reasons[b][:n])[assigned < 0].sum(axis=0)
            out["fit_errors"] = {
                msg: int(c) for msg, c in zip(REASON_MESSAGES, hist.tolist())
                if c
            }
        if req["evictions"]:
            claim = np.asarray(host.claim_node[b][:n])
            victims = np.flatnonzero(np.asarray(host.victims[b]))
            task_keys = meta.task_keys
            out["evictions"] = {
                "claim_nodes": [
                    node_names[i] if 0 <= i < len(node_names) else None
                    for i in claim.tolist()
                ],
                "victims": sorted(
                    task_keys[t] for t in victims.tolist()
                    if t < len(task_keys) and task_keys[t]
                ),
                "covered": bool(host.evict_covered[b]),
            }
        return out
