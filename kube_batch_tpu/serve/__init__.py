"""serve/ — the read-side query plane over the resident snapshot.

The write path (actions/, the scheduling cycle) COMMITS decisions; this
package SERVES speculative ones at high QPS off the same compiled solve:
``POST /v1/whatif`` answers "would this gang fit, where, and what would it
evict?" without a Statement.

Three layers:

- :mod:`serve.lease` — ``SnapshotLease`` / ``LeaseBroker``: a consistent
  read handle over the per-cycle device-resident columns (api/resident.py),
  carrying the dirty-tracker version token.  Safe concurrent with the
  cycle: probes answered against lease N report ``snapshot_version: N``
  and never observe a half-applied scatter delta.
- :mod:`serve.batcher` — ``MicroBatcher``: collects concurrent requests
  into one probe dispatch per tick window (bounded queue, deadline-based
  flush, per-request futures) — hundreds of speculative queries amortized
  into one device dispatch.
- :mod:`serve.plane` — ``QueryPlane``: request parsing/encoding against
  the lease's meta, the batched :func:`ops.probe.probe_solve` dispatch
  (shard_map variant on multi-device meshes), decode, and the
  ``volcano_whatif_*`` metrics.

Wired into cmd/server.py beside the admin API; ``python -m
kube_batch_tpu.cli.whatif`` is the client, ``python scripts/whatif_smoke.py``
the CI smoke (run by scripts/check.sh).
"""

from kube_batch_tpu.serve.lease import LeaseBroker, SnapshotLease  # noqa: F401
from kube_batch_tpu.serve.plane import QueryPlane, WhatifError  # noqa: F401
