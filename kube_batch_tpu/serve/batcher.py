"""MicroBatcher — the /v1/whatif front end's amortization engine.

Concurrent HTTP handler threads call :meth:`submit` and block on a future;
one worker thread collects requests into a batch and hands it to the flush
callback (the QueryPlane's probe dispatch).  Flush fires when EITHER the
batch bucket fills OR the oldest queued request's deadline window elapses
— so a lone request pays at most ``window`` extra latency while a burst of
hundreds rides one device dispatch.

Knobs (all overridable per instance; env defaults):

- ``KB_WHATIF_BATCH``   — batch bucket (max requests per dispatch), default 16
- ``KB_WHATIF_WINDOW_MS`` — flush deadline from first enqueue, default 5 ms
- ``KB_WHATIF_QUEUE``   — bounded queue depth; overflow rejects the request
  immediately (503 at the HTTP layer) instead of building unbounded backlog

The clock is injected for the deadline/overflow tests (a stubbed clock +
``tick()`` drives the flush logic deterministically without the thread).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, List, Optional, Tuple

from kube_batch_tpu.envutil import env_int


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class QueueFull(Exception):
    """The bounded request queue is at capacity — shed, don't buffer."""


class MicroBatcher:
    def __init__(
        self,
        flush: Callable[[List[Tuple[object, Future]]], None],
        max_batch: Optional[int] = None,
        window_s: Optional[float] = None,
        max_queue: Optional[int] = None,
        clock=time,
        start_thread: bool = True,
    ):
        self._flush = flush
        self.max_batch = max_batch if max_batch is not None else env_int(
            "KB_WHATIF_BATCH", 16)
        self.window_s = window_s if window_s is not None else _env_float(
            "KB_WHATIF_WINDOW_MS", 5.0) / 1e3
        self.max_queue = max_queue if max_queue is not None else env_int(
            "KB_WHATIF_QUEUE", 1024)
        self.clock = clock
        self._cond = threading.Condition()
        self._pending: deque = deque()  # (request, future, enqueue_t)
        self._stopped = False
        self.rejected = 0
        self._thread: Optional[threading.Thread] = None
        if start_thread:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="whatif-batcher"
            )
            self._thread.start()

    # ---- producer side ---------------------------------------------------
    def submit(self, request) -> Future:
        """Enqueue one request; the returned future resolves with the
        flush callback's per-request answer (or QueueFull immediately when
        the bounded queue is at capacity)."""
        fut: Future = Future()
        with self._cond:
            if self._stopped:
                fut.set_exception(QueueFull("batcher stopped"))
                return fut
            if len(self._pending) >= self.max_queue:
                self.rejected += 1
                fut.set_exception(QueueFull(
                    f"whatif queue at capacity ({self.max_queue})"))
                return fut
            self._pending.append((request, fut, self.clock.monotonic()))
            self._cond.notify_all()
        return fut

    def depth(self) -> int:
        with self._cond:
            return len(self._pending)

    # ---- flush logic (thread-driven in production, tick-driven in tests) -
    def _due(self, now: float) -> bool:
        """Flush condition under the lock: bucket full or window elapsed."""
        if not self._pending:
            return False
        if len(self._pending) >= self.max_batch:
            return True
        return now - self._pending[0][2] >= self.window_s

    def _take(self) -> List[Tuple[object, Future]]:
        n = min(self.max_batch, len(self._pending))
        out = []
        for _ in range(n):
            req, fut, _t = self._pending.popleft()
            out.append((req, fut))
        return out

    def tick(self, now: Optional[float] = None) -> int:
        """Flush if due; returns the number of requests flushed.  The unit
        tests drive this directly with a stubbed clock; the worker thread
        is just tick() in a wait loop."""
        now = self.clock.monotonic() if now is None else now
        with self._cond:
            if not self._due(now):
                return 0
            batch = self._take()
        self._run_flush(batch)
        return len(batch)

    def _run_flush(self, batch: List[Tuple[object, Future]]) -> None:
        try:
            self._flush(batch)
        except Exception as e:  # noqa: BLE001 — a failed dispatch fails ITS batch only
            for _req, fut in batch:
                if not fut.done():
                    fut.set_exception(e)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stopped:
                    self._cond.wait()
                if self._stopped:
                    break
                # wait until tick's OWN flush condition holds — _due is
                # the single flush policy (bucket full, or the FIRST
                # queued request's window elapsed; submit notifies on
                # fill, the timed wait tracks the window deadline)
                while (not self._due(self.clock.monotonic())
                       and not self._stopped):
                    remaining = (
                        self._pending[0][2] + self.window_s
                        - self.clock.monotonic()
                    )
                    # remaining > 0 here: an elapsed window makes _due
                    # true (a clock race just means an immediate recheck)
                    self._cond.wait(max(remaining, 0.0))
                if self._stopped:
                    break
                batch = self._take()
            self._run_flush(batch)
        # drain on stop: fail whatever is still queued
        with self._cond:
            leftovers = list(self._pending)
            self._pending.clear()
        for _req, fut, _t in leftovers:
            if not fut.done():
                fut.set_exception(QueueFull("batcher stopped"))

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
