from kube_batch_tpu.testing.synthetic import synthetic_cluster, synthetic_device_snapshot

__all__ = ["synthetic_cluster", "synthetic_device_snapshot"]
