"""Synthetic cluster generator — the kubemark successor (SURVEY.md §4.3).

The reference scale-tests against GCE "hollow node" clusters (test/kubemark);
here synthetic workloads feed the device snapshot directly — no apiserver —
at the BASELINE.json config matrix scale (50k pods × 5k nodes, gang
minMember=4, multi-queue DRF/proportion, heterogeneous GPU gangs).

Two constructors:
  synthetic_device_snapshot — builds the SoA arrays directly (bench hot path;
    building 50k host TaskInfo objects would measure Python, not the solver)
  synthetic_cluster — builds a real SchedulerCache through the event handlers
    (used for smaller end-to-end tests of the full loop)
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from kube_batch_tpu.api.pod import Node, PodGroup, Queue
from kube_batch_tpu.api.resources import GPU, ResourceSpec
from kube_batch_tpu.api.snapshot import DeviceSnapshot, SnapshotMeta, UNBOUNDED, bucket
from kube_batch_tpu.api.types import PodPhase, TaskStatus

GiB = float(2**30)

NODE_CPU = 32000.0       # 32 cores in milli
NODE_MEM = 128 * GiB
NODE_PODS = 110.0
NODE_GPU = 8000.0        # 8 GPUs in milli

CPU_CHOICES = np.array([250.0, 500.0, 1000.0, 2000.0, 4000.0])
MEM_CHOICES = np.array([1, 2, 4, 8]) * GiB


def synthetic_device_snapshot(
    n_tasks: int = 50_000,
    n_nodes: int = 5_000,
    gang_size: int = 4,
    n_queues: int = 3,
    gpu_task_frac: float = 0.0,
    gpu_node_frac: float = 0.25,
    seed: int = 0,
    spec: Optional[ResourceSpec] = None,
) -> Tuple[DeviceSnapshot, SnapshotMeta]:
    """Direct SoA construction of a pending synthetic workload."""
    rng = np.random.default_rng(seed)
    spec = spec or ResourceSpec(scalar_names=(GPU,))
    R = spec.n
    gpu_col = spec.index(GPU)

    n_jobs = -(-n_tasks // gang_size)
    T, N, J, Q = bucket(n_tasks), bucket(n_nodes), bucket(n_jobs), bucket(n_queues)

    # ---- tasks ----------------------------------------------------------
    task_req = np.zeros((T, R), np.float32)
    task_req[:n_tasks, 0] = rng.choice(CPU_CHOICES, n_tasks)
    task_req[:n_tasks, 1] = rng.choice(MEM_CHOICES, n_tasks)
    task_req[:n_tasks, 2] = 1.0
    task_job = np.zeros(T, np.int32)
    task_job[:n_tasks] = np.arange(n_tasks) // gang_size
    if gpu_task_frac > 0:
        # whole gangs ask for GPUs so gang semantics stay heterogeneous
        gpu_jobs = rng.random(n_jobs) < gpu_task_frac
        is_gpu_task = gpu_jobs[task_job[:n_tasks]]
        task_req[:n_tasks, gpu_col] = np.where(
            is_gpu_task, rng.choice([1000.0, 2000.0, 4000.0], n_tasks), 0.0
        )
    task_valid = np.zeros(T, bool)
    task_valid[:n_tasks] = True

    # ---- nodes ----------------------------------------------------------
    node_alloc = np.zeros((N, R), np.float32)
    node_alloc[:n_nodes, 0] = NODE_CPU
    node_alloc[:n_nodes, 1] = NODE_MEM
    node_alloc[:n_nodes, 2] = NODE_PODS
    n_gpu_nodes = int(n_nodes * gpu_node_frac)
    node_alloc[:n_gpu_nodes, gpu_col] = NODE_GPU
    node_valid = np.zeros(N, bool)
    node_valid[:n_nodes] = True

    # ---- jobs -----------------------------------------------------------
    job_min = np.zeros(J, np.int32)
    job_min[:n_jobs] = np.minimum(
        gang_size, n_tasks - np.arange(n_jobs) * gang_size
    )  # last gang may be short
    job_queue = np.zeros(J, np.int32)
    job_queue[:n_jobs] = np.arange(n_jobs) % n_queues
    job_prio = np.zeros(J, np.int32)
    job_prio[:n_jobs] = np.where(rng.random(n_jobs) < 0.05, 100, 0)
    job_valid = np.zeros(J, bool)
    job_valid[:n_jobs] = True

    # ---- queues ---------------------------------------------------------
    queue_weight = np.ones(Q, np.float32)
    queue_weight[:n_queues] = 1.0 + np.arange(n_queues)
    queue_valid = np.zeros(Q, bool)
    queue_valid[:n_queues] = True
    queue_request = np.zeros((Q, R), np.float32)
    np.add.at(queue_request, job_queue[task_job[:n_tasks]], task_req[:n_tasks])

    total = node_alloc[:n_nodes].sum(axis=0).astype(np.float32)

    snap = DeviceSnapshot(
        task_req=task_req,
        task_resreq=task_req.copy(),
        task_job=task_job,
        task_prio=np.zeros(T, np.int32),
        task_creation=np.arange(T, dtype=np.int32),
        task_status=np.where(task_valid, TaskStatus.PENDING, TaskStatus.UNKNOWN).astype(
            np.int32
        ),
        task_valid=task_valid,
        task_pending=task_valid.copy(),
        task_best_effort=np.zeros(T, bool),
        task_sel_bits=np.zeros((T, 1), np.uint32),
        task_sel_impossible=np.zeros(T, bool),
        task_tol_bits=np.zeros((T, 1), np.uint32),
        task_node=np.full(T, -1, np.int32),
        task_critical=np.zeros(T, bool),
        task_needs_host=np.zeros(T, bool),
        task_aff_idx=np.full(1, -1, np.int32),
        task_aff_mask=np.ones((1, N), bool),
        task_pref_idx=np.full(1, -1, np.int32),
        task_pref_node=np.zeros((1, N), np.float32),
        task_pref_pod=np.zeros((1, N), np.float32),
        node_idle=node_alloc.copy(),
        node_releasing=np.zeros((N, R), np.float32),
        node_used=np.zeros((N, R), np.float32),
        node_alloc=node_alloc,
        node_valid=node_valid,
        node_sched=node_valid.copy(),
        node_label_bits=np.zeros((N, 1), np.uint32),
        node_taint_bits=np.zeros((N, 1), np.uint32),
        job_min_avail=job_min,
        job_ready=np.zeros(J, np.int32),
        job_queue=job_queue,
        job_prio=job_prio,
        job_creation=np.arange(J, dtype=np.int32),
        job_valid=job_valid,
        job_schedulable=job_valid.copy(),
        job_allocated=np.zeros((J, R), np.float32),
        queue_weight=queue_weight,
        queue_capability=np.full((Q, R), UNBOUNDED, np.float32),
        queue_alloc=np.zeros((Q, R), np.float32),
        queue_request=queue_request,
        queue_valid=queue_valid,
        total=total,
        quanta=spec.quanta.astype(np.float32),
    )
    meta = SnapshotMeta(
        spec=spec,
        task_keys=[f"bench/t{i}" for i in range(n_tasks)],
        node_names=[f"n{i}" for i in range(n_nodes)],
        job_uids=[f"bench/j{i}" for i in range(n_jobs)],
        queue_names=[f"q{i}" for i in range(n_queues)],
        label_pair_bit={},
        taint_bit={},
        n_tasks=n_tasks,
        n_nodes=n_nodes,
        n_jobs=n_jobs,
        n_queues=n_queues,
    )
    return snap, meta


def synthetic_overcommit_cluster(
    n_running: int = 800,
    n_pending: int = 400,
    n_nodes: int = 100,
    gang_size: int = 4,
    seed: int = 0,
):
    """Overcommitted 2-queue cluster for preempt/reclaim benchmarks: queue q0
    (weight 1) runs gangs that fill most of every node; queue q1 (weight 3)
    has pending gangs that can only start by reclaiming cross-queue — the
    BASELINE.json "preempt + reclaim actions under queue overcommit" config."""
    from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Pod
    from kube_batch_tpu.cache.cache import SchedulerCache

    rng = np.random.default_rng(seed)
    cache = SchedulerCache()
    cache.add_queue(Queue(name="q0", weight=1))
    cache.add_queue(Queue(name="q1", weight=3))
    for i in range(n_nodes):
        cache.add_node(
            Node(
                name=f"n{i}",
                allocatable={"cpu": NODE_CPU, "memory": NODE_MEM, "pods": NODE_PODS},
            )
        )
    # running workload in q0, round-robin across nodes sized to fill them
    per_node = max(1, n_running // n_nodes)
    cpu_each = NODE_CPU / per_node  # saturates cpu exactly
    n_run_jobs = -(-n_running // gang_size)
    for j in range(n_run_jobs):
        cache.add_pod_group(
            PodGroup(name=f"run{j}", namespace="bench", min_member=1,
                     queue="q0", creation_index=j)
        )
    for i in range(n_running):
        cache.add_pod(
            Pod(
                name=f"r{i}", namespace="bench",
                requests={"cpu": cpu_each, "memory": 1 * GiB},
                annotations={GROUP_NAME_ANNOTATION: f"run{i // gang_size}"},
                phase=PodPhase.RUNNING,
                node_name=f"n{i % n_nodes}",
                creation_index=i,
            )
        )
    # pending gangs in the heavier queue
    n_pend_jobs = -(-n_pending // gang_size)
    for j in range(n_pend_jobs):
        cache.add_pod_group(
            PodGroup(name=f"pend{j}", namespace="bench",
                     min_member=min(gang_size, n_pending - j * gang_size),
                     queue="q1", creation_index=n_run_jobs + j)
        )
    for i in range(n_pending):
        cache.add_pod(
            Pod(
                name=f"p{i}", namespace="bench",
                requests={
                    "cpu": float(rng.choice(CPU_CHOICES)),
                    "memory": float(rng.choice(MEM_CHOICES)),
                },
                annotations={GROUP_NAME_ANNOTATION: f"pend{i // gang_size}"},
                phase=PodPhase.PENDING,
                creation_index=n_running + i,
            )
        )
    return cache


def synthetic_cluster(
    n_tasks: int = 200,
    n_nodes: int = 20,
    gang_size: int = 4,
    n_queues: int = 2,
    seed: int = 0,
    host_ports_frac: float = 0.0,
):
    """Small synthetic cluster through the real cache handlers (full-loop
    tests). Returns a SchedulerCache with fake binder/evictor.

    `host_ports_frac` gives that fraction of tasks a hostPort (drawn from a
    64-port pool) — a host-only constraint that routes their whole job
    through the allocate replay's slow path (BASELINE config #5's
    heterogeneous-constraints case)."""
    from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Pod
    from kube_batch_tpu.cache.cache import SchedulerCache

    rng = np.random.default_rng(seed)
    spec = ResourceSpec(scalar_names=(GPU,))
    cache = SchedulerCache(spec=spec)
    for q in range(n_queues):
        cache.add_queue(Queue(name=f"q{q}", weight=q + 1))
    for i in range(n_nodes):
        cache.add_node(
            Node(
                name=f"n{i}",
                allocatable={"cpu": NODE_CPU, "memory": NODE_MEM, "pods": NODE_PODS},
            )
        )
    n_jobs = -(-n_tasks // gang_size)
    for j in range(n_jobs):
        cache.add_pod_group(
            PodGroup(
                name=f"pg{j}",
                namespace="bench",
                min_member=min(gang_size, n_tasks - j * gang_size),
                queue=f"q{j % n_queues}",
                creation_index=j,
            )
        )
    ported = (
        rng.random(n_tasks) < host_ports_frac if host_ports_frac > 0 else None
    )
    for i in range(n_tasks):
        j = i // gang_size
        cache.add_pod(
            Pod(
                name=f"t{i}",
                namespace="bench",
                requests={
                    "cpu": float(rng.choice(CPU_CHOICES)),
                    "memory": float(rng.choice(MEM_CHOICES)),
                },
                annotations={GROUP_NAME_ANNOTATION: f"pg{j}"},
                phase=PodPhase.PENDING,
                creation_index=i,
                host_ports=(7000 + int(rng.integers(64)),) if ported is not None and ported[i] else (),
            )
        )
    return cache
