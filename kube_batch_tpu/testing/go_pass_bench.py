"""Micro-measurement backing go_baseline's "numpy is a floor" argument.

go_baseline.go_loop_allocate stands in for the reference's Go allocate loop
and uses a numpy vector pass for the per-task predicate+score scan that the
reference runs through compiled Go + a 16-worker ParallelizeUntil
(scheduler_helper.go:34-129). The floor argument: numpy's C inner loop over
N nodes is at least as fast as what the reference achieves per task. This
module MEASURES that claim (VERDICT r3 weak #4): it times the identical
pass three ways on the same buffers —

  numpy_us        the stand-in used by go_baseline
  c_single_us     compiled C, one thread (the speed class of compiled Go)
  c_pooled_us     compiled C on a persistent 16-thread pool with per-pass
                  barriers — the ParallelizeUntil shape, paying the real
                  fork/join cost the reference pays per PredicateNodes call

If numpy_us <= c_pooled_us, the reported speedup vs the go-loop is a
measured floor.  All three must agree on the argmax (sanity).

Run: python -m kube_batch_tpu.testing.go_pass_bench [--nodes 5000] [--reps 200]
"""

from __future__ import annotations

import argparse
import ctypes
import json
import os
import statistics
import subprocess
import time
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                           "native")
_SO = os.path.join(_NATIVE_DIR, "libgopass.so")

_D = ctypes.c_void_p
_I64 = ctypes.c_int64


def _load() -> Optional[ctypes.CDLL]:
    src = os.path.join(_NATIVE_DIR, "go_pass.c")
    try:
        stale = (
            not os.path.exists(_SO)
            or os.path.getmtime(_SO) < os.path.getmtime(src)
        )
    except OSError:
        stale = False  # source missing: a prebuilt .so may still load
    if stale:
        try:
            subprocess.run(["make", "-B", "-C", _NATIVE_DIR, "libgopass.so"],
                           check=True, capture_output=True, timeout=60)
        except (OSError, subprocess.SubprocessError):
            pass  # fall through — a previously built .so may still load
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    lib.go_pass_single.restype = _I64
    lib.go_pass_single.argtypes = [_D, _D, _D, _D, _I64, _I64]
    lib.go_pass_pooled.restype = _I64
    lib.go_pass_pooled.argtypes = [_D, _D, _D, _D, _I64, _I64]
    lib.go_pass_pool_init.restype = ctypes.c_int
    lib.go_pass_pool_init.argtypes = [ctypes.c_int]
    lib.go_pass_pool_shutdown.restype = None
    lib.go_pass_pool_shutdown.argtypes = []
    return lib


# the SAME function object go_loop_allocate calls — the bench times the
# loop's actual pass, and an edit there cannot silently desynchronize this
from kube_batch_tpu.testing.go_baseline import numpy_inner_pass as _numpy_pass  # noqa: E402


def measure(n_nodes: int = 5_000, reps: int = 200, threads: int = 16,
            seed: int = 0) -> dict:
    from kube_batch_tpu.testing.synthetic import synthetic_device_snapshot

    snap, meta = synthetic_device_snapshot(
        n_tasks=64, n_nodes=n_nodes, gang_size=4, n_queues=3, seed=seed
    )
    nn = meta.n_nodes
    node_idle = np.ascontiguousarray(np.asarray(snap.node_idle)[:nn], np.float64)
    node_alloc = np.ascontiguousarray(np.asarray(snap.node_alloc)[:nn], np.float64)
    quanta = np.ascontiguousarray(np.asarray(snap.quanta), np.float64)
    reqs = np.ascontiguousarray(np.asarray(snap.task_req)[:64], np.float64)
    cap_cpu = np.maximum(node_alloc[:, 0], 1.0)
    cap_mem = np.maximum(node_alloc[:, 1], 1.0)
    R = node_idle.shape[1]

    def time_us(fn):
        # warmup + per-pass p50 over reps, cycling the 64 task reqs so a
        # branch predictor can't lock onto one request vector
        fn(reqs[0])
        samples = []
        for i in range(reps):
            req = reqs[i % 64]
            t0 = time.perf_counter()
            fn(req)
            samples.append((time.perf_counter() - t0) * 1e6)
        return statistics.median(samples)

    results = {"nodes": nn, "reps": reps, "threads": threads}
    picks = {}

    def numpy_fn(req):
        picks["numpy"] = _numpy_pass(req, node_idle, node_alloc, quanta,
                                     cap_cpu, cap_mem)
    results["numpy_us"] = round(time_us(numpy_fn), 1)

    lib = _load()
    if lib is None:
        results["native"] = "unavailable (no C toolchain)"
        return results

    idle_p, alloc_p = node_idle.ctypes.data, node_alloc.ctypes.data
    q_p = quanta.ctypes.data

    def c_single(req):
        picks["c_single"] = lib.go_pass_single(
            req.ctypes.data, idle_p, alloc_p, q_p, nn, R
        )
    results["c_single_us"] = round(time_us(c_single), 1)

    if lib.go_pass_pool_init(threads) == 0:
        def c_pooled(req):
            picks["c_pooled"] = lib.go_pass_pooled(
                req.ctypes.data, idle_p, alloc_p, q_p, nn, R
            )
        results["c_pooled_us"] = round(time_us(c_pooled), 1)
        lib.go_pass_pool_shutdown()

    # all implementations must pick the same node on the final rep
    assert len(set(picks.values())) == 1, picks
    results["agreement"] = picks["numpy"]
    results["numpy_vs_c_single"] = round(
        results["c_single_us"] / results["numpy_us"], 2
    )
    if "c_pooled_us" in results:
        results["numpy_vs_c_pooled"] = round(
            results["c_pooled_us"] / results["numpy_us"], 2
        )
        results["floor_holds"] = results["numpy_us"] <= results["c_pooled_us"]
    return results


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=5_000)
    parser.add_argument("--reps", type=int, default=200)
    parser.add_argument("--threads", type=int, default=16)
    args = parser.parse_args(argv)
    print(json.dumps(measure(args.nodes, args.reps, args.threads)))


if __name__ == "__main__":
    main()
