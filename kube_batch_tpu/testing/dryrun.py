"""Multi-chip dryrun body — run as `python -m kube_batch_tpu.testing.dryrun N`.

This module holds the actual mesh work for `__graft_entry__.dryrun_multichip`.
It is designed to be executed in a *fresh child process* whose environment was
hardened before any jax import (JAX_PLATFORMS=cpu, PALLAS_AXON_POOL_IPS="",
XLA_FLAGS --xla_force_host_platform_device_count=N): with a wedged TPU tunnel,
any jax dispatch in an unhardened process hangs inside axon backend init
(make_c_api_client) — even work that would run on CPU.  Running here, after
the env is set, is immune to that hang.

Mirrors the reference's multi-core fan-out obligation (SURVEY.md §2.8, §5.7):
the node axis is sharded over the device mesh the way scheduler_helper.go:34
fans predicates over 16 workers.
"""

from __future__ import annotations

import sys

import numpy as np


def run(n_devices: int) -> None:
    import jax

    from kube_batch_tpu.ops.assignment import AllocateConfig, allocate_solve
    from kube_batch_tpu.ops.eviction import EvictConfig, evict_solve
    from kube_batch_tpu.parallel.mesh import (
        make_mesh,
        sharded_allocate_solve,
        sharded_evict_solve,
    )
    from kube_batch_tpu.testing.synthetic import synthetic_device_snapshot

    assert len(jax.devices()) >= n_devices, (
        f"need {n_devices} devices, have {len(jax.devices())}"
    )
    mesh = make_mesh(n_devices)

    # 1. quick smoke at a small shape
    snap, meta = synthetic_device_snapshot(
        n_tasks=256, n_nodes=max(64, n_devices * 8), gang_size=4, n_queues=3,
        gpu_task_frac=0.2,
    )
    result = sharded_allocate_solve(snap, AllocateConfig(), mesh)
    assigned = np.asarray(result.assigned)[: meta.n_tasks]
    placed = int((assigned >= 0).sum())
    assert placed > 0, "multichip dryrun placed nothing"
    # invariant: no node overcommitted
    assert np.all(np.asarray(result.node_idle) >= -np.asarray(snap.quanta)[None, :])
    print(
        f"dryrun_multichip({n_devices}): placed {placed}/{meta.n_tasks} tasks "
        f"across {meta.n_nodes} sharded nodes — OK"
    )

    # 2. a shape that crosses the 4096 padding bucket (task axis pads to
    # 5120, the multiple-of-1024 regime) with sharded-vs-single equivalence:
    # GSPMD partitioning must be an execution detail, not a semantic one
    snap_big, meta_big = synthetic_device_snapshot(
        n_tasks=5000, n_nodes=1024, gang_size=4, n_queues=3,
    )
    cfg = AllocateConfig()
    sharded = sharded_allocate_solve(snap_big, cfg, mesh)
    single = allocate_solve(snap_big, cfg)
    s_a = np.asarray(single.assigned)[: meta_big.n_tasks]
    m_a = np.asarray(sharded.assigned)[: meta_big.n_tasks]
    assert (s_a == m_a).all(), "sharded assignment diverged past the 4096 bucket"
    placed_big = int((m_a >= 0).sum())
    assert placed_big > 0
    print(
        f"dryrun_multichip({n_devices}): 5000x1024 (padded 5120, past the "
        f"4096 bucket) placed {placed_big}, sharded == single — OK"
    )

    # 3. the eviction solve sharded over the same mesh (preempt/reclaim's
    # production path on multi-chip parts): most jobs RUNNING on a tight
    # cluster so the pending remainder has genuine claims and victim pools
    snap_ev, meta_ev = synthetic_device_snapshot(
        n_tasks=512, n_nodes=max(16, n_devices * 2), gang_size=4, n_queues=3,
    )
    snap_ev = _with_running(snap_ev, meta_ev, frac=0.7)
    ev_cfg = EvictConfig(mode="reclaim")
    ev_sharded = sharded_evict_solve(snap_ev, ev_cfg, mesh)
    ev_single = evict_solve(snap_ev, ev_cfg)
    assert (
        np.asarray(ev_sharded.claim_node) == np.asarray(ev_single.claim_node)
    ).all(), "sharded eviction solve diverged"
    assert (
        np.asarray(ev_sharded.evicted) == np.asarray(ev_single.evicted)
    ).all()
    n_claims = int((np.asarray(ev_sharded.claim_node)[: meta_ev.n_tasks] >= 0).sum())
    print(
        f"dryrun_multichip({n_devices}): eviction solve sharded == single "
        f"({n_claims} claims) — OK"
    )


def _with_running(snap, meta, frac: float):
    """Mark the first `frac` of jobs RUNNING with round-robin node placement
    and consistent accounting — turns the pending-only synthetic snapshot
    into an eviction scenario (claimants + cross-queue victim pools)."""
    from kube_batch_tpu.api.types import TaskStatus

    task_job = np.asarray(snap.task_job)
    nj, nn = meta.n_jobs, meta.n_nodes
    run_jobs = np.zeros(snap.job_min_avail.shape[0], bool)
    run_jobs[: int(nj * frac)] = True
    run_task = run_jobs[task_job] & np.asarray(snap.task_valid)
    idxs = np.flatnonzero(run_task)
    nodes = (np.arange(idxs.size) % nn).astype(np.int32)
    task_node = np.asarray(snap.task_node).copy()
    task_node[idxs] = nodes
    status = np.asarray(snap.task_status).copy()
    status[idxs] = int(TaskStatus.RUNNING)
    pending = np.asarray(snap.task_pending) & ~run_task
    req = np.asarray(snap.task_resreq)
    used = np.zeros_like(np.asarray(snap.node_used))
    np.add.at(used, nodes, req[idxs])
    idle = np.maximum(np.asarray(snap.node_alloc) - used, 0.0)
    J = snap.job_min_avail.shape[0]
    job_ready = np.bincount(task_job[idxs], minlength=J).astype(np.int32)
    job_allocated = np.zeros_like(np.asarray(snap.job_allocated))
    np.add.at(job_allocated, task_job[idxs], req[idxs])
    Q = snap.queue_weight.shape[0]
    queue_alloc = np.zeros_like(np.asarray(snap.queue_alloc))
    np.add.at(queue_alloc, np.asarray(snap.job_queue)[task_job[idxs]], req[idxs])
    # running jobs become min_available=1 singletons-with-slack: a gang
    # sitting exactly at its minMember can never lose a member
    # (gang.go:71-94), which would leave the eviction scenario victimless
    job_min = np.asarray(snap.job_min_avail).copy()
    job_min[run_jobs] = 1
    return snap._replace(
        task_node=task_node,
        task_status=status,
        task_pending=pending,
        node_idle=idle,
        node_used=used,
        job_ready=job_ready,
        job_allocated=job_allocated,
        queue_alloc=queue_alloc,
        job_min_avail=job_min,
    )


if __name__ == "__main__":
    run(int(sys.argv[1]) if len(sys.argv) > 1 else 8)
