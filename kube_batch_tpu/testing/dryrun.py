"""Multi-chip dryrun body — run as `python -m kube_batch_tpu.testing.dryrun N`.

This module holds the actual mesh work for `__graft_entry__.dryrun_multichip`.
It is designed to be executed in a *fresh child process* whose environment was
hardened before any jax import (JAX_PLATFORMS=cpu, PALLAS_AXON_POOL_IPS="",
XLA_FLAGS --xla_force_host_platform_device_count=N): with a wedged TPU tunnel,
any jax dispatch in an unhardened process hangs inside axon backend init
(make_c_api_client) — even work that would run on CPU.  Running here, after
the env is set, is immune to that hang.

Mirrors the reference's multi-core fan-out obligation (SURVEY.md §2.8, §5.7):
the node axis is sharded over the device mesh the way scheduler_helper.go:34
fans predicates over 16 workers.
"""

from __future__ import annotations

import sys

import numpy as np


def run(n_devices: int) -> None:
    import jax

    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.parallel.mesh import make_mesh, sharded_allocate_solve
    from kube_batch_tpu.testing.synthetic import synthetic_device_snapshot

    assert len(jax.devices()) >= n_devices, (
        f"need {n_devices} devices, have {len(jax.devices())}"
    )
    mesh = make_mesh(n_devices)
    snap, meta = synthetic_device_snapshot(
        n_tasks=256, n_nodes=max(64, n_devices * 8), gang_size=4, n_queues=3,
        gpu_task_frac=0.2,
    )
    result = sharded_allocate_solve(snap, AllocateConfig(), mesh)
    assigned = np.asarray(result.assigned)[: meta.n_tasks]
    placed = int((assigned >= 0).sum())
    assert placed > 0, "multichip dryrun placed nothing"
    # invariant: no node overcommitted
    assert np.all(np.asarray(result.node_idle) >= -np.asarray(snap.quanta)[None, :])
    print(
        f"dryrun_multichip({n_devices}): placed {placed}/{meta.n_tasks} tasks "
        f"across {meta.n_nodes} sharded nodes — OK"
    )


if __name__ == "__main__":
    run(int(sys.argv[1]) if len(sys.argv) > 1 else 8)
