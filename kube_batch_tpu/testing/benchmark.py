"""Benchmark matrix — the kubemark density-test successor (SURVEY.md §4.3).

The reference measures pod-startup latency percentiles on a simulated GCE
cluster (test/e2e/benchmark.go:53-285, p50/p90/p99 via metric_util.go). Here
every BASELINE.json config runs as a synthetic scheduling-cycle benchmark
with the same percentile reporting — no apiserver, the snapshot feeds the
device directly:

  gang_allocate_kubemark      3k pods × 100 nodes, minMember=4 (the kubemark
                              density target, kubemark-benchmarking.md:40-42)
  drf_proportion_3_queues     50k × 5k, 3 weighted queues, mixed CPU/mem
  binpack_nodeorder_10k_1k    10k × 1k with the binpack score row enabled
  preempt_reclaim_overcommit  full action pipeline over an overcommitted
                              2-queue cluster (host actions + device solve)
  hetero_gpu_gangs_50k_5k     heterogeneous GPU gangs at full scale

Run: python -m kube_batch_tpu.testing.benchmark [--quick]
Prints one JSON line per config plus a summary line.
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Callable, Dict, List, NamedTuple

import numpy as np

TARGET_MS = 1000.0  # <1s/cycle north star


def _percentiles(ms: List[float]) -> Dict[str, float]:
    return {
        "p50_ms": round(float(np.percentile(ms, 50)), 2),
        "p90_ms": round(float(np.percentile(ms, 90)), 2),
        "p99_ms": round(float(np.percentile(ms, 99)), 2),
    }


class BenchCase(NamedTuple):
    name: str
    run: Callable[[int], Dict]  # cycles → result dict


def _device_case(name, n_tasks, n_nodes, gang_size=4, n_queues=3,
                 gpu_task_frac=0.0, gpu_node_frac=0.25, weights=None):
    """A device-solve cycle benchmark: host→device ship, compiled allocate
    solve, assignment back (the one-in/one-out transfer budget, §7.3)."""

    def run(cycles: int) -> Dict:
        import jax

        from kube_batch_tpu.ops.assignment import AllocateConfig, allocate_solve
        from kube_batch_tpu.ops.scoring import ScoreWeights
        from kube_batch_tpu.testing.synthetic import synthetic_device_snapshot

        config = AllocateConfig(weights=weights or ScoreWeights())
        snap_np, meta = synthetic_device_snapshot(
            n_tasks=n_tasks, n_nodes=n_nodes, gang_size=gang_size,
            n_queues=n_queues, gpu_task_frac=gpu_task_frac,
            gpu_node_frac=gpu_node_frac,
        )

        def cycle():
            snap = jax.device_put(snap_np)
            result = allocate_solve(snap, config)
            return np.asarray(result.assigned)

        assigned = cycle()  # warmup/compile
        placed = int((assigned[: meta.n_tasks] >= 0).sum())
        times = []
        for _ in range(cycles):
            t0 = time.perf_counter()
            cycle()
            times.append((time.perf_counter() - t0) * 1e3)
        return {
            "tasks": meta.n_tasks, "nodes": meta.n_nodes, "placed": placed,
            **_percentiles(times),
            "pods_per_sec": round(placed / (np.percentile(times, 50) / 1e3), 0),
        }

    return BenchCase(name, run)


def _overcommit_case(name, n_running=800, n_pending=400, n_nodes=100):
    """preempt + reclaim under queue overcommit: queue q1 (weight 3) has
    pending gangs while queue q0 (weight 1) holds every node — the full
    enqueue→reclaim→allocate→backfill→preempt pipeline runs each cycle."""

    def run(cycles: int) -> Dict:
        from kube_batch_tpu.framework.conf import load_scheduler_conf
        from kube_batch_tpu.scheduler import Scheduler
        from kube_batch_tpu.testing.synthetic import synthetic_overcommit_cluster

        conf = load_scheduler_conf(None)
        conf.actions = ["enqueue", "reclaim", "allocate", "backfill", "preempt"]
        # warmup: compile the reclaim/preempt/allocate solves at these shapes
        Scheduler(
            synthetic_overcommit_cluster(
                n_running=n_running, n_pending=n_pending, n_nodes=n_nodes
            ),
            conf=conf,
        ).run_once()
        times = []
        evicted = placed = 0
        for _ in range(cycles):
            cache = synthetic_overcommit_cluster(
                n_running=n_running, n_pending=n_pending, n_nodes=n_nodes
            )
            sched = Scheduler(cache, conf=conf)
            t0 = time.perf_counter()
            sched.run_once()
            times.append((time.perf_counter() - t0) * 1e3)
            evicted = len(cache.evictor.evicts)
            placed = len(cache.binder.binds)
        return {
            "running": n_running, "pending": n_pending, "nodes": n_nodes,
            "evicted": evicted, "placed": placed, **_percentiles(times),
        }

    return BenchCase(name, run)


def _startup_latency_case(name, n_latency_pods=3_000, n_nodes=100, batch=100,
                          gang_size=100, period=0.05):
    """Pod-startup latency decomposition — the kubemark density test
    (test/e2e/benchmark.go:53-285, doc/design kubemark target of 3k pods on
    100 hollow nodes): start the scheduler loop, land a 100-pod gang, then
    feed 1-milliCPU latency pods in node-count batches and report
    create→schedule p50/p90/p99 from binder timestamps."""

    def run(cycles: int) -> Dict:  # cycles unused — one density sweep
        import threading
        import time as _time

        from kube_batch_tpu.api.pod import (
            GROUP_NAME_ANNOTATION, Node, Pod, PodGroup, Queue,
        )
        from kube_batch_tpu.cache.cache import SchedulerCache
        from kube_batch_tpu.cache.fake import FakeBinder
        from kube_batch_tpu.scheduler import Scheduler

        created: Dict[str, float] = {}
        scheduled: Dict[str, float] = {}

        class TimestampingBinder(FakeBinder):
            def bind(self, pod, hostname):
                scheduled[f"{pod.namespace}/{pod.name}"] = _time.perf_counter()
                super().bind(pod, hostname)

            def bind_many(self, pairs):
                now = _time.perf_counter()
                for pod, _ in pairs:
                    scheduled[f"{pod.namespace}/{pod.name}"] = now
                super().bind_many(pairs)

        cache = SchedulerCache(binder=TimestampingBinder())
        cache.add_queue(Queue(name="default", weight=1))
        for i in range(n_nodes):
            cache.add_node(Node(name=f"n{i}", allocatable={
                "cpu": 32000.0, "memory": float(128 << 30), "pods": 110.0}))
        sched = Scheduler(cache, schedule_period=period)
        t = threading.Thread(target=sched.run_forever, daemon=True)
        t.start()
        try:
            # the 100-pod gang (benchmark.go:50,61-71)
            cache.add_pod_group(PodGroup(name="density-gang", min_member=gang_size))
            for i in range(gang_size):
                key = f"default/gang-{i}"
                created[key] = _time.perf_counter()
                cache.add_pod(Pod(
                    name=f"gang-{i}", requests={"cpu": 100.0},
                    annotations={GROUP_NAME_ANNOTATION: "density-gang"},
                ))
            # latency pods in node-count batches (benchmark.go:93-140)
            for start in range(0, n_latency_pods, batch):
                for i in range(start, min(start + batch, n_latency_pods)):
                    key = f"default/lat-{i}"
                    created[key] = _time.perf_counter()
                    cache.add_pod(Pod(name=f"lat-{i}", requests={"cpu": 1.0}))
                _time.sleep(period)
            deadline = _time.perf_counter() + 60
            while len(scheduled) < len(created) and _time.perf_counter() < deadline:
                _time.sleep(period)
        finally:
            sched.stop()
            t.join(5)
        lat_ms = [
            (scheduled[k] - created[k]) * 1e3 for k in created if k in scheduled
        ]
        return {
            "pods": len(created), "scheduled": len(lat_ms), "nodes": n_nodes,
            **(_percentiles(lat_ms) if lat_ms else {}),
        }

    return BenchCase(name, run)


def build_cases() -> List[BenchCase]:
    from kube_batch_tpu.ops.scoring import ScoreWeights

    return [
        _device_case("gang_allocate_kubemark", 3_000, 100),
        _device_case("drf_proportion_3_queues", 50_000, 5_000),
        _device_case("binpack_nodeorder_10k_1k", 10_000, 1_000,
                     weights=ScoreWeights(binpack=1.0)),
        _overcommit_case("preempt_reclaim_overcommit"),
        # eviction at allocate's headline scale (VERDICT r3 #3): 50k pending
        # claimants vs 10k saturating victims on 5k nodes — 60k total tasks
        # stays inside the 65536 task bucket the headline already proves on
        # HBM; 50k+50k would cross into the 131072 bucket and double every
        # [T, N] buffer
        _overcommit_case("preempt_reclaim_50k_5k", n_running=10_000,
                         n_pending=50_000, n_nodes=5_000),
        _device_case("hetero_gpu_gangs_50k_5k", 50_000, 5_000,
                     gpu_task_frac=0.2, gpu_node_frac=0.25),
        _startup_latency_case("pod_startup_latency_kubemark"),
    ]


def main(argv=None) -> None:
    from kube_batch_tpu.envutil import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()

    parser = argparse.ArgumentParser()
    parser.add_argument("--cycles", type=int, default=5)
    parser.add_argument("--quick", action="store_true",
                        help="2 cycles per config")
    args = parser.parse_args(argv)
    cycles = 2 if args.quick else args.cycles

    results = {}
    for case in build_cases():
        r = case.run(cycles)
        results[case.name] = r
        print(json.dumps({"config": case.name, **r}), flush=True)
    worst = max(r["p99_ms"] for r in results.values())
    print(json.dumps({
        "summary": "baseline_config_matrix",
        "configs": len(results),
        "worst_p99_ms": worst,
        "all_under_target": worst < TARGET_MS,
    }))


if __name__ == "__main__":
    main()
