"""Pallas round-head vs XLA round-head — the hardware decider (VERDICT r3 #2).

`ops/pallas_kernels.masked_best_node` fuses the auction round's first half
(fit + mask + two-key argmax) into VMEM tiles; the XLA path computes the same
values through fused broadcasts (`ops/assignment.round_body`). Both are timed
here on the SAME inputs at the same shapes the solve uses, so the number
decides whether the kernel earns its place as the default (flip
`AllocateConfig.use_pallas`) or gets deleted with the measurement recorded in
PARITY.md.

Each side is timed as the jitted round-head alone — score/static mask/tie
hash precomputed outside the timed region, exactly how `allocate_solve`
hoists them out of the rounds.

Run: python -m kube_batch_tpu.testing.pallas_bench [--tasks 50000] [--nodes 5000]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import statistics
import time


def compare_roundhead(
    n_tasks: int = 50_000,
    n_nodes: int = 5_000,
    reps: int = 20,
    seed: int = 0,
) -> dict:
    """Time one auction round head (fit + mask + lexicographic argmax +
    chose-idle gather) via XLA broadcasts vs the fused Pallas kernel.

    Returns p50 step ms, compile seconds, and bit-equality of the outputs
    (the kernel must match the XLA path exactly — same tie-hash constants,
    same epsilon fit — or its number is meaningless)."""
    import jax
    import jax.numpy as jnp

    from kube_batch_tpu.ops.assignment import NEG, _best_node, _tie_break_hash
    from kube_batch_tpu.ops.feasibility import fits, static_predicates
    from kube_batch_tpu.ops.pallas_kernels import masked_best_node
    from kube_batch_tpu.ops.scoring import ScoreWeights, score_matrix
    from kube_batch_tpu.testing.synthetic import synthetic_device_snapshot

    snap_np, _meta = synthetic_device_snapshot(
        n_tasks=n_tasks, n_nodes=n_nodes, gang_size=4, n_queues=3, seed=seed
    )
    snap = jax.device_put(snap_np)
    on_tpu = jax.default_backend() == "tpu"

    # hoisted round invariants (assignment.py:195-225)
    static_ok = static_predicates(snap)
    score = score_matrix(snap, ScoreWeights())
    score_static = jnp.where(static_ok, score, NEG)
    T, N = score.shape
    tie_hash = _tie_break_hash(T, N)
    pending = snap.task_pending & snap.task_valid

    @jax.jit
    def xla_head(score_static, tie_hash, task_req, idle, releasing, pending, quanta):
        fit_idle = fits(task_req, idle, quanta)
        fit_rel = fits(task_req, releasing, quanta)
        masked = jnp.where(
            (fit_idle | fit_rel) & pending[:, None], score_static, NEG
        )
        best, has = _best_node(masked, tie_hash)
        chose_idle = jnp.take_along_axis(fit_idle, best[:, None], axis=1)[:, 0]
        return best, has, chose_idle

    xla_args = (score_static, tie_hash, snap.task_req, snap.node_idle,
                snap.node_releasing, pending, snap.quanta)
    pallas_args = (score, static_ok, snap.task_req, snap.node_idle,
                   snap.node_releasing, pending, snap.quanta)

    def timed(fn, args, kwargs=None):
        kwargs = kwargs or {}
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        compile_s = time.perf_counter() - t0
        steps = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args, **kwargs))
            steps.append((time.perf_counter() - t0) * 1e3)
        return out, compile_s, statistics.median(steps)

    xla_out, xla_compile_s, xla_ms = timed(xla_head, xla_args)
    pallas_out, pallas_compile_s, pallas_ms = timed(
        masked_best_node, pallas_args, {"interpret": not on_tpu}
    )

    import numpy as np

    match = all(
        bool(np.array_equal(np.asarray(a), np.asarray(b)))
        for a, b in zip(xla_out, pallas_out)
    )
    return {
        "tasks": n_tasks, "nodes": n_nodes, "backend": jax.default_backend(),
        "xla_ms": round(xla_ms, 3), "pallas_ms": round(pallas_ms, 3),
        "xla_compile_s": round(xla_compile_s, 1),
        "pallas_compile_s": round(pallas_compile_s, 1),
        "outputs_match": match,
        "pallas_speedup": round(xla_ms / pallas_ms, 2) if pallas_ms else None,
    }


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--tasks", type=int, default=50_000)
    parser.add_argument("--nodes", type=int, default=5_000)
    parser.add_argument("--reps", type=int, default=20)
    args = parser.parse_args(argv)
    print(json.dumps(compare_roundhead(args.tasks, args.nodes, args.reps)))


if __name__ == "__main__":
    main()
