"""Live-apiserver e2e driver — the rebuild's test/e2e (job.go, queue.go).

One command runs the reference's core behavioral scenarios against a REAL
Kubernetes API server (kind or any URL) with the scheduler in --master
mode, end to end through the chart's CRDs, the list+watch shim, the
binder/evictor, and the status writeback:

    python -m kube_batch_tpu.testing.e2e --master https://127.0.0.1:6443
    python -m kube_batch_tpu.testing.e2e --stub        # CI: no cluster

Scenarios (test/e2e/job.go:82,118,189; queue.go:26; job.go:458;
predicates.go:35,84,161):
  gang              — minMember gang schedules atomically
  gang_full         — a gang that cannot fully fit binds NOTHING
  preemption        — a high-priority job evicts same-queue victims, then
                      places once the kubelet terminates them
  reclaim           — a starved weighted queue reclaims cross-queue
  proportion        — two weighted queues split capacity by weight
  node_selector     — selector pods land only on matching nodes
  taints            — only tolerating pods land on a tainted node
  hostport          — same hostPort forces distinct nodes
  volume            — a local-PV claim pins its pod; the PV pre-binds
  job_priority      — a PriorityClass-backed job wins contended capacity

With --stub, an in-process fake apiserver (real HTTP, real watch streams)
plays the cluster, including the kubelet's part: a Binding POST transitions
the pod to Running on the node, a DELETE terminates it — the state machine
the scenarios need. The same scenario code runs unmodified against a real
cluster; there the kubelet/PV controller do that work.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import logging
import queue as _queue
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("kube_batch_tpu")

SCHED = "volcano"  # default scheduler-name the shim filters on

# collection resource segment → canonical list path (mirrors k8s/watch.py)
_COLLECTIONS = {
    "namespaces": "/api/v1/namespaces",
    "pods": "/api/v1/pods",
    "nodes": "/api/v1/nodes",
    "persistentvolumes": "/api/v1/persistentvolumes",
    "persistentvolumeclaims": "/api/v1/persistentvolumeclaims",
    "podgroups": "/apis/scheduling.incubator.k8s.io/v1alpha1/podgroups",
    "queues": "/apis/scheduling.incubator.k8s.io/v1alpha1/queues",
    "poddisruptionbudgets": "/apis/policy/v1/poddisruptionbudgets",
    "priorityclasses": "/apis/scheduling.k8s.io/v1/priorityclasses",
    "storageclasses": "/apis/storage.k8s.io/v1/storageclasses",
    "customresourcedefinitions":
        "/apis/apiextensions.k8s.io/v1/customresourcedefinitions",
    "leases": "/apis/coordination.k8s.io/v1/leases",
}


def _merge(dst: dict, patch: dict) -> dict:
    for k, v in patch.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _merge(dst[k], v)
        elif v is None:
            dst.pop(k, None)
        else:
            dst[k] = v
    return dst


class StubApiServer:
    """A watchable fake apiserver with a built-in kubelet simulation."""

    def __init__(self):
        self._store: Dict[str, Dict[str, dict]] = {k: {} for k in _COLLECTIONS}
        self._watchers: Dict[str, List[_queue.Queue]] = {k: [] for k in _COLLECTIONS}
        self._rv = 0
        self._lock = threading.RLock()
        self.httpd: Optional[ThreadingHTTPServer] = None

    # ---- store ---------------------------------------------------------
    @staticmethod
    def _key(obj: dict) -> str:
        meta = obj.get("metadata") or {}
        ns = meta.get("namespace")
        return f"{ns}/{meta['name']}" if ns else meta["name"]

    def _emit(self, kind: str, etype: str, obj: dict) -> None:
        self._rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
        event = {"type": etype, "object": json.loads(json.dumps(obj))}
        for q in list(self._watchers[kind]):
            q.put(event)

    def upsert(self, kind: str, obj: dict) -> None:
        with self._lock:
            key = self._key(obj)
            etype = "MODIFIED" if key in self._store[kind] else "ADDED"
            self._store[kind][key] = obj
            self._emit(kind, etype, obj)

    def delete(self, kind: str, key: str) -> bool:
        with self._lock:
            obj = self._store[kind].pop(key, None)
            if obj is None:
                return False
            self._emit(kind, "DELETED", obj)
            return True

    def patch(self, kind: str, key: str, patch: dict) -> bool:
        with self._lock:
            obj = self._store[kind].get(key)
            if obj is None:
                return False
            _merge(obj, patch)
            self._emit(kind, "MODIFIED", obj)
            return True

    # ---- kubelet simulation -------------------------------------------
    def bind_pod(self, ns: str, name: str, node: str) -> bool:
        """Binding subresource → the kubelet runs the pod."""
        with self._lock:
            pod = self._store["pods"].get(f"{ns}/{name}")
            if pod is None:
                return False
            pod.setdefault("spec", {})["nodeName"] = node
            pod.setdefault("status", {})["phase"] = "Running"
            self._emit("pods", "MODIFIED", pod)
            return True

    # ---- HTTP ----------------------------------------------------------
    def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"  # close-delimited watch streams

            def log_message(self, *a):
                pass

            def _send(self, code: int, obj) -> None:
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _route(self) -> Tuple[Optional[str], List[str], str]:
                """path → (collection kind, trailing segments, query). The
                LAST matching segment is the resource — namespaced paths
                (/api/v1/namespaces/<ns>/pods/...) contain 'namespaces'
                first but address the inner collection."""
                path, _, query = self.path.partition("?")
                parts = [p for p in path.split("/") if p]
                for i in range(len(parts) - 1, -1, -1):
                    if parts[i] in _COLLECTIONS:
                        return parts[i], parts[i + 1:], query
                return None, [], query

            def _obj_key(self, kind: str, rest: List[str]) -> str:
                # .../namespaces/<ns>/<kind>/<name> carries the namespace
                # two segments before the kind; cluster-scoped is just name
                path = self.path.split("?")[0]
                if "/namespaces/" in path:
                    ns = path.split("/namespaces/")[1].split("/")[0]
                    return f"{ns}/{rest[0]}"
                if kind == "pods" and rest:
                    return rest[0] if "/" in rest[0] else f"default/{rest[0]}"
                return rest[0]

            def do_GET(self):
                kind, rest, query = self._route()
                if kind is None:
                    self._send(404, {"error": "not found"})
                    return
                if "watch=true" in query:
                    q: _queue.Queue = _queue.Queue()
                    with stub._lock:
                        # close the LIST→watch gap: whatever the store holds
                        # NOW replays as MODIFIED (the shim's handlers are
                        # upserts, so re-delivery is harmless) — an event
                        # emitted between the client's list and this
                        # registration cannot be lost
                        for obj in stub._store[kind].values():
                            q.put({"type": "MODIFIED",
                                   "object": json.loads(json.dumps(obj))})
                        stub._watchers[kind].append(q)
                    try:
                        self.send_response(200)
                        self.send_header("Content-Type", "application/json")
                        self.end_headers()
                        while True:
                            try:
                                event = q.get(timeout=1.0)
                            except _queue.Empty:
                                continue
                            self.wfile.write(
                                (json.dumps(event) + "\n").encode()
                            )
                            self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError, OSError):
                        return
                    finally:
                        try:
                            stub._watchers[kind].remove(q)
                        except ValueError:
                            pass
                    return
                with stub._lock:
                    if rest:  # single object GET (lease elector)
                        obj = stub._store[kind].get(self._obj_key(kind, rest))
                        if obj is None:
                            self._send(404, {"error": "not found"})
                        else:
                            self._send(200, obj)
                        return
                    items = [json.loads(json.dumps(o))
                             for o in stub._store[kind].values()]
                self._send(200, {
                    "items": items,
                    "metadata": {"resourceVersion": str(stub._rv)},
                })

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def do_POST(self):
                kind, rest, _ = self._route()
                if kind is None:
                    self._send(404, {"error": "not found"})
                    return
                body = self._body()
                if kind == "pods" and rest and rest[-1] == "binding":
                    path = self.path.split("?")[0]
                    ns = (path.split("/namespaces/")[1].split("/")[0]
                          if "/namespaces/" in path else "default")
                    ok = stub.bind_pod(ns, rest[-2], (body.get("target") or {}).get("name", ""))
                    self._send(201 if ok else 404, {})
                    return
                # creation: stamp the namespace from the URL when present
                path = self.path.split("?")[0]
                if "/namespaces/" in path:
                    ns = path.split("/namespaces/")[1].split("/")[0]
                    body.setdefault("metadata", {}).setdefault("namespace", ns)
                stub.upsert(kind, body)
                self._send(201, body)

            def do_PUT(self):
                kind, rest, _ = self._route()
                if kind is None or not rest:
                    self._send(404, {"error": "not found"})
                    return
                body = self._body()
                stub.upsert(kind, body)
                self._send(200, body)

            def do_PATCH(self):
                kind, rest, _ = self._route()
                if kind is None or not rest:
                    self._send(404, {"error": "not found"})
                    return
                key = self._obj_key(kind, rest)
                ok = stub.patch(kind, key, self._body())
                self._send(200 if ok else 404, {})

            def do_DELETE(self):
                kind, rest, _ = self._route()
                if kind is None or not rest:
                    self._send(404, {"error": "not found"})
                    return
                ok = stub.delete(kind, self._obj_key(kind, rest))
                self._send(200 if ok else 404, {})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        threading.Thread(target=self.httpd.serve_forever, daemon=True,
                         name="stub-apiserver").start()
        return f"http://{host}:{self.httpd.server_address[1]}"

    def stop(self) -> None:
        if self.httpd is not None:
            self.httpd.shutdown()
            self.httpd.server_close()


# ---------------------------------------------------------------------------
# client helpers (work against the stub AND a real apiserver)
# ---------------------------------------------------------------------------


class Cluster:
    """Minimal apiserver client for the scenarios. Creates are tracked so
    teardown() can delete them in reverse order — scenario isolation on a
    real cluster, where objects would otherwise leak across runs."""

    def __init__(self, master: str, **auth):
        from kube_batch_tpu.k8s.transport import ApiTransport

        self.t = ApiTransport(master, **auth)
        self._created: List[str] = []  # object paths, creation order

    def _obj_path(self, collection_path: str, obj: dict) -> str:
        meta = obj.get("metadata") or {}
        ns, name = meta.get("namespace"), meta.get("name", "")
        if ns and not collection_path.rstrip("/").endswith(f"namespaces/{ns}"):
            prefix, _, resource = collection_path.rpartition("/")
            return f"{prefix}/namespaces/{ns}/{resource}/{name}"
        return f"{collection_path}/{name}"

    def create(self, collection_path: str, obj: dict, tolerate_conflict=False) -> None:
        import urllib.error

        try:
            self.t.request("POST", collection_path, obj)
        except urllib.error.HTTPError as e:
            if not (tolerate_conflict and e.code == 409):
                raise
            return
        self._created.append(self._obj_path(collection_path, obj))

    def ensure_namespace(self, ns: str) -> None:
        self.create("/api/v1/namespaces",
                    {"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": ns}},
                    tolerate_conflict=True)

    def teardown(self) -> None:
        """Best-effort reverse-order cleanup of everything this client made."""
        import urllib.error

        for path in reversed(self._created):
            try:
                self.t.request("DELETE", path)
            except (urllib.error.HTTPError, OSError):
                pass
        self._created.clear()

    def pods(self, ns: str) -> Dict[str, dict]:
        # namespaced list (the stub lists everything regardless; a real
        # cluster must not pay a cluster-wide pod list per wait poll)
        listing = self.t.get_json(f"/api/v1/namespaces/{ns}/pods")
        return {
            StubApiServer._key(p): p for p in listing.get("items", [])
            if (p.get("metadata") or {}).get("namespace") == ns
        }

    def apply_crds(self) -> None:
        """Apply deployment/crds/*.yaml — the chart's CRD registration."""
        import glob
        import os
        import urllib.error

        import yaml

        crd_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "deployment", "crds")
        for path in sorted(glob.glob(os.path.join(crd_dir, "*.yaml"))):
            with open(path) as f:
                crd = yaml.safe_load(f)
            try:
                self.create(_COLLECTIONS["customresourcedefinitions"], crd)
            except urllib.error.HTTPError as e:
                if e.code != 409:  # already exists
                    raise

    # -- object builders (test/e2e/util.go analogs) ----------------------
    def queue(self, name: str, weight: int) -> None:
        self.create(_COLLECTIONS["queues"], {
            "apiVersion": "scheduling.incubator.k8s.io/v1alpha1",
            "kind": "Queue", "metadata": {"name": name},
            "spec": {"weight": weight},
        })

    def node_obj(self, name: str, cpu_m: int = 4000, mem_gi: int = 16) -> dict:
        return {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": name,
                         "labels": {"kubernetes.io/hostname": name}},
            "spec": {},
            "status": {
                "allocatable": {"cpu": f"{cpu_m}m", "memory": f"{mem_gi}Gi",
                                "pods": "110"},
                "capacity": {"cpu": f"{cpu_m}m", "memory": f"{mem_gi}Gi",
                             "pods": "110"},
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        }

    def podgroup(self, ns: str, name: str, min_member: int, queue: str) -> None:
        self.create(_COLLECTIONS["podgroups"], {
            "apiVersion": "scheduling.incubator.k8s.io/v1alpha1",
            "kind": "PodGroup",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"minMember": min_member, "queue": queue},
        })

    def pod(self, ns: str, name: str, group: str, cpu_m: int = 1000,
            priority: int = 0, node: Optional[str] = None,
            node_selector: Optional[dict] = None,
            tolerations: Optional[list] = None,
            host_port: Optional[int] = None) -> None:
        container = {
            "name": "c", "image": "busybox",
            "resources": {"requests": {"cpu": f"{cpu_m}m", "memory": "1Gi"}},
        }
        if host_port is not None:
            container["ports"] = [{"containerPort": host_port,
                                   "hostPort": host_port}]
        obj = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {
                "name": name, "namespace": ns,
                "uid": f"{ns}-{name}-uid",
                "annotations": {"scheduling.k8s.io/group-name": group},
            },
            "spec": {
                "schedulerName": SCHED,
                "priority": priority,
                "containers": [container],
            },
            "status": {"phase": "Pending"},
        }
        if node_selector:
            obj["spec"]["nodeSelector"] = node_selector
        if tolerations:
            obj["spec"]["tolerations"] = tolerations
        if node is not None:
            obj["spec"]["nodeName"] = node
            obj["status"]["phase"] = "Running"
        self.create(f"/api/v1/namespaces/{ns}/pods", obj)

    def wait(self, predicate, timeout: float = 60.0, what: str = "",
             interval: float = 0.25) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if predicate():
                return
            time.sleep(interval)
        raise TimeoutError(f"e2e wait timed out: {what}")

    def n_on_nodes(self, ns: str, prefix: str = "") -> int:
        return sum(
            1 for k, p in self.pods(ns).items()
            if k.split("/", 1)[1].startswith(prefix)
            and (p.get("spec") or {}).get("nodeName")
        )


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def scenario_gang(c: Cluster, ns: str) -> None:
    """Gang scheduling (job.go:82): all minMember tasks bind together."""
    c.queue(f"{ns}-q", 1)
    c.create(_COLLECTIONS["nodes"], c.node_obj(f"{ns}-n1"))
    c.create(_COLLECTIONS["nodes"], c.node_obj(f"{ns}-n2"))
    c.podgroup(ns, "gang", 6, f"{ns}-q")
    for i in range(6):
        c.pod(ns, f"g{i}", "gang")
    c.wait(lambda: c.n_on_nodes(ns, "g") == 6, what="gang fully scheduled")


def scenario_gang_full(c: Cluster, ns: str) -> None:
    """Gang: Full Occupied (job.go:118): an unsatisfiable gang binds NOTHING
    (no partial placement) while a fitting gang proceeds."""
    c.queue(f"{ns}-q", 1)
    c.create(_COLLECTIONS["nodes"], c.node_obj(f"{ns}-n1", cpu_m=4000))
    c.podgroup(ns, "big", 8, f"{ns}-q")   # 8 x 1000m > 4000m — can't fit
    for i in range(8):
        c.pod(ns, f"big{i}", "big")
    c.podgroup(ns, "ok", 3, f"{ns}-q")
    for i in range(3):
        c.pod(ns, f"ok{i}", "ok")
    c.wait(lambda: c.n_on_nodes(ns, "ok") == 3, what="fitting gang scheduled")
    time.sleep(2.0)  # give the scheduler cycles to (wrongly) place the big gang
    assert c.n_on_nodes(ns, "big") == 0, "partial gang placement happened"


def scenario_preemption(c: Cluster, ns: str) -> None:
    """Preemption (job.go:189): a high-priority same-queue job evicts
    running victims and places once they terminate."""
    c.queue(f"{ns}-q", 1)
    c.create(_COLLECTIONS["nodes"], c.node_obj(f"{ns}-n1", cpu_m=4000))
    # minMember 2 with 4 running replicas: gang slack 2 — the victims the
    # gang plugin permits (evicting from a min==replicas gang would break
    # it, and the reference's Evictable refuses that too, gang.go:71-94)
    c.podgroup(ns, "low", 2, f"{ns}-q")
    for i in range(4):  # fills the node
        c.pod(ns, f"low{i}", "low", node=f"{ns}-n1")
    c.podgroup(ns, "high", 2, f"{ns}-q")
    for i in range(2):
        c.pod(ns, f"high{i}", "high", priority=1000)
    c.wait(lambda: c.n_on_nodes(ns, "high") == 2, timeout=90,
           what="high-priority job placed after preemption")


def scenario_reclaim(c: Cluster, ns: str) -> None:
    """Reclaim across queues (queue.go:26): a starved weighted queue evicts
    another queue's overuse."""
    c.queue(f"{ns}-q1", 1)
    c.queue(f"{ns}-q2", 1)
    c.create(_COLLECTIONS["nodes"], c.node_obj(f"{ns}-n1", cpu_m=4000))
    # gang slack 2 (see scenario_preemption): reclaimable without breaking
    # the hog's own gang
    c.podgroup(ns, "hog", 2, f"{ns}-q1")
    for i in range(4):
        c.pod(ns, f"hog{i}", "hog", node=f"{ns}-n1")
    c.podgroup(ns, "starved", 2, f"{ns}-q2")
    for i in range(2):
        c.pod(ns, f"starved{i}", "starved")
    c.wait(lambda: c.n_on_nodes(ns, "starved") == 2, timeout=90,
           what="starved queue reclaimed")


def scenario_proportion(c: Cluster, ns: str) -> None:
    """Proportion (job.go:458): weighted queues split contended capacity
    ~by weight; nothing is overcommitted."""
    c.queue(f"{ns}-gold", 2)
    c.queue(f"{ns}-bronze", 1)
    c.create(_COLLECTIONS["nodes"], c.node_obj(f"{ns}-n1", cpu_m=6000))
    c.podgroup(ns, "gj", 1, f"{ns}-gold")
    c.podgroup(ns, "bj", 1, f"{ns}-bronze")
    for i in range(6):
        c.pod(ns, f"gp{i}", "gj")
        c.pod(ns, f"bp{i}", "bj")
    c.wait(lambda: c.n_on_nodes(ns) >= 6, what="capacity filled")
    time.sleep(2.0)
    gold, bronze = c.n_on_nodes(ns, "gp"), c.n_on_nodes(ns, "bp")
    assert gold + bronze <= 6, f"overcommit: {gold}+{bronze}"
    assert gold >= bronze, f"weights inverted: gold={gold} bronze={bronze}"
    assert gold >= 3, f"gold under-served: {gold}"


def scenario_node_selector(c: Cluster, ns: str) -> None:
    """NodeAffinity/selector (predicates.go:35): a selector pod lands only
    on the matching node."""
    c.queue(f"{ns}-q", 1)
    red, blue = c.node_obj(f"{ns}-red"), c.node_obj(f"{ns}-blue")
    red["metadata"]["labels"]["color"] = "red"
    blue["metadata"]["labels"]["color"] = "blue"
    c.create(_COLLECTIONS["nodes"], red)
    c.create(_COLLECTIONS["nodes"], blue)
    c.podgroup(ns, "sel", 2, f"{ns}-q")
    for i in range(2):
        c.pod(ns, f"sel{i}", "sel", node_selector={"color": "blue"})
    c.wait(lambda: c.n_on_nodes(ns, "sel") == 2, what="selector pods placed")
    for k, p in c.pods(ns).items():
        assert p["spec"].get("nodeName") in (None, f"{ns}-blue"), (k, p["spec"])


def scenario_taints(c: Cluster, ns: str) -> None:
    """Taints/Tolerations (predicates.go:161): only tolerating pods land on
    the tainted node; the others go to the clean node."""
    c.queue(f"{ns}-q", 1)
    tainted = c.node_obj(f"{ns}-tainted", cpu_m=4000)
    tainted["spec"]["taints"] = [
        {"key": "dedicated", "value": "ml", "effect": "NoSchedule"}]
    c.create(_COLLECTIONS["nodes"], tainted)
    c.create(_COLLECTIONS["nodes"], c.node_obj(f"{ns}-clean", cpu_m=2000))
    c.podgroup(ns, "tol", 3, f"{ns}-q")
    tol = [{"key": "dedicated", "operator": "Equal", "value": "ml",
            "effect": "NoSchedule"}]
    for i in range(3):
        # selector pins tol pods to the tainted node: they can land there
        # ONLY via the toleration (the predicate under test), and the clean
        # node's exact capacity stays reserved for the plain gang
        c.pod(ns, f"tol{i}", "tol", tolerations=tol,
              node_selector={"kubernetes.io/hostname": f"{ns}-tainted"})
    c.podgroup(ns, "plain", 2, f"{ns}-q")
    for i in range(2):
        c.pod(ns, f"plain{i}", "plain")
    c.wait(lambda: c.n_on_nodes(ns) == 5, what="all pods placed")
    pods = c.pods(ns)
    for k, p in pods.items():
        name = k.split("/", 1)[1]
        on = p["spec"].get("nodeName")
        if name.startswith("plain"):
            assert on == f"{ns}-clean", (k, on)


def scenario_hostport(c: Cluster, ns: str) -> None:
    """Hostport (predicates.go:84): two pods claiming the same hostPort
    land on different nodes."""
    c.queue(f"{ns}-q", 1)
    c.create(_COLLECTIONS["nodes"], c.node_obj(f"{ns}-n1"))
    c.create(_COLLECTIONS["nodes"], c.node_obj(f"{ns}-n2"))
    c.podgroup(ns, "hp", 2, f"{ns}-q")
    for i in range(2):
        c.pod(ns, f"hp{i}", "hp", host_port=8080)
    c.wait(lambda: c.n_on_nodes(ns, "hp") == 2, what="hostport pods placed")
    nodes = {p["spec"]["nodeName"] for p in c.pods(ns).values()}
    assert len(nodes) == 2, f"hostPort conflict ignored: {nodes}"


def scenario_volume(c: Cluster, ns: str) -> None:
    """Local-PV reachability (the volumebinder feed, cache.go:189-209): a
    pod claiming an unbound no-provisioner PVC lands ONLY on the node its
    static PV is reachable from, and the scheduler pre-binds the PV
    (claimRef) cluster-side."""
    c.queue(f"{ns}-q", 1)
    c.create(_COLLECTIONS["nodes"], c.node_obj(f"{ns}-a"))
    c.create(_COLLECTIONS["nodes"], c.node_obj(f"{ns}-b"))
    c.create(_COLLECTIONS["storageclasses"], {
        "apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
        "metadata": {"name": f"{ns}-local"},
        "provisioner": "kubernetes.io/no-provisioner",
        "volumeBindingMode": "WaitForFirstConsumer",
    })
    c.create(_COLLECTIONS["persistentvolumes"], {
        "apiVersion": "v1", "kind": "PersistentVolume",
        "metadata": {"name": f"{ns}-pv"},
        "spec": {
            "capacity": {"storage": "10Gi"},
            "accessModes": ["ReadWriteOnce"],
            "storageClassName": f"{ns}-local",
            "local": {"path": "/mnt/ssd0"},
            "nodeAffinity": {"required": {"nodeSelectorTerms": [
                {"matchExpressions": [{"key": "kubernetes.io/hostname",
                                       "operator": "In",
                                       "values": [f"{ns}-b"]}]}
            ]}},
        },
        "status": {"phase": "Available"},
    })
    c.create(f"/api/v1/namespaces/{ns}/persistentvolumeclaims", {
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": "data", "namespace": ns},
        "spec": {"accessModes": ["ReadWriteOnce"],
                 "resources": {"requests": {"storage": "5Gi"}},
                 "storageClassName": f"{ns}-local"},
        "status": {"phase": "Pending"},
    })
    c.podgroup(ns, "stateful", 1, f"{ns}-q")
    pod = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "stateful-0", "namespace": ns,
                     "uid": f"{ns}-stateful-0-uid",
                     "annotations": {"scheduling.k8s.io/group-name": "stateful"}},
        "spec": {
            "schedulerName": SCHED,
            "containers": [{"name": "c", "image": "busybox",
                            "resources": {"requests": {"cpu": "500m",
                                                       "memory": "1Gi"}}}],
            "volumes": [{"name": "v",
                         "persistentVolumeClaim": {"claimName": "data"}}],
        },
        "status": {"phase": "Pending"},
    }
    c.create(f"/api/v1/namespaces/{ns}/pods", pod)
    c.wait(lambda: (c.pods(ns).get(f"{ns}/stateful-0") or {}).get(
        "spec", {}).get("nodeName") == f"{ns}-b",
        what="stateful pod on the PV's node")

    def claim_ref_landed():
        pv = c.t.get_json(f"/api/v1/persistentvolumes/{ns}-pv")
        ref = (pv.get("spec") or {}).get("claimRef") or {}
        return ref.get("name") == "data"
    c.wait(claim_ref_landed, timeout=30, what="PV claimRef pre-bound")


def scenario_job_priority(c: Cluster, ns: str) -> None:
    """Job priority (job.go:410): when both jobs are pending and capacity
    fits only one, the PriorityClass-backed job wins it atomically."""
    c.queue(f"{ns}-q", 1)
    c.create(_COLLECTIONS["priorityclasses"], {
        "apiVersion": "scheduling.k8s.io/v1", "kind": "PriorityClass",
        "metadata": {"name": f"{ns}-high"}, "value": 1000,
    })
    c.create(_COLLECTIONS["nodes"], c.node_obj(f"{ns}-n1", cpu_m=4000))
    # low submitted FIRST (earlier creation would win a priority tie)
    c.podgroup(ns, "low", 4, f"{ns}-q")
    for i in range(4):
        c.pod(ns, f"low{i}", "low")
    c.podgroup(ns, "high", 4, f"{ns}-q")
    for i in range(4):
        c.pod(ns, f"high{i}", "high", priority=1000)
    c.wait(lambda: c.n_on_nodes(ns, "high") == 4, timeout=60,
           what="high-priority job placed first")
    assert c.n_on_nodes(ns, "low") == 0, "low job took the contended capacity"


SCENARIOS = {
    "gang": scenario_gang,
    "gang_full": scenario_gang_full,
    "preemption": scenario_preemption,
    "reclaim": scenario_reclaim,
    "proportion": scenario_proportion,
    "node_selector": scenario_node_selector,
    "taints": scenario_taints,
    "hostport": scenario_hostport,
    "volume": scenario_volume,
    "job_priority": scenario_job_priority,
}


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def scheduler_process(master: str, extra_args=(), **auth):
    """The REAL CLI scheduler (`python -m kube_batch_tpu.cmd.main --master
    ...`, shipped 5-action conf) as a subprocess — exactly the deployment
    shape. Yields the Popen; logs drain to a temp file (an undrained PIPE
    would block the scheduler mid-run), surfaced on error."""
    import os
    import subprocess
    import tempfile

    from kube_batch_tpu.envutil import hardened_cpu_env

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from kube_batch_tpu.framework.conf import shipped_conf_path

    conf = shipped_conf_path()
    env = hardened_cpu_env()
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    )
    # hand the scheduler subprocess the same credentials the scenario
    # client carries (in_cluster_auth reads these overrides)
    token_tmp = None
    if auth.get("token"):
        token_tmp = tempfile.NamedTemporaryFile("w", delete=False, suffix=".token")
        token_tmp.write(auth["token"])
        token_tmp.close()
        env["KB_KUBE_TOKEN_FILE"] = token_tmp.name
    if auth.get("insecure"):
        env["KB_KUBE_INSECURE"] = "1"
    cmd = [
        sys.executable, "-m", "kube_batch_tpu.cmd.main",
        "--master", master,
        "--listen-address", "127.0.0.1:0",
        "--schedule-period", "0.25",
        "--scheduler-conf", conf,
        *extra_args,
    ]
    logf = tempfile.NamedTemporaryFile("w+", delete=False, suffix=".sched.log")
    proc = subprocess.Popen(cmd, env=env, stdout=logf, stderr=subprocess.STDOUT,
                            text=True)
    try:
        yield proc
    except Exception:
        logf.flush()
        try:
            with open(logf.name) as f:
                logger.error("scheduler process output:\n%s", f.read()[-4000:])
        except OSError:
            pass
        raise
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
        logf.close()
        os.unlink(logf.name)
        if token_tmp is not None:
            os.unlink(token_tmp.name)


def run_scenario(name: str, master: str, **auth) -> None:
    """One scenario: scheduler up, scenario body, scheduler DOWN, then
    teardown — deleting the scenario's objects under a live scheduler would
    bury failure-time log diagnostics in teardown-reaction noise."""
    c = Cluster(master, **auth)
    try:
        with scheduler_process(master, **auth) as proc:
            c.ensure_namespace(f"e2e-{name.replace('_', '-')}")
            SCENARIOS[name](c, ns=f"e2e-{name.replace('_', '-')}")
            if proc.poll() is not None:
                raise RuntimeError(
                    f"scheduler exited early rc={proc.returncode}")
    finally:
        c.teardown()


def run_density(master: str, n_pods: int = 3000, n_nodes: int = 100,
                gang: int = 100, **auth) -> dict:
    """The kubemark density benchmark at the LIVE protocol level
    (test/kubemark + test/e2e/benchmark.go:53-285): N hollow nodes, a
    minMember=`gang` gang, then `n_pods` 1m-cpu latency pods — all through
    the real apiserver protocol (watch in, Binding POSTs out), measuring
    per-pod create→bind PodStartupLatency percentiles.  The in-process
    testing/benchmark.py covers raw solve scale; this covers the wire."""
    ns = "e2e-density"
    c = Cluster(master, **auth)
    c.apply_crds()
    c.ensure_namespace(ns)
    # density is a THROUGHPUT measurement: lift the client egress throttle
    # (kube-api-qps 50 would serialize the per-cycle status writeback into
    # the latency signal; the reference's kubemark rig tunes QPS up too)
    # teardown runs AFTER the scheduler process exits (see run_scenario)
    with contextlib.ExitStack() as stack:
        stack.callback(c.teardown)
        stack.enter_context(scheduler_process(master, extra_args=(
            "--kube-api-qps", "5000", "--kube-api-burst", "10000"), **auth))
        c.queue(f"{ns}-q", 1)
        for i in range(n_nodes):
            c.create(_COLLECTIONS["nodes"],
                     c.node_obj(f"{ns}-n{i}", cpu_m=32000, mem_gi=64))
        # phase 1: the density gang (benchmark.go:50,61-71)
        c.podgroup(ns, "gang", gang, f"{ns}-q")
        for i in range(gang):
            c.pod(ns, f"gang-{i}", "gang", cpu_m=10)
        c.wait(lambda: c.n_on_nodes(ns, "gang-") == gang, timeout=120,
               what="density gang scheduled")
        # phase 2: latency pods in node-count batches (benchmark.go:74-110)
        created_at: Dict[str, float] = {}
        for i in range(n_pods):
            name = f"lat-{i}"
            c.podgroup(ns, name, 1, f"{ns}-q")
            created_at[name] = time.perf_counter()
            c.pod(ns, name, name, cpu_m=1)
        bound_at: Dict[str, float] = {}

        def all_bound():
            now = time.perf_counter()
            for key, p in c.pods(ns).items():
                name = key.split("/", 1)[1]
                if (name.startswith("lat-") and name not in bound_at
                        and (p.get("spec") or {}).get("nodeName")):
                    bound_at[name] = now
            return len(bound_at) >= n_pods
        # 1s poll: each poll LISTs every pod; tighter polling would load
        # the single-core stub more than it refines the percentiles
        c.wait(all_bound, timeout=600, what="latency pods scheduled",
               interval=1.0)
        lat = sorted(
            (bound_at[k] - created_at[k]) * 1e3 for k in bound_at
        )
        if not lat:
            return {"pods": 0, "nodes": n_nodes, "gang": gang}

        def pct(p):
            from kube_batch_tpu.sim.metrics import nearest_rank

            return round(nearest_rank(lat, p), 1)
        return {
            "pods": n_pods, "nodes": n_nodes, "gang": gang,
            "startup_p50_ms": pct(0.50), "startup_p90_ms": pct(0.90),
            "startup_p99_ms": pct(0.99),
            "note": "create->bind wall clock through the live watch/bind "
                    "protocol; resolution = the poll interval. Against the "
                    "--stub apiserver the protocol endpoint (pure-Python "
                    "HTTP on this host) bounds throughput, not the "
                    "scheduler — use a real/kind cluster for absolute "
                    "numbers; the in-process matrix "
                    "(testing/benchmark.py) isolates solve scale.",
        }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--master", help="apiserver URL (kind / real cluster)")
    ap.add_argument("--stub", action="store_true",
                    help="run against the in-process stub apiserver")
    ap.add_argument("--token", default=None)
    ap.add_argument("--insecure", action="store_true")
    ap.add_argument("--scenarios", default=",".join(SCENARIOS),
                    help="comma-separated subset")
    ap.add_argument("--density", action="store_true",
                    help="run the kubemark density benchmark instead of the "
                         "behavioral scenarios")
    ap.add_argument("--density-pods", type=int, default=3000)
    ap.add_argument("--density-nodes", type=int, default=100)
    args = ap.parse_args(argv)
    if not args.stub and not args.master:
        ap.error("need --master URL or --stub")
    auth = {"token": args.token, "insecure": args.insecure}

    if args.density:
        stub = None
        try:
            if args.stub:
                stub = StubApiServer()
                master = stub.start()
            else:
                master = args.master
            result = run_density(
                master, n_pods=args.density_pods, n_nodes=args.density_nodes,
                gang=min(100, args.density_pods),
                **{k: v for k, v in auth.items() if v},
            )
            print(json.dumps(result), flush=True)
            return 0
        finally:
            if stub is not None:
                stub.stop()

    names = [s for s in args.scenarios.split(",") if s]
    failures = []
    for name in names:
        stub = None
        try:
            if args.stub:
                stub = StubApiServer()
                master = stub.start()
            else:
                master = args.master
            c = Cluster(master, **{k: v for k, v in auth.items() if v})
            c.apply_crds()
            t0 = time.time()
            run_scenario(name, master,
                         **{k: v for k, v in auth.items() if v})
            print(f"PASS {name} ({time.time() - t0:.1f}s)", flush=True)
        except Exception as e:  # noqa: BLE001
            failures.append(name)
            print(f"FAIL {name}: {type(e).__name__}: {e}", flush=True)
        finally:
            if stub is not None:
                stub.stop()
    print(f"{len(names) - len(failures)}/{len(names)} scenarios passed",
          flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
