"""A faithful CPU re-creation of the reference's sequential allocate loop —
the denominator of BASELINE.md's "≥10× vs the Go allocate loop" target.

The reference's allocate (allocate.go:95-200) is an ordered greedy loop:
pop queue → pop job → per task: PredicateNodes over every node (16-worker
fan-out, scheduler_helper.go:34-64), PrioritizeNodes (LeastRequested +
BalancedResourceAllocation, nodeorder.go:188-227), SelectBestNode, place on
Idle (mutating the node for the next task), then commit the job's Statement
iff JobReady else roll every placement back (allocate.go:192-196).

This module reproduces exactly that control flow on the CPU: one task at a
time, full node scan per task, mutation between tasks, per-gang commit/
rollback.  The inner per-node predicate+score pass uses numpy vector ops as
the stand-in for the reference's compiled Go + 16-thread fan-out — a
GENEROUS stand-in: numpy's C inner loop over 5k nodes is at least as fast
as 16 goroutines chunking the same nodes, so the reported speedup is a
floor, not an estimate.  Semantics (greedy order, capacity algebra, gang
transaction) are the reference's; only the per-node arithmetic is batched.
"""

from __future__ import annotations

import time
from typing import Dict, Tuple

import numpy as np


def go_loop_allocate(
    task_req: np.ndarray,   # [T, R] f64 — InitResreq per pending task
    task_job: np.ndarray,   # [T] int — job index, tasks of a job contiguous
    job_min: np.ndarray,    # [J] int — gang minAvailable
    node_idle: np.ndarray,  # [N, R] f64 — MUTATED in place like the Go loop
    node_alloc: np.ndarray,  # [N, R] f64 — allocatable (for scoring)
    quanta: np.ndarray,     # [R]
) -> Tuple[np.ndarray, Dict[str, float]]:
    """Returns (assigned [T] node index or -1, stats)."""
    T, R = task_req.shape
    assigned = np.full(T, -1, np.int64)
    # semantic scoring dims like the k8s priorities: cpu (0) and memory (1)
    cap_cpu = np.maximum(node_alloc[:, 0], 1.0)
    cap_mem = np.maximum(node_alloc[:, 1], 1.0)

    t0 = time.perf_counter()
    placed_total = 0
    i = 0
    while i < T:
        j = task_job[i]
        lo = i
        while i < T and task_job[i] == j:
            i += 1
        gang = range(lo, i)
        placements = []  # (task, node, req) for rollback
        for t in gang:
            req = task_req[t]
            # ---- PredicateNodes: resource fit over EVERY node ----------
            feasible = np.all(req <= node_idle + quanta, axis=1)
            if not feasible.any():
                continue
            # ---- PrioritizeNodes: LeastRequested + Balanced ------------
            used_cpu = node_alloc[:, 0] - node_idle[:, 0] + req[0]
            used_mem = node_alloc[:, 1] - node_idle[:, 1] + req[1]
            fr_cpu = (cap_cpu - used_cpu) / cap_cpu
            fr_mem = (cap_mem - used_mem) / cap_mem
            least_requested = (fr_cpu + fr_mem) * 5.0   # *10/2
            balanced = 10.0 - np.abs(fr_cpu - fr_mem) * 10.0
            score = np.where(feasible, least_requested + balanced, -np.inf)
            # ---- SelectBestNode + place (mutates Idle for the next task)
            best = int(np.argmax(score))
            node_idle[best] -= req
            placements.append((t, best, req))
        # ---- gang Statement: commit iff JobReady else roll back --------
        if len(placements) >= job_min[j]:
            for t, n, _ in placements:
                assigned[t] = n
            placed_total += len(placements)
        else:
            for _, n, req in reversed(placements):
                node_idle[n] += req
    elapsed_ms = (time.perf_counter() - t0) * 1e3
    return assigned, {"elapsed_ms": elapsed_ms, "placed": placed_total}


def run_go_baseline(n_tasks: int, n_nodes: int, gang_size: int = 4,
                    n_queues: int = 3) -> Dict[str, float]:
    """Time the sequential loop over the same synthetic workload bench.py
    uses (tasks already in queue/job order — the PQ ordering the reference
    spends extra time maintaining is given to the loop for free)."""
    from kube_batch_tpu.testing.synthetic import synthetic_device_snapshot

    snap, meta = synthetic_device_snapshot(
        n_tasks=n_tasks, n_nodes=n_nodes, gang_size=gang_size, n_queues=n_queues
    )
    nt, nn = meta.n_tasks, meta.n_nodes
    task_req = np.asarray(snap.task_req)[:nt].astype(np.float64)
    task_job = np.asarray(snap.task_job)[:nt].astype(np.int64)
    job_min = np.asarray(snap.job_min_avail).astype(np.int64)
    node_idle = np.asarray(snap.node_idle)[:nn].astype(np.float64)
    node_alloc = np.asarray(snap.node_alloc)[:nn].astype(np.float64)
    quanta = np.asarray(snap.quanta).astype(np.float64)
    assigned, stats = go_loop_allocate(
        task_req, task_job, job_min, node_idle, node_alloc, quanta
    )
    stats["n_tasks"] = nt
    stats["n_nodes"] = nn
    return stats


if __name__ == "__main__":
    import json
    import sys

    nt = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    nn = int(sys.argv[2]) if len(sys.argv) > 2 else 5_000
    print(json.dumps(run_go_baseline(nt, nn)))
