"""A faithful CPU re-creation of the reference's sequential allocate loop —
the denominator of BASELINE.md's "≥10× vs the Go allocate loop" target.

The reference's allocate (allocate.go:95-200) is an ordered greedy loop:
pop queue → pop job → per task: PredicateNodes over every node (16-worker
fan-out, scheduler_helper.go:34-64), PrioritizeNodes (LeastRequested +
BalancedResourceAllocation, nodeorder.go:188-227), SelectBestNode, place on
Idle (mutating the node for the next task), then commit the job's Statement
iff JobReady else roll every placement back (allocate.go:192-196).

This module reproduces exactly that control flow on the CPU: one task at a
time, full node scan per task, mutation between tasks, per-gang commit/
rollback — in THREE denominators that bracket what the reference could
achieve, because the honest stand-in question was settled by measurement
(testing/go_pass_bench.py, VERDICT r3 weak #4):

  numpy           the original stand-in: Python loop + numpy vector pass.
                  MEASURED NOT to be a floor — a single C thread runs the
                  distilled pass ~6x faster than numpy's multi-temporary
                  vector code (37 us vs 245 us per 5k-node pass).
  native_single   the whole loop in compiled C (native/go_pass.c), one
                  thread — the MAXIMALLY GENEROUS lower bound: compiled-Go
                  speed class, zero framework overhead, no goroutine churn.
  native_pooled   same loop, per-task pass chunked over a persistent
                  16-thread pool with barriers — the reference's
                  ParallelizeUntil shape (still generous: the reference
                  spawns goroutines per call and runs the full vendored
                  predicate chain per node, not 4 float compares).

The real reference sits ABOVE these bounds: its per-node work is interface-
dispatched k8s predicates/priorities over NodeInfo maps (far heavier than
the distilled arithmetic), plus PQ maintenance, Statement allocations, and
per-placement event handlers — consistent with its own kubemark design
target of 3k pods x 100 nodes per 1 s cycle (SURVEY.md §6).
"""

from __future__ import annotations

import time
from typing import Dict, Tuple

import numpy as np


def numpy_inner_pass(req, node_idle, node_alloc, quanta, cap_cpu, cap_mem):
    """The per-task pass: PredicateNodes (resource fit over EVERY node) then
    PrioritizeNodes (LeastRequested + BalancedResourceAllocation) and argmax
    — shared with testing/go_pass_bench.py so the micro-benchmark times the
    loop's actual pass.  Returns the best node index or -1."""
    feasible = np.all(req <= node_idle + quanta, axis=1)
    if not feasible.any():
        return -1
    used_cpu = node_alloc[:, 0] - node_idle[:, 0] + req[0]
    used_mem = node_alloc[:, 1] - node_idle[:, 1] + req[1]
    fr_cpu = (cap_cpu - used_cpu) / cap_cpu
    fr_mem = (cap_mem - used_mem) / cap_mem
    least_requested = (fr_cpu + fr_mem) * 5.0   # *10/2
    balanced = 10.0 - np.abs(fr_cpu - fr_mem) * 10.0
    score = np.where(feasible, least_requested + balanced, -np.inf)
    return int(np.argmax(score))


def go_loop_allocate(
    task_req: np.ndarray,   # [T, R] f64 — InitResreq per pending task
    task_job: np.ndarray,   # [T] int — job index, tasks of a job contiguous
    job_min: np.ndarray,    # [J] int — gang minAvailable
    node_idle: np.ndarray,  # [N, R] f64 — MUTATED in place like the Go loop
    node_alloc: np.ndarray,  # [N, R] f64 — allocatable (for scoring)
    quanta: np.ndarray,     # [R]
) -> Tuple[np.ndarray, Dict[str, float]]:
    """Returns (assigned [T] node index or -1, stats)."""
    T, R = task_req.shape
    assigned = np.full(T, -1, np.int64)
    # semantic scoring dims like the k8s priorities: cpu (0) and memory (1)
    cap_cpu = np.maximum(node_alloc[:, 0], 1.0)
    cap_mem = np.maximum(node_alloc[:, 1], 1.0)

    t0 = time.perf_counter()
    placed_total = 0
    i = 0
    while i < T:
        j = task_job[i]
        lo = i
        while i < T and task_job[i] == j:
            i += 1
        gang = range(lo, i)
        placements = []  # (task, node, req) for rollback
        for t in gang:
            req = task_req[t]
            best = numpy_inner_pass(
                req, node_idle, node_alloc, quanta, cap_cpu, cap_mem
            )
            if best < 0:
                continue
            # ---- SelectBestNode + place (mutates Idle for the next task)
            node_idle[best] -= req
            placements.append((t, best, req))
        # ---- gang Statement: commit iff JobReady else roll back --------
        if len(placements) >= job_min[j]:
            for t, n, _ in placements:
                assigned[t] = n
            placed_total += len(placements)
        else:
            for _, n, req in reversed(placements):
                node_idle[n] += req
    elapsed_ms = (time.perf_counter() - t0) * 1e3
    return assigned, {"elapsed_ms": elapsed_ms, "placed": placed_total}


def _workload(n_tasks, n_nodes, gang_size, n_queues):
    from kube_batch_tpu.testing.synthetic import synthetic_device_snapshot

    snap, meta = synthetic_device_snapshot(
        n_tasks=n_tasks, n_nodes=n_nodes, gang_size=gang_size, n_queues=n_queues
    )
    nt, nn = meta.n_tasks, meta.n_nodes
    return (
        np.ascontiguousarray(np.asarray(snap.task_req)[:nt], np.float64),
        np.ascontiguousarray(np.asarray(snap.task_job)[:nt], np.int64),
        np.ascontiguousarray(np.asarray(snap.job_min_avail), np.int64),
        np.ascontiguousarray(np.asarray(snap.node_idle)[:nn], np.float64),
        np.ascontiguousarray(np.asarray(snap.node_alloc)[:nn], np.float64),
        np.ascontiguousarray(np.asarray(snap.quanta), np.float64),
        nt, nn,
    )


def go_loop_allocate_native(task_req, task_job, job_min, node_idle,
                            node_alloc, quanta, pooled: bool,
                            threads: int = 16):
    """The same loop run entirely in compiled C (native/go_pass.c).
    Returns (assigned, stats) or None when the library is unavailable."""
    import ctypes

    from kube_batch_tpu.testing.go_pass_bench import _load

    lib = _load()
    if lib is None:
        return None
    T, R = task_req.shape
    N = node_idle.shape[0]
    lib.go_loop_run.restype = ctypes.c_int64
    lib.go_loop_run.argtypes = [ctypes.c_void_p] * 6 + [
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_void_p,
    ]
    assigned = np.full(T, -1, np.int64)
    scratch = np.zeros(T, np.int64)
    if pooled and lib.go_pass_pool_init(threads) != 0:
        return None
    t0 = time.perf_counter()
    placed = lib.go_loop_run(
        task_req.ctypes.data, task_job.ctypes.data, job_min.ctypes.data,
        node_idle.ctypes.data, node_alloc.ctypes.data, quanta.ctypes.data,
        T, N, R, 1 if pooled else 0,
        assigned.ctypes.data, scratch.ctypes.data,
    )
    elapsed_ms = (time.perf_counter() - t0) * 1e3
    if pooled:
        lib.go_pass_pool_shutdown()
    return assigned, {"elapsed_ms": elapsed_ms, "placed": int(placed)}


def run_go_baseline(n_tasks: int, n_nodes: int, gang_size: int = 4,
                    n_queues: int = 3) -> Dict[str, float]:
    """Time the sequential loop over the same synthetic workload bench.py
    uses (tasks already in queue/job order — the PQ ordering the reference
    spends extra time maintaining is given to the loop for free).

    Reports the numpy re-creation plus, when the C library builds, the
    compiled-C bracket (see module docstring): `native_single_ms` is the
    maximally generous denominator; `native_pooled_ms` the reference's
    16-worker chunking shape."""
    task_req, task_job, job_min, node_idle, node_alloc, quanta, nt, nn = (
        _workload(n_tasks, n_nodes, gang_size, n_queues)
    )
    assigned, stats = go_loop_allocate(
        task_req, task_job, job_min, node_idle.copy(), node_alloc, quanta
    )
    stats["n_tasks"] = nt
    stats["n_nodes"] = nn
    # identical control flow + arithmetic ⇒ identical placements; a C run
    # whose placements diverge is NOT a valid denominator and reports its
    # divergence count INSTEAD of a time (bench.py only copies *_ms keys)
    for label, pooled in (("native_single", False), ("native_pooled", True)):
        out = go_loop_allocate_native(
            task_req, task_job, job_min, node_idle.copy(), node_alloc, quanta,
            pooled=pooled,
        )
        if out is None:
            continue
        a_native, s_native = out
        if np.array_equal(a_native, assigned):
            stats[f"{label}_ms"] = round(s_native["elapsed_ms"], 1)
            stats[f"{label}_placed"] = s_native["placed"]
        else:
            stats[f"{label}_divergence"] = int((a_native != assigned).sum())
    return stats


if __name__ == "__main__":
    import json
    import sys

    nt = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    nn = int(sys.argv[2]) if len(sys.argv) > 2 else 5_000
    print(json.dumps(run_go_baseline(nt, nn)))
