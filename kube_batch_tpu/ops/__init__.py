"""The TPU compute path: each module is a family of rows in the per-cycle
cost/mask tensor program (SURVEY.md §7.1).

feasibility — boolean [T, N] masks (PredicateFn analog)
scoring     — additive f32 [T, N] scores (NodeOrderFn analog, incl. binpack)
fairness    — DRF shares, proportion deserved/overused (drf.go / proportion.go)
ordering    — total task order encoding job/task order fns as sortable ranks
assignment  — the gang-constrained allocate solve (allocate.go + statement.go)
"""
