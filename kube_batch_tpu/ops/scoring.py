"""Node scoring — the NodeOrderFn tier as additive [T, N] score rows.

Replaces the reference's PrioritizeNodes 16-worker map/reduce
(util/scheduler_helper.go:67-129) over the nodeorder plugin's vendored k8s
priorities (plugins/nodeorder/nodeorder.go:188-247). Each function returns a
[T, N] f32 in the k8s 0..10 scale; the session sums them with per-function
weights (nodeorder.go:34-43 defaults = 1) exactly like
Session.NodeOrderFn sums plugin scores (session_plugins.go:392-412).

Also exposes the binpack row: not present in this reference snapshot (it
arrived later in Volcano) but named by the rebuild's north star, so it is a
first-class score here (SURVEY.md §2.4 note).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from kube_batch_tpu.api.snapshot import DeviceSnapshot

MAX_PRIORITY = 10.0


class ScoreWeights(NamedTuple):
    """Per-row weights (plugin args nodeorder.go:34-43 + binpack).

    `extra_rows` is the score-row EXTENSION SEAM (the reference's
    NodeOrderFn/BatchNodeOrderFn registration surface,
    session_plugins.go:392-492): a tuple of (name, fn, weight) where
    fn(snap: DeviceSnapshot) -> [T, N] f32 is traced into the compiled
    solve and summed like the built-in rows.  Register through
    Session.add_score_row.  ScoreWeights is a static jit argument, so the
    registered set keys the compile cache — use module-level functions
    (not per-session lambdas) to reuse compiles across sessions."""

    least_requested: float = 1.0
    balanced_resource: float = 1.0
    node_affinity: float = 1.0
    pod_affinity: float = 1.0
    binpack: float = 0.0  # off by default, like the reference snapshot
    extra_rows: tuple = ()  # ((name, fn, weight), ...)


def _semantic(snap: DeviceSnapshot) -> jnp.ndarray:
    """cpu+memory columns only — the k8s priorities score cpu and memory."""
    return jnp.asarray([0, 1])


def least_requested(snap: DeviceSnapshot) -> jnp.ndarray:
    """LeastRequestedPriority (vendored k8s, wired at nodeorder.go:188-205):
    score = mean over {cpu, mem} of (allocatable − used − req) * 10 /
    allocatable. Higher = emptier node → spreading."""
    cols = _semantic(snap)
    alloc = snap.node_alloc[:, cols]  # [N, 2]
    free_after = alloc[None, :, :] - snap.node_used[None, :, cols] - snap.task_req[:, None, cols]
    frac = jnp.where(alloc[None, :, :] > 0, free_after / alloc[None, :, :], 0.0)
    return jnp.clip(frac, 0.0, 1.0).mean(axis=-1) * MAX_PRIORITY  # [T, N]


def balanced_resource(snap: DeviceSnapshot) -> jnp.ndarray:
    """BalancedResourceAllocation (nodeorder.go:207-227): score = 10 −
    |cpuFraction − memFraction| * 10 where fraction = (used+req)/allocatable."""
    cols = _semantic(snap)
    alloc = snap.node_alloc[:, cols]
    want = snap.node_used[None, :, cols] + snap.task_req[:, None, cols]
    frac = jnp.where(alloc[None, :, :] > 0, want / alloc[None, :, :], 1.0)
    frac = jnp.clip(frac, 0.0, 1.0)
    diff = jnp.abs(frac[..., 0] - frac[..., 1])
    return (1.0 - diff) * MAX_PRIORITY


def binpack(snap: DeviceSnapshot) -> jnp.ndarray:
    """Binpack: prefer fuller nodes — score = mean over {cpu, mem} of
    (used+req)/allocatable * 10. The inverse of least_requested; the
    weighted-resource packing score the north star asks for (Volcano's later
    binpack plugin computes the same ratio with per-resource weights)."""
    cols = _semantic(snap)
    alloc = snap.node_alloc[:, cols]
    want = snap.node_used[None, :, cols] + snap.task_req[:, None, cols]
    frac = jnp.where(alloc[None, :, :] > 0, want / alloc[None, :, :], 0.0)
    return jnp.clip(frac, 0.0, 1.0).mean(axis=-1) * MAX_PRIORITY


def _scatter_pref(snap: DeviceSnapshot, rows: jnp.ndarray) -> jnp.ndarray:
    """[T, N] from the sparse [Kp, N] preference rows: padding index (-1)
    clips to row 0 with a zero update (rows are zeroed where idx < 0)."""
    T = snap.task_req.shape[0]
    N = snap.node_alloc.shape[0]
    upd = jnp.where((snap.task_pref_idx >= 0)[:, None], rows, 0.0)
    return jnp.zeros((T, N), jnp.float32).at[
        jnp.clip(snap.task_pref_idx, 0, T - 1)
    ].add(upd)


def node_affinity_preferred(snap: DeviceSnapshot) -> jnp.ndarray:
    """CalculateNodeAffinityPriorityMap analog (nodeorder.go:188-205), from
    the host-precompiled sparse preference rows (snapshot.task_pref_node)."""
    return _scatter_pref(snap, snap.task_pref_node)


def pod_affinity_preferred(snap: DeviceSnapshot) -> jnp.ndarray:
    """InterPodAffinityPriority analog — the BatchNodeOrderFn row
    (nodeorder.go:229-247), from snapshot.task_pref_pod."""
    return _scatter_pref(snap, snap.task_pref_pod)


def score_matrix(snap: DeviceSnapshot, w: ScoreWeights) -> jnp.ndarray:
    """Σ_k weight_k · row_k — Session.NodeOrderFn (session_plugins.go:392-412)."""
    s = jnp.zeros((snap.task_req.shape[0], snap.node_alloc.shape[0]), jnp.float32)
    if w.least_requested:
        s = s + w.least_requested * least_requested(snap)
    if w.balanced_resource:
        s = s + w.balanced_resource * balanced_resource(snap)
    if w.binpack:
        s = s + w.binpack * binpack(snap)
    if w.node_affinity:
        s = s + w.node_affinity * node_affinity_preferred(snap)
    if w.pod_affinity:
        s = s + w.pod_affinity * pod_affinity_preferred(snap)
    for _name, fn, weight in w.extra_rows:
        if weight:
            s = s + weight * fn(snap)
    return s
