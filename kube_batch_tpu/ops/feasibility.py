"""Feasibility masks — the PredicateFn tier as one [T, N] boolean program.

Replaces the reference's 16-worker PredicateNodes fan-out
(util/scheduler_helper.go:34-64) and the predicates plugin's per-task×node Go
checks (plugins/predicates/predicates.go:154-298) with vmapped bit/compare
ops over the device snapshot:

  - resource fit vs Idle / Releasing (allocate.go:80-93 composite predicate),
    epsilon-tolerant like Resource.LessEqual (resource_info.go:269-284);
    max-pods (predicates.go:162-166) falls out of the pods dimension
  - node ready / unschedulable (CheckNodeCondition/CheckNodeUnschedulable,
    predicates.go:169-192)
  - node-selector and required node-affinity as label-bit subset tests
    (MatchNodeSelector, predicates.go:194-205)
  - taints/tolerations as taint-bit coverage tests (PodToleratesNodeTaints,
    predicates.go:220-231)

Everything here is shape-polymorphic over a leading task axis and a node
axis; jit once per (T, N, R) bucket.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from kube_batch_tpu.api.snapshot import DeviceSnapshot


class FeasibilityMasks(NamedTuple):
    static_ok: jnp.ndarray   # [T, N] bool — non-resource predicates
    fit_idle: jnp.ndarray    # [T, N] bool — InitResreq ≤ Idle (+quanta)
    fit_releasing: jnp.ndarray  # [T, N] bool — InitResreq ≤ Releasing (+quanta)
    feasible: jnp.ndarray    # [T, N] bool — static ∧ (fit_idle ∨ fit_releasing)


def fits(req: jnp.ndarray, budget: jnp.ndarray, quanta: jnp.ndarray) -> jnp.ndarray:
    """Epsilon-tolerant LessEqual broadcast: req [T, R] vs budget [N, R] →
    [T, N]. A dimension passes if req ≤ budget or the excess is below the
    quantum (resource_info.go:269-284)."""
    # [T, 1, R] vs [1, N, R] — XLA fuses the broadcast+reduce, nothing [T,N,R]
    # is materialized.
    return jnp.all(req[:, None, :] <= budget[None, :, :] + quanta, axis=-1)


def static_predicates(snap: DeviceSnapshot) -> jnp.ndarray:
    """[T, N] non-resource predicate conjunction."""
    # node health: Ready and not marked Unschedulable
    node_ok = snap.node_valid & snap.node_sched  # [N]

    # selector: every required label bit present on the node
    sel_ok = jnp.all(
        (snap.task_sel_bits[:, None, :] & snap.node_label_bits[None, :, :])
        == snap.task_sel_bits[:, None, :],
        axis=-1,
    )  # [T, N]
    sel_ok &= ~snap.task_sel_impossible[:, None]

    # taints: every hard taint on the node must be tolerated
    taints_ok = jnp.all(
        (snap.node_taint_bits[None, :, :] & ~snap.task_tol_bits[:, None, :]) == 0,
        axis=-1,
    )  # [T, N]

    ok = node_ok[None, :] & sel_ok & taints_ok
    # sparse inter-pod-affinity correction rows (snapshot.task_aff_*):
    # unique task indices, padding rows (-1) clip to row 0 with an all-True
    # mask, so the scatter-min is a no-op there
    T = ok.shape[0]
    upd = jnp.where((snap.task_aff_idx >= 0)[:, None], snap.task_aff_mask, True)
    return ok.at[jnp.clip(snap.task_aff_idx, 0, T - 1)].min(upd)


def feasibility(snap: DeviceSnapshot) -> FeasibilityMasks:
    static_ok = static_predicates(snap)
    fit_idle = fits(snap.task_req, snap.node_idle, snap.quanta)
    fit_rel = fits(snap.task_req, snap.node_releasing, snap.quanta)
    feasible = static_ok & (fit_idle | fit_rel)
    return FeasibilityMasks(static_ok, fit_idle, fit_rel, feasible)


# Reason codes for fit-error diagnostics (unschedule_info.go:11-19); the host
# renders these into FitErrors strings for unplaced tasks only.
REASON_NODE_UNHEALTHY = 0
REASON_SELECTOR = 1
REASON_TAINT = 2
REASON_RESOURCE = 3
N_REASONS = 4

# canonical message per reason class (unschedule_info.go:11-19 style)
REASON_MESSAGES = (
    "node(s) were not ready or unschedulable",
    "node(s) didn't match node selector",
    "node(s) had taints that the pod didn't tolerate",
    "Insufficient resources",
)


def failure_histogram(snap: DeviceSnapshot, masks: FeasibilityMasks) -> jnp.ndarray:
    """[T, N_REASONS] i32: per task, how many valid nodes failed each
    predicate class — the device analog of FitErrors' reason histogram."""
    node_ok = snap.node_valid & snap.node_sched
    nodes = snap.node_valid[None, :]
    sel_ok = jnp.all(
        (snap.task_sel_bits[:, None, :] & snap.node_label_bits[None, :, :])
        == snap.task_sel_bits[:, None, :],
        axis=-1,
    ) & ~snap.task_sel_impossible[:, None]
    taints_ok = jnp.all(
        (snap.node_taint_bits[None, :, :] & ~snap.task_tol_bits[:, None, :]) == 0,
        axis=-1,
    )
    fit = masks.fit_idle | masks.fit_releasing
    T = snap.task_req.shape[0]
    unhealthy = jnp.broadcast_to(
        jnp.sum(snap.node_valid & ~node_ok), (T,)
    )  # task-independent
    return jnp.stack(
        [
            unhealthy,
            jnp.sum(nodes & node_ok[None, :] & ~sel_ok, axis=1),
            jnp.sum(nodes & node_ok[None, :] & sel_ok & ~taints_ok, axis=1),
            jnp.sum(nodes & masks.static_ok & ~fit, axis=1),
        ],
        axis=1,
    ).astype(jnp.int32)
