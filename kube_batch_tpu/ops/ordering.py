"""Task/job ordering — the order-fn tiers as device-computed ranks.

The reference's allocate pops queues by QueueOrderFn (proportion share), jobs
by JobOrderFn (tier chain: gang starved-first → drf share → priority →
creation/UID fallback, session_plugins.go:281-305), and tasks by TaskOrderFn
(priority → creation, :336-369). In the batched solve, that whole chain
collapses into one total order rank[T]: conflicts for the same node are won
by the lowest rank, which reproduces "who the sequential loop would have
served first".

Multi-key ordering is built by chained stable argsorts (least-significant key
first) — no packed integer keys, no precision traps.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from kube_batch_tpu.ops import fairness


def segmented_prefix(values_sorted: jnp.ndarray, is_start: jnp.ndarray) -> jnp.ndarray:
    """Exclusive per-segment prefix sum of already-sorted [T, R] values ≥ 0.
    The global exclusive cumsum is monotone per dim, so each segment's base is
    a running max of the cumsum values captured at segment starts."""
    csum = jnp.cumsum(values_sorted, axis=0)
    prev = csum - values_sorted
    base = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_start[:, None], prev, 0.0), axis=0
    )
    return prev - base


def sort_by_segment_then_rank(
    segment: jnp.ndarray, rank: jnp.ndarray, n_segments: int
) -> jnp.ndarray:
    """argsort by (segment, rank) where rank is a permutation of [0, T).

    When segment·2^ceil(log2 T) fits in int32 the two keys pack into ONE sort
    key — a single argsort instead of the chained stable pair. TPU sorts are
    the dominant cost of the solve's inner rounds, so this matters.
    """
    T = rank.shape[0]
    t_pow = 1 << max(T - 1, 1).bit_length()
    if n_segments * t_pow < 2**31:
        return jnp.argsort(segment * jnp.int32(t_pow) + rank)
    order = jnp.argsort(rank, stable=True)
    return order[jnp.argsort(segment[order], stable=True)]


def multisort_ranks(keys: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """rank[i] = position of element i under lexicographic (keys[0], keys[1],
    ...) ascending order. All keys are 1-D of equal length."""
    n = keys[0].shape[0]
    order = jnp.arange(n)
    for key in reversed(list(keys)):
        # kbt: allow[KBT005] trace-time unroll over the static key list (a
        # handful of sort keys) inside jit — no per-iteration host dispatch
        order = order[jnp.argsort(key[order], stable=True)]
    rank = jnp.zeros(n, jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
    return rank


def virtual_task_ranks(
    pending: jnp.ndarray,      # [T] bool — bidders this round
    resreq: jnp.ndarray,       # [T, R]
    task_job: jnp.ndarray,     # [T] i32
    task_queue: jnp.ndarray,   # [T] i32
    subrank: jnp.ndarray,      # [T] i32 — within-job TaskOrderFn rank
    job_prio: jnp.ndarray,     # [J] i32
    job_ready_now: jnp.ndarray,  # [J] bool
    job_creation: jnp.ndarray,   # [J] i32
    job_alloc: jnp.ndarray,    # [J, R] — incl. this cycle's placements
    queue_alloc: jnp.ndarray,  # [Q, R] — incl. this cycle's placements
    deserved: jnp.ndarray,     # [Q, R]
    total: jnp.ndarray,        # [R]
    job_need: jnp.ndarray,     # [J] i32 — minAvailable − currently-ready
    gang_enabled: bool,
    drf_enabled: bool,
    proportion_enabled: bool,
) -> jnp.ndarray:
    """[T] i32 — the total order the sequential pop loop would serve tasks in.

    The reference re-evaluates QueueOrderFn (proportion share) and JobOrderFn
    (drf share) on *live* state after every placement, producing share-ordered
    alternation between queues/jobs. The batched analog is fair-queuing
    virtual time: a task's key is the share its queue (resp. job) will have
    reached at the task's own prefix position within that queue (resp. job) —
    sorting by virtual share reproduces the alternation without a sequential
    loop.

    Gang-chunk granularity: the sequential loop serves a popped job until
    JobReady before re-evaluating any order fn (allocate.go:137-190), so an
    unready job's first `job_need` pending tasks (its gang chunk) must be
    CONTIGUOUS in the rank — otherwise two starved gangs interleave, both
    place partially, and the commit gate reverts both where the reference
    would have served one then the other. In-chunk tasks therefore all carry
    the share at the chunk start; only beyond-chunk tasks accrue per-task
    virtual time.

    Key chain (outer→inner), matching the default two-tier conf
    (pkg/scheduler/util.go:31-42: tier1 priority,gang,conformance; tier2
    drf,predicates,proportion,nodeorder):
      1. queue virtual proportion share (QueueOrderFn, proportion.go:156-169)
      2. job priority desc (priority.go:69-77)
      3. gang starved-first (gang.go:96-121)
      4. job virtual drf share (drf.go:114-132)
      5. job creation asc (fallback, session_plugins.go:281-305)
      6. within-job subrank (TaskOrderFn)
    """
    T = resreq.shape[0]
    n_jobs = job_prio.shape[0]
    n_queues = deserved.shape[0]
    rq = jnp.where(pending[:, None], resreq, 0.0)

    # job-axis: position of each pending task within its job (subrank order)
    order_j = sort_by_segment_then_rank(task_job, subrank, n_jobs)
    js = task_job[order_j]
    j_start = jnp.concatenate([jnp.array([True]), js[1:] != js[:-1]])
    ci = pending[order_j].astype(jnp.float32)[:, None]
    pos_in_job = segmented_prefix(ci, j_start)[:, 0].astype(jnp.int32)
    in_chunk_sorted = pending[order_j] & (pos_in_job < job_need[js])
    in_chunk = jnp.zeros(T, bool).at[order_j].set(in_chunk_sorted)

    # virtual drf share: chunk-start share for in-chunk tasks, per-task
    # prefix share beyond the chunk
    prefix_j = segmented_prefix(rq[order_j], j_start)
    share_start = fairness.dominant_share(job_alloc, total)  # [J]
    vd_sorted = jnp.where(
        in_chunk_sorted,
        share_start[js],
        fairness.dominant_share(job_alloc[js] + prefix_j, total),
    )
    v_drf = jnp.zeros(T, jnp.float32).at[order_j].set(vd_sorted)

    # within-queue key (everything but the queue tier)
    wq_keys = [-job_prio[task_job]]
    if gang_enabled:
        wq_keys.append(job_ready_now[task_job].astype(jnp.int32))  # starved first
    if drf_enabled:
        wq_keys.append(jnp.round(v_drf * 1e6).astype(jnp.int32))
    wq_keys += [job_creation[task_job], subrank]
    wq_rank = multisort_ranks(wq_keys)

    if not proportion_enabled:
        # QueueOrderFn falls back to creation/UID — queues drain in index
        # order, one job at a time
        return multisort_ranks([task_queue, wq_rank])

    # queue-axis virtual proportion share: prefix within queue in wq order.
    # A job's chunk is contiguous in wq_rank (all chunk tasks tie on v_drf and
    # job keys), so the chunk-head's share can be broadcast job-wide via a
    # scatter-min — the whole chunk then ties on v_q too and stays contiguous.
    order_q = sort_by_segment_then_rank(task_queue, wq_rank, n_queues)
    qs = task_queue[order_q]
    q_start = jnp.concatenate([jnp.array([True]), qs[1:] != qs[:-1]])
    prefix_q = segmented_prefix(rq[order_q], q_start)
    vq_sorted = fairness.queue_share(queue_alloc[qs] + prefix_q, deserved[qs])
    v_q = jnp.zeros(T, jnp.float32).at[order_q].set(vq_sorted)
    head_vq = jnp.full(n_jobs, jnp.inf, jnp.float32).at[task_job].min(
        jnp.where(in_chunk, v_q, jnp.inf)
    )
    v_q = jnp.where(in_chunk, head_vq[task_job], v_q)

    return multisort_ranks([jnp.round(v_q * 1e6).astype(jnp.int32), wq_rank])


def task_subranks(task_prio: jnp.ndarray, task_creation: jnp.ndarray) -> jnp.ndarray:
    """[T] i32 within-job order: priority desc then creation asc
    (TaskOrderFn via priority plugin, session_plugins.go:336-369). Static per
    cycle."""
    return multisort_ranks([-task_prio, task_creation])


