"""The gang-constrained allocate solve — allocate.go + statement.go as one
compiled tensor program.

The reference's allocate is an ordered greedy loop: pop queue (skip overused),
pop job, pop task, predicate all nodes (16 workers), score, pick best, place
on Idle or pipeline on Releasing, commit the job's Statement iff JobReady else
roll back (allocate.go:95-200, statement.go:309-337). That sequencing is
O(tasks × nodes) of host work per cycle.

Here the same semantics run as batched auction rounds on device:

  round:  every unplaced task bids for its best feasible node (argmax over a
          masked score row); conflicts on a node are resolved by admitting
          bidders in task-order-rank sequence until the node's budget is
          exhausted (a segmented prefix-sum over the rank-sorted bidders —
          the moral equivalent of "the PQ order reaches the node first");
          losers re-bid next round against updated budgets.
  gang:   after the rounds, jobs whose allocated count (existing ready + new)
          misses MinAvailable get every new placement reverted — the
          vectorized Statement.Discard (statement.go:309-322); an outer
          iteration then lets surviving tasks re-bid for the freed resources.

Divergences from the sequential loop are the sanctioned ones (SURVEY.md
§7.3): placement ties may resolve differently (the reference's
SelectBestNode is itself randomized among max-score nodes,
scheduler_helper.go:147-158), but the invariants hold — no node overcommit,
no committed partial gang, overused queues don't gain tasks.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kube_batch_tpu.api.snapshot import DeviceSnapshot
from kube_batch_tpu.utils import jitstats
from kube_batch_tpu.ops import fairness, ordering
from kube_batch_tpu.ops.ordering import segmented_prefix as _segmented_prefix
from kube_batch_tpu.ops.feasibility import fits, static_predicates
from kube_batch_tpu.ops.scoring import ScoreWeights, score_matrix

NEG = jnp.float32(-3.0e38)

# the multiplicative hash constants as wrapped int32 (two's complement):
# int32 wrapping arithmetic is bit-identical to uint32 mod-2^32, and staying
# in int32 avoids uint32<->float casts TPU Pallas doesn't support
_H1 = 0x9E3779B1 - (1 << 32)
_H2 = 0x85EBCA77 - (1 << 32)
_H3 = 0xCA87C3EB - (1 << 32)


def tie_break_hash_rows(ti: jnp.ndarray, ni: jnp.ndarray) -> jnp.ndarray:
    """[len(ti), len(ni)] deterministic per-(task, node) hash in [0, 65535]
    (i32) from explicit GLOBAL task/node indices.  The what-if probe
    (ops/probe.py) hashes a speculative gang at the rows it WOULD occupy on
    submission — sharing this one formula is what makes the probe's
    tie-breaks bit-identical to the committed solve's."""
    h = ti[:, None] * jnp.int32(_H1) + ni[None, :] * jnp.int32(_H2)
    h = (h ^ jax.lax.shift_right_logical(h, 15)) * jnp.int32(_H3)
    return jax.lax.shift_right_logical(h, 16)


def _tie_break_hash(T: int, N: int, t0=0, n0=0) -> jnp.ndarray:
    """[T, N] deterministic per-(task, node) hash in [0, 65535] (i32).
    Ordering is identical to the previous float form (a monotone rescale of
    the same 16 hash bits).  `t0`/`n0` (static or traced i32) offset the
    indices to GLOBAL coordinates when (T, N) is a block of a larger matrix
    — the shard_map round head (parallel/shard_solve.py) computes the hash
    of its local block and must agree bit-for-bit with the full matrix."""
    return tie_break_hash_rows(
        jnp.arange(T, dtype=jnp.int32) + t0,
        jnp.arange(N, dtype=jnp.int32) + n0,
    )


def _best_node(masked: jnp.ndarray, tie_hash: jnp.ndarray):
    """Lexicographic argmax: among the nodes carrying the exact maximum
    score, pick by per-(task, node) hash — the reference's SelectBestNode
    picks uniformly among max-score nodes (scheduler_helper.go:147-158), and
    without a spread every equal-score task herds onto the same argmax node,
    filling one node per bidding round. Exact two-key semantics: a hash can
    never override a genuine score difference (unlike additive jitter).

    Returns (best [T] i32, has [T] bool)."""
    best_val = jnp.max(masked, axis=1)
    tie = masked >= best_val[:, None]
    best = jnp.argmax(jnp.where(tie, tie_hash, -1), axis=1).astype(jnp.int32)
    return best, best_val > NEG


class AllocateConfig(NamedTuple):
    """Static solve configuration (plugin enables + round counts). Part of
    the jit cache key."""

    rounds: int = 6          # bidding rounds per outer iteration
    outer: int = 3           # gang discard-retry iterations
    gang: bool = True        # gang plugin (JobReady commit gate)
    drf: bool = True         # drf job ordering
    proportion: bool = True  # queue overused gating + queue order
    use_pallas: bool = False  # fused round-head kernel (ops/pallas_kernels)
    topk: int = 0            # top-K candidate compaction width (the
    #                          allocate_topk_solve path only; 0 in every
    #                          full-matrix program — see KB_TOPK in
    #                          actions/allocate.py's dispatch)
    weights: ScoreWeights = ScoreWeights()


class AllocateResult(NamedTuple):
    assigned: jnp.ndarray       # [T] i32 node index, -1 = unplaced
    pipelined: jnp.ndarray      # [T] bool — placed on Releasing (future) budget
    committed: jnp.ndarray      # [J] bool — job's new placements were kept
    node_idle: jnp.ndarray      # [N, R] post-solve
    node_releasing: jnp.ndarray  # [N, R] post-solve
    node_used: jnp.ndarray      # [N, R] post-solve
    deserved: jnp.ndarray       # [Q, R] proportion deserved (diagnostics)
    rounds_run: jnp.ndarray     # [] i32 — total bidding rounds executed
    #                             (convergence diagnostic for round tuning)
    topk_exhausted: jnp.ndarray  # [] i32 — task-rounds whose candidate list
    #                              was exhausted (0 on the full-matrix path)
    topk_reentries: jnp.ndarray  # [] i32 — rounds that re-entered the
    #                              full-matrix head for exhausted rows


@jax.jit
def failure_histogram_solve(snap: DeviceSnapshot) -> jnp.ndarray:
    """[T, N_REASONS] cycle-start fit-error histogram as its OWN dispatch.

    The histogram re-walks the [T, N]-scale predicate bitsets, so folding it
    into allocate_solve taxed every cycle — including the steady-state ones
    where every pending task places and the histogram is never read
    (allocate.go:151-155 only builds FitErrors for tasks that failed). The
    action calls this lazily, after the solve's assignment shows unplaced
    pending tasks."""
    from kube_batch_tpu.ops.feasibility import FeasibilityMasks, failure_histogram

    static_ok = static_predicates(snap)
    fit0_idle = fits(snap.task_req, snap.node_idle, snap.quanta)
    fit0_rel = fits(snap.task_req, snap.node_releasing, snap.quanta)
    return failure_histogram(
        snap,
        FeasibilityMasks(
            static_ok, fit0_idle, fit0_rel, static_ok & (fit0_idle | fit0_rel)
        ),
    )


def _queue_gate(
    cand: jnp.ndarray,        # [T] bool — bid this round
    order: jnp.ndarray,       # [T] i32 — queue-major rank-minor sort, hoisted
    #                           out of the round loop (the (queue, rank) key
    #                           is static per outer pass)
    task_job: jnp.ndarray,    # [T] i32
    task_queue: jnp.ndarray,  # [T] i32
    resreq: jnp.ndarray,      # [T, R]
    qalloc: jnp.ndarray,      # [Q, R] — queue allocation incl. this cycle
    deserved: jnp.ndarray,    # [Q, R]
    quanta: jnp.ndarray,      # [R]
    job_need: jnp.ndarray,    # [J] i32 — minAvailable − currently-ready
    n_jobs: int,
) -> jnp.ndarray:
    """Proportion admission (the Overused pop-gate, allocate.go:101-104 +
    proportion.go:198-209, at the granularity the sequential loop actually
    enforces it): walk each queue's bidders in rank order; a bidder passes
    while its queue is not yet overused at its prefix position. An unready
    job's first `need` bidders form the gang chunk and pass iff the queue
    wasn't overused when the chunk head arrived — the whole Statement commits
    even if it overshoots deserved, exactly like a popped gang job."""
    T, R = resreq.shape
    # a job's bidders are contiguous inside its queue segment because the
    # hoisted order sorts by (queue, rank) and rank orders by (job, subrank)
    cs = cand[order]
    qs = task_queue[order]
    js = task_job[order]
    rq = jnp.where(cs[:, None], resreq[order], 0.0)
    q_start = jnp.concatenate([jnp.array([True]), qs[1:] != qs[:-1]])
    prefix = _segmented_prefix(rq, q_start)  # [T, R] exclusive, per queue
    # overused over semantic dims only — pods is capacity, not fairness
    sem = fairness.semantic_mask(R)
    pos_overused = jnp.all(
        (deserved[qs] <= qalloc[qs] + prefix + quanta)[..., sem], axis=-1
    )
    # candidate position within the job (segmented candidate count)
    j_start = jnp.concatenate([jnp.array([True]), js[1:] != js[:-1]])
    ci = cs.astype(jnp.float32)[:, None]
    pos_in_job = _segmented_prefix(ci, j_start)[:, 0].astype(jnp.int32)
    in_chunk = cs & (pos_in_job < job_need[js])
    # chunk head verdict, broadcast job-wide
    head_ok = jnp.zeros(n_jobs, bool).at[js].max(cs & (pos_in_job == 0) & ~pos_overused)
    ok = cs & (~pos_overused | (in_chunk & head_ok[js]))
    return jnp.zeros(T, bool).at[order].set(ok)


def _resolve_conflicts(
    cand: jnp.ndarray,      # [T] bool — bidding this round on this budget
    choice: jnp.ndarray,    # [T] i32 — chosen node per task
    rank: jnp.ndarray,      # [T] i32 — task order (lower wins)
    fit_req: jnp.ndarray,   # [T, R] — InitResreq (fit check, allocate.go:161)
    acct_req: jnp.ndarray,  # [T, R] — Resreq (budget consumption,
    #                                  statement.go allocate→node.AddTask)
    budget: jnp.ndarray,    # [N, R]
    quanta: jnp.ndarray,    # [R]
):
    """Admit bidders per node in rank order while the prefix fits the budget.

    Returns (accept [T] bool, delta [N, R] consumed). The prefix test charges
    each bidder its predecessors' Resreq plus its own InitResreq, which is
    exactly the sequential loop's state when it reaches that task.
    """
    T, R = fit_req.shape
    N = budget.shape[0]
    seg = jnp.where(cand, choice, N)  # non-bidders park in segment N
    # rank-major within node
    order = ordering.sort_by_segment_then_rank(seg, rank, N + 1)
    seg_s = seg[order]
    acct_s = jnp.where(cand[order, None], acct_req[order], 0.0)
    fit_s = fit_req[order]
    is_start = jnp.concatenate([jnp.array([True]), seg_s[1:] != seg_s[:-1]])
    within_excl = _segmented_prefix(acct_s, is_start)
    budget_here = budget[jnp.clip(seg_s, 0, N - 1)]
    ok = jnp.all(fit_s + within_excl <= budget_here + quanta, axis=-1)
    accept_s = ok & cand[order] & (seg_s < N)
    accept = jnp.zeros(T, bool).at[order].set(accept_s)
    delta = jax.ops.segment_sum(
        jnp.where(accept_s[:, None], acct_s, 0.0), seg_s, num_segments=N + 1
    )[:N]
    return accept, delta


def round_head_parts(snap: DeviceSnapshot, config: AllocateConfig,
                     tie_hash: jnp.ndarray = None):
    """:func:`local_round_head` plus its intermediates: ``(head,
    static_ok, score)``.  The what-if probe (ops/probe.py) calls this with
    an explicit ``tie_hash`` — the hash at the GLOBAL rows a speculative
    gang would occupy — and reuses static_ok/score for its eviction bids
    and fit-error histogram; sharing ONE head body is what keeps probe
    answers structurally bit-identical to the committed solve."""
    if tie_hash is not None and config.use_pallas:
        # the Pallas kernel computes its own (offset-parameterized) hash
        # from arange rows — an explicit row override cannot route there
        raise ValueError("tie_hash override requires use_pallas=False")
    static_ok = static_predicates(snap)           # [T, N]
    score = score_matrix(snap, config.weights)
    # static predicates folded into the score once — every round reuses it
    score_static = jnp.where(static_ok, score, NEG)
    T, N = score.shape
    if tie_hash is None:
        tie_hash = _tie_break_hash(T, N)

    def head(idle, releasing, pending):
        if config.use_pallas:
            from kube_batch_tpu.ops.pallas_kernels import masked_best_node

            return masked_best_node(
                score, static_ok, snap.task_req, idle, releasing,
                pending, snap.quanta,
                interpret=jax.default_backend() != "tpu",
            )
        fit_idle = fits(snap.task_req, idle, snap.quanta)
        # zero-releasing clusters (every allocate-only cycle) skip
        # the second [T, N] fit entirely: with an all-zero budget the
        # only "fits" are tasks below quanta in every dim — BestEffort
        # tasks, which are never solver-pending (task_pending
        # excludes them), so all-False is exact for solver outputs
        fit_rel = jax.lax.cond(
            jnp.any(releasing > 0.0),
            lambda rel: fits(snap.task_req, rel, snap.quanta),
            lambda rel: jnp.zeros_like(fit_idle),
            releasing,
        )
        # score_static pre-folds the loop-invariant static predicate
        # mask into the score (hoisted out of the rounds)
        masked = jnp.where(
            (fit_idle | fit_rel) & pending[:, None], score_static, NEG
        )
        best, has = _best_node(masked, tie_hash)
        # allocate if the chosen node fits Idle, else pipeline onto
        # Releasing (allocate.go:161-184: the idle-vs-releasing decision
        # happens on the already-selected best-score node)
        chose_idle = jnp.take_along_axis(fit_idle, best[:, None], axis=1)[:, 0]
        return best, has, chose_idle

    return head, static_ok, score


def local_round_head(snap: DeviceSnapshot, config: AllocateConfig):
    """Build the single-program round head: ``head(idle, releasing,
    pending) -> (best, has, chose_idle)`` computed from the full [T, N]
    matrices in one logical program (on the pjit path GSPMD partitions it
    implicitly).  The shard_map path substitutes the explicit-collective
    block head (parallel/shard_solve.py); everything else in the solve is
    the SHARED :func:`allocate_rounds` machinery, so the two paths can only
    diverge in the head — which both compute bit-identically."""
    return round_head_parts(snap, config)[0]


def allocate_rounds(
    snap: DeviceSnapshot,
    config: AllocateConfig,
    head_fn,
    idle0: jnp.ndarray,
    releasing0: jnp.ndarray,
    used0: jnp.ndarray,
    compact_head=None,
) -> AllocateResult:
    """The solve machinery shared by every allocate path: bidding rounds
    with ``head_fn`` supplying (best, has, chose_idle) per round, conflict
    resolution, the proportion gate, and the gang commit/discard outer
    loop.  ``idle0``/``releasing0``/``used0`` are the GLOBAL [N, R] cycle-
    start ledgers (the shard_map body passes the explicitly all-gathered
    replicated copies; per-round cross-shard traffic then lives entirely
    inside ``head_fn``).

    ``compact_head`` (the top-K compaction path) replaces ``head_fn`` with
    a head returning ``(best, has, chose_idle, exhausted_count)`` — the
    candidate-table scan plus its full-matrix exhaustion re-entry (see
    :func:`allocate_topk_solve`); the extra count feeds the
    ``topk_exhausted``/``topk_reentries`` diagnostics."""
    T, R = snap.task_req.shape
    N = idle0.shape[0]
    J = snap.job_min_avail.shape[0]
    Q = snap.queue_weight.shape[0]

    subrank = ordering.task_subranks(snap.task_prio, snap.task_creation)

    # proportion deserved is computed once per cycle from the session-open
    # state (proportion.go:101-154 runs in OnSessionOpen)
    deserved = fairness.proportion_deserved(
        snap.total, snap.queue_weight, snap.queue_request, snap.queue_valid
    )

    eligible = (
        snap.task_pending
        & snap.task_valid
        & snap.job_valid[snap.task_job]
        & snap.job_schedulable[snap.task_job]
    )

    def outer_body(state):
        (idle, releasing, used, assigned, pipelined, job_failed, o,
         rounds_total, exh_total, reent_total, _more) = state

        # ---- fairness state + virtual-time rank, once per outer pass -----
        # (the rank is a static plan for the whole round set: virtual time
        # already charges each bidder its prefix position, so per-round
        # recomputation only corrects second-order drift — not worth the
        # dozen extra 50k-element sorts per round)
        placed0 = assigned >= 0
        placed_req0 = jnp.where(placed0[:, None], snap.task_resreq, 0.0)
        job_new0 = jax.ops.segment_sum(placed_req0, snap.task_job, num_segments=J)
        new_alloc_cnt0 = jax.ops.segment_sum(
            (placed0 & ~pipelined).astype(jnp.int32), snap.task_job, num_segments=J
        )
        job_ready_now = (snap.job_ready + new_alloc_cnt0) >= snap.job_min_avail
        job_need0 = jnp.maximum(
            snap.job_min_avail - (snap.job_ready + new_alloc_cnt0), 0
        )
        pending0 = eligible & ~placed0 & ~job_failed[snap.task_job]
        rank = ordering.virtual_task_ranks(
            pending0,
            snap.task_resreq,
            snap.task_job,
            snap.job_queue[snap.task_job],
            subrank,
            snap.job_prio,
            job_ready_now,
            snap.job_creation,
            snap.job_allocated + job_new0,
            snap.queue_alloc
            + jax.ops.segment_sum(job_new0, snap.job_queue, num_segments=Q),
            deserved,
            snap.total,
            job_need0,
            gang_enabled=config.gang,
            drf_enabled=config.drf,
            proportion_enabled=config.proportion,
        )
        task_queue = snap.job_queue[snap.task_job]
        # queue-major rank-minor sort for the proportion gate — static per
        # outer pass, hoisted out of the rounds (one 50k-sort per round saved)
        qgate_order = ordering.sort_by_segment_then_rank(task_queue, rank, Q)

        def round_cond(state):
            *_, i, progress = state
            return (i < config.rounds) & progress

        def round_body(state):
            (idle, releasing, used, assigned, pipelined, exh_n, reent_n,
             i, _) = state
            placed = assigned >= 0
            placed_req = jnp.where(placed[:, None], snap.task_resreq, 0.0)
            job_new = jax.ops.segment_sum(placed_req, snap.task_job, num_segments=J)
            queue_alloc = snap.queue_alloc + jax.ops.segment_sum(
                job_new, snap.job_queue, num_segments=Q
            )
            pending = eligible & ~placed & ~job_failed[snap.task_job]

            if compact_head is not None:
                best, has, chose_idle, exh_round = compact_head(
                    idle, releasing, pending
                )
                exh_n = exh_n + exh_round
                reent_n = reent_n + (exh_round > 0).astype(jnp.int32)
            else:
                best, has, chose_idle = head_fn(idle, releasing, pending)
            if config.proportion:
                new_alloc_cnt = jax.ops.segment_sum(
                    (placed & ~pipelined).astype(jnp.int32),
                    snap.task_job,
                    num_segments=J,
                )
                job_need = jnp.maximum(
                    snap.job_min_avail - (snap.job_ready + new_alloc_cnt), 0
                )
                has &= _queue_gate(
                    has,
                    qgate_order,
                    snap.task_job,
                    task_queue,
                    snap.task_resreq,
                    queue_alloc,
                    deserved,
                    snap.quanta,
                    job_need,
                    J,
                )
            alloc_cand = has & chose_idle
            pipe_cand = has & ~chose_idle

            acc_a, delta_a = _resolve_conflicts(
                alloc_cand, best, rank, snap.task_req, snap.task_resreq, idle, snap.quanta
            )
            # pipeline-on-releasing bidders exist only when eviction freed
            # capacity this cycle; the steady-state allocate-only round has
            # none — skip the second sort + segmented scan entirely
            acc_p, delta_p = jax.lax.cond(
                jnp.any(pipe_cand),
                lambda: _resolve_conflicts(
                    pipe_cand, best, rank, snap.task_req, snap.task_resreq,
                    releasing, snap.quanta,
                ),
                lambda: (jnp.zeros(T, bool), jnp.zeros_like(releasing)),
            )
            # statement.Allocate → node.AddTask(Allocated): Idle -= r, Used += r
            # statement.Pipeline → node.AddTask(Pipelined): Releasing -= r, Used += r
            idle = idle - delta_a
            releasing = releasing - delta_p
            used = used + delta_a + delta_p
            newly = acc_a | acc_p
            assigned = jnp.where(newly, best, assigned)
            pipelined = pipelined | acc_p
            return (idle, releasing, used, assigned, pipelined, exh_n,
                    reent_n, i + 1, jnp.any(newly))

        (idle, releasing, used, assigned, pipelined, exh_total, reent_total,
         rounds_i, rounds_progress) = (
            jax.lax.while_loop(
                round_cond,
                round_body,
                (idle, releasing, used, assigned, pipelined, exh_total,
                 reent_total, jnp.int32(0), jnp.bool_(True)),
            )
        )
        # inner loop capped while still placing? another outer pass continues
        rounds_capped = rounds_progress & (rounds_i >= config.rounds)
        # ---- gang commit/discard (vectorized Statement) -----------------
        new_alloc_cnt = jax.ops.segment_sum(
            ((assigned >= 0) & ~pipelined).astype(jnp.int32),
            snap.task_job,
            num_segments=J,
        )
        if config.gang:
            job_ok = (snap.job_ready + new_alloc_cnt) >= snap.job_min_avail
        else:
            job_ok = jnp.ones(J, bool)
        # a job whose placements get reverted is done for this cycle — the
        # reference pops each job once and a discarded Statement isn't
        # retried (allocate.go:192-196); without this, a big starved gang
        # would re-grab the freed capacity every iteration and smaller jobs
        # behind it would never see it
        new_any = jax.ops.segment_sum(
            (assigned >= 0).astype(jnp.int32), snap.task_job, num_segments=J
        )
        job_failed = job_failed | (~job_ok & (new_any > 0))
        revert = (assigned >= 0) & ~job_ok[snap.task_job]
        seg = jnp.where(revert, assigned, N)
        rev_req = jnp.where(revert[:, None], snap.task_resreq, 0.0)
        rev_alloc = jax.ops.segment_sum(
            jnp.where(~pipelined[:, None], rev_req, 0.0), seg, num_segments=N + 1
        )[:N]
        rev_pipe = jax.ops.segment_sum(
            jnp.where(pipelined[:, None], rev_req, 0.0), seg, num_segments=N + 1
        )[:N]
        idle = idle + rev_alloc
        releasing = releasing + rev_pipe
        used = used - rev_alloc - rev_pipe
        reverted_any = jnp.any(revert)
        assigned = jnp.where(revert, -1, assigned)
        pipelined = pipelined & ~revert
        # still work to do? when this iteration reverted a gang (freed
        # capacity another job can grab) OR the bidding rounds hit their cap
        # while still placing — AND schedulable pending tasks remain
        more = (reverted_any | rounds_capped) & jnp.any(
            eligible & (assigned < 0) & ~job_failed[snap.task_job]
        )
        return (idle, releasing, used, assigned, pipelined, job_failed, o + 1,
                rounds_total + rounds_i, exh_total, reent_total, more)

    def outer_cond(state):
        *_, o, _rounds, _exh, _reent, more = state
        return (o < config.outer) & more

    init = (
        idle0,
        releasing0,
        used0,
        jnp.full(T, -1, jnp.int32),
        jnp.zeros(T, bool),
        jnp.zeros(J, bool),
        jnp.int32(0),
        jnp.int32(0),
        jnp.int32(0),
        jnp.int32(0),
        jnp.bool_(True),
    )
    # while_loop with early exit — a scan would pay every outer iteration
    # (~12% of solve time each) even after everything is placed
    (idle, releasing, used, assigned, pipelined, _, _, rounds_run,
     exhausted, reentries, _) = (
        jax.lax.while_loop(outer_cond, outer_body, init)
    )

    # after the final outer revert, every surviving placement belongs to a
    # job that passed the commit gate; committed = "has surviving placements"
    new_any_cnt = jax.ops.segment_sum(
        (assigned >= 0).astype(jnp.int32), snap.task_job, num_segments=J
    )
    committed = new_any_cnt > 0
    return AllocateResult(
        assigned=assigned,
        pipelined=pipelined,
        committed=committed,
        node_idle=idle,
        node_releasing=releasing,
        node_used=used,
        deserved=deserved,
        rounds_run=rounds_run,
        topk_exhausted=exhausted,
        topk_reentries=reentries,
    )


@partial(jax.jit, static_argnames=("config",))
def allocate_solve(snap: DeviceSnapshot, config: AllocateConfig) -> AllocateResult:
    """One allocate action pass over the snapshot."""
    return allocate_rounds(
        snap, config, local_round_head(snap, config),
        snap.node_idle, snap.node_releasing, snap.node_used,
    )


# ==========================================================================
# Top-K candidate compaction (KB_TOPK) — the O(T·K) round inner loop
# ==========================================================================
#
# The full-matrix round head re-streams [T, N]-scale fits/argmax every
# bidding round even though (a) only the PENDING rows can bid and (b) node
# budgets only SHRINK between the cycle start and any round (gang reverts
# return exactly what accepted bids consumed, so idle/releasing never
# exceed their cycle-start values).  The compacted path exploits both:
#
#   pending bucket  — the solve's head runs on a [P] bucket of the cycle's
#     pending task rows (P ≪ T in steady state; the row map is an input);
#   candidate table — once per solve, at cycle-start budgets, each bucket
#     row's nodes are ranked by the EXACT round-head key (score_static
#     desc, tie_hash desc, node index asc) and the top-K kept.
#
# Exactness invariant (why first-fit-over-the-table == full argmax): the
# table is the exact lexicographic top-K among cycle-start-FEASIBLE nodes;
# any node outside the table has key ≤ every table entry's key; a round's
# currently-fitting nodes are a subset of cycle-start-feasible (budgets
# only shrink); so whenever ANY table entry fits, the two-key argmax over
# the fitting table entries is the full-matrix argmax.  A row whose table
# entries ALL stop fitting while the table was truncated (> K feasible
# nodes at build) is EXHAUSTED: the same round re-enters the full-matrix
# head for exactly those rows (a lax.cond — steady rounds with no
# exhaustion never pay it), so compacted-vs-full is bit-exact by
# construction, not by tolerance.

#: sort-key of NEG — table entries at or below it are invalid padding
_I32_MIN = jnp.int32(-(2 ** 31))


def f32_sort_key(x: jnp.ndarray) -> jnp.ndarray:
    """Order-preserving map f32 → i32 (finite inputs; the solve's scores
    are finite by construction): integer compare of the keys equals float
    compare of the values, so the candidate build can run entirely in
    exact integer arithmetic.  ``x + 0.0`` canonicalizes -0.0 to +0.0
    first (exact identity for every other value): float compare treats
    the two zeros as EQUAL, and the raw bit patterns would order them —
    a custom extra_rows score emitting -0.0 must not break the
    bit-exactness contract with the float-comparing full-matrix oracle.
    Zero-canonical inputs make the map a bijection (``_inv_sort_key``)."""
    b = jax.lax.bitcast_convert_type(x + jnp.float32(0.0), jnp.int32)
    return jnp.where(b < 0, b ^ jnp.int32(0x7FFFFFFF), b)


def _neg_key() -> jnp.ndarray:
    return f32_sort_key(jnp.float32(NEG))


def lex_topk(skey: jnp.ndarray, hash_: jnp.ndarray, idx0: jnp.ndarray,
             K: int, block: int = 64):
    """Exact per-row lexicographic top-K of (skey desc, hash desc,
    position asc) over [P, M] — ``jnp.argmax``'s first-max-index semantics
    extended to K extractions.  Returns ``(idx, skey, hash)`` [P, K] in
    descending key order (full-tie entries in ascending position order).

    XLA's CPU ``sort``/``top_k`` are comparator-bound (≈50× a reduction
    pass at [2k, 2k]); this is a blocked tournament instead: per-block
    two-key winner triples once, then K extraction steps that re-reduce
    ONLY the winning block under a (val, hash, position) threshold — no
    per-step scatter into the [P, M] operands, which stay read-only.
    ``idx0`` carries the caller's global identity per position (a
    broadcast arange+offset for a build over a node block; the stored
    global indices for a cross-shard merge)."""
    P, M = skey.shape
    C = min(block, M)
    Mp = -(-M // C) * C
    pad = Mp - M
    if pad:
        skey = jnp.pad(skey, ((0, 0), (0, pad)), constant_values=-(2 ** 31))
        hash_ = jnp.pad(hash_, ((0, 0), (0, pad)), constant_values=-1)
        idx0 = jnp.pad(idx0, ((0, 0), (0, pad)), constant_values=-1)
    B = Mp // C
    s3 = skey.reshape(P, B, C)
    h3 = hash_.reshape(P, B, C)
    bval = jnp.max(s3, axis=-1)
    btie = s3 >= bval[..., None]
    bh = jnp.max(jnp.where(btie, h3, -2), axis=-1)
    bcol = jnp.argmax(jnp.where(btie, h3, -2), axis=-1).astype(jnp.int32)
    rows = jnp.arange(P)
    carange = jnp.arange(C, dtype=jnp.int32)[None, :]

    def step(k, state):
        bval, bh, bcol, oi, os, oh = state
        # global two-key argmax over the per-block winners; first block
        # among full ties = lowest position (blocks are position-ordered)
        gv = jnp.max(bval, axis=1)
        tie = bval >= gv[:, None]
        ghv = jnp.max(jnp.where(tie, bh, -2), axis=1)
        gb = jnp.argmax(jnp.where(tie, bh, -2), axis=1).astype(jnp.int32)
        col = jnp.take_along_axis(bcol, gb[:, None], 1)[:, 0]
        flat = gb * C + col
        oi = jax.lax.dynamic_update_slice(
            oi, jnp.take_along_axis(idx0, flat[:, None], 1), (0, k))
        os = jax.lax.dynamic_update_slice(os, gv[:, None], (0, k))
        oh = jax.lax.dynamic_update_slice(oh, ghv[:, None], (0, k))
        # winning block re-reduces under the extracted threshold: keep
        # strictly-lower keys, or equal keys at LATER positions (extraction
        # order is monotone, so the threshold subsumes all prior ones)
        cols_ = (gb * C)[:, None] + carange
        gs = jnp.take_along_axis(skey, cols_, 1)
        gh2 = jnp.take_along_axis(hash_, cols_, 1)
        keep = (gs < gv[:, None]) | ((gs == gv[:, None]) & (
            (gh2 < ghv[:, None])
            | ((gh2 == ghv[:, None]) & (cols_ > flat[:, None]))))
        gs = jnp.where(keep, gs, _I32_MIN)
        nv = jnp.max(gs, axis=1)
        nt = gs >= nv[:, None]
        nh = jnp.max(jnp.where(nt, gh2, -2), axis=1)
        nc = jnp.argmax(jnp.where(nt, gh2, -2), axis=1).astype(jnp.int32)
        bval = bval.at[rows, gb].set(nv)
        bh = bh.at[rows, gb].set(nh)
        bcol = bcol.at[rows, gb].set(nc)
        return bval, bh, bcol, oi, os, oh

    init = (bval, bh, bcol, jnp.zeros((P, K), jnp.int32),
            jnp.full((P, K), _I32_MIN), jnp.full((P, K), -1, jnp.int32))
    *_, oi, os, oh = jax.lax.fori_loop(0, K, step, init)
    return oi, os, oh


def lex_topk3(skey: jnp.ndarray, hash_: jnp.ndarray, idx: jnp.ndarray,
              K: int, block: int = 64):
    """Exact per-row top-K of (skey desc, hash desc, **idx asc**) with the
    index as an EXPLICIT third key — :func:`lex_topk` generalized past its
    positional-tie assumption (it breaks full ties by input POSITION,
    which equals the index order only when the caller's columns are
    index-sorted).  The warm-table merge concatenates a carried table with
    a fresh changed-node block, neither index-contiguous — pre-sorting by
    index would cost a [P, W+C] comparator sort per solve (XLA's CPU sort
    is ~50× a reduction pass — the very cost lex_topk exists to avoid),
    so the tournament carries the index and reduces it with a min.

    Requires per-row-unique indices among valid entries (the merge
    guarantees it: stored nodes are distinct and changed stored entries
    are removed before their fresh versions join).  Returns ``(idx, skey,
    hash)`` [P, K] in descending lex order."""
    P, M = skey.shape
    C = min(block, M)
    Mp = -(-M // C) * C
    pad = Mp - M
    if pad:
        skey = jnp.pad(skey, ((0, 0), (0, pad)), constant_values=-(2 ** 31))
        hash_ = jnp.pad(hash_, ((0, 0), (0, pad)), constant_values=-1)
        idx = jnp.pad(idx, ((0, 0), (0, pad)),
                      constant_values=(1 << 30))
    B = Mp // C
    s3 = skey.reshape(P, B, C)
    h3 = hash_.reshape(P, B, C)
    i3 = idx.reshape(P, B, C)
    BIG = jnp.int32(1 << 30)

    def block_reduce(s, h, i):
        bval = jnp.max(s, axis=-1)
        t1 = s >= bval[..., None]
        bh = jnp.max(jnp.where(t1, h, -2), axis=-1)
        t2 = t1 & (h == bh[..., None])
        bidx = jnp.min(jnp.where(t2, i, BIG), axis=-1)
        return bval, bh, bidx

    bval, bh, bidx = block_reduce(s3, h3, i3)
    barange = jnp.arange(B, dtype=jnp.int32)[None, :]

    def step(k, state):
        bval, bh, bidx, oi, os, oh = state
        gv = jnp.max(bval, axis=1)
        t1 = bval >= gv[:, None]
        ghv = jnp.max(jnp.where(t1, bh, -2), axis=1)
        t2 = t1 & (bh == ghv[:, None])
        gidx = jnp.min(jnp.where(t2, bidx, BIG), axis=1)
        # indices are per-row unique → exactly one block holds the winner
        gb = jnp.argmax(t2 & (bidx == gidx[:, None]), axis=1).astype(
            jnp.int32
        )
        oi = jax.lax.dynamic_update_slice(oi, gidx[:, None], (0, k))
        os = jax.lax.dynamic_update_slice(os, gv[:, None], (0, k))
        oh = jax.lax.dynamic_update_slice(oh, ghv[:, None], (0, k))
        # gather ONLY the winning block, re-reduce it under the extracted
        # threshold (keep entries strictly lex-below (gv, ghv, gidx)), and
        # fold the fresh triple back with a broadcast select over the
        # [P, B] stats — per-step work stays O(P·C), and no .at scatter
        # (XLA CPU scatters serialize per row and dominated the step)
        cols_ = (gb * C)[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]
        gs = jnp.take_along_axis(skey, cols_, 1)
        gh2 = jnp.take_along_axis(hash_, cols_, 1)
        gi2 = jnp.take_along_axis(idx, cols_, 1)
        keep = (gs < gv[:, None]) | ((gs == gv[:, None]) & (
            (gh2 < ghv[:, None])
            | ((gh2 == ghv[:, None]) & (gi2 > gidx[:, None]))))
        nv, nh, ni = block_reduce(
            jnp.where(keep, gs, _I32_MIN)[:, None, :],
            gh2[:, None, :], gi2[:, None, :],
        )
        win = barange == gb[:, None]
        bval = jnp.where(win, nv, bval)
        bh = jnp.where(win, nh, bh)
        bidx = jnp.where(win, ni, bidx)
        return bval, bh, bidx, oi, os, oh

    init = (bval, bh, bidx, jnp.zeros((P, K), jnp.int32),
            jnp.full((P, K), _I32_MIN), jnp.full((P, K), -1, jnp.int32))
    *_, oi, os, oh = jax.lax.fori_loop(0, K, step, init)
    return oi, os, oh


def _remap_rows(sparse_idx: jnp.ndarray, pend_rows: jnp.ndarray) -> jnp.ndarray:
    """Map sparse per-task row indices (affinity/preference corrections)
    into pending-bucket slots; rows outside the bucket park at -1 (their
    corrections can only affect non-pending rows, which the head masks)."""
    eq = sparse_idx[:, None] == pend_rows[None, :]          # [Ks, P]
    hit = jnp.any(eq, axis=1) & (sparse_idx >= 0)
    slot = jnp.argmax(eq, axis=1).astype(jnp.int32)
    return jnp.where(hit, slot, -1)


def pend_view(snap: DeviceSnapshot, pend_rows: jnp.ndarray) -> DeviceSnapshot:
    """``snap`` with the task axis gathered to the [P] pending bucket
    (``pend_rows`` global task rows, -1 padding).  Per-element math over
    the view equals the same rows of the full matrices — the bit-exactness
    contract shared with the shard_map block view.  Padding slots carry
    row 0's data with valid/pending forced off, so every consumer masks
    them out."""
    T = snap.task_req.shape[0]
    safe = jnp.clip(pend_rows, 0, T - 1)
    live = pend_rows >= 0

    def g(arr):
        return arr[safe]

    return snap._replace(
        task_req=g(snap.task_req),
        task_resreq=g(snap.task_resreq),
        task_job=g(snap.task_job),
        task_prio=g(snap.task_prio),
        task_creation=g(snap.task_creation),
        task_status=g(snap.task_status),
        task_valid=g(snap.task_valid) & live,
        task_pending=g(snap.task_pending) & live,
        task_best_effort=g(snap.task_best_effort),
        task_sel_bits=g(snap.task_sel_bits),
        task_sel_impossible=g(snap.task_sel_impossible),
        task_tol_bits=g(snap.task_tol_bits),
        task_node=g(snap.task_node),
        task_critical=g(snap.task_critical),
        task_needs_host=g(snap.task_needs_host),
        task_aff_idx=_remap_rows(snap.task_aff_idx, pend_rows),
        task_pref_idx=_remap_rows(snap.task_pref_idx, pend_rows),
    )


def compact_candidates(view_p: DeviceSnapshot, pend_rows: jnp.ndarray,
                       idle0: jnp.ndarray, releasing0: jnp.ndarray,
                       quanta: jnp.ndarray, config: AllocateConfig, n0=0):
    """The per-solve candidate build over one node block: rank the block's
    nodes per bucket row by the exact (score_static, tie_hash, index) key
    at the CYCLE-START budgets and keep the top ``config.topk``.

    Returns ``(idx, skey, hash, n_feas, score_static, tie_hash)`` — the
    [P, K] table triple in descending key order, the per-row feasible
    count (the truncation test), and the [P, N_blk] score/hash planes
    (the single-device path reuses them for the exhaustion re-entry).
    ``n0`` offsets node indices and the tie hash to GLOBAL coordinates for
    shard-local blocks, exactly like the shard_map round head."""
    K = config.topk
    P = view_p.task_req.shape[0]
    N_blk = idle0.shape[0]
    safe_rows = jnp.maximum(pend_rows, 0)
    tie_hash = tie_break_hash_rows(
        safe_rows, jnp.arange(N_blk, dtype=jnp.int32) + n0
    )
    static_ok = static_predicates(view_p)
    score = score_matrix(view_p, config.weights)
    score_static = jnp.where(static_ok, score, NEG)
    if config.use_pallas:
        from kube_batch_tpu.ops.pallas_kernels import masked_topk_blocks

        skey0, bval, bhash, bcol = masked_topk_blocks(
            score_static, view_p.task_req, idle0, releasing0,
            safe_rows, quanta, n0=n0,
            interpret=jax.default_backend() != "tpu",
        )
        triples = (bval, bhash, bcol)
        del triples  # block partials are a fusion detail; extraction below
        # recomputes them from skey0 (the kernel's win is the fused
        # fit+mask+sort-key emit, not the cheap [P, B] triples)
    else:
        fit0 = fits(view_p.task_req, idle0, quanta)
        fit0_rel = jax.lax.cond(
            jnp.any(releasing0 > 0.0),
            lambda rel: fits(view_p.task_req, rel, quanta),
            lambda rel: jnp.zeros_like(fit0),
            releasing0,
        )
        masked0 = jnp.where(fit0 | fit0_rel, score_static, NEG)
        skey0 = f32_sort_key(masked0)
    neg_key = _neg_key()
    # dtype pinned: the count rides the shard merge's i32 payload and must
    # stay i32 under the jaxpr audit's x64 probe
    n_feas = jnp.sum(skey0 > neg_key, axis=1, dtype=jnp.int32)
    idx0 = jnp.broadcast_to(
        jnp.arange(N_blk, dtype=jnp.int32)[None, :] + n0, (P, N_blk)
    )
    ki, ks, kh = lex_topk(skey0, tie_hash, idx0, K)
    return ki, ks, kh, n_feas, score_static, tie_hash


def make_compact_head(cand_idx, cand_skey, cand_hash, truncated,
                      req_p, quanta, N: int, fallback_fn):
    """Build the compacted round head: ``head(idle, releasing, pending) ->
    (best, has, chose_idle, exhausted_count)``, all [P]-axis — the
    compacted solve runs :func:`allocate_rounds` NATIVELY on the bucket
    view (its task axis is shape-generic; the what-if probe's gang-axis
    solve is the precedent), so the per-round [T]-sized sorts and segment
    scans of the rank/gate/conflict machinery shrink to [P] too.

    Per round the head gathers ONLY the K candidate nodes' live budgets
    ([P, K, R]), two-key-argmaxes the fitting entries' stored keys (exact
    by the module invariant), and re-enters ``fallback_fn(idle, releasing,
    pending_exh) -> (best_p, has_p, chose_p)`` — the full-matrix head over
    the bucket — for exhausted rows only, under a lax.cond that steady
    rounds never execute."""
    valid = cand_skey > _neg_key()
    safe_idx = jnp.clip(cand_idx, 0, N - 1)

    def head(idle, releasing, pending):
        idle_k = idle[safe_idx]                              # [P, K, R]
        fit_idle = jnp.all(req_p[:, None, :] <= idle_k + quanta, axis=-1)
        fit_rel = jax.lax.cond(
            jnp.any(releasing > 0.0),
            lambda rel: jnp.all(
                req_p[:, None, :] <= rel[safe_idx] + quanta, axis=-1
            ),
            lambda rel: jnp.zeros_like(fit_idle),
            releasing,
        )
        fit_k = valid & (fit_idle | fit_rel) & pending[:, None]
        sk = jnp.where(fit_k, cand_skey, _I32_MIN)
        best_sk = jnp.max(sk, axis=1)
        hk = jnp.where(sk >= best_sk[:, None], cand_hash, -1)
        # first position among (key, hash) ties = lowest node index — the
        # table stores full ties in ascending index order
        pos = jnp.argmax(hk, axis=1)
        has_p = jnp.any(fit_k, axis=1)
        best_p = jnp.take_along_axis(cand_idx, pos[:, None], 1)[:, 0]
        chose_p = jnp.take_along_axis(fit_idle, pos[:, None], 1)[:, 0]
        exh_p = pending & ~has_p & truncated

        def with_fallback(_):
            fb_best, fb_has, fb_chose = fallback_fn(idle, releasing, exh_p)
            return (
                jnp.where(exh_p, fb_best, best_p),
                jnp.where(exh_p, fb_has, has_p),
                jnp.where(exh_p, fb_chose, chose_p),
            )

        best_p2, has_p2, chose_p2 = jax.lax.cond(
            jnp.any(exh_p), with_fallback,
            lambda _: (best_p, has_p, chose_p), None,
        )
        # dtype pinned: the count rides a while-loop carry, which must stay
        # i32 under the jaxpr audit's x64 probe
        return best_p2, has_p2, chose_p2, jnp.sum(exh_p, dtype=jnp.int32)

    return head


def scatter_bucket_result(res: AllocateResult, pend_rows: jnp.ndarray,
                          T: int) -> AllocateResult:
    """Re-express a bucket-axis solve result on the full [T] task axis:
    assigned/pipelined scatter at the bucket's global rows (padding slots
    land in the dropped T slot of a [T+1] buffer — the segment-sum idiom;
    negative indices must never reach a scatter).  Every other field is
    already global ([N, R] ledgers, [J]/[Q] aggregates, scalars).

    Exactness of the bucket-axis solve itself: every schedulable-pending
    row is IN the bucket (the dispatch guarantees it), non-bucket rows can
    never bid or place, their zero contributions drop out of every f32
    prefix/segment sum exactly (x + 0.0 == x), and the bucket preserves
    ascending global row order (np.flatnonzero), so every stable-sort tie
    in the rank machinery resolves identically to the full program."""
    scat = jnp.where(pend_rows >= 0, pend_rows, T)
    assigned = jnp.full(T + 1, -1, jnp.int32).at[scat].set(res.assigned)[:T]
    pipelined = jnp.zeros(T + 1, bool).at[scat].set(res.pipelined)[:T]
    return res._replace(assigned=assigned, pipelined=pipelined)


def make_bucket_fallback(view_p: DeviceSnapshot, score_static_p, tie_hash_p,
                         quanta):
    """The exhaustion re-entry for a bucket whose full score/hash planes
    are at hand: the full-matrix head restricted to the [P] bucket —
    literally :func:`round_head_parts`' masked two-key argmax over the
    [P, N] planes, masked to the exhausted rows."""
    req_p = view_p.task_req

    def fallback(idle, releasing, pending_exh):
        fit_idle = fits(req_p, idle, quanta)
        fit_rel = jax.lax.cond(
            jnp.any(releasing > 0.0),
            lambda rel: fits(req_p, rel, quanta),
            lambda rel: jnp.zeros_like(fit_idle),
            releasing,
        )
        masked = jnp.where(
            (fit_idle | fit_rel) & pending_exh[:, None], score_static_p, NEG
        )
        best_p, has_p = _best_node(masked, tie_hash_p)
        chose_p = jnp.take_along_axis(fit_idle, best_p[:, None], 1)[:, 0]
        return best_p, has_p, chose_p

    return fallback


@partial(jax.jit, static_argnames=("config",))
def allocate_topk_solve(snap: DeviceSnapshot, pend_rows: jnp.ndarray,
                        config: AllocateConfig) -> AllocateResult:
    """The compacted allocate solve: identical outputs to
    :func:`allocate_solve` (the KB_TOPK=0 oracle), computed on the [P]
    pending bucket × [P, K] candidate table instead of the [T, N]
    matrices.  ``pend_rows`` [P] i32 must cover every schedulable-pending
    task row (-1 padding); ``config.topk`` = K > 0.  The dispatch
    (actions/allocate.py) owns bucket/K selection and the full-path
    fallbacks for shapes where compaction cannot win."""
    T = snap.task_req.shape[0]
    N = snap.node_idle.shape[0]
    K = config.topk
    view_p = pend_view(snap, pend_rows)
    ki, ks, kh, n_feas, score_static_p, tie_hash_p = compact_candidates(
        view_p, pend_rows, snap.node_idle, snap.node_releasing,
        snap.quanta, config,
    )
    truncated = n_feas > K
    fallback = make_bucket_fallback(
        view_p, score_static_p, tie_hash_p, snap.quanta
    )
    head = make_compact_head(
        ki, ks, kh, truncated, view_p.task_req, snap.quanta, N, fallback,
    )
    # the rounds run NATIVELY on the bucket view — the rank / queue-gate /
    # conflict machinery's per-round sorts and segment scans all shrink
    # from [T] to [P] (see scatter_bucket_result for the exactness story)
    res = allocate_rounds(
        view_p, config, None, snap.node_idle, snap.node_releasing,
        snap.node_used, compact_head=head,
    )
    return scatter_bucket_result(res, pend_rows, T)


# ==========================================================================
# Warm-started incremental allocate (KB_WARM) — the cross-cycle candidate
# table carry + assignment repair
# ==========================================================================
#
# KB_TOPK made the ROUNDS O(P·K), but the candidate-table BUILD still
# re-ranks every bucket row against every node once per solve — the last
# O(P·N) cost in the cycle's dominant phase.  The warm path promotes the
# table to a PERSISTENT cross-cycle structure: the dispatch carries the
# [P, W] table on device between solves and each cycle only
#
#   re-ranks the INVALIDATED rows  (new/bucket-shifted rows, rows whose
#     own task features moved, eroded rows — a sub-bucket
#     compact_candidates at a fixed rung, not [P, N]);
#   merges the CHANGED NODES' fresh keys ([P, C] — C = the node rows the
#     resident scatter deltas moved since the last solve) into every
#     carried row.
#
# Exactness (why the carried table keeps the compact-head invariant —
# "exact descending lex prefix of the currently-cycle-start-feasible
# nodes"):
#
#   INV: every node ABSENT from a row's valid entries either changed since
#   the last refresh (so its fresh key is in this merge), or its key —
#   unchanged, because ALL of its key inputs are unchanged — is lex-BELOW
#   the row's last valid entry θ.
#
#   The merge removes the changed nodes' stale entries, inserts their
#   fresh keys, re-extracts the top W, and CUTS every merged entry that
#   falls lex-below θ: above θ the merged set provably contains every
#   node (unchanged ones were already stored; changed ones are fresh), so
#   the kept prefix is the exact current top-J — and the cut re-
#   establishes INV for the next cycle (cut entries are ≥ the extraction's
#   dropped ones, so everything absent is below the new θ).  A cut or an
#   extraction overflow marks the row TRUNCATED; a truncated row whose
#   valid entries all die in-round re-enters the full-matrix head the
#   SAME round (the KB_TOPK fallback, with the [P, N] planes computed
#   lazily inside the cond), so bit-exactness never depends on the table
#   being deep — only on it being an exact prefix.  Rows whose prefix
#   erodes below the nominal K report in the `eroded` output and the host
#   planner re-ranks them next cycle.
#
#   Cross-cycle soundness rides on the same two facts as KB_TOPK: budgets
#   only SHRINK within a solve (the table stays an upper bound all
#   rounds), and between solves state moves only at rows the resident
#   scatters (api/resident.py) know about — which is exactly where the
#   invalidation comes from.  KB_WARM=0 keeps the per-solve cold build as
#   the bit-exactness oracle, same contract as KB_TOPK=0 / KB_SHARD_MAP=0.


def node_view(snap: DeviceSnapshot, node_rows: jnp.ndarray) -> DeviceSnapshot:
    """``snap`` with the node axis gathered to ``node_rows`` (-1 padding →
    dead columns: node_valid forced off so static predicates fail).  The
    per-element contract of the shard_map block view, applied to an
    arbitrary node subset: every live column of the view equals the same
    column of the full matrices, which is what makes the warm merge's
    fresh [P, C] keys bit-equal to a full rebuild's."""
    N = snap.node_idle.shape[0]
    safe = jnp.clip(node_rows, 0, N - 1)
    live = node_rows >= 0

    def g(arr):
        return arr[safe]

    def g1(arr):  # [K?, N] sparse rows — node axis is axis 1
        return arr[:, safe]

    return snap._replace(
        node_idle=g(snap.node_idle),
        node_releasing=g(snap.node_releasing),
        node_used=g(snap.node_used),
        node_alloc=g(snap.node_alloc),
        node_valid=g(snap.node_valid) & live,
        node_sched=g(snap.node_sched),
        node_label_bits=g(snap.node_label_bits),
        node_taint_bits=g(snap.node_taint_bits),
        task_aff_mask=g1(snap.task_aff_mask),
        task_pref_node=g1(snap.task_pref_node),
        task_pref_pod=g1(snap.task_pref_pod),
    )


def fresh_block_skey(view_pc: DeviceSnapshot, quanta: jnp.ndarray,
                     config: AllocateConfig) -> jnp.ndarray:
    """[P, C] sort keys of the changed-node columns at the CURRENT
    cycle-start budgets — exactly ``compact_candidates``' key derivation
    restricted to a node subset (``view_pc`` = the pend view node-gathered
    at the changed rows).  The zero-releasing skip mirrors the shard_map
    block head's per-block test: exact for solver-pending rows either
    way (see local_round_head)."""
    static_ok = static_predicates(view_pc)
    score = score_matrix(view_pc, config.weights)
    score_static = jnp.where(static_ok, score, NEG)
    fit0 = fits(view_pc.task_req, view_pc.node_idle, quanta)
    fit0_rel = jax.lax.cond(
        jnp.any(view_pc.node_releasing > 0.0),
        lambda rel: fits(view_pc.task_req, rel, quanta),
        lambda rel: jnp.zeros_like(fit0),
        view_pc.node_releasing,
    )
    return f32_sort_key(jnp.where(fit0 | fit0_rel, score_static, NEG))


#: fresh candidates inserted per row per merge — rows where more changed
#: nodes belong in the top-W are φ-cut: still EXACT (the cut re-founds
#: the prefix invariant and marks the row truncated), just thinner, and
#: the spare-fill refresh budget re-ranks them on rung padding slots.
#: E prices the merge's tournament (its extraction steps are the merge's
#: dominant cost at CPU dispatch granularity), so it is sized to the
#: steady-state insertion rate (~W·C/N), not the burst worst case
FRESH_E = 8


def _lex_ge(s, h, i, ts, th, ti):
    """Entry (s, h, i) lex-at-or-above threshold (ts, th, ti) under the
    table order (skey desc, hash desc, idx asc)."""
    return (s > ts) | ((s == ts) & ((h > th) | ((h == th) & (i <= ti))))


def warm_refresh_table(t_idx, t_skey, t_hash, t_trunc, row_map, rows_m,
                       changed_nodes, skey_c, hash_c,
                       ri, rs, rh, trunc_i, rerank_slots,
                       N: int, k_min: int):
    """One cycle's table maintenance, in exact integer arithmetic over the
    [M] live prefix (M = ``row_map``'s length — the merge rung; rows past
    M are bucket padding and stay empty by induction): permute the carried
    table into the new bucket order (``row_map`` — old slot per new slot,
    -1 = fresh row), remove the changed nodes' stale entries, INSERT their
    fresh keys, θ/φ-cut, and overwrite the re-ranked sub-bucket's rows
    with their fresh [Pi, W] builds at ``rerank_slots``.

    The insert is a COUNTING merge, not a re-extraction: only the top
    FRESH_E fresh candidates per row are ranked (a short tournament over
    [M, C]), each surviving entry's merged position is a comparison count
    (kept-stored are already sorted; [M, W, E] lex compares rank both
    sides), and two rank-scatters place everything — per-solve cost is
    O(E) extraction steps instead of O(W), which is what lets a warm
    cycle undercut the cold build's K-step extraction at all.  Exactness:
    fresh candidates beyond the top E are all lex-below the E-th extracted
    key φ (a strict bound — indices are unique), so cutting the merged
    table at lexmax(θ, φ) keeps it an exact prefix; cut rows mark
    truncated and the erosion flag re-ranks them next cycle.

    Returns ``(idx, skey, hash, trunc, eroded)`` — the refreshed FULL
    [P, W] table (rows past M carried through untouched) plus the [P]
    erosion flag (truncated AND fewer than ``k_min`` valid entries)."""
    P, W = t_skey.shape
    M = row_map.shape[0]
    E = FRESH_E
    neg = _neg_key()
    BIG = jnp.int32(1 << 30)
    # ---- 1. permute the live prefix into the new bucket order --------
    live = row_map >= 0
    safe = jnp.clip(row_map, 0, M - 1)
    idx = jnp.where(live[:, None], t_idx[:M][safe], 0)
    skey = jnp.where(live[:, None], t_skey[:M][safe], _I32_MIN)
    hsh = jnp.where(live[:, None], t_hash[:M][safe], -1)
    # a fresh (carried-in) row starts TRUNCATED: its empty table claims
    # nothing, so until the re-rank overwrite below fills it, the head
    # must treat it as incomplete (exhaustion-fallback territory) — the
    # planner always re-ranks fresh rows, but correctness must not
    # depend on that scheduling
    trunc = jnp.where(live, t_trunc[:M][safe], True)
    # ---- 2. θ per row: the last valid entry, PRE-removal -------------
    valid = skey > neg
    vcnt = jnp.sum(valid, axis=1, dtype=jnp.int32)
    last = jnp.clip(vcnt - 1, 0, W - 1)[:, None]
    has_any = vcnt > 0
    th_s = jnp.where(has_any, jnp.take_along_axis(skey, last, 1)[:, 0], neg)
    th_h = jnp.where(
        has_any, jnp.take_along_axis(hsh, last, 1)[:, 0],
        jnp.int32(2 ** 31 - 1),
    )
    th_i = jnp.where(
        has_any, jnp.take_along_axis(idx, last, 1)[:, 0], jnp.int32(-1)
    )
    # ---- 3. remove the changed nodes' stale entries ------------------
    changed_mask = jnp.zeros(N + 1, bool).at[
        jnp.where(changed_nodes >= 0, changed_nodes, N)
    ].set(True, mode="drop")[:N]
    keep = valid & ~changed_mask[jnp.clip(idx, 0, N - 1)]
    skey = jnp.where(keep, skey, _I32_MIN)
    # ---- 4. top-E of the fresh block (short tournament) --------------
    C = changed_nodes.shape[0]
    idx_c = jnp.broadcast_to(changed_nodes[None, :], (M, C))
    fresh_ok = (changed_nodes >= 0)[None, :] & (skey_c > neg)
    fi, fs, fh = lex_topk3(
        jnp.where(fresh_ok, skey_c, _I32_MIN), hash_c, idx_c, E
    )
    f_valid = fs > neg
    # φ: the E-th extracted fresh key — every non-extracted fresh
    # candidate is strictly lex-below it (indices unique)
    phi_live = f_valid[:, E - 1]
    ph_s, ph_h, ph_i = fs[:, E - 1], fh[:, E - 1], fi[:, E - 1]
    # ---- 5. gather-based two-sorted-list merge -----------------------
    # kept-stored entries keep their relative (sorted) order and the
    # fresh top-E is sorted by extraction; merged output j = lexmax of
    # the two heads after consuming j entries.  Everything is gathers +
    # small broadcast counts — XLA CPU scatters serialize per row and
    # dominated the first (rank-scatter) formulation of this merge.
    kp = jnp.cumsum(keep.astype(jnp.int32), axis=1) - keep
    kept_cnt = jnp.sum(keep, axis=1, dtype=jnp.int32)
    jcols = jnp.arange(W, dtype=jnp.int32)[None, :]
    # position (in stored-entry coordinates) of the j-th KEPT entry — one
    # [M, W+1] inverse scatter instead of a [M, W, W] compare+argmax
    kth_kept = jnp.zeros((M, W + 1), jnp.int32).at[
        jnp.arange(M)[:, None], jnp.where(keep, kp, W)
    ].set(
        jnp.broadcast_to(jnp.arange(W, dtype=jnp.int32)[None, :], (M, W)),
        mode="drop",
    )[:, :W]                                             # [M, W]
    # fresh rank of each top-E entry among the merged output: its own
    # position + kept-stored entries lex-above it
    gt = _lex_ge(          # stored strictly above fresh (no equal keys)
        skey[:, :, None], hsh[:, :, None], idx[:, :, None],
        fs[:, None, :], fh[:, None, :], fi[:, None, :],
    )
    fresh_rank = jnp.arange(E, dtype=jnp.int32)[None, :] + jnp.sum(
        gt & keep[:, :, None], axis=1, dtype=jnp.int32
    )
    # fresh entries consumed before output j → the two head cursors
    b = jnp.sum(
        f_valid[:, None, :] & (fresh_rank[:, None, :] < jcols[:, :, None]),
        axis=2, dtype=jnp.int32,
    )                                                    # [M, W]
    a = jcols - b

    def g(arr, cur, ok, fill):
        v = jnp.take_along_axis(arr, jnp.clip(cur, 0, arr.shape[1] - 1), 1)
        return jnp.where(ok, v, fill)

    s_ok = a < kept_cnt[:, None]
    sp = g(kth_kept, a, s_ok, 0)
    hs_s = g(skey, sp, s_ok, _I32_MIN)
    hs_h = g(hsh, sp, s_ok, jnp.int32(-1))
    hs_i = g(idx, sp, s_ok, BIG)
    f_ok = (b < E) & jnp.take_along_axis(
        f_valid, jnp.clip(b, 0, E - 1), 1)
    hf_s = g(fs, b, f_ok, _I32_MIN)
    hf_h = g(fh, b, f_ok, jnp.int32(-1))
    hf_i = g(fi, b, f_ok, BIG)
    take_f = f_ok & ~(s_ok & _lex_ge(hs_s, hs_h, hs_i, hf_s, hf_h, hf_i))
    ns = jnp.where(take_f, hf_s, hs_s)
    nh = jnp.where(take_f, hf_h, hs_h)
    ni = jnp.where(take_f, hf_i, hs_i)
    overflow = (
        kept_cnt + jnp.sum(f_valid, axis=1, dtype=jnp.int32)
    ) > W
    # ---- 6. cut at lexmax(θ, φ): above both, the merged set provably
    # contains every node, so the kept prefix is exact -----------------
    ge = _lex_ge(ns, nh, ni, th_s[:, None], th_h[:, None], th_i[:, None])
    ge &= ~phi_live[:, None] | _lex_ge(
        ns, nh, ni, ph_s[:, None], ph_h[:, None], ph_i[:, None]
    )
    cut_any = jnp.any((ns > neg) & ~ge, axis=1)
    ns = jnp.where((ns > neg) & ge, ns, _I32_MIN)
    # a LIVE φ means non-extracted fresh candidates may exist below it —
    # the table can no longer claim completeness even when nothing was
    # cut (an empty-but-complete row gaining > E feasible changed nodes
    # keeps every merged entry above both thresholds, yet the 9th+ fresh
    # candidates are absent: without trunc the exhaustion fallback would
    # never re-enter for them)
    trunc = trunc | cut_any | overflow | phi_live
    # ---- 7. overwrite the re-ranked sub-bucket's rows ----------------
    scat = jnp.where(rerank_slots >= 0, rerank_slots, M)

    def over(dst, upd):
        pad = jnp.zeros((1,) + dst.shape[1:], dst.dtype)
        return jnp.concatenate([dst, pad], 0).at[scat].set(
            upd, mode="drop"
        )[:M]

    ni = over(ni, ri)
    ns = over(ns, rs)
    nh = over(nh, rh)
    trunc = over(trunc, trunc_i)
    # ---- 8. erosion flag + full-table assembly -----------------------
    # STAGGERED thresholds: θ-cuts thin every carried row at roughly the
    # same per-cycle rate, so a single shared floor would mature whole
    # re-rank cohorts at once — a periodic rung-spiking wave (measured:
    # a quiet er≈100 steady state punctuated by er≈1100 spikes).  Each
    # row instead refreshes at its own hashed depth in [k_min, W), which
    # spreads the cohort across the thinning trajectory; the flag is a
    # scheduling signal only (a fully eroded table still answers exactly
    # via the exhaustion fallback), so the stagger cannot affect results.
    vcnt2 = jnp.sum(ns > neg, axis=1, dtype=jnp.int32)
    spread = jnp.int32(max(W - k_min, 1))
    jitter = jax.lax.shift_right_logical(
        jnp.maximum(rows_m, 0) * jnp.int32(_H1), 16
    ) % spread
    eroded = trunc & (vcnt2 < k_min + jitter)
    upd = jax.lax.dynamic_update_slice
    return (
        upd(t_idx, ni, (0, 0)),
        upd(t_skey, ns, (0, 0)),
        upd(t_hash, nh, (0, 0)),
        upd(t_trunc, trunc, (0,)),
        upd(jnp.zeros(P, bool), eroded, (0,)),
    )


def make_lazy_bucket_fallback(view_p: DeviceSnapshot, pend_rows, quanta,
                              config: AllocateConfig):
    """The warm path's exhaustion re-entry: the full-matrix head over the
    bucket with the [P, N] score/hash planes computed INSIDE the cond —
    the whole point of the carry is that steady cycles never build those
    planes, so the fallback must not hoist them (the sharded compacted
    body's fallback is the precedent)."""
    safe_rows = jnp.maximum(pend_rows, 0)
    N = view_p.node_idle.shape[0]

    def fallback(idle, releasing, pending_exh):
        static_ok = static_predicates(view_p)
        score = score_matrix(view_p, config.weights)
        ss = jnp.where(static_ok, score, NEG)
        tie = tie_break_hash_rows(
            safe_rows, jnp.arange(N, dtype=jnp.int32)
        )
        return make_bucket_fallback(view_p, ss, tie, quanta)(
            idle, releasing, pending_exh
        )

    return fallback


def _warm_allocate_solve(snap: DeviceSnapshot, pend_rows,
                         t_idx, t_skey, t_hash, t_trunc,
                         row_map, changed_nodes, rerank_rows, rerank_slots,
                         config: AllocateConfig, k_min: int):
    """The warm-started compacted allocate solve: identical outputs to
    :func:`allocate_topk_solve` (and therefore to the KB_TOPK=0 full
    program) computed against the CARRIED candidate table, refreshed
    in-program by :func:`warm_refresh_table`.  ``config.topk`` is the
    STORED width W (the dispatch carries W = K + WARM_WIDTH_MARGIN so
    θ/φ-cut erosion rarely reaches the refresh floor); ``k_min`` is that
    floor (the dispatch passes K/4 — a thin table still answers exactly,
    so the floor trades re-rank traffic against fallback probability).

    Returns ``(AllocateResult, (idx, skey, hash, trunc), eroded)`` — the
    refreshed table stays on device for the next cycle's carry (the jit
    wrapper donates the stale table buffers off-CPU)."""
    T = snap.task_req.shape[0]
    N = snap.node_idle.shape[0]
    M = row_map.shape[0]
    view_p = pend_view(snap, pend_rows)
    # fresh keys for the changed-node columns over the [M] live prefix
    # (row_map's length IS the merge rung — the planner sizes it over the
    # live bucket rows so padding rows pay nothing), at cycle-start state
    rows_m = pend_rows[:M]
    view_pm = pend_view(snap, rows_m)
    view_pc = node_view(view_pm, changed_nodes)
    skey_c = fresh_block_skey(view_pc, snap.quanta, config)
    hash_c = tie_break_hash_rows(
        jnp.maximum(rows_m, 0), jnp.maximum(changed_nodes, 0)
    )
    # full re-rank of the invalidated sub-bucket (compact_candidates at
    # the rerank rung — the only [·, N] work of a steady warm cycle)
    view_i = pend_view(snap, rerank_rows)
    ri, rs, rh, n_feas, _ss, _tie = compact_candidates(
        view_i, rerank_rows, snap.node_idle, snap.node_releasing,
        snap.quanta, config,
    )
    ni, ns, nh, trunc, eroded = warm_refresh_table(
        t_idx, t_skey, t_hash, t_trunc, row_map, rows_m, changed_nodes,
        skey_c, hash_c, ri, rs, rh, n_feas > config.topk, rerank_slots,
        N, k_min,
    )
    fallback = make_lazy_bucket_fallback(view_p, pend_rows, snap.quanta,
                                         config)
    head = make_compact_head(
        ni, ns, nh, trunc, view_p.task_req, snap.quanta, N, fallback,
    )
    res = allocate_rounds(
        view_p, config, None, snap.node_idle, snap.node_releasing,
        snap.node_used, compact_head=head,
    )
    return scatter_bucket_result(res, pend_rows, T), (ni, ns, nh, trunc), eroded


#: argument positions of the carried table buffers — donated off-CPU so
#: the refresh writes in place (the resident scatter's donation contract)
WARM_TABLE_ARGNUMS = (2, 3, 4, 5)

_WARM_SOLVE = None


def warm_solve_fn():
    """The shared jitted warm solve — module-level memo (the _scatter_fn
    idiom): donation is backend-dependent, so the wrapper is built on
    first use, and every cache instance reuses one compiled
    specialization set per (shape, config) key."""
    global _WARM_SOLVE
    if _WARM_SOLVE is None:
        donate = (
            () if jax.default_backend() == "cpu" else WARM_TABLE_ARGNUMS
        )
        _WARM_SOLVE = jitstats.register(
            "warm_allocate_solve",
            jax.jit(_warm_allocate_solve,
                    static_argnames=("config", "k_min"),
                    donate_argnums=donate),
        )
    return _WARM_SOLVE


def warm_allocate_solve(snap, pend_rows, table, plan, config, k_min):
    """Dispatch-facing warm solve: ``table`` = the carried (idx, skey,
    hash, trunc) device arrays, ``plan`` = the host planner's (row_map,
    changed_nodes, rerank_rows, rerank_slots) int32 arrays."""
    t_idx, t_skey, t_hash, t_trunc = table
    row_map, changed, rr, rslots = plan
    return warm_solve_fn()(
        snap, pend_rows, t_idx, t_skey, t_hash, t_trunc,
        row_map, changed, rr, rslots, config=config, k_min=k_min,
    )


@jax.jit
def failure_histogram_bucket_solve(snap: DeviceSnapshot,
                                   pend_rows) -> jnp.ndarray:
    """:func:`failure_histogram_solve` computed on the [P] pending bucket
    instead of re-walking [T, N]: every consumer reads histogram rows only
    for unplaced PENDING tasks, all of which the dispatch's bucket covers,
    and each task's row is a node-axis reduction independent of the other
    task rows — so the bucket rows are bit-equal to the full program's and
    the non-bucket rows (never read) scatter back as zeros."""
    from kube_batch_tpu.ops.feasibility import (
        FeasibilityMasks,
        N_REASONS,
        failure_histogram,
    )

    T = snap.task_req.shape[0]
    view_p = pend_view(snap, pend_rows)
    static_ok = static_predicates(view_p)
    fit0_idle = fits(view_p.task_req, snap.node_idle, snap.quanta)
    fit0_rel = fits(view_p.task_req, snap.node_releasing, snap.quanta)
    h = failure_histogram(
        view_p,
        FeasibilityMasks(
            static_ok, fit0_idle, fit0_rel,
            static_ok & (fit0_idle | fit0_rel),
        ),
    )
    scat = jnp.where(pend_rows >= 0, pend_rows, T)
    return jnp.zeros((T + 1, N_REASONS), jnp.int32).at[scat].set(h)[:T]


# retrace accounting (utils/jitstats): the bench asserts these stay flat
# across steady-state cycles — shape-bucketed snapshots must hit the jit
# cache every cycle after warmup
jitstats.register("allocate_solve", allocate_solve)
jitstats.register("allocate_topk_solve", allocate_topk_solve)
jitstats.register("failure_histogram_solve", failure_histogram_solve)
jitstats.register("failure_histogram_bucket_solve",
                  failure_histogram_bucket_solve)
