"""Cycle invariant sentinel — the device-side result-integrity check fused
into every dispatching solve program (guard plane tier 1).

Five stacked fast paths (delta snapshot open, resident device columns,
shard_map collectives, KB_TOPK compaction, pipelined writeback) each ship
with a bit-exact oracle knob, yet nothing in production ever exercised
those oracles: a silent divergence — an XLA/driver regression, an HBM
bit-flip in a resident column, a future PR's bug in the delta scatters —
would dispatch wrong binds and evictions to a real cluster with zero
detection.  This module closes the gap at the solve layer: each committed
solve program gains a FUSED tail that re-derives the lawfulness of its own
result from the same snapshot it consumed —

- per-node committed allocation fits the cycle-start budget AND the node's
  capacity (the capacity cross-check is what catches a corrupted resident
  idle column: the solve's own fit math trusts the corrupt budget, but
  idle+used ≤ allocatable is redundant state the corruption breaks);
- no task is assigned that was not an eligible pending row (a task already
  RUNNING being re-assigned = "assigned twice");
- every committed assignment was cycle-start feasible (static predicates
  re-checked row-wise at the assigned node — O(T·W), not [T, N]);
- committed gangs meet min_available (the vectorized JobReady gate,
  re-derived);
- victims are valid RUNNING residents, stay within gang slack, and cover
  their claimant (eviction solves);
- an all-finite sweep over the result ledgers and every f32 snapshot
  input (ledgers, budgets, fairness state).

The check returns ONE verdict word (i32, 0 = lawful) plus a violation
histogram ([N_INVARIANTS] i32) that ride the action's existing single
annotated ``device_get`` — the AllocateResult-counters idiom — so the
steady-state cost is a handful of O(T)/O(N) reductions fused into a
program already streaming [T, N] intermediates (bench ``guard_overhead``
holds the delta under 5% of steady-cycle p50).  On a nonzero verdict the
action discards the result and FAILS CLOSED: no binds or evictions are
dispatched from a condemned solve (kube_batch_tpu/guard owns the demotion
/ audit / bundle response).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from kube_batch_tpu.api.snapshot import DeviceSnapshot
from kube_batch_tpu.ops.assignment import (
    AllocateConfig,
    AllocateResult,
    allocate_solve,
    allocate_topk_solve,
)
from kube_batch_tpu.ops.eviction import (
    EvictConfig,
    EvictResult,
    evict_solve,
    gang_slack0,
    victim_running,
)
from kube_batch_tpu.utils import jitstats

#: violation classes — one histogram slot each, shared by every solve's
#: sentinel so the guard plane and the diagnostics bundle speak one schema
INVARIANT_NAMES = (
    "assign_ineligible",   # placement/claim on a non-eligible-pending row
    "assign_infeasible",   # static predicates fail at the assigned node,
    #                        or an index out of range
    "node_overcommit",     # committed allocation exceeds budget/capacity,
    #                        or the cycle-start ledgers are inconsistent
    "gang_violation",      # committed gang below min_available / slack
    "victim_ineligible",   # evicted row is not a valid RUNNING resident
    "claim_uncovered",     # a claim's victims do not cover the claimant
    "nonfinite",           # non-finite ledger/score/budget value
    "admit_ineligible",    # enqueue-gate admission of a non-candidate
)
N_INVARIANTS = len(INVARIANT_NAMES)

_I = {name: i for i, name in enumerate(INVARIANT_NAMES)}


def _i32sum(x) -> jnp.ndarray:
    # dtype pinned: the counts ride the action readback and must stay i32
    # under the jaxpr audit's x64 probe
    return jnp.sum(x, dtype=jnp.int32)


def _nonfinite_count(*arrays) -> jnp.ndarray:
    total = jnp.int32(0)
    for a in arrays:
        # kbt: allow[KBT005] trace-time unroll over a fixed small tuple of
        # snapshot fields inside the fused sentinel program — reductions
        # fuse into one graph, zero per-iteration host dispatch
        total = total + _i32sum(~jnp.isfinite(a))
    return total


def _snapshot_nonfinite(snap: DeviceSnapshot) -> jnp.ndarray:
    """All-finite sweep over every f32 input the solves consume: ledgers,
    requests, fairness state, budgets, quanta."""
    return _nonfinite_count(
        snap.task_req, snap.task_resreq,
        snap.node_idle, snap.node_releasing, snap.node_used, snap.node_alloc,
        snap.job_allocated,
        snap.queue_weight, snap.queue_capability, snap.queue_alloc,
        snap.queue_request,
        snap.total, snap.quanta,
    )


def _eligible_pending(snap: DeviceSnapshot) -> jnp.ndarray:
    """[T] bool — exactly the solves' claimant/bidder eligibility."""
    tj = snap.task_job
    return (
        snap.task_pending
        & snap.task_valid
        & snap.job_valid[tj]
        & snap.job_schedulable[tj]
    )


#: second multiplier of the victim-checksum mix (wrapped i32 two's
#: complement — the tie-hash constants' idiom)
_CK = 0x9E3779B1 - (1 << 32)


def eligibility_checksum(snap: DeviceSnapshot) -> jnp.ndarray:
    """i32 checksum of the device's bidder-eligibility + victim-pool
    vectors — the sentinel's device-vs-host divergence probe.  A flipped
    resident status/pending/node word changes WHICH rows are eligible,
    which the purely device-side invariants cannot see (they re-derive
    from the same corrupted columns); the host recomputes this checksum
    from its own columns (:func:`host_eligibility_checksum` — the same
    formula over the same-shaped arrays) and a mismatch condemns the
    solve even when the phantom row never wins a bid (the proportion gate
    often blocks it — defense that HIDES the corruption)."""
    T = snap.task_req.shape[0]
    idx = jnp.arange(T, dtype=jnp.int32) + 1
    elig = jnp.sum(
        jnp.where(_eligible_pending(snap), idx, 0), dtype=jnp.int32
    )
    run = jnp.sum(
        jnp.where(victim_running(snap), idx * jnp.int32(_CK), 0),
        dtype=jnp.int32,
    )
    return elig ^ run


def host_eligibility_checksum(snap) -> int:
    """The host twin of :func:`eligibility_checksum`, over the HOST-backed
    snapshot columns — wrapped mod-2^32 arithmetic matches the device's
    i32 two's complement exactly."""
    import numpy as np

    from kube_batch_tpu.api.types import TaskStatus

    # kbt: allow[KBT005] the host twin reads the HOST-backed snapshot the
    # actions keep for numpy access — these asarray calls copy nothing and
    # never touch the device (the device side is eligibility_checksum,
    # fused into the solve program)
    tj, valid, pending, status, node, jvalid, jsched = [
        np.asarray(a) for a in (  # kbt: allow[KBT005] host-backed reads ^
            snap.task_job, snap.task_valid, snap.task_pending,
            snap.task_status, snap.task_node, snap.job_valid,
            snap.job_schedulable,
        )
    ]
    elig_mask = pending & valid & jvalid[tj] & jsched[tj]
    run_mask = (
        valid & (status == int(TaskStatus.RUNNING)) & (node >= 0)
        & jvalid[tj]
    )
    idx = np.arange(elig_mask.shape[0], dtype=np.int64) + 1
    elig = int(np.sum(np.where(elig_mask, idx, 0), dtype=np.int64)) & 0xFFFFFFFF
    ck = _CK & 0xFFFFFFFF
    run = int(np.sum(np.where(run_mask, (idx * ck) & 0xFFFFFFFF, 0),
                     dtype=np.int64)) & 0xFFFFFFFF
    return (elig ^ run) & 0xFFFFFFFF


def _static_feasible_at(snap: DeviceSnapshot, node_idx: jnp.ndarray,
                        active: jnp.ndarray) -> jnp.ndarray:
    """[T] bool — row-wise static-predicate re-check at ``node_idx`` (the
    assigned/claimed node per task): node health, selector bits, taint
    toleration, and the sparse inter-pod-affinity correction rows.  A
    row-wise gather, O(T·W) — never a [T, N] recompute."""
    T = snap.task_req.shape[0]
    N = snap.node_label_bits.shape[0]
    safe = jnp.clip(node_idx, 0, N - 1)
    labels = snap.node_label_bits[safe]                       # [T, W]
    taints = snap.node_taint_bits[safe]
    sel_ok = jnp.all(
        (snap.task_sel_bits & labels) == snap.task_sel_bits, axis=-1
    ) & ~snap.task_sel_impossible
    tol_ok = jnp.all((taints & ~snap.task_tol_bits) == 0, axis=-1)
    node_ok = snap.node_valid[safe] & snap.node_sched[safe]
    ok = node_ok & sel_ok & tol_ok
    # sparse affinity rows: the mask at the row's chosen node must hold
    rows = jnp.clip(snap.task_aff_idx, 0, T - 1)
    chosen = jnp.clip(node_idx[rows], 0, N - 1)
    aff_at = jnp.take_along_axis(
        snap.task_aff_mask, chosen[:, None], axis=1
    )[:, 0]
    # padding rows (-1) and rows whose node is inactive contribute True
    upd = jnp.where(
        (snap.task_aff_idx >= 0) & active[rows], aff_at, True
    )
    ok = ok.at[rows].min(upd)
    return ok | ~active


def allocate_invariants(snap: DeviceSnapshot, res: AllocateResult,
                        config: AllocateConfig):
    """(verdict i32, hist [N_INVARIANTS] i32) for one allocate-shaped
    result.  Verdict 0 ⇔ every invariant holds."""
    T, R = snap.task_req.shape
    N = snap.node_idle.shape[0]
    J = snap.job_min_avail.shape[0]
    tj = snap.task_job
    assigned, pipelined = res.assigned, res.pipelined
    placed = assigned >= 0

    # (1) only eligible pending rows may place — a RUNNING row re-assigned
    # is the "assigned twice" class
    n_inel = _i32sum(placed & ~_eligible_pending(snap))

    # (2) bounds + cycle-start static feasibility at the assigned node
    in_range = (assigned >= -1) & (assigned < N)
    feas = _static_feasible_at(snap, assigned, placed)
    n_infeas = _i32sum(~in_range) + _i32sum(placed & ~feas)

    # (3) per-node budget + capacity: the committed deltas must fit the
    # cycle-start budgets (what the solve promised), AND post-solve used
    # must stay under allocatable, AND the cycle-start ledgers themselves
    # must be self-consistent (idle+used ≤ allocatable; idle ≥ 0) — the
    # redundant cross-checks that catch a corrupted resident ledger word
    # the solve's own budget math would trust.  PIPELINED occupancy is the
    # sanctioned exception: a pipelined task borrows a dying victim's share
    # (node.AddTask(Pipelined): Releasing -= r, Used += r), so `used` may
    # lawfully exceed `allocatable` by exactly the pipelined resreq resident
    # on the node — both at cycle start (reclaim ran earlier this cycle) and
    # in the post-solve ledgers (this solve's own pipelined placements).
    from kube_batch_tpu.api.types import TaskStatus

    seg = jnp.where(placed, jnp.clip(assigned, 0, N - 1), N)
    alloc_delta = jax.ops.segment_sum(
        jnp.where((placed & ~pipelined)[:, None], snap.task_resreq, 0.0),
        seg, num_segments=N + 1,
    )[:N]
    pipe_delta = jax.ops.segment_sum(
        jnp.where((placed & pipelined)[:, None], snap.task_resreq, 0.0),
        seg, num_segments=N + 1,
    )[:N]
    pipe_here = (
        snap.task_valid
        & (snap.task_status == jnp.int32(int(TaskStatus.PIPELINED)))
        & (snap.task_node >= 0)
    )
    pipe_resident = jax.ops.segment_sum(
        jnp.where(pipe_here[:, None], snap.task_resreq, 0.0),
        jnp.where(pipe_here, snap.task_node, N), num_segments=N + 1,
    )[:N]
    q = snap.quanta
    cap = snap.node_alloc + pipe_resident
    over = (
        jnp.any(alloc_delta > snap.node_idle + q, axis=-1)
        | jnp.any(pipe_delta > snap.node_releasing + q, axis=-1)
        | (snap.node_valid & jnp.any(
            res.node_used > cap + pipe_delta + q, axis=-1))
        | (snap.node_valid & jnp.any(
            snap.node_idle + snap.node_used > cap + q, axis=-1))
        | (snap.node_valid & jnp.any(snap.node_idle < -q, axis=-1))
    )
    n_over = _i32sum(over)

    # (4) committed gangs meet min_available — the vectorized JobReady
    # commit gate, re-derived from the surviving placements
    if config.gang:
        new_alloc = jax.ops.segment_sum(
            (placed & ~pipelined).astype(jnp.int32), tj, num_segments=J
        )
        new_any = jax.ops.segment_sum(
            placed.astype(jnp.int32), tj, num_segments=J
        )
        n_gang = _i32sum(
            (new_any > 0)
            & ((snap.job_ready + new_alloc) < snap.job_min_avail)
        )
    else:
        n_gang = jnp.int32(0)

    # (5) all-finite sweep: result ledgers + every f32 snapshot input
    n_fin = _snapshot_nonfinite(snap) + _nonfinite_count(
        res.node_idle, res.node_releasing, res.node_used, res.deserved
    )

    zero = jnp.int32(0)
    hist = jnp.stack([
        n_inel, n_infeas, n_over, n_gang, zero, zero, n_fin, zero,
    ]).astype(jnp.int32)
    return jnp.sum(hist, dtype=jnp.int32), hist


def evict_invariants(snap: DeviceSnapshot, res: EvictResult,
                     config: EvictConfig):
    """(verdict i32, hist) for one eviction-shaped result (reclaim or
    preempt)."""
    T, R = snap.task_req.shape
    N = snap.node_alloc.shape[0]
    J = snap.job_min_avail.shape[0]
    claim_node, evicted, victim_claimant = (
        res.claim_node, res.evicted, res.victim_claimant,
    )
    claimed = claim_node >= 0

    # claimants must be eligible pending rows, statically feasible at the
    # claimed node, and in range
    n_inel = _i32sum(claimed & ~_eligible_pending(snap))
    in_range = (
        (claim_node >= -1) & (claim_node < N)
        & (victim_claimant >= -1) & (victim_claimant < T)
    )
    feas = _static_feasible_at(snap, claim_node, claimed)
    n_infeas = _i32sum(~in_range) + _i32sum(claimed & ~feas)

    # victims: valid RUNNING residents, victim↔claimant consistency
    running = victim_running(snap)
    n_victim = (
        _i32sum(evicted & ~running)
        + _i32sum(evicted != (victim_claimant >= 0))
    )

    # gang slack: a job never drops below MinAvailable (victim gate).
    # Only jobs that actually LOST victims are judged — an unready gang
    # (ready < min_available) has negative slack but zero evictions, which
    # is lawful
    if config.victim_gang:
        evict_cnt = jax.ops.segment_sum(
            evicted.astype(jnp.int32), snap.task_job, num_segments=J
        )
        n_gang = _i32sum(
            (evict_cnt > 0) & (evict_cnt > gang_slack0(snap, config))
        )
    else:
        n_gang = jnp.int32(0)

    # coverage: every claim's victims cover the claimant's request in
    # every dimension — evictions never happen without a covered placement
    vseg = jnp.where(
        evicted & (victim_claimant >= 0),
        jnp.clip(victim_claimant, 0, T - 1), T,
    )
    cover = jax.ops.segment_sum(
        jnp.where(evicted[:, None], snap.task_resreq, 0.0),
        vseg, num_segments=T + 1,
    )[:T]
    n_cover = _i32sum(
        claimed & jnp.any(snap.task_req > cover + snap.quanta, axis=-1)
    )

    n_fin = _snapshot_nonfinite(snap)
    zero = jnp.int32(0)
    hist = jnp.stack([
        n_inel, n_infeas, zero, n_gang, n_victim, n_cover, n_fin, zero,
    ]).astype(jnp.int32)
    return jnp.sum(hist, dtype=jnp.int32), hist


# --------------------------------------------------------------------------
# sentinel-fused solve programs — the dispatch-facing entry points.  Each is
# the committed solve body plus its invariant tail in ONE compiled program
# (jit-of-jit inlines the inner solve), so the sentinel shares the solve's
# dispatch and its verdict rides the action's existing single device_get.
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("config",))
def allocate_sentinel_solve(snap: DeviceSnapshot, config: AllocateConfig):
    """allocate_solve with the fused invariant tail → (result, verdict,
    hist, eligibility checksum)."""
    res = allocate_solve.__wrapped__(snap, config)
    verdict, hist = allocate_invariants(snap, res, config)
    return res, verdict, hist, eligibility_checksum(snap)


@partial(jax.jit, static_argnames=("config",))
def allocate_topk_sentinel_solve(snap: DeviceSnapshot, pend_rows,
                                 config: AllocateConfig):
    """The compacted allocate solve with the fused invariant tail.  The
    invariants run on the scattered-back [T] result, so a compaction bug
    that mis-scatters the bucket is in scope, not just the rounds."""
    res = allocate_topk_solve.__wrapped__(snap, pend_rows, config)
    verdict, hist = allocate_invariants(snap, res, config)
    return res, verdict, hist, eligibility_checksum(snap)


def _warm_sentinel_body(snap, pend_rows, t_idx, t_skey, t_hash, t_trunc,
                        row_map, changed_nodes, rerank_rows, rerank_slots,
                        config: AllocateConfig, k_min: int):
    """The warm-started compacted solve (ops.assignment._warm_allocate_solve)
    plus the fused invariant tail: the invariants run on the scattered-back
    [T] result, so a table-carry bug that merges a stale key into a wrong
    placement is in scope exactly like a compaction mis-scatter."""
    from kube_batch_tpu.ops.assignment import _warm_allocate_solve

    res, table, eroded = _warm_allocate_solve(
        snap, pend_rows, t_idx, t_skey, t_hash, t_trunc,
        row_map, changed_nodes, rerank_rows, rerank_slots, config, k_min,
    )
    verdict, hist = allocate_invariants(snap, res, config)
    return res, verdict, hist, eligibility_checksum(snap), table, eroded


_WARM_SENTINEL = None


def warm_sentinel_solve_fn():
    """Jitted sentinel-fused warm solve — module-level memo with the same
    backend-dependent table donation as ops.assignment.warm_solve_fn."""
    global _WARM_SENTINEL
    if _WARM_SENTINEL is None:
        from kube_batch_tpu.ops.assignment import WARM_TABLE_ARGNUMS

        donate = (
            () if jax.default_backend() == "cpu" else WARM_TABLE_ARGNUMS
        )
        _WARM_SENTINEL = jitstats.register(
            "warm_allocate_sentinel_solve",
            jax.jit(_warm_sentinel_body,
                    static_argnames=("config", "k_min"),
                    donate_argnums=donate),
        )
    return _WARM_SENTINEL


def warm_allocate_sentinel_solve(snap, pend_rows, table, plan,
                                 config: AllocateConfig, k_min: int):
    """Dispatch-facing sentinel-fused warm solve: same calling shape as
    ops.assignment.warm_allocate_solve, returning ``(result, verdict,
    hist, checksum, table', eroded)``."""
    t_idx, t_skey, t_hash, t_trunc = table
    row_map, changed, rr, rslots = plan
    return warm_sentinel_solve_fn()(
        snap, pend_rows, t_idx, t_skey, t_hash, t_trunc,
        row_map, changed, rr, rslots, config=config, k_min=k_min,
    )


@partial(jax.jit, static_argnames=("config",))
def evict_sentinel_solve(snap: DeviceSnapshot, config: EvictConfig):
    """evict_solve (reclaim/preempt) with the fused invariant tail."""
    res = evict_solve.__wrapped__(snap, config)
    verdict, hist = evict_invariants(snap, res, config)
    return res, verdict, hist, eligibility_checksum(snap)


def enqueue_gate_invariants(admitted, cand, min_res, idle0, quanta):
    """(verdict, hist) for the enqueue admission scan: an admitted row must
    have been a candidate, and the budget inputs must be finite."""
    n_admit = _i32sum(admitted & ~cand)
    n_fin = _nonfinite_count(min_res, idle0, quanta)
    zero = jnp.int32(0)
    hist = jnp.stack([
        zero, zero, zero, zero, zero, zero, n_fin, n_admit,
    ]).astype(jnp.int32)
    return jnp.sum(hist, dtype=jnp.int32), hist


_GATE_SENTINEL = None


def enqueue_gate_sentinel_fn():
    """Jitted admission scan + fused invariant tail (module-level memo,
    mirroring ops.admission.enqueue_gate_fn)."""
    global _GATE_SENTINEL
    if _GATE_SENTINEL is None:
        from kube_batch_tpu.ops.admission import gate_scan

        def fused(min_res, cand, idle0, quanta):
            admitted = gate_scan(min_res, cand, idle0, quanta)
            verdict, hist = enqueue_gate_invariants(
                admitted, cand, min_res, idle0, quanta
            )
            return admitted, verdict, hist

        _GATE_SENTINEL = jitstats.register(
            "enqueue_gate_sentinel", jax.jit(fused)
        )
    return _GATE_SENTINEL


def enqueue_gate_sentinel_solve(min_res, cand, idle0, quanta):
    return enqueue_gate_sentinel_fn()(min_res, cand, idle0, quanta)


# retrace accounting: steady-state cycles must hit the jit cache (the bench
# asserts the counters stay flat with the guard on)
jitstats.register("allocate_sentinel_solve", allocate_sentinel_solve)
jitstats.register("allocate_topk_sentinel_solve", allocate_topk_sentinel_solve)
jitstats.register("evict_sentinel_solve", evict_sentinel_solve)
