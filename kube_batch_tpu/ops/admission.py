"""Enqueue admission gate — the jitted prefix-scan over job rows.

The reference's enqueue action (enqueue.go:102-117) walks Pending-phase
podgroups in (queue, job) priority order, admitting each whose MinResources
fit the remaining overcommitted idle and deducting on admission.  The walk
was the last O(jobs) Python loop in the 5-action pipeline; here the
sequential dependence (each admission shrinks the idle the next candidate
sees) becomes one ``lax.scan`` over the PRE-ORDERED candidate rows:

- the host supplies candidates already permuted into admission order
  (queues drained in tiered queue order — exact, because the session's
  queue_order_fn is a strict total order, so the reference's heap pop/push
  degenerates to drain-by-queue — jobs within a queue in tiered job order,
  both derived from columns; actions/enqueue.py);
- per step: ``ok = cand & (min ≤ idle tolerating sub-quantum excess)``
  (Resource.less_equal's exact comparison), then
  ``idle -= min`` clamped at zero (Resource.sub_'s clamp) when admitted;
- the admitted mask comes back in ONE readback; only promoted rows touch
  Python objects.

Precision: the device scan runs in float32 (the snapshot dtype contract —
f64 would trip KBT101 and be silently downcast off-x64 anyway), while the
retained object walk deducts in float64.  A naive f32 running difference
would drift by one ulp PER admission — at the 5k-node scale (idle memory
~5e13 bytes, f32 ulp ~4e6) a few thousand admissions could push the drift
past the 10 MiB comparison quantum.  The scan therefore carries the idle
budget as a Kahan-compensated (value, compensation) pair: the low bits
each subtraction would lose are carried forward, bounding the TOTAL
accumulation error to ~1 ulp regardless of admission count, which keeps
the worst-case divergence vs the f64 walk inside the input-cast rounding
(±½ ulp on idle0 and each MinResources row) — below the comparison quanta
for every real resource magnitude, so a verdict can differ from the walk
only for a job sitting within ~1 ulp of the tolerance band's edge.

Shapes are the padded job-axis capacity, so the scan compiles once per
(capJ, R) bucket and steady-state cycles are jit cache hits (the bench's
retrace counters include it).  Registered in the jaxpr audit
(analysis/jaxpr_audit.py) so KBT101-104 cover it in tier-1.
"""

from __future__ import annotations

from kube_batch_tpu.utils import jitstats

_GATE = None


def gate_scan(min_res, cand, idle0, quanta):
    """The raw (untraced) admission scan — shared by the single-device jit
    wrapper below AND the mesh-replicated shard_map wrapper
    (parallel/mesh.enqueue_gate_solve_fn), so both paths trace the
    identical program and the verdicts are bit-equal by construction."""
    import jax
    import jax.numpy as jnp

    def step(carry, inp):
        idle, comp = carry
        m, c = inp
        eff = idle + comp  # compensated view of the budget
        fits = jnp.all((m <= eff) | (m - eff < quanta))
        ok = c & fits
        # Kahan/Neumaier-compensated deduction: carry the low bits
        # `idle - m` would round away (module docstring)
        y = jnp.where(ok, comp - m, comp)
        t = idle + y
        comp = (idle - t) + y
        idle = jnp.maximum(t, 0.0)  # Resource.sub_'s clamp
        comp = jnp.where(idle > 0.0, comp, 0.0)
        return (idle, comp), ok

    init = (idle0, jnp.zeros_like(idle0))
    _, admitted = jax.lax.scan(step, init, (min_res, cand))
    return admitted


def enqueue_gate_fn():
    """The shared jitted admission scan (module-level memo — one compile
    cache for every cache/scheduler instance in the process)."""
    global _GATE
    if _GATE is None:
        import jax

        _GATE = jitstats.register("enqueue_gate", jax.jit(gate_scan))
    return _GATE


def enqueue_gate_solve(min_res, cand, idle0, quanta):
    """Admitted mask for candidates in scan order: ``min_res`` [capJ, R]
    f32 (MinResources rows, zeros on padding), ``cand`` [capJ] bool
    (candidate AND statically enqueueable), ``idle0`` [R] f32 the
    overcommitted idle, ``quanta`` [R] f32 the comparison quanta."""
    return enqueue_gate_fn()(min_res, cand, idle0, quanta)
