"""Batched what-if / admission probe — the query plane's solve.

The scheduler's write path answers "where does this gang go?" by committing
a Statement; the serve/ query plane answers "where WOULD it go?" without
committing anything.  This kernel scores B speculative gangs against the
device-resident snapshot columns in ONE dispatch: each gang is vmapped
through the SAME solve machinery the committed cycle runs —
:func:`ops.assignment.allocate_rounds` for placement and the
:mod:`ops.eviction` victim machinery for the hypothetical preemption set —
restricted to a task axis of just the gang's members.

Oracle-exactness contract (the tests' bit-match invariant): on a frozen
snapshot, a gang reported feasible at nodes X must bind to exactly X when
actually submitted.  Three properties make that structural rather than
approximate:

- the probe view's per-element inputs (requests, selector/toleration bits,
  queue/job rows, the proportion ``queue_request`` bump the real submission
  would cause) equal what the committed snapshot-with-gang would carry at
  the gang's rows;
- the tie-break hash is computed at the GLOBAL task rows the gang would
  occupy on submission (``ColumnStore.peek_task_rows`` — the row allocator
  is deterministic against a frozen cache), via the shared
  :func:`ops.assignment.tie_break_hash_rows`;
- the round machinery is the same code: ``allocate_rounds`` with a [G, N]
  head, and the eviction probe mirrors ``evict_rounds``'s victim
  selection / caps / coverage lines at full task-axis scale.

Probe semantics: the gang is solved ALONE against the frozen snapshot
(admission-probe semantics).  Other pending work that lands in the same
real cycle can still out-compete the gang at submission time — that race is
inherent to any what-if and is what the lease's ``snapshot_version`` lets
clients reason about.

Modeled scope: the probe answers for the allocate/preempt solve plus the
enqueue action's FULL admission gate — both the cluster-capability test
(1.2×total − used) and the queue-state ``JobEnqueueable`` veto
(proportion.go:211-233): a gang naming a known queue is also checked
against that queue's capability minus its current allocation, exactly the
test :mod:`actions.enqueue` applies at enqueue time.  Best-effort members
(every semantic request below the resource quanta — including an empty
request map) are never solver-pending, so an all-best-effort gang reports
``feasible: false`` with an empty fit-error histogram even though the
backfill action would bind exactly such pods; the backfill path is the one
remaining documented non-goal (README "Query plane", ROADMAP follow-ons).

Shapes are jit-stable: B is the batcher's fixed batch bucket, G the gang
bucket (padded members have ``valid`` off), so steady-state serving never
retraces (the serving bench asserts it).  Registered in the jaxpr audit so
KBT101-104 gate the probe like the solves.

Sharding: the N-scale blocks (round head, eviction bids, fit-error
histogram, used-capacity sum) are factored out as the ``head`` / ``bid_fn``
/ ``hist_fn`` / ``overcommit_idle`` parameters of
:func:`probe_gang_core`; everything else (the allocate rounds, verdicts,
victim selection) is shared verbatim.  parallel/shard_solve.py substitutes
explicit-collective block versions (local [G, N_loc] compute + the same
two-key pargmax decomposition the sharded solves use) so the shard_map
probe is bit-exact against this single-device program by construction.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kube_batch_tpu.api.snapshot import DeviceSnapshot
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.ops import ordering
from kube_batch_tpu.ops.assignment import (
    NEG,
    AllocateConfig,
    _best_node,
    allocate_rounds,
    round_head_parts,
    tie_break_hash_rows,
)
from kube_batch_tpu.ops.eviction import (
    EvictConfig,
    claim_winners,
    gang_slack0,
    pick_victims,
    victim_running,
)
from kube_batch_tpu.ops.feasibility import (
    FeasibilityMasks,
    failure_histogram,
    fits,
)
from kube_batch_tpu.utils import jitstats

#: the enqueue action's 20% overcommit (enqueue.go:74-81) — the admission
#: verdict mirrors it
OVERCOMMIT_FACTOR = 1.2


class ProbeBatch(NamedTuple):
    """B speculative gangs, padded to the (B, G) buckets.

    Every member of a gang shares the gang's selector/toleration bits and
    priority (the dominant what-if shape: N identical replicas); per-member
    requests still vary via ``req``."""

    req: jnp.ndarray             # [B, G, R] f32 — member requests (InitResreq == Resreq)
    valid: jnp.ndarray           # [B, G] bool — live members (G is padded)
    min_avail: jnp.ndarray       # [B] i32 — gang MinAvailable
    queue: jnp.ndarray           # [B] i32 — queue row; -1 = unknown queue
    prio: jnp.ndarray            # [B] i32
    sel_bits: jnp.ndarray        # [B, W] u32 — required label bits
    sel_impossible: jnp.ndarray  # [B] bool — selector wants a pair no node has
    tol_bits: jnp.ndarray        # [B, Wt] u32 — tolerated taint bits
    min_res: jnp.ndarray         # [B, R] f32 — PodGroup MinResources (admission verdict)
    has_min_res: jnp.ndarray     # [B] bool — absent → unconditional promotion


class ProbeResult(NamedTuple):
    assigned: jnp.ndarray      # [B, G] i32 — node index, -1 unplaced
    pipelined: jnp.ndarray     # [B, G] bool — placed on Releasing budget
    committed: jnp.ndarray     # [B] bool — the gang commit gate's verdict
    feasible: jnp.ndarray      # [B] bool — every valid member placed
    reasons: jnp.ndarray       # [B, G, N_REASONS] i32 — per-member fit-error histogram
    enqueue_ok: jnp.ndarray    # [B] bool — capability gate + queue JobEnqueueable veto
    claim_node: jnp.ndarray    # [B, G] i32 — eviction claim node, -1 (preempt probe)
    victims: jnp.ndarray       # [B, T] bool — hypothetical eviction set
    evict_covered: jnp.ndarray  # [B] bool — eviction claims passed the commit gate


def _gang_view(snap: DeviceSnapshot, req, valid, min_avail, queue, prio,
               sel_bits, sel_impossible, tol_bits) -> DeviceSnapshot:
    """``snap`` with the task axis replaced by the gang's G member rows and
    the speculative job APPENDED as job row J (a fresh row, so no live row
    is ever clobbered; the job's row index is immaterial to the math — it
    only keys segment sums).  ``queue_request`` gets the gang's request
    added at its queue row, exactly what proportion's session open would
    compute after a real submission."""
    G, R = req.shape
    N = snap.node_alloc.shape[0]
    Q = snap.queue_weight.shape[0]
    i32 = jnp.int32

    # BestEffort = empty semantic InitResreq (mirrors build_snapshot); such
    # members are never solver-pending
    from kube_batch_tpu.ops import fairness

    sem = fairness.semantic_mask(R)
    best_effort = jnp.all(req[:, sem] < snap.quanta[sem], axis=1)
    pending = valid & ~best_effort
    # member creation order = submission order (clients POST pods in member
    # order, creation_index ascending) — only the RELATIVE order among the
    # gang's members matters (they are the sole candidates)
    creations = jnp.max(snap.task_creation) + 1 + jnp.arange(G, dtype=i32)

    qsafe = jnp.clip(queue, 0, Q - 1)
    gang_req = jnp.sum(jnp.where(pending[:, None], req, 0.0), axis=0)
    queue_request = snap.queue_request.at[qsafe].add(
        jnp.where(queue >= 0, gang_req, 0.0)
    )

    def app(arr, value, dtype=None):
        row = jnp.asarray(value, arr.dtype if dtype is None else dtype)
        return jnp.concatenate([arr, row[None]])

    J = snap.job_min_avail.shape[0]  # the appended job's row index
    return snap._replace(
        task_req=req,
        task_resreq=req,
        task_job=jnp.full(G, J, i32),
        task_prio=jnp.full(G, prio, i32),
        task_creation=creations,
        task_status=jnp.where(
            valid, i32(int(TaskStatus.PENDING)), i32(int(TaskStatus.UNKNOWN))
        ),
        task_valid=valid,
        task_pending=pending,
        task_best_effort=best_effort,
        task_sel_bits=jnp.broadcast_to(sel_bits[None, :], (G,) + sel_bits.shape),
        task_sel_impossible=jnp.full(G, sel_impossible),
        task_tol_bits=jnp.broadcast_to(tol_bits[None, :], (G,) + tol_bits.shape),
        task_node=jnp.full(G, -1, i32),
        task_critical=jnp.zeros(G, bool),
        task_needs_host=jnp.zeros(G, bool),
        task_aff_idx=jnp.full(1, -1, i32),
        task_aff_mask=jnp.ones((1, N), bool),
        task_pref_idx=jnp.full(1, -1, i32),
        task_pref_node=jnp.zeros((1, N), jnp.float32),
        task_pref_pod=jnp.zeros((1, N), jnp.float32),
        job_min_avail=app(snap.job_min_avail, min_avail),
        job_ready=app(snap.job_ready, 0),
        job_queue=app(snap.job_queue, qsafe),
        job_prio=app(snap.job_prio, prio),
        job_creation=app(snap.job_creation, jnp.max(snap.job_creation) + 1),
        job_valid=app(snap.job_valid, queue >= 0),
        job_schedulable=app(snap.job_schedulable, True),
        job_allocated=jnp.concatenate(
            [snap.job_allocated, jnp.zeros((1, snap.job_allocated.shape[1]),
                                           jnp.float32)]
        ),
        queue_request=queue_request,
    )


def overcommit_idle(snap: DeviceSnapshot) -> jnp.ndarray:
    """[R] — the enqueue action's capability budget: Σ allocatable×1.2 −
    Σ used over valid nodes (enqueue.go:74-81).  Gang-independent, so the
    dispatch computes it ONCE outside the vmap; the shard_map body replaces
    it with a local sum + psum."""
    used = jnp.sum(
        jnp.where(snap.node_valid[:, None], snap.node_used, 0.0), axis=0
    )
    return jnp.maximum(snap.total * OVERCOMMIT_FACTOR - used, 0.0)


def _admission_verdict(idle, quanta, min_res, has_min_res,
                       queue_alloc, queue_cap, queue_known):
    """The enqueue action's admission core for ONE speculative podgroup:
    MinResources ≤ the overcommitted idle budget, tolerating a sub-quantum
    excess (enqueue.go:74-81,102-117; ops/admission.gate_scan's fit test
    with an empty prior admission set — the probe's gang is the only
    candidate), AND the queue-state ``JobEnqueueable`` veto
    (proportion.go:211-233): MinResources plus the queue's current
    allocation must fit the queue's capability, with the same sub-quantum
    tolerance (actions/enqueue.py's ``need − cap < quanta`` test).  An
    unknown or invalid queue skips the veto — the reference treats a
    missing queue attribute as enqueueable.  No MinResources →
    unconditional promotion (enqueue.go:102-105)."""
    fits_cap = jnp.all((min_res <= idle) | (min_res - idle < quanta))
    need = min_res + queue_alloc
    fits_queue = jnp.all((need <= queue_cap) | (need - queue_cap < quanta))
    return ~has_min_res | (fits_cap & (~queue_known | fits_queue))


def _evict_probe(snap: DeviceSnapshot, req, pending, queue, min_avail,
                 assigned0, bid_fn, config: EvictConfig, n_nodes: int):
    """Hypothetical preempt pass for one gang: which nodes would its
    unplaced members claim, and which running victims would be evicted —
    built ON :mod:`ops.eviction`'s shared victim machinery
    (:func:`~ops.eviction.victim_running` / :func:`~ops.eviction.claim_winners`
    / :func:`~ops.eviction.pick_victims`), with claimants restricted to the
    gang's members, so the probe's victim eligibility, reverse-task-order
    selection, gang slack cap, coverage recheck, and commit gate are
    literally the solve's lines rather than a ~90-line mirror of them.  For
    a speculative job every same-queue RUNNING task is another job's — the
    reference's preempt victim filter (preempt.go:113-121) reduces to the
    queue test.

    ``bid_fn(claimant_ok, cap) -> (best, has)`` is the only [G, N]-scale
    block (the masked two-key argmax over per-node evictable capacity);
    the single-device and shard_map paths supply their own (bit-exact)
    versions.  ``n_nodes`` is the GLOBAL node count — every other array
    here is task-axis or [N]-sized replicated math."""
    G = req.shape[0]
    T = snap.task_req.shape[0]
    N = n_nodes
    J = snap.job_min_avail.shape[0]
    Q = snap.queue_weight.shape[0]
    i32 = jnp.int32

    task_queue = snap.job_queue[snap.task_job]
    running = victim_running(snap)
    victim_rank = ordering.multisort_ranks(
        [snap.task_prio, -snap.task_creation]
    )
    slack0 = gang_slack0(snap, config)

    q_ok = (queue >= 0) & (queue < Q)
    claimant_base = pending & (assigned0 < 0) & q_ok
    # one job's claimants: the virtual rank among them is the subrank order
    # (equal priority, ascending creation) — the member index
    rank_g = jnp.arange(G, dtype=i32)
    vn = jnp.clip(snap.task_node, 0, N - 1)

    def round_body(state):
        claim_node, evicted, i, _ = state
        placed = claim_node >= 0

        evict_cnt = jax.ops.segment_sum(
            evicted.astype(i32), snap.task_job, num_segments=J
        )
        slack_rem = slack0 - evict_cnt
        victim_ok = running & ~evicted
        if config.victim_conformance:
            victim_ok &= ~snap.task_critical
        if config.victim_gang:
            victim_ok &= slack_rem[snap.task_job] > 0
        vq = victim_ok & (task_queue == queue)

        # per-node evictable capacity for the gang's queue (the one-hot
        # gather of evict_rounds' per-queue scatter selects exactly this row)
        vreq = jnp.where(vq[:, None], snap.task_resreq, 0.0)
        cap = jax.ops.segment_sum(
            vreq, jnp.where(vq, snap.task_node, N), num_segments=N + 1
        )[:N]                                                    # [N, R]

        claimant_ok = claimant_base & ~placed
        best, has = bid_fn(claimant_ok, cap)
        has &= claimant_ok

        # one winner per node: lowest member rank (the gang's claimant axis
        # plugged into the solve's winner selection)
        is_winner, winner_member, node_has_claim = claim_winners(
            has, best, rank_g, N
        )
        node_req = jnp.where(
            node_has_claim[:, None], req[jnp.maximum(winner_member, 0)],
            jnp.inf,
        )                                                        # [N, R]

        # the solve's victim machinery: reverse-task-order selection, gang
        # slack cap (no proportion budget — preempt semantics), coverage
        vmask = vq & node_has_claim[vn]
        final_take, covered = pick_victims(
            snap, vmask, node_req, node_has_claim, victim_rank, slack_rem,
            config, N,
        )

        new_claim = is_winner & covered[jnp.clip(best, 0, N - 1)]
        claim_node = jnp.where(new_claim, best, claim_node)
        evicted = evicted | final_take
        return (claim_node, evicted, i + 1, jnp.any(new_claim))

    def round_cond(state):
        *_, i, progress = state
        return (i < config.rounds) & progress

    claim_node, evicted, _, _ = jax.lax.while_loop(
        round_cond,
        round_body,
        (jnp.full(G, -1, i32), jnp.zeros(T, bool), i32(0), jnp.bool_(True)),
    )

    if config.gang:
        # preempt commit gate: ready (placements the allocate pass kept) +
        # pipelined claims must reach MinAvailable, else claims revert and
        # victims un-evict (preempt.go:127-137) — one job, so wholesale
        n_ready = jnp.sum((assigned0 >= 0).astype(i32))
        n_pipe = jnp.sum((claim_node >= 0).astype(i32))
        job_ok = (n_ready + n_pipe) >= min_avail
        claim_node = jnp.where(job_ok, claim_node, -1)
        evicted &= job_ok
    else:
        job_ok = jnp.any(claim_node >= 0)
    return claim_node, evicted, job_ok


def probe_gang_core(snap: DeviceSnapshot, view: DeviceSnapshot, g: ProbeBatch,
                    config: AllocateConfig, evict_config: EvictConfig,
                    with_evictions: bool, *, head, bid_fn, hist_fn,
                    oc_idle, idle0, rel0, used0, n_nodes: int) -> ProbeResult:
    """One gang's full probe given the N-scale blocks: the allocate rounds,
    commit/feasibility verdicts, admission verdict, and eviction probe —
    shared verbatim by the single-device path below and the shard_map body
    (parallel/shard_solve.py), so the two paths can only diverge inside
    ``head``/``bid_fn``/``hist_fn``, each of which is bit-exact by the same
    decomposition argument as the sharded solves."""
    res = allocate_rounds(view, config, head, idle0, rel0, used0)
    J = snap.job_min_avail.shape[0]  # the appended job's row
    committed = res.committed[J]
    feasible = jnp.all(~view.task_pending | (res.assigned >= 0))
    # an empty or all-best-effort gang is not a solver verdict: backfill —
    # not this solve — would bind sub-quanta pods (module docstring)
    feasible &= jnp.any(view.task_pending)
    reasons = hist_fn()
    Q = snap.queue_valid.shape[0]
    qsafe = jnp.clip(g.queue, 0, Q - 1)
    queue_known = (g.queue >= 0) & (g.queue < Q) & snap.queue_valid[qsafe]
    enqueue_ok = _admission_verdict(
        oc_idle, snap.quanta, g.min_res, g.has_min_res,
        snap.queue_alloc[qsafe], snap.queue_capability[qsafe], queue_known,
    )

    if with_evictions:
        claim_node, victims, evict_ok = _evict_probe(
            snap, g.req, view.task_pending, g.queue, g.min_avail,
            res.assigned, bid_fn, evict_config, n_nodes,
        )
    else:
        G = g.req.shape[0]
        claim_node = jnp.full(G, -1, jnp.int32)
        victims = jnp.zeros(snap.task_req.shape[0], bool)
        evict_ok = jnp.bool_(False)
    return ProbeResult(
        assigned=res.assigned,
        pipelined=res.pipelined,
        committed=committed,
        feasible=feasible,
        reasons=reasons,
        enqueue_ok=enqueue_ok,
        claim_node=claim_node,
        victims=victims,
        evict_covered=evict_ok,
    )


def probe_body(snap: DeviceSnapshot, batch: ProbeBatch,
               probe_rows: jnp.ndarray, config: AllocateConfig,
               evict_config: EvictConfig = EvictConfig(mode="preempt"),
               with_evictions: bool = False) -> ProbeResult:
    """The single-device probe program (unjitted — :func:`probe_solve` is
    the jitted entry, parallel/mesh.py's pjit oracle re-jits this same body
    with mesh shardings).

    ``probe_rows`` [G] i32 — the global task rows the next G submitted pods
    would occupy (shared across the batch: every gang is an INDEPENDENT
    hypothetical starting from the same frozen allocator state)."""
    N = snap.node_alloc.shape[0]
    tie_hash = tie_break_hash_rows(
        probe_rows, jnp.arange(N, dtype=jnp.int32)
    )
    oc_idle = overcommit_idle(snap)

    def one(g: ProbeBatch) -> ProbeResult:
        view = _gang_view(
            snap, g.req, g.valid, g.min_avail, g.queue, g.prio,
            g.sel_bits, g.sel_impossible, g.tol_bits,
        )
        head, static_ok, score = round_head_parts(view, config, tie_hash)

        def bid_fn(claimant_ok, cap):
            feas = static_ok & claimant_ok[:, None]
            feas &= jnp.all(
                g.req[:, None, :] <= cap[None, :, :] + snap.quanta, axis=-1
            )
            masked = jnp.where(feas, score, NEG)
            return _best_node(masked, tie_hash)

        def hist_fn():
            # per-member fit-error histogram at CYCLE-START budgets — the
            # same program failure_histogram_solve runs for the submitted
            # gang's rows
            fit_idle0 = fits(view.task_req, snap.node_idle, snap.quanta)
            fit_rel0 = fits(view.task_req, snap.node_releasing, snap.quanta)
            return failure_histogram(
                view,
                FeasibilityMasks(
                    static_ok, fit_idle0, fit_rel0,
                    static_ok & (fit_idle0 | fit_rel0),
                ),
            )

        return probe_gang_core(
            snap, view, g, config, evict_config, with_evictions,
            head=head, bid_fn=bid_fn, hist_fn=hist_fn, oc_idle=oc_idle,
            idle0=snap.node_idle, rel0=snap.node_releasing,
            used0=snap.node_used, n_nodes=N,
        )

    return jax.vmap(one)(batch)


probe_solve = partial(jax.jit, static_argnames=(
    "config", "evict_config", "with_evictions"))(probe_body)
probe_solve.__doc__ = """B gangs against one snapshot in one dispatch
(the jitted :func:`probe_body`)."""

# retrace accounting: the serving bench asserts the probe stays a jit cache
# hit across varying batch fill (B and G are padded buckets)
jitstats.register("probe_solve", probe_solve)
