"""Fairness tensors — DRF shares and proportion max-min queue capacity.

drf.go:161-171 computes a job's dominant share as max over resources of
allocated/total; proportion.go:101-154 iteratively distributes the cluster
total among queues by weight, capping each queue at its request, until
nothing remains or every queue is met. Both are pure arithmetic over small
[J, R] / [Q, R] arrays — they run inside the same jitted cycle program so the
assignment rounds can recompute shares incrementally (the reference keeps
them incremental via session event handlers, drf.go:135-154,
proportion.go:87-99).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

# "pods" is a capacity-only dimension this rebuild adds on top of the
# reference's model (its MaxTaskNum is not part of Resource arithmetic,
# resource_info.go:30-40) — every fairness comparison masks it out, or an
# uncontended pod-slot dimension poisons deserved/overused/reclaimable
# verdicts the reference computes over cpu/mem/scalars only (LessEqual
# resource_info.go:252-285, Share drf.go:161-171).  PODS_INDEX is the
# layout's single source of truth (api/resources.py).
from kube_batch_tpu.api.resources import PODS_INDEX


def semantic_mask(R: int) -> np.ndarray:
    m = np.ones(R, bool)
    m[PODS_INDEX] = False
    return m


def dominant_share(alloc: jnp.ndarray, total: jnp.ndarray) -> jnp.ndarray:
    """[., R], [R] → [.] max over semantic dims (cpu/mem/scalars) of
    alloc/total, 0 where the cluster has none of a resource (drf.go:161-171
    via Resource.Share — every resource name of total participates)."""
    m = semantic_mask(total.shape[-1])
    t = total[m]
    ratios = jnp.where(t > 0, alloc[..., m] / jnp.maximum(t, 1e-9), 0.0)
    return jnp.max(ratios, axis=-1)


def proportion_deserved(
    total: jnp.ndarray,       # [R]
    weight: jnp.ndarray,      # [Q]
    request: jnp.ndarray,     # [Q, R]
    valid: jnp.ndarray,       # [Q] bool
    max_iters: int | None = None,
) -> jnp.ndarray:
    """Weighted max-min fair deserved[Q, R] (proportion.go:101-154).

    Each iteration hands every unmet queue remaining·w/Σw, caps queues that
    exceed their request, and returns the excess to the pool. Terminates when
    the pool is empty or all queues are met. An iteration that caps no queue
    distributes the whole pool (the uncapped fractions sum to 1), so every
    iteration either retires ≥1 queue or empties the pool — Q+1 iterations
    always suffice, which is the default max_iters (the reference loops to
    the same fixpoint, proportion.go:101-154)."""
    Q, R = request.shape
    if max_iters is None:
        max_iters = Q + 1

    def cond(state):
        i, deserved, met, remaining = state
        some_pool = jnp.any(remaining > 1e-6)
        some_unmet = jnp.any(valid & ~met)
        return (i < max_iters) & some_pool & some_unmet

    def body(state):
        i, deserved, met, remaining = state
        w = jnp.where(valid & ~met, weight, 0.0)
        tw = jnp.sum(w)
        frac = jnp.where(tw > 0, w / jnp.maximum(tw, 1e-9), 0.0)
        inc = remaining[None, :] * frac[:, None]  # [Q, R]
        new = deserved + inc
        # met when deserved covers request in every dim (LessEqual, tolerant)
        now_met = jnp.all(request <= new + 1e-6, axis=-1) & valid
        capped = jnp.where(now_met[:, None], jnp.minimum(new, request), new)
        granted = capped - deserved
        remaining = jnp.maximum(remaining - jnp.sum(granted, axis=0), 0.0)
        return (i + 1, capped, met | now_met, remaining)

    _, deserved, _, _ = jax.lax.while_loop(
        cond, body, (0, jnp.zeros((Q, R), total.dtype), ~valid, total)
    )
    return deserved


def overused(
    deserved: jnp.ndarray,  # [Q, R]
    alloc: jnp.ndarray,     # [Q, R]
    quanta: jnp.ndarray,    # [R]
) -> jnp.ndarray:
    """[Q] bool — queue's allocation already covers its deserved share
    (proportion.go:198-209: overused iff deserved ≤ allocated, over the
    semantic dims — pods is capacity-only)."""
    m = semantic_mask(quanta.shape[-1])
    return jnp.all((deserved <= alloc + quanta)[..., m], axis=-1)


def queue_share(
    alloc: jnp.ndarray,     # [Q, R]
    deserved: jnp.ndarray,  # [Q, R]
) -> jnp.ndarray:
    """[Q] — proportion's queue order key: dominant allocated/deserved ratio
    (proportion.go:156-169, 265-277); lower share schedules first."""
    m = semantic_mask(alloc.shape[-1])
    d = deserved[..., m]
    ratios = jnp.where(d > 0, alloc[..., m] / jnp.maximum(d, 1e-9), 0.0)
    return jnp.max(ratios, axis=-1)
