"""Pallas TPU kernels for the auction round's hot op.

`masked_best_node` fuses the per-round feasibility test + score masking +
two-key tie-broken argmax (ops/assignment.py round_body's first half) into
one VMEM pass per task tile: the [T, N] fit matrices are never materialized
in HBM — req/idle/releasing live in VMEM and the fit predicate is computed
on the fly per node tile; only the score and static-predicate matrices
stream in, and three [T]-shaped vectors stream out.

The XLA path computes the same values with fused broadcasts; this kernel
exists to cut the intermediate [T, N] bool traffic on real TPU. It is
opt-in (AllocateConfig.use_pallas, wired to env KB_PALLAS=1 / the
`allocate.pallas` conf argument by the allocate action) and falls back to
interpret mode off-TPU so the parity tests run everywhere.

TPU lowering constraints shape the kernel: everything is float32 or int32
(no uint32, no bool refs — the Mosaic lowering in this jax version supports
neither), and every ref is ≥2-D (1-D refs mis-tile). Masks travel as f32
0/1 and outputs are (T, 1) columns squeezed by the wrapper.

Reference semantics carried over: epsilon-tolerant fit (resource_info.go:
269-284 LessEqual), SelectBestNode's uniform tie-break among max-score nodes
(scheduler_helper.go:147-158) via the same per-(task, node) int32 hash as
ops/assignment._tie_break_hash.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# plain Python float — a jnp scalar would be a captured constant, which
# pallas_call rejects
NEG = -3.0e38

TASK_TILE = 256


def _kernel(score_ref, static_ref, req_ref, idle_ref, rel_ref, pending_ref,
            quanta_ref, best_ref, has_ref, chose_idle_ref):
    TM = score_ref.shape[0]
    N = score_ref.shape[1]
    R = req_ref.shape[1]

    req = req_ref[:]                      # [TM, R]
    quanta = quanta_ref[:]                # [1, R]

    # fit[t, n] = all_r req[t, r] <= budget[n, r] + quanta[r]  (tolerant
    # LessEqual); R is tiny and static — unrolled, no [TM, N, R] tensor
    def fit_matrix(budget_ref):
        fit = None
        for r in range(R):
            f = req[:, r][:, None] <= budget_ref[:, r][None, :] + quanta[0, r]
            fit = f if fit is None else (fit & f)
        return fit

    fit_idle = fit_matrix(idle_ref)
    fit_rel = fit_matrix(rel_ref)
    pending = pending_ref[:] > 0.0        # [TM, 1] f32 0/1 → bool
    feas = (static_ref[:] > 0.0) & (fit_idle | fit_rel) & pending
    masked = jnp.where(feas, score_ref[:], NEG)

    # two-key argmax: exact max score, then per-(task, node) hash among ties
    # (ops/assignment._tie_break_hash — same constants, same int32 wrapping
    # arithmetic)
    from kube_batch_tpu.ops.assignment import _H1, _H2, _H3

    ti = (
        jax.lax.broadcasted_iota(jnp.int32, (TM, N), 0)
        + pl.program_id(0) * TM
    )
    ni = jax.lax.broadcasted_iota(jnp.int32, (TM, N), 1)
    h = ti * jnp.int32(_H1) + ni * jnp.int32(_H2)
    h = (h ^ jax.lax.shift_right_logical(h, 15)) * jnp.int32(_H3)
    # Mosaic's argmax lowering is f32-only; the 16 hash bits are exactly
    # representable in f32, so the cast preserves the ordering
    tie_hash = jax.lax.shift_right_logical(h, 16).astype(jnp.float32)

    best_val = jnp.max(masked, axis=1)    # [TM]
    tie = masked >= best_val[:, None]
    best = jnp.argmax(jnp.where(tie, tie_hash, -1.0), axis=1).astype(jnp.int32)
    col = jax.lax.broadcasted_iota(jnp.int32, (TM, N), 1)
    chose_idle = jnp.any(fit_idle & (col == best[:, None]), axis=1)

    best_ref[:] = best[:, None]
    has_ref[:] = jnp.where(best_val > NEG, 1.0, 0.0)[:, None]
    chose_idle_ref[:] = jnp.where(chose_idle, 1.0, 0.0)[:, None]


@functools.partial(jax.jit, static_argnames=("interpret",))
def masked_best_node(
    score: jnp.ndarray,       # [T, N] f32
    static_ok: jnp.ndarray,   # [T, N] bool
    task_req: jnp.ndarray,    # [T, R] f32 — InitResreq
    idle: jnp.ndarray,        # [N, R] f32
    releasing: jnp.ndarray,   # [N, R] f32
    pending: jnp.ndarray,     # [T] bool
    quanta: jnp.ndarray,      # [R] f32
    interpret: bool = False,
):
    """(best [T] i32, has [T] bool, chose_idle [T] bool) — the fused round
    head. T must be a multiple of TASK_TILE (snapshot buckets guarantee it
    at scale; callers pad otherwise)."""
    T, N = score.shape
    R = task_req.shape[1]
    tile = min(TASK_TILE, T)
    grid = (T // tile,)
    q2 = quanta.reshape(1, R).astype(jnp.float32)

    best, has, chose = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, N), lambda i: (i, 0)),                 # score
            pl.BlockSpec((tile, N), lambda i: (i, 0)),                 # static_ok
            pl.BlockSpec((tile, R), lambda i: (i, 0)),                 # req
            pl.BlockSpec((N, R), lambda i: (0, 0)),                    # idle
            pl.BlockSpec((N, R), lambda i: (0, 0)),                    # releasing
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),                 # pending
            pl.BlockSpec((1, R), lambda i: (0, 0)),                    # quanta
        ],
        out_specs=[
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, 1), jnp.int32),
            jax.ShapeDtypeStruct((T, 1), jnp.float32),
            jax.ShapeDtypeStruct((T, 1), jnp.float32),
        ],
        interpret=interpret,
    )(
        score.astype(jnp.float32),
        static_ok.astype(jnp.float32),
        task_req.astype(jnp.float32),
        idle.astype(jnp.float32),
        releasing.astype(jnp.float32),
        pending.astype(jnp.float32)[:, None],
        q2,
    )
    return best[:, 0], has[:, 0] > 0.0, chose[:, 0] > 0.0
