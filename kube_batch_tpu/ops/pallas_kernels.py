"""Pallas TPU kernels for the auction round's hot op.

`masked_best_node` fuses the per-round feasibility test + score masking +
two-key tie-broken argmax (ops/assignment.py round_body's first half) into
one VMEM pass per task tile: the [T, N] fit matrices are never materialized
in HBM — req/idle/releasing live in VMEM and the fit predicate is computed
on the fly per node tile; only the score and static-predicate matrices
stream in, and three [T] vectors stream out.

The XLA path computes the same values with fused broadcasts; this kernel
exists to cut the intermediate [T, N] bool traffic on real TPU. It is
opt-in (AllocateConfig.use_pallas / env KB_PALLAS=1) and falls back to
interpret mode off-TPU so the parity tests run everywhere.

Reference semantics carried over: epsilon-tolerant fit (resource_info.go:
269-284 LessEqual), SelectBestNode's uniform tie-break among max-score nodes
(scheduler_helper.go:147-158) via the same per-(task, node) hash as
ops/assignment._tie_break_hash.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# plain Python float — a jnp scalar would be a captured constant, which
# pallas_call rejects
NEG = -3.0e38

TASK_TILE = 256


def _kernel(score_ref, static_ref, req_ref, idle_ref, rel_ref, pending_ref,
            quanta_ref, best_ref, has_ref, chose_idle_ref):
    TM = score_ref.shape[0]
    N = score_ref.shape[1]
    R = req_ref.shape[1]

    req = req_ref[:]                      # [TM, R]
    quanta = quanta_ref[:]                # [1, R]

    # fit[t, n] = all_r req[t, r] <= budget[n, r] + quanta[r]  (tolerant
    # LessEqual); R is tiny and static — unrolled, no [TM, N, R] tensor
    def fit_matrix(budget_ref):
        fit = jnp.ones((TM, N), dtype=jnp.bool_)
        for r in range(R):
            fit &= req[:, r][:, None] <= budget_ref[:, r][None, :] + quanta[0, r]
        return fit

    fit_idle = fit_matrix(idle_ref)
    fit_rel = fit_matrix(rel_ref)
    pending = pending_ref[:]              # [TM]
    feas = static_ref[:].astype(jnp.bool_) & (fit_idle | fit_rel) & pending[:, None]
    masked = jnp.where(feas, score_ref[:], NEG)

    # two-key argmax: exact max score, then per-(task, node) hash among ties
    # (ops/assignment._tie_break_hash — same constants)
    ti = (
        jax.lax.broadcasted_iota(jnp.uint32, (TM, N), 0)
        + jnp.uint32(pl.program_id(0) * TM)
    )
    ni = jax.lax.broadcasted_iota(jnp.uint32, (TM, N), 1)
    h = ti * jnp.uint32(0x9E3779B1) + ni * jnp.uint32(0x85EBCA77)
    h = (h ^ (h >> 15)) * jnp.uint32(0xCA87C3EB)
    tie_hash = (h >> 16).astype(jnp.float32) / 65536.0

    best_val = jnp.max(masked, axis=1)    # [TM]
    tie = masked >= best_val[:, None]
    best = jnp.argmax(jnp.where(tie, tie_hash, -1.0), axis=1).astype(jnp.int32)
    col = jax.lax.broadcasted_iota(jnp.int32, (TM, N), 1)
    chose_idle = jnp.any(fit_idle & (col == best[:, None]), axis=1)

    best_ref[:] = best
    has_ref[:] = best_val > NEG
    chose_idle_ref[:] = chose_idle


@functools.partial(jax.jit, static_argnames=("interpret",))
def masked_best_node(
    score: jnp.ndarray,       # [T, N] f32
    static_ok: jnp.ndarray,   # [T, N] bool
    task_req: jnp.ndarray,    # [T, R] f32 — InitResreq
    idle: jnp.ndarray,        # [N, R] f32
    releasing: jnp.ndarray,   # [N, R] f32
    pending: jnp.ndarray,     # [T] bool
    quanta: jnp.ndarray,      # [R] f32
    interpret: bool = False,
):
    """(best [T] i32, has [T] bool, chose_idle [T] bool) — the fused round
    head. T must be a multiple of TASK_TILE (snapshot buckets guarantee it
    at scale; callers pad otherwise)."""
    T, N = score.shape
    R = task_req.shape[1]
    tile = min(TASK_TILE, T)
    grid = (T // tile,)
    q2 = quanta.reshape(1, R)

    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, N), lambda i: (i, 0)),                 # score
            pl.BlockSpec((tile, N), lambda i: (i, 0)),                 # static_ok
            pl.BlockSpec((tile, R), lambda i: (i, 0)),                 # req
            pl.BlockSpec((N, R), lambda i: (0, 0)),                    # idle
            pl.BlockSpec((N, R), lambda i: (0, 0)),                    # releasing
            pl.BlockSpec((tile,), lambda i: (i,)),                     # pending
            pl.BlockSpec((1, R), lambda i: (0, 0)),                    # quanta
        ],
        out_specs=[
            pl.BlockSpec((tile,), lambda i: (i,)),
            pl.BlockSpec((tile,), lambda i: (i,)),
            pl.BlockSpec((tile,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T,), jnp.int32),
            jax.ShapeDtypeStruct((T,), jnp.bool_),
            jax.ShapeDtypeStruct((T,), jnp.bool_),
        ],
        interpret=interpret,
    )(score, static_ok, task_req, idle, releasing, pending, q2)
