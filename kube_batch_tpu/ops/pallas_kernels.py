"""Pallas TPU kernels for the auction round's hot op.

`masked_best_node` fuses the per-round feasibility test + score masking +
two-key tie-broken argmax (ops/assignment.py round_body's first half) into
VMEM-tiled passes: the [T, N] fit matrices are never materialized in HBM —
req/idle/releasing live in VMEM and the fit predicate is computed on the fly
per (task, node) tile; only the score and static-predicate matrices stream
in, and three [T]-shaped vectors stream out.

Round-3 change: the node axis is TILED too (grid (T/TM, N/TN)) with the
argmax carried across node tiles through revisited output blocks — the
round-2 kernel put the whole node axis (5 120 wide at the bench shape) in
one block, and that single-block layout was what pushed the Mosaic compile
past 10 minutes; with both axes tiled the kernel compiles in seconds at
50k×5k.  The cross-tile merge is the exact two-key order: strictly greater
score wins, equal score resolves by the tie hash, equal (score, hash) keeps
the earlier tile — reproducing jnp.argmax's first-max-index semantics.

The XLA path computes the same values with fused broadcasts; this kernel
exists to cut the intermediate [T, N] bool traffic on real TPU. It is
opt-in (AllocateConfig.use_pallas, wired to env KB_PALLAS=1 / the
`allocate.pallas` conf argument by the allocate action) and falls back to
interpret mode off-TPU so the parity tests run everywhere.

TPU lowering constraints shape the kernel: everything is float32 or int32
(no uint32, no bool refs — the Mosaic lowering in this jax version supports
neither), and every ref is ≥2-D (1-D refs mis-tile). Masks travel as f32
0/1 and outputs are (T, 1) columns squeezed by the wrapper.

Reference semantics carried over: epsilon-tolerant fit (resource_info.go:
269-284 LessEqual), SelectBestNode's uniform tie-break among max-score nodes
(scheduler_helper.go:147-158) via the same per-(task, node) int32 hash as
ops/assignment._tie_break_hash.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# plain Python float — a jnp scalar would be a captured constant, which
# pallas_call rejects
NEG = -3.0e38

TASK_TILE = 256
NODE_TILE = 512


def _kernel(score_ref, static_ref, req_ref, idle_ref, rel_ref, pending_ref,
            quanta_ref, offs_ref, best_ref, val_ref, hash_ref,
            chose_idle_ref):
    TM = score_ref.shape[0]
    TN = score_ref.shape[1]
    R = req_ref.shape[1]
    j = pl.program_id(1)

    req = req_ref[:]                      # [TM, R]
    quanta = quanta_ref[:]                # [1, R]

    # fit[t, n] = all_r req[t, r] <= budget[n, r] + quanta[r]  (tolerant
    # LessEqual); R is tiny and static — unrolled, no [TM, TN, R] tensor
    def fit_matrix(budget_ref):
        fit = None
        for r in range(R):
            f = req[:, r][:, None] <= budget_ref[:, r][None, :] + quanta[0, r]
            fit = f if fit is None else (fit & f)
        return fit

    fit_idle = fit_matrix(idle_ref)
    fit_rel = fit_matrix(rel_ref)
    pending = pending_ref[:] > 0.0        # [TM, 1] f32 0/1 → bool
    feas = (static_ref[:] > 0.0) & (fit_idle | fit_rel) & pending
    masked = jnp.where(feas, score_ref[:], NEG)

    # two-key argmax within this node tile: exact max score, then the
    # per-(task, node) hash among ties (ops/assignment._tie_break_hash —
    # same constants, same int32 wrapping arithmetic).  offs_ref carries
    # the (task, node) GLOBAL offsets of this invocation's matrix block —
    # zero on the single-program path; the shard_map round head passes its
    # shard's origin so the hash (and therefore every tie-break) matches
    # the full-matrix program bit-for-bit
    from kube_batch_tpu.ops.assignment import _H1, _H2, _H3

    ti = (
        jax.lax.broadcasted_iota(jnp.int32, (TM, TN), 0)
        + pl.program_id(0) * TM + offs_ref[0, 0]
    )
    ni = (
        jax.lax.broadcasted_iota(jnp.int32, (TM, TN), 1)
        + j * TN + offs_ref[0, 1]
    )
    h = ti * jnp.int32(_H1) + ni * jnp.int32(_H2)
    h = (h ^ jax.lax.shift_right_logical(h, 15)) * jnp.int32(_H3)
    # Mosaic's argmax lowering is f32-only; the 16 hash bits are exactly
    # representable in f32, so the cast preserves the ordering
    tie_hash = jax.lax.shift_right_logical(h, 16).astype(jnp.float32)

    lval = jnp.max(masked, axis=1)                            # [TM]
    tie = masked >= lval[:, None]
    hash_masked = jnp.where(tie, tie_hash, -1.0)
    lhash = jnp.max(hash_masked, axis=1)                      # [TM]
    pick = jnp.argmax(hash_masked, axis=1).astype(jnp.int32)  # local col
    lbest = pick + j * TN
    col = jax.lax.broadcasted_iota(jnp.int32, (TM, TN), 1)
    lchose = jnp.any(fit_idle & (col == pick[:, None]), axis=1)
    lval_c = lval[:, None]
    lhash_c = lhash[:, None]
    lbest_c = lbest[:, None]
    # bool→f32 cast, not jnp.where(_, 1.0, 0.0): two weak Python floats
    # promote to the DEFAULT float dtype — an f64 upcast the moment x64 is
    # on (caught by the jaxpr audit, KBT101)
    lchose_c = lchose.astype(jnp.float32)[:, None]

    # cross-tile merge through the revisited output blocks (the node-tile
    # grid axis iterates sequentially on TPU): strictly-better (val, hash)
    # replaces; ties keep the earlier tile = first-max-index semantics
    @pl.when(j == 0)
    def _init():
        best_ref[:] = lbest_c
        val_ref[:] = lval_c
        hash_ref[:] = lhash_c
        chose_idle_ref[:] = lchose_c

    @pl.when(j > 0)
    def _merge():
        pval = val_ref[:]
        phash = hash_ref[:]
        better = (lval_c > pval) | ((lval_c == pval) & (lhash_c > phash))
        best_ref[:] = jnp.where(better, lbest_c, best_ref[:])
        val_ref[:] = jnp.where(better, lval_c, pval)
        hash_ref[:] = jnp.where(better, lhash_c, phash)
        chose_idle_ref[:] = jnp.where(better, lchose_c, chose_idle_ref[:])


@functools.partial(jax.jit, static_argnames=("interpret",))
def masked_best_node_raw(
    score: jnp.ndarray,       # [T, N] f32
    static_ok: jnp.ndarray,   # [T, N] bool
    task_req: jnp.ndarray,    # [T, R] f32 — InitResreq
    idle: jnp.ndarray,        # [N, R] f32
    releasing: jnp.ndarray,   # [N, R] f32
    pending: jnp.ndarray,     # [T] bool
    quanta: jnp.ndarray,      # [R] f32
    t0=0,                     # global task offset of this block (i32)
    n0=0,                     # global node offset of this block (i32)
    interpret: bool = False,
):
    """(best [T] i32, val [T] f32, hash [T] f32, chose_idle [T] bool) — the
    fused round head with the winner's (score, tie-hash) key exposed.  The
    shard_map head needs the raw key to run the cross-shard two-key argmax
    reduction; ``t0``/``n0`` are the block's global matrix origin (the
    tie-hash is a function of GLOBAL coordinates).  T must be a multiple of
    the task tile and N of the node tile (snapshot buckets guarantee both
    at scale; callers pad otherwise).  ``best`` stays block-local (callers
    add their node offset)."""
    T, N = score.shape
    R = task_req.shape[1]
    tile_t = min(TASK_TILE, T)
    tile_n = min(NODE_TILE, N)
    grid = (T // tile_t, N // tile_n)
    q2 = quanta.reshape(1, R).astype(jnp.float32)
    offs = jnp.asarray([t0, n0], jnp.int32).reshape(1, 2)

    best, val, hsh, chose = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_t, tile_n), lambda i, j: (i, j)),  # score
            pl.BlockSpec((tile_t, tile_n), lambda i, j: (i, j)),  # static_ok
            pl.BlockSpec((tile_t, R), lambda i, j: (i, 0)),       # req
            pl.BlockSpec((tile_n, R), lambda i, j: (j, 0)),       # idle
            pl.BlockSpec((tile_n, R), lambda i, j: (j, 0)),       # releasing
            pl.BlockSpec((tile_t, 1), lambda i, j: (i, 0)),       # pending
            pl.BlockSpec((1, R), lambda i, j: (0, 0)),            # quanta
            pl.BlockSpec((1, 2), lambda i, j: (0, 0)),            # offsets
        ],
        out_specs=[
            pl.BlockSpec((tile_t, 1), lambda i, j: (i, 0)),       # best
            pl.BlockSpec((tile_t, 1), lambda i, j: (i, 0)),       # val
            pl.BlockSpec((tile_t, 1), lambda i, j: (i, 0)),       # hash
            pl.BlockSpec((tile_t, 1), lambda i, j: (i, 0)),       # chose_idle
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, 1), jnp.int32),
            jax.ShapeDtypeStruct((T, 1), jnp.float32),
            jax.ShapeDtypeStruct((T, 1), jnp.float32),
            jax.ShapeDtypeStruct((T, 1), jnp.float32),
        ],
        interpret=interpret,
    )(
        score.astype(jnp.float32),
        static_ok.astype(jnp.float32),
        task_req.astype(jnp.float32),
        idle.astype(jnp.float32),
        releasing.astype(jnp.float32),
        pending.astype(jnp.float32)[:, None],
        q2,
        offs,
    )
    return best[:, 0], val[:, 0], hsh[:, 0], chose[:, 0] > 0.0


# --------------------------------------------------------------------------
# top-K candidate build (ops/assignment.py's KB_TOPK compaction)
# --------------------------------------------------------------------------

#: sub-block width of the emitted per-block winner triples — must divide
#: NODE_TILE; the XLA-side extraction (ops.assignment.lex_topk) defaults to
#: the same block width, so the kernel's partials line up with its grid
TOPK_BLOCK = 64


def _topk_kernel(score_ref, req_ref, idle_ref, rel_ref, rows_ref,
                 quanta_ref, offs_ref, skey_ref, bval_ref, bhash_ref,
                 bcol_ref):
    TM = score_ref.shape[0]
    TN = score_ref.shape[1]
    R = req_ref.shape[1]
    C = TOPK_BLOCK
    NB = TN // C
    j = pl.program_id(1)

    req = req_ref[:]
    quanta = quanta_ref[:]

    def fit_matrix(budget_ref):
        fit = None
        for r in range(R):
            f = req[:, r][:, None] <= budget_ref[:, r][None, :] + quanta[0, r]
            fit = f if fit is None else (fit & f)
        return fit

    # the build-time masked key plane: score_static where the node fits the
    # CYCLE-START budgets, NEG otherwise, as the order-preserving i32 sort
    # key (ops.assignment.f32_sort_key — same bit trick, Mosaic-safe)
    feas = fit_matrix(idle_ref) | fit_matrix(rel_ref)
    masked = jnp.where(feas, score_ref[:], NEG)
    # + 0.0 canonicalizes -0.0 (exact identity otherwise) — must match
    # ops.assignment.f32_sort_key bit-for-bit
    bits = jax.lax.bitcast_convert_type(masked + 0.0, jnp.int32)
    skey = jnp.where(bits < 0, bits ^ jnp.int32(0x7FFFFFFF), bits)
    skey_ref[:] = skey

    # the tie hash at GLOBAL (task-row, node) coordinates: task rows come
    # from an explicit per-row index ref (the pending bucket's rows are
    # scattered, not an arange block), node columns from the tile offset
    from kube_batch_tpu.ops.assignment import _H1, _H2, _H3

    ti = jnp.broadcast_to(rows_ref[:], (TM, TN))
    ni = (
        jax.lax.broadcasted_iota(jnp.int32, (TM, TN), 1)
        + j * TN + offs_ref[0, 0]
    )
    h = ti * jnp.int32(_H1) + ni * jnp.int32(_H2)
    h = (h ^ jax.lax.shift_right_logical(h, 15)) * jnp.int32(_H3)
    tie_hash = jax.lax.shift_right_logical(h, 16)

    # per-C-block two-key winner triples (the extraction's phase-1 input):
    # max key, max hash among key ties, first column among full ties
    # trace-time unroll over the static sub-block count (NODE_TILE /
    # TOPK_BLOCK = 8) inside the kernel body — no per-iteration dispatch;
    # argmax rides f32 (Mosaic's argmax lowering is f32-only; hashes are
    # 16-bit ints, so the cast is exact — same trick as the round head)
    for b in range(NB):
        sb = skey[:, b * C:(b + 1) * C]
        hb = tie_hash[:, b * C:(b + 1) * C]
        # kbt: allow[KBT005] static in-kernel unroll (see loop comment)
        bval = jnp.max(sb, axis=1)
        tie = sb >= bval[:, None]
        # kbt: allow[KBT005] static in-kernel unroll (see loop comment)
        hmask = jnp.where(tie, hb, -2)
        # kbt: allow[KBT005] static in-kernel unroll (see loop comment)
        bcol = jnp.argmax(hmask.astype(jnp.float32), axis=1).astype(jnp.int32)
        # kbt: allow[KBT005] static in-kernel unroll (see loop comment)
        bhash = jnp.max(hmask, axis=1)
        bval_ref[:, b:b + 1] = bval[:, None]
        bhash_ref[:, b:b + 1] = bhash[:, None]
        bcol_ref[:, b:b + 1] = bcol[:, None]


@functools.partial(jax.jit, static_argnames=("interpret",))
def masked_topk_blocks(
    score_static: jnp.ndarray,  # [P, N] f32 — statics already folded (NEG)
    task_req: jnp.ndarray,      # [P, R] f32 — InitResreq of the bucket rows
    idle: jnp.ndarray,          # [N, R] f32 — cycle-start budgets
    releasing: jnp.ndarray,     # [N, R] f32
    rows: jnp.ndarray,          # [P] i32 — GLOBAL task row per bucket slot
    quanta: jnp.ndarray,        # [R] f32
    n0=0,                       # global node offset of this block (i32)
    interpret: bool = False,
):
    """The fused candidate-build head for the KB_TOPK compaction: one VMEM
    pass emits the masked sort-key plane ``skey`` [P, N] i32 plus the
    per-``TOPK_BLOCK`` two-key winner triples (``bval``/``bhash``/``bcol``
    [P, N/TOPK_BLOCK]) without materializing the fit matrices in HBM.  The
    XLA extraction loop (ops.assignment.lex_topk) consumes ``skey``; the
    triples prove the kernel computes the exact phase-1 reduction (the
    parity test cross-checks them).  P must be a multiple of the task tile
    and N of the node tile, like the round-head kernel."""
    P, N = score_static.shape
    R = task_req.shape[1]
    tile_t = min(TASK_TILE, P)
    tile_n = min(NODE_TILE, N)
    grid = (P // tile_t, N // tile_n)
    NB = tile_n // TOPK_BLOCK
    q2 = quanta.reshape(1, R).astype(jnp.float32)
    offs = jnp.asarray([n0], jnp.int32).reshape(1, 1)

    skey, bval, bhash, bcol = pl.pallas_call(
        _topk_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_t, tile_n), lambda i, j: (i, j)),  # score
            pl.BlockSpec((tile_t, R), lambda i, j: (i, 0)),       # req
            pl.BlockSpec((tile_n, R), lambda i, j: (j, 0)),       # idle
            pl.BlockSpec((tile_n, R), lambda i, j: (j, 0)),       # releasing
            pl.BlockSpec((tile_t, 1), lambda i, j: (i, 0)),       # rows
            pl.BlockSpec((1, R), lambda i, j: (0, 0)),            # quanta
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),            # offsets
        ],
        out_specs=[
            pl.BlockSpec((tile_t, tile_n), lambda i, j: (i, j)),  # skey
            pl.BlockSpec((tile_t, NB), lambda i, j: (i, j)),      # bval
            pl.BlockSpec((tile_t, NB), lambda i, j: (i, j)),      # bhash
            pl.BlockSpec((tile_t, NB), lambda i, j: (i, j)),      # bcol
        ],
        out_shape=[
            jax.ShapeDtypeStruct((P, N), jnp.int32),
            jax.ShapeDtypeStruct((P, N // TOPK_BLOCK), jnp.int32),
            jax.ShapeDtypeStruct((P, N // TOPK_BLOCK), jnp.int32),
            jax.ShapeDtypeStruct((P, N // TOPK_BLOCK), jnp.int32),
        ],
        interpret=interpret,
    )(
        score_static.astype(jnp.float32),
        task_req.astype(jnp.float32),
        idle.astype(jnp.float32),
        releasing.astype(jnp.float32),
        rows.astype(jnp.int32)[:, None],
        q2,
        offs,
    )
    return skey, bval, bhash, bcol


@functools.partial(jax.jit, static_argnames=("interpret",))
def masked_best_node(
    score: jnp.ndarray,       # [T, N] f32
    static_ok: jnp.ndarray,   # [T, N] bool
    task_req: jnp.ndarray,    # [T, R] f32 — InitResreq
    idle: jnp.ndarray,        # [N, R] f32
    releasing: jnp.ndarray,   # [N, R] f32
    pending: jnp.ndarray,     # [T] bool
    quanta: jnp.ndarray,      # [R] f32
    interpret: bool = False,
):
    """(best [T] i32, has [T] bool, chose_idle [T] bool) — the fused round
    head. T must be a multiple of the task tile and N of the node tile
    (snapshot buckets guarantee both at scale; callers pad otherwise)."""
    best, val, _, chose = masked_best_node_raw(
        score, static_ok, task_req, idle, releasing, pending, quanta,
        interpret=interpret,
    )
    return best, val > NEG, chose
