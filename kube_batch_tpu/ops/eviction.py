"""Device-resident eviction solve — reclaim + preempt as compiled auctions.

The reference's reclaim (actions/reclaim/reclaim.go:107-199) and preempt
phase 1 (actions/preempt/preempt.go:110-137,180-260) are host loops:
per pending "claimant" task, scan every node, collect Running victims passing
the tier-intersected Evictable verdicts (conformance ∩ gang ∩ drf/proportion,
session_plugins.go:100-182), evict until the claimant's request is covered,
then pipeline the claimant onto the freed (Releasing) resources.

Here both run as bidding rounds on device, sharing one kernel:

  round:  eligible claimants bid for their best feasible node, where
          "feasible" means the node carries enough evictable victim resource
          for the claimant's queue (cross-queue victims for reclaim,
          same-queue/other-job for preempt). One claimant — the lowest
          virtual-rank bidder — wins each node per round (evictions are far
          sparser than allocations, so per-round node exclusivity costs
          little wall-clock and keeps victim accounting exact).
  pick:   per node, victims are taken in reverse task order (the reference's
          victimsQueue pops !TaskOrderFn, preempt.go:219-224) until the
          winner's InitResreq is covered — a segmented prefix scan.
  caps:   global constraints are then enforced exactly: gang slack (a job
          never drops below MinAvailable, gang.go:71-94), proportion queue
          budget (a victim queue never drops below deserved,
          proportion.go:171-196), and DRF share dominance for preempt
          (drf.go:85-110). Victims dropped by a cap can break a claim's
          coverage; such claims cancel entirely — evictions never happen
          without a covered placement (reclaim.go:150-163 validates victim
          sufficiency before evicting).

The host action replays the result through session verbs, re-validating each
claim with the real plugin callbacks on the (small) selected sets — the
device narrows O(tasks × nodes × victims) to O(claims), the host stays
authoritative for semantics.

Memory footprint: the bidding rounds still score FULL [tasks, nodes] bid
planes, which blows the v5e HBM budget at the 1M×100k north star — the
tier-C HBM audit (analysis/hbm_audit.py) flags every evict variant under
KBT201/KBT202 and waives it in ``HBM_ALLOWLIST`` under ROADMAP 1.(1);
the sparse rebuild (candidate table over per-(queue, node) capacity keys,
with re-rank-on-growth since evictions grow capacity within a pass)
deletes those waivers, and the audit fails on the stale entries if this
file gets fixed without removing them.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kube_batch_tpu.api.snapshot import DeviceSnapshot
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.ops import fairness, ordering
from kube_batch_tpu.ops.assignment import _best_node, _tie_break_hash
from kube_batch_tpu.ops.feasibility import fits, static_predicates
from kube_batch_tpu.ops.ordering import segmented_prefix
from kube_batch_tpu.ops.scoring import ScoreWeights, score_matrix

NEG = jnp.float32(-3.0e38)
BIG = jnp.int32(1 << 30)
SHARE_DELTA = 1e-6  # drf.go:23 shareDelta


class EvictConfig(NamedTuple):
    """Static eviction-solve configuration (jit cache key).

    Victim gates mirror the reference's TIERED Evictable dispatch
    (session_plugins.go:100-182): only plugins in the first tier containing
    any voting plugin constrain victims — under the default two-tier conf
    (gang+conformance in tier 1, drf/proportion in tier 2) the drf/proportion
    victim vetoes never bind. Ordering flags are independent: they shape the
    claimant rank / overused gate / commit gate like the allocate solve."""

    mode: str = "reclaim"     # "reclaim" (cross-queue) | "preempt" (same-queue)
    rounds: int = 8
    # reclaim-only: skip claimants that fit free Idle (allocate places them
    # later this cycle) — set by the action layer ONLY when allocate is
    # actually configured after reclaim and host predicates are exact
    idle_gate: bool = False
    # ordering / gating (claimant side)
    gang: bool = True
    drf: bool = True
    proportion: bool = True
    # victim gates (first voting tier only)
    victim_gang: bool = True
    victim_conformance: bool = True
    victim_proportion: bool = False
    victim_drf: bool = False
    weights: ScoreWeights = ScoreWeights()


class EvictResult(NamedTuple):
    claim_node: jnp.ndarray       # [T] i32 — node the claimant pipelines onto, -1
    evicted: jnp.ndarray          # [T] bool — task chosen as victim
    victim_claimant: jnp.ndarray  # [T] i32 — claimant task index a victim serves, -1


# ---- the victim machinery shared by every eviction path ------------------
# (the committed solves below AND the query plane's hypothetical probe,
# ops/probe.py _evict_probe — one set of lines, so the probe cannot drift
# from the solve; tests/test_whatif.py's fixture equivalence is the
# behavioral check, this sharing is the structural one)


def victim_running(snap: DeviceSnapshot) -> jnp.ndarray:
    """[T] bool — base victim eligibility: valid RUNNING tasks on a node
    whose job is in-session.  job_valid gates victims too: the columnar
    snapshot's row space carries tasks of jobs OUTSIDE the session (dropped
    at open / unknown queue), which the per-session object snapshot never
    contained — their rows' job metadata is stale scratch and the host
    decode would drop them anyway, wasting the whole claim."""
    return (
        snap.task_valid
        & (snap.task_status == int(TaskStatus.RUNNING))
        & (snap.task_node >= 0)
        & snap.job_valid[snap.task_job]
    )


def gang_slack0(snap: DeviceSnapshot, config: EvictConfig) -> jnp.ndarray:
    """[J] i32 — evictions a job can absorb while staying ≥ MinAvailable;
    MinAvailable ≤ 1 jobs are not gangs — always evictable (gang.go:71-94).
    BIG everywhere when the gang victim gate is off."""
    J = snap.job_min_avail.shape[0]
    if not config.victim_gang:
        return jnp.full(J, BIG)
    return jnp.where(
        snap.job_min_avail > 1, snap.job_ready - snap.job_min_avail, BIG
    )


def claim_winners(has, best, rank, n_nodes: int):
    """One winner per node — the lowest-rank bidder.  The claimant axis C
    is whatever the caller bids with: the full task axis (the solves) or a
    speculative gang's members (the probe).  Returns
    (is_winner [C] bool, winner_idx [N] i32 — claimant index or -1,
    node_has_claim [N] bool)."""
    N = n_nodes
    idx = jnp.arange(has.shape[0], dtype=jnp.int32)
    bid_node = jnp.where(has, best, N)
    win_rank = (
        jnp.full(N + 1, BIG, jnp.int32)
        .at[bid_node].min(jnp.where(has, rank, BIG))
    )[:N]
    is_winner = has & (rank == win_rank[jnp.clip(best, 0, N - 1)])
    winner_idx = (
        jnp.full(N, -1, jnp.int32)
        .at[jnp.where(is_winner, best, 0)]
        .max(jnp.where(is_winner, idx, -1))
    )
    return is_winner, winner_idx, winner_idx >= 0


def pick_victims(snap: DeviceSnapshot, vmask, node_req, node_has_claim,
                 victim_rank, slack_rem, config: EvictConfig, n_nodes: int,
                 *, qbudget_rem=None, task_queue=None):
    """Victim selection for one round's claimed nodes: victims pop in
    reverse task order until the winner's request is covered (the
    reference's victimsQueue pops !TaskOrderFn, preempt.go:219-224 — a
    segmented prefix scan), then the exact global caps — gang slack (a job
    never drops below MinAvailable, gang.go:71-94) and, when
    ``qbudget_rem``/``task_queue`` are given, the proportion queue budget
    (proportion.go:171-196) — then the coverage recheck: victims dropped by
    a cap can break a claim's coverage, and such claims cancel entirely
    (evictions never happen without a covered placement,
    reclaim.go:150-163).  Returns (final_take [T] bool, covered [N] bool)."""
    T = snap.task_req.shape[0]
    N = n_nodes
    J = snap.job_min_avail.shape[0]
    Q = snap.queue_weight.shape[0]
    vn = jnp.clip(snap.task_node, 0, N - 1)

    seg = jnp.where(vmask, snap.task_node, N)
    order = ordering.sort_by_segment_then_rank(seg, victim_rank, N + 1)
    seg_s = seg[order]
    req_s = jnp.where(vmask[order, None], snap.task_resreq[order], 0.0)
    is_start = jnp.concatenate([jnp.array([True]), seg_s[1:] != seg_s[:-1]])
    prefix = segmented_prefix(req_s, is_start)                   # exclusive
    need_s = node_req[jnp.clip(seg_s, 0, N - 1)]
    covered_before = jnp.all(prefix >= need_s - snap.quanta, axis=-1)
    take_s = vmask[order] & (seg_s < N) & ~covered_before
    take = jnp.zeros(T, bool).at[order].set(take_s)

    if config.victim_gang:
        # position among taken victims of the same job < remaining slack
        jorder = ordering.sort_by_segment_then_rank(
            jnp.where(take, snap.task_job, J), victim_rank, J + 1
        )
        js = jnp.where(take, snap.task_job, J)[jorder]
        j_start = jnp.concatenate([jnp.array([True]), js[1:] != js[:-1]])
        pos = segmented_prefix(
            take[jorder].astype(jnp.float32)[:, None], j_start
        )[:, 0].astype(jnp.int32)
        keep_j = take[jorder] & (pos < slack_rem[jnp.clip(js, 0, J - 1)])
        take = jnp.zeros(T, bool).at[jorder].set(keep_j)
    if qbudget_rem is not None:
        # cumulative eviction per victim queue ≤ remaining budget
        qorder = ordering.sort_by_segment_then_rank(
            jnp.where(take, task_queue, Q), victim_rank, Q + 1
        )
        qs = jnp.where(take, task_queue, Q)[qorder]
        q_start = jnp.concatenate([jnp.array([True]), qs[1:] != qs[:-1]])
        qreq_s = jnp.where(take[qorder, None], snap.task_resreq[qorder], 0.0)
        qprefix = segmented_prefix(qreq_s, q_start)
        fits_budget = jnp.all(
            qprefix + qreq_s
            <= qbudget_rem[jnp.clip(qs, 0, Q - 1)] + snap.quanta,
            axis=-1,
        )
        take = jnp.zeros(T, bool).at[qorder].set(take[qorder] & fits_budget)

    # coverage recheck after caps; cancel uncovered claims
    got = jax.ops.segment_sum(
        jnp.where(take[:, None], snap.task_resreq, 0.0),
        jnp.where(take, snap.task_node, N),
        num_segments=N + 1,
    )[:N]
    covered = node_has_claim & jnp.all(got >= node_req - snap.quanta, axis=-1)
    return take & covered[vn], covered


def local_evict_bids(snap: DeviceSnapshot, config: EvictConfig):
    """Build the single-program bids head: ``bids(victim_ok, claimant_ok)
    -> (best, has)`` — the per-round [T, N]-scale victim-capacity /
    feasibility / masked-argmax block, computed from the full matrices in
    one logical program.  The shard_map path substitutes the explicit-
    collective block head (parallel/shard_solve.py); the rest of the solve
    is the SHARED :func:`evict_rounds` machinery."""
    T, R = snap.task_req.shape
    N = snap.node_alloc.shape[0]
    Q = snap.queue_weight.shape[0]
    preempt = config.mode == "preempt"
    task_queue = snap.job_queue[snap.task_job]                      # [T]
    static_ok = static_predicates(snap)
    score = score_matrix(snap, config.weights)
    tie_hash = _tie_break_hash(T, N)

    def bids(victim_ok, claimant_ok):
        # ---- per-(queue, node) evictable capacity --------------------
        vreq = jnp.where(victim_ok[:, None], snap.task_resreq, 0.0)
        vnode = jnp.where(victim_ok, snap.task_node, N)
        tot_v = jax.ops.segment_sum(vreq, vnode, num_segments=N + 1)[:N]  # [N, R]
        per_qn = jnp.zeros((Q, N, R), jnp.float32).at[
            task_queue, jnp.clip(snap.task_node, 0, N - 1)
        ].add(vreq)
        if preempt:
            cap = per_qn                      # same-queue victims (own job
            #                                   over-counted; corrected in
            #                                   the shared victim selection)
        else:
            cap = tot_v[None] - per_qn        # cross-queue victims

        # ---- bids ----------------------------------------------------
        # feasible[t, n] iff claimant t's InitResreq fits cap[queue_t, n].
        # Each claimant's queue-specific capacity row is gathered with a
        # one-hot matmul over the queue axis ([T,Q]@[Q,N] on the MXU, one
        # per resource dim): compile cost and kernel count stay flat as the
        # queue bucket grows, unlike the unrolled per-queue fits pass this
        # replaces (Q=128 would mean 128 full [T,N] passes). The one-hot
        # contraction selects exactly one row, so it is exact, not a sum.
        onehot_q = (task_queue[:, None] == jnp.arange(Q)[None, :]).astype(
            jnp.float32
        )                                                            # [T, Q]
        # a queue index outside [0, Q) gathers an all-zero capacity row from
        # the one-hot contraction; a near-zero request could still pass the
        # epsilon compare against it — make such tasks categorically
        # infeasible rather than relying on claimant_ok to exclude them
        feas = static_ok & claimant_ok[:, None]
        feas &= ((task_queue >= 0) & (task_queue < Q))[:, None]
        for r in range(R):  # R is the small static resource dim
            # HIGHEST precision: TPU default matmul truncates the f32
            # capacity operand to bf16 (~2^-8 relative), which at byte-unit
            # memory magnitudes (~1e11) dwarfs the 10 MiB quantum the
            # epsilon compare below relies on — exact f32 keeps the one-hot
            # contraction a true row selection
            # kbt: allow[KBT005] trace-time unroll over the small static
            # resource dim R inside jit — R fused matmuls in the compiled
            # graph, zero per-iteration host dispatch
            cap_tr = jnp.matmul(
                onehot_q, cap[:, :, r], precision=jax.lax.Precision.HIGHEST
            )                                                        # [T, N]
            feas &= snap.task_req[:, r, None] <= cap_tr + snap.quanta[r]
        masked = jnp.where(feas, score, NEG)
        # tie-hash spread: without it every equal-score claimant bids the
        # same argmax node and only one claim lands per round
        return _best_node(masked, tie_hash)

    return bids


def local_idle_fit_any(snap: DeviceSnapshot):
    """[T] bool — task fits some schedulable node's cycle-start Idle (the
    reclaim idle gate's [T, N] probe; the shard_map path computes it
    blockwise with a psum over the node shards)."""
    return jnp.any(
        fits(snap.task_req, snap.node_idle, snap.quanta)
        & static_predicates(snap),
        axis=1,
    )


def evict_rounds(
    snap: DeviceSnapshot,
    config: EvictConfig,
    bids_fn,
    fits_idle_any=None,
    n_nodes=None,
    claimant_mask=None,
) -> EvictResult:
    """The eviction machinery shared by every solve path: victim/claimant
    eligibility, ranks, winner-per-node selection, victim picking, global
    caps, coverage, and the commit gate — everything that reads only the
    task/job/queue-axis vectors (replicated under shard_map).  The [T, N]-
    scale bids come from ``bids_fn``; ``fits_idle_any`` is the idle-gate
    probe (required iff ``config.idle_gate`` on reclaim).  ``n_nodes``
    overrides the GLOBAL node count when ``snap``'s node arrays are
    shard-local blocks (the shard_map body).  ``claimant_mask`` ([T] bool)
    restricts claimants beyond the standard eligibility — callers probing
    a SUBSET of the pending work (a single job's what-if, a drained queue)
    share this machinery instead of forking it."""
    T, R = snap.task_req.shape
    N = n_nodes if n_nodes is not None else snap.node_alloc.shape[0]
    J = snap.job_min_avail.shape[0]
    Q = snap.queue_weight.shape[0]
    preempt = config.mode == "preempt"

    task_queue = snap.job_queue[snap.task_job]                      # [T]
    running = victim_running(snap)
    subrank = ordering.task_subranks(snap.task_prio, snap.task_creation)
    # victims pop in reverse task order (!TaskOrderFn, preempt.go:219-224)
    victim_rank = ordering.multisort_ranks([snap.task_prio, -snap.task_creation])

    deserved = fairness.proportion_deserved(
        snap.total, snap.queue_weight, snap.queue_request, snap.queue_valid
    )
    slack0 = gang_slack0(snap, config)
    # proportion budget: resource a queue can lose while staying ≥ deserved
    qbudget0 = jnp.maximum(snap.queue_alloc - deserved, 0.0)        # [Q, R]

    claimant_base = (
        snap.task_pending
        & snap.task_valid
        & snap.job_valid[snap.task_job]
        & snap.job_schedulable[snap.task_job]
    )
    if claimant_mask is not None:
        claimant_base &= claimant_mask
    if config.idle_gate and not preempt:
        # IMPROVEMENT over reclaim.go (which never looks at Idle and will
        # evict cross-queue victims for a task free capacity could satisfy):
        # a claimant that fits some schedulable node's cycle-start Idle is
        # left to the allocate action — eviction is for capacity that must
        # be TAKEN, not capacity that's already free.  The action layer
        # enables this only when allocate really runs after reclaim;
        # claimants with host-only constraints are exempt (their device fit
        # is approximate — allocate's host re-check might reject the node
        # and strand them).  Preempt never gates: it runs after allocate,
        # so its claimants already failed idle placement this cycle.
        claimant_base &= ~(fits_idle_any & ~snap.task_needs_host)

    def round_body(state):
        claim_node, evicted, victim_claimant, i, _ = state
        placed = claim_node >= 0

        # ---- live fairness state -------------------------------------
        placed_req = jnp.where(placed[:, None], snap.task_resreq, 0.0)
        evicted_req = jnp.where(evicted[:, None], snap.task_resreq, 0.0)
        job_delta = jax.ops.segment_sum(
            placed_req - evicted_req, snap.task_job, num_segments=J
        )
        job_alloc_now = snap.job_allocated + job_delta
        queue_alloc_now = snap.queue_alloc + jax.ops.segment_sum(
            job_delta, snap.job_queue, num_segments=Q
        )
        evict_cnt = jax.ops.segment_sum(
            evicted.astype(jnp.int32), snap.task_job, num_segments=J
        )
        slack_rem = slack0 - evict_cnt                               # [J]
        q_evicted = jax.ops.segment_sum(
            evicted_req, task_queue, num_segments=Q
        )
        qbudget_rem = qbudget0 - q_evicted                           # [Q, R]
        pipe_cnt = jax.ops.segment_sum(
            placed.astype(jnp.int32), snap.task_job, num_segments=J
        )
        job_pipelined_now = (snap.job_ready + pipe_cnt) >= snap.job_min_avail
        job_need = jnp.maximum(
            snap.job_min_avail - (snap.job_ready + pipe_cnt), 0
        )

        # ---- victim eligibility --------------------------------------
        victim_ok = running & ~evicted
        if config.victim_conformance:
            victim_ok &= ~snap.task_critical
        if config.victim_gang:
            victim_ok &= slack_rem[snap.task_job] > 0
        if config.victim_proportion and not preempt:
            # victim's resreq must fit its queue's remaining budget over the
            # semantic dims (proportion.go:171-196 LessEqual has no pods)
            sem = fairness.semantic_mask(R)
            victim_ok &= jnp.all(
                (snap.task_resreq <= qbudget_rem[task_queue] + snap.quanta)[..., sem],
                axis=-1,
            )
        if preempt and config.victim_drf:
            # victim-job share after eviction must stay ≥ some preemptor's
            # share; the exact pairwise test happens at selection time —
            # here only the per-victim post-eviction share is prepared
            victim_post_share = fairness.dominant_share(
                job_alloc_now[snap.task_job] - snap.task_resreq, snap.total
            )
        else:
            victim_post_share = jnp.zeros(T, jnp.float32)

        # ---- claimant eligibility + rank -----------------------------
        claimant_ok = claimant_base & ~placed
        if config.proportion and not preempt:
            # reclaim skips overused claimant queues (reclaim.go:112-116)
            q_overused = fairness.overused(deserved, queue_alloc_now, snap.quanta)
            claimant_ok &= ~q_overused[task_queue]
        rank = ordering.virtual_task_ranks(
            claimant_ok,
            snap.task_resreq,
            snap.task_job,
            task_queue,
            subrank,
            snap.job_prio,
            job_pipelined_now,
            snap.job_creation,
            job_alloc_now,
            queue_alloc_now,
            deserved,
            snap.total,
            job_need,
            gang_enabled=config.gang,
            drf_enabled=config.drf,
            proportion_enabled=config.proportion,
        )

        # ---- victim-capacity bids ([T, N]-scale, path-specific head) -
        best, has = bids_fn(victim_ok, claimant_ok)
        has &= claimant_ok

        # ---- one winner per node: lowest claimant rank ---------------
        is_winner, winner_task, node_has_claim = claim_winners(
            has, best, rank, N
        )
        node_req = jnp.where(
            node_has_claim[:, None], snap.task_req[jnp.maximum(winner_task, 0)], jnp.inf
        )                                                            # [N, R]
        winner_job = jnp.where(
            node_has_claim, snap.task_job[jnp.maximum(winner_task, 0)], -1
        )                                                            # [N]
        winner_queue = jnp.where(
            node_has_claim, task_queue[jnp.maximum(winner_task, 0)], -1
        )
        if preempt and config.victim_drf:
            winner_post_share = fairness.dominant_share(
                job_alloc_now[jnp.maximum(winner_job, 0)]
                + snap.task_resreq[jnp.maximum(winner_task, 0)],
                snap.total,
            )                                                        # [N]

        # ---- victims (shared machinery: selection + caps + coverage) -
        vn = jnp.clip(snap.task_node, 0, N - 1)
        vmask = victim_ok & node_has_claim[vn]
        if preempt:
            # same queue, different job (preempt.go:113-121)
            vmask &= (task_queue == winner_queue[vn]) & (snap.task_job != winner_job[vn])
            if config.victim_drf:
                # preemptor's post-allocation share must stay ≤ victim's
                # post-eviction share (drf.go:85-110)
                vmask &= winner_post_share[vn] <= victim_post_share + SHARE_DELTA
        else:
            vmask &= task_queue != winner_queue[vn]                  # cross-queue
        proportion_cap = config.victim_proportion and not preempt
        final_take, covered = pick_victims(
            snap, vmask, node_req, node_has_claim, victim_rank, slack_rem,
            config, N,
            qbudget_rem=qbudget_rem if proportion_cap else None,
            task_queue=task_queue if proportion_cap else None,
        )

        # ---- apply ---------------------------------------------------
        new_claim = is_winner & covered[jnp.clip(best, 0, N - 1)]
        claim_node = jnp.where(new_claim, best, claim_node)
        evicted = evicted | final_take
        victim_claimant = jnp.where(
            final_take, winner_task[vn], victim_claimant
        )
        return (claim_node, evicted, victim_claimant, i + 1, jnp.any(new_claim))

    def round_cond(state):
        *_, i, progress = state
        return (i < config.rounds) & progress

    claim_node, evicted, victim_claimant, _, _ = jax.lax.while_loop(
        round_cond,
        round_body,
        (
            jnp.full(T, -1, jnp.int32),
            jnp.zeros(T, bool),
            jnp.full(T, -1, jnp.int32),
            jnp.int32(0),
            jnp.bool_(True),
        ),
    )

    if preempt and config.gang:
        # commit gate: the preemptor job must reach Pipelined
        # (ready + pipelined ≥ MinAvailable, preempt.go:127-137); claims of
        # failing jobs revert, and their victims un-evict (Statement.Discard)
        pipe_cnt = jax.ops.segment_sum(
            (claim_node >= 0).astype(jnp.int32), snap.task_job, num_segments=J
        )
        job_ok = (snap.job_ready + pipe_cnt) >= snap.job_min_avail
        revert = (claim_node >= 0) & ~job_ok[snap.task_job]
        claim_node = jnp.where(revert, -1, claim_node)
        victim_revert = (victim_claimant >= 0) & revert[
            jnp.clip(victim_claimant, 0, T - 1)
        ]
        evicted &= ~victim_revert
        victim_claimant = jnp.where(victim_revert, -1, victim_claimant)

    return EvictResult(
        claim_node=claim_node, evicted=evicted, victim_claimant=victim_claimant
    )


@partial(jax.jit, static_argnames=("config",))
def evict_solve(snap: DeviceSnapshot, config: EvictConfig) -> EvictResult:
    fia = None
    if config.idle_gate and config.mode != "preempt":
        fia = local_idle_fit_any(snap)
    return evict_rounds(snap, config, local_evict_bids(snap, config), fia)
