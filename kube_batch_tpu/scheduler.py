"""Scheduler — the L1 loop (pkg/scheduler/scheduler.go:38-102).

Holds the cache, the configured action pipeline, and the plugin tiers; each
tick opens a session (snapshot + plugin open), executes the actions in conf
order, and closes the session (status writeback). `run_forever` is the
wait.Until(runOnce, period) analog — and, by default, its PIPELINED
successor: the cycle is an explicitly staged pipeline

    ingest drain → delta session open → device solve → host replay
                 → status derive ║ writeback (status flush + binder drain)

where everything left of ║ runs on the cycle thread and the writeback
stage runs on a single worker, double-buffered: cycle N+1's ingest drain,
delta open, and solve dispatch proceed while cycle N's status flush and
async binder drain complete (the PR 3 fit-error-histogram overlap inside
allocate is the in-cycle instance of the same mechanism).  Cycle
triggering is event-driven: the cache's dirty-version advance wakes a
condition variable, so an arrival burst schedules immediately instead of
waiting out the reference's fixed 1 s tick, while an idle cluster ticks at
the slow floor.  Knobs: ``KB_PIPELINE=0`` restores the serial
wait.Until loop (the bit-exactness oracle), ``KB_PERIOD_MIN`` pins the
minimum spacing between cycle starts (rate floor for bursts; unset, the
floor ADAPTS to an EWMA of the cycle's own measured cost — see
:meth:`Scheduler._note_cycle_cost`), ``KB_PERIOD_MAX`` the idle tick
period (default: the schedule period)."""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from kube_batch_tpu import actions as _actions  # registers actions
from kube_batch_tpu import plugins as _plugins  # registers plugin builders
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.framework.conf import SchedulerConfiguration, load_scheduler_conf
from kube_batch_tpu.framework.interface import Action, get_action
from kube_batch_tpu.envutil import env_flag
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu import metrics
from kube_batch_tpu.obs.alerts import alerts_of
from kube_batch_tpu.obs.trace import tracer_of
from kube_batch_tpu.utils import telemetry

logger = logging.getLogger("kube_batch_tpu")


class CycleTrigger:
    """Event-driven cycle pacing: the cache's dirty-version advance (and the
    staged-ingest arrival hook) call :meth:`notify`; the loop waits on the
    condition variable between cycles.  A pending signal — even one raised
    MID-cycle — wakes the next cycle as soon as the ``min_period`` rate
    floor allows; with no signal the loop idles until ``max_period`` since
    the last cycle start (the reference's 1 s tick becomes the slow floor).

    Deadline arithmetic reads the INJECTED clock (the Scheduler's clock
    seam) so tests can pace it; the blocking itself is the condition
    variable's (real-time) wait, re-armed against the injected deadline
    each lap."""

    def __init__(self, clock=None):
        self.clock = clock if clock is not None else time
        # the guard lock is created HERE (not Condition's default, which
        # would be born inside the threading module) so the runtime lockdep
        # checker tracks it: notify() under the cache's big lock records the
        # big→trigger edge, and any reverse nesting would report
        self._cond = threading.Condition(lock=threading.Lock())
        self._pending = False

    def notify(self) -> None:
        """Wake the loop (never blocks; safe from any thread, including
        under the cache's locks — the condition guard is a leaf)."""
        with self._cond:
            self._pending = True
            self._cond.notify_all()

    def poll(self) -> bool:
        """Consume a pending signal without waiting (the sim's virtual-time
        pacing asks 'would the trigger fire now?' instead of blocking)."""
        with self._cond:
            pending, self._pending = self._pending, False
            return pending

    def wait_for_work(self, cycle_start: float, min_period: float,
                      max_period: float) -> str:
        """Block until the next cycle should start; returns the wake reason
        (``"ingest"`` — signalled arrival churn; ``"floor"`` — the idle
        period elapsed).  The rate floor is enforced first: bursts coalesce
        into one cycle per ``min_period``, so a hot ingest stream cannot
        busy-spin the solve."""
        clock = self.clock
        floor_rem = min_period - (clock.monotonic() - cycle_start)
        if floor_rem > 0:
            clock.sleep(floor_rem)
        deadline = cycle_start + max_period
        with self._cond:
            while not self._pending:
                rem = deadline - clock.monotonic()
                if rem <= 0:
                    return "floor"
                self._cond.wait(rem)
            self._pending = False
            return "ingest"


class Scheduler:
    def __init__(
        self,
        cache: SchedulerCache,
        conf: Optional[SchedulerConfiguration] = None,
        conf_path: Optional[str] = None,
        schedule_period: float = 1.0,
        on_cycle_end=None,
        clock=None,
    ):
        self.cache = cache
        # injected time source for the loop's pacing (monotonic() + sleep());
        # defaults to the wall clock. The virtual-time simulator
        # (kube_batch_tpu/sim) injects its VirtualClock so cycle pacing is
        # simulated time, while the latency *metrics* below stay wall-clock
        # (they measure real compute, not scenario time).
        self.clock = clock if clock is not None else time
        self.conf = conf if conf is not None else load_scheduler_conf(conf_path)
        # resolve actions at construction — unknown names raise (util.go:63-70)
        self.actions: List[Action] = [get_action(n) for n in self.conf.actions]
        self.schedule_period = schedule_period
        self.on_cycle_end = on_cycle_end  # e.g. state-file save (persistence.py)
        self._stop = False
        # conf hot-reload (the reference's stated-but-unimplemented design,
        # doc/design/plugin-conf.md — its code re-reads only at startup,
        # scheduler.go:70-83): when constructed from a path, the file's
        # mtime is checked each cycle and a changed, VALID conf swaps in at
        # the cycle boundary; a broken edit logs and keeps the running conf
        self._conf_path = conf_path if conf is None else None
        # NOTE: __init__ loaded the conf above, so this stat runs after the
        # load — an edit in that window would be lost. Re-stat BEFORE
        # re-reading in _maybe_reload_conf closes the window for the loop;
        # here, force one reload check on the first cycle instead.
        self._conf_mtime: Optional[float] = None
        # soft per-cycle time budget (seconds, KB_CYCLE_BUDGET; 0 = off):
        # a cycle that already overran it when the action pipeline finishes
        # sheds the close-time status flush to the cache's async pool and
        # keeps ticking, instead of stalling the loop in egress writeback
        self.cycle_budget = float(os.environ.get("KB_CYCLE_BUDGET", "0") or 0)
        # event-driven pipelined loop (the default; KB_PIPELINE=0 restores
        # the serial wait.Until loop as the bit-exactness oracle)
        self.pipelined = env_flag("KB_PIPELINE", True)
        # cycle-start spacing: bursts coalesce to one cycle per min_period;
        # an idle cluster ticks every max_period (default: today's period).
        # The floor is ADAPTIVE by default: it tracks an EWMA of the
        # cycle's own measured cost (_note_cycle_cost), so the coalescing
        # window follows the solve instead of a static 50 ms — a 200 ms
        # solve shouldn't be re-triggered every 50 ms, and a 10 ms cycle
        # shouldn't wait out 50.  Setting KB_PERIOD_MIN pins the static
        # value back (the escape hatch, like KB_PIPELINE=0).
        raw_min = os.environ.get("KB_PERIOD_MIN", "")
        self.min_period_pinned = bool(raw_min.strip())
        self.min_period = float(raw_min or min(0.05, schedule_period))
        self.max_period = float(
            os.environ.get("KB_PERIOD_MAX", "") or schedule_period
        )
        # EWMA of measured cycle cost (seconds) — the adaptive floor's p50
        # estimator; None until the first pipelined cycle completes
        self.cycle_cost_ewma: Optional[float] = None
        self.trigger = CycleTrigger(clock=self.clock)
        # the cycle tracing plane (kube_batch_tpu/obs): per-cache span
        # recorder + flight-recorder ring; virtual-time stamping follows
        # the injected clock so sim traces attribute on the report's clock
        self.tracer = tracer_of(cache, clock=self.clock)
        # the writeback stage: one worker, double-buffered — at most one
        # cycle's (status flush + binder drain) in flight while the next
        # cycle computes; _await_writeback is the stage barrier
        self._wb_pool: Optional[ThreadPoolExecutor] = None
        self._wb_future = None

    def _stat_conf(self) -> Optional[float]:
        if not self._conf_path:
            return None
        try:
            return os.path.getmtime(self._conf_path)
        except OSError:
            return None

    def _maybe_reload_conf(self) -> None:
        if not self._conf_path:
            return
        mtime = self._stat_conf()
        if mtime is None or mtime == self._conf_mtime:
            return
        try:
            conf = load_scheduler_conf(self._conf_path)
            # resolve EVERYTHING the conf names before swapping: an unknown
            # action or plugin must reject the edit here, not crash every
            # subsequent open_session
            actions = [get_action(n) for n in conf.actions]
            from kube_batch_tpu.framework.interface import get_plugin_builder

            for tier in conf.tiers:
                for opt in tier.plugins:
                    get_plugin_builder(opt.name)
        except Exception as e:  # noqa: BLE001 — keep the running conf
            logger.error("scheduler conf reload failed (%s); keeping the "
                         "running configuration", e)
            self._conf_mtime = mtime  # don't re-log every cycle
            return
        if conf.actions != self.conf.actions or conf.tiers != self.conf.tiers:
            logger.info("scheduler conf hot-reloaded: actions=%s", conf.actions)
        self.conf, self.actions = conf, actions
        self._conf_mtime = mtime

    def run_once(self) -> None:
        """(scheduler.go:88-102) — the serial cycle: every stage inline,
        binder drain at the end, deterministic post-cycle state.  The
        pipelined loop runs the same stages via :meth:`run_once_pipelined`;
        this form stays the bit-exactness oracle (KB_PIPELINE=0)."""
        self._cycle(pipelined=False)

    def run_once_pipelined(self) -> None:
        """One pipelined cycle: staged ingest drains under one lock, the
        session opens/solves/replays on this thread, the close DERIVES the
        status pass synchronously but hands the egress half (status flush +
        async binder drain) to the writeback worker — overlapped with the
        caller's next cycle.  :meth:`drain_pipeline` (or the next cycle's
        stage barrier) joins it."""
        self._cycle(pipelined=True)

    def _cycle(self, pipelined: bool) -> None:
        tracer = self.tracer
        # the cycle's trace record: every stage below runs inside a span;
        # the pipelined writeback attaches to THIS record from its worker
        # thread, so the exported trace shows the overlap structure
        record = tracer.begin_cycle("pipelined" if pipelined else "serial")
        try:
            self._cycle_body(pipelined, record)
        finally:
            tracer.end_cycle()

    def _cycle_body(self, pipelined: bool, record) -> None:
        tracer = self.tracer
        if pipelined:
            # ingest stage: everything the watch/ingest threads staged since
            # the last cycle applies under ONE cache-lock acquisition —
            # BEFORE the resync drain, so repair decisions see the freshest
            # pod store
            drain = getattr(self.cache, "drain_staged_ingest", None)
            if drain is not None:
                with tracer.span("ingest_drain") as sp:
                    n_staged = drain()
                    sp.set(events=n_staged)
                metrics.register_staged_ingest(n_staged)
        # drain the resync queue at the cycle boundary: the background repair
        # tick (cache.go:563-581) skips while an exclusive session owns the
        # cache, and at small schedule periods sessions run nearly
        # back-to-back — this bound guarantees a failed bind/evict is
        # repaired within one cycle instead of racing for a gap
        resync = getattr(self.cache, "process_resync_tasks", None)
        if resync is not None:
            with tracer.span("resync"):
                resync()
        self._maybe_reload_conf()
        start = telemetry.perf_counter()
        # the soft budget reads the INJECTED clock (virtual elapsed inside
        # one run_once is 0 by construction, so simulated cycles never shed
        # nondeterministically; production's clock is the wall)
        budget_start = self.clock.monotonic() if self.cycle_budget > 0 else 0.0
        with tracer.span("session_open"):
            ssn = open_session(self.cache, self.conf.tiers)
        # the configured pipeline, for actions whose behavior depends on
        # what runs after them (reclaim's idle-fit claimant gate)
        ssn.action_names = [a.name for a in self.actions]
        staged_flush = None
        try:
            for action in self.actions:
                # the span IS the measurement (rule KBT014): the action
                # latency histogram feeds from its stamps instead of an
                # ad-hoc perf_counter pair around the same region
                with tracer.span("action:" + action.name) as sp:
                    action.execute(ssn)
                metrics.observe_action_latency(action.name, sp.dur_us)
        finally:
            shed = (
                self.cycle_budget > 0
                and self.clock.monotonic() - budget_start > self.cycle_budget
            )
            if shed:
                logger.warning(
                    "cycle over its %.2fs soft budget before close; shedding "
                    "the status flush", self.cycle_budget)
                metrics.register_cycle_budget_exceeded()
                # a shed is a flight-recorder anomaly: the cycles around it
                # show WHERE the budget went
                tracer.anomaly(
                    "budget_shed",
                    detail=f"cycle over KB_CYCLE_BUDGET={self.cycle_budget}s",
                )
                self.cache.shed_status_writes = True
            try:
                # pipelined: the close stages the flush (degraded verdict
                # captured NOW, while the shed flag is visible) and skips
                # the inline binder drain — both run on the writeback worker
                with tracer.span("status_derive"):
                    staged_flush = close_session(ssn, stage_flush=pipelined)
            finally:
                if shed:
                    self.cache.shed_status_writes = False
                if pipelined:
                    # stage barrier: at most one writeback generation in
                    # flight (double buffer) — join cycle N-1's egress, then
                    # hand off ours.  INSIDE the finally: a cycle that died
                    # in an action still staged its flush, and the stage
                    # already recorded the queue deltas / rate-limit windows
                    # as written — dropping the flush here would suppress
                    # those writes until the counts next change.  A close
                    # whose OWN finally raised after staging never returned
                    # the flush — recover it from the session stash.
                    if staged_flush is None:
                        staged_flush = getattr(ssn, "staged_flush", None)
                    with tracer.span("writeback_barrier"):
                        self._await_writeback()
                    self._submit_writeback(staged_flush, record)
        metrics.observe_e2e_latency((telemetry.perf_counter() - start) * 1e3)
        if not pipelined:
            # drain async binder dispatch (cache.go:478's goroutines) outside
            # the measured cycle so callers observe a deterministic
            # post-cycle state
            flush = getattr(self.cache, "flush_binds", None)
            if flush is not None:
                with tracer.span("bind_drain"):
                    flush()
        # guard-plane breaker clock: demotion cooldowns and half-open
        # probes count in SCHEDULING CYCLES, not wall seconds, so the
        # state machine is deterministic under the sim's virtual clock
        guard = getattr(self.cache, "guard_plane", None)
        if guard is not None:
            guard.end_cycle()
            # trip-rate SLO alerting rides the same deterministic clock
            alerts_of(self.cache).evaluate(guard)
        if self.on_cycle_end is not None:
            self.on_cycle_end()

    # ---- writeback stage (the overlapped half of the pipeline) ----------
    def _writeback(self, staged_flush, record=None) -> None:
        # the span targets the ORIGINATING cycle's record (already in the
        # ring) from this worker thread — chrome://tracing then shows it
        # overlapping the next cycle's compute on a separate track
        with self.tracer.cycle_span("writeback", record) as sp:
            if staged_flush:
                self.cache.run_status_flush(staged_flush)
            drain = getattr(self.cache, "flush_binds", None)
            if drain is not None:
                drain()
        metrics.observe_pipeline_overlap(sp.dur_ms)

    def _submit_writeback(self, staged_flush, record=None) -> None:
        if self._wb_pool is None:
            self._wb_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="kb-writeback"
            )
        self._wb_future = self._wb_pool.submit(
            self._writeback, staged_flush, record
        )

    def _await_writeback(self) -> None:
        fut, self._wb_future = self._wb_future, None
        if fut is not None:
            try:
                fut.result()
            except Exception:  # noqa: BLE001 — next close re-derives
                logger.exception("writeback stage failed; statuses will "
                                 "re-derive next cycle")

    # EWMA smoothing of the adaptive coalescing floor, and its clamps: the
    # floor never drops below 5 ms (a degenerate idle cycle must not let a
    # hot ingest stream busy-spin the loop) and never exceeds max_period
    # (the idle tick must stay reachable)
    EWMA_ALPHA = 0.2
    MIN_PERIOD_FLOOR = 0.005

    def _note_cycle_cost(self, elapsed: float) -> None:
        """Feed one measured cycle cost (seconds, injected clock) into the
        adaptive min-period: EWMA-smooth it and, unless KB_PERIOD_MIN
        pinned a static floor, retarget the trigger's coalescing window to
        the smoothed cost."""
        if elapsed < 0:
            return
        prev = self.cycle_cost_ewma
        self.cycle_cost_ewma = (
            elapsed if prev is None
            else self.EWMA_ALPHA * elapsed + (1.0 - self.EWMA_ALPHA) * prev
        )
        if not self.min_period_pinned:
            self.min_period = min(
                max(self.cycle_cost_ewma, self.MIN_PERIOD_FLOOR),
                self.max_period,
            )

    def drain_pipeline(self) -> None:
        """Join the in-flight writeback stage and apply any still-staged
        ingest — the deterministic post-cycle state the serial run_once
        gives inline.  Tests, the sim, and shutdown call this."""
        self._await_writeback()
        # the replication publisher's encode stage overlaps the next cycle
        # exactly like the writeback worker — join it at the same barrier
        # so a drained pipeline has the cycle's record on the stream
        rep = getattr(self.cache, "replication", None)
        if rep is not None:
            rep.barrier()
        drain = getattr(self.cache, "drain_staged_ingest", None)
        if drain is not None:
            metrics.register_staged_ingest(drain())

    def _recover_failed_cycle(self) -> None:
        # exclusive (no-clone) sessions mutate the authoritative cache in
        # place: a cycle that died mid-mutation may have leaked partial
        # state — rebuild from the pod store (the informer re-list analog)
        # before the next cycle
        recover = getattr(self.cache, "rebuild_from_pod_store", None)
        if recover is not None:
            try:
                recover()
            except Exception:  # noqa: BLE001
                logger.exception("re-list recovery failed")

    def run_forever(self) -> None:
        """The L1 loop, preceded by cache.Run — the reference starts the
        cache's background repair loops (resync + cleanup) before ticking
        (scheduler.go:63-86, cache.go:342-384).  KB_PIPELINE=0 gives the
        reference's serial wait.Until(runOnce, period); the default is the
        event-driven pipelined loop (module docstring)."""
        cache_run = getattr(self.cache, "run", None)
        if cache_run is not None:
            cache_run(resync_period=min(self.schedule_period, 1.0))
        # re-arm after a prior stop(): the warm-standby loop re-enters
        # run_forever in the same process after a leadership loss
        self._stop = False
        try:
            if self.pipelined:
                self._run_forever_pipelined()
                return
            while not self._stop:
                tick = self.clock.monotonic()
                try:
                    self.run_once()
                except Exception:  # noqa: BLE001 — next cycle self-corrects
                    logger.exception("scheduling cycle failed")
                    self._recover_failed_cycle()
                elapsed = self.clock.monotonic() - tick
                self.clock.sleep(max(self.schedule_period - elapsed, 0.0))
        finally:
            cache_stop = getattr(self.cache, "stop", None)
            if cache_stop is not None:
                cache_stop()

    def _run_forever_pipelined(self) -> None:
        """The event-driven pipelined loop (the caller holds the cache-run /
        cache-stop bracket).  Ingest staging routes watch churn through the
        leaf staging buffer, the dirty tracker's version advance wakes the
        trigger, and shutdown drains every in-flight stage before the cache
        stops."""
        cache = self.cache
        enable = getattr(cache, "enable_ingest_staging", None)
        signal = getattr(cache, "set_ingest_signal", None)
        if signal is not None:
            signal(self.trigger.notify)
        if enable is not None:
            enable()
        logger.info(
            "pipelined cycle loop: event-driven trigger, min_period=%.3fs "
            "max_period=%.3fs (KB_PIPELINE=0 for the serial oracle)",
            self.min_period, self.max_period,
        )
        try:
            while not self._stop:
                tick = self.clock.monotonic()
                try:
                    self.run_once_pipelined()
                    # successful cycles only: a fast-CRASHING cycle must
                    # not drag the adaptive floor down and turn the loop
                    # into a high-frequency crash retry
                    self._note_cycle_cost(self.clock.monotonic() - tick)
                except Exception:  # noqa: BLE001 — next cycle self-corrects
                    logger.exception("scheduling cycle failed")
                    self._recover_failed_cycle()
                reason = self.trigger.wait_for_work(
                    tick, self.min_period, self.max_period
                )
                metrics.register_trigger_wake(reason)
        finally:
            # shutdown drain: join the in-flight writeback, apply staged
            # ingest, and detach the trigger so a re-armed run_forever (the
            # warm-standby path) starts from a clean pipeline
            try:
                disable = getattr(cache, "disable_ingest_staging", None)
                if disable is not None:
                    disable()
                self.drain_pipeline()
            finally:
                if signal is not None:
                    signal(None)
                if self._wb_pool is not None:
                    self._wb_pool.shutdown(wait=True)
                    self._wb_pool = None

    def stop(self) -> None:
        self._stop = True
        # a stopping pipelined loop may be idling at the slow floor — wake it
        self.trigger.notify()

    def close(self) -> None:
        """Retire the pipelined writeback pool with a bounded drain.
        run_forever's finally-block does this for the looped path; direct
        ``run_once_pipelined`` callers (tests, the sim harness) must call
        close() or leak the pool's non-daemon worker thread."""
        self.drain_pipeline()
        if self._wb_pool is not None:
            self._wb_pool.shutdown(wait=True)
            self._wb_pool = None
