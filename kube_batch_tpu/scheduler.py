"""Scheduler — the L1 loop (pkg/scheduler/scheduler.go:38-102).

Holds the cache, the configured action pipeline, and the plugin tiers; each
tick opens a session (snapshot + plugin open), executes the actions in conf
order, and closes the session (status writeback). `run_forever` is the
wait.Until(runOnce, period) analog."""

from __future__ import annotations

import logging
import os
import time
from typing import List, Optional

from kube_batch_tpu import actions as _actions  # registers actions
from kube_batch_tpu import plugins as _plugins  # registers plugin builders
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.framework.conf import SchedulerConfiguration, load_scheduler_conf
from kube_batch_tpu.framework.interface import Action, get_action
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu import metrics
from kube_batch_tpu.utils import telemetry

logger = logging.getLogger("kube_batch_tpu")


class Scheduler:
    def __init__(
        self,
        cache: SchedulerCache,
        conf: Optional[SchedulerConfiguration] = None,
        conf_path: Optional[str] = None,
        schedule_period: float = 1.0,
        on_cycle_end=None,
        clock=None,
    ):
        self.cache = cache
        # injected time source for the loop's pacing (monotonic() + sleep());
        # defaults to the wall clock. The virtual-time simulator
        # (kube_batch_tpu/sim) injects its VirtualClock so cycle pacing is
        # simulated time, while the latency *metrics* below stay wall-clock
        # (they measure real compute, not scenario time).
        self.clock = clock if clock is not None else time
        self.conf = conf if conf is not None else load_scheduler_conf(conf_path)
        # resolve actions at construction — unknown names raise (util.go:63-70)
        self.actions: List[Action] = [get_action(n) for n in self.conf.actions]
        self.schedule_period = schedule_period
        self.on_cycle_end = on_cycle_end  # e.g. state-file save (persistence.py)
        self._stop = False
        # conf hot-reload (the reference's stated-but-unimplemented design,
        # doc/design/plugin-conf.md — its code re-reads only at startup,
        # scheduler.go:70-83): when constructed from a path, the file's
        # mtime is checked each cycle and a changed, VALID conf swaps in at
        # the cycle boundary; a broken edit logs and keeps the running conf
        self._conf_path = conf_path if conf is None else None
        # NOTE: __init__ loaded the conf above, so this stat runs after the
        # load — an edit in that window would be lost. Re-stat BEFORE
        # re-reading in _maybe_reload_conf closes the window for the loop;
        # here, force one reload check on the first cycle instead.
        self._conf_mtime: Optional[float] = None
        # soft per-cycle time budget (seconds, KB_CYCLE_BUDGET; 0 = off):
        # a cycle that already overran it when the action pipeline finishes
        # sheds the close-time status flush to the cache's async pool and
        # keeps ticking, instead of stalling the loop in egress writeback
        self.cycle_budget = float(os.environ.get("KB_CYCLE_BUDGET", "0") or 0)

    def _stat_conf(self) -> Optional[float]:
        if not self._conf_path:
            return None
        try:
            return os.path.getmtime(self._conf_path)
        except OSError:
            return None

    def _maybe_reload_conf(self) -> None:
        if not self._conf_path:
            return
        mtime = self._stat_conf()
        if mtime is None or mtime == self._conf_mtime:
            return
        try:
            conf = load_scheduler_conf(self._conf_path)
            # resolve EVERYTHING the conf names before swapping: an unknown
            # action or plugin must reject the edit here, not crash every
            # subsequent open_session
            actions = [get_action(n) for n in conf.actions]
            from kube_batch_tpu.framework.interface import get_plugin_builder

            for tier in conf.tiers:
                for opt in tier.plugins:
                    get_plugin_builder(opt.name)
        except Exception as e:  # noqa: BLE001 — keep the running conf
            logger.error("scheduler conf reload failed (%s); keeping the "
                         "running configuration", e)
            self._conf_mtime = mtime  # don't re-log every cycle
            return
        if conf.actions != self.conf.actions or conf.tiers != self.conf.tiers:
            logger.info("scheduler conf hot-reloaded: actions=%s", conf.actions)
        self.conf, self.actions = conf, actions
        self._conf_mtime = mtime

    def run_once(self) -> None:
        """(scheduler.go:88-102)"""
        # drain the resync queue at the cycle boundary: the background repair
        # tick (cache.go:563-581) skips while an exclusive session owns the
        # cache, and at small schedule periods sessions run nearly
        # back-to-back — this bound guarantees a failed bind/evict is
        # repaired within one cycle instead of racing for a gap
        resync = getattr(self.cache, "process_resync_tasks", None)
        if resync is not None:
            resync()
        self._maybe_reload_conf()
        start = telemetry.perf_counter()
        # the soft budget reads the INJECTED clock (virtual elapsed inside
        # one run_once is 0 by construction, so simulated cycles never shed
        # nondeterministically; production's clock is the wall)
        budget_start = self.clock.monotonic() if self.cycle_budget > 0 else 0.0
        ssn = open_session(self.cache, self.conf.tiers)
        # the configured pipeline, for actions whose behavior depends on
        # what runs after them (reclaim's idle-fit claimant gate)
        ssn.action_names = [a.name for a in self.actions]
        try:
            for action in self.actions:
                a_start = telemetry.perf_counter()
                action.execute(ssn)
                metrics.observe_action_latency(
                    action.name, (telemetry.perf_counter() - a_start) * 1e6
                )
        finally:
            shed = (
                self.cycle_budget > 0
                and self.clock.monotonic() - budget_start > self.cycle_budget
            )
            if shed:
                logger.warning(
                    "cycle over its %.2fs soft budget before close; shedding "
                    "the status flush", self.cycle_budget)
                metrics.register_cycle_budget_exceeded()
                self.cache.shed_status_writes = True
            try:
                close_session(ssn)
            finally:
                if shed:
                    self.cache.shed_status_writes = False
        metrics.observe_e2e_latency((telemetry.perf_counter() - start) * 1e3)
        # drain async binder dispatch (cache.go:478's goroutines) outside the
        # measured cycle so callers observe a deterministic post-cycle state
        flush = getattr(self.cache, "flush_binds", None)
        if flush is not None:
            flush()
        if self.on_cycle_end is not None:
            self.on_cycle_end()

    def run_forever(self) -> None:
        """wait.Until(runOnce, period) preceded by cache.Run — the reference
        starts the cache's background repair loops (resync + cleanup) before
        ticking (scheduler.go:63-86, cache.go:342-384)."""
        cache_run = getattr(self.cache, "run", None)
        if cache_run is not None:
            cache_run(resync_period=min(self.schedule_period, 1.0))
        # re-arm after a prior stop(): the warm-standby loop re-enters
        # run_forever in the same process after a leadership loss
        self._stop = False
        try:
            while not self._stop:
                tick = self.clock.monotonic()
                try:
                    self.run_once()
                except Exception:  # noqa: BLE001 — next cycle self-corrects
                    logger.exception("scheduling cycle failed")
                    # exclusive (no-clone) sessions mutate the authoritative
                    # cache in place: a cycle that died mid-mutation may have
                    # leaked partial state — rebuild from the pod store (the
                    # informer re-list analog) before the next cycle
                    recover = getattr(self.cache, "rebuild_from_pod_store", None)
                    if recover is not None:
                        try:
                            recover()
                        except Exception:  # noqa: BLE001
                            logger.exception("re-list recovery failed")
                elapsed = self.clock.monotonic() - tick
                self.clock.sleep(max(self.schedule_period - elapsed, 0.0))
        finally:
            cache_stop = getattr(self.cache, "stop", None)
            if cache_stop is not None:
                cache_stop()

    def stop(self) -> None:
        self._stop = True
