"""kube_batch_tpu — a TPU-native batch/gang scheduling framework.

A ground-up rebuild of the capabilities of kube-batch v0.4.2 (the scheduler
that became Volcano; reference surveyed in SURVEY.md) where every hot loop —
per-task×per-node predicates, node scoring, DRF shares, proportion fair-share,
and the gang-constrained allocate — is a compiled XLA program over
device-resident snapshot tensors instead of a Go loop over object graphs.

Layer map (mirrors SURVEY.md §1):
  scheduler.py        — L1 scheduler loop (reference pkg/scheduler/scheduler.go)
  framework/          — L2 session runtime, tiers, statement (pkg/scheduler/framework)
  actions/            — L3 enqueue/reclaim/allocate/backfill/preempt
  plugins/            — L4 gang/drf/proportion/priority/predicates/nodeorder/
                         conformance/binpack policies
  cache/              — L5 cluster cache, event ingest, binder/evictor seams
  utils/              — L6 priority queue, helpers
  api/                — L7 data model (Resource, TaskInfo, JobInfo, NodeInfo,
                         QueueInfo, device snapshot)
  ops/                — the TPU compute path: feasibility masks, score rows,
                         fairness tensors, gang-constrained assignment solve
  parallel/           — device mesh / sharding of the node axis over ICI
"""

__version__ = "0.1.0"
