"""backfill action (actions/backfill/backfill.go:42-93): place BestEffort
tasks (empty InitResreq) on the first node passing the plugin predicates —
no scoring, immediate allocate.

BEYOND-REFERENCE: non-BestEffort backfill — the reference's own acknowledged
TODO (backfill.go:87).  When the allocate replay discarded placements
host-side (a gang that failed its JobReady gate after host predicate
rejections, a volume-demoted job that could not re-place), the capacity
those discards freed is stranded for the rest of the cycle: the device solve
already ran and the reference's sequential loop has likewise moved on.  The
real-request pass re-runs the allocate solve over the live post-replay
snapshot, restricted to GANG-SAFE claimants — jobs already at or above
MinAvailable, or non-gangs (MinAvailable ≤ 1) — so no partial gang can ever
commit, and replays the result through the standard vectorized path.
Disabled with `backfill.realRequests: "false"` on any conf tier.
Pinned by tests/test_conformance.py TestRealRequestBackfill."""

from __future__ import annotations

import logging

from kube_batch_tpu.api.job_info import FitError, FitErrors
from kube_batch_tpu.api.types import PodGroupPhase, TaskStatus
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import FitFailure

logger = logging.getLogger("kube_batch_tpu")


class BackfillAction(Action):
    name = "backfill"

    def execute(self, ssn) -> None:
        self._best_effort(ssn)
        self._real_requests(ssn)

    # ---- reference semantics: BestEffort first-fit ----------------------
    def _best_effort(self, ssn) -> None:
        for job in ssn.jobs.values():
            if job.pod_group and job.pod_group.phase == PodGroupPhase.PENDING:
                continue
            pending = list(job.task_status_index.get(TaskStatus.PENDING, {}).values())
            for task in pending:
                if not task.best_effort:
                    continue
                fit_errors = FitErrors()
                for node in ssn.nodes.values():
                    try:
                        ssn.predicate(task, node)
                    except FitFailure as e:
                        fit_errors.set_node_error(
                            node.name, FitError(task, node.name, [e.reason])
                        )
                        continue
                    ssn.allocate(task, node.name)
                    break
                else:
                    job.nodes_fit_errors[task.uid] = fit_errors
                    ssn.note_fit_state(job)

    # ---- beyond-reference: stranded-capacity real-request pass ----------
    def _real_requests(self, ssn) -> None:
        if not ssn.jobs or not ssn.nodes:
            return
        if not ssn.conf_flag("backfill.realRequests", default=True):
            return
        # the pass re-pays a full [T, N] solve, so it only runs when the
        # allocate action actually stranded capacity this cycle; without
        # that signal the post-allocate pending set is exactly the set the
        # solve just failed, and re-solving is wasted work.  The signal
        # rides the SESSION (set by allocate's discard path): the action
        # registry is a process-global singleton, and reading its counter
        # here crossed wires between scheduler instances sharing a process
        # (tests, the simulator's many schedulers) — ADVICE.md #5
        if not getattr(ssn, "host_discards", 0):
            return
        import jax
        import numpy as np

        from kube_batch_tpu.actions.allocate import (
            AllocateAction,
            build_session_snapshot,
            dispatch_allocate_solve,
            session_allocate_config,
        )

        cols = ssn.columns
        if cols is not None:
            if not cols.has_schedulable_pending():
                return
        else:
            # isolated sessions: object-level pre-gate before paying the
            # full snapshot rebuild — any gang-safe job with pending tasks?
            def _safe_pending(job):
                if job.pod_group and job.pod_group.phase == PodGroupPhase.PENDING:
                    return False
                if not job.task_status_index.get(TaskStatus.PENDING):
                    return False
                return job.min_available <= 1 or job.ready()

            if not any(_safe_pending(j) for j in ssn.jobs.values()):
                return
        snap, meta = build_session_snapshot(ssn)
        # gang-safe claimants only: a job at/above MinAvailable can take
        # extra placements without atomicity risk; a MinAvailable ≤ 1 job is
        # not a gang.  An unready gang stays excluded — committing part of
        # it is exactly what allocate's discard just prevented.
        safe_np = (
            (np.asarray(snap.job_min_avail) <= 1)
            | (np.asarray(snap.job_ready) >= np.asarray(snap.job_min_avail))
        ) & np.asarray(snap.job_schedulable)
        # cheap host pre-check BEFORE the [T, N] solve: the common trigger —
        # a discarded unready gang being the only pending work — must not
        # re-pay the cycle's dominant cost for a guaranteed-empty result
        task_job = np.asarray(snap.task_job)[: meta.n_tasks]
        eligible = (
            np.asarray(snap.task_pending)[: meta.n_tasks]
            & np.asarray(snap.task_valid)[: meta.n_tasks]
            & np.asarray(snap.job_valid)[task_job]
            & safe_np[task_job]
        )
        if not eligible.any():
            return
        import jax.numpy as jnp

        snap = snap._replace(
            job_schedulable=snap.job_schedulable & jnp.asarray(safe_np)
        )
        from kube_batch_tpu.guard import guard_of
        from kube_batch_tpu.obs.trace import tracer_of

        gp = guard_of(ssn.cache)
        tracer = tracer_of(ssn.cache)
        config = session_allocate_config(ssn)
        with tracer.device_span("solve_dispatch", cols=cols,
                                action="backfill"):
            result, _mode, _topk, ginfo = dispatch_allocate_solve(
                snap, config, cols=cols, guard=gp
            )
        # this swap retired the what-if lease on donating backends — re-arm
        # it off the same (memoized) resident snapshot.  The gang-safe
        # job_schedulable mask above is probe-invisible: a probe's task
        # axis is ONLY the speculative gang (its appended job row is the
        # sole j_sched consulted), so this snapshot is oracle-equivalent
        # for serving
        from kube_batch_tpu.actions.allocate import republish_query_lease

        republish_query_lease(ssn, snap, meta)
        sentinel = ginfo["sentinel"]
        with tracer.device_span("device_wait", action="backfill"):
            # kbt: allow[KBT010] the backfill pass's one sanctioned
            # readback — the guard sentinel's verdict + histogram ride it
            assigned, pipelined, verdict, vhist, echeck = jax.device_get(
                (result.assigned, result.pipelined,
                 sentinel[0] if sentinel is not None else np.int32(0),
                 sentinel[1] if sentinel is not None else None,
                 sentinel[2] if sentinel is not None else np.int32(0))
            )
        assigned = assigned[: meta.n_tasks]
        pipelined = pipelined[: meta.n_tasks]
        if sentinel is not None:
            from kube_batch_tpu.guard import consume_assignment_sentinel

            if not consume_assignment_sentinel(
                gp, "backfill", ssn, snap, meta, ginfo,
                int(verdict), vhist, int(echeck), assigned,
            ):
                # condemned solve → fail closed: strand the capacity for
                # this cycle rather than bind from an unlawful result
                return
        if not (assigned >= 0).any():
            return
        n = int((assigned >= 0).sum())
        logger.info("backfill real-request pass placing %d stranded tasks", n)
        # replay through a throwaway action instance so the allocate
        # action's recorded phases/fallback stay those of the main pass
        helper = AllocateAction()
        helper._replay(ssn, snap, meta, assigned, pipelined, task_job)
