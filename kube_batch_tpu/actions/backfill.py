"""backfill action (actions/backfill/backfill.go:42-93): place BestEffort
tasks (empty InitResreq) on the first node passing the plugin predicates —
no scoring, immediate allocate. Non-BestEffort backfill remains the
reference's acknowledged TODO (backfill.go:87)."""

from __future__ import annotations

from kube_batch_tpu.api.job_info import FitError, FitErrors
from kube_batch_tpu.api.types import PodGroupPhase, TaskStatus
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import FitFailure


class BackfillAction(Action):
    name = "backfill"

    def execute(self, ssn) -> None:
        for job in ssn.jobs.values():
            if job.pod_group and job.pod_group.phase == PodGroupPhase.PENDING:
                continue
            pending = list(job.task_status_index.get(TaskStatus.PENDING, {}).values())
            for task in pending:
                if not task.best_effort:
                    continue
                fit_errors = FitErrors()
                for node in ssn.nodes.values():
                    try:
                        ssn.predicate(task, node)
                    except FitFailure as e:
                        fit_errors.set_node_error(
                            node.name, FitError(task, node.name, [e.reason])
                        )
                        continue
                    ssn.allocate(task, node.name)
                    break
                else:
                    job.nodes_fit_errors[task.uid] = fit_errors
