"""enqueue action (actions/enqueue/enqueue.go) — the Inqueue gatekeeper.

Computes cluster idle as Σ allocatable × 1.2 − used (20% overcommit,
enqueue.go:78-81), then walks Pending-phase podgroups in queue/job order:
no MinResources → Inqueue; else requires JobEnqueueable (proportion
capability check) AND MinResources ≤ idle, deducting on admission
(enqueue.go:102-117)."""

from __future__ import annotations

from kube_batch_tpu.api.types import PodGroupPhase
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.utils.priority_queue import PriorityQueue

OVERCOMMIT_FACTOR = 1.2


class EnqueueAction(Action):
    name = "enqueue"

    def execute(self, ssn) -> None:
        cols = ssn.columns

        def promote(job):
            """Pending → Inqueue, mirrored into the j_sched column: the
            device snapshot's schedulability row is synced at session open
            (delta across cycles), so a mid-cycle phase flip must write
            through or this cycle's allocate would still skip the job."""
            job.pod_group.phase = PodGroupPhase.INQUEUE
            if cols is not None and job._cols is cols and job._row >= 0:
                cols.j_sched[job._row] = True

        queues = PriorityQueue(less=ssn.queue_order_fn)
        queue_set = set()
        jobs_map = {}
        any_min_res = False
        for job in ssn.jobs.values():
            if job.queue not in ssn.queues:
                continue
            if job.pod_group is None or job.pod_group.phase != PodGroupPhase.PENDING:
                continue
            if job.pod_group.min_resources is None:
                # unconditional promotion (enqueue.go:102-105): admission
                # order is unobservable for jobs that consume no budget, so
                # they skip the priority-queue machinery entirely — at 12.5k
                # Pending podgroups the tiered order comparisons alone were
                # ~0.8s of host time
                promote(job)
                continue
            any_min_res = True
            queue = ssn.queues[job.queue]
            if queue.name not in queue_set:
                queue_set.add(queue.name)
                queues.push(queue)
            jobs_map.setdefault(queue.name, PriorityQueue(less=ssn.job_order_fn)).push(job)

        if not any_min_res:
            return

        # idle = total × 1.2 − used (enqueue.go:74-81)
        total = ssn.spec.empty()
        used = ssn.spec.empty()
        for node in ssn.nodes.values():
            total.add_(node.allocatable)
            used.add_(node.used)
        idle = total.multi(OVERCOMMIT_FACTOR)
        if used.less_equal(idle):
            idle.sub_(used)
        else:
            idle = ssn.spec.empty()

        while queues:
            queue = queues.pop()
            jobs = jobs_map.get(queue.name)
            if not jobs:
                continue
            job = jobs.pop()
            min_req = ssn.spec.empty()
            for name, v in job.pod_group.min_resources.items():
                if name in ssn.spec:
                    min_req.vec[ssn.spec.index(name)] = float(v)
            if ssn.job_enqueueable(job) and min_req.less_equal(idle):
                promote(job)
                idle.sub_(min_req)
            queues.push(queue)
