"""enqueue action (actions/enqueue/enqueue.go) — the Inqueue gatekeeper.

Computes cluster idle as Σ allocatable × 1.2 − used (20% overcommit,
enqueue.go:78-81), then admits Pending-phase podgroups in queue/job order:
no MinResources → Inqueue; else requires JobEnqueueable (proportion
capability check) AND MinResources ≤ idle, deducting on admission
(enqueue.go:102-117).

Columnar sessions run this with NO per-job Python loop: candidates come
off the j_sched/j_has_minres job-row columns (synced at session open,
delta across cycles), the static JobEnqueueable verdicts and ordering keys
are vectorized over the column matrices, and the sequential admission
itself (each admission shrinks the idle the next candidate sees) is the
jitted prefix-scan in ops/admission.py with a single readback of the
admitted mask — only PROMOTED jobs touch Python objects.

Ordering exactness: the session's queue_order_fn is a strict total order
(plugin verdicts fall back to the queue name), so the reference's
pop-process-push heap walk provably drains one queue fully before the
next — the gate reproduces it by sorting the involved queues once (they
are few) and concatenating each queue's candidates in tiered job order,
derived columnar for the known JOB_ORDER voters (priority/gang/drf — any
other voter falls back to the object walk below, as do non-columnar
sessions).  MinResources rows are float32 (the device column dtype);
min_resources values beyond f32 precision would shift the fit check by
<1 ulp — inside the sub-quantum tolerance for every real resource unit.
"""

from __future__ import annotations

from functools import cmp_to_key

import numpy as np

from kube_batch_tpu.api.types import PodGroupPhase
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import JOB_ENQUEUEABLE, JOB_ORDER
from kube_batch_tpu.utils.priority_queue import PriorityQueue

OVERCOMMIT_FACTOR = 1.2

#: JOB_ORDER voters the columnar gate can derive keys for
_COLUMNAR_JOB_ORDER = {"priority", "gang", "drf"}


class EnqueueAction(Action):
    name = "enqueue"

    def __init__(self):
        # which path the most recent execute() took ("columnar" | "walk") —
        # read by the bench and the gate-equivalence tests
        self.last_path = "walk"

    def execute(self, ssn) -> None:
        cols = ssn.columns
        if (
            cols is not None
            and getattr(ssn, "rows_synced", False)
            and ssn.enabled_plugin_names(JOB_ENQUEUEABLE) <= {"proportion"}
            and ssn.enabled_plugin_names(JOB_ORDER) <= _COLUMNAR_JOB_ORDER
        ):
            self.last_path = "columnar"
            if self._execute_columnar(ssn, cols):
                return
        self.last_path = "walk"
        self._execute_walk(ssn, cols)

    # ------------------------------------------------------------------
    def _promote(self, cols, job) -> None:
        """Pending → Inqueue, mirrored into the job-row columns: the device
        snapshot's schedulability row is synced at session open (delta
        across cycles), so a mid-cycle phase flip must write through or
        this cycle's allocate would still skip the job; the phase/touched
        rows keep the delta close-session pass exact."""
        from kube_batch_tpu.api.columns import PHASE_CODE

        job.pod_group.phase = PodGroupPhase.INQUEUE
        if cols is not None and job._cols is cols and job._row >= 0:
            row = job._row
            cols.j_sched[row] = True
            cols.j_phase[row] = PHASE_CODE[PodGroupPhase.INQUEUE]
            cols.j_touched[row] = True

    def _promote_rows(self, ssn, cols, rows) -> None:
        job_by_row = cols.job_by_row
        for r in rows:
            self._promote(cols, job_by_row[r])

    # ------------------------------------------------------------------
    def _execute_columnar(self, ssn, cols) -> bool:
        """The column-gate path; returns False when an exactness guard
        trips (the caller then runs the object walk).

        Promotions are DEFERRED to the end: nothing mutates until the
        admitted set is final, so (a) every fallback return leaves the
        object walk a pristine re-decide, and (b) the sampled shadow audit
        can run the walk ORACLE over the same unmutated state and compare
        decision sets — the guard-plane coverage for this gate that the
        solve paths already have via their shadow oracles."""
        import jax

        spec = ssn.spec
        cand = cols.j_sess & ~cols.j_sched & cols.j_has_pg
        if not cand.any():
            return True
        # the walk skips jobs whose queue left the session's queue dict
        qok = np.zeros(cols.queues.cap, bool)
        for name, qi in cols.queue_rows.items():
            if name in ssn.queues:
                qok[qi] = True
        cand &= qok[cols.j_queue]
        if not cand.any():
            return True
        job_by_row = cols.job_by_row
        # unconditional promotions (enqueue.go:102-105): admission order is
        # unobservable for jobs that consume no budget — decided here,
        # APPLIED at the end with the admitted rows
        uncond_rows = np.flatnonzero(cand & ~cols.j_has_minres).tolist()
        minres_rows = np.flatnonzero(cand & cols.j_has_minres)
        if minres_rows.size == 0:
            self._promote_rows(ssn, cols, uncond_rows)
            return True

        # idle = Σ allocatable × 1.2 − used (enqueue.go:74-81) over the
        # session's nodes — exactly the Ready rows; skew falls back
        if int(cols.n_valid.sum()) != len(ssn.nodes):
            return False
        nv = cols.n_valid
        if nv.any():
            total = spec.from_vec(cols.n_alloc[nv].sum(axis=0))
            used = spec.from_vec(cols.n_used[nv].sum(axis=0))
        else:
            total, used = spec.empty(), spec.empty()
        idle = total.multi(OVERCOMMIT_FACTOR)
        if used.less_equal(idle):
            idle.sub_(used)
        else:
            idle = spec.empty()

        # static JobEnqueueable verdicts (proportion.go:211-233): the
        # capability check against the queue's LIVE allocation — read off
        # the proportion plugin's own queue attrs (exactly what its
        # job_enqueueable closure reads, including any event updates since
        # open), vectorized per queue over the candidate rows
        enq_ok = np.ones(minres_rows.size, bool)
        qrows_of = cols.j_queue[minres_rows]
        if "proportion" in ssn.enabled_plugin_names(JOB_ENQUEUEABLE):
            prop = next(
                (p for p in ssn.plugins
                 if getattr(p, "name", "") == "proportion"), None,
            )
            attrs = getattr(prop, "queue_attrs", {})
            minr64 = cols.j_minres[minres_rows].astype(np.float64)
            for qi in np.unique(qrows_of).tolist():
                qinfo = ssn.queues.get(cols.queue_names[qi])
                attr = attrs.get(cols.queue_names[qi])
                # queue or attr missing → enqueueable (the closure's guard)
                if qinfo is None or attr is None:
                    continue
                capability = qinfo.queue.capability
                if not capability:
                    continue  # no cap → enqueueable
                capv = np.zeros(spec.n)
                for name, v in capability.items():
                    if name in spec:
                        capv[spec.index(name)] = float(v)
                sel = qrows_of == qi
                need = minr64[sel] + attr.allocated.vec
                ok = np.all(
                    (need <= capv) | (need - capv < spec.quanta), axis=1
                )
                idxs = np.flatnonzero(sel)
                enq_ok[idxs[~ok]] = False

        # admission order: queues drained in tiered queue order (strict
        # total order ⇒ exactly the reference heap's behavior), jobs within
        # a queue by the tiered job-order keys, columnar per voter
        qset = sorted({int(qi) for qi in np.unique(qrows_of)})
        queue_objs = [ssn.queues[cols.queue_names[qi]] for qi in qset]
        queue_objs.sort(key=cmp_to_key(
            lambda a, b: -1 if ssn.queue_order_fn(a, b) else 1
        ))
        rank_by_qi = np.zeros(cols.queues.cap, np.int32)
        for pos, q in enumerate(queue_objs):
            rank_by_qi[cols.queue_rows[q.name]] = pos
        keys = []
        from kube_batch_tpu.api.columns import READY_STATUSES

        for name in ssn.ordered_enabled_plugins(JOB_ORDER):
            if name == "priority":
                keys.append(-cols.j_prio[minres_rows])
            elif name == "gang":
                # starved (not ready) gangs first (gang.go:96-121)
                ready = (
                    cols.j_counts[minres_rows][:, READY_STATUSES]
                    .sum(axis=1) >= cols.j_min[minres_rows]
                )
                keys.append(ready.astype(np.int8))
            elif name == "drf":
                # lower dominant share first (drf.go:114-132) — same math
                # as Resource.share over the semantic dims
                m = spec.semantic_mask
                t = ssn.total_allocatable().vec[m]
                alloc = cols.j_alloc[minres_rows][:, m]
                with np.errstate(divide="ignore", invalid="ignore"):
                    ratios = np.where(t > 0, alloc / t, 0.0)
                keys.append(
                    ratios.max(axis=1) if ratios.shape[1]
                    else np.zeros(minres_rows.size)
                )
            else:
                return False  # unsupported voter → object walk
        creation = cols.j_creation[minres_rows]
        sort_keys = []
        if np.unique(creation).size != creation.size:
            # creation-index ties fall back to uid (session job_order_fn's
            # final tie-break) — materialized only when ties exist
            sort_keys.append(np.array(
                [job_by_row[r].uid for r in minres_rows.tolist()]
            ))
        sort_keys.append(creation)
        sort_keys.extend(reversed(keys))
        sort_keys.append(rank_by_qi[qrows_of])
        order = np.lexsort(tuple(sort_keys))
        ordered = minres_rows[order]

        # the jitted prefix-scan (ops/admission.py) at the padded job-axis
        # capacity — shape-stable across the steady-state wobble.  When the
        # cycle's solves shard over the mesh, the scan rides the mesh too
        # (a replicated shard_map body: every device/process computes the
        # same admitted mask — multi-controller placement consistency)
        from kube_batch_tpu.parallel.mesh import dispatch_enqueue_gate

        capJ = cols.jobs.cap
        k = ordered.size
        minr = np.zeros((capJ, spec.n), np.float32)
        minr[:k] = cols.j_minres[ordered]
        candv = np.zeros(capJ, bool)
        candv[:k] = enq_ok[order]
        from kube_batch_tpu.guard import guard_of
        from kube_batch_tpu.obs.trace import tracer_of
        from kube_batch_tpu.parallel.mesh import (
            shard_map_enabled,
            should_shard,
        )

        gp = guard_of(ssn.cache)
        tracer = tracer_of(ssn.cache)
        idle_v = idle.vec.astype(np.float32)
        quanta_v = spec.quanta.astype(np.float32)
        use_mesh = should_shard(cols.nodes.cap) and shard_map_enabled()
        with tracer.device_span("gate_dispatch", cols=cols) as sp_gate:
            if gp.enabled and not use_mesh:
                # the FUSED gate sentinel (ops/invariants): admitted ⊆
                # candidates + the all-finite budget sweep run in the same
                # compiled program as the admission scan, verdict riding the
                # one readback — the single-device twin of the solve
                # sentinels
                from kube_batch_tpu.ops.invariants import (
                    enqueue_gate_sentinel_solve,
                )

                admitted_dev, v_dev, _hist = enqueue_gate_sentinel_solve(
                    minr, candv, idle_v, quanta_v
                )
                # kbt: allow[KBT010] the enqueue gate's ONE sanctioned
                # readback: the admitted-rows mask + the fused verdict
                admitted, verdict = jax.device_get((admitted_dev, v_dev))
                admitted = np.asarray(admitted)[:k]
                bad = int(verdict)
            else:
                admitted_dev = dispatch_enqueue_gate(
                    minr, candv, idle_v, quanta_v,
                    n_nodes_padded=cols.nodes.cap,
                )
                # kbt: allow[KBT010] the enqueue gate's ONE sanctioned
                # readback: the admitted-rows mask the promotions consume
                admitted = np.asarray(jax.device_get(admitted_dev))[:k]
                bad = 0
                if gp.enabled:
                    # mesh path (the replicated shard_map gate has no fused
                    # variant): the invariant is host-checkable from the
                    # dispatch's own host-built inputs
                    bad = int(np.sum(admitted & ~candv[:k]))
                    if (not np.isfinite(minr).all()
                            or not np.isfinite(idle_v).all()
                            or not np.isfinite(quanta_v).all()):
                        bad += 1
        sp_gate.set(candidates=int(k))
        # a violation fails CLOSED: no scan-derived promotions from a
        # condemned verdict (the Pending walk re-decides next cycle); the
        # unconditional promotions never consumed the condemned scan
        if gp.enabled and not gp.consume_verdict(
            "enqueue", [], bad, detail=f"enqueue gate verdict={bad}",
        ):
            self._promote_rows(ssn, cols, uncond_rows)
            return True
        admitted_rows = ordered[admitted].tolist()
        # sampled shadow audit (guard tier 2, the object-walk coverage the
        # ROADMAP standing item asks for): every KB_AUDIT_EVERY-th gate
        # dispatch re-derives the admission through the reference walk —
        # the same oracle the gate-equivalence tests pin — over the still
        # UNMUTATED session, and compares decision SETS.  On mismatch the
        # guard trips (unattributable → conservative demotion + resident
        # heal) and the WALK's decisions are applied: the oracle is
        # authoritative, exactly like a demoted solve path running pjit.
        if gp.enabled and gp.audit_due("enqueue"):
            from kube_batch_tpu.guard import make_heal

            walk_jobs = self._walk_decisions(ssn)
            expected = {job.uid for job in walk_jobs}
            actual = {job_by_row[r].uid
                      for r in uncond_rows + admitted_rows}
            matched = expected == actual
            gp.note_audit(
                "enqueue", [], matched,
                detail=(
                    "enqueue gate vs object-walk divergence: "
                    f"gate-only={sorted(actual - expected)[:8]} "
                    f"walk-only={sorted(expected - actual)[:8]}"
                ) if not matched else "",
                heal=make_heal(ssn),
            )
            if not matched:
                for job in walk_jobs:
                    self._promote(cols, job)
                return True
        self._promote_rows(ssn, cols, uncond_rows + admitted_rows)
        return True

    # ------------------------------------------------------------------
    def _execute_walk(self, ssn, cols) -> None:
        """The reference walk (enqueue.go:74-117) — the always-correct
        fallback for non-columnar sessions and exotic plugin sets, and the
        oracle the gate-equivalence tests compare against."""
        for job in self._walk_decisions(ssn):
            self._promote(cols, job)

    def _walk_decisions(self, ssn) -> list:
        """The reference walk's admission DECISIONS, with no mutation:
        the promotion list in walk order.  Shared by the walk execution
        path and the columnar gate's sampled shadow audit (which must run
        the oracle over the still-unmutated session and diff decision
        sets)."""
        decisions = []
        queues = PriorityQueue(less=ssn.queue_order_fn)
        queue_set = set()
        jobs_map = {}
        any_min_res = False
        for job in ssn.jobs.values():
            if job.queue not in ssn.queues:
                continue
            if job.pod_group is None or job.pod_group.phase != PodGroupPhase.PENDING:
                continue
            if job.pod_group.min_resources is None:
                # unconditional promotion (enqueue.go:102-105): admission
                # order is unobservable for jobs that consume no budget, so
                # they skip the priority-queue machinery entirely — at 12.5k
                # Pending podgroups the tiered order comparisons alone were
                # ~0.8s of host time
                decisions.append(job)
                continue
            any_min_res = True
            queue = ssn.queues[job.queue]
            if queue.name not in queue_set:
                queue_set.add(queue.name)
                queues.push(queue)
            jobs_map.setdefault(queue.name, PriorityQueue(less=ssn.job_order_fn)).push(job)

        if not any_min_res:
            return decisions

        # idle = total × 1.2 − used (enqueue.go:74-81)
        total = ssn.spec.empty()
        used = ssn.spec.empty()
        for node in ssn.nodes.values():
            total.add_(node.allocatable)
            used.add_(node.used)
        idle = total.multi(OVERCOMMIT_FACTOR)
        if used.less_equal(idle):
            idle.sub_(used)
        else:
            idle = ssn.spec.empty()

        while queues:
            queue = queues.pop()
            jobs = jobs_map.get(queue.name)
            if not jobs:
                continue
            job = jobs.pop()
            min_req = ssn.spec.empty()
            for name, v in job.pod_group.min_resources.items():
                if name in ssn.spec:
                    min_req.vec[ssn.spec.index(name)] = float(v)
            if ssn.job_enqueueable(job) and min_req.less_equal(idle):
                decisions.append(job)
                idle.sub_(min_req)
            queues.push(queue)
        return decisions
