"""reclaim action (actions/reclaim/reclaim.go) — cross-queue eviction,
device-solved.

The reference scans every node per starved task serially (reclaim.go:107-199).
Here ops/eviction.evict_solve proposes (claimant → node, victims) on device;
the host replays each claim through the real plugin callbacks
(ssn.reclaimable tier-intersection) so semantics stay authoritative: victims
are evicted (immediately — reclaim holds no Statement, reclaim.go:166-179)
only when the validated set still covers the claimant, then the claimant
pipelines onto the freed resources."""

from __future__ import annotations

import logging
from collections import defaultdict
from typing import Dict, List

import jax
import numpy as np

from kube_batch_tpu.api.cluster_info import ClusterInfo
from kube_batch_tpu.api.snapshot import build_snapshot
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import FitFailure
from kube_batch_tpu.ops.eviction import EvictConfig, evict_solve

logger = logging.getLogger("kube_batch_tpu")


def _cluster_view(ssn) -> ClusterInfo:
    """Session → ClusterInfo. ALL jobs are included — the Pending-phase gate
    (reclaim.go:58-62 / preempt.go:59-63) applies to claimants only, via the
    snapshot's job_schedulable flag; Pending-phase jobs' Running tasks remain
    in the victim pool and their allocations in the fairness state."""
    cluster = ClusterInfo(ssn.spec)
    cluster.nodes = ssn.nodes
    cluster.queues = ssn.queues
    cluster.jobs = ssn.jobs
    return cluster


# plugins registering each Evictable fn kind (SURVEY.md §2.4)
_VICTIM_REGISTRANTS = {
    "reclaim": ("gang", "conformance", "proportion"),
    "preempt": ("gang", "conformance", "drf"),
}


def victim_gates(ssn, mode: str):
    """The set of plugins whose victim veto binds: the reference's Evictable
    dispatch takes the FIRST tier with any voting plugin
    (session_plugins.go:100-182) — later tiers never constrain victims."""
    registrants = _VICTIM_REGISTRANTS[mode]
    flag = "enabled_reclaimable" if mode == "reclaim" else "enabled_preemptable"
    for tier in ssn.tiers:
        voters = {
            opt.name
            for opt in tier.plugins
            if opt.name in registrants and getattr(opt, flag)
        }
        if voters:
            return voters
    return set()


def solve_claims(ssn, mode: str):
    """Run the eviction solve and decode to [(claimant_key, node_name,
    [victim_keys...])] in device claim order."""
    if not ssn.jobs or not ssn.nodes:
        return [], None
    cols = ssn.columns
    if cols is not None:
        if not cols.has_schedulable_pending():
            return [], None  # no claimants anywhere — idle cycle
        if not cols.has_running_victims():
            # nothing is running, so the evict solve is vacuous (victims
            # must be RUNNING on a node) — e.g. every first cycle of a
            # fresh cluster under the shipped 5-action conf
            return [], None
        snap, meta = cols.device_snapshot(ssn)
    else:
        snap, meta = build_snapshot(
            _cluster_view(ssn), excluded_nodes=ssn.session_excluded_nodes
        )
    gates = victim_gates(ssn, mode)
    # the idle-fit claimant gate (a declared improvement over reclaim.go —
    # PARITY "known divergences") is sound only when allocate actually runs
    # after reclaim to place the skipped claimants, and only when the
    # device fit is exact for them.  action_names is set by the scheduler
    # loop; with no pipeline information (direct action invocation) the
    # gate FAILS CLOSED to the reference behavior — an optimization whose
    # soundness depends on pipeline shape must not assume one.
    names = getattr(ssn, "action_names", None)
    idle_gate = (
        mode == "reclaim"
        # `reclaim.referenceExact: "true"` restores reclaim.go's behavior:
        # evict even for claimants free capacity could satisfy (PARITY.md)
        and not ssn.conf_flag("reclaim.referenceExact")
        and not ssn.host_only_predicates
        and names is not None
        and "allocate" in names
        and "reclaim" in names
        and names.index("allocate") > names.index("reclaim")
    )
    config = EvictConfig(
        mode=mode,
        idle_gate=idle_gate,
        gang=ssn.plugin_enabled("gang"),
        drf=ssn.plugin_enabled("drf"),
        proportion=ssn.plugin_enabled("proportion"),
        victim_gang="gang" in gates,
        victim_conformance="conformance" in gates,
        victim_proportion="proportion" in gates,
        victim_drf="drf" in gates,
        weights=ssn.score_weights,
    )
    from kube_batch_tpu.api.columns import resident_snap
    from kube_batch_tpu.guard import guard_of
    from kube_batch_tpu.obs.trace import tracer_of
    from kube_batch_tpu.parallel.mesh import (
        default_mesh,
        sentinel_sharded_evict_solve,
        sharded_evict_solve,
        should_shard,
    )

    gp = guard_of(ssn.cache)
    tracer = tracer_of(ssn.cache)
    sentinel = None
    audit_dev = None
    engaged: List[str] = []
    mesh = None
    # device-resident feature cache (see allocate's dispatch): the decode
    # below keeps reading the ORIGINAL host-backed snap
    with tracer.device_span("solve_dispatch", cols=cols, action=mode) as sp:
        if should_shard(snap.node_alloc.shape[0]):
            mesh = default_mesh()
            from kube_batch_tpu.parallel.mesh import _impl as _resolve_impl

            # demotion-aware path selection: a tripped shard_map path runs
            # the pjit oracle until its half-open probe re-promotes it
            impl = None if gp.allow("shard_map") else "pjit"
            if _resolve_impl(impl) == "shard_map":
                engaged = ["shard_map"]
            dev = resident_snap(cols, snap, mesh)
            if gp.enabled:
                result, v_dev, h_dev, e_dev = sentinel_sharded_evict_solve(
                    dev, config, mesh, impl=impl
                )
                sentinel = (v_dev, h_dev, e_dev)
            else:
                result = sharded_evict_solve(dev, config, mesh, impl=impl)
            if engaged and gp.audit_due(mode):
                # shadow oracle (tier 2): the pjit program on the same
                # snapshot, read back only after the host decode below
                audit_dev = sharded_evict_solve(dev, config, mesh,
                                                impl="pjit")
        else:
            dev = resident_snap(cols, snap)
            if gp.enabled:
                from kube_batch_tpu.ops.invariants import evict_sentinel_solve

                result, v_dev, h_dev, e_dev = evict_sentinel_solve(dev, config)
                sentinel = (v_dev, h_dev, e_dev)
            else:
                result = evict_solve(dev, config)
    sp.set(engaged=list(engaged))
    # this swap retired the what-if lease on donating backends — re-arm it
    # off the same (memoized) resident snapshot so serving doesn't stay
    # dark until the next cycle's allocate
    from kube_batch_tpu.actions.allocate import republish_query_lease

    republish_query_lease(ssn, snap, meta)
    # kbt: allow[KBT010] the evict pass's ONE sanctioned readback — batched
    # (three per-field np.asarray reads were three blocking transfers;
    # flagged by KBT010's first dogfood run); the guard sentinel's verdict
    # + histogram ride it
    with tracer.device_span("device_wait", action=mode):
        claim_node, evicted, victim_claimant, verdict, vhist, echeck = (
            jax.device_get(  # kbt: allow[KBT010] the annotated choke point ^
                (result.claim_node, result.evicted, result.victim_claimant,
                 sentinel[0] if sentinel is not None else np.int32(0),
                 sentinel[1] if sentinel is not None else None,
                 sentinel[2] if sentinel is not None else np.int32(0))
            )
        )
    claim_node = claim_node[: meta.n_tasks]
    evicted = evicted[: meta.n_tasks]
    victim_claimant = victim_claimant[: meta.n_tasks]

    if sentinel is not None:
        from kube_batch_tpu.api.types import TaskStatus as _TS
        from kube_batch_tpu.guard import consume_sentinel

        # host cross-checks: a claim must target a row the HOST believes
        # pending, a victim one the HOST believes RUNNING — the device
        # copies of those columns are exactly what a corruption flips; the
        # eligibility-checksum compare, histogram folding, bundle dump,
        # and resident+lease heal live in the SHARED consumer
        host_pending = np.asarray(snap.task_pending)[: meta.n_tasks]
        host_status = np.asarray(snap.task_status)[: meta.n_tasks]
        host_bad = int(
            np.sum((claim_node >= 0) & ~host_pending)
            + np.sum(evicted & (host_status != int(_TS.RUNNING)))
        )
        if not consume_sentinel(
            gp, mode, ssn, snap, dev, config, int(verdict), vhist,
            int(echeck), engaged, host_bad=host_bad,
        ):
            # condemned solve → fail closed: NO evictions from it
            return [], None

    task_job = np.asarray(snap.task_job)[: meta.n_tasks]

    def ref(ti: int):
        return (meta.job_uids[int(task_job[ti])], meta.task_keys[int(ti)])

    victims_by_claim: Dict[int, List[tuple]] = defaultdict(list)
    for vi in np.flatnonzero(evicted):
        ci = int(victim_claimant[vi])
        if ci >= 0:
            victims_by_claim[ci].append(ref(vi))
    claims = []
    for ti in np.flatnonzero(claim_node >= 0):
        claims.append(
            (ref(ti), meta.node_names[int(claim_node[ti])],
             victims_by_claim.get(int(ti), []))
        )
    if audit_dev is not None:
        # kbt: allow[KBT010] post-decode audit readback — the oracle solve
        # ran overlapped with the host decode above
        a_claim, a_evicted, a_vc = jax.device_get(
            (audit_dev.claim_node, audit_dev.evicted,
             audit_dev.victim_claimant)
        )
        n = meta.n_tasks
        mism = int(
            np.sum(a_claim[:n] != claim_node)
            + np.sum(a_evicted[:n] != evicted)
            + np.sum(a_vc[:n] != victim_claimant)
        )
        from kube_batch_tpu.guard import make_heal, sentinel_bundle_thunk

        gp.note_audit(
            mode, engaged, mism == 0,
            detail=f"{mode} shard_map-vs-pjit mismatch at {mism} rows",
            dump=sentinel_bundle_thunk(
                gp, mode, dev, config,
                {"audit_mismatches": mism, "engaged": engaged},
            ),
            heal=make_heal(ssn),
        )
        if mism:
            # the fast path is already demoted; the claims decoded above
            # came from the MISMATCHED program — fail closed for this cycle
            return [], meta
    return claims, meta


def find_task(ssn, ref: tuple):
    """(job_uid, task_key) → session TaskInfo, O(1)."""
    job = ssn.jobs.get(ref[0])
    return job.tasks.get(ref[1]) if job is not None else None


class ReclaimAction(Action):
    name = "reclaim"

    def execute(self, ssn) -> None:
        claims, _ = solve_claims(ssn, "reclaim")
        for claimant_ref, node_name, victim_refs in claims:
            task = find_task(ssn, claimant_ref)
            if task is None or not victim_refs:
                continue
            # host predicate re-check (reclaim.go:124), only for constraints
            # the device mask approximates (rich affinity / host ports /
            # pressure gates)
            node = ssn.nodes.get(node_name)
            try:
                if node is not None and (
                    task.needs_host_predicate or ssn.host_only_predicates
                ):
                    ssn.predicate(task, node)
            except FitFailure as e:
                logger.info("reclaim claim %s→%s rejected by host predicate: %s",
                            claimant_ref, node_name, e.reason)
                continue
            preemptees = [
                v.clone() for v in (find_task(ssn, r) for r in victim_refs)
                if v is not None
            ]
            # host validation net: the real tier-intersected verdict
            # (proportion deserved, gang survival, conformance) on the
            # device-selected set only — O(claims), not O(T × N)
            victims = ssn.reclaimable(task, preemptees)
            if not victims:
                continue
            total = ssn.spec.empty()
            for v in victims:
                total.add_(v.resreq)
            # sufficiency: victims must cover the claimant in EVERY dimension
            # (reclaim.go:150-163) — checked before any eviction happens
            if not task.init_resreq.less_equal(total):
                logger.info(
                    "reclaim claim %s→%s lost victims to host validation, skipped",
                    claimant_ref, node_name,
                )
                continue
            reclaimed = ssn.spec.empty()
            for victim in victims:  # immediate evict, no Statement
                ssn.evict(victim, "reclaim")
                reclaimed.add_(victim.resreq)
                if task.init_resreq.less_equal(reclaimed):
                    break
            ssn.pipeline(task, node_name)
