"""reclaim action (actions/reclaim/reclaim.go) — cross-queue eviction.

For each non-overused queue in order: pop job/task with Pending tasks, scan
nodes; collect Running tasks *from other queues* as reclaimees, ask
ssn.Reclaimable (proportion: victim's queue must stay ≥ deserved; gang:
victim's gang must survive), evict immediately (no Statement) until the
request is covered, then Pipeline the reclaimer (reclaim.go:107-199)."""

from __future__ import annotations

from kube_batch_tpu.api.types import PodGroupPhase, TaskStatus
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import FitFailure
from kube_batch_tpu.utils.priority_queue import PriorityQueue


class ReclaimAction(Action):
    name = "reclaim"

    def execute(self, ssn) -> None:
        queues = PriorityQueue(less=ssn.queue_order_fn)
        queue_set = set()
        preemptors_map = {}
        preemptor_tasks = {}

        for job in ssn.jobs.values():
            if job.pod_group and job.pod_group.phase == PodGroupPhase.PENDING:
                continue
            if ssn.job_valid(job) is not None:
                continue
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            if queue.name not in queue_set:
                queue_set.add(queue.name)
                queues.push(queue)
            pending = job.task_status_index.get(TaskStatus.PENDING, {})
            if pending:
                preemptors_map.setdefault(
                    job.queue, PriorityQueue(less=ssn.job_order_fn)
                ).push(job)
                tq = PriorityQueue(less=ssn.task_order_fn)
                for task in pending.values():
                    tq.push(task)
                preemptor_tasks[job.uid] = tq

        while queues:
            queue = queues.pop()
            if ssn.overused(queue):
                continue
            jobs = preemptors_map.get(queue.name)
            if not jobs:
                continue
            job = jobs.pop()
            tasks = preemptor_tasks.get(job.uid)
            if not tasks:
                continue
            task = tasks.pop()

            assigned = False
            for node in ssn.nodes.values():
                try:
                    ssn.predicate(task, node)
                except FitFailure:
                    continue
                reclaimees = []
                for t in node.tasks.values():
                    if t.status != TaskStatus.RUNNING:
                        continue
                    j = ssn.jobs.get(t.job)
                    if j is not None and j.queue != job.queue:
                        reclaimees.append(t.clone())
                victims = ssn.reclaimable(task, reclaimees)
                if not victims:
                    continue
                total = ssn.spec.empty()
                for v in victims:
                    total.add_(v.resreq)
                if total.less(task.init_resreq):
                    continue
                reclaimed = ssn.spec.empty()
                for victim in victims:
                    ssn.evict(victim, "reclaim")
                    reclaimed.add_(victim.resreq)
                    if task.init_resreq.less_equal(reclaimed):
                        break
                if task.init_resreq.less_equal(reclaimed):
                    ssn.pipeline(task, node.name)
                    assigned = True
                    break
            if assigned:
                queues.push(queue)
