"""preempt action (actions/preempt/preempt.go) — same-queue preemption,
device-solved phase 1 + host phase 2.

Phase 1 (inter-job within a queue, preempt.go:110-137): ops/eviction's
preempt-mode solve proposes (preemptor → node, victims) honoring conformance,
gang slack, and DRF share dominance; the host replays each preemptor job
through a Statement — evictions + pipelines commit only when the job reaches
Pipelined, mirroring the reference's commit gate.

The solve dispatch is GUARDED (kube_batch_tpu/guard): ``solve_claims``
(shared with reclaim) runs the sentinel-fused eviction program, consumes
its invariant verdict + host eligibility cross-checks, and FAILS CLOSED —
returning zero claims — when the solve is condemned, so no preemption can
ever be replayed from a corrupted or divergent result.

Phase 2 (intra-job task-priority rebalancing, preempt.go:145-174) stays a
host loop but only runs for jobs where a pending task outranks a running one
— the common all-equal-priority case short-circuits to nothing."""

from __future__ import annotations

import logging
from collections import defaultdict
from typing import Callable, Dict, List, Tuple

from kube_batch_tpu.actions.reclaim import find_task, solve_claims
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.api.types import PodGroupPhase, TaskStatus
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import FitFailure
from kube_batch_tpu.utils.priority_queue import PriorityQueue

logger = logging.getLogger("kube_batch_tpu")


class PreemptAction(Action):
    name = "preempt"

    def execute(self, ssn) -> None:
        self._phase1(ssn)
        self._phase2(ssn)

    # ---- phase 1: inter-job within queue (device-solved) ---------------
    def _phase1(self, ssn) -> None:
        claims, _ = solve_claims(ssn, "preempt")
        # group claims by preemptor job — the Statement boundary
        by_job: Dict[str, List[Tuple[TaskInfo, str, List[tuple]]]] = defaultdict(list)
        for claimant_ref, node_name, victim_refs in claims:
            task = find_task(ssn, claimant_ref)
            if task is not None and victim_refs:
                by_job[task.job].append((task, node_name, victim_refs))

        for job_uid, job_claims in by_job.items():
            job = ssn.jobs.get(job_uid)
            if job is None:
                continue
            stmt = ssn.statement()
            for task, node_name, victim_refs in job_claims:
                # host predicate re-check (preempt.go:191), only for
                # host-only constraints (see allocate replay)
                node = ssn.nodes.get(node_name)
                try:
                    if node is not None and (
                        task.needs_host_predicate or ssn.host_only_predicates
                    ):
                        ssn.predicate(task, node)
                except FitFailure:
                    continue
                preemptees = [
                    v.clone() for v in (find_task(ssn, r) for r in victim_refs)
                    if v is not None and v.status == TaskStatus.RUNNING
                ]
                victims = ssn.preemptable(task, preemptees)
                if not victims:
                    continue
                total = ssn.spec.empty()
                for v in victims:
                    total.add_(v.resreq)
                if not task.init_resreq.less_equal(total):
                    continue  # victims must cover every dimension
                # evict lowest-task-order first (preempt.go:219-237)
                vq = PriorityQueue(less=lambda l, r: not ssn.task_order_fn(l, r))
                for v in victims:
                    vq.push(v)
                preempted = ssn.spec.empty()
                while vq:
                    victim = vq.pop()
                    stmt.evict(victim, "preempt")
                    preempted.add_(victim.resreq)
                    if task.init_resreq.less_equal(preempted):
                        break
                stmt.pipeline(task, node_name)
            if ssn.job_pipelined(job):
                stmt.commit()
            else:
                stmt.discard()

    # ---- phase 2: intra-job (host, guarded) ----------------------------
    def _phase2(self, ssn) -> None:
        for job in ssn.jobs.values():
            # claimant gates (preempt.go:59-63): enqueued jobs in known queues
            if job.pod_group and job.pod_group.phase == PodGroupPhase.PENDING:
                continue
            if job.queue not in ssn.queues:
                continue
            pending = job.task_status_index.get(TaskStatus.PENDING, {})
            running = job.task_status_index.get(TaskStatus.RUNNING, {})
            if not pending or not running:
                continue
            # cheap skip: the reference runs phase 2 unconditionally
            # (preempt.go:145-174); we gate on the tiered task-order plugin
            # verdict — preempt only when some enabled plugin (priority, or a
            # custom task_order) says the best pending task outranks the
            # worst running one. The creation-index tie-break deliberately
            # does NOT open the gate: evicting an equal-rank sibling for its
            # slot is zero-gain work.  `preempt.referenceExact: "true"` on
            # any conf tier restores the reference's ungated phase 2
            # (PARITY.md "known divergences").
            if not ssn.conf_flag("preempt.referenceExact"):
                to = ssn.task_order_fn
                best_p = None
                for t in pending.values():
                    if best_p is None or to(t, best_p):
                        best_p = t
                worst_r = None
                for t in running.values():
                    if worst_r is None or to(worst_r, t):
                        worst_r = t
                verdict = ssn.task_order_plugin_verdict(best_p, worst_r)
                if verdict == 0:
                    # no task-order plugin voted (e.g. priority disabled in
                    # conf): fall back to comparing the extreme raw
                    # priorities — NOT best_p/worst_r, which were picked by
                    # the degenerate creation-order comparator and need not
                    # carry the extreme priorities
                    hi = max(t.priority for t in pending.values())
                    lo = min(t.priority for t in running.values())
                    verdict = -1 if hi > lo else 1
                if verdict >= 0:
                    continue  # nothing to rebalance
            tq = PriorityQueue(less=ssn.task_order_fn)
            for task in pending.values():
                tq.push(task)
            while tq:
                preemptor = tq.pop()

                def intra_job_filter(task: TaskInfo) -> bool:
                    return (
                        task.status == TaskStatus.RUNNING
                        and preemptor.job == task.job
                    )

                stmt = ssn.statement()
                assigned = self._preempt_host(ssn, stmt, preemptor, intra_job_filter)
                stmt.commit()  # phase 2 commits unconditionally (preempt.go:168)
                if not assigned:
                    break

    def _preempt_host(
        self,
        ssn,
        stmt,
        preemptor: TaskInfo,
        victim_filter: Callable[[TaskInfo], bool],
    ) -> bool:
        """Sequential preemption for one task (preempt.go:180-260)."""
        candidates = []
        for node in ssn.nodes.values():
            try:
                ssn.predicate(preemptor, node)
            except FitFailure:
                continue
            candidates.append((ssn.node_order(preemptor, node), node))
        candidates.sort(key=lambda sn: -sn[0])

        for _, node in candidates:
            preemptees = [t.clone() for t in node.tasks.values() if victim_filter(t)]
            victims = ssn.preemptable(preemptor, preemptees)
            if not victims:
                continue
            total = ssn.spec.empty()
            for v in victims:
                total.add_(v.resreq)
            if not preemptor.init_resreq.less_equal(total):
                continue  # victims must cover every dimension
            vq = PriorityQueue(less=lambda l, r: not ssn.task_order_fn(l, r))
            for v in victims:
                vq.push(v)
            preempted = ssn.spec.empty()
            while vq:
                victim = vq.pop()
                stmt.evict(victim, "preempt")
                preempted.add_(victim.resreq)
                if preemptor.init_resreq.less_equal(preempted):
                    break
            if preemptor.init_resreq.less_equal(preempted):
                stmt.pipeline(preemptor, node.name)
                return True
        return False
