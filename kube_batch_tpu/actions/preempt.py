"""preempt action (actions/preempt/preempt.go) — same-queue preemption.

Phase 1: between jobs in a queue — starved (pending-task) jobs pipeline onto
resources freed by evicting Running victims of *other* jobs in the same
queue; the Statement commits only once the preemptor job is Pipelined
(preempt.go:110-137). Phase 2: within a job — task-priority rebalancing,
committed unconditionally (preempt.go:145-174).

Victim choice per node: filter → ssn.Preemptable (tier-intersection of
conformance ∩ gang ∩ drf) → validate total covers the request → evict
lowest-task-order first until covered (preempt.go:180-277)."""

from __future__ import annotations

from typing import Callable, List

from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.api.types import PodGroupPhase, TaskStatus
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import FitFailure
from kube_batch_tpu.utils.priority_queue import PriorityQueue


class PreemptAction(Action):
    name = "preempt"

    def execute(self, ssn) -> None:
        preemptors_map = {}
        preemptor_tasks = {}
        under_request = []
        queues = {}

        for job in ssn.jobs.values():
            if job.pod_group and job.pod_group.phase == PodGroupPhase.PENDING:
                continue
            if ssn.job_valid(job) is not None:
                continue
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            queues[queue.name] = queue
            pending = job.task_status_index.get(TaskStatus.PENDING, {})
            if pending:
                preemptors_map.setdefault(
                    job.queue, PriorityQueue(less=ssn.job_order_fn)
                ).push(job)
                under_request.append(job)
                tq = PriorityQueue(less=ssn.task_order_fn)
                for task in pending.values():
                    tq.push(task)
                preemptor_tasks[job.uid] = tq

        for queue in queues.values():
            # Phase 1: inter-job within queue
            preemptors = preemptors_map.get(queue.name)
            while preemptors:
                preemptor_job = preemptors.pop()
                stmt = ssn.statement()
                assigned = False
                while preemptor_tasks[preemptor_job.uid]:
                    preemptor = preemptor_tasks[preemptor_job.uid].pop()

                    def inter_job_filter(task: TaskInfo) -> bool:
                        if task.status != TaskStatus.RUNNING:
                            return False
                        job = ssn.jobs.get(task.job)
                        if job is None:
                            return False
                        return job.queue == preemptor_job.queue and preemptor.job != task.job

                    if self._preempt(ssn, stmt, preemptor, inter_job_filter):
                        assigned = True
                    if ssn.job_pipelined(preemptor_job):
                        break
                if ssn.job_pipelined(preemptor_job):
                    stmt.commit()
                    if assigned:
                        preemptors.push(preemptor_job)
                else:
                    stmt.discard()

            # Phase 2: intra-job task-priority preemption
            for job in under_request:
                tq = preemptor_tasks.get(job.uid)
                while tq:
                    preemptor = tq.pop()

                    def intra_job_filter(task: TaskInfo) -> bool:
                        return task.status == TaskStatus.RUNNING and preemptor.job == task.job

                    stmt = ssn.statement()
                    assigned = self._preempt(ssn, stmt, preemptor, intra_job_filter)
                    stmt.commit()
                    if not assigned:
                        break

    def _preempt(
        self,
        ssn,
        stmt,
        preemptor: TaskInfo,
        victim_filter: Callable[[TaskInfo], bool],
    ) -> bool:
        """(preempt.go:180-260)"""
        # predicate + score + sort nodes descending
        candidates = []
        for node in ssn.nodes.values():
            try:
                ssn.predicate(preemptor, node)
            except FitFailure:
                continue
            candidates.append((ssn.node_order(preemptor, node), node))
        candidates.sort(key=lambda sn: -sn[0])

        for _, node in candidates:
            preemptees = [t.clone() for t in node.tasks.values() if victim_filter(t)]
            victims = ssn.preemptable(preemptor, preemptees)
            if not victims:
                continue
            total = ssn.spec.empty()
            for v in victims:
                total.add_(v.resreq)
            if total.less(preemptor.init_resreq):
                continue  # not enough even with every victim
            # evict lowest-task-order first (victimsQueue uses !TaskOrderFn)
            vq = PriorityQueue(less=lambda l, r: not ssn.task_order_fn(l, r))
            for v in victims:
                vq.push(v)
            preempted = ssn.spec.empty()
            while vq:
                victim = vq.pop()
                stmt.evict(victim, "preempt")
                preempted.add_(victim.resreq)
                if preemptor.init_resreq.less_equal(preempted):
                    break
            if preemptor.init_resreq.less_equal(preempted):
                stmt.pipeline(preemptor, node.name)
                return True
        return False
