"""allocate action — the hot placement pass, device-solved.

The reference's allocate (actions/allocate/allocate.go) is the
O(tasks × nodes) host loop; here it becomes: build the device snapshot, run
ops/assignment.allocate_solve (one compiled program: predicates, scoring,
fairness, ordering, gang commit/discard), then replay the resulting
assignment through the session's Statement verbs so host state, plugin event
handlers, and the binder observe exactly the sequential semantics
(statement.go:29-337)."""

from __future__ import annotations

import logging
from collections import defaultdict
from typing import Dict, List, Tuple

import numpy as np

from kube_batch_tpu.api.cluster_info import ClusterInfo
from kube_batch_tpu.api.snapshot import build_snapshot
from kube_batch_tpu.api.types import PodGroupPhase
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import FitFailure
from kube_batch_tpu.ops.assignment import AllocateConfig, allocate_solve

logger = logging.getLogger("kube_batch_tpu")


class AllocateAction(Action):
    name = "allocate"

    def execute(self, ssn) -> None:
        # session → ClusterInfo view (the session's jobs/nodes/queues ARE the
        # snapshot clone; invalid jobs were already dropped at open). ALL jobs
        # are included so fairness state (queue_alloc/job_allocated) counts
        # Pending-phase jobs' allocations; the Pending-phase gate
        # (allocate.go:50-52) is the snapshot's job_schedulable flag
        cluster = ClusterInfo(ssn.spec)
        cluster.nodes = ssn.nodes
        cluster.queues = ssn.queues
        cluster.jobs = ssn.jobs
        if not cluster.jobs or not cluster.nodes:
            return

        snap, meta = build_snapshot(cluster)
        config = AllocateConfig(
            gang=ssn.plugin_enabled("gang"),
            drf=ssn.plugin_enabled("drf"),
            proportion=ssn.plugin_enabled("proportion"),
            weights=ssn.score_weights,
        )
        result = allocate_solve(snap, config)
        assigned = np.asarray(result.assigned)[: meta.n_tasks]
        pipelined = np.asarray(result.pipelined)[: meta.n_tasks]
        task_job = np.asarray(snap.task_job)[: meta.n_tasks]
        pending = np.asarray(snap.task_pending)[: meta.n_tasks]
        self._record_fit_errors(ssn, meta, result, assigned, task_job, pending)

        # group placements by job, in device task order
        by_job: Dict[int, List[Tuple[str, int, bool]]] = defaultdict(list)
        for ti in np.flatnonzero(assigned >= 0):
            by_job[int(task_job[ti])].append(
                (meta.task_keys[ti], int(assigned[ti]), bool(pipelined[ti]))
            )

        # replay through Statement per job — host is authoritative for the
        # commit gate (JobReady, allocate.go:192-196)
        for ji, placements in by_job.items():
            job = ssn.jobs.get(meta.job_uids[ji])
            if job is None:
                continue
            stmt = ssn.statement()
            for task_key, ni, pipe in placements:
                task = job.tasks.get(task_key)
                if task is None:
                    continue
                node_name = meta.node_names[ni]
                # validation net: re-check a *proposed* placement only when
                # the task carries host-only constraints (host ports, rich
                # affinity — TaskInfo.needs_host_predicate); the device mask
                # is exact for everything else, so the common case skips the
                # per-placement predicate walk entirely
                node = ssn.nodes.get(node_name)
                try:
                    if node is not None and (
                        task.needs_host_predicate or ssn.host_only_predicates
                    ):
                        ssn.predicate(task, node)
                    # live fit re-check: a host-fallback placement (below) may
                    # have consumed capacity the device solve promised to this
                    # placement; node.add_task does not re-verify fit
                    if node is not None and not (
                        (not pipe and task.init_resreq.less_equal(node.idle))
                        or (pipe and task.init_resreq.less_equal(node.releasing))
                    ):
                        raise FitFailure("node resources taken by host fallback")
                except FitFailure as e:
                    logger.info("device placement %s→%s rejected by host predicate: %s",
                                task_key, node_name, e.reason)
                    # the device would re-propose the same node next cycle
                    # (the solve is deterministic), so fall back to the
                    # reference's own sequential path for this task
                    self._host_place(ssn, stmt, task)
                    continue
                if pipe:
                    stmt.pipeline(task, node_name)
                else:
                    stmt.allocate(task, node_name)
            if ssn.job_ready(job):
                stmt.commit()
            else:
                logger.info(
                    "job %s not ready after device solve (%d placements), discarding",
                    job.uid,
                    len(placements),
                )
                stmt.discard()

    def _record_fit_errors(self, ssn, meta, result, assigned, task_job, pending) -> None:
        """FitErrors for unplaced pending tasks (allocate.go:151-155). The
        reason histogram comes out of the solve itself (AllocateResult
        .fail_hist) — diagnostics add no extra [T, N] dispatch."""
        from kube_batch_tpu.api.job_info import FitErrors
        from kube_batch_tpu.ops.feasibility import REASON_MESSAGES

        unplaced = np.flatnonzero(pending & (assigned < 0))
        if unplaced.size == 0:
            return
        hist = np.asarray(result.fail_hist)[: meta.n_tasks]
        for ti in unplaced:
            job = ssn.jobs.get(meta.job_uids[int(task_job[ti])])
            if job is None:
                continue
            task = job.tasks.get(meta.task_keys[int(ti)])
            if task is None:
                continue
            counts = dict(zip(REASON_MESSAGES, hist[ti].tolist()))
            if not any(counts.values()):
                # task was feasible at cycle start but lost the contention —
                # capacity went to other tasks this cycle
                counts = {
                    "node(s) resources were consumed by other tasks this cycle":
                        meta.n_nodes
                }
            fe = FitErrors()
            fe.set_histogram(counts, meta.n_nodes)
            job.nodes_fit_errors[task.uid] = fe

    def _host_place(self, ssn, stmt, task) -> bool:
        """Sequential placement for a task the device model couldn't encode:
        predicate every node, pick the best-scoring fit — exactly
        allocate.go:151-184 (PredicateNodes → PrioritizeNodes →
        SelectBestNode → Allocate on Idle / Pipeline on Releasing)."""
        best, best_score = None, None
        for node in ssn.nodes.values():
            try:
                ssn.predicate(task, node)
            except FitFailure:
                continue
            if not (task.init_resreq.less_equal(node.idle)
                    or task.init_resreq.less_equal(node.releasing)):
                continue
            score = ssn.node_order(task, node)
            if best is None or score > best_score:
                best, best_score = node, score
        if best is None:
            return False
        # allocate-vs-pipeline is decided on the already-selected node
        # (allocate.go:161-184), not folded into the selection
        if task.init_resreq.less_equal(best.idle):
            stmt.allocate(task, best.name)
        else:
            stmt.pipeline(task, best.name)
        return True
