"""allocate action — the hot placement pass, device-solved.

The reference's allocate (actions/allocate/allocate.go) is the
O(tasks × nodes) host loop; here it becomes: build the device snapshot, run
ops/assignment.allocate_solve (one compiled program: predicates, scoring,
fairness, ordering, gang commit/discard), then apply the resulting
assignment to host state.

The apply is *vectorized*: jobs whose readiness gate is the gang arithmetic
(JobReady ⊆ {gang}) and whose tasks carry no host-only constraints take a
bulk path — readiness decided up front from the snapshot's ready counts
(so discards never mutate anything), then per-job index moves and presummed
per-node accounting (job_info/node_info bulk methods), batched event
handlers, and one bulk_bind for every committed placement.  Jobs needing
host-side predicate re-validation (ports, rich affinity, pressure gates) or
nonstandard JobReady vetoes replay through the per-task Statement path with
exactly the sequential semantics (statement.go:29-337).
"""

from __future__ import annotations

import logging
import os
from kube_batch_tpu.utils import telemetry
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from kube_batch_tpu.api.cluster_info import ClusterInfo
from kube_batch_tpu.api.columns import resident_snap
from kube_batch_tpu.api.snapshot import build_snapshot
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import FitFailure, JOB_READY
from kube_batch_tpu import metrics
from kube_batch_tpu.ops.assignment import (
    AllocateConfig,
    allocate_solve,
    allocate_topk_solve,
)

logger = logging.getLogger("kube_batch_tpu")

# --------------------------------------------------------------------------
# top-K candidate compaction (KB_TOPK) — dispatch-side planning
# --------------------------------------------------------------------------

#: the pending-row bucket ladder.  The compacted solve's task axis is ONE
#: FIXED bucket per task-capacity shape: the largest ladder value at or
#: below capT/4 (compaction only runs where it wins — pending well under
#: the task bucket).  Deriving the bucket from capT instead of the
#: instantaneous pending count makes steady-state retraces structurally
#: impossible: the bucket cannot move while the cache's shape buckets
#: don't, no matter how the pending count wobbles (an instantaneous-count
#: ladder flapped a boundary mid-steady and retraced — measured, rejected).
#: Cycles whose pending exceeds the bucket (cold starts) run the full
#: program, which is the right shape there anyway.
TOPK_PEND_BUCKETS = (256, 512, 1024, 2048, 4096, 8192, 16384, 32768, 65536)

#: default candidate-list width — the measured knee at bench scales; the
#: exhaustion re-entry keeps ANY width bit-exact, so K tunes cost, never
#: correctness
TOPK_DEFAULT = 32


def resolve_topk() -> int:
    """KB_TOPK: candidate-list width K (default 32); 0 disables compaction
    and keeps the full-matrix program as the oracle — same contract as
    KB_SHARD_MAP=0 / KB_PIPELINE=0.  An unparsable value DISABLES
    compaction (a typo'd attempt to turn the knob off must not silently
    re-enable it and invalidate an oracle comparison)."""
    raw = os.environ.get("KB_TOPK", "").strip()
    if not raw:
        return TOPK_DEFAULT
    try:
        return max(0, int(raw))
    except ValueError:
        logger.warning("unparsable KB_TOPK=%r; compaction disabled", raw)
        return 0


def resolve_warm() -> bool:
    """KB_WARM: carry the candidate table across cycles and repair it from
    the resident-scatter deltas (default ON whenever compaction runs);
    KB_WARM=0 rebuilds the table cold every solve — the bit-exactness
    oracle, same contract as KB_TOPK=0 / KB_SHARD_MAP=0 / KB_PIPELINE=0.
    Any value other than an explicit enable counts as OFF (the KB_TOPK
    garbage-disables discipline: a typo'd disable attempt must not
    silently re-enable the fast path under an oracle comparison)."""
    raw = os.environ.get("KB_WARM", "").strip().lower()
    if not raw:
        return True
    return raw in ("1", "true", "on", "yes")


def _warm_state(cols, mesh, impl, config, guard, warm: bool, k: int):
    """The carried-table state for this dispatch slot, or None when the
    warm path must not run: opt-out (KB_WARM=0), guard demotion, the
    Pallas head (its fused build is a cold-build kernel), no ColumnStore,
    or an explicitly cold caller (the backfill real-request pass solves a
    mid-cycle snapshot and must not consume the allocate carry's deltas).

    Called BEFORE the resident swap so a fresh state still absorbs this
    cycle's delta record and cold-builds the same dispatch."""
    if (
        not warm or cols is None or k <= 0
        or not resolve_warm()
        or config.use_pallas
        # a custom score row may read ANY snapshot field (the seam's
        # contract) — including per-cycle state the carry's invalidation
        # sources don't track (queue_alloc, job rows, statuses), which
        # would silently stale the carried keys.  Same policy as the
        # columnar host fast path: custom scoring defers to the general
        # machinery (here: the cold per-solve build).
        or config.weights.extra_rows
        or (guard is not None and not guard.allow("warm"))
    ):
        return None
    return cols.warm_table_state(mesh=mesh, impl=impl)


def _warm_commit(wstate, call):
    """Run one warm solve thunk and adopt its refreshed table (the last
    two outputs of every warm program).  ANY failure drops the carried
    state wholesale — plan() already consumed the invalidation
    accumulators, and off-CPU the solve donated the stale table buffers,
    so a carried-on state would pair stale (or deleted) entries with the
    new bucket order."""
    try:
        out = call()
    except BaseException:
        wstate.drop()
        raise
    wstate.commit(out[-2], out[-1])
    return out


def warm_k_min(k: int) -> int:
    """The erosion floor of the carried table: a row re-ranks when its
    valid prefix thins below (a per-row staggered threshold above) this.
    K/4, not K: a thin table still answers EXACTLY — the head's argmax
    over an exact prefix equals the full argmax while any entry fits, and
    exhaustion re-enters the full-matrix head the same round — so the
    floor trades re-rank traffic against fallback probability, and
    ``topk_exhausted`` (read back every cycle) monitors the latter."""
    return max(4, k // 4)


def _warm_plan(state, cols, pend_rows, k: int, config, tracer):
    """The post-swap invalidation plan (api/resident.WarmTableState.plan),
    span-attributed as table maintenance under the owning solve_dispatch
    span.  None = the delta chain is broken this cycle (no per-cycle
    resident cache, or a swap the state did not absorb) — the dispatch
    falls back to the cold per-solve build."""
    if state is None:
        return None
    if tracer is None:
        return state.plan(cols, pend_rows, k, config)
    with tracer.span("table_invalidate") as sp:
        plan = state.plan(cols, pend_rows, k, config)
        if plan is not None:
            sp.set(cold=bool(plan["cold"]),
                   reranked=int(state.last.get("reranked", 0)),
                   changed=int(state.last.get("changed", 0)))
    return plan


def topk_bucket_for(capT: int):
    """The ONE pending bucket a task capacity of ``capT`` compacts into —
    the largest ladder value at or below capT/4, or None below the
    smallest rung (tiny clusters: the full program is already cheap)."""
    fit = [b for b in TOPK_PEND_BUCKETS if b <= capT // 4]
    return fit[-1] if fit else None


def plan_topk_bucket(snap, cols, k: int):
    """The dispatch's compaction plan: (pend_rows [P] np.int32, K) or
    (None, 0) when the full-matrix program should run.

    Compaction is declined when it cannot win: no pending rows (idle
    cycles are skipped upstream anyway), K no smaller than the node
    bucket, a task bucket too small to carry a compaction rung, or a
    pending set past the bucket (the cold-start regime — the full
    program IS the right shape there).  The bucket itself is a pure
    function of the task-capacity shape (:func:`topk_bucket_for`), so
    the compacted program's shapes can only change when the cache's own
    shape buckets do — zero steady-state retraces by construction."""
    del cols  # the bucket is shape-derived; no per-cache state
    capT = int(snap.task_req.shape[0])
    capN = int(snap.node_idle.shape[0])
    if k <= 0 or k >= capN:
        return None, 0
    bucket = topk_bucket_for(capT)
    if bucket is None:
        return None, 0
    rows = np.flatnonzero(np.asarray(snap.task_pending))
    if rows.size == 0 or rows.size > bucket:
        return None, 0
    pend_rows = np.full(bucket, -1, np.int32)
    pend_rows[: rows.size] = rows.astype(np.int32)
    return pend_rows, k


def _run_bounds(sorted_arr) -> list:
    """[lo..hi) run boundaries of equal values in a sorted array — the
    segmentation idiom shared by the per-job and per-node replay groupings."""
    return np.concatenate(
        ([0], np.flatnonzero(np.diff(sorted_arr)) + 1, [sorted_arr.size])
    ).tolist()


class _PhaseMarks:
    """Accumulating wall-clock sub-phase marks: each mark() charges the
    elapsed time since the previous one to `sink[key]` (in ms)."""

    def __init__(self, sink: Dict[str, float]):
        self.sink = sink
        self.t = telemetry.perf_counter()

    def mark(self, key: str) -> None:
        now = telemetry.perf_counter()
        self.sink[key] = self.sink.get(key, 0.0) + (now - self.t) * 1e3
        self.t = now


def _pallas_enabled(ssn) -> bool:
    """Opt into the fused Pallas round-head kernel via an `allocate.pallas`
    argument on any conf tier plugin (Arguments are free-form string maps,
    arguments.go:26-66) or env KB_PALLAS=1 (pallas_kernels.py)."""
    env = os.environ.get("KB_PALLAS", "").lower() in ("1", "true", "yes")
    return ssn.conf_flag("allocate.pallas", default=env)


def build_session_snapshot(ssn):
    """(DeviceSnapshot, meta) for the session — columnar row space when the
    session is exclusive, object rebuild for isolated sessions.  Shared by
    execute() and the backfill real-request pass so both solve the
    identically-constructed problem."""
    cols = ssn.columns
    if cols is not None:
        return cols.device_snapshot(ssn)
    cluster = ClusterInfo(ssn.spec)
    cluster.nodes = ssn.nodes
    cluster.queues = ssn.queues
    cluster.jobs = ssn.jobs
    return build_snapshot(cluster, excluded_nodes=ssn.session_excluded_nodes)


def session_allocate_config(ssn) -> AllocateConfig:
    """The solve configuration a session implies (plugin enables + opt-ins);
    `weights` is the session's ScoreWeights (ops/scoring.py)."""
    return AllocateConfig(
        gang=ssn.plugin_enabled("gang"),
        drf=ssn.plugin_enabled("drf"),
        proportion=ssn.plugin_enabled("proportion"),
        use_pallas=_pallas_enabled(ssn),
        weights=ssn.score_weights,
    )


def dispatch_allocate_solve(snap, config, cols=None, guard=None,
                            warm=False, tracer=None):
    """Shard-or-local solve dispatch; returns (result, mode, topk_info,
    ginfo).

    ``warm=True`` (the allocate action's steady path) lets the compacted
    program run WARM-STARTED: the [P, K] candidate table carries across
    cycles on device, invalidated from the resident-scatter delta records
    and repaired in-program (ops.assignment.warm_allocate_solve) instead
    of re-ranked from scratch — ``topk_info["warm"]`` records the plan
    (cold / re-ranked rows / changed nodes).  ``tracer`` attributes the
    table maintenance as children of the caller's solve_dispatch span.

    With a ColumnStore, the ingest-static feature columns ride the
    device-resident cache (columns.resident_features) so per-cycle
    host→device traffic is only the truly per-cycle arrays; the caller's
    `snap` stays host-backed for its numpy reads.

    ``topk_info`` records the compaction decision ({"k", "bucket"} when
    the KB_TOPK compacted program ran, None otherwise) — the action folds
    the solve's exhaustion counters into it for the bench/sim.

    ``guard`` (a :class:`kube_batch_tpu.guard.GuardPlane`) makes the
    dispatch GUARDED: demoted fast paths fall back to their oracles
    (KB_TOPK=0 / pjit / use_pallas off) and the sentinel-fused program
    variants run, returning the invariant verdict + histogram in ``ginfo``
    ("sentinel") alongside the engaged fast-path names ("engaged") and the
    compaction plan ("pend_rows", for the diagnostics bundle).  The caller
    MUST feed the verdict through ``guard.consume_verdict`` before acting
    on the result (rule KBT013 enforces this at every dispatch site)."""
    # kbt: allow[KBT013] the dispatch RETURNS the sentinel verdict to its
    # caller — consume_verdict happens at the action's readback, the one
    # place the verdict exists on host
    from kube_batch_tpu.parallel.mesh import (
        TASK_AXIS,
        default_mesh,
        sentinel_sharded_allocate_solve,
        sentinel_sharded_allocate_topk_solve,
        sharded_allocate_solve,
        sharded_allocate_topk_solve,
        should_shard,
    )

    sentinel_on = guard is not None and guard.enabled
    impl = None
    if guard is not None and not guard.allow("shard_map"):
        impl = "pjit"  # shard_map demoted → the pjit oracle
    if guard is not None and not guard.allow("pallas") and config.use_pallas:
        config = config._replace(use_pallas=False)
    k = resolve_topk()
    if guard is not None and not guard.allow("topk"):
        k = 0  # compaction demoted → the full-matrix oracle
    pend_rows, k = plan_topk_bucket(snap, cols, k)

    def ginfo(engaged, sentinel, dev, cfg):
        return {
            "engaged": engaged, "sentinel": sentinel,
            "pend_rows": pend_rows, "impl": impl,
            # the exact (post-resident-swap) snapshot the solve consumed —
            # what a trip's diagnostics bundle must capture
            "dev": dev,
            # the EFFECTIVE config the program ran with (demotions applied:
            # use_pallas off, topk as dispatched) — a bundle must replay
            # the condemned program, not the session's nominal one
            "config": cfg,
        }

    if should_shard(snap.node_alloc.shape[0]):
        mesh = default_mesh()
        from kube_batch_tpu.parallel.mesh import _impl as resolve_impl

        engaged = ["shard_map"] if resolve_impl(impl) == "shard_map" else []
        if config.use_pallas:
            engaged.append("pallas")
        # the compacted body requires a 1-D node mesh — the 2-D task-axis
        # grid is the cold-start HBM escape, where compaction can't apply
        if pend_rows is not None and dict(mesh.shape).get(TASK_AXIS, 1) == 1:
            info = {"k": k, "bucket": int(pend_rows.shape[0])}
            cfg = config._replace(topk=k)
            wstate = _warm_state(cols, mesh, resolve_impl(impl), config,
                                 guard, warm, k)
            dev = resident_snap(cols, snap, mesh)
            wplan = _warm_plan(wstate, cols, pend_rows, k, config, tracer)
            if wplan is not None:
                from kube_batch_tpu.parallel.mesh import (
                    sentinel_sharded_warm_allocate_solve,
                    sharded_warm_allocate_solve,
                )

                info["warm"] = dict(wstate.last)
                cfg_w = config._replace(topk=wplan["w"])
                ptuple = (wplan["row_map"], wplan["changed"],
                          wplan["rerank_rows"], wplan["rerank_slots"])
                if sentinel_on:
                    res, v, h, e, _t, _er = _warm_commit(
                        wstate,
                        lambda: sentinel_sharded_warm_allocate_solve(
                            dev, pend_rows, wplan["table"], ptuple, cfg_w,
                            warm_k_min(k), mesh, impl=impl,
                        ),
                    )
                    # ginfo carries the EFFECTIVE config (topk=W): a trip
                    # bundle replays the cold compacted program at the
                    # condemned program's own width (the carry itself is
                    # not replayable — the table is cross-cycle state)
                    return (res, "sharded", info,
                            ginfo(engaged + ["topk", "warm"], (v, h, e),
                                  dev, cfg_w))
                res, _t, _er = _warm_commit(
                    wstate,
                    lambda: sharded_warm_allocate_solve(
                        dev, pend_rows, wplan["table"], ptuple, cfg_w,
                        warm_k_min(k), mesh, impl=impl,
                    ),
                )
                return (res, "sharded", info,
                        ginfo(engaged + ["topk", "warm"], None, dev, cfg_w))
            if sentinel_on:
                res, v, h, e = sentinel_sharded_allocate_topk_solve(
                    dev, pend_rows, cfg, mesh, impl=impl
                )
                return (res, "sharded", info,
                        ginfo(engaged + ["topk"], (v, h, e), dev, cfg))
            return (
                sharded_allocate_topk_solve(dev, pend_rows, cfg, mesh,
                                            impl=impl),
                "sharded", info, ginfo(engaged + ["topk"], None, dev, cfg),
            )
        dev = resident_snap(cols, snap, mesh)
        if sentinel_on:
            res, v, h, e = sentinel_sharded_allocate_solve(
                dev, config, mesh, impl=impl
            )
            return (res, "sharded", None,
                    ginfo(engaged, (v, h, e), dev, config))
        return (
            sharded_allocate_solve(dev, config, mesh, impl=impl),
            "sharded", None, ginfo(engaged, None, dev, config),
        )
    engaged = ["pallas"] if config.use_pallas else []
    if pend_rows is not None:
        info = {"k": k, "bucket": int(pend_rows.shape[0])}
        cfg = config._replace(topk=k)
        wstate = _warm_state(cols, None, None, config, guard, warm, k)
        dev = resident_snap(cols, snap)
        wplan = _warm_plan(wstate, cols, pend_rows, k, config, tracer)
        if wplan is not None:
            from kube_batch_tpu.ops.assignment import warm_allocate_solve

            info["warm"] = dict(wstate.last)
            cfg_w = config._replace(topk=wplan["w"])
            ptuple = (wplan["row_map"], wplan["changed"],
                      wplan["rerank_rows"], wplan["rerank_slots"])
            if sentinel_on:
                from kube_batch_tpu.ops.invariants import (
                    warm_allocate_sentinel_solve,
                )

                res, v, h, e, _t, _er = _warm_commit(
                    wstate,
                    lambda: warm_allocate_sentinel_solve(
                        dev, pend_rows, wplan["table"], ptuple, cfg_w,
                        warm_k_min(k),
                    ),
                )
                # effective config (topk=W) — see the sharded site
                return (res, "single", info,
                        ginfo(engaged + ["topk", "warm"], (v, h, e), dev,
                              cfg_w))
            res, _t, _er = _warm_commit(
                wstate,
                lambda: warm_allocate_solve(
                    dev, pend_rows, wplan["table"], ptuple, cfg_w,
                    warm_k_min(k),
                ),
            )
            return (res, "single", info,
                    ginfo(engaged + ["topk", "warm"], None, dev, cfg_w))
        if sentinel_on:
            from kube_batch_tpu.ops.invariants import (
                allocate_topk_sentinel_solve,
            )

            res, v, h, e = allocate_topk_sentinel_solve(dev, pend_rows, cfg)
            return (res, "single", info,
                    ginfo(engaged + ["topk"], (v, h, e), dev, cfg))
        return (
            allocate_topk_solve(dev, pend_rows, cfg),
            "single", info, ginfo(engaged + ["topk"], None, dev, cfg),
        )
    dev = resident_snap(cols, snap)
    if sentinel_on:
        from kube_batch_tpu.ops.invariants import allocate_sentinel_solve

        res, v, h, e = allocate_sentinel_solve(dev, config)
        return res, "single", None, ginfo(engaged, (v, h, e), dev, config)
    return (allocate_solve(dev, config), "single", None,
            ginfo(engaged, None, dev, config))


def dispatch_allocate_oracle(snap, config, cols, mode):
    """The shadow-oracle dispatch for an allocate-shaped audit: the same
    snapshot through the all-oracle program (KB_TOPK=0, use_pallas off;
    pjit impl when the committed solve ran sharded).  ``resident_snap`` is
    memoized on the snap object, so this re-dispatch is device work only —
    no re-upload."""
    oracle_cfg = config._replace(topk=0, use_pallas=False)
    if mode == "sharded":
        from kube_batch_tpu.parallel.mesh import (
            default_mesh,
            sharded_allocate_solve,
        )

        mesh = default_mesh()
        return sharded_allocate_solve(
            resident_snap(cols, snap, mesh), oracle_cfg, mesh, impl="pjit"
        )
    return allocate_solve(resident_snap(cols, snap), oracle_cfg)


def republish_query_lease(ssn, snap=None, meta=None, build=None) -> None:
    """THE guarded what-if lease publish — every publish path (allocate's
    solve and idle/empty cycles, reclaim/backfill/preempt's post-swap
    re-arms) goes through here, so the gate, the version-token source, and
    the failure policy live once.

    On donating backends EVERY resident swap retires the published lease
    (serve/lease.py) — and reclaim, backfill, and preempt all swap after
    allocate's publish, so without the post-dispatch re-arms the query
    plane would sit leaseless from the last swap until the NEXT cycle's
    allocate: the whole schedule period, exactly on the hardware serving
    targets.  ``resident_snap`` is memoized on the exact ``snap`` object
    the caller's dispatch used, so a re-arm is bookkeeping, not device
    work.  ``build`` is the lazy (snap, meta) builder for the idle/empty
    paths: the snapshot rebuild runs only when the publish is actually
    owed (no plane attached, an isolated/object session, or a live lease
    already covering the open's version — CPU: swaps never retire — all
    skip it).  A publish failure degrades serving, never the cycle."""
    qp = getattr(ssn.cache, "query_plane", None)
    if qp is None or ssn.columns is None:
        return
    try:
        if not qp.needs_publish(
            int(getattr(ssn.cache, "last_open_version", 0))
        ):
            return
        if build is not None:
            snap, meta = build()
        qp.publish_session(ssn, snap, meta)
    except Exception:  # noqa: BLE001 — the write path outranks serving
        logger.exception("whatif lease publication failed")


class AllocateAction(Action):
    name = "allocate"

    def __init__(self):
        # per-phase ms of the most recent execute() — read by bench.py via
        # get_action("allocate").last_phase_ms
        self.last_phase_ms: Dict[str, float] = {}
        # "single" | "sharded" — which solve the last execute() dispatched
        self.last_solve_mode = "single"
        # bidding rounds the last solve executed (early exits make this
        # the measured convergence, not the 6x3 cap)
        self.last_solve_rounds = 0
        # candidate-compaction record of the most recent execute():
        # {"k", "bucket", "exhausted", "reentries"} when the KB_TOPK
        # compacted program ran, None otherwise (bench/sim evidence)
        self.last_topk = None
        # warm-carry record ({"cold", "reranked", "changed", ...}) when
        # the KB_WARM carried-table program ran, None otherwise
        self.last_warm = None
        # fallback pressure of the most recent execute() (VERDICT r2 #6)
        self.last_fallback: Dict[str, int] = {}
        # jobs whose placements were DISCARDED host-side this execute()
        # (slow-replay JobReady failures, volume demotion dead-ends): their
        # freed capacity is stranded for the rest of the cycle unless the
        # backfill action's real-request pass re-offers it
        self.last_host_discards = 0
        self._host_place_count = 0
        self._n_applied = 0
        self._ports_by_node: Optional[Dict[int, set]] = None

    def execute(self, ssn) -> None:
        self.last_phase_ms = {}
        self.last_fallback = {}
        self.last_host_discards = 0
        self.last_solve_rounds = 0
        self.last_topk = None
        self.last_warm = None
        self._host_place_count = 0
        self._n_applied = 0
        self._ports_by_node = None
        # session → ClusterInfo view (the session's jobs/nodes/queues ARE the
        # snapshot clone; invalid jobs were already dropped at open). ALL jobs
        # are included so fairness state (queue_alloc/job_allocated) counts
        # Pending-phase jobs' allocations; the Pending-phase gate
        # (allocate.go:50-52) is the snapshot's job_schedulable flag
        cols = ssn.columns
        if not ssn.jobs or not ssn.nodes:
            # an empty (or node-less) cluster still serves what-ifs:
            # publish the lease so probes answer against the real — if
            # vacuous — state instead of 503ing until first ingest
            republish_query_lease(
                ssn, build=lambda: build_session_snapshot(ssn)
            )
            return

        from kube_batch_tpu.obs.trace import tracer_of

        tracer = tracer_of(ssn.cache)
        t0 = telemetry.perf_counter()
        if cols is not None and not cols.has_schedulable_pending():
            # steady-state idle cycle: nothing schedulable anywhere — skip
            # the snapshot/solve/replay entirely (the reference's loop with
            # an empty pending set is ~free; ours must be too at a 1 s
            # schedule period)
            self.last_phase_ms = {"snapshot_build": 0.0, "solve": 0.0,
                                  "fit_errors": 0.0, "replay": 0.0}
            # serving deployments still need a lease for this state: an
            # idle cluster is exactly when capacity-planning what-ifs
            # arrive.  The snapshot build + resident swap run only when a
            # query plane is attached AND ingest moved the version since
            # the last publish — a steadily idle cluster pays for the
            # rebuild once, not every schedule period.
            republish_query_lease(
                ssn, build=lambda: build_session_snapshot(ssn)
            )
            return
        with tracer.span("snapshot_build"):
            snap, meta = build_session_snapshot(ssn)
        t1 = telemetry.perf_counter()
        # multi-chip parts shard the node axis over the ICI mesh — the
        # production analog of the reference's always-on 16-worker fan-out
        # (scheduler_helper.go:34-64); single-chip or small-N stays local
        from kube_batch_tpu.guard import guard_of

        gp = guard_of(ssn.cache)
        config = session_allocate_config(ssn)
        # device-attributed span: a retrace or an unexpected full resident
        # upload is annotated onto THIS dispatch, not smeared into a p50
        with tracer.device_span("solve_dispatch", cols=cols) as sp_solve:
            result, self.last_solve_mode, topk_info, ginfo = (
                dispatch_allocate_solve(snap, config, cols=cols, guard=gp,
                                        warm=True, tracer=tracer)
            )
        sp_solve.set(mode=self.last_solve_mode,
                     engaged=list(ginfo["engaged"]))
        if self.last_solve_mode == "sharded":
            tracer.annotate_collectives(
                sp_solve, ginfo["config"], snap,
                pend_rows=ginfo.get("pend_rows"),
            )
        # shadow-oracle audit (guard tier 2): every KB_AUDIT_EVERY-th
        # dispatch re-runs the committed solve through its oracle path,
        # DISPATCHED here so the oracle re-solve overlaps the readback +
        # host replay (the fit-histogram idiom) and COMPARED after the
        # replay — audit cycles pay device time, never critical-path time
        audit_dev = None
        if ginfo["engaged"] and gp.audit_due("allocate"):
            with tracer.device_span("audit_dispatch"):
                audit_dev = dispatch_allocate_oracle(
                    snap, config, cols, self.last_solve_mode
                )
        # the lease shares this dispatch's resident swap (memoized on the
        # same snap object), so publication is bookkeeping-only
        republish_query_lease(ssn, snap, meta)
        sentinel = ginfo["sentinel"]
        # kbt: allow[KBT010] THE sanctioned choke point: one blocking
        # transfer for everything the host replay reads — the sentinel
        # verdict + violation histogram ride it (the AllocateResult-
        # counters idiom), so the guard adds zero extra transfers
        with tracer.device_span("device_wait") as sp_wait:
            (assigned, pipelined, rounds_run, topk_exh, topk_reent,
             verdict, vhist, echeck) = jax.device_get(  # kbt: allow[KBT010] ^
                (result.assigned, result.pipelined, result.rounds_run,
                 result.topk_exhausted, result.topk_reentries,
                 sentinel[0] if sentinel is not None else np.int32(0),
                 sentinel[1] if sentinel is not None else None,
                 sentinel[2] if sentinel is not None else np.int32(0))
            )
        sp_wait.set(rounds=int(rounds_run))
        # convergence diagnostic (round-cap tuning); NOT in last_phase_ms —
        # that dict is ms-typed for the bench phases map
        self.last_solve_rounds = int(rounds_run)
        if topk_info is not None:
            topk_info = dict(
                topk_info, exhausted=int(topk_exh), reentries=int(topk_reent)
            )
        self.last_topk = topk_info
        # warm-carry record of this execute ({"cold", "reranked",
        # "changed", "bucket_live", "w"} when the carried-table program
        # ran, None otherwise) — bench incremental_solve / sim evidence
        self.last_warm = (topk_info or {}).get("warm")
        assigned = assigned[: meta.n_tasks]
        pipelined = pipelined[: meta.n_tasks]
        if sentinel is not None and not self._consume_sentinel(
            ssn, gp, snap, config, ginfo, int(verdict), vhist,
            assigned, meta, int(echeck),
        ):
            # guard tier 1: the solve is CONDEMNED — fail closed.  Nothing
            # below this line runs: no replay, no binds, no fit errors.
            # The guard has already demoted the engaged fast paths, healed
            # the resident cache, and dumped the diagnostics bundle.
            self.last_phase_ms.update(
                snapshot_build=(t1 - t0) * 1e3,
                solve=(telemetry.perf_counter() - t1) * 1e3,
                fit_errors=0.0, replay=0.0,
            )
            return
        t2 = telemetry.perf_counter()
        task_job = np.asarray(snap.task_job)[: meta.n_tasks]
        # fit errors only for tasks of jobs that are IN this session (the
        # columnar row space also carries rows of jobs the session dropped —
        # gang-invalid or unknown-queue — which the object path never saw);
        # Pending-phase jobs stay included: their histogram rows carry the
        # real per-node reasons, keeping the condition dedup stable across
        # cycles
        job_in_session = np.asarray(snap.job_valid)
        pending = (
            np.asarray(snap.task_pending)[: meta.n_tasks]
            & job_in_session[task_job]
        )
        # the fit-error histogram is a SEPARATE lazy dispatch: only cycles
        # with unplaced pending tasks pay its [T, N] predicate re-walk
        # (allocate.go:151-155 builds FitErrors only for failing tasks).
        # It is DISPATCHED here but read back only after the host replay:
        # jax dispatch is async, so the device grinds the histogram while
        # the host replays the assignment.  This is the IN-CYCLE instance
        # of the cycle pipeline's general stage-overlap mechanism (the
        # scheduler module's staged loop overlaps the close-time status
        # flush and the binder drain with the NEXT cycle the same way) —
        # the async-binder seam extended one stage earlier into the cycle.
        # Timed under its own key (dispatch + post-replay readback) so
        # failure cycles don't read as a replay-phase regression.
        t_fit0 = telemetry.perf_counter()
        fail_hist_dev = None
        if bool(np.any(pending & (assigned < 0))):
            # the compacted dispatch's [P] pending bucket covers every
            # schedulable-pending row, and the histogram is only ever read
            # at unplaced pending rows — so failure cycles walk [P, N]
            # instead of [T, N] whenever a bucket exists (ROADMAP standing
            # item: the PR 10 bucket applies to the histogram verbatim)
            p_rows = ginfo.get("pend_rows")
            with tracer.device_span("fit_histogram_dispatch"):
                if self.last_solve_mode == "sharded":
                    from kube_batch_tpu.parallel.mesh import (
                        TASK_AXIS as _TA,
                        default_mesh as _dm,
                        sharded_failure_histogram,
                        sharded_failure_histogram_bucket,
                    )

                    mesh = _dm()
                    # the bucketed body requires a 1-D node mesh, exactly
                    # like the compacted solve (which also declined on a
                    # 2-D grid even though the bucket was planned)
                    if dict(mesh.shape).get(_TA, 1) != 1:
                        p_rows = None
                    if p_rows is not None:
                        fail_hist_dev = sharded_failure_histogram_bucket(
                            resident_snap(cols, snap, mesh), p_rows, mesh
                        )
                    else:
                        fail_hist_dev = sharded_failure_histogram(
                            resident_snap(cols, snap, mesh), mesh
                        )
                elif p_rows is not None:
                    from kube_batch_tpu.ops.assignment import (
                        failure_histogram_bucket_solve,
                    )

                    fail_hist_dev = failure_histogram_bucket_solve(
                        resident_snap(cols, snap), p_rows
                    )
                else:
                    from kube_batch_tpu.ops.assignment import (
                        failure_histogram_solve,
                    )

                    fail_hist_dev = failure_histogram_solve(
                        resident_snap(cols, snap)
                    )
        t_fit1 = telemetry.perf_counter()
        with tracer.span("host_replay"):
            self._replay(ssn, snap, meta, assigned, pipelined, task_job)
        t3 = telemetry.perf_counter()
        if fail_hist_dev is not None:
            # blocks only on whatever the device hasn't finished during the
            # replay; fit-error recording touches job diagnostic dicts the
            # replay never reads, so the reordering is invisible to it
            with tracer.device_span("fit_errors"):
                self._record_fit_errors(
                    # kbt: allow[KBT010] sanctioned post-replay readback: the
                    # histogram was dispatched before the replay precisely so
                    # this read overlaps host work instead of stalling
                    ssn, meta, np.asarray(fail_hist_dev), assigned, task_job,
                    pending,
                )
        t4 = telemetry.perf_counter()
        # update, not replace: _replay already folded its replay_* sub-phases in
        self.last_phase_ms.update(
            snapshot_build=(t1 - t0) * 1e3,
            solve=(t2 - t1) * 1e3,
            fit_errors=((t_fit1 - t_fit0) + (t4 - t3)) * 1e3,
            replay=(t3 - t_fit1) * 1e3,
        )
        if self._n_applied:
            # amortized per-task latency over placements actually APPLIED
            # (bulk-committed + statement-committed), so the histogram count
            # matches real placements (metrics.go:66-72 analog)
            metrics.observe_task_latencies(
                (t4 - t0) * 1e6 / self._n_applied, self._n_applied
            )
        if audit_dev is not None:
            self._compare_audit(
                ssn, gp, snap, config, ginfo, audit_dev, assigned, pipelined,
                meta,
            )

    # ------------------------------------------------------------------
    # guard plane wiring (tiers 1 + 2)
    # ------------------------------------------------------------------
    def _consume_sentinel(self, ssn, gp, snap, config, ginfo, verdict, vhist,
                 assigned, meta, echeck) -> bool:
        """The SHARED assignment-shaped consumer (guard/plane: host
        pending cross-check + checksum compare + histogram folding +
        bundle + resident/lease heal) — one copy with backfill's
        real-request pass."""
        from kube_batch_tpu.guard import consume_assignment_sentinel

        return consume_assignment_sentinel(
            gp, "allocate", ssn, snap, meta, ginfo, verdict, vhist,
            echeck, assigned, extra_report={"mode": self.last_solve_mode},
        )

    def _compare_audit(self, ssn, gp, snap, config, ginfo, audit_dev,
                       assigned, pipelined, meta) -> None:
        """Bit-compare the committed fast-path result against the shadow
        oracle (read back AFTER the host replay — the oracle re-solve ran
        overlapped with it)."""
        from kube_batch_tpu.guard import make_heal, sentinel_bundle_thunk

        # kbt: allow[KBT010] sanctioned post-replay audit readback: the
        # oracle was dispatched before the replay precisely so this read
        # overlaps host work instead of stalling the cycle
        a_assigned, a_pipelined = jax.device_get(
            (audit_dev.assigned, audit_dev.pipelined)
        )
        n = meta.n_tasks
        mism = int(
            np.sum(a_assigned[:n] != assigned)
            + np.sum(a_pipelined[:n] != pipelined)
        )
        report = {
            "audit_mismatches": mism, "engaged": ginfo["engaged"],
            "mode": self.last_solve_mode,
        }
        gp.note_audit(
            "allocate", ginfo["engaged"], mism == 0,
            detail=f"fast-vs-oracle mismatch at {mism} task rows",
            dump=sentinel_bundle_thunk(
                gp, "allocate", ginfo["dev"], ginfo["config"],
                report, pend_rows=ginfo.get("pend_rows"),
            ),
            heal=make_heal(ssn),
        )

    # ------------------------------------------------------------------
    def _replay(self, ssn, snap, meta, assigned, pipelined, task_job) -> None:
        placed = np.flatnonzero(assigned >= 0)
        if placed.size == 0:
            return
        # sub-phase wall clock (folded into last_phase_ms as replay_*) — the
        # host replay is the cycle's second-biggest phase and its internals
        # must stay visible in the bench artifact
        _mark = _PhaseMarks(self.last_phase_ms).mark
        # group placements by job, preserving device task order within a job;
        # groups are (job_idx, lo, hi) ranges over the sorted flat arrays
        order = np.argsort(task_job[placed], kind="stable")
        placed = placed[order]
        pjobs = task_job[placed]
        bounds = _run_bounds(pjobs)

        # the bulk path is sound only when the gang arithmetic is the whole
        # JobReady gate (gang.go:122-129 delegates to job.ready(), which is
        # exactly snapshot ready count + new allocations vs min_available)
        gang_only_ready = ssn.enabled_plugin_names(JOB_READY) <= {"gang"}
        nJ, nN = len(meta.job_objs), len(meta.node_names)
        resreq64 = meta.task_resreq64
        spec = ssn.spec
        R = resreq64.shape[1] if resreq64.ndim == 2 else spec.n
        pipe_flags = pipelined[placed].astype(bool)
        n_alloc_per_job = np.bincount(pjobs[~pipe_flags], minlength=nJ)
        if ssn.plugin_enabled("gang"):
            committed = (
                np.asarray(snap.job_ready)[:nJ] + n_alloc_per_job
            ) >= np.asarray(snap.job_min_avail)[:nJ]
        else:
            # no gang plugin ⇒ JobReady is vacuously true (veto dispatch over
            # zero fns, session_plugins.go:202-220): every placement commits
            committed = np.ones(nJ, bool)
        job_slow = np.zeros(nJ, bool)
        if not gang_only_ready or ssn.host_only_predicates:
            job_slow[:] = True
        else:
            np.logical_or.at(job_slow, pjobs, meta.task_needs_host[placed])

        # ---- bulk path FIRST ------------------------------------------
        # Bulk placements need no host state (the solve guarantee covers
        # their fit, and readiness is snapshot arithmetic), while the slow
        # path's host predicates must observe them live — an inter-pod
        # affinity follower co-locates with an anchor this cycle only if the
        # anchor is on the node when the follower is validated.  Host
        # fallbacks, the one mutation the solve can't account for, then
        # happen strictly after every bulk placement has landed.
        #
        # All resreq sums are computed globally up front (segment sums over
        # the float64 resreq matrix) and the apply loop runs over plain
        # python lists — gangs are small, so per-group numpy would pay call
        # overhead 10k+ times for 4-row reductions.
        placed_l = placed.tolist()
        pjobs_l = pjobs.tolist()
        pipe_l = pipe_flags.tolist()
        node_l = assigned[placed].tolist()
        task_objs = meta.task_objs
        node_names = meta.node_names
        n_groups = len(bounds) - 1
        _mark("replay_prep")

        # ---- promote host-ports-only jobs back to the bulk path --------
        # A job is "slow" when any task carries host-only constraints, but
        # the dominant such constraint (hostPorts) is checkable in one batch
        # pass: a placement conflicts iff its (node, port) is already held
        # by a resident task or claimed earlier this cycle.  Conflict-free
        # jobs keep the solve's guarantees and bulk-apply; only conflicted
        # or affinity-carrying jobs pay the sequential Statement replay
        # (VERDICT r2 weak #6 — 30% ported tasks degraded the cycle ~5×).
        promoted_jobs = 0
        cols0 = ssn.columns
        if (
            job_slow.any() and gang_only_ready
            and not ssn.host_only_predicates and cols0 is not None
        ):
            # resident occupancy snapshot, O(ported tasks) once — exact
            # here because nothing has been applied yet this cycle; the
            # slow phase later uses the live per-query view instead
            # (_port_held_nodes) so Statement discards roll claims back
            occupied = set()
            t_node_col = cols0.t_node
            task_by_row = cols0.task_by_row
            for r in cols0._ported_rows:
                ni = int(t_node_col[r])
                if ni < 0:
                    continue
                rt = task_by_row[r]
                if rt is not None:
                    for p in rt.pod.host_ports:
                        occupied.add((ni, p))
            # claims of jobs promoted earlier in this pass — their t_node
            # rows are only written when the bulk apply runs below
            for g in range(n_groups):
                lo, hi = bounds[g], bounds[g + 1]
                ji = pjobs_l[lo]
                # uncommitted jobs never apply — promoting them would only
                # plant phantom port claims that demote real jobs
                if not job_slow[ji] or not committed[ji]:
                    continue
                claims: Optional[set] = set()
                for i in range(lo, hi):
                    t = task_objs[placed_l[i]]
                    if not t.needs_host_predicate:
                        continue
                    if t.pod.affinity is not None:
                        claims = None  # rich constraints → sequential path
                        break
                    ni = node_l[i]
                    for p in t.pod.host_ports:
                        key = (ni, p)
                        if key in occupied or key in claims:
                            claims = None
                            break
                        claims.add(key)
                    if claims is None:
                        break
                if claims is None:
                    continue  # conflict → sequential replay re-decides
                occupied.update(claims)
                job_slow[ji] = False
                promoted_jobs += 1

        slow_l = job_slow.tolist()
        committed_l = committed.tolist()

        # volume pre-check (AllocateVolumes, session.go:252-257): a rejected
        # group demotes to the sequential path BEFORE anything is mutated or
        # summed, so the bulk apply below has no failure path.  Skipped
        # wholesale when the volume binder declares itself a no-op.
        demoted_jobs: set = set()
        volume_noop = getattr(ssn.cache.volume_binder, "noop", False)
        if not volume_noop:
            allocate_volumes = ssn.cache.allocate_volumes
            for g in range(n_groups):
                lo = bounds[g]
                ji = pjobs_l[lo]
                if slow_l[ji] or not committed_l[ji]:
                    continue
                try:
                    for i in range(lo, bounds[g + 1]):
                        if not pipe_l[i]:
                            allocate_volumes(
                                task_objs[placed_l[i]], node_names[node_l[i]]
                            )
                except FitFailure:
                    demoted_jobs.add(ji)
                    # free this group's pre-check reservations: the slow
                    # replay re-reserves per task, and tasks it fails to
                    # place must not hold PVs across cycles
                    release = getattr(
                        ssn.cache.volume_binder, "release_task", None
                    )
                    if release is not None:
                        for i in range(lo, bounds[g + 1]):
                            release(task_objs[placed_l[i]].uid)

        apply_job = np.asarray(
            [committed[j] and not job_slow[j] and j not in demoted_jobs
             for j in range(nJ)], bool,
        ) if demoted_jobs else (committed & ~job_slow)
        apply_mask = apply_job[pjobs]          # placements to bulk-apply
        alloc_sel = apply_mask & ~pipe_flags
        pipe_sel = apply_mask & pipe_flags
        self._n_applied += int(apply_mask.sum())
        placed_rows = resreq64[placed]
        node_of = assigned[placed]
        job_alloc_sum = np.zeros((nJ, R))
        np.add.at(job_alloc_sum, pjobs[alloc_sel], placed_rows[alloc_sel])
        job_total_sum = np.zeros((nJ, R))
        np.add.at(job_total_sum, pjobs[apply_mask], placed_rows[apply_mask])
        node_alloc_sum = np.zeros((nN, R))
        np.add.at(node_alloc_sum, node_of[alloc_sel], placed_rows[alloc_sel])
        node_pipe_sum = np.zeros((nN, R))
        np.add.at(node_pipe_sum, node_of[pipe_sel], placed_rows[pipe_sel])

        EMPTY = spec.empty()
        apply_l = apply_job.tolist()
        wrap_vec = spec.wrap_vec
        binds: List[Tuple[object, str]] = []
        by_node: Dict[int, Tuple[list, list]] = {}
        # shared by the columnar count update and the bulk_bind job sums
        n_alloc_applied = np.bincount(pjobs[alloc_sel], minlength=nJ)
        _mark("replay_sums")

        cols = ssn.columns
        columnar = (
            cols is not None
            and meta.task_objs is cols.task_by_row  # snapshot IS the row space
            and ssn.all_handlers_columnar()
        )
        # the no-pipeline columnar cycle (every placement allocates — the
        # steady-state headline shape) takes a flat-array residue path below
        # instead of the per-task branching group loop
        fast_residue = columnar and not bool(pipe_sel.any())
        if columnar:
            # ---- columnar apply: every ledger/count/status column updated
            # by whole-matrix ops; the Python loop below only does what MUST
            # touch objects (status-index buckets, node task dicts, the
            # binds list).  The ledger matrices are the same buffers the
            # JobInfo/NodeInfo Resource views wrap, so the object model
            # observes every update with zero double bookkeeping.
            BINDING_I = int(TaskStatus.BINDING)
            PIPELINED_I = int(TaskStatus.PIPELINED)
            PENDING_I = int(TaskStatus.PENDING)
            alloc_rows = placed[alloc_sel]
            pipe_rows = placed[pipe_sel]
            cols.t_status[alloc_rows] = BINDING_I
            cols.t_status[pipe_rows] = PIPELINED_I
            apply_rows = placed[apply_mask]
            cols.t_node[apply_rows] = node_of[apply_mask]
            cols.j_alloc += job_alloc_sum
            # alloc-twin choke: the f32 j_alloc32 refresh visits exactly
            # the rows this vectorized update moved
            cols.note_job_alloc_rows(np.any(job_alloc_sum != 0.0, axis=1))
            cols.j_pend -= job_total_sum
            np.maximum(cols.j_pend, 0.0, out=cols.j_pend)
            n_pipe_applied = np.bincount(pjobs[pipe_sel], minlength=nJ)
            jc = cols.j_counts
            jc[:, PENDING_I] -= n_alloc_applied + n_pipe_applied
            jc[:, BINDING_I] += n_alloc_applied
            jc[:, PIPELINED_I] += n_pipe_applied
            # count choke point: the delta close-session pass visits exactly
            # the rows this vectorized update moved
            cols.j_touched[(n_alloc_applied + n_pipe_applied) > 0] = True
            cols.n_idle -= node_alloc_sum
            np.maximum(cols.n_idle, 0.0, out=cols.n_idle)
            cols.n_used += node_alloc_sum + node_pipe_sum
            cols.n_rel -= node_pipe_sum
            np.maximum(cols.n_rel, 0.0, out=cols.n_rel)
            # ledger choke point: the f32 snapshot twins refresh these rows
            cols.note_node_ledger_rows(
                np.any(node_alloc_sum != 0.0, axis=1)
                | np.any(node_pipe_sum != 0.0, axis=1)
            )
            ssn.fire_columnar_allocations(cols, job_total_sum)
            _mark("replay_columns")

        if fast_residue:
            # ---- flat residue: binds / bucket moves / node registration
            # from whole arrays.  Per task this costs one object gather and
            # one dict insert (inside bulk_register_tasks) instead of the
            # general loop's slot lookups, branches, and appends.
            ptasks_l = [task_objs[r] for r in placed_l]
            apply_pos = np.flatnonzero(apply_mask)
            app_tasks = (
                ptasks_l if apply_pos.size == len(ptasks_l)
                else [ptasks_l[i] for i in apply_pos.tolist()]
            )
            app_nodes = node_of[apply_mask]
            binds = list(zip(app_tasks, (node_names[n] for n in app_nodes.tolist())))
            # job bucket moves: applied groups are contiguous runs of placed
            job_objs = meta.job_objs
            for g in range(n_groups):
                lo = bounds[g]
                ji = pjobs_l[lo]
                if apply_l[ji]:
                    job_objs[ji].rebucket_moved(
                        ptasks_l[lo:bounds[g + 1]], TaskStatus.BINDING
                    )
            # node registration grouped by one argsort over the node column
            if app_nodes.size:
                nsort = np.argsort(app_nodes, kind="stable")
                nodes_sorted = app_nodes[nsort]
                run_bounds = _run_bounds(nodes_sorted)
                nsort_l = nsort.tolist()
                get_node = ssn.nodes.get
                for k in range(len(run_bounds) - 1):
                    lo, hi = run_bounds[k], run_bounds[k + 1]
                    node = get_node(node_names[nodes_sorted[lo]])
                    if node is not None:
                        node.bulk_register_tasks(
                            [app_tasks[i] for i in nsort_l[lo:hi]], ()
                        )
            by_node = {}  # residue fully handled; skip the general pass

        for g in range(0 if fast_residue else n_groups):
            lo, hi = bounds[g], bounds[g + 1]
            ji = pjobs_l[lo]
            if not apply_l[ji]:
                continue
            job = meta.job_objs[ji]
            alloc_tasks: list = []
            pipe_tasks: list = []
            if columnar:
                # object residue only: bucket moves, node dicts, binds.
                # _status/_node_name are written as raw attrs — the columns
                # were already updated vectorized above, and going through
                # the property setters would redo 50k scalar column writes
                for i in range(lo, hi):
                    t = task_objs[placed_l[i]]
                    ni = node_l[i]
                    name = node_names[ni]
                    t._node_name = name
                    slot = by_node.get(ni)
                    if slot is None:
                        slot = by_node[ni] = ([], [])
                    if pipe_l[i]:
                        pnode = ssn.nodes.get(name)
                        if pnode is not None:
                            job.nodes_fit_delta[name] = (
                                t.init_resreq.fit_delta(pnode.idle)
                            )
                            ssn.note_fit_state(job)
                        pipe_tasks.append(t)
                        slot[1].append(t)
                    else:
                        alloc_tasks.append(t)
                        slot[0].append(t)
                        binds.append((t, name))
                job.rebucket_moved(alloc_tasks, TaskStatus.BINDING)
                if pipe_tasks:
                    job.rebucket_moved(pipe_tasks, TaskStatus.PIPELINED)
                    ssn.pipelined_tasks.extend(pipe_tasks)
                continue
            for i in range(lo, hi):
                t = task_objs[placed_l[i]]
                ni = node_l[i]
                t.node_name = node_names[ni]
                slot = by_node.get(ni)
                if slot is None:
                    slot = by_node[ni] = ([], [])
                if pipe_l[i]:
                    # pipeline-on-releasing ⇒ the task did NOT fit Idle:
                    # record the shortfall diagnostic (allocate.go:170-175)
                    pnode = ssn.nodes.get(t.node_name)
                    if pnode is not None:
                        job.nodes_fit_delta[t.node_name] = (
                            t.init_resreq.fit_delta(pnode.idle)
                        )
                        ssn.note_fit_state(job)
                    pipe_tasks.append(t)
                    slot[1].append(t)
                else:
                    alloc_tasks.append(t)
                    slot[0].append(t)
                    binds.append((t, t.node_name))
            # committed & ready → every new allocation dispatches immediately
            # (session.go:286-294); BINDING directly, skipping the
            # ALLOCATED→BINDING index churn
            asum = wrap_vec(job_alloc_sum[ji])
            job.bulk_transition(alloc_tasks, TaskStatus.BINDING, asum,
                                pending_sum=asum)
            if pipe_tasks:
                job.bulk_transition(
                    pipe_tasks, TaskStatus.PIPELINED, EMPTY,
                    pending_sum=wrap_vec(job_total_sum[ji] - job_alloc_sum[ji]),
                )
                ssn.pipelined_tasks.extend(pipe_tasks)
            ssn.fire_batch_allocations(job, alloc_tasks + pipe_tasks,
                                       wrap_vec(job_total_sum[ji]))

        # per-node accounting with the presummed rows (node_info.go:165-222
        # algebra); columnar path already applied the resource algebra via
        # the column matrices — only the task dict / acct residue remains
        for ni, (allocs, pipes) in by_node.items():
            node = ssn.nodes.get(node_names[ni])
            if node is None:
                continue
            if columnar:
                node.bulk_register_tasks(allocs, pipes)
            else:
                node.bulk_add_tasks(
                    allocs, pipes,
                    spec.wrap_vec(node_alloc_sum[ni]), spec.wrap_vec(node_pipe_sum[ni]),
                )
        _mark("replay_residue")

        if binds:
            # BindVolumes precedes every dispatch (statement.go:253-277)
            if not volume_noop:
                bind_volumes = ssn.cache.bind_volumes
                for t, _ in binds:
                    bind_volumes(t)
            # hand the cache the segment sums this replay already computed
            # ({key: (count, vec)}; bulk_bind falls back to accumulating any
            # group whose applied count differs)
            job_sums = {
                meta.job_objs[ji].uid: (int(n_alloc_applied[ji]), job_alloc_sum[ji])
                for ji in np.flatnonzero(n_alloc_applied).tolist()
            }
            node_alloc_cnt = np.bincount(node_of[alloc_sel], minlength=nN)
            node_sums = {
                node_names[ni]: (int(node_alloc_cnt[ni]), node_alloc_sum[ni])
                for ni in np.flatnonzero(node_alloc_cnt).tolist()
            }
            ssn.cache.bulk_bind(binds, job_sums=job_sums, node_sums=node_sums)
        _mark("replay_bind")

        # slow path after every bulk placement has landed: host predicates
        # observe them; jobs the bulk path demoted replay sequentially too
        n_slow = 0
        for g in range(n_groups):
            ji = pjobs_l[bounds[g]]
            if slow_l[ji] or ji in demoted_jobs:
                n_slow += 1
                self._slow_replay_job(
                    ssn, meta, assigned, pipelined, ji, placed[bounds[g]:bounds[g + 1]]
                )
        self.last_fallback = {
            "slow_jobs": n_slow,
            "promoted_ports_jobs": promoted_jobs,
            "host_place_tasks": self._host_place_count,
        }
        metrics.register_slow_replay_jobs(n_slow)
        metrics.register_host_fallback_tasks(self._host_place_count)

    # ------------------------------------------------------------------
    def _slow_replay_job(self, ssn, meta, assigned, pipelined, ji, idxs) -> None:
        """Per-task Statement replay — host is authoritative for the commit
        gate (JobReady, allocate.go:192-196) and for every predicate."""
        job = meta.job_objs[ji]
        stmt = ssn.statement()
        for ti in idxs:
            task = meta.task_objs[int(ti)]
            node_name = meta.node_names[int(assigned[ti])]
            pipe = bool(pipelined[ti])
            node = ssn.nodes.get(node_name)
            try:
                if node is not None and (
                    task.needs_host_predicate or ssn.host_only_predicates
                ):
                    ssn.predicate(task, node)
                # live fit re-check: a host-fallback placement may have
                # consumed capacity the device solve promised to this
                # placement; node.add_task does not re-verify fit
                if node is not None and not (
                    (not pipe and task.init_resreq.less_equal(node.idle))
                    or (pipe and task.init_resreq.less_equal(node.releasing))
                ):
                    raise FitFailure("node resources taken by host fallback")
                if pipe:
                    if node is not None:
                        job.nodes_fit_delta[node_name] = (
                            task.init_resreq.fit_delta(node.idle)
                        )
                        ssn.note_fit_state(job)
                    stmt.pipeline(task, node_name)
                else:
                    # raises FitFailure before mutating when a volume claim
                    # can't be satisfied from this node (cache.go:189-209)
                    stmt.allocate(task, node_name)
            except FitFailure as e:
                logger.info("device placement %s→%s rejected by host predicate: %s",
                            task.key(), node_name, e.reason)
                # the device would re-propose the same node next cycle
                # (the solve is deterministic), so fall back to the
                # reference's own sequential path for this task
                self._host_place(ssn, stmt, task)
        if ssn.job_ready(job):
            self._n_applied += len(stmt.operations)
            stmt.commit()
        else:
            logger.info(
                "job %s not ready after device solve (%d placements), discarding",
                job.uid, int(idxs.size),
            )
            # the session carries the control signal (backfill's real-request
            # gate reads ssn.host_discards — ADVICE.md #5: the registry
            # singleton's counter crossed wires between scheduler instances);
            # the instance counter stays as a bench/diagnostics record
            self.last_host_discards += 1
            ssn.host_discards += 1
            stmt.discard()

    def _record_fit_errors(self, ssn, meta, fail_hist, assigned, task_job, pending) -> None:
        """FitErrors for unplaced pending tasks (allocate.go:151-155). The
        reason histogram comes from the lazy failure_histogram_solve dispatch
        the caller ran — only failure cycles pay it."""
        from kube_batch_tpu.api.job_info import FitErrors
        from kube_batch_tpu.ops.feasibility import REASON_MESSAGES

        unplaced = np.flatnonzero(pending & (assigned < 0))
        if unplaced.size == 0:
            return
        hist = fail_hist[: meta.n_tasks]
        n_nodes = getattr(meta, "live_nodes", meta.n_nodes)
        for ti in unplaced:
            job = meta.job_objs[int(task_job[ti])]
            task = meta.task_objs[int(ti)]
            if job is None or task is None:
                continue
            counts = dict(zip(REASON_MESSAGES, hist[ti].tolist()))
            if not any(counts.values()):
                # task was feasible at cycle start but lost the contention —
                # capacity went to other tasks this cycle
                counts = {
                    "node(s) resources were consumed by other tasks this cycle":
                        n_nodes
                }
            fe = FitErrors()
            fe.set_histogram(counts, n_nodes)
            job.nodes_fit_errors[task.uid] = fe
            ssn.note_fit_state(job)

    def _port_rows(self, cols) -> Dict[int, list]:
        """Lazily built per-execute: port → [task rows] of EVERY ported task
        (resident, pending, placed).  Occupancy is derived LIVE from the
        t_node column at query time — placements, discards, and object-scan
        fallbacks all flow through the node_name property that keeps t_node
        current, so there is exactly one source of truth and nothing to roll
        back."""
        idx = self._ports_by_node
        if idx is None:
            idx = self._ports_by_node = {}
            for row in cols._ported_rows:
                t = cols.task_by_row[row]
                if t is None:
                    continue
                for p in t.pod.host_ports:
                    idx.setdefault(p, []).append(row)
        return idx

    def _port_held_nodes(self, cols, port: int, exclude_row: int) -> set:
        """Node rows currently holding `port` (live t_node view)."""
        rows = self._port_rows(cols).get(port)
        if not rows:
            return set()
        t_node = cols.t_node
        return {
            int(t_node[r]) for r in rows
            if r != exclude_row and t_node[r] >= 0
        }

    def _host_place_columns(self, ssn, stmt, task) -> Optional[bool]:
        """Vectorized residual placement over the column matrices for tasks
        whose only host-side constraint is hostPorts: fit + static predicates
        + port exclusion as array ops, device-weight scoring, then the same
        Idle-vs-Releasing decision.  Returns None when the task needs the
        full object scan (affinity, host-only predicate plugins, no
        columns)."""
        cols = ssn.columns
        from kube_batch_tpu.framework.session import NODE_ORDER

        if (
            cols is None
            or ssn.host_only_predicates
            or task.pod.affinity is not None
            or getattr(task, "_row", -1) < 0
            # a custom scoring policy (an extension score row or a NODE_ORDER
            # scorer beyond the built-in nodeorder plugin) isn't encoded in
            # the vectorized score below — the object scan consults
            # ssn.node_order, so policy stays consistent with the device solve
            or ssn.score_weights.extra_rows
            or set(ssn._fns.get(NODE_ORDER, {})) - {"nodeorder"}
        ):
            return None
        req = task.init_resreq.vec
        quanta = cols.spec.quanta
        fit_idle = np.all(req <= cols.n_idle + quanta, axis=1)
        fit_rel = np.all(req <= cols.n_rel + quanta, axis=1)
        cand = (fit_idle | fit_rel) & cols.n_valid & cols.n_sched
        excluded_rows = cols.excluded_node_rows(ssn)
        if excluded_rows:
            cand[excluded_rows] = False
        row = task._row
        # selector / taint bitsets (same encoding the device predicate uses)
        if cols.t_sel_impossible[row]:
            return False
        sel = cols.t_sel_bits[row]
        if sel.any():
            cand &= ~np.any(sel[None, :] & ~cols.n_label_bits, axis=1)
        cand &= ~np.any(cols.n_taint_bits & ~cols.t_tol_bits[row][None, :], axis=1)
        for p in task.pod.host_ports:
            held = self._port_held_nodes(cols, p, exclude_row=task._row)
            if held:
                cand[list(held)] = False
        if not cand.any():
            return False
        # device-weight scoring rows (ops/scoring.py's host twin)
        w = ssn.score_weights
        alloc = cols.n_alloc
        with np.errstate(divide="ignore", invalid="ignore"):
            used_after = cols.n_used + req
            frac = np.where(alloc > 0, np.minimum(used_after / np.maximum(alloc, 1e-9), 1.0), 1.0)
        free_cpu, free_mem = 1.0 - frac[:, 0], 1.0 - frac[:, 1]
        score = (
            w.least_requested * (free_cpu + free_mem) * 5.0
            + w.balanced_resource * (10.0 - np.abs(free_cpu - free_mem) * 10.0)
            + w.binpack * (frac[:, 0] + frac[:, 1]) * 5.0
        )
        score = np.where(cand, score, -np.inf)
        volume_ok = getattr(ssn.cache.volume_binder, "noop", False)
        for _ in range(8):  # volume-infeasible nodes retire and we re-pick
            ni = int(np.argmax(score))
            if score[ni] == -np.inf:
                return False
            name = cols.node_names[ni]
            if volume_ok or ssn.cache.volume_feasible(task, name):
                break
            score[ni] = -np.inf
        else:
            # more than 8 volume-infeasible picks: defer to the full object
            # scan, which probes volume feasibility on every node — a 9th
            # node may fit and must not be missed forever
            return None
        try:
            if fit_idle[ni]:
                stmt.allocate(task, name)
            else:
                job = ssn.jobs.get(task.job)
                node = ssn.nodes.get(name)
                if job is not None and node is not None:
                    job.nodes_fit_delta[name] = task.init_resreq.fit_delta(node.idle)
                    ssn.note_fit_state(job)
                stmt.pipeline(task, name)
        except FitFailure as e:
            logger.info("columns host placement %s→%s failed: %s",
                        task.key(), name, e.reason)
            return False
        # no port-ledger update needed: the placement just wrote t_node via
        # the node_name property, which is exactly what _port_held_nodes
        # reads — discards roll it back the same way
        return True

    def _host_place(self, ssn, stmt, task) -> bool:
        """Sequential placement for a task the device model couldn't encode:
        predicate every node, pick the best-scoring fit — exactly
        allocate.go:151-184 (PredicateNodes → PrioritizeNodes →
        SelectBestNode → Allocate on Idle / Pipeline on Releasing).  Tasks
        whose only host constraint is hostPorts take the vectorized column
        path instead of the O(nodes) object scan (VERDICT r2 weak #6)."""
        self._host_place_count += 1
        fast = self._host_place_columns(ssn, stmt, task)
        if fast is not None:
            return fast
        best, best_score = None, None
        for node in ssn.nodes.values():
            try:
                ssn.predicate(task, node)
            except FitFailure:
                continue
            if not (task.init_resreq.less_equal(node.idle)
                    or task.init_resreq.less_equal(node.releasing)):
                continue
            # volume reachability is part of host placement (AllocateVolumes
            # failing a node, cache.go:189-209)
            if not ssn.cache.volume_feasible(task, node.name):
                continue
            score = ssn.node_order(task, node)
            if best is None or score > best_score:
                best, best_score = node, score
        if best is None:
            return False
        # allocate-vs-pipeline is decided on the already-selected node
        # (allocate.go:161-184), not folded into the selection
        try:
            if task.init_resreq.less_equal(best.idle):
                stmt.allocate(task, best.name)
            else:
                job = ssn.jobs.get(task.job)
                if job is not None:
                    job.nodes_fit_delta[best.name] = (
                        task.init_resreq.fit_delta(best.idle)
                    )
                    ssn.note_fit_state(job)
                stmt.pipeline(task, best.name)
        except FitFailure as e:
            # e.g. a same-cycle reservation raced the feasibility probe;
            # the task stays Pending and the next cycle self-corrects
            # (allocate.go logs and moves on the same way)
            logger.info("host placement %s→%s failed: %s",
                        task.key(), best.name, e.reason)
            return False
        return True
