"""Action registry (actions/factory.go:29-35)."""

from kube_batch_tpu.framework.interface import register_action

from kube_batch_tpu.actions.allocate import AllocateAction
from kube_batch_tpu.actions.backfill import BackfillAction
from kube_batch_tpu.actions.enqueue import EnqueueAction
from kube_batch_tpu.actions.preempt import PreemptAction
from kube_batch_tpu.actions.reclaim import ReclaimAction

ALL_ACTIONS = (
    EnqueueAction(),
    ReclaimAction(),
    AllocateAction(),
    BackfillAction(),
    PreemptAction(),
)

for action in ALL_ACTIONS:
    register_action(action)

__all__ = [
    "AllocateAction",
    "BackfillAction",
    "EnqueueAction",
    "PreemptAction",
    "ReclaimAction",
    "ALL_ACTIONS",
]
