"""proportion plugin (plugins/proportion/proportion.go) — weighted max-min
fair queue capacity.

Registers: QueueOrder (lower share first), Reclaimable (victim's queue must
stay ≥ deserved), Overused, JobEnqueueable (capability cap), and event
handlers keeping per-queue allocation live. The deserved waterfill here is
the host (numpy) twin of ops/fairness.proportion_deserved used by the device
solve.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from kube_batch_tpu.api.job_info import JobInfo
from kube_batch_tpu.api.queue_info import QueueInfo
from kube_batch_tpu.api.resources import Resource
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.api.types import TaskStatus, is_allocated
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework import session as fw


class _QueueAttr:
    __slots__ = ("queue", "weight", "deserved", "allocated", "request",
                 "_share", "_dirty", "_gen")

    def __init__(self, queue: QueueInfo, spec):
        self.queue = queue
        self.weight = queue.weight
        self.deserved = spec.empty()
        self.allocated = spec.empty()
        self.request = spec.empty()
        self._share = 0.0
        self._dirty = True
        self._gen = 0


class ProportionPlugin(Plugin):
    name = "proportion"

    def __init__(self, arguments=None):
        super().__init__(arguments)
        self.total: Resource | None = None
        self.queue_attrs: Dict[str, _QueueAttr] = {}
        # columnar mode: [nq, R] allocated matrix the attrs wrap + a
        # job-row → attr-index map for the vectorized allocate events
        self._qalloc = None
        self._jq_rows = None
        self._jq_vals = None
        self._generation = 0

    def _share(self, attr: _QueueAttr) -> float:
        """share = dominant allocated/deserved (proportion.go:265-277),
        recomputed lazily on read — the allocate replay fires thousands of
        batch events whose shares nothing reads until queue ordering."""
        if attr._dirty or attr._gen != self._generation:
            attr._share = _dominant(attr.allocated, attr.deserved)
            attr._dirty = False
            attr._gen = self._generation
        return attr._share

    def on_session_open(self, ssn: fw.Session) -> None:
        spec = ssn.spec
        self.total = ssn.total_allocatable().clone()
        cols = ssn.columns
        if cols is not None and getattr(ssn, "rows_synced", False):
            # columnar session: the open-time row sync already derived
            # session membership and queue rows (j_sess/j_queue — delta
            # against the previous cycle when churn allows), so queue attrs
            # are one segment-sum over the job ledger matrices: no per-job
            # Python loop at all (proportion.go:67-99)
            rows = np.flatnonzero(cols.j_sess)
            qrows = cols.j_queue[rows]
            capQ = cols.queues.cap
            alloc_m = np.zeros((capQ, spec.n))
            request_m = np.zeros((capQ, spec.n))
            np.add.at(alloc_m, qrows, cols.j_alloc[rows])
            np.add.at(request_m, qrows, cols.j_alloc[rows] + cols.j_pend[rows])
            self._qalloc, self._jq_rows, self._jq_vals = alloc_m, rows, qrows
            wrap = spec.wrap_vec
            for qi in np.unique(qrows).tolist():
                qinfo = ssn.queues.get(cols.queue_names[qi])
                if qinfo is None:
                    continue  # queue row/dict skew — attr-less queues fail open
                attr = _QueueAttr(qinfo, spec)
                attr.allocated = wrap(alloc_m[qi])
                attr.request = wrap(request_m[qi])
                self.queue_attrs[qinfo.name] = attr
        else:
            # queue attrs from jobs present this session (proportion.go:67-99)
            for job in ssn.jobs.values():
                if job.queue not in ssn.queues:
                    continue
                attr = self.queue_attrs.get(job.queue)
                if attr is None:
                    attr = _QueueAttr(ssn.queues[job.queue], spec)
                    self.queue_attrs[job.queue] = attr
                # request = allocated + pending (proportion.go:87-99), both
                # read straight off the JobInfo ledgers — no task iteration
                attr.allocated.add_(job.allocated)
                attr.request.add_(job.allocated)
                attr.request.add_(job.pending_request)
        self._waterfill(spec)

        def queue_order(l: QueueInfo, r: QueueInfo) -> int:
            la = self.queue_attrs.get(l.name)
            ra = self.queue_attrs.get(r.name)
            ls = self._share(la) if la else 0.0
            rs = self._share(ra) if ra else 0.0
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        def reclaimable(reclaimer: TaskInfo, reclaimees: List[TaskInfo]) -> List[TaskInfo]:
            """(proportion.go:171-196) victim OK if its queue stays ≥ deserved."""
            victims: List[TaskInfo] = []
            allocations: Dict[str, Resource] = {}
            for ee in reclaimees:
                job = ssn.jobs.get(ee.job)
                if job is None or job.queue not in self.queue_attrs:
                    continue
                attr = self.queue_attrs[job.queue]
                if job.queue not in allocations:
                    allocations[job.queue] = attr.allocated.clone()
                alloc = allocations[job.queue]
                if not ee.resreq.less_equal(alloc):
                    continue
                alloc.sub_(ee.resreq)
                # semantic dims only — pods is capacity, not fairness
                if attr.deserved.less_equal_semantic(alloc):
                    victims.append(ee)
            return victims

        def overused_fn(queue: QueueInfo) -> bool:
            attr = self.queue_attrs.get(queue.name)
            if attr is None:
                return False
            # semantic dims only — pods is capacity, not fairness
            return attr.deserved.less_equal_semantic(attr.allocated)

        def job_enqueueable(job: JobInfo) -> bool:
            """(proportion.go:211-233) capability quota not exceeded."""
            queue = ssn.queues.get(job.queue)
            attr = self.queue_attrs.get(job.queue)
            if queue is None or attr is None:
                return True
            capability = queue.queue.capability
            if not capability:
                return True
            cap = ssn.spec.empty()
            for name, v in capability.items():
                if name in ssn.spec:
                    cap.vec[ssn.spec.index(name)] = float(v)
            min_res = ssn.spec.empty()
            for name, v in (job.pod_group.min_resources or {}).items():
                if name in ssn.spec:
                    min_res.vec[ssn.spec.index(name)] = float(v)
            return min_res.add(attr.allocated).less_equal(cap)

        def on_allocate(event: fw.Event) -> None:
            job = ssn.jobs.get(event.task.job)
            if job and job.queue in self.queue_attrs:
                attr = self.queue_attrs[job.queue]
                attr.allocated.add_(event.task.resreq)
                attr._dirty = True

        def on_deallocate(event: fw.Event) -> None:
            job = ssn.jobs.get(event.task.job)
            if job and job.queue in self.queue_attrs:
                attr = self.queue_attrs[job.queue]
                attr.allocated.sub_(event.task.resreq)
                attr._dirty = True

        def on_batch_allocate(job: JobInfo, tasks, total_resreq) -> None:
            # linear in resreq: one presummed add per queue ≡ per-task events
            if job.queue in self.queue_attrs:
                attr = self.queue_attrs[job.queue]
                attr.allocated.add_(total_resreq)
                attr._dirty = True

        def on_columnar_allocate(cols, job_sums) -> None:
            # one segment-sum for the whole replay ≡ 12.5k batch events
            np.add.at(self._qalloc, self._jq_vals, job_sums[self._jq_rows])
            self._generation += 1

        ssn.add_fn(fw.QUEUE_ORDER, self.name, queue_order)
        ssn.add_fn(fw.RECLAIMABLE, self.name, reclaimable)
        ssn.add_fn(fw.OVERUSED, self.name, overused_fn)
        ssn.add_fn(fw.JOB_ENQUEUEABLE, self.name, job_enqueueable)
        ssn.add_event_handler(
            fw.EventHandler(
                allocate_func=on_allocate, deallocate_func=on_deallocate,
                batch_allocate_func=on_batch_allocate,
                columnar_allocate_func=(
                    on_columnar_allocate if self._qalloc is not None else None
                ),
            )
        )

    def _waterfill(self, spec) -> None:
        """deserved by weighted max-min (proportion.go:101-154); host twin of
        ops/fairness.proportion_deserved."""
        attrs = list(self.queue_attrs.values())
        if not attrs:
            return
        remaining = self.total.vec.copy()
        met = [False] * len(attrs)
        for _ in range(max(len(attrs) * 2, 16)):
            if not np.any(remaining > 1e-6) or all(met):
                break
            weights = np.array(
                [a.weight if not m else 0.0 for a, m in zip(attrs, met)]
            )
            tw = weights.sum()
            if tw <= 0:
                break
            for i, attr in enumerate(attrs):
                if met[i]:
                    continue
                inc = remaining * (weights[i] / tw)
                new = attr.deserved.vec + inc
                if np.all(attr.request.vec <= new + 1e-6):
                    new = np.minimum(new, attr.request.vec)
                    met[i] = True
                attr.deserved = spec.from_vec(new)
            granted = sum(a.deserved.vec for a in attrs)
            remaining = np.maximum(self.total.vec - granted, 0.0)

    def on_session_close(self, ssn: fw.Session) -> None:
        self.total = None
        self.queue_attrs = {}
        self._qalloc = self._jq_rows = self._jq_vals = None


def _dominant(alloc: Resource, deserved: Resource) -> float:
    # max over semantic dims of alloc/deserved, 0 where deserved is 0 —
    # exactly Resource.share's contract (native fast path)
    return alloc.share(deserved)
