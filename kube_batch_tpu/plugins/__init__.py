"""Plugin registry (plugins/factory.go:31-42 + binpack, SURVEY.md §2.4)."""

from kube_batch_tpu.framework.interface import register_plugin_builder

from kube_batch_tpu.plugins.binpack import BinpackPlugin
from kube_batch_tpu.plugins.conformance import ConformancePlugin
from kube_batch_tpu.plugins.drf import DrfPlugin
from kube_batch_tpu.plugins.gang import GangPlugin
from kube_batch_tpu.plugins.nodeorder import NodeOrderPlugin
from kube_batch_tpu.plugins.predicates import PredicatesPlugin
from kube_batch_tpu.plugins.priority import PriorityPlugin
from kube_batch_tpu.plugins.proportion import ProportionPlugin

ALL_PLUGINS = (
    GangPlugin,
    DrfPlugin,
    ProportionPlugin,
    PriorityPlugin,
    PredicatesPlugin,
    NodeOrderPlugin,
    ConformancePlugin,
    BinpackPlugin,
)

for cls in ALL_PLUGINS:
    register_plugin_builder(cls.name, cls)

__all__ = [cls.__name__ for cls in ALL_PLUGINS] + ["ALL_PLUGINS"]
