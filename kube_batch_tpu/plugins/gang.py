"""gang plugin (plugins/gang/gang.go) — gang-integrity policy.

Registers: JobValid (minMember check), Preemptable/Reclaimable (never shrink
a gang below minAvailable), JobOrder (starved gangs first), JobReady,
JobPipelined. OnSessionClose writes Unschedulable conditions + fit errors for
still-unready jobs (gang.go:132-175).

The device allocate solve enforces the same commit gate tensor-side
(ops/assignment.py outer_body); this host plugin is authoritative for the
host-path actions (preempt/reclaim/backfill) and for session bookkeeping.
"""

from __future__ import annotations

from typing import Dict, List

from kube_batch_tpu.api.job_info import JobInfo
from kube_batch_tpu.api.pod import PodGroupCondition
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework import session as fw


class GangPlugin(Plugin):
    name = "gang"

    def on_session_open(self, ssn: fw.Session) -> None:
        def job_valid(job: JobInfo):
            """(gang.go:48-69) valid iff enough potentially-runnable tasks."""
            valid = job.valid_task_num
            if valid < job.min_available:
                return (
                    f"Not enough valid tasks for gang-scheduling, "
                    f"valid: {valid}, min: {job.min_available}"
                )
            return None

        def evictable(evictor: TaskInfo, evictees: List[TaskInfo]) -> List[TaskInfo]:
            """(gang.go:71-94) a task is a victim only if its job stays at or
            above minAvailable after all victims so far are removed.
            MinAvailable <= 1 jobs are not gangs and are always evictable
            (gang.go:78's `|| job.MinAvailable == 1` escape — the device
            solve's slack gate, ops/eviction.py, has the same special case);
            the cumulative accounting for real gangs is deliberately
            stricter than the reference's per-victim snapshot test, which
            could approve a victim set that jointly breaks the gang."""
            victims: List[TaskInfo] = []
            occupied: Dict[str, int] = {}
            for ee in evictees:
                job = ssn.jobs.get(ee.job)
                if job is None:
                    continue
                if job.min_available <= 1:
                    victims.append(ee)
                    continue
                if job.uid not in occupied:
                    occupied[job.uid] = job.ready_task_num
                if occupied[job.uid] > job.min_available:
                    occupied[job.uid] -= 1
                    victims.append(ee)
            return victims

        def job_order(l: JobInfo, r: JobInfo) -> int:
            """(gang.go:96-121) starved (not ready) gangs first."""
            l_ready, r_ready = l.ready(), r.ready()
            if l_ready == r_ready:
                return 0
            return 1 if l_ready else -1

        ssn.add_fn(fw.JOB_VALID, self.name, job_valid)
        ssn.add_fn(fw.PREEMPTABLE, self.name, evictable)
        ssn.add_fn(fw.RECLAIMABLE, self.name, evictable)
        ssn.add_fn(fw.JOB_ORDER, self.name, job_order)
        ssn.add_fn(fw.JOB_READY, self.name, lambda job: job.ready())
        ssn.add_fn(fw.JOB_PIPELINED, self.name, lambda job: job.pipelined())

    def on_session_close(self, ssn: fw.Session) -> None:
        """(gang.go:132-175) mark still-unready jobs Unschedulable."""
        cols = ssn.columns
        if cols is not None and ssn.rows_synced and ssn.jobs:
            # one counts-matrix expression finds the (normally sparse)
            # unready set; only those jobs pay the condition rendering
            import numpy as np

            from kube_batch_tpu.api.columns import READY_STATUSES

            rows, jobs_list = ssn.session_rows()
            counts = cols.j_counts[rows]
            ready = counts[:, READY_STATUSES].sum(axis=1) >= cols.j_min[rows]
            has_tasks = counts.sum(axis=1) > 0
            candidates = [
                jobs_list[i] for i in np.flatnonzero(~ready & has_tasks)
            ]
        else:
            candidates = [
                job for job in ssn.jobs.values()
                if not job.ready() and job.tasks
            ]
        for job in candidates:
            # still unschedulable with a prior Unschedulable condition ⇒ a
            # retry of a previously-failed job (job_retry_counts analog,
            # metrics.go:113-121 — declared but never written there)
            if job.pod_group is not None and any(
                c.type == "Unschedulable" and c.status == "True"
                and c.transition_id != ssn.uid
                for c in job.pod_group.conditions
            ):
                from kube_batch_tpu import metrics

                metrics.register_job_retry(job.uid)
            fit_errors = [fe.error() for fe in job.nodes_fit_errors.values()]
            message = job.fit_error() + (
                f"; {fit_errors[0]}" if fit_errors else ""
            )
            job.job_fit_errors = message  # read by RecordJobStatusEvent
            ssn.note_fit_state(job)
            ssn.update_job_condition(
                job,
                PodGroupCondition(
                    type="Unschedulable",
                    status="True",
                    transition_id=ssn.uid,
                    reason="NotEnoughResources",
                    message=message,
                ),
            )
            # events are recorded once per job by the close-session status
            # pass (UpdateJobStatus → RecordJobStatusEvent, cache.go:722-736)
            # — the reference's gang close writes conditions only
