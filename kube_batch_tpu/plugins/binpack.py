"""binpack plugin — weighted-resource packing score.

Absent from the reference snapshot (it arrived later in Volcano) but named by
the rebuild's north star (SURVEY.md §2.4 note): prefer filling nodes to
spreading, so large gangs find contiguous capacity. Configures the device
binpack score row; also registers a host scorer."""

from __future__ import annotations

from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework import session as fw

BINPACK_WEIGHT = "binpack.weight"
MAX_PRIORITY = 10.0


def binpack_score(task: TaskInfo, node: NodeInfo) -> float:
    total = 0.0
    for i in (0, 1):
        alloc = node.allocatable.vec[i]
        if alloc <= 0:
            continue
        want = node.used.vec[i] + task.resreq.vec[i]
        total += min(want / alloc, 1.0) * MAX_PRIORITY
    return total / 2.0


class BinpackPlugin(Plugin):
    name = "binpack"

    def on_session_open(self, ssn: fw.Session) -> None:
        weight = self.arguments.get_int(BINPACK_WEIGHT, 1)
        ssn.score_weights = ssn.score_weights._replace(binpack=float(weight))

        def node_order(task: TaskInfo, node: NodeInfo) -> float:
            return weight * binpack_score(task, node)

        ssn.add_fn(fw.NODE_ORDER, self.name, node_order)
