"""nodeorder plugin (plugins/nodeorder/nodeorder.go) — weighted node scoring.

Configures the device score rows (ops/scoring.py) via session.score_weights
and registers the host per-(task, node) scorer used by preempt/reclaim.
Weights come from plugin arguments (nodeorder.go:34-43), default 1 each.
"""

from __future__ import annotations

import numpy as np

from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework import session as fw

NODE_AFFINITY_WEIGHT = "nodeaffinity.weight"
POD_AFFINITY_WEIGHT = "podaffinity.weight"
LEAST_REQUESTED_WEIGHT = "leastrequested.weight"
BALANCED_RESOURCE_WEIGHT = "balancedresource.weight"

MAX_PRIORITY = 10.0


def minmax_scale_rows(raw):
    """Min-max reduce score rows to the 0..10 priority scale, per row (k8s
    InterPodAffinityPriority's reduce): 10·(v−min)/(max−min), all-zero when a
    row is constant. `raw` is [K, N]; returns same shape. Single definition
    shared by the host scorer below and the device snapshot rows
    (api/snapshot.py) so the two can't diverge."""
    mn = raw.min(axis=1, keepdims=True)
    rng = raw.max(axis=1, keepdims=True) - mn
    return np.where(
        rng > 0, MAX_PRIORITY * (raw - mn) / np.where(rng > 0, rng, 1.0), 0.0
    )


def least_requested_score(task: TaskInfo, node: NodeInfo) -> float:
    total = 0.0
    for i in (0, 1):  # cpu, memory
        alloc = node.allocatable.vec[i]
        if alloc <= 0:
            continue
        free = alloc - node.used.vec[i] - task.resreq.vec[i]
        total += max(min(free / alloc, 1.0), 0.0) * MAX_PRIORITY
    return total / 2.0


def balanced_resource_score(task: TaskInfo, node: NodeInfo) -> float:
    fracs = []
    for i in (0, 1):
        alloc = node.allocatable.vec[i]
        want = node.used.vec[i] + task.resreq.vec[i]
        fracs.append(min(want / alloc, 1.0) if alloc > 0 else 1.0)
    return (1.0 - abs(fracs[0] - fracs[1])) * MAX_PRIORITY


def _term_matches(term, labels) -> bool:
    for key, op, values in term:
        has = key in labels
        if op == "In" and labels.get(key) not in values:
            return False
        if op == "NotIn" and labels.get(key) in values:
            return False
        if op == "Exists" and not has:
            return False
        if op == "DoesNotExist" and has:
            return False
    return True


def preferred_node_affinity_score(task: TaskInfo, node: NodeInfo) -> float:
    """CalculateNodeAffinityPriorityMap analog (nodeorder.go:188-205): sum of
    weights of matching preferred terms. Raw weighted sum — the reference
    normalizes to 0..10 over the batch, a monotone rescale that never changes
    the argmax."""
    aff = task.pod.affinity
    if aff is None or not aff.preferred_node_terms:
        return 0.0
    labels = node.node.labels if node.node else {}
    return float(sum(
        w for w, term in aff.preferred_node_terms if _term_matches(term, labels)
    ))


def preferred_pod_affinity_score(task: TaskInfo, node: NodeInfo, all_nodes) -> float:
    """InterPodAffinityPriority analog (nodeorder.go:229-247): each preferred
    pod-affinity term adds its weight when a matching pod exists in the
    node's topology domain; anti-affinity terms subtract."""
    from kube_batch_tpu.plugins.predicates import _topology_domain

    aff = task.pod.affinity
    if aff is None:
        return 0.0
    score = 0.0
    for sign, terms in (
        (1.0, aff.preferred_pod_affinity),
        (-1.0, aff.preferred_pod_anti_affinity),
    ):
        for w, term in terms:
            domain = _topology_domain(node, term.topology_key, all_nodes)
            if any(
                term.matches(t.pod.labels)
                for n in domain for t in n.tasks.values()
            ):
                score += sign * w
    return score


class NodeOrderPlugin(Plugin):
    name = "nodeorder"

    def on_session_open(self, ssn: fw.Session) -> None:
        w_least = self.arguments.get_int(LEAST_REQUESTED_WEIGHT, 1)
        w_balanced = self.arguments.get_int(BALANCED_RESOURCE_WEIGHT, 1)
        w_affinity = self.arguments.get_int(NODE_AFFINITY_WEIGHT, 1)
        w_pod_aff = self.arguments.get_int(POD_AFFINITY_WEIGHT, 1)

        ssn.score_weights = ssn.score_weights._replace(
            least_requested=float(w_least),
            balanced_resource=float(w_balanced),
            node_affinity=float(w_affinity),
            pod_affinity=float(w_pod_aff),
        )

        # per-task normalized pod-affinity rows, memoized for the session —
        # InterPodAffinityPriority min-max reduces raw ±weight sums to the
        # 0..10 priority scale across the node batch before weighting, so a
        # large term weight (k8s allows 100) can't dominate the bounded
        # least-requested/balanced rows. Memo trades exactness under
        # mid-session placement churn for O(N) instead of O(N²) host scoring
        # (scores are preferences, and the reference's batch scorer is
        # likewise computed once per PrioritizeNodes call).
        pod_aff_rows: dict = {}

        def normalized_pod_affinity(task: TaskInfo, node: NodeInfo) -> float:
            aff = task.pod.affinity
            if aff is None or not (
                aff.preferred_pod_affinity or aff.preferred_pod_anti_affinity
            ):
                return 0.0
            row = pod_aff_rows.get(task.key())
            if row is None:
                node_objs = list(ssn.nodes.values())
                raw = np.array(
                    [[preferred_pod_affinity_score(task, n, node_objs)
                      for n in node_objs]]
                )
                scaled = minmax_scale_rows(raw)[0]
                row = {n.name: float(s) for n, s in zip(node_objs, scaled)}
                pod_aff_rows[task.key()] = row
            return row.get(node.name, 0.0)

        def node_order(task: TaskInfo, node: NodeInfo) -> float:
            return (
                w_least * least_requested_score(task, node)
                + w_balanced * balanced_resource_score(task, node)
                + w_affinity * preferred_node_affinity_score(task, node)
                + w_pod_aff * normalized_pod_affinity(task, node)
            )

        ssn.add_fn(fw.NODE_ORDER, self.name, node_order)
