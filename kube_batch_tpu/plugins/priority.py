"""priority plugin (plugins/priority/priority.go:27-82): orders tasks by pod
priority and jobs by PodGroup PriorityClass value (resolved in the cache
snapshot, cache.go:610-620)."""

from __future__ import annotations

from kube_batch_tpu.api.job_info import JobInfo
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework import session as fw


class PriorityPlugin(Plugin):
    name = "priority"

    def on_session_open(self, ssn: fw.Session) -> None:
        def task_order(l: TaskInfo, r: TaskInfo) -> int:
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        def job_order(l: JobInfo, r: JobInfo) -> int:
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        ssn.add_fn(fw.TASK_ORDER, self.name, task_order)
        ssn.add_fn(fw.JOB_ORDER, self.name, job_order)
