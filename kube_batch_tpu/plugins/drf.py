"""drf plugin (plugins/drf/drf.go) — dominant-resource fairness at job level.

Registers: Preemptable (preemptor's post-allocation share must stay ≤
victim-job's post-eviction share), JobOrder (lower share first), and event
handlers keeping per-job allocated/share incrementally updated during the
session (drf.go:135-154). The device solve reproduces the same ordering via
virtual drf shares (ops/ordering.py); this host state drives preempt/reclaim.
"""

from __future__ import annotations

from typing import Dict, List

from kube_batch_tpu.api.job_info import JobInfo
from kube_batch_tpu.api.resources import Resource
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.api.types import is_allocated
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework import session as fw

SHARE_DELTA = 1e-6  # drf.go:33


class _JobAttr:
    __slots__ = ("allocated", "_share", "_dirty", "_gen")

    def __init__(self, allocated: Resource):
        self.allocated = allocated
        self._share = 0.0
        self._dirty = True
        self._gen = 0


class DrfPlugin(Plugin):
    name = "drf"

    def __init__(self, arguments=None):
        super().__init__(arguments)
        self.total: Resource | None = None
        self.job_attrs: Dict[str, _JobAttr] = {}
        # columnar mode: per-job-row allocated matrix the attrs' Resources
        # are views into; _generation invalidates every cached share after a
        # vectorized update
        self._arr = None
        self._generation = 0

    def _share(self, attr: _JobAttr) -> float:
        # recomputed lazily on read: the allocate replay fires thousands of
        # batch events whose shares nothing reads until preempt/reclaim
        if attr._dirty or attr._gen != self._generation:
            attr._share = attr.allocated.share(self.total)
            attr._dirty = False
            attr._gen = self._generation
        return attr._share

    def on_session_open(self, ssn: fw.Session) -> None:
        self.total = ssn.total_allocatable().clone()
        cols = ssn.columns
        if cols is not None:
            # columnar session: one matrix copy seeds every job's allocated
            # state; attrs are built LAZILY on first read, wrapping rows
            # zero-copy — the headline allocate cycle never reads a share
            # (ordering runs on device), so eagerly building 12.5k attr
            # objects was pure open-session overhead.  Per-task events from
            # evictions write the same rows the vectorized allocate updates,
            # so every path composes.
            self._arr = cols.j_alloc.copy()
        else:
            for job in ssn.jobs.values():
                # job.allocated IS the sum of allocated-status task resreqs —
                # the ledger add_task/bulk_transition maintain (job_info.py);
                # re-deriving it per task was the session-open hot loop
                self.job_attrs[job.uid] = _JobAttr(job.allocated.clone())

        wrap = ssn.spec.wrap_vec

        def attr_for(uid: str):
            """The job's attr, lazily wrapping its _arr row in columnar
            sessions; None for unknown jobs."""
            attr = self.job_attrs.get(uid)
            if attr is None and self._arr is not None:
                job = ssn.jobs.get(uid)
                if job is not None and job._row >= 0:
                    attr = self.job_attrs[uid] = _JobAttr(
                        wrap(self._arr[job._row])
                    )
            return attr

        def preemptable(preemptor: TaskInfo, preemptees: List[TaskInfo]) -> List[TaskInfo]:
            """(drf.go:85-110)"""
            lattr = attr_for(preemptor.job)
            if lattr is None:
                return []
            lalloc = lattr.allocated.add(preemptor.resreq)
            ls = lalloc.share(self.total)
            allocations: Dict[str, Resource] = {}
            victims: List[TaskInfo] = []
            for ee in preemptees:
                rattr = attr_for(ee.job)
                if rattr is None:
                    continue
                if ee.job not in allocations:
                    allocations[ee.job] = rattr.allocated.clone()
                ralloc = allocations[ee.job]
                if not ee.resreq.less_equal(ralloc):
                    continue
                ralloc.sub_(ee.resreq)
                rs = ralloc.share(self.total)
                if ls < rs or abs(ls - rs) <= SHARE_DELTA:
                    victims.append(ee)
            return victims

        def job_order(l: JobInfo, r: JobInfo) -> int:
            """(drf.go:114-132) lower dominant share first."""
            la = attr_for(l.uid)
            ra = attr_for(r.uid)
            ls = self._share(la) if la is not None else 0.0
            rs = self._share(ra) if ra is not None else 0.0
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        def on_allocate(event: fw.Event) -> None:
            attr = attr_for(event.task.job)
            if attr is not None:
                attr.allocated.add_(event.task.resreq)
                attr._dirty = True

        def on_deallocate(event: fw.Event) -> None:
            attr = attr_for(event.task.job)
            if attr is not None:
                attr.allocated.sub_(event.task.resreq)
                attr._dirty = True

        def on_batch_allocate(job: JobInfo, tasks, total_resreq) -> None:
            # linear in resreq: one presummed add per job ≡ per-task events
            attr = attr_for(job.uid)
            if attr is not None:
                attr.allocated.add_(total_resreq)
                attr._dirty = True

        def on_columnar_allocate(cols, job_sums) -> None:
            # one matrix add for the whole replay ≡ 12.5k batch events
            self._arr += job_sums
            self._generation += 1

        ssn.add_fn(fw.PREEMPTABLE, self.name, preemptable)
        ssn.add_fn(fw.JOB_ORDER, self.name, job_order)
        ssn.add_event_handler(
            fw.EventHandler(
                allocate_func=on_allocate, deallocate_func=on_deallocate,
                batch_allocate_func=on_batch_allocate,
                columnar_allocate_func=(
                    on_columnar_allocate if self._arr is not None else None
                ),
            )
        )

    def on_session_close(self, ssn: fw.Session) -> None:
        self.total = None
        self.job_attrs = {}
        self._arr = None
